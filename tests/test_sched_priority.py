"""Scheduler priority semantics (ISSUE 7 satellite): ap/spq/pbq pop
order under mixed priorities, the keep_highest_priority_task bypass
slot, FIFO-within-priority under dynamic updates, and the online
ClassProfile's upward-rank/scarcity boosts."""
import numpy as np
import pytest

import parsec_tpu
from parsec_tpu.runtime.profile import ClassProfile, _PRIO_SCALE
from parsec_tpu.runtime.scheduling import schedule, schedule_keep_best
from parsec_tpu.runtime.taskpool import Task, TaskClass
from parsec_tpu.utils.params import params


class _FakePool:
    """Just enough taskpool for a Task living in scheduler queues."""
    taskpool_id = 0
    name = "fake"


def _mk_tasks(prios, cls="T"):
    tc = TaskClass(cls, 0, 0)
    tp = _FakePool()
    return [Task(tp, tc, (i,), priority=p) for i, p in enumerate(prios)]


def _ctx(sched, cores=1, **kw):
    return parsec_tpu.init(nb_cores=cores, scheduler=sched,
                           enable_tpu=False, **kw)


# --------------------------------------------------------------------- #
# pop order under mixed priorities                                      #
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("sched", ["ap", "spq"])
def test_priority_pop_order_desc_fifo_within(sched):
    ctx = _ctx(sched)
    try:
        es = ctx.execution_streams[0]
        tasks = _mk_tasks([1, 5, 3, 5, 0])
        ctx.scheduler.schedule(es, list(tasks))
        got = [ctx.scheduler.select(es) for _ in range(5)]
        # priority desc; FIFO between the two priority-5 tasks
        assert got == [tasks[1], tasks[3], tasks[2], tasks[0], tasks[4]]
        assert ctx.scheduler.select(es) is None
    finally:
        ctx.fini()


def test_ip_pops_worst_first():
    ctx = _ctx("ip")
    try:
        es = ctx.execution_streams[0]
        tasks = _mk_tasks([1, 5, 3])
        ctx.scheduler.schedule(es, list(tasks))
        got = [ctx.scheduler.select(es) for _ in range(3)]
        assert got == [tasks[0], tasks[2], tasks[1]]
    finally:
        ctx.fini()


def test_pbq_local_buffer_pops_best():
    """pbq keeps a priority-aware local buffer: a local push set pops
    highest-priority first on the pushing stream."""
    ctx = _ctx("pbq", cores=2)
    try:
        es = ctx.execution_streams[0]
        tasks = _mk_tasks([2, 9, 4])
        ctx.scheduler.schedule(es, list(tasks), distance=0)
        got = [ctx.scheduler.select(es) for _ in range(3)]
        assert got == [tasks[1], tasks[2], tasks[0]]
    finally:
        ctx.fini()


# --------------------------------------------------------------------- #
# the keep_highest_priority_task bypass slot (scheduling.py)            #
# --------------------------------------------------------------------- #
def test_keep_highest_priority_bypass_slot():
    ctx = _ctx("ap")
    try:
        es = ctx.execution_streams[0]
        assert ctx.keep_highest_priority_task
        tasks = _mk_tasks([3, 8, 5])
        schedule_keep_best(es, list(tasks))
        # the best freshly-enabled task stays on the releasing thread
        assert es.next_task is tasks[1]
        # the rest went to the scheduler in priority order
        assert ctx.scheduler.select(es) is tasks[2]
        assert ctx.scheduler.select(es) is tasks[0]
        # an occupied slot is never displaced
        es.next_task = tasks[1]
        more = _mk_tasks([99])
        schedule_keep_best(es, list(more))
        assert es.next_task is tasks[1]
        assert ctx.scheduler.select(es) is more[0]
        es.next_task = None
    finally:
        ctx.fini()


# --------------------------------------------------------------------- #
# dynamic priorities: stamping + FIFO within equal priority             #
# --------------------------------------------------------------------- #
def test_dynamic_boost_jumps_queue_static_breaks_ties():
    """A critical-path class (profile boost) beats a higher STATIC
    priority of a non-critical class; within one class the static
    expression still decides."""
    ctx = _ctx("ap")
    try:
        es = ctx.execution_streams[0]
        prof = ctx.class_profile
        assert prof is not None   # sched_dynamic_priority default on
        prof.add_edges("CRIT", ["LEAF"])
        prof.add_edges("LEAF", [])
        tc_crit = TaskClass("CRIT", 0, 0)
        tc_leaf = TaskClass("LEAF", 1, 0)
        tp = _FakePool()
        leaf_hi = Task(tp, tc_leaf, (0,), priority=1000)
        crit_lo = Task(tp, tc_crit, (1,), priority=1)
        crit_hi = Task(tp, tc_crit, (2,), priority=7)
        schedule(es, [leaf_hi, crit_lo, crit_hi])
        got = [ctx.scheduler.select(es) for _ in range(3)]
        assert got == [crit_hi, crit_lo, leaf_hi]
        # the stamp is boost * SCALE + static, recomputed from base
        assert crit_hi.priority == prof.boost_of("CRIT") * _PRIO_SCALE + 7
        assert crit_hi.base_priority == 7
    finally:
        ctx.fini()


def test_dynamic_updates_keep_fifo_within_priority():
    """Profile updates between pushes must not reorder equal-priority
    tasks: FIFO within a priority is a scheduler invariant."""
    ctx = _ctx("ap")
    try:
        es = ctx.execution_streams[0]
        prof = ctx.class_profile
        prof.add_edges("A", ["B"])
        prof.add_edges("B", [])
        tc = TaskClass("A", 0, 0)
        tp = _FakePool()
        first = Task(tp, tc, (0,), priority=5)
        schedule(es, [first])
        # an EWMA update between pushes (same class set: boosts stable)
        prof.note("A", 100.0)
        prof.note("A", 250.0)
        second = Task(tp, tc, (1,), priority=5)
        schedule(es, [second])
        assert first.priority == second.priority
        assert ctx.scheduler.select(es) is first
        assert ctx.scheduler.select(es) is second
    finally:
        ctx.fini()


def test_dynamic_priority_off_keeps_static():
    with params.cmdline_override("sched_dynamic_priority", "0"):
        ctx = _ctx("ap")
    try:
        assert ctx.class_profile is None
        es = ctx.execution_streams[0]
        tasks = _mk_tasks([4, 2])
        schedule(es, list(tasks))
        assert tasks[0].priority == 4   # untouched
        assert ctx.scheduler.select(es) is tasks[0]
    finally:
        ctx.fini()


# --------------------------------------------------------------------- #
# ClassProfile: upward rank + scarcity                                  #
# --------------------------------------------------------------------- #
def test_class_profile_chain_ranks_descend():
    prof = ClassProfile()
    prof.add_edges("A", ["B"])
    prof.add_edges("B", ["C"])
    prof.add_edges("C", [])
    assert prof.boost_of("A") > prof.boost_of("B") > prof.boost_of("C")
    # unknown classes are never boosted and keep their static priority
    assert prof.boost_of("ZZZ") == 0
    assert prof.effective("ZZZ", 42) == 42


def test_class_profile_cycle_scarcity_orders_dpotrf_classes():
    """The dpotrf class graph is one SCC; within it the duration-
    weighted scarcity must rank POTRF (rare) above GEMM (abundant)."""
    prof = ClassProfile()
    prof.add_edges("POTRF", ["TRSM"])
    prof.add_edges("TRSM", ["SYRK", "GEMM"])
    prof.add_edges("SYRK", ["POTRF", "SYRK"])
    prof.add_edges("GEMM", ["TRSM", "GEMM"])
    # steady-state-ish samples: first per class is discarded (compile)
    for _ in range(3):
        prof.note("POTRF", 100.0, 4)
        prof.note("TRSM", 100.0, 16)
        prof.note("SYRK", 100.0, 16)
        prof.note("GEMM", 100.0, 64)
    assert prof.boost_of("POTRF") > prof.boost_of("GEMM")
    assert prof.boost_of("TRSM") > prof.boost_of("GEMM")
    snap = prof.snapshot()
    assert snap["GEMM"]["count"] == 3 * 64


def test_class_profile_effective_packing():
    prof = ClassProfile()
    prof.add_edges("A", ["B"])
    prof.add_edges("B", [])
    # boost dominates any clamped static; static breaks ties in-class
    assert prof.effective("A", -10) > prof.effective("B", 10**9)
    assert prof.effective("A", 3) > prof.effective("A", 2)


def test_dpotrf_run_populates_profile():
    """End-to-end: a classic-runtime dpotrf feeds the profile and the
    result stays correct with dynamic priorities on (the default)."""
    from parsec_tpu.collections import TwoDimBlockCyclic
    from parsec_tpu.ops import dpotrf_taskpool, make_spd

    with params.cmdline_override("device_tpu_max", "1"):
        ctx = parsec_tpu.Context(nb_cores=2)
        try:
            M = make_spd(192)
            A = TwoDimBlockCyclic(192, 192, 32, 32,
                                  dtype=np.float32).from_numpy(M)
            ctx.add_taskpool(dpotrf_taskpool(A))
            ctx.wait()
            L = np.tril(A.to_numpy()).astype(np.float64)
            resid = np.abs(L @ L.T - M).max() / np.abs(M).max()
            assert resid < 1e-5
            snap = ctx.class_profile.snapshot()
            assert set(snap) == {"POTRF", "TRSM", "SYRK", "GEMM"}
            assert all(c["count"] > 0 for c in snap.values())
        finally:
            ctx.fini()


# --------------------------------------------------------------------- #
# multi-tenant fairness + admission (serve/, ISSUE 18)                  #
# --------------------------------------------------------------------- #
class _TenantPool:
    """A fake pool with a distinct id so TenantFairness can attribute
    its tasks to a tenant."""
    name = "tenant-pool"

    def __init__(self, tp_id):
        self.taskpool_id = tp_id


def _tenant_tasks(tp, n, cls="T"):
    tc = TaskClass(cls, 0, 0)
    return [Task(tp, tc, (i,), priority=0) for i in range(n)]


def _fairness_ctx(sched="spq"):
    from parsec_tpu.serve import TenantFairness
    ctx = _ctx(sched)
    fair = TenantFairness()
    fair.register("latency", 8)
    fair.register("bulk", 1)
    fair.bind_pool(101, "latency")
    fair.bind_pool(102, "bulk")
    ctx.serve_fairness = fair
    return ctx, fair, _TenantPool(101), _TenantPool(102)


@pytest.mark.parametrize("sched", ["ap", "spq"])
def test_mixed_tenant_weighted_pop_order(sched):
    """At cold start the heavier tenant's weight bias wins every pop;
    FIFO within each tenant is preserved (one shared boost per
    tenant)."""
    ctx, fair, pool_lat, pool_blk = _fairness_ctx(sched)
    try:
        es = ctx.execution_streams[0]
        lat = _tenant_tasks(pool_lat, 2)
        blk = _tenant_tasks(pool_blk, 2)
        # interleaved arrival, saturated queue
        schedule(es, [blk[0], lat[0], blk[1], lat[1]])
        got = [ctx.scheduler.select(es) for _ in range(4)]
        assert got == [lat[0], lat[1], blk[0], blk[1]]
    finally:
        ctx.fini()


def test_weighted_share_follows_deficit_under_saturation():
    """Once the heavy tenant has consumed its weighted share, the
    light tenant's deficit boost overtakes the weight bias — weighted
    fair share, not absolute priority."""
    ctx, fair, pool_lat, pool_blk = _fairness_ctx()
    try:
        es = ctx.execution_streams[0]
        # latency has completed 80 weight-normalized units (v=10),
        # bulk none (v=0): bulk is now the starved tenant
        fair.note_done("latency", 80)
        assert fair.boost_of_tenant("bulk") > fair.boost_of_tenant("latency")
        lat = _tenant_tasks(pool_lat, 1)
        blk = _tenant_tasks(pool_blk, 1)
        schedule(es, [lat[0], blk[0]])
        assert ctx.scheduler.select(es) is blk[0]
        assert ctx.scheduler.select(es) is lat[0]
    finally:
        ctx.fini()


def test_no_starvation_of_low_weight_tenant():
    """A weight-1 tenant sharing with a saturating weight-8 tenant must
    still be served: every completion charged to the heavy tenant
    raises the light tenant's deficit boost monotonically until it
    wins."""
    ctx, fair, pool_lat, pool_blk = _fairness_ctx()
    try:
        es = ctx.execution_streams[0]
        popped_bulk = False
        for _round in range(64):
            lat = _tenant_tasks(pool_lat, 1)
            blk = _tenant_tasks(pool_blk, 1)
            schedule(es, [lat[0], blk[0]])
            first = ctx.scheduler.select(es)
            second = ctx.scheduler.select(es)
            assert {first, second} == {lat[0], blk[0]}
            if first is blk[0]:
                popped_bulk = True
                break
            # the heavy tenant keeps winning AND completing
            fair.note_done("latency", 1)
        assert popped_bulk, "low-weight tenant starved for 64 rounds"
    finally:
        ctx.fini()


def test_fifo_within_tenant_across_batches():
    """Tasks of one tenant stamped in separate batches (no completion
    in between: boost unchanged) keep FIFO order — the fairness fold
    must not perturb the scheduler's within-priority invariant."""
    ctx, fair, pool_lat, _pool_blk = _fairness_ctx()
    try:
        es = ctx.execution_streams[0]
        first = _tenant_tasks(pool_lat, 1)[0]
        schedule(es, [first])
        second = _tenant_tasks(pool_lat, 1)[0]
        schedule(es, [second])
        assert first.priority == second.priority
        assert ctx.scheduler.select(es) is first
        assert ctx.scheduler.select(es) is second
    finally:
        ctx.fini()


def test_foreign_pool_ranks_with_lowest_tenant():
    """Pools the server does not own get boost 0 — the same floor the
    least-entitled tenant sits on, so foreign workloads compete there
    instead of starving behind every serve pool."""
    ctx, fair, pool_lat, _pool_blk = _fairness_ctx()
    try:
        es = ctx.execution_streams[0]
        foreign = _mk_tasks([5])     # _FakePool id 0: unknown to fair
        lat = _tenant_tasks(pool_lat, 1)
        schedule(es, [foreign[0], lat[0]])
        # latency's weight bias outranks the foreign static-5 (packed
        # above the class band) but the foreign task still pops second,
        # not never
        assert ctx.scheduler.select(es) is lat[0]
        assert ctx.scheduler.select(es) is foreign[0]
        assert fair.boost_of_task(foreign[0]) == 0
    finally:
        ctx.fini()


def test_mempool_quota_admission_rejection():
    """Declared-bytes quota + bound named-Mempool outstanding bytes
    both count at admission; reject policy raises, release re-admits."""
    from parsec_tpu.core.mempool import Mempool
    from parsec_tpu.serve import AdmissionError, SessionServer

    ctx = _ctx("ap", cores=2)
    srv = SessionServer(ctx)
    try:
        srv.open_tenant("t", quota_bytes=1000)
        mp = Mempool(lambda: bytearray(100), name="SERVE_T_Q")
        srv.bind_mempool("t", mp, 100)
        held = [mp.allocate() for _ in range(8)]   # 800 bytes outstanding

        import parsec_tpu as _pt
        from parsec_tpu import dtd

        def build():
            return dtd.taskpool_new()

        # 800 (mempool) + 300 (declared) > 1000 -> rejected
        with pytest.raises(AdmissionError):
            srv.submit("t", build, nbytes=300)
        # a rejected submission must not leak accounting
        assert srv.stats()["tenants"]["t"]["used_bytes"] == 800
        # freeing mempool items re-admits the same declaration
        for elt in held[:4]:
            mp.free(elt)
        sub = srv.submit("t", build, nbytes=300)
        assert sub.wait(20) and sub.error is None
        assert srv.stats()["tenants"]["t"]["used_bytes"] == 400
        mp.unregister_gauges()
    finally:
        srv.close()
        ctx.fini()


def test_queue_policy_defers_over_quota_submission():
    """serve_admission=queue: the over-cap submission parks in the
    tenant's FIFO and launches when an in-flight pool retires."""
    from parsec_tpu.serve import SessionServer
    from parsec_tpu import dtd

    ctx = _ctx("ap", cores=2)
    srv = SessionServer(ctx, admission="queue")
    try:
        srv.open_tenant("t", max_pools=1)
        import threading as _th
        gate = _th.Event()

        def blocked_build():
            tp = dtd.taskpool_new()

            def body(es, task):
                gate.wait(20)
            tp.insert_task(body)
            return tp

        def quick_build():
            return dtd.taskpool_new()

        first = srv.submit("t", blocked_build)
        second = srv.submit("t", quick_build)   # over max_pools: queued
        assert srv.stats()["tenants"]["t"]["queued"] == 1
        assert not second.done.is_set()
        gate.set()
        assert first.wait(20) and second.wait(20)
        assert first.error is None and second.error is None
        assert srv.stats()["tenants"]["t"]["queued"] == 0
        assert srv.stats()["tenants"]["t"]["pools_done"] == 2
    finally:
        srv.close()
        ctx.fini()
