"""DOT capture of the executed DAG.

Reference behavior: ``parsec_prof_grapher`` writes a per-rank DOT file of
the tasks that actually ran and the dependency edges that fired, enabled
by ``--parsec_dot`` (ref: parsec/parsec_prof_grapher.c:1-266, wired from
parsec/parsec.c:596-614). Like the reference it is called directly from
the runtime hot path (node at task completion, edge at successor
activation), not through PINS.

Enable programmatically (``grapher.enable()``) or with the MCA param
``profiling_dot=<path-prefix>``; ``grapher.dump(path)`` writes the DOT.
"""
from __future__ import annotations

import re
import threading
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["Grapher", "grapher"]

_COLORS = ["#88CCEE", "#CC6677", "#DDCC77", "#117733", "#332288", "#AA4499",
           "#44AA99", "#999933", "#882255", "#661100", "#6699CC", "#888888"]

_ID_RE = re.compile(r"[^A-Za-z0-9_]")


def _node_id(label: str) -> str:
    return _ID_RE.sub("_", label)


class Grapher:
    def __init__(self) -> None:
        self.enabled = False
        self._nodes: Dict[str, Dict[str, Any]] = {}
        self._edges: List[Tuple[str, str, str]] = []
        self._lock = threading.Lock()
        self._seq = 0

    def enable(self) -> None:
        with self._lock:
            self._nodes.clear()
            self._edges.clear()
            self._seq = 0
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    # -- capture sites (hot path; no-ops when disabled) ---------------------
    def task_executed(self, es: Any, task: Any) -> None:
        if not self.enabled:
            return
        label = task.snprintf()
        tc = task.task_class.name
        with self._lock:
            n = self._nodes.get(label)
            if n is None:
                self._nodes[label] = {"tc": tc, "thid": getattr(es, "th_id", 0),
                                      "order": self._seq}
                self._seq += 1

    def dep(self, src_task: Any, dst_label: str, flow: str = "") -> None:
        """Edge from an executed task to a (possibly not-yet-created)
        successor instance, identified by its printed name."""
        if not self.enabled:
            return
        with self._lock:
            self._edges.append((src_task.snprintf(), dst_label, flow))

    # -- export -------------------------------------------------------------
    def to_dot(self, name: str = "dag") -> str:
        with self._lock:
            nodes = dict(self._nodes)
            edges = list(self._edges)
        classes = sorted({n["tc"] for n in nodes.values()})
        color = {tc: _COLORS[i % len(_COLORS)] for i, tc in enumerate(classes)}
        out = [f"digraph {name} {{", "  node [style=filled];"]
        for label, n in sorted(nodes.items(), key=lambda kv: kv[1]["order"]):
            out.append(
                f'  {_node_id(label)} [label="{label}",'
                f'fillcolor="{color[n["tc"]]}",thid={n["thid"]}];')
        for src, dst, flow in edges:
            attr = f' [label="{flow}"]' if flow else ""
            out.append(f"  {_node_id(src)} -> {_node_id(dst)}{attr};")
        out.append("}")
        return "\n".join(out)

    def dump(self, path: str, name: str = "dag") -> str:
        with open(path, "w") as fh:
            fh.write(self.to_dot(name))
        return path

    def nb_nodes(self) -> int:
        return len(self._nodes)

    def nb_edges(self) -> int:
        return len(self._edges)


#: process-wide singleton, same lifecycle as the reference's per-rank grapher
grapher = Grapher()
