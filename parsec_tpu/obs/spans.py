"""Span-tracing hooks for the comm engine and device layer.

The scheduler hot path already has PINS sites; the comm engine and
devices had none. ``CommObs`` / ``DeviceObs`` are the per-rank hook
objects those layers call through a single attribute check
(``self._obs is not None`` — the PINS ``_active == 0`` pattern), so
uninstrumented runs pay one attribute load per site and nothing else.

Spans land in the rank's ``profiling.trace.Profile`` on dedicated
streams (``comm``, ``dev:<name>``) so Perfetto shows communication and
transfers as their own rows next to the worker exec rows; byte counters
land in the context's SDE registry under ``PARSEC::COMM::*`` /
``PARSEC::DEVICE::*``; transfer latencies feed the metrics histogram.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .metrics import COMM_XFER_SECONDS, MetricsRegistry

__all__ = ["CommObs", "DeviceObs", "OverlapTracker",
           "register_device_gauges",
           "COMM_BYTES_SENT", "COMM_BYTES_RECEIVED",
           "COMM_MSGS_SENT", "COMM_MSGS_RECEIVED",
           "COMM_ACTIVE_TRANSFERS", "COMM_PENDING_MESSAGES",
           "COMM_COALESCED", "COMM_CHUNKS_INFLIGHT",
           "COMM_COMPRESS_RATIO", "COMM_LINK_BW_PREFIX",
           "COMM_RECONNECTS", "COMM_REPLAYED_FRAMES",
           "COMM_DUP_DROPPED", "COMM_SUSPECT_MS",
           "FT_PEER_ALIVE", "FT_HB_RTT_PREFIX",
           "OBS_OVERLAP_FRACTION", "OBS_EXPOSED_COMM_US",
           "OBS_FLOW_SENT", "OBS_FLOW_RECV", "OBS_CLOCK_OFFSET_PREFIX",
           "OBS_HEALTH_STATUS", "OBS_HEALTH_WINDOWS",
           "OBS_HEALTH_FIRINGS", "OBS_HEALTH_STRAGGLER",
           "OBS_HEALTH_DEGRADED", "OBS_HEALTH_STUCK",
           "OBS_HEALTH_WORST_LINK_US",
           "TUNE_DECISIONS", "TUNE_REVERTS",
           "TUNE_ACTIVE_CODEC_PREFIX", "TUNE_OBJECTIVE_US",
           "SERVE_TENANTS", "SERVE_ADMITTED", "SERVE_REJECTED",
           "SERVE_QUEUED", "SERVE_INFLIGHT_PREFIX",
           "SERVE_QUOTA_BYTES_PREFIX", "SERVE_P99_LATENCY_PREFIX",
           "XSTAGE_COMPILES", "XSTAGE_TASKS",
           "XSTAGE_COLLECTIVE_BYTES", "XSTAGE_FALLBACKS",
           "flow_event_id", "inbound_flow_ctx", "set_inbound_flow_ctx",
           "payload_nbytes"]

COMM_BYTES_SENT = "PARSEC::COMM::BYTES_SENT"
COMM_BYTES_RECEIVED = "PARSEC::COMM::BYTES_RECEIVED"
COMM_MSGS_SENT = "PARSEC::COMM::MSGS_SENT"
COMM_MSGS_RECEIVED = "PARSEC::COMM::MSGS_RECEIVED"
COMM_ACTIVE_TRANSFERS = "PARSEC::COMM::ACTIVE_TRANSFERS"
COMM_PENDING_MESSAGES = "PARSEC::COMM::PENDING_MESSAGES"
# wire fast-path telemetry (TCP transport): messages that rode a
# multi-message coalesced frame, chunked-transfer segments in flight,
# cumulative compressed/raw byte ratio, and per-peer send-bandwidth
# EWMA gauges (PARSEC::COMM::LINK_BW::R<peer>, MB/s)
COMM_COALESCED = "PARSEC::COMM::COALESCED"
COMM_CHUNKS_INFLIGHT = "PARSEC::COMM::CHUNKS_INFLIGHT"
COMM_COMPRESS_RATIO = "PARSEC::COMM::COMPRESS_RATIO"
COMM_LINK_BW_PREFIX = "PARSEC::COMM::LINK_BW"
# reliable-session telemetry (comm/tcp.py, ISSUE 10): completed link
# reconnects, frames replayed from the window after a resume,
# duplicate frames the receiver dropped by seq, and cumulative
# milliseconds peers spent in SUSPECT (live episode included)
COMM_RECONNECTS = "PARSEC::COMM::RECONNECTS"
COMM_REPLAYED_FRAMES = "PARSEC::COMM::REPLAYED_FRAMES"
COMM_DUP_DROPPED = "PARSEC::COMM::DUP_DROPPED"
COMM_SUSPECT_MS = "PARSEC::COMM::SUSPECT_MS"
# device-plane / planned-redistribution telemetry (xfer/, ISSUE 19):
# bulk bytes and pull count that left the session wire for the device
# plane, alltoall rounds the redistribution planner executed, and
# two-level hierarchical reductions the wave collective lane issued —
# engine-owned counters (ce.dplane_stats), polled like elastic_stats
COMM_DPLANE_BYTES = "PARSEC::COMM::DPLANE_BYTES"
COMM_DPLANE_XFERS = "PARSEC::COMM::DPLANE_XFERS"
COMM_REDIST_ROUNDS = "PARSEC::COMM::REDIST_ROUNDS"
COMM_TWO_LEVEL_REDUCES = "PARSEC::COMM::TWO_LEVEL_REDUCES"
# fault-tolerance telemetry (ft/detector.py): peers currently confirmed
# alive, and the per-peer heartbeat round-trip EWMA in milliseconds
# (PARSEC::FT::HB_RTT::R<peer>, 0 until measured)
FT_PEER_ALIVE = "PARSEC::FT::PEER_ALIVE"
FT_HB_RTT_PREFIX = "PARSEC::FT::HB_RTT"
# elastic recovery telemetry (ft/elastic.py): completed grid resizes
# (shrink + grow) on this rank, joiners folded in, and the cross-grid
# reshard volume/wall landed here — engine-owned counters
# (ce.elastic_stats), polled like every other engine gauge
FT_ELASTIC_RESIZES = "PARSEC::FT::ELASTIC_RESIZES"
FT_ELASTIC_JOINS = "PARSEC::FT::ELASTIC_JOINS"
FT_RESHARD_BYTES = "PARSEC::FT::RESHARD_BYTES"
FT_RESHARD_US = "PARSEC::FT::RESHARD_US"
# LIVE T3-style overlap telemetry (ISSUE 7): the fraction of this
# rank's communication time (comm spans + host<->device transfers)
# hidden under task execution, and the exposed remainder in us — the
# same metric obs/critpath.py computes offline, maintained online by
# OverlapTracker so perf gates can assert it DURING a run.  1.0 for a
# zero-comm rank (nothing to hide = nothing exposed).
OBS_OVERLAP_FRACTION = "PARSEC::OBS::OVERLAP_FRACTION"
OBS_EXPOSED_COMM_US = "PARSEC::OBS::EXPOSED_COMM_US"
# cross-rank flow tracing (ISSUE 15): wire trace contexts stamped on
# data-plane messages under the ``obs_flow`` knob — FLOW_SENT counts
# the sender halves ("s" flow events), FLOW_RECV the receiver halves
# ("f"); and the NTP-style per-peer clock-offset estimate in µs
# (PARSEC::OBS::CLOCK_OFFSET_US::R<peer>, peer_clock - my_clock, 0
# until measured; identically 0 on same-clock in-process fabrics)
OBS_FLOW_SENT = "PARSEC::OBS::FLOW_SENT"
OBS_FLOW_RECV = "PARSEC::OBS::FLOW_RECV"
OBS_CLOCK_OFFSET_PREFIX = "PARSEC::OBS::CLOCK_OFFSET_US"
# streaming health monitor (ISSUE 16, obs/live.py, ``obs_live`` knob):
# current detector verdict (0 healthy / 1 degraded / 2 stuck), rolling
# windows folded, total detector firings plus the per-kind breakdown,
# and the worst link's cumulative exposed-wait in µs.  Registered ONLY
# when the knob is set — an unset knob adds no gauges at all.
OBS_HEALTH_STATUS = "PARSEC::OBS::HEALTH::STATUS"
OBS_HEALTH_WINDOWS = "PARSEC::OBS::HEALTH::WINDOWS"
OBS_HEALTH_FIRINGS = "PARSEC::OBS::HEALTH::FIRINGS"
OBS_HEALTH_STRAGGLER = "PARSEC::OBS::HEALTH::STRAGGLER_FIRINGS"
OBS_HEALTH_DEGRADED = "PARSEC::OBS::HEALTH::DEGRADED_LINK_FIRINGS"
OBS_HEALTH_STUCK = "PARSEC::OBS::HEALTH::STUCK_FIRINGS"
OBS_HEALTH_WORST_LINK_US = "PARSEC::OBS::HEALTH::WORST_LINK_EXPOSED_US"
# closed-loop self-tuning (ISSUE 17, tune/controller.py, ``tune_auto``
# knob): knob moves the controller committed, moves it rolled back on
# objective regression, the codec-ladder rung actually active toward a
# peer (PARSEC::TUNE::ACTIVE_CODEC::R<peer>, 0 lossless / 1 qbf16 /
# 2 qint8), and the device us/task objective EWMA the pipeline
# hill-climber steers by.  Registered ONLY under the knob — an unset
# knob constructs no controller and adds no gauges.
TUNE_DECISIONS = "PARSEC::TUNE::DECISIONS"
TUNE_REVERTS = "PARSEC::TUNE::REVERTS"
TUNE_ACTIVE_CODEC_PREFIX = "PARSEC::TUNE::ACTIVE_CODEC"
TUNE_OBJECTIVE_US = "PARSEC::TUNE::OBJECTIVE_US"
# cross-rank SPMD stages (ISSUE 20, stagec/xrank.py, guide §6.4/§9.1):
# wave-front stages compiled as ONE shard_map program over the spanning
# ranks' lane devices — programs built, member tasks they retired,
# boundary-tile bytes moved by the in-program all-gather (per rank:
# payload bytes received from peers inside the program), and planned
# cross-rank dispatches that downgraded to the rank-local ladder
XSTAGE_COMPILES = "PARSEC::STAGEC::XSTAGE_COMPILES"
XSTAGE_TASKS = "PARSEC::STAGEC::XSTAGE_TASKS"
XSTAGE_COLLECTIVE_BYTES = "PARSEC::STAGEC::XSTAGE_COLLECTIVE_BYTES"
XSTAGE_FALLBACKS = "PARSEC::STAGEC::XSTAGE_FALLBACKS"
# multi-tenant persistent serving (ISSUE 18, serve/server.py, ``serve``
# knob family): open tenant sessions, admission outcomes (admitted /
# rejected / queued submissions across all tenants), and per-tenant
# gauges registered at open_tenant — in-flight taskpools
# (PARSEC::SERVE::INFLIGHT::<tenant>), bytes charged against the
# declared Mempool quota (PARSEC::SERVE::QUOTA_BYTES::<tenant>), and
# the rolling p99 taskpool latency
# (PARSEC::SERVE::P99_LATENCY_US::<tenant>).  Registered ONLY when a
# SessionServer is constructed — no server, no gauges.
SERVE_TENANTS = "PARSEC::SERVE::TENANTS"
SERVE_ADMITTED = "PARSEC::SERVE::ADMITTED"
SERVE_REJECTED = "PARSEC::SERVE::REJECTED"
SERVE_QUEUED = "PARSEC::SERVE::QUEUED"
SERVE_INFLIGHT_PREFIX = "PARSEC::SERVE::INFLIGHT"
SERVE_QUOTA_BYTES_PREFIX = "PARSEC::SERVE::QUOTA_BYTES"
SERVE_P99_LATENCY_PREFIX = "PARSEC::SERVE::P99_LATENCY_US"


def flow_event_id(ctx: Tuple[int, ...]) -> int:
    """The Chrome-trace flow id of one wire trace context: the span id
    with the origin rank in the high bits, so ids from every rank's
    allocator stay globally unique in a merged timeline.  Tolerates the
    obs_live EXTENDED context ``(origin, span, pool, t_send_ns)`` — the
    flow id depends only on the first two fields, so a live-extended
    edge stitches with a plain one."""
    origin, span = ctx[0], ctx[1]
    return (int(origin) << 40) | (int(span) & ((1 << 40) - 1))


#: inbound trace context of the message currently being delivered on
#: this thread (remote_dep sets it around the activation walk) — how a
#: compiled stage task learns which wire flows fed it without any
#: signature change through the activate chain (stagec/runtime.py)
_INBOUND_TLS = threading.local()


def inbound_flow_ctx() -> Optional[Tuple[int, int]]:
    return getattr(_INBOUND_TLS, "ctx", None)


def set_inbound_flow_ctx(ctx: Optional[Tuple[int, int]]) -> None:
    _INBOUND_TLS.ctx = ctx

#: trace stream ids (outside any plausible worker th_id range)
COMM_STREAM_TID = 1 << 20
DEVICE_STREAM_TID = (1 << 20) + 1
#: the obs_live monitor's annotation stream (detector firings land as
#: Chrome-trace instant events so merged timelines show verdicts at
#: the right instant); must stay above every DEVICE_STREAM_TID + index
HEALTH_STREAM_TID = (1 << 20) + (1 << 10)


_TAG_NAMES: Dict[int, str] = {}


def _tag_name(tag: int) -> str:
    """Human label for a wire tag (span names beat raw tag ints in
    Perfetto). Lazy so obs never imports the comm layer at module load."""
    if not _TAG_NAMES:
        from ..comm import engine as _e
        _TAG_NAMES.update({
            _e.TAG_ACTIVATE: "activate", _e.TAG_GET_REQ: "get_req",
            _e.TAG_GET_DATA: "get_data", _e.TAG_PUT_DATA: "put_data",
            _e.TAG_TERMDET: "termdet", _e.TAG_DTD_DATA: "dtd_data",
            _e.TAG_MEM_PUT: "mem_put"})
    return _TAG_NAMES.get(tag, str(tag))


def payload_nbytes(payload: Any) -> int:
    """Structural byte count of an AM payload. Sender and receiver apply
    the SAME function to the SAME structure (deep-copied or re-pickled by
    the wire), so BYTES_SENT and BYTES_RECEIVED balance across ranks."""
    nbytes = getattr(payload, "nbytes", None)
    if nbytes is not None:
        return int(nbytes)
    if isinstance(payload, dict):
        return sum(payload_nbytes(v) for v in payload.values())
    if isinstance(payload, (list, tuple)):
        return sum(payload_nbytes(v) for v in payload)
    return 8


class OverlapTracker:
    """Online compute/comm interval accumulator behind the live
    ``PARSEC::OBS::OVERLAP_FRACTION`` gauge (ISSUE 7).

    The span sinks report completed intervals into two channels —
    ``compute`` (task execution, fed by the EXEC-site timer) and
    ``comm`` (comm-engine spans + host<->device transfers).  The gauge
    read merges each channel's union and intersects them — the exact
    T3 metric obs/critpath.py computes offline, on the live run.
    Appends are O(1) under a lock; past ``COALESCE_AT`` intervals per
    channel the lists merge, and if still too long the old prefix
    (everything before a shared time watermark) is SEALED into scalar
    totals — its union length and cross-channel intersection are exact
    at seal time, so the reported fractions never drift while memory
    stays bounded on long runs.  (The one approximation: a span that
    *completes* after a seal but *started* before the watermark can no
    longer intersect sealed intervals of the other channel, so overlap
    may be slightly under-reported — conservative for a gate.)
    Timestamps are monotonic-ns (the span sinks' clock); intervals are
    stored in microseconds."""

    __slots__ = ("_lock", "_iv", "_closed")

    COALESCE_AT = 4096
    #: intervals kept live per channel after a seal
    KEEP_AT = 1024

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._iv: Dict[str, List[Tuple[float, float]]] = {
            "compute": [], "comm": []}
        # sealed-prefix totals (us): exact union lengths + their exact
        # intersection, accumulated when old intervals are retired
        self._closed = {"compute_us": 0.0, "comm_us": 0.0,
                        "overlap_us": 0.0}

    def note(self, channel: str, t0_ns: int, t1_ns: int) -> None:
        if t1_ns <= t0_ns:
            return
        with self._lock:
            self._iv[channel].append((t0_ns / 1e3, t1_ns / 1e3))
            if len(self._iv[channel]) > self.COALESCE_AT:
                self._compact_locked()

    def _compact_locked(self) -> None:
        """Merge both channels; if a channel is still over the budget
        (disjoint intervals cannot merge away), seal everything before
        a shared watermark into the closed totals."""
        from .critpath import merge_intervals, overlap_us
        comp = merge_intervals(self._iv["compute"])
        comm = merge_intervals(self._iv["comm"])
        if max(len(comp), len(comm)) > self.COALESCE_AT:
            # watermark: the start of the KEEP_AT-th-from-last interval
            # of every over-budget channel — both channels seal at the
            # SAME cut so the sealed intersection is exact
            w = min(ch[-self.KEEP_AT][0] for ch in (comp, comm)
                    if len(ch) > self.KEEP_AT)

            def split(ivs):
                old, new = [], []
                for b, e in ivs:
                    if e <= w:
                        old.append((b, e))
                    elif b >= w:
                        new.append((b, e))
                    else:           # straddles the cut: clip, no loss
                        old.append((b, w))
                        new.append((w, e))
                return old, new

            old_comp, comp = split(comp)
            old_comm, comm = split(comm)
            self._closed["compute_us"] += sum(e - b for b, e in old_comp)
            self._closed["comm_us"] += sum(e - b for b, e in old_comm)
            self._closed["overlap_us"] += overlap_us(old_comp, old_comm)
        self._iv["compute"], self._iv["comm"] = comp, comm

    def snapshot(self) -> Dict[str, float]:
        from .critpath import merge_intervals, overlap_us
        with self._lock:
            comp = list(self._iv["compute"])
            comm = list(self._iv["comm"])
            closed = dict(self._closed)
        comp = merge_intervals(comp)
        comm = merge_intervals(comm)
        comm_us = closed["comm_us"] + sum(e - b for b, e in comm)
        hidden = closed["overlap_us"] + overlap_us(comp, comm)
        return {"compute_us": (closed["compute_us"]
                               + sum(e - b for b, e in comp)),
                "comm_us": comm_us, "overlap_us": hidden,
                # zero-comm: nothing to hide — report PERFECT overlap
                # (1.0) so gates don't trip on comm-free ranks
                "overlap_fraction": (hidden / comm_us if comm_us > 0
                                     else 1.0)}

    def fraction(self) -> float:
        return round(self.snapshot()["overlap_fraction"], 4)

    def exposed_us(self) -> float:
        s = self.snapshot()
        return round(s["comm_us"] - s["overlap_us"], 1)


class CommObs:
    """Per-rank comm telemetry sink. Construct with the rank's metrics
    registry and (optionally) its Profile; every hook is safe to call
    from any thread."""

    __slots__ = ("metrics", "stream", "_open_gets", "_hist", "tracker",
                 "live")

    def __init__(self, metrics: MetricsRegistry,
                 profile: Optional[Any] = None,
                 tracker: Optional[OverlapTracker] = None,
                 live: Optional[Any] = None) -> None:
        self.metrics = metrics
        self.stream = (profile.stream(COMM_STREAM_TID, "comm")
                       if profile is not None else None)
        self._open_gets: Dict[int, int] = {}  # token -> t0_ns
        self._hist = metrics.histogram(COMM_XFER_SECONDS)
        self.tracker = tracker
        # obs_live streaming monitor (ISSUE 16): every span the sink
        # records is ALSO folded into the rolling health channels with
        # the same src/dst attribution the span args carry, so the live
        # per-link exposure matches the offline per-link report
        self.live = live

    # -- active messages -----------------------------------------------------
    def am_sent(self, src: int, dst: int, tag: int, payload: Any,
                t0_ns: int) -> None:
        nbytes = payload_nbytes(payload)
        sde = self.metrics.sde
        sde.inc(COMM_MSGS_SENT)
        sde.inc(COMM_BYTES_SENT, nbytes)
        t1 = time.monotonic_ns()
        if self.tracker is not None:
            self.tracker.note("comm", t0_ns, t1)
        if self.live is not None:
            self.live.note_comm(t0_ns, t1, src=src, dst=dst)
        st = self.stream
        if st is not None:
            st.span("comm:send", t0_ns, t1,
                    {"src": src, "dst": dst, "tag": tag, "bytes": nbytes})

    def am_arrived(self, src: int, tag: int, payload: Any) -> None:
        """Counted at arrival (even if the tag's handler is not bound yet
        and the message is deferred) so sent/received totals balance."""
        sde = self.metrics.sde
        sde.inc(COMM_MSGS_RECEIVED)
        sde.inc(COMM_BYTES_RECEIVED, payload_nbytes(payload))

    def delivered(self, src: int, me: int, tag: int, t0_ns: int) -> None:
        t1 = time.monotonic_ns()
        if self.live is not None:
            # delivers are comm spans offline (critpath._is_comm) but
            # NOT OverlapTracker channels — the live monitor keeps its
            # own channels so its numbers parity-match the report
            self.live.note_comm(t0_ns, t1, src=src, dst=me)
        st = self.stream
        if st is not None:
            st.span(f"comm:deliver:{_tag_name(tag)}", t0_ns, t1,
                    {"src": src, "dst": me, "tag": tag})

    # -- cross-rank flow edges (ISSUE 15) ------------------------------------
    def flow_sent(self, dst: int, tag: int, ctx: Any, t0_ns: int) -> None:
        """The sender half of one wire flow edge: the message left with
        trace context ``ctx`` stamped on it at enqueue time ``t0_ns``."""
        self.metrics.sde.inc(OBS_FLOW_SENT)
        # serve-extended context (ISSUE 18): field 4 is the tenant that
        # submitted the pool — None on live-only contexts and on serve
        # traffic of pools no server owns
        tenant = ctx[4] if len(ctx) >= 5 else None
        if self.live is not None and len(ctx) >= 4:
            # extended live context: field 2 is the taskpool wire id
            self.live.note_flow_sent(dst, ctx[2], tenant=tenant)
        st = self.stream
        if st is not None:
            args = {"dst": dst}
            if tenant is not None:
                args["tenant"] = tenant
            st.flow(f"flow:{_tag_name(tag)}", flow_event_id(ctx), "s",
                    t0_ns, args)

    def flow_recv(self, src: int, tag: int, ctx: Any) -> None:
        """The receiver half: a message carrying ``ctx`` arrived —
        recorded once per message at arrival (deferred or not), so the
        merged timeline stitches exactly one edge per wire hop."""
        self.metrics.sde.inc(OBS_FLOW_RECV)
        t1 = time.monotonic_ns()
        tenant = ctx[4] if len(ctx) >= 5 else None
        if self.live is not None and len(ctx) >= 4:
            # extended live context: (origin, span, pool, t_send_ns) —
            # the sender's monotonic send instant converts to lag via
            # the live clock-offset estimate inside the monitor
            self.live.note_flow_recv(src, ctx[2], ctx[3], t1,
                                     tenant=tenant)
        st = self.stream
        if st is not None:
            args = {"src": src}
            if tenant is not None:
                args["tenant"] = tenant
            st.flow(f"flow:{_tag_name(tag)}", flow_event_id(ctx), "f",
                    t1, args)

    # -- one-sided transfers -------------------------------------------------
    def get_begin(self, token: int, src_rank: int) -> None:
        self._open_gets[token] = time.monotonic_ns()

    def get_end(self, token: int, src_rank: int, payload: Any) -> None:
        t0 = self._open_gets.pop(token, None)
        if t0 is None:
            return
        t1 = time.monotonic_ns()
        self._hist.observe((t1 - t0) / 1e9)
        if self.tracker is not None:
            self.tracker.note("comm", t0, t1)
        if self.live is not None:
            self.live.note_comm(t0, t1, src=src_rank)
        st = self.stream
        if st is not None:
            st.span("comm:get", t0, t1,
                    {"src": src_rank, "token": token,
                     "bytes": payload_nbytes(payload)})

    def put(self, dst_rank: int, payload: Any, t0_ns: int) -> None:
        # the span covers the local post only (one-sided puts complete
        # on the receiver's progress with no ack) — so puts do NOT feed
        # the transfer-latency histogram; GETs, which have a matched
        # reply, do
        t1 = time.monotonic_ns()
        if self.tracker is not None:
            self.tracker.note("comm", t0_ns, t1)
        if self.live is not None:
            self.live.note_comm(t0_ns, t1, dst=dst_rank)
        st = self.stream
        if st is not None:
            st.span("comm:put", t0_ns, t1,
                    {"dst": dst_rank, "bytes": payload_nbytes(payload)})

    # -- generic protocol spans (remote_dep et al.) --------------------------
    def span(self, key: str, t0_ns: int, info: Any = None) -> None:
        t1 = time.monotonic_ns()
        if self.tracker is not None:
            self.tracker.note("comm", t0_ns, t1)
        if self.live is not None:
            src = dst = None
            if isinstance(info, dict):
                src, dst = info.get("src"), info.get("dst")
            self.live.note_comm(t0_ns, t1, src=src, dst=dst)
        st = self.stream
        if st is not None:
            st.span(key, t0_ns, t1, info)

    # -- progress ------------------------------------------------------------
    def progress(self, handled: int, t0_ns: int) -> None:
        """Called after a drain; only drains that handled at least one
        message become spans (idle polls would drown the trace)."""
        if handled <= 0:
            return
        t1 = time.monotonic_ns()
        if self.live is not None:
            # progress drains are comm:* offline too (unattributed —
            # they widen the comm union for the overlap fraction)
            self.live.note_comm(t0_ns, t1)
        st = self.stream
        if st is not None:
            st.span("comm:progress", t0_ns, t1, {"handled": handled})

    # -- engine gauge wiring -------------------------------------------------
    def register_engine_gauges(self, ce: Any) -> None:
        """Pull gauges over the engine's live queues: outstanding GET
        tokens (ACTIVE_TRANSFERS), not-yet-deliverable deferred
        messages (PENDING_MESSAGES), and — on transports with the wire
        fast path — coalescing/chunking/compression counters plus
        per-peer link-bandwidth EWMA gauges. Poll-only: nothing lands
        on the transport's hot path."""
        sde = self.metrics.sde
        get_cbs = getattr(ce, "_get_cbs", None)
        if get_cbs is not None:
            sde.register_poll(COMM_ACTIVE_TRANSFERS, lambda: len(get_cbs))
        sde.register_poll(COMM_PENDING_MESSAGES,
                          lambda: len(ce._deferred))
        ws = getattr(ce, "wire_stats", None)
        if ws is not None:
            sde.register_poll(COMM_COALESCED,
                              lambda w=ws: w["coalesced_msgs"])
        if ws is not None and "reconnects" in ws:
            sde.register_poll(COMM_RECONNECTS,
                              lambda w=ws: w["reconnects"])
            sde.register_poll(COMM_REPLAYED_FRAMES,
                              lambda w=ws: w["replayed_frames"])
            sde.register_poll(COMM_DUP_DROPPED,
                              lambda w=ws: w["dup_dropped"])
        if hasattr(ce, "suspect_ms"):
            sde.register_poll(COMM_SUSPECT_MS, ce.suspect_ms)
        if hasattr(ce, "chunks_inflight"):
            sde.register_poll(COMM_CHUNKS_INFLIGHT, ce.chunks_inflight)
        if hasattr(ce, "compress_ratio"):
            sde.register_poll(
                COMM_COMPRESS_RATIO,
                lambda c=ce: (lambda r: 1.0 if r is None else r)(
                    c.compress_ratio()))
        if hasattr(ce, "codec_ratio") and hasattr(ce, "wire_codec_names"):
            # per-link, CODEC-LABELED reduction ratios (ISSUE 14):
            # COMPRESS_RATIO::R<peer>::<codec> is raw/encoded (> 1 =
            # that codec engaged and shrank the wire; 1.0 = inactive),
            # so lossless-vs-quantized engagement is distinguishable
            # per link in /metrics
            for peer in range(ce.nb_ranks):
                if peer == ce.rank:
                    continue
                for cname in ce.wire_codec_names():
                    sde.register_poll(
                        f"{COMM_COMPRESS_RATIO}::R{peer}::{cname}",
                        lambda c=ce, p=peer, n=cname: c.codec_ratio(p, n))
        if hasattr(ce, "link_bw_mbps"):
            for peer in range(ce.nb_ranks):
                if peer == ce.rank:
                    continue
                sde.register_poll(
                    f"{COMM_LINK_BW_PREFIX}::R{peer}",
                    lambda c=ce, p=peer: (lambda b: 0.0 if b is None
                                          else round(b, 3))(
                        c.link_bw_mbps(p)))
        flow_on = getattr(ce, "_flow_enabled", None)
        if flow_on is None:
            from ..utils.params import params
            flow_on = bool(params.get_or("obs_flow", "bool", False))
        if flow_on and hasattr(ce, "clock_offset_us"):
            # per-peer clock-offset estimate (ISSUE 15): peer_clock -
            # my_clock in µs, 0 until a clock-extended pong landed (and
            # identically 0 on same-clock in-process fabrics).  Only
            # under the knob: a big fleet with metrics on must not pay
            # nb_ranks-1 lock-taking polls per sample for a feature
            # that is off
            for peer in range(ce.nb_ranks):
                if peer == ce.rank:
                    continue
                sde.register_poll(
                    f"{OBS_CLOCK_OFFSET_PREFIX}::R{peer}",
                    lambda c=ce, p=peer: (lambda o: 0.0 if o is None
                                          else o)(c.clock_offset_us(p)))
        ds = getattr(ce, "dplane_stats", None)
        if ds is not None:
            sde.register_poll(COMM_DPLANE_BYTES,
                              lambda s=ds: s["dplane_bytes"])
            sde.register_poll(COMM_DPLANE_XFERS,
                              lambda s=ds: s["dplane_xfers"])
            sde.register_poll(COMM_REDIST_ROUNDS,
                              lambda s=ds: s["redist_rounds"])
            sde.register_poll(COMM_TWO_LEVEL_REDUCES,
                              lambda s=ds: s["two_level_reduces"])
        es = getattr(ce, "elastic_stats", None)
        if es is not None:
            sde.register_poll(FT_ELASTIC_RESIZES,
                              lambda s=es: s["elastic_resizes"])
            sde.register_poll(FT_ELASTIC_JOINS,
                              lambda s=es: s["elastic_joins"])
            sde.register_poll(FT_RESHARD_BYTES,
                              lambda s=es: s["reshard_bytes"])
            sde.register_poll(FT_RESHARD_US,
                              lambda s=es: s["reshard_us"])
        det = getattr(ce, "ft_detector", None)
        if det is not None:
            sde.register_poll(FT_PEER_ALIVE, det.alive_count)
            for peer in range(ce.nb_ranks):
                if peer == ce.rank:
                    continue
                sde.register_poll(
                    f"{FT_HB_RTT_PREFIX}::R{peer}",
                    lambda d=det, p=peer: (lambda r: 0.0 if r is None
                                           else round(r * 1e3, 3))(
                        d.rtt_s(p)))


def register_device_gauges(sde: Any, device: Any) -> None:
    """Pull gauges over one device's accounting state — poll-only, so
    registering them costs nothing on any hot path (safe to do for
    uninstrumented runs too)."""
    prefix = f"PARSEC::DEVICE::{device.name}"
    sde.register_poll(f"{prefix}::TASKS",
                      lambda d=device: d.executed_tasks)
    sde.register_poll(f"{prefix}::LOAD", lambda d=device: d.device_load)
    if hasattr(device, "mem_used"):
        sde.register_poll(f"{prefix}::MEM_USED",
                          lambda d=device: d.mem_used)
    if hasattr(device, "mem_highwater"):
        sde.register_poll(f"{prefix}::MEM_HIGHWATER",
                          lambda d=device: d.mem_highwater)
    if hasattr(device, "mesh_shards"):
        # chips in the device's mesh (device_mesh_shape; ISSUE 6) —
        # COLLECTIVE_BYTES / MESH_DISPATCHES / MESH_MOVES ride the
        # stats loop below
        sde.register_poll(f"{prefix}::MESH_SHARDS",
                          lambda d=device: d.mesh_shards)
    stats = getattr(device, "stats", None)
    if isinstance(stats, dict):
        for key in stats:
            sde.register_poll(f"{prefix}::{key.upper()}",
                              lambda s=stats, k=key: s[k])
    # batched-dispatch pipeline health (guide §9.1): mean tasks per
    # stacked dispatch, fraction of prefetched stage-ins that the
    # consuming task found already resident, and the mean CPU-side
    # dispatch cost per task (batched + per-task submissions combined)
    if isinstance(stats, dict) and "batches" in stats:
        sde.register_poll(
            f"{prefix}::BATCH_OCCUPANCY",
            lambda s=stats: round(s["batched_tasks"] / s["batches"], 3)
            if s["batches"] else 0.0)
    if isinstance(stats, dict) and "prefetch_issued" in stats:
        sde.register_poll(
            f"{prefix}::PREFETCH_HIT_RATE",
            lambda s=stats: round(s["prefetch_hits"]
                                  / s["prefetch_issued"], 3)
            if s["prefetch_issued"] else 0.0)
    if isinstance(stats, dict) and "dispatch_ns" in stats:
        sde.register_poll(
            f"{prefix}::DISPATCH_US",
            lambda s=stats: round(s["dispatch_ns"] / 1e3
                                  / s["dispatch_tasks"], 3)
            if s["dispatch_tasks"] else 0.0)


class DeviceObs:
    """Per-device span/histogram sink — installed as ``device._obs``
    only when telemetry is enabled, so uninstrumented transfer sites
    keep the one-attribute-check fast path (gauges are registered
    separately via :func:`register_device_gauges`)."""

    __slots__ = ("metrics", "stream", "name", "_hist", "tracker", "live")

    def __init__(self, metrics: MetricsRegistry, device: Any,
                 profile: Optional[Any] = None,
                 tracker: Optional[OverlapTracker] = None,
                 live: Optional[Any] = None) -> None:
        self.metrics = metrics
        self.name = device.name
        self.stream = (profile.stream(DEVICE_STREAM_TID + device.device_index,
                                      f"dev:{device.name}")
                       if profile is not None else None)
        self._hist = metrics.histogram(COMM_XFER_SECONDS)
        self.tracker = tracker
        self.live = live

    def xfer(self, direction: str, nbytes: int, t0_ns: int) -> None:
        """A host<->device transfer completed (direction: "in"|"out")."""
        t1 = time.monotonic_ns()
        self._hist.observe((t1 - t0_ns) / 1e9)
        if self.tracker is not None:
            # transfers count as COMM for the overlap gauge — the same
            # classification the offline analyzer applies (dev:xfer*)
            self.tracker.note("comm", t0_ns, t1)
        if self.live is not None:
            self.live.note_comm(t0_ns, t1)
        st = self.stream
        if st is not None:
            st.span(f"dev:xfer_{direction}", t0_ns, t1,
                    {"device": self.name, "bytes": nbytes})
