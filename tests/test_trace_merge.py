"""Fleet trace merge + distributed critical path (ISSUE 15): clock
alignment math, send→recv edge stitching, the cross-rank critical-path
walk, per-link exposed-wait attribution, flow-pair validation, and the
obs_trace_merge / obs_report CLIs.
"""
import json

import pytest

from parsec_tpu.obs import validate_chrome_trace
from parsec_tpu.obs.critpath import (Interval, analyze,
                                     distributed_critical_path,
                                     load_flow_events, merge_intervals,
                                     merge_trace_docs,
                                     per_link_exposed_wait,
                                     rank_clock_shifts, stitch_flows,
                                     subtract_intervals)


def _doc(rank, t0_ns, events, offsets=None):
    meta = {"rank": rank, "trace_t0_ns": t0_ns}
    if offsets is not None:
        meta["clock_offsets_us"] = json.dumps(offsets)
    return {"traceEvents": events, "metadata": meta}


def _x(pid, name, ts, dur, args=None, tid=0):
    ev = {"name": name, "ph": "X", "pid": pid, "tid": tid,
          "ts": ts, "dur": dur}
    if args:
        ev["args"] = args
    return ev


def _flow(pid, phase, fid, ts, name="flow:activate"):
    ev = {"name": name, "ph": phase, "pid": pid, "tid": 0, "ts": ts,
          "id": fid, "cat": "flow"}
    if phase == "f":
        ev["bp"] = "e"
    return ev


# ---------------------------------------------------------------------- #
# clock alignment                                                        #
# ---------------------------------------------------------------------- #
def test_rank_clock_shifts_prefers_reference_measurement():
    """Rank 1's events shift by (t0_1 - t0_0)/1e3 - offset, with the
    REFERENCE rank's measurement of the peer preferred."""
    d0 = _doc(0, 1_000_000, [], offsets={"1": 250.0})
    d1 = _doc(1, 3_000_000, [], offsets={"0": -240.0})
    shifts = rank_clock_shifts([d0, d1])
    assert shifts[0] == 0.0
    # (3e6 - 1e6)/1e3 - 250 = 2000 - 250
    assert shifts[1] == pytest.approx(1750.0)


def test_rank_clock_shifts_falls_back_to_negated_peer_estimate():
    d0 = _doc(0, 0, [])                       # ref measured nothing
    d1 = _doc(1, 1_000_000, [], offsets={"0": -300.0})
    shifts = rank_clock_shifts([d0, d1])
    assert shifts[1] == pytest.approx(1000.0 - 300.0)


def test_rank_clock_shifts_without_metadata_is_zero():
    d0 = {"traceEvents": [_x(0, "exec:a", 0, 1)]}
    d1 = {"traceEvents": [_x(1, "exec:b", 0, 1)]}
    shifts = rank_clock_shifts([d0, d1])
    assert shifts == {0: 0.0, 1: 0.0}


def test_merge_applies_shifts_and_keeps_rank_rows():
    d0 = _doc(0, 0, [_x(0, "exec:a", 10.0, 5.0),
                     _flow(0, "s", 7, 12.0)], offsets={"1": 100.0})
    d1 = _doc(1, 1_000_000, [_x(1, "exec:b", 0.0, 5.0),
                             _flow(1, "f", 7, 1.0)])
    merged = merge_trace_docs([d0, d1])
    assert merged["metadata"]["merged_ranks"] == [0, 1]
    # rank 1 shifts by 1000 - 100 = 900 us
    assert merged["metadata"]["clock_shifts_us"]["1"] == \
        pytest.approx(900.0)
    by_name = {e["name"]: e for e in merged["traceEvents"]}
    assert by_name["exec:a"]["ts"] == 10.0
    assert by_name["exec:b"]["ts"] == pytest.approx(900.0)
    assert by_name["exec:b"]["pid"] == 1
    edges, unmatched = stitch_flows(load_flow_events(merged))
    assert unmatched == 0 and len(edges) == 1
    assert edges[0]["lag_us"] == pytest.approx(901.0 - 12.0)
    # a re-merge of the merged doc is a no-op shift (no trace_t0_ns)
    again = merge_trace_docs([merged])
    assert {e["name"]: e["ts"] for e in again["traceEvents"]} == \
        {e["name"]: e["ts"] for e in merged["traceEvents"]}


# ---------------------------------------------------------------------- #
# stitching + interval algebra                                           #
# ---------------------------------------------------------------------- #
def test_stitch_flows_counts_one_sided_halves():
    events = [
        {"phase": "s", "id": 1, "pid": 0, "tid": 0, "ts": 0.0,
         "name": "flow:activate", "args": None},
        {"phase": "f", "id": 1, "pid": 1, "tid": 0, "ts": 5.0,
         "name": "flow:activate", "args": None},
        {"phase": "s", "id": 2, "pid": 0, "tid": 0, "ts": 1.0,
         "name": "flow:get_req", "args": None},   # lost message
        {"phase": "f", "id": 3, "pid": 1, "tid": 0, "ts": 2.0,
         "name": "flow:get_data", "args": None},  # truncated sender
    ]
    edges, unmatched = stitch_flows(events)
    assert len(edges) == 1 and edges[0]["id"] == 1
    assert edges[0]["src"] == 0 and edges[0]["dst"] == 1
    assert edges[0]["lag_us"] == pytest.approx(5.0)
    assert unmatched == 2


def test_subtract_intervals():
    a = merge_intervals([(0.0, 10.0), (20.0, 30.0)])
    b = merge_intervals([(2.0, 4.0), (8.0, 22.0), (29.0, 40.0)])
    assert subtract_intervals(a, b) == [(0.0, 2.0), (4.0, 8.0),
                                        (22.0, 29.0)]
    assert subtract_intervals(a, []) == a
    assert subtract_intervals([], b) == []


# ---------------------------------------------------------------------- #
# distributed critical path                                              #
# ---------------------------------------------------------------------- #
def test_distributed_critpath_follows_the_binding_edge():
    """Rank 1's last task B started at 21 with its local predecessor C
    done at 2 but the inbound edge landing at 20 — the wire is the
    binding constraint; the walk crosses to rank 0's producer A."""
    intervals = [
        Interval(0, 0, "exec:A", 0.0, 10.0, {"task": "A(0)"}),
        Interval(1, 0, "exec:C", 0.0, 2.0, {"task": "C(0)"}),
        Interval(1, 0, "exec:B", 21.0, 30.0, {"task": "B(0)"}),
    ]
    edges = [{"id": 9, "name": "flow:activate", "src": 0, "dst": 1,
              "send_ts": 9.0, "recv_ts": 20.0, "lag_us": 11.0}]
    dcp = distributed_critical_path(intervals, edges)
    assert dcp["cross_edges"] == 1
    assert dcp["ranks_visited"] == [0, 1]
    kinds = [n.get("task", n.get("link")) for n in dcp["chain"]]
    assert kinds == ["A(0)", "R0->R1", "B(0)"]
    assert dcp["length_us"] == pytest.approx(30.0)


def test_distributed_critpath_prefers_later_local_predecessor():
    """When the local predecessor finished AFTER the inbound edge
    landed, the local chain is the binding constraint."""
    intervals = [
        Interval(0, 0, "exec:A", 0.0, 10.0, None),
        Interval(1, 0, "exec:C", 0.0, 19.0, None),
        Interval(1, 0, "exec:B", 21.0, 30.0, None),
    ]
    edges = [{"id": 9, "name": "flow:activate", "src": 0, "dst": 1,
              "send_ts": 5.0, "recv_ts": 12.0, "lag_us": 7.0}]
    dcp = distributed_critical_path(intervals, edges)
    assert dcp["cross_edges"] == 0
    assert [n["name"] for n in dcp["chain"]] == ["exec:C", "exec:B"]


def test_distributed_critpath_leading_edge_counts_its_lag():
    """A path may BEGIN with a wire edge (no producer interval known
    at/before the send instant): the send instant is the path start,
    so the edge's lag counts toward length_us and the chain's head is
    the wire arrival (code-review regression)."""
    intervals = [
        Interval(1, 0, "exec:gemm", 100.0, 200.0, None),
        Interval(0, 0, "exec:potrf", 150.0, 180.0, None),
    ]
    edges = [{"id": 1, "name": "flow:activate", "src": 0, "dst": 1,
              "send_ts": 50.0, "recv_ts": 99.5, "lag_us": 49.5}]
    dcp = distributed_critical_path(intervals, edges)
    assert dcp["cross_edges"] == 1
    assert "link" in dcp["chain"][0]          # head = the wire arrival
    assert dcp["length_us"] == pytest.approx(150.0)   # 200 - send(50)


def test_distributed_critpath_empty_and_cyclic_safe():
    assert distributed_critical_path([], [])["chain"] == []
    # an edge pointing FORWARD in time toward an earlier interval must
    # not loop the walk (visited guard)
    intervals = [Interval(0, 0, "exec:A", 0.0, 10.0, None),
                 Interval(1, 0, "exec:B", 11.0, 20.0, None)]
    edges = [{"id": 1, "name": "e", "src": 0, "dst": 1,
              "send_ts": 9.0, "recv_ts": 11.0, "lag_us": 2.0},
             {"id": 2, "name": "e2", "src": 1, "dst": 0,
              "send_ts": 19.0, "recv_ts": 21.0, "lag_us": 2.0}]
    dcp = distributed_critical_path(intervals, edges)
    assert len(dcp["chain"]) <= 4


# ---------------------------------------------------------------------- #
# per-link exposed wait                                                  #
# ---------------------------------------------------------------------- #
def test_per_link_exposed_wait_attribution():
    """A comm span half-hidden under compute attributes only its
    EXPOSED half to the link named by its args."""
    intervals = [
        Interval(1, 0, "exec:A", 0.0, 10.0, None),
        # 10 us of GET from rank 0: 4 hidden under exec:A, 6 exposed
        Interval(1, 5, "comm:get", 6.0, 16.0, {"src": 0, "token": 1}),
        # outbound send toward rank 2, fully exposed
        Interval(1, 5, "comm:send", 20.0, 23.0, {"src": 1, "dst": 2}),
        # a comm span with no peer args contributes to no link
        Interval(1, 5, "comm:progress", 30.0, 31.0, {"handled": 2}),
    ]
    table = per_link_exposed_wait(intervals)
    assert table[1]["R0->R1"] == pytest.approx(6.0)
    assert table[1]["R1->R2"] == pytest.approx(3.0)
    assert set(table[1]) == {"R0->R1", "R1->R2"}


def test_analyze_cross_rank_section():
    """analyze() over two synthetic rank docs produces the cross_rank
    report: stitched edges per direction, the distributed path, and
    exposed-wait per link."""
    d0 = _doc(0, 0, [
        _x(0, "exec:A", 0.0, 10.0, {"task": "A(0)"}),
        _x(0, "comm:send", 9.0, 2.0, {"src": 0, "dst": 1}),
        _flow(0, "s", 7, 9.0),
    ])
    d1 = _doc(1, 0, [
        _x(1, "comm:deliver:activate", 19.5, 1.0, {"src": 0, "dst": 1}),
        _flow(1, "f", 7, 20.0),
        _x(1, "exec:B", 21.0, 9.0, {"task": "B(0)"}),
    ])
    report = analyze([d0, d1])
    cr = report["cross_rank"]
    assert cr["flow_edges"] == 1
    assert cr["edges_per_link"] == {"R0->R1": 1}
    assert cr["unmatched_flows"] == 0
    assert cr["negative_lag_edges"] == 0
    assert cr["min_lag_us"] == pytest.approx(11.0)
    assert cr["critical_path"]["cross_edges"] == 1
    assert cr["per_link_exposed_us"][1]["R0->R1"] > 0
    # without flow events the section is absent (pre-ISSUE-15 shape)
    assert "cross_rank" not in analyze([
        {"traceEvents": [_x(0, "exec:A", 0.0, 1.0)]}])


# ---------------------------------------------------------------------- #
# validate_chrome_trace flow pairing (ISSUE 15 satellite)                #
# ---------------------------------------------------------------------- #
def test_analyze_accepts_bare_array_documents():
    """The Chrome trace's bare-JSON-array form (no metadata wrapper)
    still analyzes — the alignment helpers must not assume the object
    form (code-review regression: AttributeError on list docs)."""
    doc = [{"name": "exec:t", "ph": "X", "ts": 0.0, "dur": 5.0,
            "pid": 0, "tid": 1}]
    report = analyze([doc])
    assert report["nb_intervals"] == 1
    assert merge_trace_docs([doc])["traceEvents"]


def test_validate_counts_matched_and_unmatched_flows():
    doc = {"traceEvents": [
        _flow(0, "s", 1, 0.0), _flow(1, "f", 1, 5.0),
        _flow(0, "s", 2, 1.0),                     # lone start
        _flow(1, "f", 3, 2.0), _flow(1, "f", 4, 3.0),  # lone finishes
    ]}
    v = validate_chrome_trace(doc)
    assert v["flows"] == 1
    assert v["unmatched_flows"] == 3


def test_validate_flow_order_independent():
    """The receiver half may precede the sender half in a merged list
    (rank concatenation order) — pairing must not care."""
    doc = {"traceEvents": [_flow(1, "f", 1, 5.0), _flow(0, "s", 1, 0.0)]}
    v = validate_chrome_trace(doc)
    assert v["flows"] == 1 and v["unmatched_flows"] == 0


def test_validate_flow_requires_id_and_ts():
    with pytest.raises(ValueError, match="missing id"):
        validate_chrome_trace({"traceEvents": [
            {"name": "flow:x", "ph": "s", "ts": 0.0}]})
    with pytest.raises(ValueError, match="missing numeric ts"):
        validate_chrome_trace({"traceEvents": [
            {"name": "flow:x", "ph": "s", "id": 1}]})


# ---------------------------------------------------------------------- #
# the CLIs                                                               #
# ---------------------------------------------------------------------- #
def test_obs_trace_merge_cli(tmp_path, capsys):
    from tools import obs_trace_merge

    d0 = _doc(0, 0, [_x(0, "exec:A", 0.0, 10.0), _flow(0, "s", 7, 9.0)],
              offsets={"1": 0.0})
    d1 = _doc(1, 500_000, [_x(1, "exec:B", 0.0, 5.0),
                           _flow(1, "f", 7, 1.0)])
    p0, p1 = tmp_path / "a.rank0.trace.json", tmp_path / "a.rank1.trace.json"
    p0.write_text(json.dumps(d0))
    p1.write_text(json.dumps(d1))
    out = tmp_path / "merged.json"
    rc = obs_trace_merge.main([str(p0), str(p1), "-o", str(out),
                               "--strict"])
    assert rc == 0
    msg = capsys.readouterr().out
    assert "1 cross-rank flow edge" in msg
    with open(out) as fh:
        merged = json.load(fh)
    v = validate_chrome_trace(merged)
    assert v["flows"] == 1 and v["unmatched_flows"] == 0

    # strict mode trips on a negative corrected lag (bad alignment)
    d1_bad = _doc(1, 500_000, [_flow(1, "f", 7, 1.0)],
                  offsets={"0": -2000.0})
    p1.write_text(json.dumps(d1_bad))
    d0_bad = _doc(0, 0, [_flow(0, "s", 7, 9.0)],
                  offsets={"1": 2000.0})
    p0.write_text(json.dumps(d0_bad))
    rc = obs_trace_merge.main([str(p0), str(p1), "-o", str(out),
                               "--strict"])
    assert rc == 2


def test_obs_trace_merge_cli_tolerates_flight_records(tmp_path, capsys):
    """Forensics traces dumped mid-abort hold in-flight B-without-E
    spans; the merge CLI must still write the post-mortem (warn, not
    crash — code-review regression)."""
    from tools import obs_trace_merge

    d0 = _doc(0, 0, [
        {"name": "exec:stuck", "ph": "B", "pid": 0, "tid": 1, "ts": 0.0},
        _flow(0, "s", 7, 1.0),
    ])
    d1 = _doc(1, 0, [_flow(1, "f", 7, 5.0)])
    p0, p1 = tmp_path / "pm.rank0.json", tmp_path / "pm.rank1.json"
    p0.write_text(json.dumps(d0))
    p1.write_text(json.dumps(d1))
    out = tmp_path / "pm.merged.json"
    rc = obs_trace_merge.main([str(p0), str(p1), "-o", str(out)])
    captured = capsys.readouterr()
    assert rc == 0
    assert out.exists()
    assert "1 cross-rank flow edge" in captured.out
    assert "schema irregularities" in captured.err


def test_obs_report_prints_cross_rank_section(tmp_path, capsys):
    from tools import obs_report

    d0 = _doc(0, 0, [
        _x(0, "exec:A", 0.0, 10.0, {"task": "A(0)"}),
        _x(0, "comm:send", 9.0, 2.0, {"src": 0, "dst": 1}),
        _flow(0, "s", 7, 9.0),
    ])
    d1 = _doc(1, 0, [
        _x(1, "comm:deliver:activate", 19.5, 1.0, {"src": 0, "dst": 1}),
        _flow(1, "f", 7, 20.0),
        _x(1, "exec:B", 21.0, 9.0, {"task": "B(0)"}),
    ])
    p0, p1 = tmp_path / "r0.json", tmp_path / "r1.json"
    p0.write_text(json.dumps(d0))
    p1.write_text(json.dumps(d1))
    assert obs_report.main([str(p0), str(p1)]) == 0
    out = capsys.readouterr().out
    assert "cross-rank flow edges: 1" in out
    assert "distributed critical path:" in out
    assert "R0->R1" in out
    assert "exposed wait per link" in out
    # --json carries the raw section
    assert obs_report.main([str(p0), str(p1), "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["cross_rank"]["flow_edges"] == 1
