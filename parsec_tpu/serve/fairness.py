"""Weighted deficit fair-share boosts for multi-tenant serving.

``TenantFairness`` turns per-tenant completion accounting into the
priority boosts that ``stamp_dynamic_priority`` (runtime/scheduling.py)
folds ABOVE the class-profile band: each tenant accrues virtual runtime
``v_t = completed_tasks / weight``, and a tenant whose ``v_t`` lags the
front-runner earns a boost proportional to the lag.  A saturating
tenant's ``v_t`` races ahead (its boost decays to the floor), a starved
tenant's lags (its boost rises without bound up to the clamp) — the
deficit-round-robin invariant, expressed as priorities the untouched
ap/spq/pbq schedulers consume unchanged.

Design constraints inherited from the restamping seam (ISSUE 7):

- charging happens at pool COMPLETION (``note_done``), never at stamp
  time, so restamping the same ready set twice is idempotent;
- every queued task of one tenant shares one boost, so FIFO order
  *within* a tenant is exactly what the scheduler's priority tie-break
  already provides;
- ``boost_of_task`` is called on the scheduler hot path under no lock:
  it reads two plain dicts (``_pools``, ``_boost``) that are only ever
  rebound/assigned whole — the GIL makes each read atomic, and a stale
  boost merely delays fairness by one restamp.

Boosts are normalized so the *lowest* tenant sits at 0: pools the
server does not own (``boost_of_task`` -> 0) compete exactly like the
least-entitled tenant instead of starving behind every serve pool.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, Optional

__all__ = ["TenantFairness"]

_GUARDED_BY = {
    "TenantFairness._weight": "_lock",
    "TenantFairness._done": "_lock",
}

#: boost steps per unit of weight-normalized completion lag — coarse
#: enough that single-task jitter does not thrash restamps, fine enough
#: that a starved tenant rises within a few foreign completions
DEFICIT_GRAIN = 4.0
#: lead-term clamp: bounds the packed boost so
#: ``boost * TENANT_PRIO_SCALE`` (scheduling.py) stays well inside an
#: int64 even with the weight bias below it
_LEAD_CLAMP = (1 << 20) - 1
#: weight bias occupies the low 8 bits under the lead term: at equal
#: deficit (e.g. cold start) the heavier tenant wins the tie, which is
#: what gives a weight-8 latency tenant its head start before any
#: completion history exists
_WEIGHT_BIAS_MAX = 255


class TenantFairness:
    """Per-tenant deficit accounting -> cached priority boosts."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._weight: Dict[str, int] = {}
        self._done: Dict[str, float] = {}
        # read lock-free on the scheduler hot path; rebound whole under
        # _lock by _recompute_locked (never mutated in place)
        self._boost: Dict[str, int] = {}
        # taskpool_id -> tenant; plain-dict item set/del are GIL-atomic
        self._pools: Dict[Any, str] = {}

    # -- tenant registry ----------------------------------------------------
    def register(self, tenant: str, weight: int) -> None:
        with self._lock:
            self._weight[tenant] = max(1, int(weight))
            self._done.setdefault(tenant, 0.0)
            self._recompute_locked()

    def forget(self, tenant: str) -> None:
        with self._lock:
            self._weight.pop(tenant, None)
            self._done.pop(tenant, None)
            self._recompute_locked()

    def tenants(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._weight)

    # -- pool binding -------------------------------------------------------
    def bind_pool(self, taskpool_id: Any, tenant: str) -> None:
        self._pools[taskpool_id] = tenant

    def release_pool(self, taskpool_id: Any) -> None:
        self._pools.pop(taskpool_id, None)

    def tenant_of(self, taskpool_id: Any) -> Optional[str]:
        return self._pools.get(taskpool_id)

    # -- accounting ---------------------------------------------------------
    def note_done(self, tenant: str, n: int = 1) -> None:
        """Charge ``n`` completed work units (tasks) to ``tenant``.

        Called from the pool-completion hook — worker-thread context,
        so the recompute must stay cheap (it is O(#tenants))."""
        with self._lock:
            if tenant not in self._weight:
                return
            self._done[tenant] = self._done.get(tenant, 0.0) + float(n)
            self._recompute_locked()

    def _recompute_locked(self) -> None:  # holds: self._lock
        if not self._weight:
            self._boost = {}
            return
        v = {t: self._done.get(t, 0.0) / w
             for t, w in self._weight.items()}
        v_max = max(v.values())
        raw: Dict[str, int] = {}
        for t, w in self._weight.items():
            lead = min(_LEAD_CLAMP, int((v_max - v[t]) * DEFICIT_GRAIN))
            raw[t] = lead * (_WEIGHT_BIAS_MAX + 1) + min(w, _WEIGHT_BIAS_MAX)
        floor = min(raw.values())
        # rebind whole: hot-path readers see either the old or the new
        # dict, never a half-updated one
        self._boost = {t: b - floor for t, b in raw.items()}

    # -- scheduler hot path (lock-free) -------------------------------------
    def boost_of_task(self, task: Any) -> int:
        """The fairness boost for one task, 0 for pools the server does
        not own.  Called from ``stamp_dynamic_priority`` for every task
        of every restamp batch — no locks, two dict reads."""
        tp = task.taskpool
        if tp is None:
            return 0
        tenant = self._pools.get(tp.taskpool_id)
        if tenant is None:
            return 0
        return self._boost.get(tenant, 0)

    def boost_of_tenant(self, tenant: str) -> int:
        return self._boost.get(tenant, 0)
