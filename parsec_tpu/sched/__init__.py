"""Scheduler MCA framework: module registry + selection.

ref: mca_components_open_bytype / parsec_set_scheduler
(parsec/scheduling.c:246-272, parsec/mca/mca_repository.c).
"""
from __future__ import annotations

from typing import Dict, Type

from .base import SchedulerModule
from .modules import (APScheduler, GDScheduler, IPScheduler, LFQScheduler,
                      LHQScheduler, LLScheduler, LTQScheduler, PBQScheduler,
                      RNDScheduler, SPQScheduler)

_REGISTRY: Dict[str, Type[SchedulerModule]] = {
    cls.name: cls for cls in (
        LFQScheduler, LHQScheduler, LTQScheduler, LLScheduler, GDScheduler,
        APScheduler, IPScheduler, SPQScheduler, PBQScheduler, RNDScheduler)
}


def sched_new(name: str) -> SchedulerModule:
    try:
        return _REGISTRY[name]()
    except KeyError:
        # the reference's MCA select logs help and falls back to the
        # default component rather than failing init (scheduling.c:246-272)
        from ..utils.show_help import show_help
        show_help("help-runtime.txt", "unknown-scheduler", want_error=True,
                  name=name, available=", ".join(sorted(_REGISTRY)),
                  fallback="lfq")
        return _REGISTRY["lfq"]()


def sched_register(cls: Type[SchedulerModule]) -> None:
    _REGISTRY[cls.name] = cls


def available() -> list:
    return sorted(_REGISTRY)
