"""Test configuration: force a virtual 8-device CPU mesh before jax loads.

Multi-chip TPU hardware is not available in CI; sharding and multi-device
semantics are validated on XLA's host platform with 8 virtual devices
(the reference's analog: oversubscribed mpiexec on one node, SURVEY.md §4).
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the sandbox's TPU plugin force-prepends itself to jax_platforms; pin the
# device module to the virtual CPU platform explicitly
os.environ.setdefault("PARSEC_MCA_device_tpu_platform", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 quick run (-m 'not slow')")

# the axon plugin shadows JAX_PLATFORMS=cpu: pin eager computation to the
# virtual CPU devices and full matmul precision so references match
import jax  # noqa: E402

jax.config.update("jax_default_matmul_precision", "highest")
try:
    jax.config.update("jax_default_device", jax.devices("cpu")[0])
except RuntimeError:
    pass


def spmd(nb_ranks, fn, timeout=120, fabric=None):
    """Run fn(rank, fabric) on one thread per rank over an in-process
    fabric; propagate exceptions. Delegates to the canonical harness
    (parsec_tpu/utils/spmd.py)."""
    from parsec_tpu.utils.spmd import spmd_threads

    return spmd_threads(nb_ranks, fn, timeout=timeout, fabric=fabric)


@pytest.fixture
def ctx():
    import parsec_tpu
    c = parsec_tpu.init(nb_cores=2)
    yield c
    c.fini()


@pytest.fixture
def ctx4():
    import parsec_tpu
    c = parsec_tpu.init(nb_cores=4)
    yield c
    c.fini()
