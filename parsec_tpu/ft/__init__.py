"""ft — the fault-tolerance subsystem (detection, injection, recovery).

The reference has NO runtime-level recovery (SURVEY.md §5.4:
checkpointing "absent") and, until this subsystem, our port noticed a
dead peer only when a TCP send to it happened to fail — a rank that
went silent mid-rendezvous hung termination detection forever. At the
job lengths the source paper targets ("Large Scale Distributed Linear
Algebra With Tensor Processing Units", arXiv:2112.09017 — multi-hour
tile factorizations), mean-time-to-failure is shorter than job time,
so the runtime itself must detect, tolerate, and recover. Three
pillars:

- :mod:`ft.detector` — **proactive failure detection**: heartbeat
  probes riding the comm engines (wire-level ``K_PING``/``K_PONG``
  frames on TCP, answered by the receiver thread; ``TAG_HEARTBEAT``
  active messages on the in-process fabrics), per-peer liveness by
  plain timeout or phi-accrual-style EWMA, eviction funneled through
  the transport-uniform ``CommEngine.report_peer_failure``.
- :mod:`ft.inject` — **deterministic fault injection**: a seeded chaos
  layer (``--mca ft_inject "kill:rank=1:after=3,drop:pct=2:seed=7"``)
  that kills a rank at a task boundary, drops/duplicates/delays/fails
  sends at the wire layer — robustness is testable in-process, no real
  process kills needed.
- :mod:`ft.restart` — **checkpoint-integrated restart**: a policy
  driver wrapping the taskpool-boundary snapshots of
  ``utils/checkpoint`` — snapshot every K taskpools; on failure either
  abort cleanly or roll back to the last snapshot and re-run with
  bounded, backed-off retries.
- :mod:`ft.elastic` — **elastic grid recovery** (the fourth pillar):
  cross-grid checkpoint reshard (``reshard_restore`` — a snapshot
  written on any rank count / process grid lands on the current one
  via ``collections/redistribute``), and in-world grid RESIZE — with
  ``ft_elastic=shrink`` the survivors of a rank loss agree on a
  reduced grid over ``TAG_ELASTIC``/``K_ELASTIC`` membership frames,
  rebuild, reshard, and replay from the last snapshot; with ``grow``
  late-arriving ranks are folded in at stage boundaries.

Knobs: ``ft_heartbeat_interval``, ``ft_heartbeat_timeout``,
``ft_detector_mode``, ``ft_inject``, ``ft_restart_policy``,
``ft_elastic``, ``ft_elastic_grow_min``, ``ft_elastic_timeout`` (see
docs/guide.md §"Fault tolerance").
"""
from __future__ import annotations

from .detector import HeartbeatDetector, maybe_install_detector
from .elastic import (ElasticBlockCyclic, ElasticCoordinator, ElasticError,
                      ElasticPolicy, GridSpec, maybe_install_elastic,
                      plan_grid, reshard_restore)
from .inject import (FaultInjector, FTInjectModule, InjectedKill,
                     InjectedTaskFault)
from .restart import RestartPolicy, run_with_restart

__all__ = [
    "HeartbeatDetector", "maybe_install_detector",
    "FaultInjector", "FTInjectModule", "InjectedKill", "InjectedTaskFault",
    "RestartPolicy", "run_with_restart",
    "ElasticBlockCyclic", "ElasticCoordinator", "ElasticError",
    "ElasticPolicy", "GridSpec", "maybe_install_elastic", "plan_grid",
    "reshard_restore",
]
