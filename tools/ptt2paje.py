#!/usr/bin/env python
"""Convert .ptt binary traces to the Paje trace format (text).

The reference's Python trace tooling ships a Paje export example
(tools/profiling/python/examples/); this is the supported equivalent.
Multiple per-rank .ptt files merge into one Paje file: each rank is a
container, each thread stream a sub-container, begin/end event pairs
become PajeSetState/PajeResetState, counters become PajeSetVariable.

    python tools/ptt2paje.py trace.rank*.ptt -o run.paje
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from parsec_tpu.profiling.binfmt import read_profile  # noqa: E402

HEADER = """\
%EventDef PajeDefineContainerType 0
%  Alias string
%  Type string
%  Name string
%EndEventDef
%EventDef PajeDefineStateType 1
%  Alias string
%  Type string
%  Name string
%EndEventDef
%EventDef PajeDefineVariableType 2
%  Alias string
%  Type string
%  Name string
%EndEventDef
%EventDef PajeCreateContainer 3
%  Time date
%  Alias string
%  Type string
%  Container string
%  Name string
%EndEventDef
%EventDef PajeSetState 4
%  Time date
%  Type string
%  Container string
%  Value string
%EndEventDef
%EventDef PajeResetState 5
%  Time date
%  Type string
%  Container string
%EndEventDef
%EventDef PajeSetVariable 6
%  Time date
%  Type string
%  Container string
%  Value double
%EndEventDef
%EventDef PajeDefineEventType 7
%  Alias string
%  Type string
%  Name string
%EndEventDef
%EventDef PajeNewEvent 8
%  Time date
%  Type string
%  Container string
%  Value string
%EndEventDef
"""


def convert(paths, out):
    profs = [read_profile(p) for p in paths]
    out.write(HEADER)
    out.write('0 CT_Rank 0 "Rank"\n')
    out.write('0 CT_Thread CT_Rank "Thread"\n')
    out.write('1 ST_Task CT_Thread "Task"\n')
    out.write('7 ET_Mark CT_Thread "Marker"\n')
    # one Paje variable type per distinct counter name
    counters = sorted({key
                       for prof in profs
                       for _tid, st in prof._streams.items()
                       for _ts, ph, key, _info in st.events if ph == "C"})
    var_alias = {}
    for i, name in enumerate(counters):
        var_alias[name] = f"V{i}"
        out.write(f'2 V{i} CT_Thread "{name}"\n')
    # Paje consumers (pj_dump/pj_validate, ViTE) require globally
    # non-decreasing timestamps: emit all containers at t=0, then merge
    # every stream's events into one time-sorted sequence
    merged = []
    for prof in profs:
        rc = f"rank{prof.rank}"
        out.write(f'3 0.0 {rc} CT_Rank 0 "{rc}"\n')
        for tid, st in sorted(prof._streams.items()):
            tc = f"{rc}.t{tid}"
            out.write(f'3 0.0 {tc} CT_Thread {rc} "{st.name}"\n')
            for ts, ph, key, info in st.events:
                merged.append((ts, tc, ph, key, info))
    merged.sort(key=lambda e: e[0])
    for ts, tc, ph, key, info in merged:
        t = ts / 1e9
        if ph == "B":
            out.write(f'4 {t:.9f} ST_Task {tc} "{key}"\n')
        elif ph == "E":
            out.write(f"5 {t:.9f} ST_Task {tc}\n")
        elif ph == "X":
            # complete span (comm/device): push+pop around its duration
            t1 = (ts + (info or {}).get("dur_ns", 0)) / 1e9
            out.write(f'4 {t:.9f} ST_Task {tc} "{key}"\n')
            out.write(f"5 {t1:.9f} ST_Task {tc}\n")
        elif ph == "C":
            out.write(f"6 {t:.9f} {var_alias[key]} {tc} {float(info)}\n")
        else:  # punctual marker events (stream.trace)
            out.write(f'8 {t:.9f} ET_Mark {tc} "{key}"\n')
    return sum(p.nb_events() for p in profs)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("traces", nargs="+", help=".ptt input files")
    ap.add_argument("-o", "--output", default="trace.paje")
    args = ap.parse_args(argv)
    with open(args.output, "w") as fh:
        n = convert(args.traces, fh)
    print(f"{len(args.traces)} trace(s), {n} events -> {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
