"""core subpackage."""
