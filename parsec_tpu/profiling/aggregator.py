"""Live cross-rank counter aggregation — the aggregator_visu analog.

Reference behavior: a demo TCP server (tools/aggregator_visu/demo_server.c)
receives PAPI-SDE counter pushes from every rank of a running job; a
Python GUI aggregates and plots them live (tools/aggregator_visu/, SURVEY
§5.1 "live telemetry").

TPU-native re-design: a threaded line-JSON TCP server
(``AggregatorServer``) plus a per-context daemon pusher (``SDEPusher``)
that samples ``ctx.sde`` every interval and ships
``{"rank", "ts", "counters": {...}}``. The server keeps the latest and
extremal samples per (counter, rank) and serves a fleet-wide aggregate —
the same min/max/last/sum_of_last table ``tools/counter_aggregate.py``
computes offline — to pull clients that send the single line ``QUERY``.
Enable from any run with ``--mca sde_push host:port`` (interval knob
``sde_push_interval_ms``); the CLI front end is ``tools/aggregator_server.py``.
"""
from __future__ import annotations

import json
import socket
import socketserver
import threading
import time
from typing import Any, Dict, Optional

__all__ = ["AggregatorServer", "SDEPusher"]


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        server: "AggregatorServer" = self.server.owner  # type: ignore[attr-defined]
        for raw in self.rfile:
            line = raw.strip()
            if not line:
                continue
            if line.startswith(b"GET "):
                # minimal HTTP so a Prometheus scraper (or curl) can hit
                # the same port: GET /metrics returns text exposition of
                # every rank's latest pushed counters
                self._serve_http(server, line)
                return
            if line == b"QUERY":
                payload = json.dumps(server.fleet()).encode() + b"\n"
                self.wfile.write(payload)
                self.wfile.flush()
                continue
            try:
                msg = json.loads(line.decode())
            except ValueError:
                continue
            if isinstance(msg, dict):  # well-formed non-object JSON: drop
                server._ingest(msg)

    def _serve_http(self, server: "AggregatorServer", request: bytes) -> None:
        from ..obs.prometheus import fleet_to_prometheus
        # drain the request headers (blank line terminates)
        for raw in self.rfile:
            if not raw.strip():
                break
        path = request.split()[1].decode(errors="replace") \
            if len(request.split()) > 1 else "/"
        if path in ("/metrics", "/"):
            body = fleet_to_prometheus(server.fleet()).encode()
            head = (b"HTTP/1.0 200 OK\r\n"
                    b"Content-Type: text/plain; version=0.0.4\r\n"
                    b"Content-Length: " + str(len(body)).encode() +
                    b"\r\n\r\n")
        elif path in ("/health", "/timeline"):
            # obs_live (ISSUE 16): fleet-merged health snapshot /
            # merged detector-firing timeline, JSON
            doc = (server.health_fleet() if path == "/health"
                   else server.timeline())
            body = json.dumps(doc).encode() + b"\n"
            head = (b"HTTP/1.0 200 OK\r\n"
                    b"Content-Type: application/json\r\n"
                    b"Content-Length: " + str(len(body)).encode() +
                    b"\r\n\r\n")
        else:
            body = b"not found\n"
            head = (b"HTTP/1.0 404 Not Found\r\n"
                    b"Content-Type: text/plain\r\n"
                    b"Content-Length: " + str(len(body)).encode() +
                    b"\r\n\r\n")
        self.wfile.write(head + body)
        self.wfile.flush()


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class AggregatorServer:
    """Collects counter pushes; query with :meth:`fleet` (in-process) or
    by sending ``QUERY`` over a TCP connection."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._srv = _TCPServer((host, port), _Handler)
        self._srv.owner = self  # type: ignore[attr-defined]
        self.host, self.port = self._srv.server_address[:2]
        self._lock = threading.Lock()
        # {counter: {rank: {"last", "min", "max", "n", "ts"}}}
        self._series: Dict[str, Dict[int, Dict[str, Any]]] = {}
        # obs_live (ISSUE 16): latest per-rank health snapshot (the
        # "health" key of a push, present only when the sender runs
        # with the knob set)
        self._health: Dict[int, Dict[str, Any]] = {}
        self.nb_pushes = 0
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "AggregatorServer":
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        name="sde-aggregator", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _ingest(self, msg: Dict[str, Any]) -> None:
        rank = int(msg.get("rank", 0))
        ts = float(msg.get("ts", time.time()))
        counters = msg.get("counters") or {}
        health = msg.get("health")
        with self._lock:
            self.nb_pushes += 1
            if isinstance(health, dict):
                self._health[rank] = health
            for name, value in counters.items():
                try:
                    v = float(value)
                except (TypeError, ValueError):
                    continue
                per_rank = self._series.setdefault(name, {})
                cell = per_rank.get(rank)
                if cell is None:
                    per_rank[rank] = {"last": v, "min": v, "max": v,
                                      "n": 1, "ts": ts}
                else:
                    cell["last"] = v
                    cell["min"] = min(cell["min"], v)
                    cell["max"] = max(cell["max"], v)
                    cell["n"] += 1
                    cell["ts"] = ts

    def fleet(self) -> Dict[str, Any]:
        """The live analog of counter_aggregate.aggregate(): per-rank
        stats plus fleet-wide min/max/sum_of_last per counter."""
        with self._lock:
            out: Dict[str, Any] = {}
            for name, per_rank in sorted(self._series.items()):
                ranks = {str(r): dict(cell)
                         for r, cell in sorted(per_rank.items())}
                lasts = [cell["last"] for cell in per_rank.values()]
                out[name] = {
                    "ranks": ranks,
                    # min/max span every sample seen (matching the offline
                    # counter_aggregate table), not just the latest values
                    "fleet": {"nb_ranks": len(per_rank),
                              "min": min(c["min"] for c in per_rank.values()),
                              "max": max(c["max"] for c in per_rank.values()),
                              "sum_of_last": sum(lasts)},
                }
            return {"counters": out, "nb_pushes": self.nb_pushes}

    def health_fleet(self) -> Dict[str, Any]:
        """The fleet-merged health document ``GET /health`` serves:
        per-rank snapshots folded over the comm plane exactly like the
        counter aggregation (worst status, merged firings, fleet-wide
        per-link exposure and worst link)."""
        from ..obs.live import fleet_health
        with self._lock:
            per_rank = {r: dict(s) for r, s in self._health.items()}
        return fleet_health(per_rank)

    def timeline(self) -> Dict[str, Any]:
        """The merged detector-firing timeline ``GET /timeline``
        serves: every rank's recent firings on one time axis (wall
        clock — firings are stamped with time.time() at the source)."""
        with self._lock:
            per_rank = {r: list(s.get("firings") or ())
                        for r, s in self._health.items()}
        events = [dict(f) for firings in per_rank.values()
                  for f in firings if isinstance(f, dict)]
        events.sort(key=lambda f: f.get("ts", 0.0))
        return {"nb_ranks": len(per_rank), "events": events}

    def clear_health(self) -> None:
        """Forget every rank's health snapshot — chaos_run --soak calls
        this between iterations so each JSONL record reflects one
        iteration's firings only."""
        with self._lock:
            self._health.clear()


class SDEPusher:
    """Daemon thread sampling an SDERegistry and pushing snapshots to an
    AggregatorServer address (host:port). One per Context (= per rank)."""

    def __init__(self, sde, addr: str, rank: int = 0,
                 interval: float = 1.0, extra_sde=None,
                 health_fn=None) -> None:
        host, sep, port = addr.rpartition(":")
        if not sep or not port.isdigit():
            raise ValueError(
                f"sde_push address {addr!r} is not host:port")
        self._addr = (host or "127.0.0.1", int(port))
        self._sde = sde
        # optional second registry merged into every push (the process-
        # global one: named mempools, contextless user counters); the
        # primary registry wins on name collision
        self._extra_sde = extra_sde
        # obs_live (ISSUE 16): optional zero-arg callable returning the
        # rank's health snapshot dict, shipped under "health" with each
        # push (absent when the knob is unset)
        self._health_fn = health_fn
        self.rank = rank
        self.interval = interval
        self._stop = threading.Event()
        self._sock: Optional[socket.socket] = None
        self._thread = threading.Thread(target=self._run, name="sde-push",
                                        daemon=True)

    def start(self) -> "SDEPusher":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    def push_once(self) -> bool:
        """One synchronous sample+send; False if the server is unreachable
        (pushes are best-effort: telemetry must never take down the run)."""
        merged = dict(self._extra_sde.snapshot()) \
            if self._extra_sde is not None else {}
        merged.update(self._sde.snapshot())
        snap = {k: v for k, v in merged.items()
                if isinstance(v, (int, float))}
        doc = {"rank": self.rank, "ts": time.time(), "counters": snap}
        if self._health_fn is not None:
            try:
                doc["health"] = self._health_fn()
            except Exception:  # noqa: BLE001 - best-effort telemetry
                pass
        msg = json.dumps(doc) + "\n"
        try:
            if self._sock is None:
                self._sock = socket.create_connection(self._addr, timeout=2)
            self._sock.sendall(msg.encode())
            return True
        except OSError:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
            return False

    def _run(self) -> None:
        while not self._stop.is_set():
            self.push_once()
            self._stop.wait(self.interval)
        self.push_once()  # final sample so short runs are visible
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
