"""Binary trace file format ("ptt" — parsec-tpu trace), the dbp analog.

Reference behavior: per-rank binary profile files with a header, a
dictionary of event classes, and per-thread event buffers
(ref: parsec/parsec_binary_profile.h:1-172, dbp readers in
tools/profiling/dbpreader.c). The offline toolchain converts these to
pandas/HDF5 (tools/profiling/python/pbt2ptt.pyx, profile2h5.py).

Layout (little-endian):

    magic   b"PTTB1\\n"
    u32     header JSON length, then header JSON
            {"rank": int, "info": {...}, "version": 1}
    u32     string-table entry count, then per entry: u16 len + utf8 bytes
    u32     stream count
    per stream:
        u32 tid; u16 name len + utf8; u32 event count
        per event: i64 ts_rel_ns; u8 phase; u32 key_id;
                   u32 info JSON length (0 = None) + bytes

Timestamps are stored relative to the profile's t0 so files from
different ranks merge on a common clock base (the in-process fabric
shares one monotonic clock; cross-host merge aligns on each file's t0
like the reference's dbp merge does).
"""
from __future__ import annotations

import json
import struct
from typing import Any, BinaryIO, Dict, List

MAGIC = b"PTTB1\n"


def _w_u32(fh: BinaryIO, v: int) -> None:
    fh.write(struct.pack("<I", v))


def _w_u16(fh: BinaryIO, v: int) -> None:
    fh.write(struct.pack("<H", v))


def _r(fh: BinaryIO, fmt: str):
    size = struct.calcsize(fmt)
    buf = fh.read(size)
    if len(buf) != size:
        raise EOFError("truncated ptt file")
    return struct.unpack(fmt, buf)


def write_profile(profile, path: str) -> str:
    """Serialize a profiling.trace.Profile to one binary file."""
    keys: Dict[str, int] = {}
    for st in profile._streams.values():
        for _ts, _ph, key, _info in st.events:
            if key not in keys:
                keys[key] = len(keys)
    with open(path, "wb") as fh:
        fh.write(MAGIC)
        header = json.dumps({"rank": profile.rank, "info": profile.info,
                             "version": 1}).encode()
        _w_u32(fh, len(header))
        fh.write(header)
        _w_u32(fh, len(keys))
        for key in keys:  # insertion order == id order
            kb = key.encode()
            _w_u16(fh, len(kb))
            fh.write(kb)
        streams = sorted(profile._streams.items())
        _w_u32(fh, len(streams))
        for tid, st in streams:
            _w_u32(fh, tid)
            nb = st.name.encode()
            _w_u16(fh, len(nb))
            fh.write(nb)
            _w_u32(fh, len(st.events))
            for ts, ph, key, info in st.events:
                # default=repr: like the Chrome export, arbitrary info
                # payloads must never abort the binary dump
                ib = b"" if info is None else json.dumps(
                    info, default=repr).encode()
                fh.write(struct.pack("<qBI", ts - profile._t0,
                                     ord(ph[0]), keys[key]))
                _w_u32(fh, len(ib))
                fh.write(ib)
    return path


def read_profile(path: str):
    """Read a .ptt file back into a Profile (timestamps re-based at 0)."""
    from .trace import Profile

    with open(path, "rb") as fh:
        if fh.read(len(MAGIC)) != MAGIC:
            raise ValueError(f"{path}: not a ptt trace (bad magic)")
        (hlen,) = _r(fh, "<I")
        header = json.loads(fh.read(hlen).decode())
        if header.get("version") != 1:
            raise ValueError(f"{path}: unsupported ptt version "
                             f"{header.get('version')}")
        (nkeys,) = _r(fh, "<I")
        keys: List[str] = []
        for _ in range(nkeys):
            (klen,) = _r(fh, "<H")
            keys.append(fh.read(klen).decode())
        prof = Profile(rank=header.get("rank", 0), info=header.get("info"))
        prof._t0 = 0
        (nstreams,) = _r(fh, "<I")
        for _ in range(nstreams):
            (tid,) = _r(fh, "<I")
            (nlen,) = _r(fh, "<H")
            name = fh.read(nlen).decode()
            st = prof.stream(tid, name)
            (nev,) = _r(fh, "<I")
            for _ in range(nev):
                ts, ph, key_id = _r(fh, "<qBI")
                (ilen,) = _r(fh, "<I")
                info: Any = None
                if ilen:
                    info = json.loads(fh.read(ilen).decode())
                st.events.append((ts, chr(ph), keys[key_id], info))
    return prof
