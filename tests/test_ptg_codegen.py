"""Generated task-class code vs the interpreted AST walk
(ref: the jdf2c-generated iterate_successors/dependency counters must
agree with the JDF semantics; here the interpreter IS the executable
spec, so equivalence over whole iteration spaces is the check).
"""
import numpy as np
import pytest

import parsec_tpu
from parsec_tpu.collections import TwoDimBlockCyclic
from parsec_tpu.dsl import ptg
from parsec_tpu.dsl.ptg.codegen import generate_source


def _edges_interpreted(tc, locals_):
    """Successor edges via the AST walk (mirrors _iterate_successors)."""
    from parsec_tpu.dsl.ptg.runtime import _expand_args
    env = tc.env_of(locals_)
    out = []
    for i, f in enumerate(tc.ast.flows):
        for d in f.deps_out():
            t = d.resolve(env)
            if t is None or t.kind in ("null", "new", "memory"):
                continue
            for succ_locals in _expand_args(t.args, env):
                out.append((t.task_class, succ_locals, t.flow, i))
    return out


def _edges_generated(tc, locals_):
    copies = [None] * len(tc.ast.flows)
    out = []
    tc._gen_succ(locals_, copies,
                 lambda name, loc, fl, cp, idx: out.append(
                     (name, loc, fl, idx)))
    return out


def _taskpool_for(which):
    if which == "dpotrf":
        from parsec_tpu.ops.dpotrf import dpotrf_taskpool
        A = TwoDimBlockCyclic(5 * 8, 5 * 8, 8, 8, dtype=np.float32)
        return dpotrf_taskpool(A)
    if which == "dgeqrf":
        from parsec_tpu.ops.dgeqrf import dgeqrf_taskpool
        A = TwoDimBlockCyclic(4 * 8, 3 * 8, 8, 8, dtype=np.float32)
        return dgeqrf_taskpool(A)
    if which == "dgetrf":
        from parsec_tpu.ops.dgetrf import dgetrf_nopiv_taskpool
        A = TwoDimBlockCyclic(4 * 8, 4 * 8, 8, 8, dtype=np.float32)
        return dgetrf_nopiv_taskpool(A)
    if which == "stencil":
        from tests.test_apps import STENCIL_JDF
        from parsec_tpu.collections import VectorTwoDimCyclic
        U = VectorTwoDimCyclic(4 * 8, 8)
        return ptg.compile_jdf(STENCIL_JDF, name="stencil").new(
            descU=U, NT=4, NI=3)
    raise KeyError(which)


@pytest.mark.parametrize("which", ["dpotrf", "dgeqrf", "dgetrf", "stencil"])
def test_generated_matches_interpreted(which):
    """goal + successor edges agree for EVERY instance of every class."""
    tp = _taskpool_for(which)
    checked = 0
    for tc in tp.task_classes:
        assert tc._gen_goal is not None, f"{tc.name}: codegen did not run"
        for locals_ in tc.iter_space():
            env = tc.env_of(locals_)
            assert tc._gen_goal(locals_) == tc.input_goal(env), \
                f"{tc.name}{locals_}: goal mismatch"
            assert _edges_generated(tc, locals_) == \
                _edges_interpreted(tc, locals_), \
                f"{tc.name}{locals_}: successor edges mismatch"
            checked += 1
    assert checked >= 16  # whole space walked


def test_codegen_source_is_plausible():
    from parsec_tpu.ops.dpotrf import dpotrf_factory
    jdf = dpotrf_factory().jdf
    gemm = jdf.task_class_by_name("GEMM")
    src = generate_source(gemm)
    assert "__ptg_goal_GEMM" in src and "__ptg_succ_GEMM" in src
    compile(src, "<test>", "exec")  # must be valid Python


def test_codegen_disabled_falls_back(ctx):
    from parsec_tpu.ops import dpotrf_taskpool, make_spd
    parsec_tpu.params.reset()
    parsec_tpu.params.set_cmdline("ptg_codegen", "0")
    try:
        M = make_spd(64)
        A = TwoDimBlockCyclic(64, 64, 16, 16, dtype=np.float32).from_numpy(M)
        tp = dpotrf_taskpool(A)
        assert tp.task_classes[0]._gen_succ is None
        ctx.add_taskpool(tp)
        ctx.wait()
        L = np.tril(A.to_numpy())
        np.testing.assert_allclose(L @ L.T, M, atol=5e-4)
    finally:
        parsec_tpu.params.reset()
