"""Once-per-topic help catalog (the show_help analog).

Reference behavior: verbose, actionable error/help texts live in catalog
files keyed by (file, topic); ``parsec_show_help("help-mca-param.txt",
"missing-param", ...)`` prints the formatted topic once and suppresses
repeats (ref: parsec/utils/show_help.c, show-help text catalogs).

Catalogs here are ini-style text files in ``parsec_tpu/utils/help/``:

    [topic-name]
    Multi-line message with {placeholders}.
"""
from __future__ import annotations

import os
import re
import threading
from typing import Dict, Set, Tuple

from . import logging as plog

_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "help")
_lock = threading.Lock()
_seen: Set[Tuple[str, str]] = set()
_cache: Dict[str, Dict[str, str]] = {}


def _load(filename: str) -> Dict[str, str]:
    topics = _cache.get(filename)
    if topics is not None:
        return topics
    topics = {}
    path = os.path.join(_DIR, filename)
    if os.path.exists(path):
        cur = None
        buf: list = []
        with open(path) as fh:
            for line in fh:
                m = re.match(r"^\[([^\]]+)\]\s*$", line)
                if m:
                    if cur is not None:
                        topics[cur] = "".join(buf).strip()
                    cur, buf = m.group(1), []
                elif cur is not None:
                    buf.append(line)
        if cur is not None:
            topics[cur] = "".join(buf).strip()
    _cache[filename] = topics
    return topics


def show_help(filename: str, topic: str, want_error: bool = False,
              **fmt) -> str:
    """Emit the catalog text for (filename, topic) once; later calls for
    the same pair are suppressed (returns the text either way)."""
    topics = _load(filename)
    text = topics.get(topic)
    if text is None:
        text = (f"[no help found for {topic!r} in {filename}; "
                f"args: {fmt or '{}'}]")
    else:
        try:
            text = text.format(**fmt)
        except (KeyError, IndexError):
            pass
    with _lock:
        if (filename, topic) in _seen:
            return text
        _seen.add((filename, topic))
    if want_error:
        plog.warning("%s", text)
    else:
        plog.inform("%s", text)
    return text


def reset() -> None:
    """Forget suppression state and cached catalogs (tests)."""
    with _lock:
        _seen.clear()
        _cache.clear()
