"""Redistribution engine: move an M×N submatrix between two arbitrary
tiled distributions.

Reference behavior: ``parsec_redistribute(Y, T, size_row, size_col,
disi_Y, disj_Y, disi_T, disj_T)`` — PTG- and DTD-based full submatrix
redistribution between any two block-cyclic distributions with unaligned
offsets and different tile sizes; each target tile assembles up to nine
source-fragment classes (NW/N/NE/W/I/E/SW/S/SE)
(ref: parsec/data_dist/matrix/redistribute/redistribute.jdf,
redistribute_wrapper.c:185, SURVEY.md §2.6).

TPU-native re-design: expressed through the DTD front end — one assembly
task per target tile, with INPUT deps on every intersecting source tile
and INOUT on the target tile. Task placement follows the target tile's
owner (AFFINITY); cross-rank fragments ride the DTD data plane
automatically, so the same code is the single-process and the
distributed path. For mesh-resident jax arrays, ``reshard_array`` is the
XLA fast path: device_put between NamedShardings compiles to all-to-all
collectives over ICI.
"""
from __future__ import annotations

from typing import Any, List, Optional, Tuple

import numpy as np

from ..dsl import dtd
from ..dsl.dtd import AFFINITY, INOUT, INPUT, VALUE, unpack_args
from .matrix import TiledMatrix


def _tile_range(lo: int, hi: int, tb: int) -> range:
    """Tiles of size tb intersecting global element rows [lo, hi)."""
    return range(lo // tb, (hi - 1) // tb + 1)


def _copy_frag(es, task) -> None:
    """One fragment: DTD task classes have a fixed flow signature (ref
    limit, insert_function_internal.h:30), so assembly is one 2-flow task
    per (target tile, source tile) pair; the INOUT chain on the target
    tile orders the disjoint fragment writes."""
    tgt, frag, src = unpack_args(task)
    dr0, dr1, dc0, dc1, sr0, sr1, sc0, sc1 = frag
    tgt[dr0:dr1, dc0:dc1] = src[sr0:sr1, sc0:sc1]


def _reshuffle_applicable(source: TiledMatrix, target: TiledMatrix,
                          size_row: int, size_col: int,
                          disi_Y: int, disj_Y: int,
                          disi_T: int, disj_T: int) -> bool:
    """The optimized-reshuffle precondition (ref: the reference's
    dedicated reshuffle path, redistribute_reshuffle.jdf via
    redistribute_wrapper.c:185: same tile grid, tile-aligned offsets):
    every target tile then maps 1:1 to one source tile — a pure
    rank/tile permutation, no fragment assembly."""
    return (source.mb == target.mb and source.nb == target.nb
            and disi_Y % source.mb == 0 and disj_Y % source.nb == 0
            and disi_T % target.mb == 0 and disj_T % target.nb == 0
            and size_row % source.mb == 0 and size_col % source.nb == 0)


def _copy_tile(es, task) -> None:
    tgt, src = unpack_args(task)
    tgt[:, :] = src


def _whole_matrix_applicable(source: TiledMatrix, target: TiledMatrix,
                             size_row: int, size_col: int,
                             disi_Y: int, disj_Y: int,
                             disi_T: int, disj_T: int) -> bool:
    """Whole-matrix same-tile-grid case: zero offsets, full extent,
    identical tiling on both ends. Unlike the aligned-subregion
    precondition above, this holds even with ragged edge tiles
    (``lm % mb != 0``) — every target tile still maps 1:1 to one
    equal-shape source tile, so it rides the reshuffle path.  This is
    the cross-grid checkpoint-reshard shape (ft/elastic.py): geometry
    is immutable across snapshots, only the distribution moves."""
    return (source.mb == target.mb and source.nb == target.nb
            and source.lm == target.lm and source.ln == target.ln
            and disi_Y == disj_Y == disi_T == disj_T == 0
            and size_row == source.lm and size_col == source.ln)


def _engine_of(context: Any) -> Optional[Any]:
    """The rank's comm engine, unwrapped from a Context's RemoteDep
    layer (mirrors ft/elastic._engine_of)."""
    if context is None:
        return None
    comm = getattr(context, "comm", None)
    if comm is None:
        return None
    return getattr(comm, "ce", comm)


def redistribute(source: TiledMatrix, target: TiledMatrix,
                 size_row: int, size_col: int,
                 disi_Y: int = 0, disj_Y: int = 0,
                 disi_T: int = 0, disj_T: int = 0,
                 context: Any = None,
                 taskpool: Optional[Any] = None,
                 allow_reshuffle: bool = True,
                 tiles: Optional[Any] = None) -> Any:
    """Copy source[disi_Y:disi_Y+size_row, disj_Y:disj_Y+size_col] into
    target[disi_T:..., disj_T:...] across distributions.

    SPMD: call on every rank. With ``taskpool`` the tasks are inserted
    into an existing DTD pool (composing with other work); otherwise a
    fresh pool is created, and with ``context`` it is enqueued + waited.
    Returns the taskpool.

    When both ends share the tile grid and all offsets/sizes are
    tile-aligned, the optimized reshuffle path runs instead: one
    whole-tile copy task per target tile — the reference's dedicated
    reshuffle JDF (redistribute_reshuffle.jdf). Honest measurement note:
    unlike the reference (whose general 9-fragment-class JDF pays its
    machinery even when aligned), this module's fragment enumerator
    already degenerates to one whole-tile fragment per tile on aligned
    inputs, so the two paths measure equal here (348 vs 313 ms at 32x32
    tiles, single process); the reshuffle path's value is the explicit
    1:1 permutation structure, which the static :func:`redistribute_ptg`
    graph builds on. ``allow_reshuffle=False`` forces the general
    fragment path (used by the equivalence tests).

    ``tiles`` (an iterable of target (m, n) coords) restricts the walk
    to an explicit tile set — required for triangular-storage
    collections whose off-storage tiles must never be touched, and only
    supported on the whole-matrix same-grid reshuffle shape (the
    checkpoint-reshard path, ft/elastic.py). The built taskpool is
    stamped with ``redist_bytes`` — the GLOBAL payload volume of the
    inserted plan (identical on every rank: insertion is SPMD) — an
    observable distinct from the per-rank landed bytes the
    ``FT::RESHARD_BYTES`` gauge reports.
    """
    assert disi_Y + size_row <= source.lm and disj_Y + size_col <= source.ln, \
        "source region out of bounds"
    assert disi_T + size_row <= target.lm and disj_T + size_col <= target.ln, \
        "target region out of bounds"
    if taskpool is None and context is None:
        raise ValueError(
            "redistribute() needs a context (fresh pool, enqueued + waited) "
            "or an existing taskpool to compose into")
    # collective-planner fast path (xfer/plan.py, ISSUE 19): behind the
    # ``xfer_collective_redist`` knob the whole-matrix same-grid reshard
    # (the checkpoint-reshard shape — ft/elastic.py rides this call) is
    # compiled into coalesced alltoall rounds and executed directly over
    # the comm engine instead of one DTD task per target tile.  Only on
    # a fresh pool (``taskpool`` composition keeps DTD ordering) and
    # only multi-rank — a single participant has nothing to coalesce.
    if (taskpool is None and allow_reshuffle
            and _whole_matrix_applicable(source, target, size_row, size_col,
                                         disi_Y, disj_Y, disi_T, disj_T)):
        from ..utils.params import params
        if params.get_or("xfer_collective_redist", "bool", False):
            ce = _engine_of(context)
            if ce is not None and getattr(ce, "nb_ranks", 1) > 1:
                from ..xfer.plan import run_redistribution
                return run_redistribution(source, target, ce, tiles=tiles)
    tp = taskpool if taskpool is not None else dtd.taskpool_new(
        name=f"redistribute_{source.lm}x{source.ln}")
    # redistribution is pure data MOVEMENT — checkpoint-reshard restores
    # (ft/elastic.py) ride it and must land bit-identical, so its wire
    # traffic is never eligible for the lossy quantized codecs
    # (comm/remote_dep.py consults this mark per flow)
    tp.wire_lossless = True
    own = taskpool is None
    if own and context is not None:
        context.add_taskpool(tp)
    # the DTD tile registry keys messages by collection name: give the two
    # ends deterministic distinct names when the user didn't. A per-taskpool
    # counter keeps composed calls collision-free (insertion streams are
    # identical on every rank, so the counter is SPMD-consistent)
    seq = getattr(tp, "_redist_seq", 0)
    tp._redist_seq = seq + 1
    if getattr(source, "name", None) in (None, type(source).__name__):
        source.name = f"redist{seq}_Y"
    if getattr(target, "name", None) in (None, type(target).__name__):
        target.name = f"redist{seq}_T"
    assert source.name != target.name, \
        "source and target collections need distinct .name values"
    if not hasattr(tp, "redist_bytes"):
        tp.redist_bytes = 0
    itemsize = np.dtype(target.dtype).itemsize

    if allow_reshuffle and _whole_matrix_applicable(
            source, target, size_row, size_col,
            disi_Y, disj_Y, disi_T, disj_T):
        for (m, n) in (tiles if tiles is not None else target.tiles()):
            tm, tn = target.tile_shape(m, n)
            tp.insert_task(
                _copy_tile,
                (tp.tile_of(target, (m, n)), INOUT | AFFINITY),
                (tp.tile_of(source, (m, n)), INPUT),
                name=f"reshuffle({m},{n})<-({m},{n})")
            tp.redist_bytes += tm * tn * itemsize
        if own:
            tp.data_flush_all()
            if context is not None:
                tp.wait()
        return tp
    if tiles is not None:
        raise ValueError(
            "redistribute(tiles=...) restricts the whole-matrix "
            "same-grid reshuffle walk only; the sub-region and "
            "fragment paths derive their tile sets from the region")

    if allow_reshuffle and _reshuffle_applicable(
            source, target, size_row, size_col,
            disi_Y, disj_Y, disi_T, disj_T):
        mb, nb = source.mb, source.nb
        dm, dn = disi_T // mb - disi_Y // mb, disj_T // nb - disj_Y // nb
        for sm in _tile_range(disi_Y, disi_Y + size_row, mb):
            for sn in _tile_range(disj_Y, disj_Y + size_col, nb):
                tp.insert_task(
                    _copy_tile,
                    (tp.tile_of(target, (sm + dm, sn + dn)),
                     INOUT | AFFINITY),
                    (tp.tile_of(source, (sm, sn)), INPUT),
                    name=f"reshuffle({sm + dm},{sn + dn})<-({sm},{sn})")
                tp.redist_bytes += mb * nb * itemsize
        if own:
            tp.data_flush_all()
            if context is not None:
                tp.wait()
        return tp

    mbT, nbT = target.mb, target.nb
    mbY, nbY = source.mb, source.nb
    # walk target tiles intersecting the target region
    for tm in _tile_range(disi_T, disi_T + size_row, mbT):
        # this target tile's rows ∩ region, in global-region coordinates r
        tr_lo = max(tm * mbT, disi_T) - disi_T
        tr_hi = min((tm + 1) * mbT, disi_T + size_row) - disi_T
        for tn in _tile_range(disj_T, disj_T + size_col, nbT):
            tc_lo = max(tn * nbT, disj_T) - disj_T
            tc_hi = min((tn + 1) * nbT, disj_T + size_col) - disj_T
            ttile = tp.tile_of(target, (tm, tn))
            # source tiles covering region rows [tr_lo, tr_hi) / cols ...
            for sm in _tile_range(disi_Y + tr_lo, disi_Y + tr_hi, mbY):
                sr_lo = max(sm * mbY, disi_Y + tr_lo)
                sr_hi = min((sm + 1) * mbY, disi_Y + tr_hi)
                for sn in _tile_range(disj_Y + tc_lo, disj_Y + tc_hi, nbY):
                    sc_lo = max(sn * nbY, disj_Y + tc_lo)
                    sc_hi = min((sn + 1) * nbY, disj_Y + tc_hi)
                    # fragment in region coords → slices in each tile
                    r0, r1 = sr_lo - disi_Y, sr_hi - disi_Y
                    c0, c1 = sc_lo - disj_Y, sc_hi - disj_Y
                    frag = (
                        r0 + disi_T - tm * mbT, r1 + disi_T - tm * mbT,
                        c0 + disj_T - tn * nbT, c1 + disj_T - tn * nbT,
                        sr_lo - sm * mbY, sr_hi - sm * mbY,
                        sc_lo - sn * nbY, sc_hi - sn * nbY)
                    tp.insert_task(
                        _copy_frag, (ttile, INOUT | AFFINITY),
                        (frag, VALUE), (tp.tile_of(source, (sm, sn)), INPUT),
                        name=f"redist({tm},{tn})<-({sm},{sn})")
                    tp.redist_bytes += (r1 - r0) * (c1 - c0) * itemsize
    if own:
        tp.data_flush_all()
        if context is not None:
            tp.wait()
    return tp


REDISTRIBUTE_RESHUFFLE_JDF = """
descY [ type="collection" ]
descT [ type="collection" ]
SM0 [ type="int" ]
SN0 [ type="int" ]
TM0 [ type="int" ]
TN0 [ type="int" ]
MT [ type="int" ]
NT [ type="int" ]

SRC(m, n)

m = 0 .. MT-1
n = 0 .. NT-1

: descY( SM0+m, SN0+n )

READ Y <- descY( SM0+m, SN0+n )
       -> T DST( m, n )

BODY
{
    pass
}
END

DST(m, n)

m = 0 .. MT-1
n = 0 .. NT-1

: descT( TM0+m, TN0+n )

RW T <- Y SRC( m, n )
     -> descT( TM0+m, TN0+n )

BODY
{
    pass
}
END
"""

_reshuffle_factory = None


def redistribute_ptg(source: TiledMatrix, target: TiledMatrix,
                     size_row: int, size_col: int,
                     disi_Y: int = 0, disj_Y: int = 0,
                     disi_T: int = 0, disj_T: int = 0,
                     rank: int = 0, nb_ranks: int = 1) -> Any:
    """PTG-generated reshuffle (the reference's redistribute.jdf role,
    ref: redistribute_wrapper.c:185): a static two-class task graph —
    SRC(m,n) placed on the source tile's owner reads it and ships it
    along a task edge to DST(m,n) on the target tile's owner, whose
    memory writeback lands it. Requires the aligned same-tile-grid
    precondition (the general unaligned fragment case runs through the
    DTD path in :func:`redistribute`). Returns the taskpool — enqueue
    with context.add_taskpool() on every rank."""
    from ..dsl import ptg
    global _reshuffle_factory
    assert _reshuffle_applicable(source, target, size_row, size_col,
                                 disi_Y, disj_Y, disi_T, disj_T), \
        "redistribute_ptg needs same tile grid + tile-aligned offsets"
    if _reshuffle_factory is None:
        _reshuffle_factory = ptg.compile_jdf(REDISTRIBUTE_RESHUFFLE_JDF,
                                             name="redistribute_reshuffle")
    mb, nb = source.mb, source.nb
    return _reshuffle_factory.new(
        descY=source, descT=target,
        SM0=disi_Y // mb, SN0=disj_Y // nb,
        TM0=disi_T // mb, TN0=disj_T // nb,
        MT=size_row // mb, NT=size_col // nb,
        rank=rank, nb_ranks=nb_ranks)


def reshard_array(arr: Any, mesh: Any, spec: Any) -> Any:
    """XLA fast path for mesh-resident arrays: re-lay ``arr`` out as
    NamedSharding(mesh, spec). XLA compiles the movement to all-to-all /
    collective-permute over ICI — the sharded-array analog of the tile
    redistribution above (SURVEY.md §5.7)."""
    import jax
    from jax.sharding import NamedSharding
    return jax.device_put(arr, NamedSharding(mesh, spec))
