"""Multi-tenant persistent serving over one shared Context (ISSUE 18).

Admission control, weighted-fair scheduling, per-tenant quotas and SLO
attribution in front of the untouched runtime.  Nothing here is
constructed unless a :class:`SessionServer` is — with the ``serve``
knob unset the runtime, schedulers and wire format are bit-for-bit
those of a pre-serve build (the capture-identity differential in
bench.py proves it).
"""
from .client import ServeClient, ServeTimeout
from .fairness import TenantFairness
from .server import AdmissionError, SessionServer, Submission, Tenant

__all__ = ["AdmissionError", "ServeClient", "ServeTimeout",
           "SessionServer", "Submission", "Tenant", "TenantFairness"]
