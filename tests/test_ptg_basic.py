"""PTG/JDF front-end tests.

Mirrors the reference's tutorial examples and compiler tests:
Ex02 (chain of CTL deps), Ex04_ChainData (RW chain through memory),
Ex05_Broadcast (range fan-out), tests/dsl/ptg (branching, choice,
local-indices, startup corner cases).
"""
import numpy as np
import pytest

import parsec_tpu
from parsec_tpu.collections.collection import DictCollection, LocalArrayCollection
from parsec_tpu.dsl import ptg


CHAIN_JDF = """
mydata  [ type="collection" ]
NB      [ type="int" ]

Task(k)

k = 0 .. NB

: mydata( k )

RW  A <- (k == 0)  ? mydata( k ) : A Task( k-1 )
      -> (k == NB) ? mydata( k ) : A Task( k+1 )

BODY
{
    A[0] += 1
}
END
"""


def test_chain_data(ctx):
    """Ex04_ChainData: a chain of NB+1 tasks each incrementing the datum."""
    arr = np.array([[300.0]])

    # single-datum collection where every index maps to datum 0 (the Ex04
    # pattern: one memory cell walked by the whole chain)
    class Single(DictCollection):
        def data_of(self, *idx):
            return DictCollection.data_of(self, 0)
        def rank_of(self, *idx):
            return 0
    s = Single()
    s.add(0, 0, arr[0])

    factory = ptg.compile_jdf(CHAIN_JDF, name="chain")
    tp = factory.new(mydata=s, NB=20)
    ctx.add_taskpool(tp)
    ctx.wait()
    assert tp.completed
    assert tp.nb_local_tasks == 21
    assert arr[0, 0] == 321.0


BCAST_JDF = """
mydata  [ type="collection" ]
NB      [ type="int" hidden=on default="(6)" ]

TaskBcast(k)

k = 0 .. 0

: mydata( k )

RW  A <- mydata( k )
      -> A TaskRecv( 0 .. NB .. 2 )

BODY
{
    A[0] = 42.0
}
END

TaskRecv(n)

n = 0 .. NB .. 2

: mydata( n )

READ A <- A TaskBcast( 0 )

BODY
{
    sink(n, A[0])
}
END
"""


def test_broadcast_range_fanout(ctx):
    """Ex05: one producer broadcasts to a strided range of consumers."""
    received = []
    arr = np.zeros((8, 1))
    coll = LocalArrayCollection(arr, 8)
    factory = ptg.compile_jdf(BCAST_JDF, name="bcast")
    tp = factory.new(mydata=coll)
    tp.global_env["sink"] = lambda n, v: received.append((n, v))
    ctx.add_taskpool(tp)
    ctx.wait()
    assert sorted(received) == [(0, 42.0), (2, 42.0), (4, 42.0), (6, 42.0)]
    assert tp.nb_local_tasks == 1 + 4


CTL_JDF = """
NT [ type="int" ]
dummy [ type="collection" ]

First(k)

k = 0 .. NT

: dummy( k )

CTL X -> X Second( k )

BODY
{
    order.append(("first", k))
}
END

Second(k)

k = 0 .. NT

: dummy( k )

CTL X <- X First( k )

BODY
{
    order.append(("second", k))
}
END
"""


def test_ctl_flow_ordering(ctx):
    """Pure control dependencies order tasks without moving data
    (ref: tests/dsl/ptg controlgather)."""
    order = []

    class NoData(DictCollection):
        def rank_of(self, *i):
            return 0
    nd = NoData()
    factory = ptg.compile_jdf(CTL_JDF, name="ctl")
    tp = factory.new(NT=5, dummy=nd)
    tp.global_env["order"] = order
    ctx.add_taskpool(tp)
    ctx.wait()
    assert len(order) == 12
    for k in range(6):
        assert order.index(("first", k)) < order.index(("second", k))


DIAMOND_JDF = """
A_coll [ type="collection" ]

Top(k)
k = 0 .. 0
: A_coll( 0 )
RW  A <- A_coll( 0 )
      -> A Left( 0 )
      -> A Right( 0 )
BODY
{
    A[0] = 1.0
}
END

Left(k)
k = 0 .. 0
: A_coll( 0 )
READ A <- A Top( 0 )
CTL  X -> X Bottom( 0 )
BODY
{
    log.append(("L", A[0]))
}
END

Right(k)
k = 0 .. 0
: A_coll( 0 )
READ A <- A Top( 0 )
CTL  X -> X Bottom( 0 )
BODY
{
    log.append(("R", A[0]))
}
END

Bottom(k)
k = 0 .. 0
: A_coll( 0 )
CTL X <- X Left( 0 )
      <- X Right( 0 )
BODY
{
    log.append(("B", None))
}
END
"""


def test_diamond_multi_input(ctx):
    """A task with two task-sourced inputs fires exactly once, after both."""
    log = []
    arr = np.zeros((1, 1))
    coll = LocalArrayCollection(arr, 1)
    tp = ptg.compile_jdf(DIAMOND_JDF, name="diamond").new(A_coll=coll)
    tp.global_env["log"] = log
    ctx.add_taskpool(tp)
    ctx.wait()
    assert len(log) == 3
    assert log[-1] == ("B", None)
    assert {l[0] for l in log[:2]} == {"L", "R"}
    assert all(v == 1.0 for tag, v in log[:2])


PRIO_JDF = """
NT [ type="int" ]
dummy [ type="collection" ]

T(k)
k = 0 .. NT
: dummy( k )
; k
BODY
{
    out.append(k)
}
END
"""


def test_priority_expression():
    """Higher-priority instances run first under the ap scheduler."""
    ctx = parsec_tpu.Context(nb_cores=1, scheduler="ap")
    try:
        out = []

        class NoData(DictCollection):
            def rank_of(self, *i):
                return 0
        tp = ptg.compile_jdf(PRIO_JDF, name="prio").new(NT=9, dummy=NoData())
        tp.global_env["out"] = out
        ctx.add_taskpool(tp)
        ctx.wait()
        assert out == list(range(9, -1, -1))
    finally:
        ctx.fini()


def test_parse_errors():
    with pytest.raises(ptg.JDFParseError):
        ptg.compile_jdf("T(k)\nk = 0 .. 3\n: c( k )\nBODY\nx\nEND\n")  # unknown coll
    with pytest.raises(ptg.JDFParseError):
        ptg.compile_jdf("c [type=x]\nT(k)\nk = 0 .. 3\n: c( k )\n")  # no body
    with pytest.raises(ptg.JDFParseError):
        # dep to unknown task class
        ptg.compile_jdf("""
c [type=x]
T(k)
k = 0 .. 1
: c( k )
RW A <- c( k ) -> A Nope( k )
BODY
x = 1
END
""")


def test_missing_global_raises():
    f = ptg.compile_jdf(PRIO_JDF, name="prio")
    with pytest.raises(TypeError):
        f.new(NT=3)  # dummy missing
    with pytest.raises(TypeError):
        f.new(NT=3, dummy=None, extra=1)


GUARD_SINGLE_JDF = """
NT [ type="int" ]
dummy [ type="collection" ]

P(k)
k = 0 .. NT
: dummy( k )
RW A <- dummy( k )
     -> (k < NT) ? A C( k+1 )
BODY
{
    A[0] = k
}
END

C(k)
k = 1 .. NT
: dummy( k )
READ A <- A P( k-1 )
BODY
{
    got.append((k, A[0]))
}
END
"""


def test_guarded_single_target_dep(ctx):
    """``(cond) ? target`` with no alternative: edge exists only when true."""
    got = []
    arr = np.zeros((8, 1))
    coll = LocalArrayCollection(arr, 8)
    tp = ptg.compile_jdf(GUARD_SINGLE_JDF, name="guard").new(NT=3, dummy=coll)
    tp.global_env["got"] = got
    ctx.add_taskpool(tp)
    ctx.wait()
    assert sorted(got) == [(1, 0.0), (2, 1.0), (3, 2.0)]


NULL_INPUT_JDF = """
dummy [ type="collection" ]
NT [ type="int" ]

T(k)
k = 0 .. NT-1
: dummy( k )
READ A <- (k > 0) ? dummy( k-1 )
BODY
{
    got.append((k, None if A is None else float(A[0, 0])))
}
END
"""


def test_null_input_when_all_guards_false(ctx):
    """A guarded input dep with no ':' alternative binds NULL (None) in the
    instances where the guard is false (reference: alternative-less guarded
    input deps yield NULL; parser.py ``cond ? a`` form)."""
    got = []
    arr = np.arange(8, dtype=np.float64).reshape(8, 1)
    coll = LocalArrayCollection(arr, 8)
    tp = ptg.compile_jdf(NULL_INPUT_JDF, name="nullin").new(NT=3, dummy=coll)
    tp.global_env["got"] = got
    ctx.add_taskpool(tp)
    ctx.wait()
    assert sorted(got, key=lambda x: x[0]) == [(0, None), (1, 0.0), (2, 1.0)]
