"""Checkpoint-integrated restart: the recovery pillar of ft/.

``utils/checkpoint`` gives quiescent-point snapshots (a consistent
per-rank tile dump between taskpools); this module adds the POLICY that
turns snapshots into recovery: run a sequence of taskpool stages,
snapshot every K completed stages, and on failure either abort cleanly
(the pre-ft behavior, now guaranteed rather than best-effort) or roll
the collections back to the last snapshot and re-run from there, with
bounded, exponentially backed-off retries.

Scope: rollback-and-retry recovers IN PROCESS from transient faults
(an injected task fault, a failed send that aborted one stage) on
SINGLE-RANK contexts. A hard rank loss (``RankFailedError``, or this
rank's own ``InjectedKill``) cannot be re-run inside the same comm
world — the dead rank is gone (or IS us) — and on a multi-rank run
even a transient fault aborts: rollback is a local act the surviving
peers cannot observe, so a lone retry would leave them waiting on the
original taskpool forever. In both cases the driver aborts after
restoring a consistent snapshot set; a fresh incarnation of the job
(relaunched processes, or a fresh fabric in tests) then calls
:func:`run_with_restart` with ``resume_from`` pointing at the same
prefix and continues from the last completed stage. Either way the
guarantee is the same: the ON-DISK snapshot set is always a consistent
stage boundary, never a half-written DAG (the abort path also rolls
the in-memory collections back best-effort).

Policy grammar (``--mca ft_restart_policy``)::

    abort                              # snapshot, but never retry
    restart:retries=2:backoff=0.25:every=1

`every=K` snapshots after every K completed stages (the last stage is
always snapshotted).
"""
from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, Optional, Sequence

from ..utils import checkpoint as ckpt
from ..utils import logging as plog
from ..utils.params import params

__all__ = ["RestartPolicy", "run_with_restart"]


class RestartPolicy:
    """mode="abort" | "restart"; retries/backoff/every as in the
    module docstring."""

    def __init__(self, mode: str = "restart", retries: int = 2,
                 backoff: float = 0.25, every: int = 1) -> None:
        if mode not in ("abort", "restart"):
            raise ValueError(f"unknown restart mode {mode!r}")
        if every < 1:
            raise ValueError("snapshot cadence `every` must be >= 1")
        self.mode = mode
        self.retries = int(retries)
        self.backoff = float(backoff)
        self.every = int(every)

    @classmethod
    def parse(cls, spec: str) -> "RestartPolicy":
        parts = [p for p in spec.strip().split(":") if p]
        if not parts:
            return cls()
        kw: Dict[str, Any] = {"mode": parts[0]}
        for kv in parts[1:]:
            k, v = kv.split("=", 1)
            if k == "retries":
                kw["retries"] = int(v)
            elif k == "backoff":
                kw["backoff"] = float(v)
            elif k == "every":
                kw["every"] = int(v)
            else:
                raise ValueError(
                    f"ft_restart_policy: unknown key {k!r}")
        return cls(**kw)

    @classmethod
    def from_params(cls) -> "RestartPolicy":
        spec = str(params.get("ft_restart_policy") or "").strip()
        return cls.parse(spec) if spec else cls()

    def __repr__(self) -> str:  # pragma: no cover
        return (f"RestartPolicy({self.mode}, retries={self.retries}, "
                f"backoff={self.backoff}, every={self.every})")


def _stage_prefix(prefix: str, stage: int) -> str:
    return f"{prefix}.stage{stage}"


def _save(collections: Sequence[Any], prefix: str, stage: int,
          context: Any) -> None:
    for i, coll in enumerate(collections):
        ckpt.save_collection(coll, f"{_stage_prefix(prefix, stage)}.c{i}",
                             context=context)


def _restore(collections: Sequence[Any], prefix: str, stage: int,
             context: Any = None, reshard: bool = False) -> None:
    for i, coll in enumerate(collections):
        ckpt.restore_collection(coll, f"{_stage_prefix(prefix, stage)}.c{i}",
                                reshard=reshard, context=context)


def _restore_fallback(collections: Sequence[Any], prefix: str, stage: int,
                      context: Any = None, reshard: bool = False) -> int:
    """Restore the stage-``stage`` snapshot set, falling back to the
    previous COMPLETE snapshot when a shard is torn/corrupt (a rank
    that crashed mid-write before atomic saves, or truncating storage,
    must not dead-end the whole recovery). Walks one stage at a time
    so skipped cadence stages (``every > 1``) are stepped over; the
    requested stage itself must at least exist. Returns the stage
    actually restored."""
    s = stage
    while True:
        try:
            _restore(collections, prefix, s, context=context,
                     reshard=reshard)
            return s
        except ckpt.CheckpointCorruptError as exc:
            if s <= 0:
                raise
            plog.warning(
                "ft.restart: snapshot at stage %d is torn/corrupt (%s); "
                "falling back toward the previous complete snapshot", s, exc)
            s -= 1
        except FileNotFoundError:
            if s <= 0 or s == stage:
                raise
            s -= 1   # not a snapshot boundary (every > 1): keep walking


def _complete_stage(ncolls: int, prefix: str, stage: int) -> int:
    """Latest stage <= ``stage`` whose FULL writer shard set is on disk
    for every collection — the stage this rank can safely VOTE in a
    shrink round. A rank killed after its stage completed but before
    its atomic save PUBLISHED leaves the newest snapshot one shard
    short; a reshard restore of it would dead-end mid-collective. Disk
    state is shared, so every survivor probing the same snapshot set
    computes the same answer (the SPMD consistency the vote needs)."""
    from .elastic import _participants

    def complete(s: int) -> bool:
        for i in range(ncolls):
            p = f"{_stage_prefix(prefix, s)}.c{i}"
            try:
                man = ckpt.find_manifest(p)
            except (FileNotFoundError, ckpt.CheckpointCorruptError):
                return False
            if not all(os.path.exists(ckpt.checkpoint_path(p, w))
                       for w in _participants(man)):
                return False
        return True

    s = stage
    while s > 0 and not complete(s):
        s -= 1   # skipped cadence stages (every > 1) also walk through
    return s


def run_with_restart(ctx: Any, stages: Optional[Sequence[Callable[[], Any]]],
                     collections: Optional[Sequence[Any]], prefix: str,
                     policy: Optional[RestartPolicy] = None,
                     resume_from: Optional[int] = None,
                     elastic: Optional[Any] = None) -> Dict[str, Any]:
    """Run ``stages`` (zero-arg factories, each returning a FRESH
    taskpool — a taskpool object cannot be re-enqueued) under the
    snapshot/rollback policy. ``collections`` is the application state
    the stages mutate; ``prefix`` names the snapshot files
    (``<prefix>.stage<k>.c<i>.rank<r>.npz``).

    Returns ``{"stages", "retries", "snapshots", "last_snapshot",
    "resizes", "grid"}``. ``resume_from=k`` skips the initial
    snapshot, restores the stage-k snapshot set, and continues with
    stage k — the fresh-incarnation entry point after a hard rank
    loss.

    ``elastic`` (an :class:`ft.elastic.ElasticPolicy`) turns hard rank
    loss from a dead end into a resize: on a ``RankFailedError`` with
    shrink enabled the survivors agree on a reduced grid, rebuild the
    run via ``elastic.rebuild(grid)``, reshard-restore the last
    snapshot onto it, and replay from ``last_snap``; with grow
    enabled, announced joiners are folded in at stage boundaries
    (fresh-snapshot quiescent points), gated by
    ``elastic.grow_min``. With ``elastic`` the ``stages``/
    ``collections`` arguments may be ``None`` — ``rebuild`` is then
    the single source of truth for the initial grid too. Strict runs
    (no ``elastic``, or ``ft_elastic`` unset) keep today's fail-fast
    behavior exactly.
    """
    # a coordinator this call creates is detached on exit: leaving it
    # attached would carry pending joins/views into a LATER run on the
    # same context (phantom grow rounds holding every boundary). One
    # installed by Context (maybe_install_elastic) outlives the call.
    co_made = None
    if (elastic is not None and elastic.mode and ctx.comm is not None
            and ctx.nb_ranks >= 2):
        ce = getattr(ctx.comm, "ce", ctx.comm)
        if ce.ft_elastic is None:
            from .elastic import ElasticCoordinator
            co_made = ElasticCoordinator(ce)
    try:
        return _run_with_restart(ctx, stages, collections, prefix,
                                 policy, resume_from, elastic)
    finally:
        if co_made is not None:
            co_made.detach()


def _run_with_restart(ctx, stages, collections, prefix, policy,
                      resume_from, elastic) -> Dict[str, Any]:
    policy = policy or RestartPolicy.from_params()
    co = grid = ce = None
    joined_at: Optional[int] = None
    if elastic is not None and not elastic.mode:
        elastic = None   # knob off: strict contract, bit for bit
    if elastic is not None:
        if ctx.comm is None or ctx.nb_ranks < 2:
            raise ValueError(
                "elastic recovery needs a multi-rank comm world")
        from .elastic import ElasticCoordinator, plan_grid
        ce = getattr(ctx.comm, "ce", ctx.comm)
        co = ce.ft_elastic or ElasticCoordinator(ce)
        members = elastic.members or tuple(range(ctx.nb_ranks))
        grid = plan_grid(members, ctx.nb_ranks, ctx.rank)
        if elastic.join:
            # late joiner: announce, learn the member set + resume
            # stage from the welcome, reshard into the grown grid
            welcome = co.announce_join(deadline_s=elastic.timeout)
            if welcome.get("tp_base") is not None:
                # align taskpool WIRE ids with the incumbents (DTD
                # traffic is keyed by registration order, and they
                # registered pools for every stage we never ran)
                ctx.comm.sync_tp_ids(int(welcome["tp_base"]))
            grid = plan_grid(tuple(welcome["members"]), ctx.nb_ranks,
                             ctx.rank)
            stages, collections = elastic.rebuild(grid)
            joined_at = int(welcome["stage"])
            _restore(collections, prefix, joined_at, context=ctx,
                     reshard=True)
            ce.elastic_stats["elastic_resizes"] += 1
            ce.elastic_stats["elastic_joins"] += 1
            plog.inform("ft.restart: rank %d joined grid %dx%d (members "
                        "%s) at stage %d", ctx.rank, grid.P, grid.Q,
                        grid.members, joined_at)
        elif stages is None:
            stages, collections = elastic.rebuild(grid)
    assert stages is not None and collections is not None, \
        "stages/collections may only be omitted with an elastic policy"
    n = len(stages)
    retries_total = snapshots = resizes = 0
    if joined_at is not None:
        i = last_snap = joined_at
        resizes = 1   # the join itself resized this rank's grid
    elif resume_from is None:
        _save(collections, prefix, 0, ctx)
        snapshots += 1
        i = last_snap = 0
    else:
        i = last_snap = _restore_fallback(
            collections, prefix, resume_from, context=ctx,
            reshard=elastic is not None)
    # per-STAGE attempt counters: with every>1 a rollback replays
    # earlier (succeeding) stages, and a single shared counter reset on
    # their completion would let a persistently failing stage retry
    # forever with the backoff stuck at its first step
    attempts: Dict[int, int] = {}
    while i < n:
        try:
            tp = stages[i]()
            ctx.add_taskpool(tp)
            ctx.wait()
        except Exception as exc:  # noqa: BLE001 - the policy decides
            root = exc.__cause__ or exc
            from ..comm.engine import RankFailedError
            from .inject import InjectedKill
            # hard = unrecoverable in this incarnation: a peer is gone
            # (RankFailedError) or THIS rank was killed (InjectedKill —
            # its engine is permanently dark; retrying a stage on it
            # would hang termdet, the exact failure ft/ exists to stop)
            hard = isinstance(root, (RankFailedError, InjectedKill))
            # elastic shrink: a PEER's loss is recoverable in-world —
            # the survivors agree on a reduced grid and reshard the
            # last snapshot onto it. Our OWN kill (InjectedKill) is
            # not: this engine is dark — and neither is a silenced
            # (kill-injected) engine whose own detector evicted every
            # peer it stopped hearing: a dead rank must never "win" a
            # phantom agreement with itself. Bounded by the world size
            # so a cascade of losses cannot loop forever.
            # split-brain guard (ISSUE 10): a LINK fault partitions the
            # grid without killing anyone — both sides of the partition
            # would otherwise shrink to themselves and double-complete.
            # Only the side still seeing a STRICT MAJORITY of the
            # current members may resize; a minority partition takes
            # the strict abort (its snapshots stay consistent, and a
            # fresh incarnation can resume). Kill-based losses on >= 3
            # ranks are unaffected: the survivors ARE the majority.
            majority = True
            if (co is not None and grid is not None
                    and isinstance(root, RankFailedError)):
                reachable = [m for m in grid.members
                             if m == ctx.rank or m not in ce.dead_peers]
                majority = 2 * len(reachable) > len(grid.members)
                if not majority and elastic.allows_shrink:
                    plog.warning(
                        "ft.restart: only %d of %d members reachable — "
                        "a minority partition must not shrink (split-"
                        "brain); falling back to the strict abort path",
                        len(reachable), len(grid.members))
            if (co is not None and elastic.allows_shrink and majority
                    and isinstance(root, RankFailedError)
                    and not isinstance(root, InjectedKill)
                    and not getattr(ce, "_ft_silenced", False)
                    and resizes < ctx.nb_ranks):
                from .elastic import ElasticError, plan_grid
                recovered = False
                tries = 0
                # another rank can die DURING the agreement or the
                # reshard itself — re-enter with the further-reduced
                # survivor set; bounded by the world size
                while resizes + tries < ctx.nb_ranks:
                    try:
                        ctx.clear_task_errors()
                        # vote a snapshot this rank can PROVE complete:
                        # the dead rank may have died between finishing
                        # the stage and publishing its shard
                        safe = _complete_stage(len(collections), prefix,
                                               last_snap)
                        decision = co.agree(
                            "shrink", grid.members, safe,
                            deadline_s=elastic.timeout,
                            tp_next=getattr(ctx.comm, "next_tp_id", None))
                        if 2 * len(decision["members"]) \
                                <= len(grid.members):
                            # deaths DURING the round can shrink the
                            # committed set below a majority (down to
                            # this rank alone on a full partition):
                            # re-validate the decision, not just the
                            # entry view — the minority side must abort
                            raise ElasticError(
                                f"committed members "
                                f"{tuple(decision['members'])} are a "
                                f"minority of {grid.members} — refusing "
                                f"a split-brain resize")
                        if decision["tp_base"] is not None:
                            # survivors can diverge by one registration
                            # at a mid-stage failure: align wire ids
                            # before the reshard pool registers
                            ctx.comm.sync_tp_ids(decision["tp_base"])
                        grid = plan_grid(decision["members"],
                                         ctx.nb_ranks, ctx.rank)
                        stages, collections = elastic.rebuild(grid)
                        assert len(stages) == n, \
                            "elastic rebuild changed the stage count"
                        # the COMMITTED stage (min over votes — peers a
                        # snapshot behind us reconcile the round there;
                        # every voter provably wrote that snapshot)
                        last_snap = int(decision["stage"])
                        _restore(collections, prefix, last_snap,
                                 context=ctx, reshard=True)
                        ce.elastic_stats["elastic_resizes"] += 1
                        resizes += 1
                        recovered = True
                        break
                    except Exception as eexc:  # noqa: BLE001 - triaged below
                        nested = eexc.__cause__ or eexc
                        if isinstance(nested, RankFailedError) \
                                and not isinstance(nested, InjectedKill):
                            tries += 1
                            plog.warning(
                                "ft.restart: rank failure during elastic "
                                "shrink (%s) — re-agreeing on the reduced "
                                "survivor set", nested)
                            continue
                        plog.warning(
                            "ft.restart: elastic shrink failed (%s: %s) — "
                            "falling back to the strict abort path",
                            type(eexc).__name__, eexc)
                        break
                if recovered:
                    plog.warning(
                        "ft.restart: elastic shrink -> %dx%d over members "
                        "%s after %s; resharded snapshot %d, replaying",
                        grid.P, grid.Q, grid.members,
                        type(root).__name__, last_snap)
                    i = last_snap
                    continue
            # in-world rollback is a LOCAL act: on a multi-rank run the
            # peers saw no error and keep waiting on the original
            # taskpool (whose wire id a lone re-registration would
            # shift), so an uncoordinated retry deadlocks them — on
            # multi-rank, every failure aborts to a consistent snapshot
            # and recovery is a fresh incarnation (resume_from)
            multi = int(getattr(ctx, "nb_ranks", 1) or 1) > 1
            attempt = attempts[i] = attempts.get(i, 0) + 1
            if policy.mode == "abort" or hard or multi \
                    or attempt > policy.retries:
                # guaranteed-clean abort: errors drained, scheduler
                # queues flushed, the last snapshot still consistent —
                # a fresh incarnation resumes with resume_from=last_snap
                ctx.clear_task_errors()
                # best-effort in-memory rollback too, so a caller that
                # catches the abort never sees half-mutated tiles; the
                # ON-DISK snapshot set is the hard guarantee (a failed
                # restore must not mask the original error)
                try:
                    _restore_fallback(collections, prefix, last_snap)
                except Exception:  # noqa: BLE001  pragma: no cover
                    plog.warning("ft.restart: in-memory rollback to "
                                 "snapshot %d failed; on-disk snapshots "
                                 "remain authoritative", last_snap)
                why = (" — hard rank loss" if hard else
                       " — in-world retry unsupported on multi-rank "
                       "runs (peers cannot observe this rank's "
                       "rollback)" if multi and policy.mode != "abort"
                       else "")
                plog.warning(
                    "ft.restart: aborting at stage %d after %d "
                    "attempt(s) (%s%s); resume_from=%d", i, attempt,
                    type(root).__name__, why, last_snap)
                raise
            delay = policy.backoff * (2 ** (attempt - 1))
            plog.warning(
                "ft.restart: stage %d failed (%s: %s) — rolling back "
                "to snapshot %d, retry %d/%d in %.2fs", i,
                type(root).__name__, root, last_snap, attempt,
                policy.retries, delay)
            retries_total += 1
            time.sleep(delay)
            ctx.clear_task_errors()
            i = last_snap = _restore_fallback(collections, prefix, last_snap)
            continue
        i += 1
        if (i - last_snap) >= policy.every or i == n:
            _save(collections, prefix, i, ctx)
            snapshots += 1
            last_snap = i
        # elastic grow: fold announced joiners in at a quiescent point
        # that has a FRESH snapshot (the joiner reshards from it). The
        # round is optional — the leader holds the boundary only
        # ``grow_window`` seconds, so a straggling incumbent defers
        # the resize to the next boundary instead of stalling the run.
        if (co is not None and elastic.allows_grow and i < n
                and last_snap == i):
            # a fast, purely-local stage can complete without one comm
            # progress cycle: drain the engine HERE or a join sitting in
            # the inbox is invisible at exactly the boundary it targets
            ce.progress()
            joins = co.pending_joins(grid.members)
            if len(joins) >= elastic.grow_min:
                from .elastic import ElasticError, plan_grid
                try:
                    decision = co.agree(
                        "grow", grid.members, last_snap,
                        deadline_s=elastic.timeout,
                        window_s=elastic.grow_window,
                        tp_next=getattr(ctx.comm, "next_tp_id", None))
                except ElasticError as eexc:
                    # the round is OPTIONAL: a non-converging agreement
                    # (e.g. a peer saw the join only after passing its
                    # own boundary check, so it never voted) must not
                    # abort a healthy run — release the boundary, the
                    # joiner stays pending for the next one
                    plog.warning(
                        "ft.restart: grow round at stage %d released "
                        "(%s); joiners stay pending", last_snap, eexc)
                    decision = None
                if decision is not None:
                    committed = decision["members"]
                    if decision["tp_base"] is not None:
                        ctx.comm.sync_tp_ids(decision["tp_base"])
                    new = [r for r in committed if r not in grid.members]
                    grid = plan_grid(committed, ctx.nb_ranks, ctx.rank)
                    stages, collections = elastic.rebuild(grid)
                    assert len(stages) == n, \
                        "elastic rebuild changed the stage count"
                    # adopt the COMMITTED stage: an incumbent a boundary
                    # ahead of the slowest voter replays from the common
                    # snapshot so every member (joiner included) runs
                    # the same remaining stage sequence in lockstep
                    i = last_snap = int(decision["stage"])
                    _restore(collections, prefix, last_snap, context=ctx,
                             reshard=True)
                    ce.elastic_stats["elastic_resizes"] += 1
                    ce.elastic_stats["elastic_joins"] += len(new)
                    resizes += 1
                    plog.inform(
                        "ft.restart: elastic grow -> %dx%d over members "
                        "%s (+%s); resharded snapshot %d",
                        grid.P, grid.Q, grid.members, new, last_snap)
    return {"stages": n, "retries": retries_total,
            "snapshots": snapshots, "last_snapshot": last_snap,
            "resizes": resizes,
            "grid": grid.members if grid is not None else None}
