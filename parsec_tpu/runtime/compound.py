"""Compound taskpools: run several taskpools sequentially as one.

Reference behavior: ``parsec_compose(start, next)`` chains two taskpools
into a compound whose parts execute one after the other; composing onto an
existing compound appends (ref: parsec/compound.c:13-30). The compound
itself holds no tasks — it enqueues part i+1 from part i's completion
callback and terminates after the last part.
"""
from __future__ import annotations

from typing import List

from .taskpool import Taskpool

__all__ = ["CompoundTaskpool", "compose"]


class CompoundTaskpool(Taskpool):
    def __init__(self, parts: List[Taskpool]) -> None:
        super().__init__(name="compound")
        self.parts: List[Taskpool] = list(parts)
        self._idx = 0
        self.startup_hook = self._startup

    def _startup(self, context, tp):
        # one pending action keeps the compound alive across the chain
        # (it owns no tasks of its own)
        self.add_pending_action()
        self._launch_next(context)
        return []

    def _launch_next(self, context) -> None:
        if self._idx >= len(self.parts):
            self.pending_action_done()
            return
        sub = self.parts[self._idx]
        self._idx += 1
        prev_cb = sub.on_complete

        def chained(done_tp):
            if prev_cb is not None:
                prev_cb(done_tp)
            self._launch_next(context)

        sub.on_complete = chained
        context.add_taskpool(sub)
        # pools with an explicit end-of-insertion protocol (DTD) must be
        # sealed: nobody calls their blocking wait() inside a chain
        seal = getattr(sub, "seal", None)
        if seal is not None:
            seal()


def compose(start: Taskpool, next_tp: Taskpool) -> CompoundTaskpool:
    """Chain ``next_tp`` after ``start``; both must not be enqueued yet.
    If ``start`` is already a compound, ``next_tp`` is appended in place
    (ref: parsec_compose appending to an existing compound)."""
    assert start.context is None and next_tp.context is None, \
        "compose() operands must not be enqueued yet"
    if isinstance(start, CompoundTaskpool):
        start.parts.append(next_tp)
        return start
    return CompoundTaskpool([start, next_tp])
