#!/usr/bin/env python
"""Benchmark driver: PTG tile Cholesky (dpotrf_L) GFLOP/s on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference repo publishes no numbers (BASELINE.md); the
north-star target is >=60% of an A100-node's per-device dpotrf rate. We
take 15.5 TFLOP/s as the A100-class dpotrf rate (DPLASMA-style dpotrf
sustains ~80% of the A100's 19.5 TFLOP/s FP64-TC peak), making the target
0.6 * 15500 = 9300 GFLOP/s; vs_baseline = measured / 9300.

Two execution modes (BENCH_MODE):

- ``capture`` (default): the PTG DAG is compiled into ONE XLA executable
  via graph capture (dsl/ptg/capture.py) — single dispatch, zero host
  loop in the timed region, MXU-bound (~0.2 ms for the N=8192 DAG,
  measured ~900 TF/s on the tunnel chip).
- ``runtime``: tasks dispatch through the scheduler/device module one by
  one (the distributed-capable path; ~33 TF/s: each task pays ~0.3 ms of
  Python dispatch, amortized by NB=2048 kernels and async overlap).

Knobs (env): BENCH_N (default 8192), BENCH_NB (2048), BENCH_DTYPE
(float32), BENCH_REPS (3, best-of), BENCH_CORES (runtime mode worker
threads, default 1: eager completion makes one thread the fastest driver
on a single-CPU-core host). Don't raise BENCH_N casually: the untimed
staging/verify transfers are tunnel-bound (BASELINE.md notes the link can
be as slow as ~7-27 MB/s).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

BASELINE_GFLOPS = 9300.0


def make_input(n, dtype):
    # O(N^2) SPD construction (symmetric + strictly diagonally dominant);
    # a Gram-matrix form would be O(N^3) on the host and dominate wall time
    rng0 = np.random.RandomState(0)
    B = rng0.rand(n, n) - 0.5
    return ((B + B.T) / 2 + n * np.eye(n)).astype(dtype)


def check_numerics(L_np, M, n):
    # O(N^2) residual ||L(L^T x) - M x|| / ||M x|| on random vectors so
    # verification does not dwarf the timed region at large N
    L = np.tril(L_np).astype(np.float64)
    rng = np.random.RandomState(0)
    X = rng.rand(n, 4)
    ref = M.astype(np.float64) @ X
    return float(np.abs(L @ (L.T @ X) - ref).max() / np.abs(ref).max())


def emit(n, nb, dtype, mode, best, err):
    if err > 5e-2:
        print(json.dumps({"metric": "dpotrf_gflops", "value": 0.0,
                          "unit": "GFLOP/s", "vs_baseline": 0.0,
                          "error": f"numerics failed: {err}"}))
        return
    flops = n ** 3 / 3.0 + n ** 2 / 2.0
    gflops = flops / best / 1e9
    print(json.dumps({
        "metric": f"dpotrf_gflops(N={n},NB={nb},{dtype.name},1chip,{mode})",
        "value": round(gflops, 2),
        "unit": "GFLOP/s",
        "vs_baseline": round(gflops / BASELINE_GFLOPS, 4),
    }))


def bench_capture(n, nb, reps, dtype):
    """Whole-DAG XLA execution: one captured executable per shape."""
    import jax
    from parsec_tpu.collections import TwoDimBlockCyclic
    from parsec_tpu.dsl import ptg
    from parsec_tpu.ops import dpotrf_taskpool

    M = make_input(n, dtype)
    A = TwoDimBlockCyclic(n, n, nb, nb, dtype=dtype).from_numpy(M)
    cg = ptg.capture(dpotrf_taskpool(A))
    dev = jax.devices()[0]
    tiles = {"descA": {c: jax.device_put(A.tile(*c), dev)
                       for c in A.tiles()}}
    jax.block_until_ready(tiles)
    out = cg.fn(tiles)            # compile (untimed, one-time per shape)
    jax.block_until_ready(out)
    best = None
    for _ in range(reps):
        t0 = time.perf_counter()
        out = cg.fn(tiles)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    Lh = np.zeros((n, n), dtype)
    for (m, k), arr in out["descA"].items():
        if m >= k:  # lower tiles only: skip untouched upper-tile pulls
            Lh[m * nb:(m + 1) * nb, k * nb:(k + 1) * nb] = np.asarray(arr)
    return best, check_numerics(Lh, M, n)


def bench_wave(n, nb, reps, dtype):
    """Wave execution: ready antichains as batched per-class XLA calls
    over device tile pools (dsl/ptg/wave.py) — the runtime path that
    stays scalable at small NB where per-task dispatch would dominate."""
    import jax
    from parsec_tpu.collections import TwoDimBlockCyclic
    from parsec_tpu.dsl.ptg.wave import wave
    from parsec_tpu.ops import dpotrf_taskpool

    M = make_input(n, dtype)
    A = TwoDimBlockCyclic(n, n, nb, nb, dtype=dtype).from_numpy(M)
    w = wave(dpotrf_taskpool(A),
             max_chunk=int(os.environ.get("BENCH_WAVE_CHUNK", "256")))
    dev = jax.devices()[0]
    pools = w.execute(w.build_pools(device=dev))   # warm the kernel cache
    jax.block_until_ready(pools)
    best = None
    for _ in range(reps):
        pools = w.build_pools(device=dev)
        jax.block_until_ready(pools)
        t0 = time.perf_counter()
        pools = w.execute(pools)
        jax.block_until_ready(pools)
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    w.scatter_pools(pools)
    return best, check_numerics(np.tril(A.to_numpy()), M, n)


def bench_runtime(n, nb, reps, cores, dtype):
    """Per-task dispatch through the scheduler + TPU device module."""
    import parsec_tpu
    from parsec_tpu.collections import TwoDimBlockCyclic
    from parsec_tpu.ops import dpotrf_taskpool, make_spd

    M = make_input(n, dtype)
    ctx = parsec_tpu.init(nb_cores=cores)
    try:
        # warmup: 3x3 tiles so POTRF/TRSM/SYRK *and* GEMM kernels compile
        # (a 2x2 grid has no GEMM task and would leak its XLA compile
        # into the first timed rep)
        wm = make_spd(3 * nb, dtype=dtype)
        Aw = TwoDimBlockCyclic(3 * nb, 3 * nb, nb, nb, dtype=dtype).from_numpy(wm)
        ctx.add_taskpool(dpotrf_taskpool(Aw))
        ctx.wait()

        tpu_devs = [d for d in ctx.devices if d.device_type == "tpu"]
        best = None
        A = None
        for _ in range(reps):
            A = TwoDimBlockCyclic(n, n, nb, nb, dtype=dtype).from_numpy(M)
            # prestage tiles into HBM (steady-state model: data lives on
            # device; the timed region measures the factorization DAG)
            if tpu_devs:
                import jax
                for (tm, tn) in A.tiles():
                    tpu_devs[0].data_advise(A.data_of(tm, tn), "prefetch")
                jax.block_until_ready([
                    A.data_of(tm, tn).get_copy(tpu_devs[0].device_index).payload
                    for (tm, tn) in A.tiles()])
            t0 = time.perf_counter()
            ctx.add_taskpool(dpotrf_taskpool(A))
            ctx.wait()
            # the DAG is done when every output tile's device result
            # exists; block on the newest copies so async dispatch is
            # fully timed
            import jax
            pend = []
            for (tm, tn) in A.tiles():
                c = A.data_of(tm, tn).newest_copy()
                if c is not None and c.payload is not None:
                    pend.append(c.payload)
            jax.block_until_ready(pend)
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best, check_numerics(A.to_numpy(), M, n)
    finally:
        ctx.fini()


def main() -> None:
    n = int(os.environ.get("BENCH_N", "8192"))
    nb = int(os.environ.get("BENCH_NB", "2048"))
    reps = int(os.environ.get("BENCH_REPS", "3"))
    cores = int(os.environ.get("BENCH_CORES", "1"))
    mode = os.environ.get("BENCH_MODE", "capture")
    dtype = np.dtype(os.environ.get("BENCH_DTYPE", "float32"))

    if mode == "capture":
        best, err = bench_capture(n, nb, reps, dtype)
    elif mode == "wave":
        best, err = bench_wave(n, nb, reps, dtype)
    else:
        best, err = bench_runtime(n, nb, reps, cores, dtype)
    emit(n, nb, dtype, mode, best, err)


if __name__ == "__main__":
    main()
