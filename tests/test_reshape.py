"""Reshape engine tests: conversion kernels, promise dedup, PTG edges.

Mirrors the reference's reshape coverage (tests/collections/reshape/ — 18
files exercising local and remote conversion paths, SURVEY.md §4) at the
engine and DSL levels.
"""
import threading

import numpy as np
import pytest

import parsec_tpu
from parsec_tpu.comm import RemoteDepEngine
from parsec_tpu.collections import TwoDimBlockCyclic
from parsec_tpu.data.datatype import Datatype, dtt_of_array
from parsec_tpu.data.data import Coherency, Data, DataCopy
from parsec_tpu.data.reshape import ReshapeRepo, reshape_array
from parsec_tpu.dsl import ptg

from test_comm_multirank import spmd


def _copy_of(arr, dtt=None):
    d = Data(nb_elts=arr.size)
    c = DataCopy(d, 0, payload=arr, dtt=dtt)
    c.version = 1
    c.coherency = Coherency.OWNED
    d.attach_copy(c)
    return c


# --------------------------------------------------------------------- #
# conversion kernel                                                     #
# --------------------------------------------------------------------- #
def test_reshape_array_regions_and_cast():
    a = np.arange(16, dtype=np.float64).reshape(4, 4) + 1
    lo = reshape_array(a, Datatype(np.float32, (4, 4), "lower"))
    assert lo.dtype == np.float32
    assert lo[2, 1] == a[2, 1] and lo[1, 2] == 0.0
    up = reshape_array(a, Datatype(np.float64, (4, 4), "upper"))
    assert up[1, 2] == a[1, 2] and up[2, 1] == 0.0
    band = reshape_array(a, Datatype(np.float64, (4, 4), "band", band=(1, 0)))
    assert band[1, 0] == a[1, 0] and band[3, 1] == 0.0 and band[0, 1] == 0.0
    # element-count-preserving reshape
    flat = reshape_array(a, Datatype(np.float64, (16,)))
    assert flat.shape == (16,)
    with pytest.raises(ValueError):
        reshape_array(a, Datatype(np.float64, (3, 3)))


def test_reshape_array_jax():
    import jax.numpy as jnp
    a = jnp.ones((4, 4), jnp.float32)
    lo = reshape_array(a, Datatype(np.float32, (4, 4), "lower"))
    assert float(lo[0, 3]) == 0.0 and float(lo[3, 0]) == 1.0


# --------------------------------------------------------------------- #
# promise dedup                                                         #
# --------------------------------------------------------------------- #
def test_repo_dedups_concurrent_consumers():
    repo = ReshapeRepo()
    src = _copy_of(np.arange(16, dtype=np.float32).reshape(4, 4))
    dst = Datatype(np.float32, (4, 4), "lower")
    got = []
    lock = threading.Lock()

    def consume():
        c = repo.reshaped_copy(src, dst)
        with lock:
            got.append(c)

    ts = [threading.Thread(target=consume) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert repo.stats["conversions"] == 1  # one conversion for 8 consumers
    assert all(c is got[0] for c in got)  # shared converted copy
    assert got[0].payload[1, 2] == 0.0
    # a different target type converts separately
    c2 = repo.reshaped_copy(src, Datatype(np.float32, (4, 4), "upper"))
    assert repo.stats["conversions"] == 2
    assert c2.payload[2, 1] == 0.0
    # matching type short-circuits without a promise
    same = repo.reshaped_copy(src, dtt_of_array(src.payload))
    assert same is src


def test_incoming_promise_remote_variant():
    repo = ReshapeRepo()
    dst = Datatype(np.float32, (4, 4), "lower")
    fut, deliver = repo.incoming_promise(("tp0", "T", (3,), "A"), dst)
    # same edge+type re-arms onto the same promise
    fut2, _ = repo.incoming_promise(("tp0", "T", (3,), "A"), dst)
    assert fut is fut2
    got = []

    def consume():
        got.append(fut.get_or_trigger(timeout=10))

    ts = [threading.Thread(target=consume) for _ in range(4)]
    for t in ts:
        t.start()
    deliver(np.ones((4, 4), np.float32))
    for t in ts:
        t.join(10)
    assert len(got) == 4 and all(g is got[0] for g in got)
    assert got[0].payload[0, 3] == 0.0 and got[0].payload[3, 0] == 1.0
    assert repo.stats["conversions"] == 1


# --------------------------------------------------------------------- #
# PTG edges                                                             #
# --------------------------------------------------------------------- #
RESHAPE_JDF = """
descA [ type="collection" ]
out [ type="object" ]

Prod(k)
k = 0 .. 0
: descA( 0, 0 )
RW A <- descA( 0, 0 )
     -> A Lo( 0 ) [type=lower]
     -> A Lo( 1 ) [type=lower]
     -> A Up( 0 ) [type=upper]
BODY
{
    A += 1.0
}
END

Lo(k)
k = 0 .. 1
: descA( 0, 0 )
READ A <- A Prod( 0 ) [type=lower]
BODY
{
    out['lo%d' % k] = np.array(A)
}
END

Up(k)
k = 0 .. 0
: descA( 0, 0 )
READ A <- A Prod( 0 ) [type=upper]
BODY
{
    out['up'] = np.array(A)
}
END
"""


def test_ptg_local_reshape_edges(ctx):
    n = 4
    coll = TwoDimBlockCyclic(n, n, n, n, dtype=np.float64)
    base = np.arange(n * n, dtype=np.float64).reshape(n, n)
    coll.from_numpy(base.copy())
    out = {}
    tp = ptg.compile_jdf(RESHAPE_JDF, name="reshape_local").new(
        descA=coll, out=out)
    ctx.add_taskpool(tp)
    ctx.wait()
    prod = base + 1.0
    tril = np.tril(prod)
    triu = np.triu(prod)
    np.testing.assert_array_equal(out["lo0"], tril)
    np.testing.assert_array_equal(out["lo1"], tril)
    np.testing.assert_array_equal(out["up"], triu)
    # two lower-consumers shared one conversion; upper adds one more
    assert tp.reshape_repo.stats["conversions"] == 2


MEM_TYPE_JDF = """
descA [ type="collection" ]
out [ type="object" ]

T(k)
k = 0 .. 0
: descA( 0, 0 )
READ A <- descA( 0, 0 ) [type=lower]
BODY
{
    out['seen'] = np.array(A)
}
END
"""


def test_ptg_memory_input_type(ctx):
    n = 4
    coll = TwoDimBlockCyclic(n, n, n, n, dtype=np.float64)
    base = np.arange(n * n, dtype=np.float64).reshape(n, n) + 1
    coll.from_numpy(base.copy())
    out = {}
    tp = ptg.compile_jdf(MEM_TYPE_JDF, name="reshape_mem").new(
        descA=coll, out=out)
    ctx.add_taskpool(tp)
    ctx.wait()
    np.testing.assert_array_equal(out["seen"], np.tril(base))
    # the home tile was not mutated by the read-side conversion
    np.testing.assert_array_equal(coll.data_of(0, 0).host_copy().payload, base)


REMOTE_RESHAPE_JDF = """
descA [ type="collection" ]
out [ type="object" ]

Prod(k)
k = 0 .. 0
: descA( 0, 0 )
RW A <- descA( 0, 0 )
     -> A Cons( 0 )
BODY
{
    A += 1.0
}
END

Cons(k)
k = 0 .. 0
: descA( 1, 0 )
READ A <- A Prod( 0 ) [type=lower]
BODY
{
    out['seen'] = np.array(A)
}
END
"""


def test_ptg_remote_reshape_edge():
    """Producer on rank 0, consumer on rank 1 declaring [type=lower]: the
    conversion happens on the receiver from the wire payload."""
    n = 4
    outs = [dict() for _ in range(2)]

    def rank_fn(rank, fabric):
        eng = RemoteDepEngine(fabric.engine(rank))
        ctx = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
        try:
            coll = TwoDimBlockCyclic(2 * n, n, n, n, P=2, Q=1, nodes=2,
                                     rank=rank, dtype=np.float64)
            coll.name = "descA"
            base = np.tile(np.arange(n, dtype=np.float64), (2 * n, 1))
            coll.from_numpy(base)
            tp = ptg.compile_jdf(REMOTE_RESHAPE_JDF, name="reshape_remote").new(
                descA=coll, out=outs[rank], rank=rank, nb_ranks=2)
            ctx.add_taskpool(tp)
            ctx.wait()
            return tp.reshape_repo.stats.copy()
        finally:
            ctx.fini()

    results, _ = spmd(2, rank_fn)
    expect = np.tril(np.tile(np.arange(n, dtype=np.float64), (n, 1)) + 1.0)
    np.testing.assert_array_equal(outs[1]["seen"], expect)
    assert "seen" not in outs[0]
    # conversion ran on the consumer rank only
    assert results[1]["conversions"] == 1
    assert results[0]["conversions"] == 0
