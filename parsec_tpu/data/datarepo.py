"""Data repos: task-output hash tables with usage-count reclamation.

Reference behavior: each task class has a repo hashing task key ->
entry of produced data copies; the entry stays until every consumer has
taken its input (``usagecnt``), plus an explicit retain while the producer
is still filling it (ref: parsec/datarepo.c/.h, SURVEY.md §2.1).
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ..core.hashtable import HashTable


class RepoEntry:
    __slots__ = ("key", "data", "usagecnt", "retained", "repo")

    def __init__(self, repo: "DataRepo", key: Any, nb_flows: int) -> None:
        self.repo = repo
        self.key = key
        self.data: List[Optional[Any]] = [None] * nb_flows  # DataCopy per out-flow
        self.usagecnt = 0
        self.retained = 0

    def set_output(self, flow_index: int, copy: Any) -> None:
        self.data[flow_index] = copy


class DataRepo:
    """Hash table keyed by task key, entries reclaimed when fully consumed."""

    def __init__(self, nb_flows: int) -> None:
        self.nb_flows = nb_flows
        self._table = HashTable()
        self._lock = threading.Lock()

    def lookup_and_create(self, key: Any) -> RepoEntry:
        """ref: data_repo_lookup_entry_and_create — creation retains."""
        def factory() -> RepoEntry:
            return RepoEntry(self, key, self.nb_flows)
        entry, created = self._table.find_or_insert(key, factory)
        with self._lock:
            entry.retained += 1
        return entry

    def lookup(self, key: Any) -> Optional[RepoEntry]:
        return self._table.find(key)

    def entry_addto_usage_limit(self, key: Any, nb_usage: int) -> None:
        """Producer declares how many consumptions the entry must survive."""
        entry = self._table.find(key)
        assert entry is not None
        dead = False
        with self._lock:
            entry.usagecnt += nb_usage
            dead = entry.usagecnt == 0 and entry.retained == 0
        if dead:
            self._reclaim(entry)

    def entry_used_once(self, key: Any) -> None:
        """ref: data_repo_entry_used_once — one consumer took its input."""
        entry = self._table.find(key)
        if entry is None:
            return
        dead = False
        with self._lock:
            entry.usagecnt -= 1
            dead = entry.usagecnt == 0 and entry.retained == 0
        if dead:
            self._reclaim(entry)

    def entry_release(self, key: Any) -> None:
        """Drop the producer's retain."""
        entry = self._table.find(key)
        if entry is None:
            return
        dead = False
        with self._lock:
            entry.retained -= 1
            dead = entry.usagecnt <= 0 and entry.retained == 0
        if dead:
            self._reclaim(entry)

    def _reclaim(self, entry: RepoEntry) -> None:
        self._table.remove(entry.key)
        for copy in entry.data:
            if copy is not None and hasattr(copy, "release"):
                copy.release()
        entry.data = []

    def __len__(self) -> int:
        return len(self._table)
