"""Runtime concurrency lint: declared lock discipline over parsec_tpu/.

A module opts in by declaring a ``_GUARDED_BY`` map at module level
(the clang ``GUARDED_BY`` annotation, as data):

    _GUARDED_BY = {
        "Data._copies": "_lock",      # Class.field -> lock attr on the
        "_Peer.ctrl":   "cond",       # same receiver object
    }

Rules enforced (LCK3xx):

- ``LCK301`` unguarded-field: an attribute access ``<recv>.<field>``
  where ``field`` is registered must be lexically inside
  ``with <recv>.<lock>:`` (same receiver expression).  ``Class.field``
  keys bind ``self.field`` accesses inside that class; accesses through
  any other simple receiver name match by field name.
- ``LCK302`` blocking-while-locked: no blocking call (``time.sleep``,
  socket send/recv/accept/connect, ``select``, thread ``join``,
  ``wait``/``wait_for`` on anything but the held condition) while a
  declared lock is held.  ``Condition.wait`` on the *held* condition is
  exempt — it releases the lock.
- ``LCK303`` unregistered-lock: in a module that declares a
  ``_GUARDED_BY`` map (even an empty one), every
  ``threading.Lock/RLock/Condition/Semaphore`` construction must be
  registered as some field's lock in the map.  This is what makes an
  empty map a *contract* rather than a no-op: adding a lock to an
  audited-lock-free module fails the gate until its fields are
  declared.

Holding is established by (a) an enclosing ``with <recv>.<lock>:``,
(b) a ``<recv>.<lock>.acquire(...)`` call earlier in the same function
(the try/finally-release manager pattern), or (c) a ``# holds:
<recv>.<lock>`` annotation on the ``def`` line — the clang
``REQUIRES()`` analog for helpers documented as called-with-lock-held.

Escapes, used sparingly and always with a reason:

- ``__init__`` / ``__new__`` / ``__del__`` / ``_destruct`` bodies are
  exempt (single-owner construction/teardown).
- a trailing ``# lock: <reason>`` comment waives one line (the TSan
  benign-race annotation analog);
- a ``# lock: exempt(<reason>)`` comment on a ``def`` line waives the
  whole function (teardown paths quiesced by protocol).

Modules without a ``_GUARDED_BY`` map are skipped — the lint is a
contract checker, not a race detector.
"""
from __future__ import annotations

import ast as pyast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import Finding

_EXEMPT_FUNCS = {"__init__", "__new__", "__del__", "_destruct"}
_BLOCKING_SOCKET = {"sendall", "sendmsg", "recv", "recv_into", "accept",
                    "connect", "sendto", "recvfrom"}
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}


def _attr_chain(node: pyast.AST) -> List[str]:
    parts: List[str] = []
    while isinstance(node, pyast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, pyast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return []


def _find_guarded_by(tree: pyast.Module) -> Optional[Dict[str, str]]:
    for node in tree.body:
        if isinstance(node, pyast.Assign):
            for t in node.targets:
                if isinstance(t, pyast.Name) and t.id == "_GUARDED_BY":
                    try:
                        val = pyast.literal_eval(node.value)
                    except (ValueError, SyntaxError):
                        return None
                    return val if isinstance(val, dict) else None
        elif isinstance(node, pyast.AnnAssign) and \
                isinstance(node.target, pyast.Name) and \
                node.target.id == "_GUARDED_BY" and node.value is not None:
            try:
                val = pyast.literal_eval(node.value)
            except (ValueError, SyntaxError):
                return None
            return val if isinstance(val, dict) else None
    return None


class _FieldRules:
    """field name -> [(class or None, lock attr)]"""

    def __init__(self, guarded_by: Dict[str, str]) -> None:
        self.by_field: Dict[str, List[Tuple[Optional[str], str]]] = {}
        self.lock_names: Set[str] = set(guarded_by.values())
        for key, lock in guarded_by.items():
            cls, _, fld = key.rpartition(".")
            self.by_field.setdefault(fld, []).append((cls or None, lock))

    def lock_for(self, field: str, recv: str,
                 enclosing_class: Optional[str]) -> Optional[str]:
        """The lock attr required for this access, or None if the field
        is not governed for this receiver."""
        rules = self.by_field.get(field)
        if not rules:
            return None
        if recv == "self":
            for cls, lock in rules:
                if cls is None or cls == enclosing_class:
                    return lock
            return None
        # non-self receiver: class unknown statically — any rule for the
        # field name applies (module-scoped maps keep this unambiguous)
        return rules[0][1]


class _FuncLinter(pyast.NodeVisitor):
    """Lint one function body with lexical lock tracking."""

    def __init__(self, rules: _FieldRules, lines: Sequence[str],
                 where_prefix: str, enclosing_class: Optional[str],
                 base_held: Set[str], findings: List[Finding]) -> None:
        self.rules = rules
        self.lines = lines
        self.where = where_prefix
        self.cls = enclosing_class
        self.held: Set[str] = set(base_held)
        self.findings = findings

    # -- helpers -------------------------------------------------------
    def _line_comment(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            line = self.lines[lineno - 1]
            idx = line.find("#")
            if idx >= 0:
                return line[idx:]
        return ""

    def _waived(self, node: pyast.AST) -> bool:
        return "# lock:" in self._line_comment(getattr(node, "lineno", 0))

    def _lock_expr(self, node: pyast.AST) -> Optional[str]:
        """Normalize a with-context / acquire receiver to 'recv.attr'."""
        chain = _attr_chain(node)
        if len(chain) >= 2:
            return ".".join(chain)
        return None

    # -- lock tracking -------------------------------------------------
    def visit_With(self, node: pyast.With) -> None:
        added: Set[str] = set()
        for item in node.items:
            lk = self._lock_expr(item.context_expr)
            if lk is not None and lk not in self.held:
                added.add(lk)
        self.held |= added
        for item in node.items:
            self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        self.held -= added

    # nested defs/lambdas run later, without the current locks
    def visit_FunctionDef(self, node) -> None:
        _lint_function(node, self.rules, self.lines, self.where, self.cls,
                       self.findings)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: pyast.Lambda) -> None:
        pass

    # -- the two rules -------------------------------------------------
    def _held_lock_names(self) -> Set[str]:
        return {h.rpartition(".")[2] for h in self.held}

    def visit_Call(self, node: pyast.Call) -> None:
        # acquire() heuristic: held for the remainder of the function
        # (the try/finally-release manager pattern)
        chain = _attr_chain(node.func)
        if chain and chain[-1] == "acquire" and len(chain) >= 3:
            self.held.add(".".join(chain[:-1]))
        elif chain and chain[-1] == "release" and len(chain) >= 3:
            self.held.discard(".".join(chain[:-1]))
        elif chain and not self._waived(node) and \
                self._held_lock_names() & self.rules.lock_names:
            self._check_blocking(node, chain)
        self.generic_visit(node)

    def _check_blocking(self, node: pyast.Call, chain: List[str]) -> None:
        last = chain[-1]
        blocking = None
        if chain in (["time", "sleep"], ["sleep"]):
            blocking = "sleep"
        elif chain == ["select", "select"]:
            blocking = "select"
        elif last in _BLOCKING_SOCKET:
            blocking = f"socket .{last}()"
        elif last == "join" and len(chain) >= 2:
            blocking = ".join()"
        elif last in ("wait", "wait_for") and len(chain) >= 2:
            recv = ".".join(chain[:-1])
            if recv not in self.held:
                blocking = f".{last}() on a lock/event not held here"
        if blocking is not None:
            held = ", ".join(sorted(
                h for h in self.held
                if h.rpartition(".")[2] in self.rules.lock_names))
            self.findings.append(Finding(
                "LCK302",
                f"blocking call ({blocking}: {'.'.join(chain)}) while "
                f"holding {held}",
                f"{self.where}:{node.lineno}"))

    def visit_Attribute(self, node: pyast.Attribute) -> None:
        if isinstance(node.ctx, (pyast.Load, pyast.Store, pyast.Del)):
            recv_chain = _attr_chain(node.value)
            if len(recv_chain) == 1:
                recv = recv_chain[0]
                lock = self.rules.lock_for(node.attr, recv, self.cls)
                if lock is not None:
                    need = f"{recv}.{lock}"
                    if need not in self.held and not self._waived(node):
                        self.findings.append(Finding(
                            "LCK301",
                            f"{recv}.{node.attr} is guarded by {need} "
                            f"(_GUARDED_BY) but accessed without it",
                            f"{self.where}:{node.lineno}"))
        self.generic_visit(node)


def _def_annotations(node, lines: Sequence[str]) -> Tuple[Set[str], bool]:
    """(# holds: locks, whole-function waiver) from the def line(s)."""
    held: Set[str] = set()
    exempt = False
    end = getattr(node.body[0], "lineno", node.lineno) if node.body \
        else node.lineno
    for ln in range(node.lineno, end + 1):
        if not (1 <= ln <= len(lines)):
            continue
        line = lines[ln - 1]
        idx = line.find("#")
        if idx < 0:
            continue
        comment = line[idx:]
        if "# lock: exempt" in comment:
            exempt = True
        hidx = comment.find("# holds:")
        if hidx >= 0:
            spec = comment[hidx + len("# holds:"):].strip()
            for part in spec.split(","):
                part = part.strip()
                if part:
                    held.add(part)
    return held, exempt


def _lint_function(node, rules: _FieldRules, lines: Sequence[str],
                   where_prefix: str, enclosing_class: Optional[str],
                   findings: List[Finding]) -> None:
    if node.name in _EXEMPT_FUNCS:
        return
    base_held, exempt = _def_annotations(node, lines)
    if exempt:
        return
    linter = _FuncLinter(rules, lines, where_prefix, enclosing_class,
                         base_held, findings)
    for stmt in node.body:
        linter.visit(stmt)


def _line_waived(lines: Sequence[str], lineno: int) -> bool:
    if 1 <= lineno <= len(lines):
        idx = lines[lineno - 1].find("#")
        if idx >= 0:
            return "# lock:" in lines[lineno - 1][idx:]
    return False


def _scan_unregistered_locks(tree: pyast.Module, rules: _FieldRules,
                             lines: Sequence[str], filename: str,
                             findings: List[Finding]) -> None:
    """LCK303: every lock constructed in an opted-in module must be some
    field's registered lock — this is what keeps an EMPTY map a contract
    (a future lock in an audited-lock-free module fails the gate until
    its fields are declared)."""
    for node in pyast.walk(tree):
        if isinstance(node, pyast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, pyast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        if not isinstance(value, pyast.Call):
            continue
        chain = _attr_chain(value.func)
        if not chain or chain[-1] not in _LOCK_CTORS:
            continue
        for t in targets:
            name = t.attr if isinstance(t, pyast.Attribute) else (
                t.id if isinstance(t, pyast.Name) else None)
            if name is None or name in rules.lock_names:
                continue
            if _line_waived(lines, node.lineno):
                continue
            findings.append(Finding(
                "LCK303",
                f"lock {name} ({'.'.join(chain)}) is not registered as "
                f"any field's guard in this module's _GUARDED_BY map",
                f"{filename}:{node.lineno}"))


def lint_source(source: str, filename: str = "<module>") -> List[Finding]:
    """Lint one module's source.  No ``_GUARDED_BY`` map: no findings."""
    try:
        tree = pyast.parse(source)
    except SyntaxError as exc:
        return [Finding("LCK300", f"cannot parse: {exc}", filename)]
    guarded = _find_guarded_by(tree)
    if guarded is None:
        return []
    rules = _FieldRules(guarded)
    lines = source.splitlines()
    findings: List[Finding] = []
    _scan_unregistered_locks(tree, rules, lines, filename, findings)

    def walk_body(body, cls: Optional[str]) -> None:
        for node in body:
            if isinstance(node, (pyast.FunctionDef,
                                 pyast.AsyncFunctionDef)):
                _lint_function(node, rules, lines, filename, cls, findings)
            elif isinstance(node, pyast.ClassDef):
                walk_body(node.body, node.name)
            elif isinstance(node, (pyast.If, pyast.Try, pyast.With)):
                # module-level control flow: keep walking
                for sub in pyast.iter_child_nodes(node):
                    if isinstance(sub, (pyast.FunctionDef, pyast.ClassDef)):
                        walk_body([sub], cls)
    walk_body(tree.body, None)
    return findings


def lint_file(path: str) -> List[Finding]:
    with open(path) as fh:
        return lint_source(fh.read(), path)


def lint_tree(root: str) -> List[Finding]:
    """Lint every ``*.py`` under ``root`` (modules without a
    ``_GUARDED_BY`` map contribute nothing)."""
    findings: List[Finding] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                findings.extend(lint_file(os.path.join(dirpath, fn)))
    return findings
