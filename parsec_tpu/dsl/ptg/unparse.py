"""JDF unparser: AST back to canonical JDF text
(ref: parsec/interfaces/ptg/ptg-compiler/jdf_unparse.c — the reference
regenerates .jdf source from its AST for tooling and debugging; the
roundtrip parse(unparse(ast)) must preserve structure).
"""
from __future__ import annotations

from typing import List

from .ast import DepAST, DepTarget, JDFFile, RangeExpr, TaskClassAST


def _range_src(r) -> str:
    if isinstance(r, RangeExpr):
        s = f"{r.lo.src} .. {r.hi.src}"
        if r.step is not None:
            s += f" .. {r.step.src}"
        return s
    return r.src


def _target_src(t: DepTarget) -> str:
    if t.kind == "null":
        return "NULL"
    if t.kind == "new":
        return "NEW"
    args = ", ".join(_range_src(a) for a in t.args)
    if t.kind == "memory":
        return f"{t.collection}( {args} )"
    return f"{t.flow} {t.task_class}( {args} )"


def _prop_val(v: str) -> str:
    # quote anything the unquoted \S+ grammar could not re-read intact
    if v and " " not in v and "\t" not in v and '"' not in v and "]" not in v:
        return v
    return '"' + v.replace('"', "") + '"'


def _props_src(props) -> str:
    if not props:
        return ""
    inner = " ".join(f"{k}={_prop_val(v)}" for k, v in props.items())
    return f"  [{inner}]"


def _dep_src(d: DepAST) -> str:
    arrow = "<-" if d.direction == "in" else "->"
    body = _target_src(d.target)
    if d.guard is not None:
        body = f"({d.guard.src}) ? {body}"
        if d.alt_target is not None:
            body += f" : {_target_src(d.alt_target)}"
    return f"{arrow} {body}{_props_src(d.properties)}"


def unparse_task_class(tc: TaskClassAST) -> str:
    head = f"{tc.name}({', '.join(tc.params)})"
    head += _props_src(tc.properties)
    out: List[str] = [head, ""]
    for ld in tc.locals:
        if ld.range is not None:
            out.append(f"{ld.name} = {_range_src(ld.range)}")
        else:
            out.append(f"{ld.name} = {ld.expr.src}")
    out.append("")
    if tc.affinity_collection is not None:
        args = ", ".join(a.src for a in tc.affinity_args)
        out.append(f": {tc.affinity_collection}( {args} )")
        out.append("")
    for f in tc.flows:
        deps = f.deps
        head = f"{f.access:<5s} {f.name} "
        if deps:
            out.append(head + _dep_src(deps[0]))
            pad = " " * len(head)
            for d in deps[1:]:
                out.append(pad + _dep_src(d))
        else:
            out.append(head.rstrip())
    out.append("")
    if tc.priority is not None:
        out.append(f"; {tc.priority.src}")
        out.append("")
    for b in tc.bodies:
        props = _props_src(b.properties).strip()
        out.append(f"BODY {props}".rstrip())
        out.append("{")
        for line in b.code.splitlines():
            out.append(f"    {line}" if line.strip() else "")
        out.append("}")
        out.append("END")
        out.append("")
    return "\n".join(out)


def unparse(jdf: JDFFile) -> str:
    """Canonical JDF text for the whole file."""
    out: List[str] = []
    for block in jdf.prologue:
        # the grammar only recognizes externs with a language tag; the
        # block carries its own newlines, so emit delimiters inline for
        # an exact roundtrip
        out.append('extern "PYTHON" %{' + block + "%}")
        out.append("")
    for g in jdf.globals:
        props = _props_src(g.properties).strip()
        out.append(f"{g.name} {props}".rstrip())
    out.append("")
    for tc in jdf.task_classes:
        out.append(unparse_task_class(tc))
    for block in jdf.epilogue:
        out.append('extern "PYTHON" %{' + block + "%}")
        out.append("")
    return "\n".join(out) + "\n"
