"""Recursive task calls: a task body spawns a nested taskpool and completes
only when it terminates.

Reference behavior: ``parsec_recursivecall`` submits a nested taskpool on
behalf of the running task; the task's hook returns ASYNC, and the nested
pool's completion callback finishes the generator task (ref:
parsec/recursive.h:44-70, callback ``parsec_recursivecall_callback``).
The completion is deferred to a scheduler thread via ``Context.defer`` —
termination detection may fire on any thread, and ``complete_execution``
needs a live execution stream (ref: HOOK_RETURN_ASYNC re-entry,
scheduling.c:503-506).

Typical use (the reference's pattern: a too-large tile kernel re-expressed
over sub-tiles, ref: parsec/data_dist/matrix/subtile.c):

    def potrf_body(es, task):
        (tile,) = unpack_args(task)
        sub = SubtileView(tile, smaller_nb, smaller_nb)
        return recursive_call(es, task, dpotrf_taskpool(sub))
"""
from __future__ import annotations

from typing import Callable, Optional

from .taskpool import HookReturn, Task, Taskpool

__all__ = ["recursive_call"]


def recursive_call(es, task: Task, subpool: Taskpool,
                   callback: Optional[Callable] = None) -> HookReturn:
    """Enqueue ``subpool``; when it completes, run ``callback(subpool,
    task)`` (if given) and complete ``task``. Returns ``HookReturn.ASYNC``
    for the body to return, so the runtime does not complete the task now."""
    ctx = task.taskpool.context
    assert ctx is not None, "recursive_call before context.add_taskpool"
    prev_cb = subpool.on_complete

    def done(sub_tp):
        if prev_cb is not None:
            prev_cb(sub_tp)

        def finish(wes):
            from .scheduling import complete_execution
            # subtile views (or any collection with a pull_home protocol)
            # fold device results back into the parent tile before the
            # generator task is declared complete
            for v in getattr(sub_tp, "global_env", {}).values():
                if hasattr(v, "pull_home"):
                    v.pull_home(ctx.devices)
            if callback is not None:
                callback(sub_tp, task)
            complete_execution(wes, task)

        ctx.defer(finish)

    subpool.on_complete = done
    ctx.add_taskpool(subpool)
    # DTD sub-pools: all inserts were buffered before this call; seal so
    # the pool terminates without a blocking wait()
    seal = getattr(subpool, "seal", None)
    if seal is not None:
        seal()
    return HookReturn.ASYNC
