"""Pipeline parallelism: GPipe-style microbatch schedule over the pp axis.

Each pp shard holds one stage's parameters; activations flow stage-to-stage
with ``lax.ppermute`` in a ``lax.scan`` over M + S - 1 ticks (M microbatches
through S stages), so the schedule compiles to one XLA loop with
neighbor-only ICI traffic. Differentiable: reverse-mode AD through the scan
reproduces the backward pipeline (the reference expresses pipelining as DAG
edges + per-device chores, SURVEY.md §2.8; this is the compiled-collective
equivalent).
"""
from __future__ import annotations

from typing import Any, Callable

import jax.numpy as jnp
from jax import lax

from .mesh import axis_size


def gpipe(stage_fn: Callable[[Any, Any], Any], stage_params: Any,
          x_micro: Any, axis_name: str = "pp", with_aux: bool = False) -> Any:
    """Run the pipeline.

    stage_fn(stage_params, x) applies THIS shard's stage to one microbatch.
    x_micro: [M, mb, ...] microbatches (only stage 0's value is consumed).
    Returns [M, mb, ...] stage-S-1 outputs — valid ON THE LAST STAGE ONLY
    (other shards hold garbage; reduce with a masked psum, see
    models/train.py).

    with_aux: stage_fn returns (y, aux_scalar); gpipe accumulates aux only
    over the (stage, tick) pairs doing real work (bubble ticks run on
    garbage and are masked out) and returns (outs, aux_sum) where aux_sum
    is THIS stage's total over its layers x all microbatches.
    """
    S = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    M = x_micro.shape[0]
    steps = M + S - 1
    fwd = [(i, i + 1) for i in range(S - 1)]

    from .mesh import vary_on
    # scan carries become pp-varying through the stage params / axis_index;
    # promote the fresh-zeros initials to the matching VMA type
    target = (axis_name,)
    out0 = vary_on(jnp.zeros_like(x_micro), target, like=x_micro)
    buf0 = vary_on(jnp.zeros_like(x_micro[0]), target, like=x_micro)
    aux0 = vary_on(jnp.zeros((), jnp.float32), target, like=x_micro)

    def tick(carry, t):
        buf, outs, aux_sum = carry
        # stage 0 feeds microbatch t (while t < M); other stages consume
        # what arrived from the previous stage
        feed = x_micro[jnp.clip(t, 0, M - 1)]
        inp = jnp.where(idx == 0, feed, buf)
        if with_aux:
            y, aux = stage_fn(stage_params, inp)
            # stage idx works on microbatch t-idx at this tick
            work = (t - idx >= 0) & (t - idx < M)
            aux_sum = aux_sum + jnp.where(work, aux, 0.0)
        else:
            y = stage_fn(stage_params, inp)
        # drain: the last stage completed microbatch t-(S-1) at this tick
        mb = t - (S - 1)
        valid = (mb >= 0) & (mb < M)
        slot = jnp.clip(mb, 0, M - 1)
        outs = outs.at[slot].set(jnp.where(valid, y, outs[slot]))
        buf_next = lax.ppermute(y, axis_name, fwd) if S > 1 else buf
        return (buf_next, outs, aux_sum), None

    (_, outs, aux_sum), _ = lax.scan(tick, (buf0, out0, aux0),
                                     jnp.arange(steps))
    return (outs, aux_sum) if with_aux else outs


def last_stage_value(x: Any, axis_name: str = "pp") -> Any:
    """Reduce a per-shard value to the LAST pp stage's contribution,
    replicated everywhere (masked psum)."""
    S = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    return lax.psum(jnp.where(idx == S - 1, x, jnp.zeros_like(x)), axis_name)
