"""In-process loopback transfer backend: the device-plane contract
(``start_transfer_server`` → server with ``address``/``await_pull``/
``connect``, connection with ``pull``) over plain TCP sockets.

``jax.experimental.transfer`` is a platform feature — TPU/GPU builds
expose the DCN/ICI pull API, CPU wheels may not, and the native
transport additionally refuses two servers in one OS process (abseil
local-bulk-transport CHECK).  This module keeps the device-plane CODE
PATH exercisable everywhere: same wire contract (uuid-keyed one-shot
pulls of parked arrays), host sockets instead of the interconnect
fabric, no process-count restriction — so CI runs the real
:class:`~parsec_tpu.comm.xfer.DeviceDataPlane` logic instead of
skipping it.  Selection is the ``xfer_backend`` MCA knob (auto/native/
loopback); ``auto`` falls back here exactly when the jax API is absent.

Wire protocol (one request/response per pull, persistent connection):
request = ``<Q`` uuid; response = ``<I`` buffer count (``0xFFFFFFFF``
= unknown uuid) then per buffer ``<Q`` length + raw bytes.
"""
from __future__ import annotations

import socket
import struct
import threading
from typing import Any, Dict, List, Sequence

import numpy as np

from ..utils import logging as plog

_MISSING = 0xFFFFFFFF

# concurrency contract checked by tools/lock_check (LCK3xx)
_GUARDED_BY = {
    "LoopbackTransferServer._parked": "_lock",
}


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("loopback transfer peer closed")
        buf += chunk
    return bytes(buf)


class LoopbackConnection:
    """Client half: one persistent socket to a peer's server; pulls are
    serialized request/response round-trips (the lock covers the full
    round-trip so interleaved pulls from racing threads can't tear)."""

    def __init__(self, address: str) -> None:
        host, port = address.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)), timeout=30)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()

    def pull(self, uuid: int, specs: Sequence[Any]) -> List[Any]:
        """Fetch the arrays parked under ``uuid``; each lands shaped and
        placed per its ``jax.ShapeDtypeStruct`` spec (host numpy when a
        spec carries no sharding)."""
        with self._lock:
            self._sock.sendall(struct.pack("<Q", uuid))  # lock: the lock IS the pull serializer — one request/response round-trip per holder, racing pulls must not interleave on the socket
            (count,) = struct.unpack("<I", _read_exact(self._sock, 4))
            if count == _MISSING:
                raise KeyError(f"no parked arrays under uuid {uuid:#x}")
            bufs = []
            for _ in range(count):
                (ln,) = struct.unpack("<Q", _read_exact(self._sock, 8))
                bufs.append(_read_exact(self._sock, ln))
        if len(bufs) != len(specs):
            raise ValueError(
                f"uuid {uuid:#x}: {len(bufs)} parked buffers != "
                f"{len(specs)} requested specs")
        out = []
        for raw, spec in zip(bufs, specs):
            arr = np.frombuffer(raw, dtype=np.dtype(spec.dtype)).reshape(
                spec.shape)
            sharding = getattr(spec, "sharding", None)
            if sharding is not None:
                import jax
                arr = jax.device_put(arr, sharding)
            out.append(arr)
        return out

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class LoopbackTransferServer:
    """Server half: parks host copies of arrays under their uuid and
    serves each to exactly one pull (pop-on-serve — the native
    ``await_pull`` contract), over an accept loop of daemon threads."""

    def __init__(self, address: str) -> None:
        host, port = address.rsplit(":", 1)
        self._listen = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind((host, int(port)))
        self._listen.listen(64)
        self._addr = f"{host}:{self._listen.getsockname()[1]}"
        self._parked: Dict[int, List[bytes]] = {}
        self._lock = threading.Lock()
        self._closed = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"loopback-xfer-{self._addr}")
        self._accept_thread.start()

    # -- the native transfer-server surface ----------------------------- #
    def address(self) -> str:
        return self._addr

    def await_pull(self, uuid: int, arrays: Sequence[Any]) -> None:
        """Park host copies of ``arrays`` for one pull of ``uuid``.  The
        copy happens here (device arrays come down via ``np.asarray``)
        so later producer-side mutation can't tear an in-flight serve."""
        bufs = [np.ascontiguousarray(np.asarray(a)).tobytes()
                for a in arrays]
        with self._lock:
            self._parked[uuid] = bufs

    def connect(self, address: str) -> LoopbackConnection:
        return LoopbackConnection(address)

    def close(self) -> None:
        self._closed = True
        try:
            self._listen.close()
        except OSError:
            pass
        with self._lock:
            self._parked.clear()

    # -- serving -------------------------------------------------------- #
    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._listen.accept()
            except OSError:
                return  # closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve_loop, args=(conn,),
                             daemon=True).start()

    def _serve_loop(self, conn: socket.socket) -> None:
        try:
            while not self._closed:
                (uuid,) = struct.unpack("<Q", _read_exact(conn, 8))
                with self._lock:
                    bufs = self._parked.pop(uuid, None)
                if bufs is None:
                    conn.sendall(struct.pack("<I", _MISSING))
                    continue
                parts = [struct.pack("<I", len(bufs))]
                for b in bufs:
                    parts.append(struct.pack("<Q", len(b)))
                    parts.append(b)
                conn.sendall(b"".join(parts))
        except (ConnectionError, OSError):
            pass  # peer closed (or server shutdown): thread exits
        finally:
            try:
                conn.close()
            except OSError:
                pass
        plog.debug.verbose(4, "loopback xfer %s: serve loop exit",
                           self._addr)


def start_transfer_server(client: Any, address: str,
                          transports: Sequence[str] = ()) -> Any:
    """Signature-compatible stand-in for
    ``jax.experimental.transfer.start_transfer_server`` — ``client``
    and ``transports`` are accepted for parity and ignored (host
    sockets need neither a backend client nor separate bulk-transport
    endpoints)."""
    del client, transports
    return LoopbackTransferServer(address)
