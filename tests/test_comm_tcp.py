"""TCP transport tests: real process isolation with an actual wire
(the reference's mpiexec-launched multi-rank analog, SURVEY.md §4 —
but with our own transport instead of MPI).

In-process tests cover the engine mechanics; the subprocess test runs a
full SPMD PTG chain across OS processes over localhost sockets.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from parsec_tpu.comm.tcp import TCPCommEngine, free_ports

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _engines(n):
    ports = free_ports(n)
    eps = [("127.0.0.1", p) for p in ports]
    import concurrent.futures as cf
    # constructors block dialing each other: bring them up concurrently
    with cf.ThreadPoolExecutor(n) as ex:
        return list(ex.map(lambda r: TCPCommEngine(r, eps), range(n)))


def test_am_roundtrip_and_ordering():
    e0, e1 = _engines(2)
    got = []
    TAG = 100
    e1.tag_register(TAG, lambda src, p: got.append((src, p)))
    try:
        for i in range(5):
            e0.send_am(1, TAG, {"i": i, "arr": np.full((3,), i, np.float32)})
        import time
        deadline = time.time() + 10
        while len(got) < 5 and time.time() < deadline:
            e1.progress()
            time.sleep(0.01)
        assert [p["i"] for _, p in got] == list(range(5))  # FIFO per pair
        np.testing.assert_array_equal(got[3][1]["arr"], np.full((3,), 3))
        assert got[0][0] == 0
    finally:
        e0.fini()
        e1.fini()


def test_get_rendezvous_over_sockets():
    e0, e1 = _engines(2)
    try:
        src = np.arange(64, dtype=np.float32).reshape(8, 8)
        h = e0.mem_register(src)
        got = []
        e1.get(0, h.handle_id, got.append)
        import time
        deadline = time.time() + 10
        while not got and time.time() < deadline:
            e0.progress()
            e1.progress()
            time.sleep(0.01)
        assert got and np.array_equal(got[0], src)
    finally:
        e0.fini()
        e1.fini()


def test_barrier():
    import threading
    e0, e1, e2 = _engines(3)
    order = []
    try:
        def arrive(e, name, delay):
            import time
            time.sleep(delay)
            e.sync()
            order.append(name)

        ts = [threading.Thread(target=arrive, args=(e, n, d)) for e, n, d in
              ((e1, "r1", 0.0), (e2, "r2", 0.15), (e0, "r0", 0.05))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(20)
            assert not t.is_alive()
        assert len(order) == 3  # nobody passed before everyone arrived
    finally:
        for e in (e0, e1, e2):
            e.fini()




def _run_ranks(nb_ranks, hops, mode=None, timeout=180, expect_rcs=None):
    """Launch one tcp_rank_main.py process per rank and collect each
    rank's JSON report (None for ranks expected to exit non-zero).
    ``expect_rcs``: per-rank expected returncode, default all 0."""
    ports = free_ports(nb_ranks)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    argv_tail = [str(hops)] + ([mode] if mode else [])
    procs = [subprocess.Popen(
        [sys.executable, os.path.join(ROOT, "tests", "tcp_rank_main.py"),
         str(r), str(nb_ranks), ",".join(map(str, ports))] + argv_tail,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for r in range(nb_ranks)]
    expect_rcs = expect_rcs or [0] * nb_ranks
    outs = []
    for p, want in zip(procs, expect_rcs):
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == want, (p.returncode, out, err)
        outs.append(json.loads(out.strip().splitlines()[-1])
                    if want == 0 else None)
    return outs


@pytest.mark.parametrize("nb_ranks", [2, 3])
def test_spmd_chain_across_processes(nb_ranks):
    """Full PTG chain with every hop a remote dep over real sockets
    between OS processes; payloads above the short limit take the GET
    rendezvous."""
    hops = 2 * nb_ranks
    outs = _run_ranks(nb_ranks, hops)
    finals = [o["final"] for o in outs if "final" in o]
    assert finals == [float(hops + 1)]
    assert all(o["msgs"] > 0 for o in outs)
    assert sum(o["bytes"] for o in outs) > hops * 1024  # data went over TCP


def test_dtd_chain_across_processes():
    """DTD cross-rank chain over real sockets: the (tile, seq) data plane
    with the 4KB payload taking the GET rendezvous."""
    nb_ranks, hops = 2, 6
    outs = _run_ranks(nb_ranks, hops, mode="dtd")
    finals = [o["final"] for o in outs if "final" in o]
    assert finals == [float(hops)]


def test_xfer_stress_across_processes():
    """Device-plane soak (round-2 VERDICT item 7): ~100 concurrent
    MB-scale device-to-device pulls over one connection from a thread
    pool; producer asserts zero leaked parks, consumer asserts every
    byte arrived intact.  Runs everywhere: xfer_backend=auto rides the
    PJRT transfer API when the build has it, else the loopback backend
    (parsec_tpu/xfer/loopback.py) carries the identical code path."""
    outs = _run_ranks(2, 0, mode="xfer_stress", timeout=420)
    prod = next(o for o in outs if o["rank"] == 0)
    cons = next(o for o in outs if o["rank"] == 1)
    assert cons["errors"] == []
    assert cons["pulls"] == prod["serves"] == 96
    assert cons["bytes"] == cons["expected_bytes"]
    assert prod["leaked_parks"] == 0


def test_wave_dpotrf_across_processes():
    """Distributed WAVE dpotrf across 2 real OS processes with the
    HOST-BYTE fallback forced (wave_dist_plane=off): the static tile
    exchange schedule rides the sockets end to end (wave throughput +
    distribution in one engine — round-2 VERDICT item 3; the default
    device-plane hop is covered by the _device_plane variant)."""
    outs = _run_ranks(2, 0, mode="wave", timeout=300)
    assert all(o["max_err"] < 5e-3 for o in outs), outs
    assert all(o["msgs"] > 0 for o in outs)
    assert sum(o["bytes"] for o in outs) > 4 * 64 * 64 * 4  # tiles crossed


def test_wave_dpotrf_device_plane_across_processes():
    """Distributed wave with the device-plane payload hop — the
    DEFAULT on cross-process transports (the runner auto-attaches;
    nothing opts in): tile exchanges move device-to-device through the
    transfer plane, TCP carries only descriptors and park acks; zero
    leaked parks, same numerics.  xfer_backend=auto falls back to the
    loopback transfer backend on builds without the PJRT API."""
    outs = _run_ranks(2, 0, mode="wave_xfer", timeout=300)
    assert all(o["max_err"] < 5e-3 for o in outs), outs
    tile_bytes = 64 * 64 * 8
    pulls = sum(o["xfer"]["pulls"] for o in outs)
    assert pulls > 0, outs
    assert all(o["xfer"]["leaked_parks"] == 0 for o in outs), outs
    # the control plane must NOT be carrying the tiles: wire bytes stay
    # far below the exchanged tile volume
    assert sum(o["bytes"] for o in outs) < pulls * tile_bytes / 2, outs


def test_wave_bcast_tree_device_resident_forwards():
    """Binomial-tree broadcast over 4 ranks with the device plane (the
    cross-process default): interior tree nodes re-forward from the
    DEVICE arrays the plane pulled — zero host np.stack on the forward
    path (round-4 VERDICT Weak #5; stats counters prove the route).
    xfer_backend=auto falls back to the loopback transfer backend on
    builds without the PJRT API."""
    outs = _run_ranks(4, 0, mode="wave_bcast_xfer", timeout=300)
    assert all(o["max_err"] < 1e-6 for o in outs), outs
    st = [o["stats"] for o in outs]
    assert all(s["device_plane"] for s in st), st
    assert sum(s["tiles_forwarded"] for s in st) >= 1, st
    assert sum(s["fwd_device_stacks"] for s in st) >= 1, st
    assert sum(s["fwd_host_stacks"] for s in st) == 0, st


def test_wave_peer_death_aborts_quickly():
    """A rank dying mid-distributed-wave must abort the survivors via
    the failure detector in seconds — not hang for the 120 s exchange
    timeout (the reference's MPI would hang forever, SURVEY.md §5.3)."""
    outs = _run_ranks(2, 0, mode="wave_fail", timeout=180,
                      expect_rcs=[0, 3])
    ok = outs[0]
    assert ok["detected"], ok
    assert ok["secs"] < 60, f"took {ok['secs']}s — detector not used"


def test_dposv_across_processes():
    """Distributed Cholesky solve across 4 real OS processes: three
    sequential taskpools, panel broadcasts, cross-rank writebacks and
    the early-activation buffering, all over sockets."""
    outs = _run_ranks(4, 0, mode="dposv", timeout=300)
    assert all(o["max_err"] < 5e-3 for o in outs), outs
    assert all(o["msgs"] > 0 for o in outs)


def test_rank_failure_detected_not_hung():
    """Rank 1 hard-exits (os._exit) mid-chain: rank 0's wait() must raise
    RankFailedError-caused RuntimeError well before the timeout instead
    of hanging in termination detection (failure detection — the explicit
    extension over the reference, SURVEY.md §5.3)."""
    rep, _crashed = _run_ranks(2, 8, mode="fail", timeout=120,
                               expect_rcs=[0, 3])
    assert rep["detected"] is True
    assert rep["failed_rank"] == 1


def test_clean_shutdown_is_not_a_failure_but_sends_raise():
    """An orderly peer fini (GOODBYE frame) is not flagged as a rank
    failure, but later sends to it still fail loudly."""
    import time as _time
    from parsec_tpu.comm.tcp import RankFailedError
    e0, e1 = _engines(2)
    try:
        e1.fini()
        deadline = _time.time() + 10
        while 1 not in e0.finished_peers and _time.time() < deadline:
            _time.sleep(0.01)
        assert 1 in e0.finished_peers
        assert 1 not in e0.dead_peers
        with pytest.raises(RankFailedError):
            e0.send_am(1, 100, {"x": 1})
    finally:
        e0.fini()


def test_abrupt_death_marks_peer_dead():
    """A connection torn without the GOODBYE frame marks the peer dead."""
    import time as _time
    from parsec_tpu.comm.tcp import RankFailedError
    e0, e1 = _engines(2)
    try:
        # simulate a crash: tear e1's connections without the goodbye
        # (shutdown, not close: an in-process close() cannot interrupt a
        # cross-thread blocked recv; a real process death closes the fd
        # at OS level and delivers FIN/RST — the subprocess test covers
        # that path)
        import socket as _socket
        for sock in e1._conns.values():
            sock.shutdown(_socket.SHUT_RDWR)
        deadline = _time.time() + 10
        while 0 not in e1.dead_peers and 1 not in e0.dead_peers \
                and _time.time() < deadline:
            _time.sleep(0.01)
        assert 1 in e0.dead_peers or 0 in e1.dead_peers
        dead_side = e0 if 1 in e0.dead_peers else e1
        with pytest.raises(RankFailedError):
            dead_side.send_am(1 - dead_side.rank, 100, {"x": 1})
    finally:
        e1._closing = True
        e0.fini()


def test_pending_get_reports_failure_without_strict():
    """A peer that goes away owing rendezvous data is a definite failure:
    the on_peer_failure callback fires even with strict mode off."""
    import time as _time
    e0, e1 = _engines(2)
    failures = []
    e0.on_peer_failure = lambda peer, reason: failures.append(peer)
    try:
        # issue a GET whose reply will never come (e1 never progresses),
        # then shut e1 down — even a "clean" exit owing data is a failure
        h = e1.mem_register(np.ones((4,), np.float32))
        e0.get(1, h.handle_id, lambda data: None)
        _time.sleep(0.05)
        e1.fini()
        deadline = _time.time() + 10
        while not failures and _time.time() < deadline:
            _time.sleep(0.01)
        assert failures == [1]
    finally:
        e0.fini()


def test_dposv_device_plane_across_processes():
    """Distributed Cholesky solve where bulk tile payloads move
    DEVICE-to-device through the jax transfer server (comm/xfer.py);
    TCP carries only control traffic. Every rank must have pulled real
    device bytes (ref role: parsec_mpi_funnelled.c:245-365's data plane,
    re-landed on the PJRT transfer fabric; xfer_backend=auto rides the
    loopback backend on builds without the PJRT API)."""
    outs = _run_ranks(2, 0, mode="dposv_xfer", timeout=300)
    assert all(o["max_err"] < 5e-3 for o in outs), outs
    total_pulled = sum(o["xfer"]["bytes_pulled"] for o in outs)
    total_served = sum(o["xfer"]["serves"] for o in outs)
    assert total_pulled > 0 and total_served > 0, outs
    # tiles are 32x32 f32 = 4 KiB; device-PRODUCED payloads crossing
    # ranks ride the plane (memory-sourced initial tiles stay classic)
    assert total_pulled >= 4 * 4096, outs
