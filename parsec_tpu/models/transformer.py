"""Flagship model: decoder-only transformer, 5-axis-parallel from scratch.

The forward/backward runs inside ONE shard_map over the (dp, pp, tp, sp, ep)
mesh with manual collectives:
- dp: batch sharded; gradient psum at the end
- pp: layers stacked per stage, GPipe microbatch schedule (parallel/pipeline)
- tp: Megatron-style — attention heads + FFN hidden sharded, psum after the
  output projections
- sp: sequence sharded; ring attention (or Ulysses all-to-all) per layer
- ep: MoE experts sharded (parallel/moe), psum combine

Everything is functional pytrees + jnp — XLA sees one traced program per
shard and fuses normalization/elementwise into the matmuls (MXU-friendly,
bf16-ready via cfg.dtype).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..parallel.moe import load_balance_loss, moe_ffn
from ..parallel.pipeline import gpipe, last_stage_value
from ..parallel.ring_attention import ring_attention
from ..parallel.sequence import ulysses_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 256
    d_model: int = 64
    n_heads: int = 4          # divisible by tp (and by sp for ulysses)
    d_head: int = 16
    n_stages: int = 1         # == pp axis size
    layers_per_stage: int = 1
    d_ff: int = 128           # divisible by tp
    n_experts: int = 0        # 0 = dense FFN; else divisible by ep
    moe_top_k: int = 2
    seq_len: int = 32         # divisible by sp
    batch: int = 8            # divisible by dp; batch/dp divisible by n_micro
    n_micro: int = 1          # pipeline microbatches per shard
    attention: str = "ring"   # "ring" | "ulysses" | "flash" | "local"
    remat: bool = False       # jax.checkpoint each layer: trade FLOPs
    # for activation memory (SURVEY.md HBM guidance)
    dtype: Any = jnp.float32
    aux_loss_weight: float = 0.01

    @property
    def n_layers(self) -> int:
        return self.n_stages * self.layers_per_stage


def init_params(cfg: TransformerConfig, seed: int = 0) -> Dict[str, Any]:
    """Global (unsharded) parameter pytree; shard with param_specs()."""
    rng = np.random.RandomState(seed)
    dt = cfg.dtype

    def w(*shape, scale=None):
        scale = scale or (1.0 / np.sqrt(shape[-2] if len(shape) >= 2 else shape[-1]))
        return jnp.asarray(rng.normal(0, scale, size=shape), dtype=dt)

    S, L = cfg.n_stages, cfg.layers_per_stage
    D, H, Dh, F = cfg.d_model, cfg.n_heads, cfg.d_head, cfg.d_ff
    params: Dict[str, Any] = {
        "embed": w(cfg.vocab, D, scale=0.02),
        "pos": w(cfg.seq_len, D, scale=0.02),
        "ln_f": jnp.ones((D,), dt),
        "stages": {
            "ln1": jnp.ones((S, L, D), dt),
            "ln2": jnp.ones((S, L, D), dt),
            "wqkv": w(S, L, D, 3, H, Dh),
            "wo": w(S, L, H, Dh, D),
        },
    }
    if cfg.n_experts:
        params["stages"]["gate"] = w(S, L, D, cfg.n_experts, scale=0.02)
        params["stages"]["w1e"] = w(S, L, cfg.n_experts, D, F)
        params["stages"]["w2e"] = w(S, L, cfg.n_experts, F, D)
    else:
        params["stages"]["w1"] = w(S, L, D, F)
        params["stages"]["w2"] = w(S, L, F, D)
    return params


def param_specs(cfg: TransformerConfig) -> Dict[str, Any]:
    """PartitionSpec per leaf: stages stack over pp; heads/ffn over tp;
    experts over ep; everything else replicated."""
    from jax.sharding import PartitionSpec as P
    specs: Dict[str, Any] = {
        "embed": P(),
        "pos": P(),
        "ln_f": P(),
        "stages": {
            "ln1": P("pp"),
            "ln2": P("pp"),
            "wqkv": P("pp", None, None, None, "tp", None),
            "wo": P("pp", None, "tp", None, None),
        },
    }
    if cfg.n_experts:
        specs["stages"]["gate"] = P("pp")
        specs["stages"]["w1e"] = P("pp", None, "ep", None, "tp")
        specs["stages"]["w2e"] = P("pp", None, "ep", "tp", None)
    else:
        specs["stages"]["w1"] = P("pp", None, None, "tp")
        specs["stages"]["w2"] = P("pp", None, "tp", None)
    return specs


def _rmsnorm(x: Any, g: Any) -> Any:
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * g


def _attention(cfg: TransformerConfig, q, k, v) -> Any:
    if cfg.attention == "ring":
        return ring_attention(q, k, v, "sp", causal=True)
    if cfg.attention == "ulysses":
        return ulysses_attention(q, k, v, "sp", causal=True)
    if cfg.attention == "flash":
        # Pallas kernel: O(T) memory — no materialized [T, T] scores
        # (single-shard sequence; combine with sp via ring for multi-chip)
        from ..ops.pallas_kernels import flash_attention
        return flash_attention(q, k, v, causal=True)
    from ..parallel.ring_attention import local_attention
    return local_attention(q, k, v, causal=True)


def _layer(cfg: TransformerConfig, lp: Dict[str, Any], x: Any,
           aux: Any) -> Tuple[Any, Any]:
    """One transformer block on a local shard. x: [mb, T_local, D]."""
    h = _rmsnorm(x, lp["ln1"])
    qkv = jnp.einsum("btd,dchn->bcthn", h, lp["wqkv"],
                     preferred_element_type=jnp.float32).astype(x.dtype)
    # [mb, 3, T, H_local, Dh] -> three [mb, H_local, T, Dh]
    q = qkv[:, 0].transpose(0, 2, 1, 3)
    k = qkv[:, 1].transpose(0, 2, 1, 3)
    v = qkv[:, 2].transpose(0, 2, 1, 3)
    a = _attention(cfg, q, k, v)          # [mb, H_local, T_local, Dh]
    o = jnp.einsum("bhtd,hdD->btD", a, lp["wo"],
                   preferred_element_type=jnp.float32).astype(x.dtype)
    o = lax.psum(o, "tp")                  # heads are tp-sharded
    x = x + o
    h2 = _rmsnorm(x, lp["ln2"])
    if "w1e" in lp:
        gate_logits = jnp.einsum("btd,de->bte", h2, lp["gate"])
        f = moe_ffn(h2, lp["gate"], lp["w1e"], lp["w2e"], "ep",
                    top_k=cfg.moe_top_k, gate_logits=gate_logits)
        f = lax.psum(f, "tp")              # expert FFN hidden is tp-sharded
        aux = aux + load_balance_loss(gate_logits)
    else:
        u = jnp.einsum("btd,df->btf", h2, lp["w1"],
                       preferred_element_type=jnp.float32)
        u = jax.nn.gelu(u).astype(x.dtype)
        f = jnp.einsum("btf,fD->btD", u, lp["w2"],
                       preferred_element_type=jnp.float32).astype(x.dtype)
        f = lax.psum(f, "tp")              # ffn hidden is tp-sharded
    return x + f, aux


def forward_shard(cfg: TransformerConfig, params: Dict[str, Any],
                  tokens: Any) -> Tuple[Any, Any]:
    """Per-shard forward (inside shard_map over all 5 axes).

    tokens: [B_local, T_local] int32. Returns (logits [B_local, T_local, V]
    valid on the LAST pp stage, aux scalar).
    """
    sp_idx = lax.axis_index("sp")
    Tl = tokens.shape[1]
    pos = sp_idx * Tl + jnp.arange(Tl)
    x = params["embed"][tokens] + params["pos"][pos][None, :, :]
    x = x.astype(cfg.dtype)

    # microbatch: [M, mb, T, D]
    M = cfg.n_micro
    B_local = x.shape[0]
    assert B_local % M == 0, f"local batch {B_local} not divisible by {M} microbatches"
    x_micro = x.reshape(M, B_local // M, Tl, -1)

    # stage params: the pp-sharded leading axis leaves [S_local, L, ...]
    # per shard; flatten to this shard's local layer stack [S_local*L, ...]
    stage = jax.tree.map(lambda a: a.reshape(-1, *a.shape[2:]),
                         params["stages"])

    with_aux = bool(cfg.n_experts)

    def stage_fn(sparams, xm):
        layer_fn = _layer
        if cfg.remat:
            layer_fn = jax.checkpoint(_layer, static_argnums=(0,))

        def body(carry, lp):
            y, aux = carry
            y, aux = layer_fn(cfg, lp, y, aux)
            return (y, aux), None
        from ..parallel.mesh import vary_on
        aux0 = vary_on(jnp.zeros((), jnp.float32), ("pp",), like=xm)
        (y, aux), _ = lax.scan(body, (xm, aux0), sparams)
        return (y, aux) if with_aux else y

    if with_aux:
        # aux_local: this pp stage's load-balance sum over its layers and
        # every real (stage, microbatch) tick; all stages contribute, so
        # the per-layer mean needs a psum over pp (loss_shard does it)
        y_micro, aux_local = gpipe(stage_fn, stage, x_micro, "pp",
                                   with_aux=True)
    else:
        y_micro = gpipe(stage_fn, stage, x_micro, "pp")
        aux_local = jnp.zeros((), jnp.float32)
    y = y_micro.reshape(B_local, Tl, -1)
    y = _rmsnorm(y, params["ln_f"])
    logits = jnp.einsum("btd,vd->btv", y.astype(jnp.float32),
                        params["embed"].astype(jnp.float32))
    return logits, aux_local


def loss_shard(cfg: TransformerConfig, params: Dict[str, Any],
               tokens: Any, labels: Any) -> Any:
    """Global mean cross-entropy (replicated scalar on every shard)."""
    logits, aux = forward_shard(cfg, params, tokens)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    local_sum = nll.sum()
    # only the last pp stage holds real logits
    local_sum = last_stage_value(local_sum, "pp")
    total = lax.psum(local_sum, ("dp", "sp"))
    n_tokens = labels.size * lax.psum(1, "dp") * lax.psum(1, "sp")
    loss = total / n_tokens
    if cfg.n_experts:
        # per-layer / per-microbatch mean of the Switch aux, averaged over
        # the token shards; every pp stage contributed its own layers
        n_layers = cfg.n_stages * cfg.layers_per_stage
        aux_mean = lax.psum(aux, "pp") / (n_layers * cfg.n_micro)
        aux_mean = lax.pmean(aux_mean, ("dp", "sp"))
        loss = loss + cfg.aux_loss_weight * aux_mean
    return loss
