"""Mini-apps as integration tests (ref: tests/apps/ — stencil, merge_sort,
haar_tree, generalized_reduction). Single-process apps here; the
communication apps (rtt/bandwidth/all2all, ref tests/apps/pingpong,
all2all) live in test_apps_comm.py.

Each app follows the reference's measurement pattern: the stencil prints
GFLOPS from its flop count (ref: testing_stencil_1D.c:141-199).
"""
import time

import numpy as np
import pytest

import parsec_tpu
from parsec_tpu import dtd
from parsec_tpu.collections import VectorTwoDimCyclic
from parsec_tpu.dsl import ptg
from parsec_tpu.dsl.dtd import INOUT, INPUT, OUTPUT, VALUE, unpack_args

# --------------------------------------------------------------------- #
# 1D stencil (ref: tests/apps/stencil/testing_stencil_1D.c)             #
# --------------------------------------------------------------------- #
STENCIL_JDF = """
descU [ type="collection" ]
NT [ type="int" ]
NI [ type="int" ]
W0 [ type="float" default="0.25" ]
W1 [ type="float" default="0.5" ]
W2 [ type="float" default="0.25" ]

ST(t, i)

t = 0 .. NT-1
i = 0 .. NI

: descU( t, 0 )

READ L <- ((i > 0) and (t > 0)) ? GR ST( t-1, i-1 )
READ R <- ((i > 0) and (t < NT-1)) ? GL ST( t+1, i-1 )
RW X <- (i == 0) ? descU( t, 0 ) : X ST( t, i-1 )
     -> (i == NI) ? descU( t, 0 )
     -> (i < NI) ? X ST( t, i+1 )
WRITE GL -> ((i < NI) and (t > 0)) ? R ST( t-1, i+1 )  [shape=1x1]
WRITE GR -> ((i < NI) and (t < NT-1)) ? L ST( t+1, i+1 )  [shape=1x1]

; NI - i

BODY
{
    # i == 0 only snapshots the boundary ghosts; i > 0 applies the
    # 3-point update using the neighbors' iteration i-1 ghosts
    if i > 0:
        x = X[:, 0]
        ghost_l = L[-1, 0] if L is not None else 0.0
        ghost_r = R[0, 0] if R is not None else 0.0
        xm = np.concatenate([[ghost_l], x[:-1]])
        xp = np.concatenate([x[1:], [ghost_r]])
        X = (W0 * xm + W1 * x + W2 * xp)[:, None]
    GL = X[:1, :]
    GR = X[-1:, :]
}
END
"""


def _stencil_reference(u0: np.ndarray, ni: int, w=(0.25, 0.5, 0.25)):
    u = u0.astype(np.float64)
    for _ in range(ni):
        um = np.concatenate([[0.0], u[:-1]])
        up = np.concatenate([u[1:], [0.0]])
        u = w[0] * um + w[1] * u + w[2] * up
    return u


@pytest.mark.parametrize("nt,mb,ni", [(4, 16, 3), (6, 32, 8), (1, 16, 4)])
def test_stencil_1d(ctx, nt, mb, ni):
    rng = np.random.RandomState(1)
    u0 = rng.rand(nt * mb).astype(np.float32)
    U = VectorTwoDimCyclic(nt * mb, mb)
    for t in range(nt):
        np.copyto(U.tile(t, 0), u0[t * mb:(t + 1) * mb][:, None])
    tp = ptg.compile_jdf(STENCIL_JDF, name="stencil").new(
        descU=U, NT=nt, NI=ni)
    t0 = time.perf_counter()
    ctx.add_taskpool(tp)
    ctx.wait()
    dt = time.perf_counter() - t0
    assert tp.completed
    got = np.concatenate([U.tile(t, 0)[:, 0] for t in range(nt)])
    np.testing.assert_allclose(got, _stencil_reference(u0, ni), atol=1e-5)
    flops = 5.0 * nt * mb * ni  # 3 mul + 2 add per point per iteration
    print(f"stencil_1D NT={nt} MB={mb} NI={ni}: "
          f"{flops / dt / 1e9:.6f} gflops")


# --------------------------------------------------------------------- #
# merge sort (ref: tests/apps/merge_sort)                               #
# --------------------------------------------------------------------- #
def test_merge_sort(ctx):
    """Tile-sort leaves then a DTD merge tree; dynamic task insertion
    discovers the tree edges from tile access modes."""
    n_leaves, leaf = 8, 64
    rng = np.random.RandomState(2)
    arrays = [rng.rand(leaf).astype(np.float32) for _ in range(n_leaves)]
    tp = dtd.taskpool_new()
    ctx.add_taskpool(tp)

    def sort_leaf(es, task):
        (x,) = unpack_args(task)
        x.sort(axis=0)

    def merge(es, task):
        out, a, b = unpack_args(task)
        m = np.concatenate([a, b], axis=0)
        m.sort(axis=0)
        out[:] = m

    level = [tp.tile_of_array(a[:, None]) for a in arrays]
    for t in level:
        tp.insert_task(sort_leaf, (t, INOUT))
    width = leaf
    while len(level) > 1:
        width *= 2
        nxt = []
        for i in range(0, len(level), 2):
            out = tp.tile_new((width, 1), dtype=np.float32)
            tp.insert_task(merge, (out, OUTPUT),
                           (level[i], INPUT), (level[i + 1], INPUT))
            nxt.append(out)
        level = nxt
    tp.data_flush_all()
    tp.wait()
    got = np.asarray(level[0].data.get_copy(0).payload)[:, 0]
    np.testing.assert_allclose(got, np.sort(np.concatenate(arrays)))


# --------------------------------------------------------------------- #
# generalized reduction (ref: tests/apps/generalized_reduction)         #
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("n_tiles", [1, 5, 8])
def test_generalized_reduction(ctx, n_tiles):
    """Binary-tree reduction with a user-supplied elementwise op, built by
    dynamic insertion (non-power-of-two tile counts exercise the odd
    carry path)."""
    rng = np.random.RandomState(3)
    tiles_np = [rng.rand(16, 1).astype(np.float32) for _ in range(n_tiles)]
    tp = dtd.taskpool_new()
    ctx.add_taskpool(tp)

    def reduce_pair(es, task):
        a, b = unpack_args(task)
        np.maximum(a, b, out=a)

    level = [tp.tile_of_array(t.copy()) for t in tiles_np]
    while len(level) > 1:
        nxt = []
        for i in range(0, len(level) - 1, 2):
            tp.insert_task(reduce_pair, (level[i], INOUT),
                           (level[i + 1], INPUT))
            nxt.append(level[i])
        if len(level) % 2:
            nxt.append(level[-1])
        level = nxt
    tp.data_flush_all()
    tp.wait()
    got = np.asarray(level[0].data.get_copy(0).payload)
    np.testing.assert_allclose(got, np.maximum.reduce(tiles_np))


# --------------------------------------------------------------------- #
# haar wavelet tree (ref: tests/apps/haar-tree, dynamic DAG)            #
# --------------------------------------------------------------------- #
def test_haar_tree(ctx):
    """Bottom-up Haar transform: each level computes (a+b)/sqrt2 averages
    (feeding the next level — a dynamically-discovered dependency chain)
    and (a-b)/sqrt2 details (leaves of the output)."""
    depth = 4
    n = 1 << depth
    rng = np.random.RandomState(4)
    x = rng.rand(n).astype(np.float64)

    tp = dtd.taskpool_new()
    ctx.add_taskpool(tp)
    s = 1.0 / np.sqrt(2.0)

    def haar_step(es, task):
        avg, det, a, b = unpack_args(task)
        avg[0, 0] = (a[0, 0] + b[0, 0]) * s
        det[0, 0] = (a[0, 0] - b[0, 0]) * s

    level = [tp.tile_of_array(np.array([[v]])) for v in x]
    details = []
    while len(level) > 1:
        nxt = []
        lvl_details = []
        for i in range(0, len(level), 2):
            avg = tp.tile_new((1, 1), dtype=np.float64)
            det = tp.tile_new((1, 1), dtype=np.float64)
            tp.insert_task(haar_step, (avg, OUTPUT), (det, OUTPUT),
                           (level[i], INPUT), (level[i + 1], INPUT))
            nxt.append(avg)
            lvl_details.append(det)
        details.append(lvl_details)
        level = nxt
    tp.data_flush_all()
    tp.wait()

    def val(tile):
        return float(np.asarray(tile.data.get_copy(0).payload)[0, 0])

    # reference Haar analysis
    ref = x.copy()
    ref_details = []
    while len(ref) > 1:
        a, b = ref[0::2], ref[1::2]
        ref_details.append((a - b) * s)
        ref = (a + b) * s
    np.testing.assert_allclose(val(level[0]), ref[0], atol=1e-12)
    for lvl, ref_lvl in zip(details, ref_details):
        np.testing.assert_allclose([val(t) for t in lvl], ref_lvl,
                                   atol=1e-12)
