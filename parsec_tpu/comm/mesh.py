"""MeshFabric: the device-mesh transport — data plane over ICI.

Reference behavior being replaced: the funnelled-MPI comm engine moves
tile payloads host-to-host with Isend/Irecv on negotiated tags
(parsec/parsec_mpi_funnelled.c:245-365). TPU-native re-design per
SURVEY.md §5.8: the *data plane* is device-to-device transfers between
the ranks' chips — ``jax.device_put`` onto the consumer's device, which
PJRT routes over ICI on a real slice — while the small, latency-bound
*control plane* (activations, GET requests) travels host-side (the
in-process queues here; gRPC/DCN in a multi-host deployment). Tile
payloads therefore never round-trip through host memory on the data
path.

Each rank of the SPMD run is pinned to one ``jax.Device`` of a mesh.
Registered memory handles may hold device arrays; a GET is served by
transferring the producer's device buffer directly onto the requester's
device. On CI this runs over the 8-virtual-device CPU mesh; the
transfer calls are identical on TPU hardware.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional

import numpy as np

from .engine import TAG_PUT_DATA
from .local import LocalCommEngine, LocalFabric


def _devices(n: Optional[int] = None) -> List[Any]:
    import jax
    devs = jax.devices()
    if n is not None:
        if len(devs) < n:
            raise RuntimeError(
                f"mesh fabric needs {n} devices, jax has {len(devs)}")
        devs = devs[:n]
    return devs


class MeshFabric(LocalFabric):
    """One rank per mesh device; control messages in-process, payloads
    moved device-to-device."""

    def __init__(self, nb_ranks: Optional[int] = None,
                 devices: Optional[List[Any]] = None) -> None:
        devices = list(devices) if devices is not None else _devices(nb_ranks)
        super().__init__(len(devices))
        self.devices = devices
        self.d2d_transfers = 0
        self.d2d_bytes = 0

    def engine(self, rank: int) -> "MeshCommEngine":
        eng = MeshCommEngine(self, rank)
        self.engines[rank] = eng
        return eng

    def _count_d2d(self, nbytes: int) -> None:
        with self._stat_lock:
            self.d2d_transfers += 1
            self.d2d_bytes += nbytes


class MeshCommEngine(LocalCommEngine):
    """GET/PUT data rides the mesh interconnect; AMs stay host-side."""

    fabric: MeshFabric

    @property
    def device(self) -> Any:
        return self.fabric.devices[self.rank]

    def _to_device_of(self, rank: int, array: Any) -> Any:
        """Move a payload onto ``rank``'s device (ICI D2D on hardware;
        numpy sources are an H2D staging upload)."""
        import jax
        out = jax.device_put(array, self.fabric.devices[rank])
        self.fabric._count_d2d(getattr(out, "nbytes", 0))
        return out

    # -- GET: serve by pushing the buffer onto the requester's device ----
    def _serve_get(self, requester: int, h: Any) -> Any:
        return self._to_device_of(requester, h.array)

    # -- PUT: transfer first, land in the registered region on arrival --
    def put(self, dst_rank: int, remote_handle_id: int, array: Any,
            on_complete: Optional[Callable] = None) -> None:
        data = self._to_device_of(dst_rank, array)
        self.send_am(dst_rank, TAG_PUT_DATA,
                     {"handle": remote_handle_id, "data": data})
        if on_complete is not None:
            on_complete(array)

    def _on_put_data(self, src: int, payload: Any) -> None:
        h = self._mem.get(payload["handle"])
        assert h is not None, f"PUT for unknown mem handle {payload['handle']}"
        if isinstance(h.array, np.ndarray):
            np.copyto(h.array, np.asarray(payload["data"]))
        else:
            # device-resident region: rebind to the arrived buffer (jax
            # arrays are immutable; the handle is the indirection layer)
            h.array = payload["data"]
