#!/usr/bin/env python
"""Benchmark driver: PTG tile Cholesky (dpotrf_L) GFLOP/s on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference repo publishes no numbers (BASELINE.md); the
north-star target is >=60% of an A100-node's per-device dpotrf rate. We
take 15.5 TFLOP/s as the A100-class dpotrf rate (DPLASMA-style dpotrf
sustains ~80% of the A100's 19.5 TFLOP/s FP64-TC peak), making the target
0.6 * 15500 = 9300 GFLOP/s; vs_baseline = measured / 9300.

Knobs (env): BENCH_N (matrix size, default 8192), BENCH_NB (tile size,
default 2048), BENCH_DTYPE (float32), BENCH_REPS (default 3, best-of),
BENCH_CORES (worker threads, default 1: with eager completion one
thread drives async dispatch without GIL/lock contention — measured
32.7 TF/s at 1 core vs 25.9 at 2/4 on the single-CPU-core sandbox).
NB=2048 is the measured single-chip sweet spot (v5e): large enough that
per-task XLA kernels (~0.3-3ms) amortize the ~0.3ms Python task-dispatch
overhead, small enough for panel parallelism (NT=4). NB=1024 gave
6.4 TF/s; NB=2048 sustains ~33 TF/s steady-state (the first rep pays a
one-time device-pool warm cost even after kernel warmup, which
best-of-REPS filters; REPS>=2 required for a steady-state number).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

BASELINE_GFLOPS = 9300.0


def main() -> None:
    import parsec_tpu
    from parsec_tpu.collections import TwoDimBlockCyclic
    from parsec_tpu.ops import dpotrf_taskpool, make_spd

    n = int(os.environ.get("BENCH_N", "8192"))
    nb = int(os.environ.get("BENCH_NB", "2048"))
    reps = int(os.environ.get("BENCH_REPS", "3"))
    cores = int(os.environ.get("BENCH_CORES", "1"))
    dtype = np.dtype(os.environ.get("BENCH_DTYPE", "float32"))

    ctx = parsec_tpu.init(nb_cores=cores)
    try:
        # warmup: small factorization compiles every kernel shape used
        # below — 3x3 tiles so POTRF/TRSM/SYRK *and* GEMM all compile
        # (a 2x2 grid has no GEMM task and would leak its ~30s XLA
        # compile into the first timed rep)
        wm = make_spd(3 * nb, dtype=dtype)
        Aw = TwoDimBlockCyclic(3 * nb, 3 * nb, nb, nb, dtype=dtype).from_numpy(wm)
        tp = dpotrf_taskpool(Aw)
        ctx.add_taskpool(tp)
        ctx.wait()

        # O(N^2) SPD construction (symmetric + strictly diagonally
        # dominant); make_spd's Gram-matrix form is O(N^3) on the host
        # and would dominate wall time at large N
        rng0 = np.random.RandomState(0)
        B = rng0.rand(n, n) - 0.5
        M = ((B + B.T) / 2 + n * np.eye(n)).astype(dtype)
        tpu_devs = [d for d in ctx.devices if d.device_type == "tpu"]
        best = None
        for _ in range(reps):
            A = TwoDimBlockCyclic(n, n, nb, nb, dtype=dtype).from_numpy(M)
            # prestage tiles into HBM (steady-state model: data lives on
            # device; the timed region measures the factorization DAG)
            if tpu_devs:
                import jax
                for (tm, tn) in A.tiles():
                    tpu_devs[0].data_advise(A.data_of(tm, tn), "prefetch")
                jax.block_until_ready([
                    A.data_of(tm, tn).get_copy(tpu_devs[0].device_index).payload
                    for (tm, tn) in A.tiles()])
            t0 = time.perf_counter()
            tp = dpotrf_taskpool(A)
            ctx.add_taskpool(tp)
            ctx.wait()
            # the DAG is done when every output tile's device result exists;
            # block on the newest copies so async dispatch is fully timed
            import jax
            pend = []
            for (tm, tn) in A.tiles():
                c = A.data_of(tm, tn).newest_copy()
                if c is not None and c.payload is not None:
                    pend.append(c.payload)
            jax.block_until_ready(pend)
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        # correctness gate (the watchdog pattern of dtd_test_simple_gemm);
        # O(N^2) residual check ||L(L^T x) - M x|| / ||M x|| on random
        # vectors so verification does not dwarf the timed region at
        # large N (full L L^T reconstruction is O(N^3) on the host)
        L = np.tril(A.to_numpy()).astype(np.float64)
        rng = np.random.RandomState(0)
        X = rng.rand(n, 4)
        ref = M.astype(np.float64) @ X
        err = float(np.abs(L @ (L.T @ X) - ref).max() / np.abs(ref).max())
        if err > 5e-2:
            print(json.dumps({"metric": "dpotrf_gflops", "value": 0.0,
                              "unit": "GFLOP/s", "vs_baseline": 0.0,
                              "error": f"numerics failed: {err}"}))
            return
        flops = n ** 3 / 3.0 + n ** 2 / 2.0
        gflops = flops / best / 1e9
        print(json.dumps({
            "metric": f"dpotrf_gflops(N={n},NB={nb},{dtype.name},1chip)",
            "value": round(gflops, 2),
            "unit": "GFLOP/s",
            "vs_baseline": round(gflops / BASELINE_GFLOPS, 4),
        }))
    finally:
        ctx.fini()


if __name__ == "__main__":
    main()
