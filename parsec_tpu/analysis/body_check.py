"""Batch/donation-safety lint over PTG BODY code and DTD task functions.

The device layer (devices/tpu.py + devices/batching.py) silently
downgrades per class at trace time: a ``this_task`` read makes a class
permanently unbatchable, an untraceable construct fails the first
batched flush and falls the class back to per-task dispatch, aliased
same-tile arguments suppress buffer donation per dispatch.  This pass
predicts those downgrades statically from the stdlib ``ast`` of the
body source, so a spec author learns the cost before the first run.

Finding codes (BDY2xx):

- ``BDY200`` body-syntax: the body is not valid Python.
- ``BDY201`` this-task: a device body reads ``this_task`` — the class
  NEVER batches (``batch_spec`` is withheld; every instance pays the
  per-task dyld dispatch).
- ``BDY202`` untraceable: a device body uses a construct jax cannot
  trace over device arrays (``np.*`` calls, ``print``/``open``/
  ``input``, ``.item()``/``.tolist()``, or an ``if``/``while``
  statement whose test reads a flow payload) — the first batched flush
  fails to trace and PERMANENTLY downgrades the class to per-task
  dispatch (``spec.batchable = False``).
- ``BDY203`` nondeterminism: a device body reads wall-clock time or an
  unseeded random stream — stacked executions lose the bit-exact
  batched-vs-per-task guarantee of ``device_batch_mode=unroll``.
- ``BDY204`` aliased-args (warn): two flows of one class read the same
  memory tile — at dispatch the same buffer sits at two argument
  slots, so buffer donation (``device_donate``) is suppressed for
  every such dispatch.
- ``BDY205`` missing-write (warn): a device body never assigns one of
  its written (RW/WRITE) flow names — the staged-out "result" is the
  unmodified input.

Only accelerator bodies (``BODY [type=tpu]`` and friends) are checked:
CPU bodies run on the host interpreter where all of this is legal.
"""
from __future__ import annotations

import ast as pyast
import inspect
import textwrap
from typing import Callable, List, Optional, Sequence, Set, Tuple

from ..dsl.ptg.ast import JDFFile, RangeExpr, TaskClassAST
from . import Finding

#: attribute roots whose *call* in a traced body breaks tracing
_UNTRACEABLE_ROOTS = {"np", "numpy"}
#: builtins whose call in a traced body breaks tracing (side effects /
#: host-concretization)
_UNTRACEABLE_CALLS = {"print", "open", "input"}
#: method calls that force device->host concretization
_UNTRACEABLE_METHODS = {"item", "tolist"}
#: attribute roots that make a body nondeterministic across dispatches
_NONDET_ROOTS = {"random", "time", "datetime", "uuid"}


def _attr_chain(node: pyast.AST) -> List[str]:
    """``np.random.rand`` -> ["np", "random", "rand"]; [] if not a
    simple name/attribute chain."""
    parts: List[str] = []
    while isinstance(node, pyast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, pyast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return []


def _names_under(node: pyast.AST) -> Set[str]:
    return {n.id for n in pyast.walk(node) if isinstance(n, pyast.Name)}


def _check_traced_source(tree: pyast.AST, where: str, label: str,
                         flow_names: Sequence[str],
                         findings: List[Finding]) -> None:
    """The trace-safety predicates shared by PTG device bodies and DTD
    device-chore functions."""
    flow_set = set(flow_names)
    for node in pyast.walk(tree):
        if isinstance(node, pyast.Call):
            chain = _attr_chain(node.func)
            if not chain:
                continue
            root = chain[0]
            if len(chain) == 1 and root in _UNTRACEABLE_CALLS:
                findings.append(Finding(
                    "BDY202",
                    f"{label}: call to {root}() is untraceable — the "
                    f"first batched flush fails and the class "
                    f"permanently falls back to per-task dispatch",
                    where, severity="warn"))
            elif root in _UNTRACEABLE_ROOTS:
                if len(chain) > 1 and chain[1] == "random":
                    findings.append(Finding(
                        "BDY203",
                        f"{label}: {'.'.join(chain)}(...) draws from a "
                        f"process-global random stream — batched "
                        f"executions lose bit-exact reproducibility "
                        f"(use a jax PRNG key threaded as a flow)",
                        where, severity="warn"))
                else:
                    findings.append(Finding(
                        "BDY202",
                        f"{label}: {'.'.join(chain)}(...) is a numpy "
                        f"call — it cannot trace over device arrays, "
                        f"so the first batched flush fails and the "
                        f"class permanently falls back to per-task "
                        f"dispatch (use jnp.*)",
                        where, severity="warn"))
            elif root in _NONDET_ROOTS:
                findings.append(Finding(
                    "BDY203",
                    f"{label}: {'.'.join(chain)}(...) is "
                    f"nondeterministic — stacked dispatches lose the "
                    f"bit-exact batched-vs-per-task guarantee",
                    where, severity="warn"))
            elif chain[-1] in _UNTRACEABLE_METHODS:
                findings.append(Finding(
                    "BDY202",
                    f"{label}: .{chain[-1]}() concretizes a device "
                    f"array on the host — untraceable; the class "
                    f"permanently falls back to per-task dispatch",
                    where, severity="warn"))
        elif isinstance(node, (pyast.If, pyast.While)):
            tested = _names_under(node.test)
            hot = tested & flow_set
            if hot:
                findings.append(Finding(
                    "BDY202",
                    f"{label}: {'if' if isinstance(node, pyast.If) else 'while'} "
                    f"on flow payload {sorted(hot)} concretizes a "
                    f"traced value — the first batched flush raises "
                    f"TracerBoolConversionError and the class "
                    f"permanently falls back to per-task dispatch "
                    f"(use jnp.where / lax.cond)",
                    where, severity="warn"))


def _assigned_names(tree: pyast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in pyast.walk(tree):
        targets: List[pyast.AST] = []
        if isinstance(node, pyast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (pyast.AugAssign, pyast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            for el in pyast.walk(t):
                if isinstance(el, pyast.Name):
                    out.add(el.id)
                elif isinstance(el, pyast.Subscript) and \
                        isinstance(el.value, pyast.Name):
                    out.add(el.value.id)  # A[...] = / A[...] +=
    return out


def _aliased_tiles(tc: TaskClassAST) -> List[Tuple[str, str, str]]:
    """Pairs of non-CTL flows whose in-deps read a textually identical
    memory tile: (flow_a, flow_b, "coll(args)")."""
    def norm(t) -> Optional[str]:
        if t is None or t.kind != "memory":
            return None
        args = []
        for a in t.args:
            if isinstance(a, RangeExpr):
                return None  # broadcast range: not a single tile
            args.append(a.src.replace(" ", ""))
        return f"{t.collection}({','.join(args)})"

    tiles: List[Tuple[str, str]] = []
    for f in tc.flows:
        if f.is_ctl:
            continue
        for d in f.deps_in():
            for t in (d.target, d.alt_target):
                key = norm(t)
                if key is not None:
                    tiles.append((f.name, key))
    out: List[Tuple[str, str, str]] = []
    for i, (fa, ka) in enumerate(tiles):
        for fb, kb in tiles[i + 1:]:
            if ka == kb and fa != fb:
                out.append((fa, fb, ka))
    return out


def check_jdf_bodies(jdf: JDFFile, name: Optional[str] = None
                     ) -> List[Finding]:
    """Lint every accelerator BODY of a parsed JDF."""
    name = name or jdf.name
    findings: List[Finding] = []
    for tc in jdf.task_classes:
        flow_names = [f.name for f in tc.flows if not f.is_ctl]
        written = [f.name for f in tc.flows
                   if not f.is_ctl and f.access in ("RW", "WRITE")]
        for fa, fb, tile in _aliased_tiles(tc):
            findings.append(Finding(
                "BDY204",
                f"{tc.name}: flows {fa!r} and {fb!r} read the same tile "
                f"{tile} — the same device buffer sits at two argument "
                f"slots, so buffer donation (device_donate) is "
                f"suppressed for every dispatch of this class",
                f"{name} {tc.name}", severity="warn"))
        for b in tc.bodies:
            if b.device_type in ("cpu", "recursive"):
                continue  # host bodies: everything here is legal
            where = f"{name}:{b.line} {tc.name}.BODY" if b.line else \
                f"{name} {tc.name}.BODY"
            label = f"{tc.name} BODY[{b.device_type}]"
            try:
                tree = pyast.parse(b.code)
            except SyntaxError as exc:
                findings.append(Finding(
                    "BDY200", f"{label}: body is not valid Python: {exc}",
                    where))
                continue
            if "this_task" in _names_under(tree):
                findings.append(Finding(
                    "BDY201",
                    f"{label}: reads this_task (per-task runtime "
                    f"state) — the class NEVER batches: no batch_spec "
                    f"is built, every instance pays the per-task dyld "
                    f"dispatch", where, severity="warn"))
            _check_traced_source(tree, where, label, flow_names, findings)
            if written and not (_assigned_names(tree) & set(written)):
                findings.append(Finding(
                    "BDY205",
                    f"{label}: never assigns any written flow "
                    f"({', '.join(written)}) — the staged-out result "
                    f"is the unmodified input", where, severity="warn"))
    return findings


def check_function(fn: Callable | str, name: Optional[str] = None,
                   device: bool = True) -> List[Finding]:
    """Lint a DTD task function (or raw function source) with the same
    trace-safety predicates.  ``device=True`` assumes the function runs
    as a device chore (``add_chore``/jitted body) where trace safety
    matters; host-only task functions can pass ``device=False`` to get
    only the nondeterminism checks."""
    if callable(fn):
        label = name or getattr(fn, "__name__", "task_fn")
        try:
            src = textwrap.dedent(inspect.getsource(fn))
        except (OSError, TypeError):
            return [Finding("BDY200", f"{label}: source unavailable "
                            f"(lambda/builtin?)", label, severity="note")]
    else:
        src = textwrap.dedent(fn)
        label = name or "task_fn"
    try:
        tree = pyast.parse(src)
    except SyntaxError as exc:
        return [Finding("BDY200", f"{label}: not valid Python: {exc}",
                        label)]
    findings: List[Finding] = []
    # DTD payload args: the function's positional parameters stand in
    # for flow payloads
    params: List[str] = []
    for node in pyast.walk(tree):
        if isinstance(node, (pyast.FunctionDef, pyast.AsyncFunctionDef)):
            params = [a.arg for a in node.args.args]
            break
    if "this_task" in params or "this_task" in _names_under(tree):
        findings.append(Finding(
            "BDY201", f"{label}: reads this_task — the class never "
            f"batches (per-task dispatch only)", label, severity="warn"))
    if device:
        _check_traced_source(tree, label, label, params, findings)
    else:
        dev_findings: List[Finding] = []
        _check_traced_source(tree, label, label, params, dev_findings)
        findings.extend(f for f in dev_findings if f.code == "BDY203")
    return findings
