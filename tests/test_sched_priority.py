"""Scheduler priority semantics (ISSUE 7 satellite): ap/spq/pbq pop
order under mixed priorities, the keep_highest_priority_task bypass
slot, FIFO-within-priority under dynamic updates, and the online
ClassProfile's upward-rank/scarcity boosts."""
import numpy as np
import pytest

import parsec_tpu
from parsec_tpu.runtime.profile import ClassProfile, _PRIO_SCALE
from parsec_tpu.runtime.scheduling import schedule, schedule_keep_best
from parsec_tpu.runtime.taskpool import Task, TaskClass
from parsec_tpu.utils.params import params


class _FakePool:
    """Just enough taskpool for a Task living in scheduler queues."""
    taskpool_id = 0
    name = "fake"


def _mk_tasks(prios, cls="T"):
    tc = TaskClass(cls, 0, 0)
    tp = _FakePool()
    return [Task(tp, tc, (i,), priority=p) for i, p in enumerate(prios)]


def _ctx(sched, cores=1, **kw):
    return parsec_tpu.init(nb_cores=cores, scheduler=sched,
                           enable_tpu=False, **kw)


# --------------------------------------------------------------------- #
# pop order under mixed priorities                                      #
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("sched", ["ap", "spq"])
def test_priority_pop_order_desc_fifo_within(sched):
    ctx = _ctx(sched)
    try:
        es = ctx.execution_streams[0]
        tasks = _mk_tasks([1, 5, 3, 5, 0])
        ctx.scheduler.schedule(es, list(tasks))
        got = [ctx.scheduler.select(es) for _ in range(5)]
        # priority desc; FIFO between the two priority-5 tasks
        assert got == [tasks[1], tasks[3], tasks[2], tasks[0], tasks[4]]
        assert ctx.scheduler.select(es) is None
    finally:
        ctx.fini()


def test_ip_pops_worst_first():
    ctx = _ctx("ip")
    try:
        es = ctx.execution_streams[0]
        tasks = _mk_tasks([1, 5, 3])
        ctx.scheduler.schedule(es, list(tasks))
        got = [ctx.scheduler.select(es) for _ in range(3)]
        assert got == [tasks[0], tasks[2], tasks[1]]
    finally:
        ctx.fini()


def test_pbq_local_buffer_pops_best():
    """pbq keeps a priority-aware local buffer: a local push set pops
    highest-priority first on the pushing stream."""
    ctx = _ctx("pbq", cores=2)
    try:
        es = ctx.execution_streams[0]
        tasks = _mk_tasks([2, 9, 4])
        ctx.scheduler.schedule(es, list(tasks), distance=0)
        got = [ctx.scheduler.select(es) for _ in range(3)]
        assert got == [tasks[1], tasks[2], tasks[0]]
    finally:
        ctx.fini()


# --------------------------------------------------------------------- #
# the keep_highest_priority_task bypass slot (scheduling.py)            #
# --------------------------------------------------------------------- #
def test_keep_highest_priority_bypass_slot():
    ctx = _ctx("ap")
    try:
        es = ctx.execution_streams[0]
        assert ctx.keep_highest_priority_task
        tasks = _mk_tasks([3, 8, 5])
        schedule_keep_best(es, list(tasks))
        # the best freshly-enabled task stays on the releasing thread
        assert es.next_task is tasks[1]
        # the rest went to the scheduler in priority order
        assert ctx.scheduler.select(es) is tasks[2]
        assert ctx.scheduler.select(es) is tasks[0]
        # an occupied slot is never displaced
        es.next_task = tasks[1]
        more = _mk_tasks([99])
        schedule_keep_best(es, list(more))
        assert es.next_task is tasks[1]
        assert ctx.scheduler.select(es) is more[0]
        es.next_task = None
    finally:
        ctx.fini()


# --------------------------------------------------------------------- #
# dynamic priorities: stamping + FIFO within equal priority             #
# --------------------------------------------------------------------- #
def test_dynamic_boost_jumps_queue_static_breaks_ties():
    """A critical-path class (profile boost) beats a higher STATIC
    priority of a non-critical class; within one class the static
    expression still decides."""
    ctx = _ctx("ap")
    try:
        es = ctx.execution_streams[0]
        prof = ctx.class_profile
        assert prof is not None   # sched_dynamic_priority default on
        prof.add_edges("CRIT", ["LEAF"])
        prof.add_edges("LEAF", [])
        tc_crit = TaskClass("CRIT", 0, 0)
        tc_leaf = TaskClass("LEAF", 1, 0)
        tp = _FakePool()
        leaf_hi = Task(tp, tc_leaf, (0,), priority=1000)
        crit_lo = Task(tp, tc_crit, (1,), priority=1)
        crit_hi = Task(tp, tc_crit, (2,), priority=7)
        schedule(es, [leaf_hi, crit_lo, crit_hi])
        got = [ctx.scheduler.select(es) for _ in range(3)]
        assert got == [crit_hi, crit_lo, leaf_hi]
        # the stamp is boost * SCALE + static, recomputed from base
        assert crit_hi.priority == prof.boost_of("CRIT") * _PRIO_SCALE + 7
        assert crit_hi.base_priority == 7
    finally:
        ctx.fini()


def test_dynamic_updates_keep_fifo_within_priority():
    """Profile updates between pushes must not reorder equal-priority
    tasks: FIFO within a priority is a scheduler invariant."""
    ctx = _ctx("ap")
    try:
        es = ctx.execution_streams[0]
        prof = ctx.class_profile
        prof.add_edges("A", ["B"])
        prof.add_edges("B", [])
        tc = TaskClass("A", 0, 0)
        tp = _FakePool()
        first = Task(tp, tc, (0,), priority=5)
        schedule(es, [first])
        # an EWMA update between pushes (same class set: boosts stable)
        prof.note("A", 100.0)
        prof.note("A", 250.0)
        second = Task(tp, tc, (1,), priority=5)
        schedule(es, [second])
        assert first.priority == second.priority
        assert ctx.scheduler.select(es) is first
        assert ctx.scheduler.select(es) is second
    finally:
        ctx.fini()


def test_dynamic_priority_off_keeps_static():
    with params.cmdline_override("sched_dynamic_priority", "0"):
        ctx = _ctx("ap")
    try:
        assert ctx.class_profile is None
        es = ctx.execution_streams[0]
        tasks = _mk_tasks([4, 2])
        schedule(es, list(tasks))
        assert tasks[0].priority == 4   # untouched
        assert ctx.scheduler.select(es) is tasks[0]
    finally:
        ctx.fini()


# --------------------------------------------------------------------- #
# ClassProfile: upward rank + scarcity                                  #
# --------------------------------------------------------------------- #
def test_class_profile_chain_ranks_descend():
    prof = ClassProfile()
    prof.add_edges("A", ["B"])
    prof.add_edges("B", ["C"])
    prof.add_edges("C", [])
    assert prof.boost_of("A") > prof.boost_of("B") > prof.boost_of("C")
    # unknown classes are never boosted and keep their static priority
    assert prof.boost_of("ZZZ") == 0
    assert prof.effective("ZZZ", 42) == 42


def test_class_profile_cycle_scarcity_orders_dpotrf_classes():
    """The dpotrf class graph is one SCC; within it the duration-
    weighted scarcity must rank POTRF (rare) above GEMM (abundant)."""
    prof = ClassProfile()
    prof.add_edges("POTRF", ["TRSM"])
    prof.add_edges("TRSM", ["SYRK", "GEMM"])
    prof.add_edges("SYRK", ["POTRF", "SYRK"])
    prof.add_edges("GEMM", ["TRSM", "GEMM"])
    # steady-state-ish samples: first per class is discarded (compile)
    for _ in range(3):
        prof.note("POTRF", 100.0, 4)
        prof.note("TRSM", 100.0, 16)
        prof.note("SYRK", 100.0, 16)
        prof.note("GEMM", 100.0, 64)
    assert prof.boost_of("POTRF") > prof.boost_of("GEMM")
    assert prof.boost_of("TRSM") > prof.boost_of("GEMM")
    snap = prof.snapshot()
    assert snap["GEMM"]["count"] == 3 * 64


def test_class_profile_effective_packing():
    prof = ClassProfile()
    prof.add_edges("A", ["B"])
    prof.add_edges("B", [])
    # boost dominates any clamped static; static breaks ties in-class
    assert prof.effective("A", -10) > prof.effective("B", 10**9)
    assert prof.effective("A", 3) > prof.effective("A", 2)


def test_dpotrf_run_populates_profile():
    """End-to-end: a classic-runtime dpotrf feeds the profile and the
    result stays correct with dynamic priorities on (the default)."""
    from parsec_tpu.collections import TwoDimBlockCyclic
    from parsec_tpu.ops import dpotrf_taskpool, make_spd

    with params.cmdline_override("device_tpu_max", "1"):
        ctx = parsec_tpu.Context(nb_cores=2)
        try:
            M = make_spd(192)
            A = TwoDimBlockCyclic(192, 192, 32, 32,
                                  dtype=np.float32).from_numpy(M)
            ctx.add_taskpool(dpotrf_taskpool(A))
            ctx.wait()
            L = np.tril(A.to_numpy()).astype(np.float64)
            resid = np.abs(L @ L.T - M).max() / np.abs(M).max()
            assert resid < 1e-5
            snap = ctx.class_profile.snapshot()
            assert set(snap) == {"POTRF", "TRSM", "SYRK", "GEMM"}
            assert all(c["count"] > 0 for c in snap.values())
        finally:
            ctx.fini()
