"""XLA/TPU device module: asynchronous offload engine over jax.

Reference behavior reproduced (from the CUDA module, SURVEY.md §2.5, §3.4):
- the accelerator chore hands the task to a per-device mini-scheduler and
  returns HOOK_RETURN_ASYNC; the first thread to submit becomes the device
  *manager* (atomic mutex CAS, ref: device_cuda_module.c:2574-2577), others
  just enqueue to ``pending``;
- stage-in reserves device space, pulls the newest copy, and respects the
  coherency protocol (parsec_gpu_data_reserve_device_space / push,
  ref: device_cuda_module.c:864-1040, 2099-2195);
- two LRU lists (clean / dirty-owned) drive eviction with writeback
  (ref: device_gpu.h:128-129);
- per-stream in-flight tracking with events → here jax async dispatch with
  readiness polling (progress_stream, ref: device_cuda_module.c:1961-2012);
- the epilog hands ownership back OWNED→SHARED and bumps versions
  (ref: device_cuda_module.c:2365-2430).

TPU-native re-design: "streams" are jax's async dispatch queues — device_put
and jitted execution return immediately; completion is observed with
``jax.Array.is_ready``-style polling (committed arrays). Kernel bodies are
jax-jit callables (XLA) or Pallas kernels; the runtime caches the jitted
callable per task class. HBM capacity is tracked by payload accounting; an
eviction drops our reference (clean) or writes back to host first (owned).
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from ..core.lists import Dequeue
from ..data.data import Coherency, Data, DataCopy, FlowAccess
from ..runtime.taskpool import HookReturn, Task
from ..utils import logging as plog
from ..utils.params import params
from .device import Device

_log = plog.device_stream


def _array_ready(arr: Any) -> bool:
    """True when the backing buffer is materialized (event-query analog)."""
    try:
        return arr.is_ready()
    except AttributeError:
        return True  # host/numpy arrays are always ready


class _InFlight:
    __slots__ = ("task", "outputs", "out_flows", "es_hint", "est")

    def __init__(self, task: Task, outputs: List[Any], out_flows: List[int], est: float) -> None:
        self.task = task
        self.outputs = outputs
        self.out_flows = out_flows
        self.est = est


class JaxDevice(Device):
    """One jax.Device managed as a PaRSEC accelerator device."""

    def __init__(self, device_index: int, jax_device: Any) -> None:
        plat = getattr(jax_device, "platform", "tpu")
        super().__init__("tpu", device_index, name=f"{plat}:{jax_device.id}")
        self.jax_device = jax_device
        self.time_estimate_default = 1.0
        # device manager state (ref: gpu_device->mutex + pending)
        self.pending = Dequeue()
        self._manager_lock = threading.Lock()
        self._inflight: List[_InFlight] = []
        # memory accounting + LRU (ref: zone_malloc + gpu_mem_lru/_owned_lru)
        self.mem_budget = self._probe_budget()
        self.mem_used = 0
        self.mem_highwater = 0  # HBM accounting high-water mark (gauge)
        self._lru_clean: "OrderedDict[int, DataCopy]" = OrderedDict()
        self._lru_owned: "OrderedDict[int, DataCopy]" = OrderedDict()
        self._mem_lock = threading.Lock()
        self.stats = {"stage_in_bytes": 0, "stage_out_bytes": 0,
                      "evictions": 0, "tasks": 0}
        # eager completion (async dispatch IS completion; XLA orders the
        # dataflow) with a bounded in-flight window
        self.eager_complete = bool(params.get("tpu_eager_complete"))
        self.eager_window = int(params.get("tpu_eager_window"))
        self._window: List[_InFlight] = []
        self._eager_done: List[_InFlight] = []

    def _probe_budget(self) -> int:
        try:
            stats = self.jax_device.memory_stats()
            limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
            if limit:
                return int(limit * params.get("tpu_memory_fraction_pct") / 100)
        except Exception:
            pass
        return 8 << 30  # fall back to 8 GiB of accounting space

    # ------------------------------------------------------------------ #
    # submission: the accelerator chore calls this and returns ASYNC     #
    # ------------------------------------------------------------------ #
    def kernel_scheduler(self, es, task: Task) -> HookReturn:
        """ref: parsec_cuda_kernel_scheduler (device_cuda_module.c:2537)."""
        task.selected_device = self
        est = (task.task_class.time_estimate(task, self)
               if task.task_class.time_estimate else self.time_estimate_default)
        self.load_add(est)
        task.es_hint = es.th_id
        self.pending.push_back((task, est))
        # try to become the manager right away (first thread wins)
        self.progress(es)
        return HookReturn.ASYNC

    # ------------------------------------------------------------------ #
    # the manager loop, run opportunistically from idle workers          #
    # ------------------------------------------------------------------ #
    def progress(self, es) -> int:
        if not self._manager_lock.acquire(blocking=False):
            return 0  # someone else is the manager (CAS-owner pattern)
        try:
            n = 0
            # push phase: submit everything pending
            while True:
                item = self.pending.pop_front()
                if item is None:
                    break
                task, est = item
                try:
                    self._submit(es, task, est)
                except Exception as exc:  # surfacing beats hanging the DAG
                    plog.warning("tpu submit failed for %s: %s", task.snprintf(), exc)
                    raise
            # poll phase: complete ready in-flight tasks
            if self._eager_done:
                done, self._eager_done = self._eager_done, []
                for rec in done:
                    self._epilog(es, rec)
                    n += 1
            if self._window:
                # retire finished window entries so device_load drains on
                # idle devices and async errors surface during the run
                still_w = []
                for rec in self._window:
                    if all(_array_ready(a) for a in rec.outputs):
                        self._retire(rec, es)
                    else:
                        still_w.append(rec)
                self._window = still_w
            still: List[_InFlight] = []
            done = []
            for rec in self._inflight:
                if all(_array_ready(a) for a in rec.outputs):
                    done.append(rec)
                else:
                    still.append(rec)
            self._inflight = still
            for rec in done:
                self._epilog(es, rec)
                n += 1
            return n
        finally:
            self._manager_lock.release()

    # ------------------------------------------------------------------ #
    # stage-in / execute                                                 #
    # ------------------------------------------------------------------ #
    def _stage_in(self, task: Task) -> List[Any]:
        """Resolve every input flow to an array on this device
        (ref: parsec_cuda_kernel_push, device_cuda_module.c:2099-2195)."""
        import jax
        arrays: List[Any] = []
        for flow in task.task_class.flows:
            access = task.access_of(flow)
            ref = task.data[flow.flow_index]
            if flow.ctl or ref.data_in is None:
                arrays.append(None)
                continue
            data = ref.data_in.data
            if data is None:
                # detached copy (e.g. NEW tile scratch): move payload directly
                arrays.append(jax.device_put(ref.data_in.payload, self.jax_device))
                continue
            copy = data.get_copy(self.device_index)
            if copy is None:
                copy = DataCopy(data, self.device_index, payload=None,
                                dtt=ref.data_in.dtt)
                data.attach_copy(copy)
            src = data.start_transfer_ownership(self.device_index, access)
            if src is not None:
                nbytes = getattr(src.payload, "nbytes", 0)
                # credit the stale payload being replaced before reserving
                self._account(-getattr(copy.payload, "nbytes", 0))
                self._reserve(nbytes)
                obs = self._obs
                t0 = time.monotonic_ns() if obs is not None else 0
                copy.payload = jax.device_put(src.payload, self.jax_device)
                if obs is not None:
                    obs.xfer("in", nbytes, t0)
                self.stats["stage_in_bytes"] += nbytes
            data.complete_transfer_ownership(self.device_index, access)
            self._lru_touch(copy, owned=bool(access & FlowAccess.WRITE))
            arrays.append(copy.payload)
        return arrays

    def _submit(self, es, task: Task, est: float) -> None:
        tc = task.task_class
        chore = tc.incarnations[task.selected_chore]
        fn = chore.dyld_fn
        assert fn is not None, f"tpu chore of {tc.name} has no executable"
        inputs = self._stage_in(task)
        # fn is the DSL's wrapper: (task, per-flow device arrays) -> outputs
        outputs = fn(task, inputs)
        if outputs is None:
            outputs = ()
        elif not isinstance(outputs, (tuple, list)):
            outputs = (outputs,)
        out_flows = [f.flow_index for f in tc.flows
                     if (task.access_of(f) & FlowAccess.WRITE) and not f.ctl
                     and task.data[f.flow_index].data_in is not None]
        assert len(outputs) == len(out_flows), (
            f"{tc.name} tpu body returned {len(outputs)} arrays for "
            f"{len(out_flows)} written flows")
        rec = _InFlight(task, list(outputs), out_flows, est)
        self.stats["tasks"] += 1
        if self.eager_complete:
            # TPU-native completion model: jax dispatch is async and XLA's
            # execution queue already orders consumers after producers, so
            # dependency release need not wait for the kernel — successors
            # chain their jit calls on the in-flight arrays. Host-side
            # reads still block on conversion (device->host sync point).
            # A bounded window keeps the queue from running unboundedly
            # ahead (ref: the CUDA module bounds in-flight per stream).
            self._window.append(rec)
            if len(self._window) > self.eager_window:
                # backpressure: block on the oldest submission
                self._retire(self._window.pop(0), es)
            self._eager_done.append(rec)
        else:
            self._inflight.append(rec)

    def drain(self, context=None) -> None:
        """Retire every remaining window entry (called at wait()-exit:
        the DAGs are complete, and the records would otherwise pin the
        final tasks' object graphs — taskpool, collections, copies —
        until some future taskpool's progress happens to run). Async
        kernel failures in these trailing entries are RECORDED on the
        context so the caller's raise_pending_error surfaces them
        instead of a silently-successful wait()."""
        if not self._manager_lock.acquire(blocking=True):
            return  # pragma: no cover - Lock.acquire(True) returns True
        try:
            for rec in self._window:
                self._retire(rec, context=context)
            self._window = []
        finally:
            self._manager_lock.release()

    def _retire(self, rec: _InFlight, es=None, context=None) -> None:
        """Release a window entry: drop its load contribution and surface
        any async kernel error — against the task that DISPATCHED it
        (es or context present: recorded as a task error; teardown:
        logged)."""
        self.load_sub(rec.est)
        try:
            for a in rec.outputs:
                if a is not None and hasattr(a, "block_until_ready"):
                    a.block_until_ready()
        except Exception as exc:
            ctx = context if context is not None else \
                (es.context if es is not None else None)
            if ctx is not None:
                ctx.record_task_error(exc, rec.task)
            else:
                plog.warning("async kernel of %s failed at drain: %s",
                             rec.task.snprintf(), exc)

    def _epilog(self, es, rec: _InFlight) -> None:
        """ref: parsec_cuda_kernel_epilog (device_cuda_module.c:2365-2430)."""
        from ..runtime.scheduling import complete_execution
        task = rec.task
        for arr, fidx in zip(rec.outputs, rec.out_flows):
            ref = task.data[fidx]
            data = ref.data_in.data if ref.data_in is not None else None
            if data is not None:
                copy = data.get_copy(self.device_index)
                old = getattr(copy.payload, "nbytes", 0)
                copy.payload = arr
                self._account(getattr(arr, "nbytes", 0) - old)
                data.version_bump(self.device_index)
                ref.data_out = copy
            else:
                ref.data_in.payload = arr
                ref.data_in.version += 1
        for flow in task.task_class.flows:
            if task.access_of(flow) == FlowAccess.READ and not flow.ctl:
                ref = task.data[flow.flow_index]
                if ref.data_in is not None and ref.data_in.data is not None:
                    ref.data_in.data.release_reader(self.device_index)
        if not self.eager_complete:
            self.load_sub(rec.est)  # eager mode releases at window exit
        self.executed_tasks += 1
        complete_execution(es, task)

    # ------------------------------------------------------------------ #
    # memory management: accounting arena + LRU eviction                 #
    # ------------------------------------------------------------------ #
    def _account(self, delta: int) -> None:
        with self._mem_lock:
            self.mem_used = max(0, self.mem_used + delta)
            if self.mem_used > self.mem_highwater:
                self.mem_highwater = self.mem_used

    def _reserve(self, nbytes: int) -> None:
        """ref: parsec_gpu_data_reserve_device_space w/ LRU eviction and
        cycling guard (device_cuda_module.c:864-1040)."""
        with self._mem_lock:
            self.mem_used += nbytes
            if self.mem_used > self.mem_highwater:
                self.mem_highwater = self.mem_used
            if self.mem_used <= self.mem_budget:
                return
            # evict clean copies first
            for key in list(self._lru_clean):
                if self.mem_used <= self.mem_budget:
                    break
                copy = self._lru_clean.pop(key)
                if not self._evict(copy, writeback=False):
                    self._lru_clean[key] = copy  # in use: keep tracked
            # then dirty (owned) copies with writeback
            for key in list(self._lru_owned):
                if self.mem_used <= self.mem_budget:
                    break
                copy = self._lru_owned.pop(key)
                if not self._evict(copy, writeback=True):
                    self._lru_owned[key] = copy

    def _evict(self, copy: DataCopy, writeback: bool) -> bool:
        """Returns True when the copy was evicted (False: keep it listed)."""
        if copy.payload is None or copy.data is None:
            return True
        if copy.readers > 0:
            return False  # in use; cycling guard keeps it resident
        import numpy as np
        data = copy.data
        if writeback and copy.coherency == Coherency.OWNED:
            host = data.get_copy(0)
            if host is not None:
                # np.array (not asarray): jax arrays view as READ-ONLY numpy
                obs = self._obs
                t0 = time.monotonic_ns() if obs is not None else 0
                host.payload = np.array(copy.payload)
                if obs is not None:
                    obs.xfer("out", getattr(host.payload, "nbytes", 0), t0)
                host.version = copy.version
                host.coherency = Coherency.OWNED
                data.owner_device = 0
                self.stats["stage_out_bytes"] += getattr(host.payload, "nbytes", 0)
        self.mem_used = max(0, self.mem_used - getattr(copy.payload, "nbytes", 0))
        copy.payload = None
        copy.coherency = Coherency.INVALID
        self.stats["evictions"] += 1
        return True

    def _lru_touch(self, copy: DataCopy, owned: bool) -> None:
        key = id(copy)
        with self._mem_lock:
            self._lru_clean.pop(key, None)
            self._lru_owned.pop(key, None)
            (self._lru_owned if owned else self._lru_clean)[key] = copy

    # ------------------------------------------------------------------ #
    # explicit transfers (used by DSLs for flush / pushout)              #
    # ------------------------------------------------------------------ #
    def pull_to_host(self, data: Data) -> Any:
        """D2H writeback of this device's copy if it owns the newest version
        (ref: parsec_cuda_kernel_pop D2H for pushout flows)."""
        import numpy as np
        copy = data.get_copy(self.device_index)
        if copy is None or copy.payload is None:
            return None
        host = data.get_copy(0)
        # np.array (not asarray): numpy views of jax arrays are READ-ONLY,
        # and host bodies mutate the pulled payload in place
        obs = self._obs
        t0 = time.monotonic_ns() if obs is not None else 0
        arr = np.array(copy.payload)
        if obs is not None:
            obs.xfer("out", arr.nbytes, t0)
        if host is None:
            host = DataCopy(data, 0, payload=arr)
            data.attach_copy(host)
        else:
            host.payload = arr
        host.version = copy.version
        host.coherency = Coherency.SHARED
        copy.coherency = Coherency.SHARED
        self.stats["stage_out_bytes"] += arr.nbytes
        return arr

    def data_advise(self, data: Data, advice: str) -> None:
        if advice == "prefetch":
            import jax
            copy = data.get_copy(self.device_index)
            src = data.newest_copy(exclude_device=self.device_index)
            if src is None:
                return
            if copy is None:
                copy = DataCopy(data, self.device_index, payload=None, dtt=src.dtt)
                data.attach_copy(copy)
            if copy.payload is None:
                self._reserve(getattr(src.payload, "nbytes", 0))
                copy.payload = jax.device_put(src.payload, self.jax_device)
                copy.version = src.version
                copy.coherency = Coherency.SHARED
                self._lru_touch(copy, owned=False)
        elif advice == "preferred_device":
            data.preferred_device = self.device_index

    def fini(self) -> None:
        assert not self._inflight, "device finalized with in-flight tasks"
        for rec in self._window:
            self._retire(rec)  # teardown: must finalize every device
        self._window.clear()


def tpu_chore_hook(device_selector=None):
    """The TPU chore hook: pick an attached tpu device, hand off
    (ref: the generated CUDA hook, jdf2c.c:6557-6904). One dispatch path
    for all accelerator types — see devices/template.template_chore_hook."""
    from .template import template_chore_hook
    return template_chore_hook("tpu", device_selector=device_selector)
