"""Device MCA framework: registry + construction.

ref: parsec_mca_device_init/attach (parsec/parsec.c:832-837), component
selection via MCA param ``device_tpu_enabled`` (analog of
``device_cuda_enabled`` used throughout the reference test suite).
"""
from __future__ import annotations

from typing import List

from ..utils import logging as plog
from ..utils.params import params
from .cpu import CPUDevice
from .device import Device, get_best_device

params.reg_bool("device_tpu_enabled", True, "attach XLA devices as accelerators")
params.reg_int("device_tpu_max", -1, "max number of XLA devices to attach (-1 all)")
params.reg_string("device_tpu_platform", "",
                  "XLA platform to attach (tpu|cpu|...); empty = jax default")


def build_devices(context, enable_tpu: bool = True) -> List[Device]:
    devices: List[Device] = [CPUDevice(0)]
    if enable_tpu and params.get("device_tpu_enabled"):
        try:
            import jax
            plat = params.get("device_tpu_platform")
            jdevs = jax.devices(plat) if plat else jax.local_devices()
        except Exception as exc:  # no jax backend available
            from ..utils.show_help import show_help
            show_help("help-runtime.txt", "tpu-device-unavailable",
                      want_error=True, error=exc)
            jdevs = []
        cap = params.get("device_tpu_max")
        if cap >= 0:
            jdevs = jdevs[:cap]
        mesh_dev = _maybe_mesh_device(context, jdevs)
        if mesh_dev is not None:
            devices.append(mesh_dev)
            plog.device_stream.verbose(
                3, "attached mesh device %s over %d chip(s)",
                mesh_dev.name, len(mesh_dev.chips))
            return devices
        from .tpu import JaxDevice
        for i, jd in enumerate(jdevs):
            devices.append(JaxDevice(1 + i, jd))
        if jdevs:
            plog.device_stream.verbose(3, "attached %d XLA device(s): %s",
                                       len(jdevs), [d.name for d in devices[1:]])
    return devices


def _maybe_mesh_device(context, jdevs):
    """Build the rank's chip-mesh device when ``device_mesh_shape``
    asks for one (ISSUE 6): this rank takes a contiguous slice of the
    local chips offset by rank*chips (in-process SPMD ranks carve
    disjoint sub-meshes of the virtual device pool; a multi-process
    deployment owns its local chips outright). Falls back — with a
    warning, never an error — to one device per chip when the jax
    build lacks shard_map or too few chips exist."""
    shape = params.get("device_mesh_shape")
    if not shape or not jdevs:
        return None
    from .tpu import JaxMeshDevice, parse_mesh_shape
    gp, gq = parse_mesh_shape(shape)
    need = gp * gq
    if need <= 1:
        return None
    from ..parallel.mesh import has_shard_map
    if not has_shard_map():
        plog.warning("device_mesh_shape=%s ignored: this jax build has "
                     "no shard_map; attaching one device per chip",
                     shape)
        return None
    if len(jdevs) < need:
        plog.warning("device_mesh_shape=%s needs %d chips, have %d; "
                     "attaching one device per chip", shape, need,
                     len(jdevs))
        return None
    rank = int(getattr(context, "rank", 0) or 0)
    off = (rank * need) % len(jdevs)
    chips = (list(jdevs) * 2)[off:off + need]   # wraps, stays distinct
    return JaxMeshDevice(1, chips, (gp, gq))


from .template import TemplateDevice, template_chore_hook  # noqa: E402

__all__ = ["Device", "CPUDevice", "build_devices", "get_best_device",
           "TemplateDevice", "template_chore_hook", "JaxMeshDevice"]


def __getattr__(name):
    # lazy: importing the package must not import jax-heavy tpu.py
    if name == "JaxMeshDevice":
        from .tpu import JaxMeshDevice
        return JaxMeshDevice
    raise AttributeError(name)
