"""Event tracing: per-thread append-only streams + Chrome/Perfetto export.

Reference behavior: the binary "dbp" trace format — per-thread append-only
event buffers, a global dictionary of event classes (keyword -> key id,
color, packed info), begin/end event pairs, one file per rank
(ref: parsec/profiling.c, parsec/parsec_binary_profile.h:1-172,
parsec_profiling_add_dictionary_keyword / parsec_profiling_trace_flags
parsec/profiling.h:234-377). Offline conversion to pandas/HDF5 lives in
tools/profiling.

TPU-native re-design: events are appended to per-thread lists (no locking on
the hot path) with monotonic-ns timestamps; export is Chrome trace-event JSON
(loadable in Perfetto) plus a pandas DataFrame helper, replacing the dbp →
pbt2ptt → HDF5 pipeline.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional


class Dictionary:
    """Event-class dictionary (keyword -> id, color) (ref: profiling.h:234)."""

    def __init__(self) -> None:
        self._by_name: Dict[str, int] = {}
        self._info: List[Dict[str, Any]] = []
        self._lock = threading.Lock()

    def add_keyword(self, name: str, color: str = "#888888") -> int:
        with self._lock:
            if name in self._by_name:
                return self._by_name[name]
            key = len(self._info)
            self._by_name[name] = key
            self._info.append({"name": name, "color": color})
            return key

    def name_of(self, key: int) -> str:
        return self._info[key]["name"]


class ThreadStream:
    """Per-thread append-only event buffer (ref: parsec_profiling_stream_t)."""

    def __init__(self, profile: "Profile", tid: int, name: str = "") -> None:
        self.profile = profile
        self.tid = tid
        self.name = name or f"thread-{tid}"
        self.events: List[tuple] = []  # (ts_ns, phase, key_or_name, info)

    # NOTE: the former ``tid``/``event_id`` parameters of begin()/trace()
    # were silently dropped from the emitted event tuple; a stream already
    # IS one tid, so they are gone from the signatures (callers updated).
    def trace(self, key: str, info: Any = None, phase: str = "i") -> None:
        self.events.append((time.monotonic_ns(), phase, key, info))

    def begin(self, key: str, info: Any = None) -> None:
        self.events.append((time.monotonic_ns(), "B", key, info))

    def end(self, key: str, info: Any = None) -> None:
        self.events.append((time.monotonic_ns(), "E", key, info))

    def span(self, key: str, t0_ns: int, t1_ns: int, info: Any = None) -> None:
        """Append a COMPLETE span ("X" phase) with explicit timestamps —
        for sites that only know a span is worth recording after it
        finished (comm/device hooks). A complete event carries its own
        duration, so concurrent same-name spans from several threads
        landing on one shared stream cannot mis-nest the way B/E pairs
        would (Chrome-trace requires B/E to nest per tid)."""
        info = dict(info) if isinstance(info, dict) else {}
        info["dur_ns"] = t1_ns - t0_ns
        self.events.append((t0_ns, "X", key, info))

    def counter(self, key: str, value: float) -> None:
        self.events.append((time.monotonic_ns(), "C", key, value))

    def flow(self, key: str, flow_id: int, phase: str, ts_ns: int,
             info: Any = None) -> None:
        """Append one half of a Chrome-trace FLOW pair (ISSUE 15):
        ``phase`` is ``"s"`` (start, the sender's enqueue) or ``"f"``
        (finish, the receiver's delivery).  The two halves share
        ``flow_id`` — Perfetto draws an arrow from the slice enclosing
        the start to the slice enclosing the finish, which for comm
        spans means an arrow crossing rank rows in a merged timeline."""
        assert phase in ("s", "f"), phase
        info = dict(info) if isinstance(info, dict) else {}
        info["flow_id"] = flow_id
        self.events.append((ts_ns, phase, key, info))


class Profile:
    """One trace per rank (ref: parsec_profiling_dbp_start, parsec.c:706-726)."""

    def __init__(self, rank: int = 0, info: Optional[Dict[str, str]] = None) -> None:
        self.rank = rank
        self.dictionary = Dictionary()
        self.info = dict(info or {})
        self._streams: Dict[int, ThreadStream] = {}
        self._lock = threading.Lock()
        self._t0 = time.monotonic_ns()

    def thread_stream(self, es: Any) -> ThreadStream:
        tid = getattr(es, "th_id", 0)
        st = self._streams.get(tid)
        if st is None:
            with self._lock:
                st = self._streams.setdefault(tid, ThreadStream(self, tid))
        return st

    def stream(self, tid: int, name: str = "") -> ThreadStream:
        with self._lock:
            st = self._streams.get(tid)
            if st is None:
                st = ThreadStream(self, tid, name)
                self._streams[tid] = st
            return st

    def add_information(self, key: str, value: str) -> None:
        self.info[key] = value

    # -- export -------------------------------------------------------------
    def to_chrome_trace(self) -> Dict[str, Any]:
        events: List[Dict[str, Any]] = [
            # process/thread metadata so Perfetto labels the rank row and
            # each stream (thread_name events follow per stream below)
            {"name": "process_name", "ph": "M", "pid": self.rank,
             "args": {"name": f"rank {self.rank}"}},
        ]
        for tid, st in sorted(self._streams.items()):
            events.append({"name": "thread_name", "ph": "M", "pid": self.rank,
                           "tid": tid, "args": {"name": st.name}})
            for ts, ph, key, info in st.events:
                ev: Dict[str, Any] = {
                    "name": key, "pid": self.rank, "tid": tid,
                    "ts": (ts - self._t0) / 1000.0,
                }
                if ph in ("B", "E"):
                    ev["ph"] = ph
                elif ph == "X":
                    ev["ph"] = "X"
                    ev["dur"] = (info or {}).get("dur_ns", 0) / 1000.0
                    args = {k: v for k, v in (info or {}).items()
                            if k != "dur_ns"}
                    if args:
                        ev["args"] = args
                elif ph == "C":
                    ev["ph"] = "C"
                    ev["args"] = {key: info}
                elif ph in ("s", "f"):
                    # flow pair halves (ISSUE 15): same id on the "s"
                    # (sender) and "f" (receiver) events = one arrow
                    # between the enclosing slices in Perfetto
                    ev["ph"] = ph
                    ev["cat"] = "flow"
                    ev["id"] = (info or {}).get("flow_id", 0)
                    if ph == "f":
                        ev["bp"] = "e"   # bind to the ENCLOSING slice
                    args = {k: v for k, v in (info or {}).items()
                            if k != "flow_id"}
                    if args:
                        ev["args"] = args
                else:
                    ev["ph"] = "i"
                    ev["s"] = "t"
                if info is not None and ph in ("B", "i"):
                    # instant annotations (obs_live detector firings)
                    # carry their verdict in args like "B" slices do
                    ev["args"] = info if isinstance(info, dict) else {"info": info}
                events.append(ev)
        # rank + the monotonic origin of this profile's normalized
        # timestamps: what tools/obs_trace_merge.py needs to put N rank
        # traces back onto ONE clock (offset-corrected via the
        # "clock_offsets_us" metadata the context stamps at export)
        meta = dict(self.info)
        meta.setdefault("rank", self.rank)
        meta.setdefault("trace_t0_ns", self._t0)
        return {"traceEvents": events, "metadata": meta}

    def dump(self, path: str) -> str:
        """Write the Chrome trace JSON; returns the path written."""
        out = path if path.endswith(".json") else f"{path}.rank{self.rank}.trace.json"
        os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
        with open(out, "w") as fh:
            # default=repr: info payloads are arbitrary user objects
            # (ndarrays, task handles) — export must never crash on them
            json.dump(self.to_chrome_trace(), fh, default=repr)
        return out

    def dump_binary(self, path: str) -> str:
        """Write the binary .ptt trace (the reference's per-rank dbp file;
        read back with profiling.binfmt.read_profile or the tools/ CLIs)."""
        from .binfmt import write_profile
        out = path if path.endswith(".ptt") else f"{path}.rank{self.rank}.ptt"
        os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
        return write_profile(self, out)

    def to_dataframe(self):
        """Interval table like the reference's parsec_trace_tables.py."""
        import pandas as pd  # local import; pandas is optional at runtime
        rows = []
        for tid, st in self._streams.items():
            open_ev: Dict[str, List[int]] = {}
            for ts, ph, key, info in st.events:
                if ph == "B":
                    open_ev.setdefault(key, []).append(ts)
                elif ph == "E" and open_ev.get(key):
                    b = open_ev[key].pop()
                    rows.append({"tid": tid, "name": key,
                                 "begin_ns": b - self._t0,
                                 "end_ns": ts - self._t0,
                                 "duration_ns": ts - b})
                elif ph == "X":
                    dur = (info or {}).get("dur_ns", 0)
                    rows.append({"tid": tid, "name": key,
                                 "begin_ns": ts - self._t0,
                                 "end_ns": ts - self._t0 + dur,
                                 "duration_ns": dur})
        return pd.DataFrame(rows)

    def nb_events(self) -> int:
        return sum(len(s.events) for s in self._streams.values())
