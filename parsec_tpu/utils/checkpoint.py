"""Collection checkpoint/resume.

The reference has NO runtime-level checkpointing (SURVEY.md §5.4 —
"absent"; apps re-run from user data, with parsec_dtd_data_flush as the
only return-data-to-home building block). This module is the TPU-native
answer the survey calls for: since all application state lives in data
collections (tiles), a checkpoint is a consistent snapshot of a
collection's local tiles taken between taskpools (when no DAG is in
flight), and resume rebuilds the collection tile-by-tile. SPMD: each
rank writes only the tiles it owns; a restore on R ranks reads each
rank's own shard file set.

Format: one ``.npz`` per (collection, rank) holding tile arrays keyed
``t<m>_<n>`` plus a JSON-encoded manifest (geometry, dtype, distribution
parameters, format ``version``) used to validate compatibility at
restore time. Files are written atomically (temp file + ``os.replace``)
so a rank crashing mid-snapshot can never leave a torn ``.npz`` under
the published name — the previous complete snapshot survives intact.

Cross-grid restore (ISSUE 9): by default a snapshot only restores onto
the identical rank count / process grid (fail-fast,
:class:`CheckpointMismatchError`). With ``reshard=True`` a grid or rank
mismatch is instead resolved by :func:`parsec_tpu.ft.reshard_restore`
— surviving ranks load the shard files folded onto them and
``collections/redistribute`` lands every tile on the *current* grid.
"""
from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, List, Optional

import numpy as np

#: manifest format version; bumped when the on-disk layout changes.
#: v2 = atomic writes + version field (v1 manifests have no version
#: key and still load).
CHECKPOINT_VERSION = 2

#: manifest keys that describe the GEOMETRY of the data (must always
#: match — resharding cannot reinterpret bytes) vs the DISTRIBUTION
#: (relaxed under reshard=True: that is exactly what resharding fixes)
GEOMETRY_KEYS = ("lm", "ln", "mb", "nb", "dtype", "uplo")
DISTRIBUTION_KEYS = ("kind", "nodes", "rank", "P", "Q", "krows", "kcols",
                     "members")


class CheckpointMismatchError(ValueError):
    """The snapshot's manifest does not match the restoring collection
    (different geometry, rank count, or process grid). Raised BEFORE
    any tile is loaded: a rank file holds only the tiles the saving
    rank owned under ITS distribution, so restoring under a different
    grid would silently leave foreign tiles empty / place tiles on the
    wrong ranks."""


class CheckpointCorruptError(ValueError):
    """A snapshot file exists but cannot be read (torn/partial write —
    e.g. a rank crashed mid-``np.savez`` before atomic writes, or the
    storage truncated it). Distinct from a manifest mismatch so the
    restart driver can SKIP the corrupt snapshot and fall back to the
    previous complete one instead of dead-ending."""


def _manifest_of(coll: Any) -> Dict[str, Any]:
    man = {"version": CHECKPOINT_VERSION,
           "lm": coll.lm, "ln": coll.ln, "mb": coll.mb, "nb": coll.nb,
           "dtype": np.dtype(coll.dtype).name,
           "kind": type(coll).__name__,
           # distribution identity: the shard set is only meaningful on
           # the identical rank count / process grid it was written with
           "nodes": getattr(coll, "nodes", 1),
           "rank": getattr(coll, "rank", 0)}
    # "members" = the logical-rank -> world-rank map of an elastic
    # (remapped) grid: a resharding restore needs it to replay the
    # snapshot's tile ownership
    for attr in ("P", "Q", "krows", "kcols", "uplo", "members"):
        if hasattr(coll, attr):
            v = getattr(coll, attr)
            man[attr] = list(v) if attr == "members" else v
    return man


def _grid_str(man: Dict[str, Any]) -> str:
    grid = ""
    if "P" in man and "Q" in man:
        grid = f", grid {man['P']}x{man['Q']}"
    return f"{man.get('nodes', '?')} rank(s){grid}"


def checkpoint_path(prefix: str, rank: int) -> str:
    return f"{prefix}.rank{rank}.npz"


def _atomic_savez(path: str, arrays: Dict[str, Any]) -> None:
    """Write ``path`` atomically: a crash mid-write leaves only a stale
    ``.tmp`` (ignored by every reader), never a torn published file."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):  # pragma: no cover - only on write error
            os.unlink(tmp)


def _open_snapshot(path: str):
    """np.load with torn-file detection: any unreadable/half-written
    snapshot surfaces as CheckpointCorruptError (missing files stay
    FileNotFoundError — absent and torn are different failures)."""
    try:
        z = np.load(path, allow_pickle=False)
    except FileNotFoundError:
        raise
    except Exception as exc:  # noqa: BLE001 - zipfile/struct/OSError zoo
        raise CheckpointCorruptError(
            f"checkpoint {path} is torn or unreadable ({exc}); it was "
            f"likely half-written by a crashing rank — fall back to the "
            f"previous complete snapshot") from exc
    if "__manifest__" not in z.files:
        z.close()
        raise CheckpointCorruptError(
            f"checkpoint {path} has no manifest — torn or foreign file")
    return z


def read_manifest(path: str) -> Dict[str, Any]:
    with _open_snapshot(path) as z:
        try:
            return json.loads(str(z["__manifest__"]))
        except ValueError as exc:
            raise CheckpointCorruptError(
                f"checkpoint {path} manifest is not valid JSON") from exc


def find_manifest(prefix: str) -> Dict[str, Any]:
    """Manifest of any readable shard of ``prefix`` (a resharding
    restore cannot guess which writer ranks existed). Torn shards are
    skipped; all-torn or no shards raises."""
    paths = sorted(glob.glob(f"{glob.escape(prefix)}.rank*.npz"))
    if not paths:
        raise FileNotFoundError(f"no checkpoint shards at {prefix}.rank*")
    last: Optional[Exception] = None
    for p in paths:
        try:
            return read_manifest(p)
        except CheckpointCorruptError as exc:
            last = exc
    raise CheckpointCorruptError(
        f"every checkpoint shard at {prefix}.rank* is torn") from last


def save_collection(coll: Any, prefix: str, context: Optional[Any] = None) -> str:
    """Write this rank's local tiles. Call between taskpools (quiescent
    point); device-resident newest copies are pulled back first. The
    write is atomic: the file at the published path is always either
    the previous complete snapshot or this one, never a torn mix."""
    tiles: Dict[str, Any] = {}
    for (m, n) in coll.local_tiles():
        copy = coll.data_of(m, n).sync_to_host(
            context.devices if context is not None else None)
        if copy.payload is not None:
            tiles[f"t{m}_{n}"] = np.asarray(copy.payload)
    path = checkpoint_path(prefix, coll.rank)
    tiles["__manifest__"] = json.dumps(_manifest_of(coll))
    _atomic_savez(path, tiles)
    return path


def _mismatches(man: Dict[str, Any], ours: Dict[str, Any]) -> List[str]:
    """Keys on which the snapshot and the restoring collection diverge.
    "nodes"/"rank" are absent from pre-ft manifests, "version"/"members"
    from pre-elastic ones: optional keys are only compared when the
    snapshot recorded them ("version" never — it is a format marker,
    not an identity)."""
    keys = ["lm", "ln", "mb", "nb", "dtype", "kind", "P", "Q",
            "krows", "kcols", "uplo"]
    keys += [k for k in ("nodes", "rank") if k in man]
    bad = [k for k in keys if man.get(k) != ours.get(k)]
    if "members" in man or "members" in ours:
        # an elastic (remapped) grid on either side: the absent side is
        # the identity map over its own logical grid
        def _norm(m):
            if m.get("members") is not None:
                return list(m["members"])
            return list(range((m.get("P") or 1) * (m.get("Q") or 1)))
        if _norm(man) != _norm(ours):
            bad.append("members")
    return bad


def restore_collection(coll: Any, prefix: str, reshard: bool = False,
                       context: Optional[Any] = None) -> int:
    """Load this rank's tiles back into ``coll``; returns #tiles restored.

    Geometry must match the manifest (same tiling and dtype). By
    default the distribution must match too — fail-fast, today's
    contract. With ``reshard=True`` a snapshot written on a DIFFERENT
    rank count / process grid is redistributed onto ``coll``'s grid
    (``ft.reshard_restore``; ``context`` is required when the current
    grid spans more than one rank). Geometry mismatches (tile size,
    dtype, extent) hard-fail either way.
    """
    if reshard:
        # "rank" is writer-local (find_manifest returns SOME shard's
        # manifest) — it cannot distinguish grids, only shards
        man = find_manifest(prefix)
        if [k for k in _mismatches(man, _manifest_of(coll))
                if k != "rank"]:
            from ..ft.elastic import reshard_restore
            return reshard_restore(coll, prefix, context=context)
        # identical grid: fall through to the plain per-rank fast path
    path = checkpoint_path(prefix, coll.rank)
    with _open_snapshot(path) as z:
        man = json.loads(str(z["__manifest__"]))
        ours = _manifest_of(coll)
        # geometry AND distribution must match: a rank file holds only
        # the tiles the saving rank owned, so restoring under a
        # different kind/grid/rank-count would silently leave foreign
        # tiles empty or place tiles on the wrong ranks. Collect EVERY
        # mismatch (one clear error beats a fix-one-rerun loop).
        bad = _mismatches(man, ours)
        if bad:
            detail = [f"{k}: snapshot {man.get(k)!r} != ours {ours.get(k)!r}"
                      for k in bad]
            # when ONLY the distribution diverged the data is
            # recoverable — name the escape hatch instead of
            # dead-ending the operator on a grid change
            hint = ""
            if all(k in DISTRIBUTION_KEYS for k in bad):
                hint = (" The tile geometry matches: pass reshard=True "
                        "(ft.reshard_restore) to redistribute the "
                        "snapshot onto the current grid, or run under "
                        "--mca ft_elastic shrink for automatic "
                        "grid-resize recovery.")
            raise CheckpointMismatchError(
                f"checkpoint {path} is incompatible with the restoring "
                f"collection ({'; '.join(detail)}). The snapshot was "
                f"written on {_grid_str(man)}; this collection spans "
                f"{_grid_str(ours)} — restore requires the identical "
                f"tiling, dtype, rank count, and process grid.{hint}")
        n = 0
        for name in z.files:
            if not name.startswith("t"):
                continue
            m_, n_ = (int(x) for x in name[1:].split("_"))
            coll.set_tile(m_, n_, z[name])
            n += 1
    return n


def arrays_path(prefix: str, rank: int) -> str:
    """Namespaced separately from collection shards so the two can share
    one prefix without clobbering each other."""
    return f"{prefix}.arrays.rank{rank}.npz"


def save_arrays(prefix: str, rank: int = 0, **arrays: Any) -> str:
    """Checkpoint loose named arrays (e.g. model/optimizer state from
    parallel/ training) alongside collections. Atomic like
    :func:`save_collection`."""
    path = arrays_path(prefix, rank)
    _atomic_savez(path, {k: np.asarray(v) for k, v in arrays.items()})
    return path


def load_arrays(prefix: str, rank: int = 0) -> Dict[str, np.ndarray]:
    with np.load(arrays_path(prefix, rank), allow_pickle=False) as z:
        return {k: z[k] for k in z.files}
