"""Tile Cholesky (the north-star workload) correctness tests."""
import numpy as np
import pytest

import parsec_tpu
from parsec_tpu.collections import TwoDimBlockCyclic
from parsec_tpu.ops import dpotrf, dpotrf_taskpool, make_spd


@pytest.mark.parametrize("n,nb", [(64, 64), (128, 32), (192, 64), (100, 32)])
def test_dpotrf_numerics(ctx, n, nb):
    """L L^T must reconstruct A, including partial edge tiles (100/32)."""
    M = make_spd(n)
    A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
    tp = dpotrf_taskpool(A)
    ctx.add_taskpool(tp)
    ctx.wait()
    assert tp.completed
    nt = A.nt
    assert tp.nb_local_tasks == nt + 2 * (nt * (nt - 1) // 2) + \
        (nt * (nt - 1) * (nt - 2) // 6)
    L = np.tril(A.to_numpy())
    np.testing.assert_allclose(L @ L.T, M, atol=5e-4)


def test_dpotrf_matches_numpy(ctx):
    M = make_spd(96)
    A = TwoDimBlockCyclic(96, 96, 32, 32, dtype=np.float32).from_numpy(M)
    tp = dpotrf_taskpool(A)
    ctx.add_taskpool(tp)
    ctx.wait()
    L = np.tril(A.to_numpy())
    Lref = np.linalg.cholesky(M.astype(np.float64))
    np.testing.assert_allclose(L, Lref, atol=5e-4)


def test_dpotrf_batched_dispatch_bit_exact():
    """The stacked (unroll-mode) batched device path must be BIT-EXACT
    vs per-task dispatch: each task's subgraph lowers identically, one
    dispatch or many (ISSUE 5 acceptance)."""
    import parsec_tpu
    from parsec_tpu.utils.params import params

    M = make_spd(192)

    def run(batch_max):
        with params.cmdline_override("device_batch_max", str(batch_max)), \
             params.cmdline_override("device_tpu_max", "1"):
            c = parsec_tpu.init(nb_cores=2)
            try:
                A = TwoDimBlockCyclic(192, 192, 32, 32,
                                      dtype=np.float32).from_numpy(M.copy())
                tp = dpotrf_taskpool(A)
                c.add_taskpool(tp)
                c.wait()
                devs = [d for d in c.devices if d.device_type == "tpu"]
                batches = sum(d.stats["batches"] for d in devs)
                return np.tril(A.to_numpy()), batches
            finally:
                c.fini()

    L_single, b0 = run(1)
    L_batched, b1 = run(16)
    assert b0 == 0 and b1 > 0, (b0, b1)
    np.testing.assert_array_equal(L_batched, L_single)
    np.testing.assert_allclose(L_batched @ L_batched.T, M, atol=5e-4)


def test_dpotrf_mesh_sharded_residual_gate():
    """Mesh-sharded batched dispatch (device_mesh_shape; ISSUE 6): the
    north-star workload over a 2x2 chip mesh must hold the same
    residual gate as the single-chip path AND match it bit-exactly
    (unroll mode lowers the identical per-example subgraphs, one chip
    or four)."""
    import parsec_tpu
    from parsec_tpu.parallel.mesh import has_shard_map
    from parsec_tpu.utils.params import params

    if not has_shard_map():
        pytest.skip("no shard_map spelling in this jax build")
    M = make_spd(192)

    def run(shape):
        from contextlib import ExitStack
        with ExitStack() as stack:
            if shape:
                stack.enter_context(
                    params.cmdline_override("device_mesh_shape", shape))
            else:
                stack.enter_context(
                    params.cmdline_override("device_tpu_max", "1"))
            c = parsec_tpu.init(nb_cores=2)
            try:
                A = TwoDimBlockCyclic(192, 192, 32, 32,
                                      dtype=np.float32).from_numpy(M.copy())
                c.add_taskpool(dpotrf_taskpool(A))
                c.wait()
                dev = c.device_by_type("tpu")
                return np.tril(A.to_numpy()), dict(dev.stats)
            finally:
                c.fini()

    L_mesh, st = run("2x2")
    assert st["mesh_dispatches"] > 0, st
    resid = np.abs(L_mesh @ L_mesh.T - M).max() / np.abs(M).max()
    assert resid < 1e-5, f"mesh-sharded dpotrf residual {resid:.2e}"
    L_single, _ = run(None)
    np.testing.assert_array_equal(L_mesh, L_single)


def test_dpotrf_runs_on_device(ctx4):
    M = make_spd(128)
    A = TwoDimBlockCyclic(128, 128, 32, 32, dtype=np.float32).from_numpy(M)
    tp = dpotrf_taskpool(A)
    ctx4.add_taskpool(tp)
    ctx4.wait()
    devs = [d for d in ctx4.devices if d.device_type == "tpu"]
    assert sum(d.stats["tasks"] for d in devs) == tp.nb_local_tasks
    L = np.tril(A.to_numpy())
    np.testing.assert_allclose(L @ L.T, M, atol=5e-4)
