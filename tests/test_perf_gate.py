"""DTD tile-GEMM with a sustained-rate watchdog gate
(ref: tests/dsl/dtd/dtd_test_simple_gemm.c:651-670 — the test computes a
deadline from an expected GFLOP/s floor and alarm()s if execution
exceeds it; SURVEY.md §4 "Performance gating" calls this the pattern to
reuse for TPU CI).

The gate is opt-in: set PARSEC_TEST_MIN_GFLOPS to a floor (e.g. "5" on a
CPU runner, "5000" on a TPU chip) to turn the timing assertion on; by
default only correctness is checked, so the suite stays robust on
arbitrary shared CI machines. The measured rate prints either way, like
the reference's DTD_GEMM report line.
"""
import os
import time

import numpy as np

import parsec_tpu
from parsec_tpu import dtd
from parsec_tpu.dsl.dtd import INOUT, INPUT, unpack_args


def test_dtd_simple_gemm_rate(ctx4):
    mt = nt = kt = 3
    nb = 64
    rng = np.random.RandomState(0)
    A = [[rng.rand(nb, nb).astype(np.float32) for _ in range(kt)]
         for _ in range(mt)]
    B = [[rng.rand(nb, nb).astype(np.float32) for _ in range(nt)]
         for _ in range(kt)]
    C = [[np.zeros((nb, nb), np.float32) for _ in range(nt)]
         for _ in range(mt)]

    tp = dtd.taskpool_new()
    ctx4.add_taskpool(tp)
    ta = [[tp.tile_of_array(A[m][k]) for k in range(kt)] for m in range(mt)]
    tb = [[tp.tile_of_array(B[k][n]) for n in range(nt)] for k in range(kt)]
    tc = [[tp.tile_of_array(C[m][n]) for n in range(nt)] for m in range(mt)]

    def gemm_body(es, task):
        c, a, b = unpack_args(task)
        c += a @ b

    t0 = time.perf_counter()
    for m in range(mt):
        for n in range(nt):
            for k in range(kt):
                tp.insert_task(gemm_body, (tc[m][n], INOUT),
                               (ta[m][k], INPUT), (tb[k][n], INPUT))
    tp.data_flush_all()
    tp.wait()
    dt = time.perf_counter() - t0

    flops = 2.0 * mt * nt * kt * nb ** 3
    gflops = flops / dt / 1e9
    print(f"DTD_GEMM {mt}x{nt}x{kt} nb={nb}: {gflops:.2f} gflops "
          f"({dt * 1e3:.1f} ms)")

    # correctness always gates
    for m in range(mt):
        for n in range(nt):
            ref = sum(A[m][k].astype(np.float64) @ B[k][n]
                      for k in range(kt))
            got = np.asarray(tc[m][n].data.get_copy(0).payload)
            np.testing.assert_allclose(got, ref, atol=1e-3)

    # rate gates only when the runner declares its floor (the reference
    # takes min_perf on the command line the same way)
    floor = os.environ.get("PARSEC_TEST_MIN_GFLOPS")
    if floor:
        assert gflops >= float(floor), \
            f"sustained {gflops:.2f} gflops below the {floor} floor"


def test_captured_dpotrf_rate():
    """Graph-capture rate gate (same watchdog pattern, capture path).

    Opt-in via PARSEC_TEST_MIN_GFLOPS_CAPTURE (e.g. "100000" on a TPU
    chip where the captured DAG sustains several hundred TF/s); default
    checks correctness only and prints the measured rate."""
    import jax

    from parsec_tpu.collections import TwoDimBlockCyclic
    from parsec_tpu.dsl import ptg
    from parsec_tpu.ops import dpotrf_taskpool, make_spd

    n, nb = 512, 128
    M = make_spd(n)
    A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
    cg = ptg.capture(dpotrf_taskpool(A))
    tiles = {"descA": {c: A.tile(*c) for c in A.tiles()}}
    out = cg.fn(tiles)           # compile (untimed)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        out = cg.fn(tiles)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    gflops = (n ** 3 / 3.0) / dt / 1e9
    print(f"CAPTURED_DPOTRF n={n} nb={nb}: {gflops:.1f} gflops")
    Lf = np.zeros((n, n), np.float32)
    for (m, k), arr in out["descA"].items():
        Lf[m * nb:(m + 1) * nb, k * nb:(k + 1) * nb] = np.asarray(arr)
    L = np.tril(Lf)
    assert np.linalg.norm(L @ L.T - M) / np.linalg.norm(M) < 1e-5
    floor = float(os.environ.get("PARSEC_TEST_MIN_GFLOPS_CAPTURE", "0"))
    if floor > 0:
        assert gflops >= floor, \
            f"captured dpotrf sustained {gflops:.1f} < floor {floor}"


def _calibrate_gemm_gflops(reps: int = 3) -> float:
    """The host's CURRENT f32 GEMM rate through one jitted matmul —
    the same XLA/CPU substrate the wave kernels run on, measured in
    the same process at the same moment, so suite load discounts the
    wave floor exactly as much as it discounts the wave itself."""
    import jax
    import jax.numpy as jnp

    k = 1024
    f = jax.jit(lambda a, b: a @ b)
    a = jnp.asarray(np.random.RandomState(0).rand(k, k)
                    .astype(np.float32))
    jax.block_until_ready(f(a, a))   # compile outside the clock
    best = None
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(f(a, a))
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return 2.0 * k ** 3 / best / 1e9


def test_wave_dpotrf_rate():
    """Wave-execution rate gate at the north-star NB=512 (round-2
    VERDICT item 6: the path carrying the perf story had no regression
    alarm — a silent fall-back to per-task dispatch rates must FAIL).

    The floor is LOAD-NORMALIZED (ISSUE 6 satellite, replacing the
    PR-5 retry band-aid): a bare jitted GEMM calibrates the host's
    current f32 rate before and after the wave measurement, and the
    wave must sustain >= 5% of the slower calibration (healthy runs
    measure ~20%+; a broken dispatch path manages ~1-3%). Parallel
    test pressure slows the calibration GEMM and the wave kernels
    alike, so the ratio holds where a fixed 3.5-GFLOP floor tripped
    at 3.1 under suite load. An absolute PARSEC_TEST_MIN_GFLOPS_WAVE
    (e.g. "5000" on a chip runner) overrides the ratio gate."""
    import jax

    from parsec_tpu.collections import TwoDimBlockCyclic
    from parsec_tpu.dsl import ptg
    from parsec_tpu.ops import dpotrf_taskpool, make_spd

    n, nb = 2048, 512
    M = make_spd(n)
    A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
    w = ptg.wave(dpotrf_taskpool(A))
    pools = w.execute(w.build_pools())   # warm the kernel cache
    jax.block_until_ready(pools)
    calib_pre = _calibrate_gemm_gflops()
    best = None
    for _ in range(2):                   # best-of-2: GC/compaction blips
        pools = w.build_pools()
        jax.block_until_ready(pools)
        t0 = time.perf_counter()
        pools = w.execute(pools)
        jax.block_until_ready(pools)
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    calib_post = _calibrate_gemm_gflops()
    calib = min(calib_pre, calib_post)
    gflops = (n ** 3 / 3.0) / best / 1e9
    print(f"WAVE_DPOTRF n={n} nb={nb}: {gflops:.1f} gflops "
          f"(host gemm calibration {calib:.1f})")

    w.scatter_pools(pools)
    L = np.tril(A.to_numpy()).astype(np.float64)
    ref = make_spd(n).astype(np.float64)
    assert np.linalg.norm(L @ L.T - ref) / np.linalg.norm(ref) < 1e-5

    env_floor = os.environ.get("PARSEC_TEST_MIN_GFLOPS_WAVE")
    if env_floor:
        assert gflops >= float(env_floor), \
            f"wave dpotrf sustained {gflops:.1f} < declared floor " \
            f"{env_floor} — the batched dispatch path has regressed"
        return
    # the ratio can only LOWER the bar under load — 3.5 (the historical
    # absolute floor, ~10x above broken-dispatch rates on an idle CI
    # host) caps it so a fast host never raises its own bar
    floor = min(3.5, 0.05 * calib)
    assert gflops >= floor, \
        f"wave dpotrf sustained {gflops:.1f} GFLOP/s < {floor:.1f} " \
        f"(5% of the host's concurrent {calib:.1f}-GFLOP/s GEMM " \
        f"calibration, capped at 3.5) — the batched dispatch path " \
        f"has regressed"


def test_batched_dispatch_beats_per_task():
    """Device-module dispatch gate (ISSUE 5): for a same-class 64-task
    burst on CPU-jax, the stacked batched path's amortized CPU-side
    dispatch cost per task must beat per-task dispatch.

    Deliberately generous (beat, not the bench's ~6x) and measured on
    the device's own dispatch_ns counter rather than wall clock, so CI
    load flakes cannot trip it; the bench (BENCH_MODE=dispatch) reports
    the honest margin. Steady state: the burst runs twice per config
    and the cheaper rep gates (first batched rep pays the one-time
    stacked-callable compile)."""
    import jax
    import jax.numpy as jnp

    import parsec_tpu
    from parsec_tpu import dtd
    from parsec_tpu.dsl.dtd import INOUT, INPUT
    from parsec_tpu.utils.params import params

    burst, nb = 64, 48
    kern = jax.jit(lambda c, a, b:
                   c - jnp.dot(a, b.T, preferred_element_type=jnp.float32))

    def run(batch_max):
        with params.cmdline_override("device_batch_max", str(batch_max)), \
             params.cmdline_override("device_tpu_max", "1"):
            ctx = parsec_tpu.init(nb_cores=1)
            try:
                devs = [d for d in ctx.devices
                        if d.device_type == "tpu"]
                assert devs, "no XLA device attached"
                best = None
                for rep in range(2):
                    tp = dtd.taskpool_new()
                    ctx.add_taskpool(tp)

                    def body(es, task):
                        c, a, b = dtd.unpack_args(task)
                        c -= a @ b.T

                    boot = tp.tile_of_array(np.zeros((nb, nb), np.float32))
                    tp.insert_task(body, (boot, INOUT),
                                   (boot, INPUT), (boot, INPUT))
                    tp.add_chore(body, "tpu", kern)
                    rng = np.random.RandomState(rep)
                    tiles = [[tp.tile_of_array(
                        rng.rand(nb, nb).astype(np.float32))
                        for _ in range(3)] for _ in range(burst)]
                    s0 = sum(d.stats["dispatch_ns"] for d in devs)
                    c0 = sum(d.stats["dispatch_tasks"] for d in devs)
                    for c, a, b in tiles:
                        tp.insert_task(body, (c, INOUT),
                                       (a, INPUT), (b, INPUT))
                    tp.wait()
                    dns = sum(d.stats["dispatch_ns"] for d in devs) - s0
                    dt = sum(d.stats["dispatch_tasks"] for d in devs) - c0
                    us = dns / 1e3 / max(1, dt)
                    best = us if best is None else min(best, us)
                batches = sum(d.stats["batches"] for d in devs)
                return best, batches
            finally:
                ctx.fini()

    pertask_us, b0 = run(1)
    batched_us, b1 = run(16)
    print(f"DISPATCH_GATE 64-burst nb={nb}: batched {batched_us:.1f} "
          f"us/task vs per-task {pertask_us:.1f} us/task "
          f"({b1} batches)")
    assert b0 == 0 and b1 > 0, (b0, b1)
    assert batched_us < pertask_us, \
        f"batched dispatch {batched_us:.1f} us/task did not beat " \
        f"per-task {pertask_us:.1f} us/task"
