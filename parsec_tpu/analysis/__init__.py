"""Static analysis over the task-graph DSLs and the runtime itself.

The reference PTG compiler front-loads a battery of sanity checks over
the parsed JDF before emitting code (``jdf_sanity_checks``, jdf.c) —
mismatched flow endpoints, unused symbols, unguardable dataflow are
compile-time errors there, while our Python reproduction historically
discovered every spec bug at runtime (usually as a hang or a wrong
residual deep inside a multirank run).  This package is that missing
compile-time story, plus two lints the reference never had:

- :mod:`.ptg_check` — the JDF dataflow verifier: endpoint existence and
  direction compatibility, arity, dependency reciprocity, unused
  globals/locals, statically-unsatisfiable guards, and CTL/data cycle
  detection by enumerating a small concrete instantiation (PTG1xx).
- :mod:`.body_check` — the batch/donation-safety linter: predicts, from
  the stdlib ``ast`` of PTG BODY code and DTD task functions, the
  per-class fallbacks the device layer would otherwise hit at trace
  time (``this_task`` reads, untraceable constructs, nondeterminism,
  aliased same-tile args) and names the exact downgrade (BDY2xx).
- :mod:`.lock_check` — the runtime concurrency lint: fields registered
  in a module's ``_GUARDED_BY`` map may only be touched while holding
  the declared lock, and no blocking call may run while holding an
  engine/data lock (LCK3xx).

``tools/parsec_lint.py`` drives all three over the shipped specs,
examples, and the ``parsec_tpu/`` source tree; ``--strict`` turns any
error/warn finding into a non-zero exit (the tier-1 self-lint gate).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List

#: finding severities: ``error`` = the spec/source is wrong, ``warn`` =
#: suspicious or performance-degrading (both fail ``--strict``);
#: ``note`` = informational only (never fails a gate)
SEVERITIES = ("error", "warn", "note")


@dataclass
class Finding:
    """One analysis finding (the ``jdf_fatal``/``jdf_warn`` analog)."""

    code: str          # e.g. "PTG105"
    message: str
    where: str = ""    # "file:line task.flow" when known
    severity: str = "error"

    def __post_init__(self) -> None:
        assert self.severity in SEVERITIES, self.severity

    def __str__(self) -> str:
        loc = f"{self.where}: " if self.where else ""
        return f"{self.code} [{self.severity}] {loc}{self.message}"


def gate(findings: List["Finding"]) -> List["Finding"]:
    """The findings that fail a ``--strict`` run (errors + warnings)."""
    return [f for f in findings if f.severity in ("error", "warn")]


from .ptg_check import verify_jdf, verify_jdf_text  # noqa: E402
from .body_check import check_jdf_bodies, check_function  # noqa: E402
from .lock_check import lint_source, lint_file, lint_tree  # noqa: E402

__all__ = ["Finding", "gate", "SEVERITIES",
           "verify_jdf", "verify_jdf_text",
           "check_jdf_bodies", "check_function",
           "lint_source", "lint_file", "lint_tree"]
