"""Tile Cholesky (dpotrf_L) as a PTG task graph — the DPLASMA-slice.

The right-looking lower-triangular tile Cholesky with the classic four task
classes POTRF / TRSM / SYRK / GEMM and the same dataflow as DPLASMA's
dpotrf_L JDF running on the reference runtime (the north-star workload,
BASELINE.md config 5). Tile kernels are the jitted XLA executables from
ops/linalg.py, dispatched through the device module onto the TPU.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..collections.matrix import TiledMatrix
from ..dsl import ptg

DPOTRF_L_JDF = """
descA [ type="collection" ]
NT [ type="int" ]

POTRF(k)

k = 0 .. NT-1

: descA( k, k )

RW T <- (k == 0) ? descA( k, k ) : T SYRK( k-1, k )
     -> T TRSM( k, k+1 .. NT-1 )
     -> descA( k, k )

; (NT - k) * 1000

BODY [type=tpu]
{
    T = ops.potrf(T)
}
END

TRSM(k, m)

k = 0 .. NT-2
m = k+1 .. NT-1

: descA( m, k )

READ T <- T POTRF( k )
RW   C <- (k == 0) ? descA( m, k ) : C GEMM( k-1, m, k )
       -> A SYRK( k, m )
       -> A GEMM( k, m, k+1 .. m-1 )
       -> B GEMM( k, m+1 .. NT-1, m )
       -> descA( m, k )

; (NT - m) * 100 + (NT - k) * 10

BODY [type=tpu]
{
    C = ops.trsm_panel(T, C)
}
END

SYRK(k, m)

k = 0 .. NT-2
m = k+1 .. NT-1

: descA( m, m )

READ A <- C TRSM( k, m )
RW   T <- (k == 0) ? descA( m, m ) : T SYRK( k-1, m )
       -> (m == k+1) ? T POTRF( m ) : T SYRK( k+1, m )

; (NT - m) * 1000

BODY [type=tpu]
{
    T = ops.syrk_ln(T, A)
}
END

GEMM(k, m, n)

k = 0 .. NT-3
m = k+2 .. NT-1
n = k+1 .. m-1

: descA( m, n )

READ A <- C TRSM( k, m )
READ B <- C TRSM( k, n )
RW   C <- (k == 0) ? descA( m, n ) : C GEMM( k-1, m, n )
       -> (n == k+1) ? C TRSM( n, m ) : C GEMM( k+1, m, n )

; (NT - m) * 10

BODY [type=tpu]
{
    C = ops.gemm_nt(C, A, B)
}
END
"""

_factory = None


def dpotrf_factory() -> "ptg.JDFFactory":
    global _factory
    if _factory is None:
        _factory = ptg.compile_jdf(DPOTRF_L_JDF, name="dpotrf_L")
    return _factory


def dpotrf(context, A: TiledMatrix, rank: int = 0, nb_ranks: int = 1) -> None:
    """Run the Cholesky factorization of the SPD tiled matrix A in place
    (lower triangle holds L on return). Blocking: enqueue + wait."""
    assert A.mt == A.nt, "dpotrf needs a square tile grid"
    tp = dpotrf_taskpool(A, rank=rank, nb_ranks=nb_ranks)
    context.add_taskpool(tp)
    context.wait()


def dpotrf_taskpool(A: TiledMatrix, rank: int = 0, nb_ranks: int = 1):
    from .. import ops as ops_module
    tp = dpotrf_factory().new(descA=A, NT=A.nt, rank=rank, nb_ranks=nb_ranks)
    tp.global_env["ops"] = ops_module
    return tp


def make_spd(n: int, dtype=np.float32, seed: int = 0) -> np.ndarray:
    """A well-conditioned SPD matrix for testing/benchmarks."""
    rng = np.random.RandomState(seed)
    B = rng.rand(n, n).astype(np.float64) - 0.5
    M = (B @ B.T) / n + np.eye(n)
    return M.astype(dtype)
