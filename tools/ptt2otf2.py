#!/usr/bin/env python
"""Convert .ptt binary traces to OTF2 archives (the offline face of the
reference's direct-to-OTF2 trace backend, parsec/profiling_otf2.c).

    python tools/ptt2otf2.py trace.rank0.ptt [-o outdir]

One archive per input file (OTF2 archives are per-rank like the
reference's; Vampir/otf2-print merge them by opening all anchors).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from parsec_tpu.profiling.binfmt import read_profile  # noqa: E402
from parsec_tpu.profiling.otf2 import write_otf2  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("traces", nargs="+", help=".ptt input files")
    ap.add_argument("-o", "--outdir", default=".",
                    help="directory to place the archives in")
    args = ap.parse_args(argv)
    for path in args.traces:
        prof = read_profile(path)
        base = os.path.basename(path)
        if base.endswith(".ptt"):
            base = base[:-4]
        anchor = write_otf2(prof, os.path.join(args.outdir, base + ".otf2-archive"))
        print(f"{path}: {prof.nb_events()} events -> {anchor}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
