"""Mempool: per-thread freelists with owner-returning frees
(ref: parsec/mempool.c, private_mempool.c)."""
import threading

import numpy as np

from parsec_tpu.core.mempool import Mempool


def test_allocate_recycles():
    made = []

    def ctor():
        b = np.empty((64,), np.float32)
        made.append(b)
        return b

    pool = Mempool(ctor)
    a = pool.allocate()
    pool.free(a)
    b = pool.allocate()
    assert b is a                   # recycled, not re-constructed
    assert pool.nb_constructed() == 1
    pool.free(b)
    assert pool.nb_cached() == 1


def test_cross_thread_free_returns_to_owner():
    pool = Mempool(lambda: np.empty((8,), np.float32))
    elt = pool.allocate()           # owned by the main thread's freelist
    owner = pool.thread_mempool()

    def worker():
        pool.free(elt)              # freed from another thread

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert len(owner) == 1          # landed in the OWNER's list
    assert pool.allocate() is elt   # main thread gets it back


def test_max_cached_bounds_retention():
    pool = Mempool(lambda: object(), max_cached=2)
    elts = [pool.allocate() for _ in range(4)]
    for e in elts:
        pool.free(e)
    assert pool.nb_cached() == 2    # the rest went to GC


def test_foreign_element_free_is_noop():
    pool = Mempool(lambda: object())
    pool.free(object())             # not pool-constructed: dropped quietly
    assert pool.nb_cached() == 0


def test_per_thread_freelists_are_private():
    pool = Mempool(lambda: object())
    got = {}
    barrier = threading.Barrier(3)  # overlap: thread idents are reused
    # after join, which would alias freelists

    def worker(name):
        barrier.wait()
        e = pool.allocate()
        pool.free(e)
        got[name] = pool.thread_mempool()
        barrier.wait()

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    lists = set(id(tm) for tm in got.values())
    assert len(lists) == 3          # one freelist per thread
    assert pool.nb_cached() == 3


def test_dropped_numpy_element_purges_id_tracking():
    # numpy arrays reject attributes but support weakrefs: dropping one
    # without free() must purge its id entry (no unbounded growth, no
    # id-reuse aliasing a foreign array into the freelist)
    import gc
    pool = Mempool(lambda: np.empty((8,), np.float32))
    pool.allocate()                 # dropped immediately, never freed
    gc.collect()
    assert len(pool.owner_of) == 0


def test_attr_capable_elements_carry_owner_intrusively():
    class Elt:
        pass

    pool = Mempool(Elt)
    e = pool.allocate()
    assert len(pool.owner_of) == 0  # no id-keyed side table at all
    pool.free(e)
    assert pool.allocate() is e


def test_overflow_dropped_element_is_disowned():
    class Elt:
        pass

    pool = Mempool(Elt, max_cached=1)
    e1, e2 = pool.allocate(), pool.allocate()
    pool.free(e1)
    pool.free(e2)                   # over cap: dropped + disowned
    pool.free(e2)                   # stray double-free of the dropped one
    assert pool.nb_cached() == 1    # must NOT re-enter the pool
    assert pool.allocate() is e1


def test_finalizer_does_not_retain_pool():
    import gc
    import weakref as wr
    pool = Mempool(lambda: np.empty((8,), np.float32))
    escaped = pool.allocate()       # held by user, never freed
    ref = wr.ref(pool)
    del pool
    gc.collect()
    assert ref() is None            # escaped element must not pin the pool
    del escaped
