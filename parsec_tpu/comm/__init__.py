"""Communication: comm-engine abstraction, transports, remote-dep protocol
(SURVEY.md §2.4)."""
from .engine import (CommEngine, MemHandle, RankFailedError, TAG_ACTIVATE,
                     TAG_DTD_DATA, TAG_GET_DATA, TAG_GET_REQ, TAG_HEARTBEAT,
                     TAG_TERMDET, TAG_USER_BASE)
from .local import LocalCommEngine, LocalFabric
from .mesh import MeshCommEngine, MeshFabric
from .tcp import TCPCommEngine, free_ports
from .remote_dep import RemoteDepEngine, bcast_children
from .xfer import DeviceDataPlane

__all__ = ["CommEngine", "MemHandle", "RankFailedError", "LocalFabric",
           "LocalCommEngine", "MeshFabric", "MeshCommEngine", "TCPCommEngine",
           "free_ports", "RemoteDepEngine", "bcast_children",
           "DeviceDataPlane", "TAG_ACTIVATE", "TAG_DTD_DATA", "TAG_GET_DATA",
           "TAG_GET_REQ", "TAG_HEARTBEAT", "TAG_TERMDET", "TAG_USER_BASE"]
