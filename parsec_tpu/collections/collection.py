"""Data collection base: the distribution *is* the collection vtable.

Reference behavior: ``parsec_data_collection_t`` exposes
``rank_of(...)/vpid_of(...)/data_of(...)/data_key(...)`` (+ ``*_of_key``)
virtual functions; user code overrides them to define arbitrary
distributions (ref: parsec/data_distribution.c,
examples/Ex04_ChainData.jdf:127-133).
"""
from __future__ import annotations

import threading
from typing import Any, Dict, Iterable, Optional, Tuple

import numpy as np

from ..data.data import Data, DataCopy, Coherency, data_new_with_payload


class DataCollection:
    """Subclasses override rank_of/vpid_of/data_of/data_key."""

    def __init__(self, nodes: int = 1, rank: int = 0, name: str = "") -> None:
        self.nodes = nodes
        self.rank = rank
        # the name is the collection's SPMD-wide identity on the wire
        # (multi-rank DTD keys tile messages by it); give distinct logical
        # collections distinct names
        self.name = name or type(self).__name__
        self.dtt: Any = None  # default datatype descriptor of one element/tile

    # -- key-based interface ------------------------------------------------
    def data_key(self, *indices) -> Any:
        return indices if len(indices) != 1 else indices[0]

    def rank_of(self, *indices) -> int:
        raise NotImplementedError

    def vpid_of(self, *indices) -> int:
        return 0

    def data_of(self, *indices) -> Data:
        raise NotImplementedError

    # ``*_of_key`` variants (ref: rank_of_key/data_of_key)
    def rank_of_key(self, key: Any) -> int:
        idx = key if isinstance(key, tuple) else (key,)
        return self.rank_of(*idx)

    def data_of_key(self, key: Any) -> Data:
        idx = key if isinstance(key, tuple) else (key,)
        return self.data_of(*idx)

    def is_local(self, *indices) -> bool:
        return self.rank_of(*indices) == self.rank


class LocalArrayCollection(DataCollection):
    """A host ndarray split into equal chunks along axis 0; chunk k is one
    datum. The simplest collection for examples/tests (the reference's
    Ex01-Ex05 use hand-rolled single-datum collections like this)."""

    def __init__(self, array: np.ndarray, nb_chunks: int,
                 nodes: int = 1, rank: int = 0) -> None:
        super().__init__(nodes, rank)
        assert array.shape[0] % nb_chunks == 0, \
            f"axis 0 ({array.shape[0]}) not divisible into {nb_chunks} chunks"
        self.array = array
        self.nb_chunks = nb_chunks
        self.chunk = array.shape[0] // nb_chunks
        self._data: Dict[int, Data] = {}
        self._lock = threading.Lock()

    def rank_of(self, k: int) -> int:
        return k % self.nodes

    def data_of(self, k: int) -> Data:
        with self._lock:
            d = self._data.get(k)
            if d is None:
                view = self.array[k * self.chunk:(k + 1) * self.chunk]
                d = data_new_with_payload(view, device_id=0, key=(id(self), k))
                d.collection = self
                self._data[k] = d
            return d

    def keys(self) -> Iterable[int]:
        return range(self.nb_chunks)


class DictCollection(DataCollection):
    """Key -> (rank, data) table; the irregular 'hash datadist'
    (ref: parsec/data_dist/hash_datadist.c)."""

    def __init__(self, nodes: int = 1, rank: int = 0) -> None:
        super().__init__(nodes, rank)
        self._entries: Dict[Any, Tuple[int, int, Optional[Data]]] = {}
        self._lock = threading.Lock()

    def add(self, key: Any, rank: int, payload: Any = None, vpid: int = 0) -> None:
        with self._lock:
            data = None
            if payload is not None:
                data = data_new_with_payload(payload, device_id=0,
                                             key=(id(self), key))
                data.collection = self
            self._entries[key] = (rank, vpid, data)

    def rank_of(self, *indices) -> int:
        key = indices if len(indices) != 1 else indices[0]
        return self._entries[key][0]

    def vpid_of(self, *indices) -> int:
        key = indices if len(indices) != 1 else indices[0]
        return self._entries[key][1]

    def data_of(self, *indices) -> Data:
        key = indices if len(indices) != 1 else indices[0]
        ent = self._entries[key]
        if ent[2] is None:
            raise KeyError(f"key {key} is remote (rank {ent[0]}); no local data")
        return ent[2]

    def keys(self):
        return list(self._entries)
