"""Termination detection modules (MCA framework ``termdet``).

Reference behavior: every taskpool gets a termination-detector monitor that
counts known tasks + pending runtime actions and fires the completion
callback when both are provably zero. Modules: ``local`` (single atomic
counter, ref: parsec/mca/termdet/local/termdet_local_module.c, 243 LoC) and
``fourcounter`` (distributed credit algorithm over the comm engine,
ref: parsec/mca/termdet/fourcounter/termdet_fourcounter_module.c, 706 LoC);
interface parsec/mca/termdet/termdet.h:42-296.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional


class TermDet:
    """Monitor interface (ref: parsec_termdet_module_t)."""

    name = "base"

    def __init__(self, taskpool) -> None:
        self.taskpool = taskpool

    def taskpool_addto_nb_tasks(self, delta: int) -> int:
        raise NotImplementedError

    def taskpool_addto_runtime_actions(self, delta: int) -> int:
        raise NotImplementedError

    def taskpool_set_nb_tasks(self, n: int) -> int:
        raise NotImplementedError

    def taskpool_ready(self) -> None:
        """Monitoring starts: zero counts before ready() do not terminate."""
        raise NotImplementedError


class LocalTermDet(TermDet):
    """Single-process counting detector (ref: termdet_local_module.c).

    Termination when (nb_tasks == 0 and runtime_actions == 0) after the
    taskpool was declared ready. ``UNDEFINED_NB_TASKS`` semantics: DTD-style
    pools that don't know their total keep a live insertion count.
    """

    name = "local"

    def __init__(self, taskpool) -> None:
        super().__init__(taskpool)
        self._lock = threading.Lock()
        self.nb_tasks = 0
        self.runtime_actions = 0
        self._ready = False
        self._terminated = False

    def _check(self) -> None:
        fire = False
        with self._lock:
            if (self._ready and not self._terminated
                    and self.nb_tasks == 0 and self.runtime_actions == 0):
                self._terminated = True
                fire = True
        if fire:
            self.taskpool.termination_detected()

    def taskpool_addto_nb_tasks(self, delta: int) -> int:
        with self._lock:
            self.nb_tasks += delta
            v = self.nb_tasks
            assert v >= 0, "nb_tasks went negative"
        if v == 0:
            self._check()
        return v

    def taskpool_addto_runtime_actions(self, delta: int) -> int:
        with self._lock:
            self.runtime_actions += delta
            v = self.runtime_actions
            assert v >= 0, "runtime_actions went negative"
        if v == 0:
            self._check()
        return v

    def taskpool_set_nb_tasks(self, n: int) -> int:
        with self._lock:
            self.nb_tasks = n
        if n == 0:
            self._check()
        return n

    def taskpool_ready(self) -> None:
        with self._lock:
            self._ready = True
        self._check()


class UserTriggerTermDet(LocalTermDet):
    """User-declared completion (ref: termdet user_trigger module)."""

    name = "user_trigger"

    def __init__(self, taskpool) -> None:
        super().__init__(taskpool)
        self.nb_tasks = 1  # held until the user triggers

    def user_trigger(self) -> None:
        self.taskpool_addto_nb_tasks(-1)


class FourCounterTermDet(LocalTermDet):
    """Distributed 4-counter credit termination detection.

    ref: termdet_fourcounter_module.c — each rank tracks (sent, received)
    message counts plus local activity; rank 0 aggregates waves of
    (total_sent, total_received) and declares termination after two
    consistent waves. Here the wave runs over the comm engine's AM channel;
    single-rank degenerates to local counting.
    """

    name = "fourcounter"

    def __init__(self, taskpool, comm=None) -> None:
        super().__init__(taskpool)
        self.comm = comm
        self.msgs_sent = 0
        self.msgs_received = 0
        self._last_wave: Optional[tuple] = None

    def msg_sent(self) -> None:
        with self._lock:
            self.msgs_sent += 1

    def msg_received(self) -> None:
        with self._lock:
            self.msgs_received += 1

    def _locally_quiet(self) -> bool:
        return self._ready and self.nb_tasks == 0 and self.runtime_actions == 0

    def local_counts(self) -> tuple:
        with self._lock:
            return (self.msgs_sent, self.msgs_received, self._locally_quiet())

    # rank 0 drives waves through comm.termdet_wave(); see comm/remote_dep.py
    def _check(self) -> None:
        if self.comm is None or self.comm.nb_ranks <= 1:
            super()._check()
            return
        if self._locally_quiet():
            self.comm.termdet_local_quiet(self)

    def distributed_terminate(self) -> None:
        fire = False
        with self._lock:
            if not self._terminated:
                self._terminated = True
                fire = True
        if fire:
            self.taskpool.termination_detected()


from ..utils import mca as _mca

_MODULES: Dict[str, Any] = {
    "local": LocalTermDet,
    "user_trigger": UserTriggerTermDet,
    "fourcounter": FourCounterTermDet,
}
for _n, _c in _MODULES.items():
    _mca.register("termdet", _n, _c)


def termdet_new(name: str, taskpool, **kw) -> TermDet:
    cls = _mca.open_component("termdet", name)
    if cls is None:
        raise ValueError(
            f"unknown termdet module {name!r}; "
            f"have {_mca.components('termdet')}")
    return cls(taskpool, **kw)
