"""tools/launch.py — the mpiexec analog: one command deploys the same
program SPMD across real OS processes, each rank's Context auto-wiring
its comm engine from the launcher's env (VERDICT r2 item 4)."""
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CPU_MULTIPROC_MSG = "Multiprocess computations aren't implemented on the CPU"


def _skip_if_cpu_multiproc_unsupported(p):
    """jax's CPU backend only gained cross-process collectives recently;
    on older jax the distributed runtime comes up but the first sharded
    computation aborts with a canned error — an environment limit, not
    a launcher bug, so those probes skip instead of failing."""
    if p.returncode != 0 and _CPU_MULTIPROC_MSG in (p.stdout + p.stderr):
        pytest.skip("jax CPU backend lacks multiprocess collectives")


def _launch(n, prog, extra=(), timeout=240):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", str(n), *extra, os.path.join(ROOT, prog)],
        capture_output=True, text=True, timeout=timeout, env=env)
    assert p.returncode == 0, (p.returncode, p.stdout[-3000:],
                               p.stderr[-2000:])
    return p.stdout


def test_launch_ex05_two_ranks():
    out = _launch(2, "examples/ex05_broadcast.py")
    assert "[0] rank 0/2" in out and "[1] rank 1/2" in out


def test_launch_dposv_three_ranks():
    out = _launch(3, "examples/ex10_dposv_multiprocess.py", timeout=300)
    for r in range(3):
        assert f"rank {r}/3: dposv ok" in out, out[-2000:]


def test_launch_jax_distributed_global_mesh(tmp_path):
    probe = tmp_path / "probe.py"
    probe.write_text(
        "import sys; sys.path.insert(0, %r)\n"
        "import parsec_tpu\n"
        "ctx = parsec_tpu.init(nb_cores=1)\n"
        "import jax\n"
        "print(f'rank {ctx.rank}: global={len(jax.devices())} "
        "procs={jax.process_count()}')\n"
        "ctx.fini()\n" % ROOT)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    p = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", "2", "--jax-distributed", str(probe)],
        capture_output=True, text=True, timeout=240, env=env)
    assert p.returncode == 0, (p.stdout[-3000:], p.stderr[-2000:])
    # 2 processes x 4 local virtual devices = ONE 8-device global mesh
    assert "global=8 procs=2" in p.stdout, p.stdout[-2000:]


def test_launch_fail_fast(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import sys, os\n"
                   "rank = int(os.environ['PARSEC_MCA_comm_rank'])\n"
                   "sys.exit(9 if rank == 1 else 0)\n")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", "2", str(bad)],
        capture_output=True, text=True, timeout=120, env=env)
    assert p.returncode == 9


def test_launch_jax_distributed_cross_process_collective(tmp_path):
    """A jitted reduction over an array sharded across BOTH processes:
    XLA inserts a cross-process all-reduce over the distributed runtime
    — the actual §5.8 execution substrate, not just device counting."""
    probe = tmp_path / "coll.py"
    probe.write_text(
        "import sys; sys.path.insert(0, %r)\n"
        "import numpy as np\n"
        "import parsec_tpu\n"
        "ctx = parsec_tpu.init(nb_cores=1)\n"
        "import jax, jax.numpy as jnp\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "devs = jax.devices()\n"
        "mesh = Mesh(np.array(devs), ('x',))\n"
        "sh = NamedSharding(mesh, P('x'))\n"
        "n = len(devs)\n"
        "local = [jax.device_put(\n"
        "    np.full((1, 4), float(devs.index(d)), np.float32), d)\n"
        "    for d in jax.local_devices()]\n"
        "garr = jax.make_array_from_single_device_arrays(\n"
        "    (n, 4), sh, local)\n"
        "out = jax.jit(lambda a: a.sum(),\n"
        "              out_shardings=NamedSharding(mesh, P()))(garr)\n"
        "total = float(out)\n"
        "expect = 4.0 * sum(range(n))\n"
        "assert total == expect, (total, expect)\n"
        "print(f'rank {ctx.rank}: allreduce over {n} devices across '\n"
        "      f'{jax.process_count()} processes = {total} OK')\n"
        "ctx.fini()\n" % ROOT)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    p = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", "2", "--jax-distributed", str(probe)],
        capture_output=True, text=True, timeout=240, env=env)
    _skip_if_cpu_multiproc_unsupported(p)
    assert p.returncode == 0, (p.stdout[-3000:], p.stderr[-2000:])
    assert p.stdout.count("across 2 processes = 112.0 OK") == 2, \
        p.stdout[-2000:]


def _parse_lane_stats(stdout):
    """Per-rank lane stats from the probe's LANE-OK lines."""
    import re
    out = []
    for m in re.finditer(r"member=(\d) calls=(\d+) joins=(\d+) "
                         r"ctiles=(\d+)", stdout):
        out.append({"member": bool(int(m.group(1))),
                    "calls": int(m.group(2)),
                    "joins": int(m.group(3)),
                    "ctiles": int(m.group(4))})
    return out


def test_launch_collective_lane_multiprocess(tmp_path):
    """The compiled collective lane over a REAL multi-controller mesh:
    2 launcher processes under --jax-distributed run dist-wave dpotrf;
    full-broadcast panels ride one jitted all-reduce per (wave, pool)
    over the cross-process global mesh instead of per-destination sends
    (round-4 VERDICT Missing #2 — the SPMD substrate, not a thread
    shim). The probe asserts collective_calls > 0, correct numerics,
    and that p2p tile traffic shrank to the non-broadcast share."""
    probe = tmp_path / "lane.py"
    probe.write_text(
        "import sys; sys.path.insert(0, %r)\n"
        "import numpy as np\n"
        "import parsec_tpu\n"
        "from parsec_tpu.collections import TwoDimBlockCyclic\n"
        "from parsec_tpu.dsl import ptg\n"
        "from parsec_tpu.ops import dpotrf_taskpool, make_spd\n"
        "ctx = parsec_tpu.init(nb_cores=1)\n"
        "import jax\n"
        "rank, nr = ctx.rank, ctx.nb_ranks\n"
        "n, nb = 256, 32\n"
        "M = make_spd(n, dtype=np.float64)\n"
        "A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float64, P=nr,\n"
        "                      Q=1, nodes=nr, rank=rank)\n"
        "A.name = 'descA'\n"
        "A.from_numpy(M.copy())\n"
        "tp = dpotrf_taskpool(A, rank=rank, nb_ranks=nr)\n"
        "w = ptg.wave(tp, comm=ctx.comm.ce)\n"
        "member = any(rank in m for by_g in w._lane_sched.values()\n"
        "             for (_c, m) in by_g)\n"
        "w.run()\n"
        "ref = np.linalg.cholesky(M)\n"
        "err = 0.0\n"
        "for (i, j) in A.tiles():\n"
        "    if A.rank_of(i, j) != rank or i < j: continue\n"
        "    t = np.asarray(A.data_of(i, j).sync_to_host().payload)\n"
        "    if i == j: t = np.tril(t)\n"
        "    err = max(err, float(np.abs(\n"
        "        t - ref[i*nb:(i+1)*nb, j*nb:(j+1)*nb]).max()))\n"
        "s = w.stats\n"
        "assert err < 1e-4, err\n"
        "print(f'rank {rank}: lane={s[\"collective_lane\"]} '\n"
        "      f'member={int(member)} '\n"
        "      f'calls={s[\"collective_calls\"]} '\n"
        "      f'joins={s[\"collective_joins\"]} '\n"
        "      f'ctiles={s[\"collective_tiles\"]} '\n"
        "      f'sent={s[\"tiles_sent\"]} err={err:.1e} LANE-OK')\n"
        "ctx.fini()\n" % ROOT)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", "3", "--jax-distributed", str(probe)],
        capture_output=True, text=True, timeout=300, env=env)
    _skip_if_cpu_multiproc_unsupported(p)
    assert p.returncode == 0, (p.stdout[-3000:], p.stderr[-2000:])
    assert p.stdout.count("LANE-OK") == 3, p.stdout[-2000:]
    assert "lane=multiproc" in p.stdout, p.stdout[-2000:]
    # collective_calls/collective_tiles must prove MEMBERSHIP, not just
    # that a zero-contribution join happened (ADVICE r5): every member
    # rank carried tiles through the lane; row-cyclic panels make every
    # rank a member here
    stats = _parse_lane_stats(p.stdout)
    assert len(stats) == 3 and all(s["member"] for s in stats), stats
    assert all(s["calls"] > 0 and s["ctiles"] > 0 for s in stats), stats


def test_launch_collective_lane_multiprocess_partial_groups(tmp_path):
    """PARTIAL broadcast groups over a REAL multi-controller mesh: 4
    launcher processes, P=2 x Q=2 — a distribution where panel readers
    are a row/column SUBSET of ranks. Every process joins each group's
    global all-reduce (multi-controller XLA requires the same call
    sequence everywhere); non-members contribute zeros and drop the
    result. Asserts at least one scheduled group really is partial,
    collective calls happened, and numerics match cholesky."""
    probe = tmp_path / "lane_partial.py"
    probe.write_text(
        "import sys; sys.path.insert(0, %r)\n"
        "import numpy as np\n"
        "import parsec_tpu\n"
        "from parsec_tpu.collections import TwoDimBlockCyclic\n"
        "from parsec_tpu.dsl import ptg\n"
        "from parsec_tpu.ops import dpotrf_taskpool, make_spd\n"
        "ctx = parsec_tpu.init(nb_cores=1)\n"
        "rank, nr = ctx.rank, ctx.nb_ranks\n"
        "n, nb = 192, 32\n"
        "M = make_spd(n, dtype=np.float64)\n"
        "A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float64, P=2,\n"
        "                      Q=nr // 2, nodes=nr, rank=rank)\n"
        "A.name = 'descA'\n"
        "A.from_numpy(M.copy())\n"
        "tp = dpotrf_taskpool(A, rank=rank, nb_ranks=nr)\n"
        "w = ptg.wave(tp, comm=ctx.comm.ce)\n"
        "groups = {m for by_g in w._lane_sched.values()\n"
        "          for (_c, m) in by_g}\n"
        "assert any(len(m) < nr for m in groups), groups\n"
        "member = any(rank in m for m in groups)\n"
        "w.run()\n"
        "ref = np.linalg.cholesky(M)\n"
        "err = 0.0\n"
        "for (i, j) in A.tiles():\n"
        "    if A.rank_of(i, j) != rank or i < j: continue\n"
        "    t = np.asarray(A.data_of(i, j).sync_to_host().payload)\n"
        "    if i == j: t = np.tril(t)\n"
        "    err = max(err, float(np.abs(\n"
        "        t - ref[i*nb:(i+1)*nb, j*nb:(j+1)*nb]).max()))\n"
        "s = w.stats\n"
        "assert err < 1e-4, err\n"
        "print(f'rank {rank}: lane={s[\"collective_lane\"]} '\n"
        "      f'member={int(member)} '\n"
        "      f'calls={s[\"collective_calls\"]} '\n"
        "      f'joins={s[\"collective_joins\"]} '\n"
        "      f'ctiles={s[\"collective_tiles\"]} '\n"
        "      f'sent={s[\"tiles_sent\"]} err={err:.1e} LANE-OK')\n"
        "ctx.fini()\n" % ROOT)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PARSEC_MCA_wave_dist_collective"] = "auto"
    p = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", "4", "--jax-distributed", str(probe)],
        capture_output=True, text=True, timeout=300, env=env)
    _skip_if_cpu_multiproc_unsupported(p)
    assert p.returncode == 0, (p.stdout[-3000:], p.stderr[-2000:])
    assert p.stdout.count("LANE-OK") == 4, p.stdout[-2000:]
    assert "lane=multiproc" in p.stdout, p.stdout[-2000:]
    # member-only accounting (ADVICE r5): every MEMBER rank proves its
    # tiles rode the lane; non-members of partial groups only join
    # (collective_joins) and must not count calls for them
    stats = _parse_lane_stats(p.stdout)
    assert len(stats) == 4, p.stdout[-2000:]
    for s in stats:
        if s["member"]:
            assert s["calls"] > 0 and s["ctiles"] > 0, stats
        else:
            assert s["ctiles"] == 0, stats


def test_launch_multi_host_ssh():
    """--hosts NAME:BINDADDR spawns non-local ranks through --ssh and
    binds each rank's endpoint on its own interface (two loopback
    aliases here; the ssh transport is tests/fake_ssh.py since CI has
    no sshd — the command construction, `env` wiring, and per-host
    endpoint binding are the real code path). The program itself does
    a cross-rank broadcast, so the two "hosts" really talk."""
    fake = os.path.join(ROOT, "tests", "fake_ssh.py")
    out = _launch(2, "examples/ex05_broadcast.py", extra=(
        "--hosts", "nodeA:127.0.0.2,nodeB:127.0.0.3",
        "--ssh", f"{sys.executable} {fake}",
        "--port-base", "29410"))
    assert "[0] rank 0/2" in out and "[1] rank 1/2" in out


def test_launch_multi_host_local_names_spawn_directly(tmp_path):
    """127.* / localhost entries in --hosts bypass ssh entirely."""
    probe = tmp_path / "p.py"
    probe.write_text(
        "import os\n"
        "print('rank', os.environ['PARSEC_MCA_comm_rank'], 'ep',\n"
        "      os.environ['PARSEC_MCA_comm_endpoints'])\n")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", "2", "--hosts", "127.0.0.1", "--ssh", "/nonexistent-ssh",
         "--port-base", "29420", str(probe)],
        capture_output=True, text=True, timeout=120, env=env)
    assert p.returncode == 0, (p.stdout[-2000:], p.stderr[-1000:])
    assert "ep 127.0.0.1:29420,127.0.0.1:29421" in p.stdout
