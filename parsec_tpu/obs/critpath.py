"""Offline critical-path + overlap analysis over exported traces.

Input: a Chrome-trace JSON written by ``profiling.trace.Profile.dump``
plus (optionally) the executed-DAG DOT written by the grapher
(``profiling_dot=<prefix>``). Output (see :func:`analyze`):

- **critical path** — the longest duration-weighted path through the
  executed DAG, with its task chain: the lower bound on makespan no
  scheduler can beat without changing the DAG;
- **per-task-class breakdown** — count / total / mean exec time per
  class per rank (where the time went);
- **compute/comm overlap fraction per rank** — the T3-style metric
  (arXiv:2401.16677): the fraction of communication time hidden under
  task execution. 1.0 = perfectly overlapped, 0.0 = fully exposed;
- **cross-rank section** (ISSUE 15, when the traces carry ``obs_flow``
  flow events): stitched send→recv wire edges, a DISTRIBUTED critical
  path that follows the binding constraint backwards across rank
  boundaries, and a per-link exposed-wait attribution table — which
  peer/link each rank's un-hidden comm time was spent waiting on.

Rank traces from different processes sit on different monotonic clocks;
:func:`rank_clock_shifts` aligns them from the ``trace_t0_ns`` +
``clock_offsets_us`` metadata the context stamps at export (the
ping/pong midpoint estimates, comm/tcp.py), and
:func:`merge_trace_docs` fuses N per-rank documents into ONE
offset-corrected Perfetto timeline (CLI: ``tools/obs_trace_merge.py``).

The report CLI front end is ``tools/obs_report.py``.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["load_trace_intervals", "load_flow_events", "parse_dot",
           "critical_path", "merge_intervals", "overlap_us",
           "subtract_intervals", "rank_clock_shifts", "merge_trace_docs",
           "stitch_flows", "distributed_critical_path",
           "per_link_exposed_wait", "analyze", "format_report"]


class Interval:
    __slots__ = ("pid", "tid", "name", "begin", "end", "args")

    def __init__(self, pid, tid, name, begin, end, args) -> None:
        self.pid, self.tid, self.name = pid, tid, name
        self.begin, self.end, self.args = begin, end, args

    @property
    def duration(self) -> float:
        return self.end - self.begin


def load_trace_intervals(doc: Dict[str, Any],
                         shift_us: float = 0.0) -> List[Interval]:
    """Intervals from complete ("X", ts+dur) events and from B/E pairs
    (matched per (pid, tid, name), LIFO — the same matching
    ``Profile.to_dataframe`` applies). Timestamps are the export's
    microseconds, plus ``shift_us`` (the per-rank clock correction
    :func:`rank_clock_shifts` computes)."""
    events = _doc_events(doc)
    out: List[Interval] = []
    # complete events carry their own duration — no pairing needed
    for e in events:
        if e.get("ph") == "X":
            out.append(Interval(e.get("pid", 0), e.get("tid", 0),
                                e.get("name", ""), e["ts"] + shift_us,
                                e["ts"] + e.get("dur", 0.0) + shift_us,
                                e.get("args")))
    # B/E events may interleave streams out of order in the list
    be = sorted(
        (e for e in events if e.get("ph") in ("B", "E")),
        key=lambda e: (e.get("pid", 0), e.get("tid", 0), e.get("ts", 0.0)))
    open_ev: Dict[Tuple, List[Tuple[float, Any]]] = {}
    for e in be:
        key = (e.get("pid", 0), e.get("tid", 0), e.get("name", ""))
        if e["ph"] == "B":
            open_ev.setdefault(key, []).append((e["ts"], e.get("args")))
        else:
            stack = open_ev.get(key)
            if stack:
                ts0, args = stack.pop()
                out.append(Interval(key[0], key[1], key[2], ts0 + shift_us,
                                    e["ts"] + shift_us, args))
    return out


def load_flow_events(doc: Dict[str, Any],
                     shift_us: float = 0.0) -> List[Dict[str, Any]]:
    """Flow-pair halves (``ph:"s"``/``"f"``, ISSUE 15) as plain dicts:
    ``{"phase", "id", "pid", "tid", "name", "ts", "args"}`` with the
    per-rank clock correction applied."""
    events = _doc_events(doc)
    out: List[Dict[str, Any]] = []
    for e in events:
        if e.get("ph") in ("s", "f"):
            out.append({"phase": e["ph"], "id": e.get("id", 0),
                        "pid": e.get("pid", 0), "tid": e.get("tid", 0),
                        "name": e.get("name", ""),
                        "ts": e.get("ts", 0.0) + shift_us,
                        "args": e.get("args")})
    return out


# ---------------------------------------------------------------------- #
# fleet merge: N per-rank traces onto one reference clock                #
# ---------------------------------------------------------------------- #
def _doc_events(doc: Any) -> List[Any]:
    """The event list of a Chrome trace in either accepted form: an
    object with ``traceEvents`` or a bare JSON array (the same duality
    ``load_trace_intervals`` supports)."""
    if isinstance(doc, list):
        return doc
    return doc.get("traceEvents", []) if isinstance(doc, dict) else []


def _doc_meta(doc: Any) -> Dict[str, Any]:
    meta = doc.get("metadata") if isinstance(doc, dict) else None
    return meta if isinstance(meta, dict) else {}


def _doc_rank(doc: Any) -> Optional[int]:
    meta = _doc_meta(doc)
    try:
        return int(meta["rank"])
    except (KeyError, TypeError, ValueError):
        # fall back to the dominant pid of the events (pid == rank in
        # every Profile export)
        pids = [e.get("pid") for e in _doc_events(doc)
                if isinstance(e, dict) and e.get("pid") is not None]
        return pids[0] if pids else None


def _doc_offsets(doc: Dict[str, Any]) -> Dict[int, float]:
    """Per-peer clock offsets (peer_clock - this_rank_clock, µs) the
    context stamped into the trace metadata at export."""
    import json as _json
    raw = _doc_meta(doc).get("clock_offsets_us")
    if isinstance(raw, str):
        try:
            raw = _json.loads(raw)
        except ValueError:
            return {}
    if not isinstance(raw, dict):
        return {}
    out = {}
    for k, v in raw.items():
        try:
            out[int(k)] = float(v)
        except (TypeError, ValueError):
            continue
    return out


def rank_clock_shifts(docs: List[Dict[str, Any]]) -> Dict[int, float]:
    """Per-document timestamp shift (µs, keyed by list index) that puts
    every rank's events onto the REFERENCE rank's clock (the
    lowest-numbered rank present).

    A rank-r timestamp ``ts`` maps to monotonic ``t0_r + ts`` on rank
    r's clock; the reference clock reads that instant as
    ``t0_r + ts - off`` where ``off = clock_r - clock_ref`` — the
    ping/pong midpoint estimate. The reference's own measurement of r
    is preferred; r's measurement of the reference (negated) is the
    fallback; 0 (same clock, e.g. in-process fabrics or a pre-merge
    document without metadata) otherwise."""
    ranks = [_doc_rank(d) for d in docs]
    known = [r for r in ranks if r is not None]
    if not known:
        return {i: 0.0 for i in range(len(docs))}
    ref_rank = min(known)
    ref_i = ranks.index(ref_rank)
    ref_meta = _doc_meta(docs[ref_i])
    ref_t0 = float(ref_meta.get("trace_t0_ns", 0.0))
    ref_offs = _doc_offsets(docs[ref_i])
    shifts: Dict[int, float] = {}
    for i, doc in enumerate(docs):
        r = ranks[i]
        if i == ref_i or r is None:
            shifts[i] = 0.0
            continue
        meta = _doc_meta(doc)
        t0 = float(meta.get("trace_t0_ns", ref_t0))
        if r in ref_offs:
            off = ref_offs[r]
        else:
            back = _doc_offsets(doc).get(ref_rank)
            off = -back if back is not None else 0.0
        shifts[i] = (t0 - ref_t0) / 1e3 - off
    return shifts


def merge_trace_docs(docs: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fuse N per-rank Chrome-trace documents into ONE offset-corrected
    timeline: every event keeps its pid (= rank row in Perfetto), its
    ``ts``/``dur`` shifted onto the reference rank's clock; flow pairs
    (same id on an "s" in one rank row and an "f" in another) become
    arrows CROSSING rank rows. The merged metadata records the applied
    shifts — and no ``trace_t0_ns``, so re-merging is a no-op shift."""
    shifts = rank_clock_shifts(docs)
    events: List[Dict[str, Any]] = []
    ranks: List[int] = []
    applied: Dict[str, float] = {}
    for i, doc in enumerate(docs):
        r = _doc_rank(doc)
        if r is not None:
            ranks.append(r)
            applied[str(r)] = round(shifts[i], 3)
        sh = shifts[i]
        for e in _doc_events(doc):
            if not isinstance(e, dict):
                continue
            e = dict(e)
            if isinstance(e.get("ts"), (int, float)):
                e["ts"] = e["ts"] + sh
            events.append(e)
    return {"traceEvents": events,
            "metadata": {"merged_ranks": sorted(set(ranks)),
                         "clock_shifts_us": applied}}


# ---------------------------------------------------------------------- #
# cross-rank edge stitching + distributed critical path (ISSUE 15)       #
# ---------------------------------------------------------------------- #
def _ev_tenant(ev: Dict[str, Any]) -> Optional[str]:
    """The tenant a flow half / interval was attributed to, or None."""
    args = ev.get("args") if isinstance(ev, dict) else None
    if isinstance(args, dict):
        t = args.get("tenant")
        return t if isinstance(t, str) else None
    return None


def stitch_flows(flow_events: List[Dict[str, Any]]
                 ) -> Tuple[List[Dict[str, Any]], int]:
    """Pair "s"/"f" halves by flow id into send→recv edges:
    ``{"id", "name", "src", "dst", "send_ts", "recv_ts", "lag_us"}``.
    Returns (edges, unmatched_count) — a one-sided id is a truncated
    trace or a lost message, counted but never fabricated into an
    edge."""
    sends: Dict[Any, Dict[str, Any]] = {}
    recvs: Dict[Any, Dict[str, Any]] = {}
    unmatched = 0
    for ev in flow_events:
        side = sends if ev["phase"] == "s" else recvs
        if ev["id"] in side:
            unmatched += 1   # duplicate half: keep the first
            continue
        side[ev["id"]] = ev
    edges = []
    for fid, s in sends.items():
        f = recvs.pop(fid, None)
        if f is None:
            unmatched += 1
            continue
        edge = {"id": fid, "name": s["name"],
                "src": s["pid"], "dst": f["pid"],
                "send_ts": s["ts"], "recv_ts": f["ts"],
                "lag_us": f["ts"] - s["ts"]}
        # serve attribution (ISSUE 18): either half may carry the
        # submitting tenant in its args — pre-serve traces have
        # neither, and the key is then simply absent
        tenant = _ev_tenant(s) or _ev_tenant(f)
        if tenant is not None:
            edge["tenant"] = tenant
        edges.append(edge)
    unmatched += len(recvs)
    edges.sort(key=lambda e: e["send_ts"])
    return edges, unmatched


#: slack for "happened at/just before" comparisons: clock-correction
#: residue must not hide a genuinely-binding edge (µs)
_CP_EPS = 1.0


def distributed_critical_path(intervals: List[Interval],
                              edges: List[Dict[str, Any]]
                              ) -> Dict[str, Any]:
    """The cross-rank critical path: a backward walk from the globally
    last-finishing exec interval, at each step following whichever
    constraint BOUND the current node's start — the latest preceding
    exec interval on the same rank, or the latest inbound wire edge
    (then the walk jumps to the sending rank at the send instant).
    The standard last-gap-wins heuristic over distributed traces: it
    needs no DAG capture, only the stitched flow edges."""
    from bisect import bisect_right

    by_end: Dict[int, List[Interval]] = {}
    for iv in intervals:
        if iv.name.startswith("exec:"):
            by_end.setdefault(iv.pid, []).append(iv)
    if not by_end:
        return {"chain": [], "length_us": 0.0, "cross_edges": 0,
                "ranks_visited": []}
    # per rank, two sorted views + their key arrays so every backward
    # step is a bisect, not a scan (merged fleet traces hold 10^5+
    # intervals and the chain can run thousands of steps)
    ends: Dict[int, List[float]] = {}
    by_begin: Dict[int, List[Interval]] = {}
    begins: Dict[int, List[float]] = {}
    for pid, ivs in by_end.items():
        ivs.sort(key=lambda iv: iv.end)
        ends[pid] = [iv.end for iv in ivs]
        bb = sorted(ivs, key=lambda iv: iv.begin)
        by_begin[pid] = bb
        begins[pid] = [iv.begin for iv in bb]
    in_edges: Dict[int, List[Dict[str, Any]]] = {}
    recv_keys: Dict[int, List[float]] = {}
    for e in edges:
        in_edges.setdefault(e["dst"], []).append(e)
    for pid, evs in in_edges.items():
        evs.sort(key=lambda e: e["recv_ts"])
        recv_keys[pid] = [e["recv_ts"] for e in evs]

    def _latest_before(pid: int, t: float,
                       exclude: Optional[Interval]) -> Optional[Interval]:
        ivs = by_end.get(pid, ())
        i = bisect_right(ends.get(pid, ()), t + _CP_EPS) - 1
        while i >= 0 and ivs[i] is exclude:
            i -= 1
        return ivs[i] if i >= 0 else None

    def _containing(pid: int, t: float) -> Optional[Interval]:
        """The interval covering (or most recently started before) t —
        where the sending rank WAS when the edge left."""
        i = bisect_right(begins.get(pid, ()), t + _CP_EPS) - 1
        return by_begin[pid][i] if i >= 0 else None

    cur = max((iv for ivs in by_end.values() for iv in ivs),
              key=lambda iv: iv.end)
    end_ts = cur.end
    chain: List[Dict[str, Any]] = []
    visited = set()
    cross = 0
    while cur is not None and id(cur) not in visited:
        visited.add(id(cur))
        node = {"rank": cur.pid, "name": cur.name,
                "begin_us": cur.begin, "end_us": cur.end,
                "dur_us": cur.duration}
        if isinstance(cur.args, dict) and "task" in cur.args:
            node["task"] = cur.args["task"]
        chain.append(node)
        t = cur.begin
        prev = _latest_before(cur.pid, t, cur)
        edge = None
        evs = in_edges.get(cur.pid, ())
        i = bisect_right(recv_keys.get(cur.pid, ()), t + _CP_EPS) - 1
        if i >= 0:
            edge = evs[i]
        if edge is not None and (prev is None
                                 or edge["recv_ts"] > prev.end):
            # the inbound message is the binding constraint: cross to
            # the sender's timeline at the send instant
            cross += 1
            chain.append({"edge": edge["name"],
                          "link": f"R{edge['src']}->R{edge['dst']}",
                          "send_ts_us": edge["send_ts"],
                          "recv_ts_us": edge["recv_ts"],
                          "lag_us": round(edge["lag_us"], 1)})
            cur = _containing(edge["src"], edge["send_ts"])
            if cur is not None and id(cur) in visited:
                cur = None   # revisit guard: the edge stays as the
                #              chain's (wire-arrival) head
        else:
            cur = prev
    chain.reverse()
    # the path may legitimately BEGIN with a wire edge (no producer
    # interval known at/before the send instant): the send instant is
    # then the path start, so the edge's lag counts toward the length
    start_ts = next((n.get("begin_us", n.get("send_ts_us"))
                     for n in chain), end_ts)
    return {"chain": chain,
            "length_us": end_ts - start_ts,
            "cross_edges": cross,
            "ranks_visited": sorted({n["rank"] for n in chain
                                     if "rank" in n})}


def subtract_intervals(a: List[Tuple[float, float]],
                       b: List[Tuple[float, float]]
                       ) -> List[Tuple[float, float]]:
    """``a \\ b`` for MERGED interval lists: the parts of ``a`` no
    interval of ``b`` covers (the exposed remainder)."""
    out: List[Tuple[float, float]] = []
    j = 0
    for lo, hi in a:
        cur = lo
        while j < len(b) and b[j][1] <= cur:
            j += 1
        k = j
        while k < len(b) and b[k][0] < hi:
            if b[k][0] > cur:
                out.append((cur, b[k][0]))
            cur = max(cur, b[k][1])
            if cur >= hi:
                break
            k += 1
        if cur < hi:
            out.append((cur, hi))
    return out


def per_link_exposed_wait(intervals: List[Interval]
                          ) -> Dict[int, Dict[str, float]]:
    """Per-rank attribution of EXPOSED comm time to named links: each
    comm span whose args carry a peer (``src`` = inbound wait,
    ``dst`` = outbound send) contributes the part of itself no compute
    hid, summed per ``R<src>->R<dst>`` — "rank 2's exposed comm is 78%
    waiting on R0->R2 activations" becomes a table lookup."""
    by_rank: Dict[int, List[Interval]] = {}
    for iv in intervals:
        by_rank.setdefault(iv.pid, []).append(iv)
    out: Dict[int, Dict[str, float]] = {}
    for rank, ivs in by_rank.items():
        compute = merge_intervals([(iv.begin, iv.end) for iv in ivs
                                   if _is_compute(iv)])
        links: Dict[str, float] = {}
        for iv in ivs:
            if not _is_comm(iv) or not isinstance(iv.args, dict):
                continue
            if "src" in iv.args and iv.args["src"] != rank:
                link = f"R{iv.args['src']}->R{rank}"
            elif "dst" in iv.args and iv.args["dst"] != rank:
                link = f"R{rank}->R{iv.args['dst']}"
            else:
                continue
            exposed = iv.duration - overlap_us([(iv.begin, iv.end)],
                                               compute)
            if exposed > 0:
                links[link] = links.get(link, 0.0) + exposed
        out[rank] = {k: round(v, 1) for k, v in
                     sorted(links.items(), key=lambda kv: -kv[1])}
    return out


# ---------------------------------------------------------------------- #
# DOT (grapher output) parsing                                           #
# ---------------------------------------------------------------------- #
_NODE_RE = re.compile(r'^\s*(\w+)\s*\[label="([^"]*)"')
_EDGE_RE = re.compile(r"^\s*(\w+)\s*->\s*(\w+)")


def parse_dot(text: str) -> Tuple[Dict[str, str], List[Tuple[str, str]]]:
    """Returns (node_id -> label, [(src_label, dst_label), ...])."""
    labels: Dict[str, str] = {}
    raw_edges: List[Tuple[str, str]] = []
    for line in text.splitlines():
        if "->" in line:
            m = _EDGE_RE.match(line)
            if m:
                raw_edges.append((m.group(1), m.group(2)))
            continue
        m = _NODE_RE.match(line)
        if m:
            labels[m.group(1)] = m.group(2)
    edges = [(labels.get(a, a), labels.get(b, b)) for a, b in raw_edges]
    return labels, edges


def critical_path(durations: Dict[str, float],
                  edges: List[Tuple[str, str]]) -> Tuple[float, List[str]]:
    """Longest node-weighted path through the DAG. Nodes appearing only
    in ``edges`` default to zero weight. Raises ValueError on a cycle."""
    nodes = set(durations)
    for a, b in edges:
        nodes.update((a, b))
    succs: Dict[str, List[str]] = {n: [] for n in nodes}
    indeg: Dict[str, int] = {n: 0 for n in nodes}
    for a, b in edges:
        succs[a].append(b)
        indeg[b] += 1
    # Kahn topological order
    order: List[str] = [n for n in nodes if indeg[n] == 0]
    i = 0
    while i < len(order):
        for s in succs[order[i]]:
            indeg[s] -= 1
            if indeg[s] == 0:
                order.append(s)
        i += 1
    if len(order) != len(nodes):
        raise ValueError("dependency graph has a cycle")
    dist: Dict[str, float] = {}
    prev: Dict[str, Optional[str]] = {}
    for n in order:
        if n not in dist:
            dist[n] = durations.get(n, 0.0)
            prev[n] = None
        for s in succs[n]:
            cand = dist[n] + durations.get(s, 0.0)
            if cand > dist.get(s, float("-inf")):
                dist[s] = cand
                prev[s] = n
    if not dist:
        return 0.0, []
    tail = max(dist, key=lambda n: dist[n])
    path: List[str] = []
    cur: Optional[str] = tail
    while cur is not None:
        path.append(cur)
        cur = prev[cur]
    return dist[tail], list(reversed(path))


# ---------------------------------------------------------------------- #
# interval algebra                                                       #
# ---------------------------------------------------------------------- #
def merge_intervals(spans: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    """Union of possibly-overlapping (begin, end) pairs."""
    if not spans:
        return []
    spans = sorted(spans)
    out = [list(spans[0])]
    for b, e in spans[1:]:
        if b <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([b, e])
    return [(b, e) for b, e in out]


def overlap_us(a: List[Tuple[float, float]],
               b: List[Tuple[float, float]]) -> float:
    """Total length of the intersection of two merged interval lists."""
    i = j = 0
    total = 0.0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return total


# ---------------------------------------------------------------------- #
# the report                                                             #
# ---------------------------------------------------------------------- #
def _is_compute(iv: Interval) -> bool:
    return iv.name.startswith("exec:")


def _is_comm(iv: Interval) -> bool:
    return iv.name.startswith(("comm:", "dev:xfer"))


def analyze(trace_docs: List[Dict[str, Any]],
            dot_text: Optional[str] = None,
            tenant: Optional[str] = None) -> Dict[str, Any]:
    """Build the full report from one or more rank trace documents
    (already-parsed Chrome JSON) and an optional grapher DOT. Multiple
    per-rank documents are clock-aligned first (``trace_t0_ns`` +
    ``clock_offsets_us`` metadata, 0-shift when absent) so cross-rank
    flow edges stitch on one timeline.

    ``tenant`` (ISSUE 18) narrows the cross-rank section to the flow
    halves a SessionServer attributed to that tenant — the SLO view of
    one customer's traffic through a shared fleet."""
    shifts = rank_clock_shifts(trace_docs)
    intervals: List[Interval] = []
    flow_events: List[Dict[str, Any]] = []
    for i, doc in enumerate(trace_docs):
        intervals.extend(load_trace_intervals(doc, shifts[i]))
        flow_events.extend(load_flow_events(doc, shifts[i]))
    if tenant is not None:
        flow_events = [ev for ev in flow_events
                       if _ev_tenant(ev) == tenant]

    # per-task-class breakdown per rank
    by_class: Dict[int, Dict[str, Dict[str, float]]] = {}
    task_durations: Dict[str, float] = {}
    for iv in intervals:
        if not _is_compute(iv):
            continue
        cls = iv.name[len("exec:"):]
        cell = by_class.setdefault(iv.pid, {}).setdefault(
            cls, {"count": 0, "total_us": 0.0})
        cell["count"] += 1
        cell["total_us"] += iv.duration
        if isinstance(iv.args, dict) and "task" in iv.args:
            # individual executed-task durations keyed by the same
            # printed name the grapher uses as the DOT node label
            task_durations[iv.args["task"]] = (
                task_durations.get(iv.args["task"], 0.0) + iv.duration)
    for cells in by_class.values():
        for cell in cells.values():
            cell["mean_us"] = cell["total_us"] / max(1, cell["count"])

    # T3-style compute/comm overlap per rank
    overlap: Dict[int, Dict[str, float]] = {}
    pids = sorted({iv.pid for iv in intervals})
    for pid in pids:
        rank_ivs = [iv for iv in intervals if iv.pid == pid]
        compute = merge_intervals([(iv.begin, iv.end) for iv in rank_ivs
                                   if _is_compute(iv)])
        comm = merge_intervals([(iv.begin, iv.end) for iv in rank_ivs
                                if _is_comm(iv)])
        comm_us = sum(e - b for b, e in comm)
        comp_us = sum(e - b for b, e in compute)
        hidden = overlap_us(compute, comm)
        # the rank's makespan: the span of everything it did — the
        # denominator that tells whether the EXPOSED comm (the part no
        # compute hid) actually matters for wall time
        makespan = (max(iv.end for iv in rank_ivs)
                    - min(iv.begin for iv in rank_ivs)) if rank_ivs else 0.0
        exposed = comm_us - hidden
        overlap[pid] = {
            "compute_us": comp_us,
            "comm_us": comm_us,
            "overlap_us": hidden,
            # zero-comm ranks report PERFECT overlap (1.0): nothing to
            # hide means nothing exposed — a single-rank run must not
            # trip an overlap gate (tools/obs_report.py --gate-overlap)
            "overlap_fraction": hidden / comm_us if comm_us > 0 else 1.0,
            "exposed_comm_us": exposed,
            "makespan_us": makespan,
            "exposed_share_of_makespan": (exposed / makespan
                                          if makespan > 0 else 0.0),
        }

    report: Dict[str, Any] = {
        "ranks": pids,
        "nb_intervals": len(intervals),
        "by_class": by_class,
        "overlap": overlap,
    }

    if flow_events:
        # cross-rank causal section (ISSUE 15): stitched wire edges,
        # the distributed critical path over them, and the per-link
        # exposed-wait attribution
        edges, unmatched = stitch_flows(flow_events)
        cross = [e for e in edges if e["src"] != e["dst"]]
        by_dir: Dict[str, int] = {}
        neg = 0
        min_lag = None
        for e in cross:
            key = f"R{e['src']}->R{e['dst']}"
            by_dir[key] = by_dir.get(key, 0) + 1
            if e["lag_us"] < 0:
                neg += 1
            min_lag = e["lag_us"] if min_lag is None \
                else min(min_lag, e["lag_us"])
        report["cross_rank"] = {
            "flow_edges": len(cross),
            "edges_per_link": by_dir,
            "unmatched_flows": unmatched,
            "negative_lag_edges": neg,
            "min_lag_us": round(min_lag, 1) if min_lag is not None
            else None,
            "critical_path": distributed_critical_path(intervals, cross),
            "per_link_exposed_us": per_link_exposed_wait(intervals),
        }
        # per-tenant rollups (ISSUE 18): only when some edge carries an
        # attribution — pre-serve traces keep the pre-serve report shape
        tenants = sorted({e["tenant"] for e in edges if "tenant" in e})
        if tenants:
            report["cross_rank"]["per_tenant"] = {
                t: _tenant_rollup(t, intervals, edges) for t in tenants}

    if dot_text:
        _labels, edges = parse_dot(dot_text)
        length, path = critical_path(task_durations, edges)
        total_exec = sum(task_durations.values())
        report["critical_path"] = {
            "length_us": length,
            "tasks": path,
            "nb_tasks": len(path),
            "total_exec_us": total_exec,
            # >1 means the DAG has exploitable parallelism
            "parallelism": total_exec / length if length > 0 else 0.0,
        }
    return report


def _tenant_rollup(tenant: str, intervals: List[Interval],
                   edges: List[Dict[str, Any]]) -> Dict[str, Any]:
    """One tenant's slice of the cross-rank view: its wire edges, the
    distributed critical path constrained to THOSE edges, and the
    exposed wait of its attributed comm spans."""
    own = [e for e in edges if e.get("tenant") == tenant]
    cross = [e for e in own if e["src"] != e["dst"]]
    lags = sorted(e["lag_us"] for e in cross)
    dcp = distributed_critical_path(intervals, cross) if cross else None
    # exposed wait of this tenant's attributed comm spans only
    own_comm = [iv for iv in intervals
                if _ev_tenant({"args": iv.args}) == tenant]
    exposed = per_link_exposed_wait(
        own_comm + [iv for iv in intervals if _is_compute(iv)])
    exposed_us = round(sum(us for links in exposed.values()
                           for us in links.values()), 1)
    out: Dict[str, Any] = {
        "flow_edges": len(cross),
        "lag_us_mean": round(sum(lags) / len(lags), 1) if lags else 0.0,
        "lag_us_max": round(lags[-1], 1) if lags else 0.0,
        "exposed_wait_us": exposed_us,
    }
    if dcp is not None:
        out["critical_path_us"] = round(dcp["length_us"], 1)
        out["critical_path_cross_edges"] = dcp["cross_edges"]
    return out


def format_report(report: Dict[str, Any]) -> str:
    """Human-readable rendering (what tools/obs_report.py prints)."""
    out: List[str] = []
    cp = report.get("critical_path")
    if cp is not None:
        out.append(f"critical path: {cp['length_us'] / 1e3:.3f} ms over "
                   f"{cp['nb_tasks']} tasks "
                   f"(total exec {cp['total_exec_us'] / 1e3:.3f} ms, "
                   f"parallelism {cp['parallelism']:.2f}x)")
        if cp["tasks"]:
            chain = " -> ".join(cp["tasks"][:8])
            if cp["nb_tasks"] > 8:
                chain += " -> ..."
            out.append(f"  chain: {chain}")
    out.append("per-task-class breakdown:")
    for pid in sorted(report.get("by_class", {})):
        for cls, cell in sorted(report["by_class"][pid].items()):
            out.append(f"  rank {pid} {cls:<20} n={int(cell['count']):<6} "
                       f"total={cell['total_us'] / 1e3:.3f} ms "
                       f"mean={cell['mean_us']:.1f} us")
    out.append("compute/comm overlap per rank:")
    for pid in sorted(report.get("overlap", {})):
        ov = report["overlap"][pid]
        out.append(f"  rank {pid}: compute={ov['compute_us'] / 1e3:.3f} ms "
                   f"comm={ov['comm_us'] / 1e3:.3f} ms "
                   f"overlap fraction={ov['overlap_fraction']:.3f} "
                   f"exposed={ov.get('exposed_comm_us', 0.0) / 1e3:.3f} ms "
                   f"({ov.get('exposed_share_of_makespan', 0.0):.1%} of "
                   f"makespan)")
    cr = report.get("cross_rank")
    if cr is not None:
        out.append(f"cross-rank flow edges: {cr['flow_edges']} "
                   f"({cr['unmatched_flows']} unmatched, "
                   f"{cr['negative_lag_edges']} negative-lag) per link: "
                   + (", ".join(f"{k}={v}" for k, v in
                                sorted(cr["edges_per_link"].items()))
                      or "none"))
        dcp = cr["critical_path"]
        out.append(f"distributed critical path: "
                   f"{dcp['length_us'] / 1e3:.3f} ms crossing "
                   f"{dcp['cross_edges']} wire edge(s) over ranks "
                   f"{dcp['ranks_visited']}")
        steps = []
        for n in dcp["chain"][:12]:
            if "link" in n:
                steps.append(f"={n['link']}=>")
            else:
                steps.append(n.get("task") or n["name"])
        if steps:
            out.append("  chain: " + " ".join(steps)
                       + (" ..." if len(dcp["chain"]) > 12 else ""))
        out.append("exposed wait per link (µs of un-hidden comm, "
                   "by rank):")
        for rank in sorted(cr["per_link_exposed_us"]):
            links = cr["per_link_exposed_us"][rank]
            total = sum(links.values())
            if not links:
                out.append(f"  rank {rank}: none")
                continue
            parts = ", ".join(
                f"{lk}={us:.0f} ({us / total:.0%})"
                for lk, us in links.items())
            out.append(f"  rank {rank}: {parts}")
        tenants = cr.get("per_tenant") or {}
        if tenants:
            out.append("per-tenant cross-rank rollup:")
            for t in sorted(tenants):
                cell = tenants[t]
                line = (f"  tenant {t:<10} edges={cell['flow_edges']} "
                        f"lag mean/max={cell['lag_us_mean']:.0f}/"
                        f"{cell['lag_us_max']:.0f} us "
                        f"exposed={cell['exposed_wait_us']:.0f} us")
                if "critical_path_us" in cell:
                    cp_ms = cell["critical_path_us"] / 1e3
                    line += (f" critpath={cp_ms:.3f} ms"
                             f" ({cell['critical_path_cross_edges']} wire"
                             f" edge(s))")
                out.append(line)
    return "\n".join(out)
