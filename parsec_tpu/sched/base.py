"""Scheduler module interface (MCA framework ``sched``).

Reference behavior: pluggable policy modules with
``{install, flow_init(per-ES), schedule(es, ring, distance), select(es),
remove}`` (ref: parsec/mca/sched/sched.h;
parsec/mca/sched/lfq/sched_lfq_module.c:39-49), selected at runtime by MCA
parameter ``sched`` (default lfq).
"""
from __future__ import annotations

from typing import Any, List, Optional


class SchedulerModule:
    name = "base"

    def install(self, context) -> None:
        self.context = context

    def flow_init(self, es) -> None:
        """Set up per-execution-stream queues (es.sched_obj)."""

    def schedule(self, es, tasks: List, distance: int = 0) -> None:
        raise NotImplementedError

    def select(self, es) -> Optional[Any]:
        raise NotImplementedError

    def remove(self, context) -> None:
        for es in context.execution_streams:
            es.sched_obj = None

    # PAPI-SDE-style introspection (ref: sched_lfq_module.c:141-151)
    def pending_tasks(self, context) -> int:
        return -1
