"""OTF2 trace export — the profiling_otf2.c analog.

Reference behavior: an alternative trace backend that writes OTF2
archives directly instead of the dbp binary format, mapping per-thread
event streams to OTF2 locations, event classes to OTF2 regions, and
counter samples to OTF2 metrics (ref: parsec/profiling_otf2.c:1-1247;
selected at build time by PARSEC_PROF_TRACE_SYSTEM=otf2).

TPU-native re-design: export is offline (any in-memory or .ptt Profile
can be converted after the run — no build-time switch needed). When the
real ``otf2`` Python bindings are installed, they are used and the
archive is readable by otf2-print/Vampir. Without them (this
environment), the fallback writer below produces an archive with the
same *structure* — an anchor file plus a trace directory holding one
global-definitions file and one event file per location, ULEB128-
compressed records with delta-encoded timestamps, which is OTF2's
storage scheme — validated by the matching reader in this module.

Record vocabulary (subset):

  global defs:  STRING(id, utf8)  CLOCK(resolution, t0)
                LOCATION_GROUP(id, name_ref, rank)
                LOCATION(id, name_ref, group_ref, nb_events, tid)
                REGION(id, name_ref)  METRIC(id, name_ref)
  events:       ENTER(dt, region)  LEAVE(dt, region)
                METRIC_SAMPLE(dt, metric, f64)  MARKER(dt, region)

All integers are ULEB128 varints except the METRIC_SAMPLE value (f64 LE).
Timestamps are nanosecond deltas from the previous event in the same
location (first event: delta from the clock t0), OTF2's timestamp
compression model.
"""
from __future__ import annotations

import json
import os
import struct
from typing import Any, BinaryIO, Dict, List, Tuple

ANCHOR_MAGIC = b"OTF2-LITE\n"
FORMAT_VERSION = 1

# record type tags
DEF_STRING = 0x01
DEF_CLOCK = 0x02
DEF_LOCATION_GROUP = 0x03
DEF_LOCATION = 0x04
DEF_REGION = 0x05
DEF_METRIC = 0x06
EVT_ENTER = 0x10
EVT_LEAVE = 0x11
EVT_METRIC = 0x12
EVT_MARKER = 0x13


def _w_uleb(fh: BinaryIO, v: int) -> None:
    if v < 0:
        raise ValueError("uleb128 encodes unsigned values only")
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            fh.write(bytes((b | 0x80,)))
        else:
            fh.write(bytes((b,)))
            return


def _r_uleb(fh: BinaryIO) -> int:
    shift = 0
    out = 0
    while True:
        raw = fh.read(1)
        if not raw:
            raise EOFError("truncated varint")
        b = raw[0]
        out |= (b & 0x7F) << shift
        if not (b & 0x80):
            return out
        shift += 7


class _StringTable:
    def __init__(self) -> None:
        self._ids: Dict[str, int] = {}
        self.strings: List[str] = []

    def ref(self, s: str) -> int:
        sid = self._ids.get(s)
        if sid is None:
            sid = len(self.strings)
            self._ids[s] = sid
            self.strings.append(s)
        return sid


def _have_real_otf2() -> bool:
    try:
        import otf2  # noqa: F401
        return True
    except ImportError:
        return False


def _normalized_events(st):
    """Expand complete ("X") spans — comm/device telemetry — into B/E
    pairs and sort by timestamp: OTF2 event streams are strictly
    time-ordered, while X events are appended at completion carrying
    begin timestamps in the past."""
    out = []
    for ts, ph, key, info in st.events:
        if ph == "X":
            dur = (info or {}).get("dur_ns", 0)
            out.append((ts, "B", key, None))
            out.append((ts + dur, "E", key, None))
        else:
            out.append((ts, ph, key, info))
    out.sort(key=lambda e: e[0])
    return out


def write_otf2(profile, path: str) -> str:
    """Write ``profile`` as an OTF2 archive rooted at ``path`` (a
    directory name). Returns the anchor path — ``<path>/anchor.otf2``
    for the structural fallback format, ``<path>/traces.otf2`` when the
    real otf2 bindings are importable; either return value feeds
    straight back into :func:`read_otf2`."""
    if _have_real_otf2():  # pragma: no cover - bindings absent in CI image
        return _write_real_otf2(profile, path)
    os.makedirs(os.path.join(path, "traces"), exist_ok=True)
    strings = _StringTable()
    streams = sorted(profile._streams.items())

    # regions/metrics discovered from the event streams
    region_ids: Dict[str, int] = {}
    metric_ids: Dict[str, int] = {}
    for _tid, st in streams:
        for _ts, ph, key, _info in st.events:
            if ph == "C":
                metric_ids.setdefault(key, len(metric_ids))
            else:
                region_ids.setdefault(key, len(region_ids))

    # one event file per location (= per thread stream)
    for loc_id, (tid, st) in enumerate(streams):
        with open(os.path.join(path, "traces", f"{loc_id}.evt"), "wb") as fh:
            prev_ts = 0
            for ts, ph, key, info in _normalized_events(st):
                rel = ts - profile._t0
                dt = rel - prev_ts
                prev_ts = rel
                if ph == "C":
                    fh.write(bytes((EVT_METRIC,)))
                    _w_uleb(fh, dt)
                    _w_uleb(fh, metric_ids[key])
                    fh.write(struct.pack("<d", float(info)))
                elif ph == "B":
                    fh.write(bytes((EVT_ENTER,)))
                    _w_uleb(fh, dt)
                    _w_uleb(fh, region_ids[key])
                elif ph == "E":
                    fh.write(bytes((EVT_LEAVE,)))
                    _w_uleb(fh, dt)
                    _w_uleb(fh, region_ids[key])
                else:
                    fh.write(bytes((EVT_MARKER,)))
                    _w_uleb(fh, dt)
                    _w_uleb(fh, region_ids[key])

    # global definitions
    group_name = strings.ref(f"rank {profile.rank}")
    loc_names = [strings.ref(st.name) for _tid, st in streams]
    region_names = {rid: strings.ref(name) for name, rid in region_ids.items()}
    metric_names = {mid: strings.ref(name) for name, mid in metric_ids.items()}
    with open(os.path.join(path, "traces", "global.def"), "wb") as fh:
        for s in strings.strings:
            sb = s.encode()
            fh.write(bytes((DEF_STRING,)))
            _w_uleb(fh, len(sb))
            fh.write(sb)
        fh.write(bytes((DEF_CLOCK,)))
        _w_uleb(fh, 1_000_000_000)  # ns resolution
        _w_uleb(fh, 0)
        fh.write(bytes((DEF_LOCATION_GROUP,)))
        _w_uleb(fh, 0)
        _w_uleb(fh, group_name)
        _w_uleb(fh, profile.rank)
        for loc_id, (tid, st) in enumerate(streams):
            fh.write(bytes((DEF_LOCATION,)))
            _w_uleb(fh, loc_id)
            _w_uleb(fh, loc_names[loc_id])
            _w_uleb(fh, 0)
            _w_uleb(fh, len(st.events))
            _w_uleb(fh, tid)  # original stream id, for exact round-trip
        for rid in range(len(region_ids)):
            fh.write(bytes((DEF_REGION,)))
            _w_uleb(fh, rid)
            _w_uleb(fh, region_names[rid])
        for mid in range(len(metric_ids)):
            fh.write(bytes((DEF_METRIC,)))
            _w_uleb(fh, mid)
            _w_uleb(fh, metric_names[mid])

    anchor = os.path.join(path, "anchor.otf2")
    with open(anchor, "wb") as fh:
        fh.write(ANCHOR_MAGIC)
        meta = json.dumps({
            "version": FORMAT_VERSION,
            "writer": "parsec_tpu (otf2-lite fallback)",
            "rank": profile.rank,
            "num_locations": len(streams),
            "info": profile.info,
        }).encode()
        fh.write(struct.pack("<I", len(meta)))
        fh.write(meta)
    return anchor


def _write_real_otf2(profile, path: str) -> str:  # pragma: no cover
    import otf2
    from otf2.enums import RegionRole, Paradigm

    timer_res = 1_000_000_000
    with otf2.writer.open(path, timer_resolution=timer_res) as trace:
        root = trace.definitions.system_tree_node("node")
        group = trace.definitions.location_group(
            f"rank {profile.rank}", system_tree_parent=root)
        regions: Dict[str, Any] = {}
        metrics: Dict[str, Any] = {}
        for _tid, st in sorted(profile._streams.items()):
            writer = trace.event_writer(st.name, group=group)
            for ts, ph, key, info in _normalized_events(st):
                rel = ts - profile._t0
                if ph == "C":
                    m = metrics.get(key)
                    if m is None:
                        m = trace.definitions.metric(key, unit="#")
                        metrics[key] = m
                    writer.metric(rel, m, float(info))
                    continue
                r = regions.get(key)
                if r is None:
                    r = trace.definitions.region(
                        key, source_file="parsec_tpu",
                        region_role=RegionRole.TASK,
                        paradigm=Paradigm.USER)
                    regions[key] = r
                if ph == "B":
                    writer.enter(rel, r)
                elif ph == "E":
                    writer.leave(rel, r)
                else:
                    # OTF2 has no punctual event; a zero-length
                    # enter/leave pair preserves markers
                    writer.enter(rel, r)
                    writer.leave(rel, r)
    return os.path.join(path, "traces.otf2")


def _read_real_otf2(root: str):  # pragma: no cover - bindings absent in CI
    import otf2
    from .trace import Profile

    prof = Profile(rank=0)
    prof._t0 = 0
    with otf2.reader.open(os.path.join(root, "traces.otf2")) as trace:
        loc_ids: Dict[Any, int] = {}
        for location, event in trace.events:
            tid = loc_ids.setdefault(location, len(loc_ids))
            st = prof.stream(tid, str(getattr(location, "name", tid)))
            cls = type(event).__name__
            if cls == "Enter":
                st.events.append((event.time, "B", event.region.name, None))
            elif cls == "Leave":
                st.events.append((event.time, "E", event.region.name, None))
            elif cls == "Metric":
                st.events.append((event.time, "C",
                                  event.metric.members[0].name,
                                  float(event.values[0])))
    return prof


def read_otf2(path: str):
    """Read a fallback-format archive back into a profiling Profile
    (round-trip validation; timestamps re-based at 0)."""
    from .trace import Profile

    anchor = path if path.endswith(".otf2") else os.path.join(path, "anchor.otf2")
    root = os.path.dirname(anchor)
    if (not os.path.exists(anchor) or not anchor.endswith("anchor.otf2")) \
            and os.path.exists(os.path.join(root, "traces.otf2")):
        # a real OTF2 archive (bindings were installed at write time,
        # anchor is traces.otf2): read it back through the bindings too
        return _read_real_otf2(root)  # pragma: no cover
    with open(anchor, "rb") as fh:
        if fh.read(len(ANCHOR_MAGIC)) != ANCHOR_MAGIC:
            raise ValueError(f"{anchor}: not an otf2-lite anchor")
        (mlen,) = struct.unpack("<I", fh.read(4))
        meta = json.loads(fh.read(mlen).decode())
    if meta.get("version") != FORMAT_VERSION:
        raise ValueError(f"unsupported otf2-lite version {meta.get('version')}")

    strings: List[str] = []
    locations: List[Tuple[int, int, int, int]] = []  # (loc_id, name_ref, nb_events, tid)
    regions: Dict[int, int] = {}
    metrics: Dict[int, int] = {}
    with open(os.path.join(root, "traces", "global.def"), "rb") as fh:
        while True:
            tag_raw = fh.read(1)
            if not tag_raw:
                break
            tag = tag_raw[0]
            if tag == DEF_STRING:
                n = _r_uleb(fh)
                strings.append(fh.read(n).decode())
            elif tag == DEF_CLOCK:
                _r_uleb(fh)
                _r_uleb(fh)
            elif tag == DEF_LOCATION_GROUP:
                _r_uleb(fh)
                _r_uleb(fh)
                _r_uleb(fh)
            elif tag == DEF_LOCATION:
                loc_id = _r_uleb(fh)
                name_ref = _r_uleb(fh)
                _r_uleb(fh)  # group ref
                nb = _r_uleb(fh)
                tid = _r_uleb(fh)
                locations.append((loc_id, name_ref, nb, tid))
            elif tag == DEF_REGION:
                rid = _r_uleb(fh)
                regions[rid] = _r_uleb(fh)
            elif tag == DEF_METRIC:
                mid = _r_uleb(fh)
                metrics[mid] = _r_uleb(fh)
            else:
                raise ValueError(f"unknown def record tag {tag:#x}")

    prof = Profile(rank=meta.get("rank", 0), info=meta.get("info"))
    prof._t0 = 0
    for loc_id, name_ref, nb, tid in locations:
        st = prof.stream(tid, strings[name_ref])
        with open(os.path.join(root, "traces", f"{loc_id}.evt"), "rb") as fh:
            ts = 0
            for _ in range(nb):
                tag = fh.read(1)[0]
                ts += _r_uleb(fh)
                if tag == EVT_METRIC:
                    mid = _r_uleb(fh)
                    (val,) = struct.unpack("<d", fh.read(8))
                    st.events.append((ts, "C", strings[metrics[mid]], val))
                elif tag in (EVT_ENTER, EVT_LEAVE, EVT_MARKER):
                    rid = _r_uleb(fh)
                    ph = {EVT_ENTER: "B", EVT_LEAVE: "E", EVT_MARKER: "i"}[tag]
                    st.events.append((ts, ph, strings[regions[rid]], None))
                else:
                    raise ValueError(f"unknown event record tag {tag:#x}")
    return prof
