"""Runtime integration: execute compiled stages as single chores
interleaved with the interpreted residue (ISSUE 12 tentpole, part 4).

A :class:`StageCompiler` attaches to a ``PTGTaskpool`` at startup when
the ``stage_compile`` MCA knob is on.  Each compilable stage becomes
ONE synthetic task on the ordinary runtime: its flows are the stage's
packed buffer slots, its chore is the fused jitted callable (or the
shard_map-compiled wave-front variant on a mesh device), and it rides
the untouched scheduler / device-module / eager-completion machinery —
stage-in, HBM accounting, donation guards, priority stamping and the
PR 7 eager-release window all apply to a stage exactly as they do to a
single task, which is what lets a compiled stage's cross-rank sends
overlap its own execution.

Dynamic dependency tracking for stages piggybacks on the existing
activation protocol: ``PTGTaskClass.activate`` consults the compiler
first (``on_activate``), so activations from local residue tasks,
other stages, AND remote ranks all count toward a stage's external
goal without any wire-format change; when the counter hits zero the
stage task spawns (its fused callable AOT-validated right there) and
is scheduled like any ready task.  On completion the stage's release
walk reuses each member's untouched ``_release_deps`` — remote
activations batch per rank, memory writebacks ride the device epilog —
with intra-stage edges swallowed by the same ``on_activate`` seam.

Fallback ladder (semantics are never at risk):

1. a class the lowerability pass rejects stays interpreted (residue);
2. a stage whose fused trace fails at spawn DOWNGRADES — its buffered
   activations replay through the normal dynamic path and its members
   execute via the PR 5/7 batched dispatch, permanently but only for
   that stage (the failure is cached, other stages keep compiling);
3. a sharded (mesh) build/dispatch failure falls back to the fused
   single-chip callable for that stage;
4. ``stage_compile`` unset: ``tp._stagec`` is None and behavior is
   bit-for-bit the pre-stagec runtime.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..data.data import Coherency, Data, DataCopy, FlowAccess
from ..runtime.taskpool import (ACTION_RELEASE_ALL, Chore, Flow, Task,
                                TaskClass)
from ..utils import logging as plog
from ..utils.params import params
from .lower import (StageLayout, build_layout, build_stage_fn,
                    spec_token, stage_signature)
from .plan import StagePlan, plan_stages

#: declared lock discipline (analysis/lock_check.py): a stage record's
#: dependency counter, buffered activation events, and lifecycle status
#: are mutated from worker threads AND the comm delivery path — every
#: access goes through the record's own lock.  ``edge_copies`` is
#: single-owner by lifecycle (written by the dispatching manager, read
#: by the completing worker's release walk, ordered by the task
#: lifecycle) and deliberately unregistered.
_GUARDED_BY = {
    "_StageRec.remaining": "_lock",
    "_StageRec.events": "_lock",
    "_StageRec.status": "_lock",
}

# _StageRec lifecycle
_PENDING, _SPAWNED, _DONE, _DOWNGRADED = range(4)

#: cache sentinel: a stage signature whose build already failed —
#: the next taskpool over the same spec downgrades instantly instead
#: of re-tracing the known failure ("permanent, but only for that
#: stage")
_FAILED = object()


class _StageRec:
    """One stage's dynamic state on one taskpool."""

    def __init__(self, stage, layout: StageLayout, priority: int) -> None:
        self.stage = stage
        self.layout = layout
        self.priority = priority
        self._lock = threading.Lock()
        self.remaining = layout.goal
        self.events: List[Tuple] = []   # (member_key, flow, copy) buffered
        self.status = _PENDING
        self.fn = None                  # fused jitted callable
        self.sharded = None             # (fn, sharding, info) or None
        self.task: Optional[Task] = None
        self.edge_copies: Dict[Tuple, Any] = {}
        self.shapes: Tuple = ()
        self.donate: Tuple = ()


class StageTaskClass(TaskClass):
    """The synthetic task class of ONE compiled stage: flows are the
    stage's packed buffer slots.  Never registered on the taskpool's
    ``task_classes`` (remote activation ids index that list), so the
    wire protocol is untouched."""

    def __init__(self, compiler: "StageCompiler", rec: _StageRec) -> None:
        lay = rec.layout
        flows: List[Flow] = []
        for i, ((coll, coords), access) in enumerate(lay.mem_slots):
            flows.append(Flow(f"{coll}{coords}", access, i))
        base = len(lay.mem_slots)
        for j, (mkey, fname) in enumerate(lay.act_slots):
            flows.append(Flow(f"{mkey[0]}{mkey[1]}.{fname}",
                              FlowAccess.READ, base + j))
        super().__init__(f"STAGE{rec.stage.index}[{compiler.tp.name}]",
                         -1 - rec.stage.index, len(flows), flows=flows)
        from ..devices.tpu import tpu_chore_hook
        self.incarnations = [Chore("tpu", tpu_chore_hook(),
                                   dyld_fn=compiler._make_dyld(rec))]
        self.release_deps = \
            lambda es, task, mask, c=compiler, r=rec: c._release(es, r)
        # one stage completion retires every member task's count (the
        # final unit comes from complete_execution's own decrement)
        n = rec.stage.n_tasks
        if n > 1:
            self.complete_execution = \
                lambda es, task, tp=compiler.tp: tp.task_completed(n - 1)


class StageCompiler:
    """Per-taskpool stage-compile engine (``tp._stagec``)."""

    def __init__(self, tp, context, plan: StagePlan) -> None:
        self.tp = tp
        self.context = context
        self.plan = plan
        self.stats = context.stage_stats
        from ..dsl.ptg.capture import _pick_body
        self._codes = {
            tc.ast.name: compile(_pick_body(tc.ast).code,
                                 f"<jdf:{tc.ast.name}:BODY[stagec]>",
                                 "exec")
            for tc in tp.task_classes}
        self._token = spec_token(tp)
        self._donate_on = bool(params.get("device_donate"))
        # the mesh device, when this rank's accelerator is one (PR 6):
        # wave-front stages then compile through shard_map over it
        self._mesh_dev = next(
            (d for d in context.devices
             if d.device_type == "tpu" and getattr(d, "mesh", None)
             is not None and len(getattr(d, "chips", ())) > 1), None)
        self._recs: List[_StageRec] = []
        self._member_rec: Dict[Tuple, _StageRec] = {}
        for stage, layout, prio in plan.prepared:
            rec = _StageRec(stage, layout, prio)
            self._recs.append(rec)
            for m in stage.members:
                self._member_rec[m.key] = rec

    def _tc(self, inst):
        """The LIVE taskpool's class for a (possibly cached-plan)
        instance: plans are cached per spec token across taskpools, so
        ``inst.tc`` may belong to an earlier pool — every runtime
        action rebinds by name."""
        return self.tp.class_by_name(inst.tc.ast.name)

    # ------------------------------------------------------------------ #
    # dependency tracking: the activate redirect                         #
    # ------------------------------------------------------------------ #
    def on_activate(self, tc, locals_: Tuple, flow_name: str,
                    copy) -> Tuple[bool, Optional[Task]]:
        """Called by ``PTGTaskClass.activate`` before its own dynamic
        dep table.  Returns ``(handled, ready_task)``; handled=False
        passes through to the interpreted path (non-members and
        downgraded stages)."""
        rec = self._member_rec.get((tc.ast.name, locals_))
        if rec is None:
            return False, None
        spawn = False
        with rec._lock:
            if rec.status == _DOWNGRADED:
                return False, None
            if rec.status != _PENDING:
                # an intra-stage edge emitted by the release walk of
                # this very stage: already computed inside the fused
                # program — swallow
                return True, None
            rec.events.append(((tc.ast.name, locals_), flow_name, copy))
            rec.remaining -= 1
            assert rec.remaining >= 0, \
                f"{tc.ast.name}{locals_}: stage overshoot"
            if rec.remaining == 0:
                rec.status = _SPAWNED   # claim; build outside the lock
                spawn = True
        if not spawn:
            return True, None
        tasks = self._spawn(rec)
        if not tasks:
            return True, None
        if len(tasks) > 1:
            from ..runtime.scheduling import schedule
            schedule(self.context.execution_streams[0], tasks[1:])
        return True, tasks[0]

    def startup_tasks(self) -> List[Task]:
        """Stages with no external task inputs are startup tasks."""
        out: List[Task] = []
        for rec in self._recs:
            with rec._lock:
                if rec.status != _PENDING or rec.remaining > 0:
                    continue
                rec.status = _SPAWNED
            out.extend(self._spawn(rec))
        return out

    def is_member(self, class_name: str, locals_: Tuple) -> bool:
        rec = self._member_rec.get((class_name, locals_))
        if rec is None:
            return False
        with rec._lock:
            return rec.status != _DOWNGRADED

    # ------------------------------------------------------------------ #
    # spawn: AOT-validate the fused callable, bind slots, emit the task  #
    # ------------------------------------------------------------------ #
    def _spawn(self, rec: _StageRec) -> List[Task]:
        try:
            return [self._make_stage_task(rec)]
        except Exception as exc:  # noqa: BLE001 - any failure interprets
            plog.warning(
                "stagec: stage %d of %s failed to lower (%s: %s); its %d "
                "member task(s) run interpreted",
                rec.stage.index, self.tp.name, type(exc).__name__,
                str(exc)[:200], rec.stage.n_tasks)
            return self._downgrade(rec)

    def _slot_shapes(self, rec: _StageRec, bindings: Dict) -> Tuple:
        shapes = []
        for (coll_name, coords), _access in rec.layout.mem_slots:
            coll = self.tp.global_env[coll_name]
            data = coll.data_of(*coords)
            newest = data.newest_copy()
            if newest is not None and newest.payload is not None:
                shapes.append((tuple(newest.payload.shape),
                               str(newest.payload.dtype)))
            else:
                shapes.append((tuple(coll.tile_shape(*coords)),
                               str(np.dtype(coll.dtype))))
        for ak in rec.layout.act_slots:
            cp = bindings.get(ak)
            if cp is None or cp.payload is None:
                raise RuntimeError(
                    f"activation slot {ak} bound no payload")
            shapes.append((tuple(cp.payload.shape),
                           str(cp.payload.dtype)))
        return tuple(shapes)

    def _lowered(self, rec: _StageRec, donate: Tuple) -> Any:
        """The AOT-cached fused callable for this stage signature —
        alongside the bucket cache (devices/batching.py); a repeat
        taskpool over the same spec/NB/dtype hits it without
        re-tracing.  A cached failure re-raises instantly."""
        import jax
        from ..devices.batching import cached_stage_callable

        key = stage_signature(rec.stage, rec.shapes) + (donate, "fused")

        def build():
            t0 = time.perf_counter_ns()
            run = build_stage_fn(self.tp, rec.stage, rec.layout,
                                 self._codes)
            fn = jax.jit(run, donate_argnums=donate)
            # force the trace NOW: untraceable bodies must downgrade at
            # spawn, not poison the device dispatch path
            avals = tuple(jax.ShapeDtypeStruct(s, np.dtype(d))
                          for (s, d) in rec.shapes)
            jax.eval_shape(run, *avals)
            dt = time.perf_counter_ns() - t0
            self.stats["stage_compiles"] += 1
            self.stats["stage_compile_ns"] += dt
            return fn

        fn = cached_stage_callable(self._token, key, build)
        if fn is _FAILED:
            raise RuntimeError("stage lowering previously failed "
                               "(cached verdict)")
        return fn

    def _make_stage_task(self, rec: _StageRec) -> Task:
        with rec._lock:
            events = list(rec.events)
        bindings: Dict[Tuple, Any] = {}
        for (mkey, fname, copy) in events:
            if copy is not None:
                bindings[(mkey, fname)] = copy
        rec.shapes = self._slot_shapes(rec, bindings)
        rec.donate = tuple(
            i for i, (_k, acc) in enumerate(rec.layout.mem_slots)
            if self._donate_on and (acc & FlowAccess.WRITE))
        from ..devices.batching import cached_stage_callable
        try:
            rec.fn = self._lowered(rec, rec.donate)
        except Exception:
            # record the verdict so the next taskpool over the same
            # spec downgrades this stage instantly (permanent, but
            # only for this stage)
            cached_stage_callable(
                self._token,
                stage_signature(rec.stage, rec.shapes)
                + (rec.donate, "fused"),
                lambda: _FAILED)
            raise
        if self._mesh_dev is not None \
                and params.get("stage_compile_shard"):
            rec.sharded = self._try_sharded(rec)
        tc = StageTaskClass(self, rec)
        task = Task(self.tp, tc, locals_=(rec.stage.index,),
                    priority=rec.priority)
        task.user = rec
        for i, ((coll_name, coords), _a) in enumerate(rec.layout.mem_slots):
            coll = self.tp.global_env[coll_name]
            task.data[i].data_in = coll.data_of(*coords).host_copy()
            task.data[i].fulfilled = True
        base = len(rec.layout.mem_slots)
        for j, ak in enumerate(rec.layout.act_slots):
            task.data[base + j].data_in = bindings[ak]
            task.data[base + j].fulfilled = True
        rec.task = task
        return task

    def _try_sharded(self, rec: _StageRec):
        """Wave-front stages on a mesh rank compile through shard_map
        over the rank's chips (stagec/sharded.py); any failure keeps
        the fused single-chip callable."""
        from .sharded import build_wavefront_callable, wavefront_info
        dev = self._mesh_dev
        k = len(dev.chips)
        n = rec.stage.n_tasks
        if n < k or n % k:
            return None
        try:
            info = wavefront_info(self.tp, rec.stage, rec.layout,
                                  self._codes)
            if info is None:
                return None
            row_shapes = tuple(
                rec.shapes[info.arg_slots[0][j]] for j in range(info.nargs))
            from ..devices.batching import cached_stage_callable
            key = stage_signature(rec.stage, rec.shapes) + \
                ("sharded", dev.mesh)

            def build():
                t0 = time.perf_counter_ns()
                fn_sh = build_wavefront_callable(dev.mesh, info,
                                                 self.tp.rank, row_shapes)
                self.stats["stage_compiles"] += 1
                self.stats["stage_compile_ns"] += \
                    time.perf_counter_ns() - t0
                return fn_sh

            fn, sharding = cached_stage_callable(self._token, key, build)
            return (fn, sharding, info)
        except Exception as exc:  # noqa: BLE001 - fused path stands by
            plog.debug.verbose(
                2, "stagec: sharded lowering of stage %d declined (%s); "
                "fused single-chip callable", rec.stage.index, exc)
            return None

    # ------------------------------------------------------------------ #
    # downgrade: replay into the interpreted dynamic path                #
    # ------------------------------------------------------------------ #
    def _downgrade(self, rec: _StageRec) -> List[Task]:
        """Transparent per-stage fallback: buffered external
        activations replay through the normal per-class dep tables and
        the members execute via the interpreted (batched, PR 5/7)
        dispatch.  Permanent only for this stage — other stages keep
        their compiled path."""
        with rec._lock:
            rec.status = _DOWNGRADED
            events, rec.events = rec.events, []
        self.stats["stage_fallbacks"] += 1
        ready: List[Task] = []
        for inst in rec.stage.members:
            tc = self._tc(inst)
            if tc.goal_of(inst.locals) == 0:
                ready.append(tc.make_task(inst.locals, None))
        for (mkey, fname, copy) in events:
            tc = self.tp.class_by_name(mkey[0])
            t = tc.activate(mkey[1], fname, copy)
            if t is not None:
                ready.append(t)
        return ready

    # ------------------------------------------------------------------ #
    # execution: the stage chore                                         #
    # ------------------------------------------------------------------ #
    def _make_dyld(self, rec: _StageRec):
        def dyld(task: Task, arrays: List[Any]):
            return self._execute_stage(task, rec, arrays)
        return dyld

    def _execute_stage(self, task: Task, rec: _StageRec,
                       arrays: List[Any]):
        lay = rec.layout
        tile_outs = edge_outs = None
        if rec.sharded is not None:
            from .sharded import dispatch_sharded
            fn, sharding, info = rec.sharded
            try:
                tile_outs, edge_outs = dispatch_sharded(
                    self._mesh_dev, fn, sharding, info, arrays)
                self.stats["stage_sharded"] += 1
            except Exception as exc:  # noqa: BLE001 - fused fallback
                plog.warning(
                    "stagec: sharded dispatch of stage %d failed (%s); "
                    "fused single-chip dispatch", rec.stage.index, exc)
                rec.sharded = None
                tile_outs = None
        if tile_outs is None:
            fn = rec.fn
            if rec.donate and len({id(a) for a in arrays}) != len(arrays):
                # the same buffer at two slots: donation would trip
                # XLA's aliasing rule — use the undonated variant
                fn = self._lowered(rec, ())
            outs = fn(*arrays)
            ntile = len(lay.out_mem)
            tile_outs, edge_outs = list(outs[:ntile]), list(outs[ntile:])
        dev = task.selected_device
        for ek, arr in zip(lay.edge_outs, edge_outs):
            if arr is None:
                continue   # a NULL-forwarded flow: successors bind None
            rec.edge_copies[ek] = _edge_copy(arr)
        self.stats["stage_dispatches"] += 1
        self.stats["stage_tasks"] += rec.stage.n_tasks
        if dev is not None:
            dev.stats["tasks"] += rec.stage.n_tasks - 1  # +1 from epilog
        return tuple(tile_outs)

    # ------------------------------------------------------------------ #
    # release: each member's untouched _release_deps over the stash      #
    # ------------------------------------------------------------------ #
    def _release(self, es, rec: _StageRec) -> List[Task]:
        with rec._lock:
            rec.status = _DONE
        ready: List[Task] = []
        for inst in rec.stage.members:
            if inst.key not in rec.layout.release_members:
                continue   # every successor is fused into this stage
            tc = self._tc(inst)
            shim = Task(self.tp, tc, inst.locals)
            for i, f in enumerate(tc.ast.flows):
                cp = rec.edge_copies.get((inst.key, f.name))
                if cp is not None:
                    shim.data[i].data_out = cp
            ready.extend(tc._release_deps(
                es, shim, ACTION_RELEASE_ALL) or [])
        rec.edge_copies.clear()
        return ready


def _edge_copy(arr) -> DataCopy:
    """Wrap a stage live-out device array as a deliverable DataCopy
    (the shape _deliver_activation builds for remote arrivals): a
    detached Data whose newest copy holds the (possibly still
    in-flight) device buffer — consumers chain on it like on any
    eager-completed task output."""
    d = Data(nb_elts=int(getattr(arr, "size", 0)))
    cp = DataCopy(d, 0, payload=arr)
    cp.version = 1
    cp.coherency = Coherency.OWNED
    d.attach_copy(cp)
    return cp


def try_install(tp, context) -> Optional[StageCompiler]:
    """Build a StageCompiler for ``tp`` when the stage_compile knob is
    on and the pool is eligible; None keeps the interpreted runtime
    bit-for-bit (the knob's off-contract).  The plan + layouts are a
    pure function of (spec, globals, geometry, distribution, rank), so
    they cache under the spec token — a repeat taskpool skips the whole
    enumeration/partition walk, not just the retrace."""
    if not any(d.device_type == "tpu" for d in context.devices):
        return None
    wavefront = any(
        d.device_type == "tpu" and getattr(d, "mesh", None) is not None
        and len(getattr(d, "chips", ())) > 1 for d in context.devices)
    max_tasks = int(params.get("stage_compile_max_tasks"))

    def build_plan():
        plan = plan_stages(tp, rank=tp.rank, max_tasks=max_tasks,
                           wavefront=wavefront)
        for stage in plan.stages:
            layout = build_layout(tp, plan, stage)
            # the max over the members' TRUE priorities (negative
            # included — a spec that deprioritizes a class must not
            # see its compiled stage boosted to 0)
            prios = [int(m.tc.ast.priority(m.env))
                     for m in stage.members
                     if m.tc.ast.priority is not None]
            plan.prepared.append((stage, layout,
                                  max(prios) if prios else 0))
        return plan

    try:
        from ..devices.batching import cached_stage_callable
        plan = cached_stage_callable(
            spec_token(tp), ("stageplan", wavefront, max_tasks),
            build_plan)
    except Exception as exc:  # noqa: BLE001 - unenumerable: interpret
        plog.debug.verbose(
            2, "stagec: %s not plannable (%s: %s); interpreted path",
            tp.name, type(exc).__name__, exc)
        return None
    if not plan.stages:
        return None
    plog.debug.verbose(
        3, "stagec: %s rank %d -> %d stage(s) covering %d/%d local "
        "task(s), %d residue", tp.name, tp.rank, len(plan.stages),
        plan.n_staged, plan.n_local, plan.n_residue)
    return StageCompiler(tp, context, plan)
