"""Data substrate tests: coherency protocol, arenas, data repos.

Mirrors the reference's data.c ownership-transfer semantics
(parsec_data_transfer_ownership_to_copy, parsec/data.c:286-370).
"""
import numpy as np
import pytest

from parsec_tpu.data.data import (Coherency, Data, DataCopy, FlowAccess,
                                  data_new_with_payload)
from parsec_tpu.data.datatype import Datatype, dtt_of_array
from parsec_tpu.data.arena import Arena
from parsec_tpu.data.datarepo import DataRepo


def test_single_copy_owned():
    a = np.zeros(4)
    d = data_new_with_payload(a)
    c = d.get_copy(0)
    assert c.coherency == Coherency.OWNED
    assert c.version == 1
    assert d.owner_device == 0


def test_read_transfer_creates_shared():
    d = data_new_with_payload(np.arange(4.0))
    dev_copy = DataCopy(d, 1)
    d.attach_copy(dev_copy)
    src = d.start_transfer_ownership(1, FlowAccess.READ)
    assert src is d.get_copy(0)
    dev_copy.payload = src.payload.copy()
    d.complete_transfer_ownership(1, FlowAccess.READ)
    assert dev_copy.coherency == Coherency.SHARED
    assert dev_copy.version == 1
    assert dev_copy.readers == 1
    # host copy still the owner
    assert d.get_copy(0).coherency == Coherency.OWNED


def test_write_transfer_moves_ownership():
    d = data_new_with_payload(np.arange(4.0))
    dev_copy = DataCopy(d, 1)
    d.attach_copy(dev_copy)
    src = d.start_transfer_ownership(1, FlowAccess.RW)
    dev_copy.payload = src.payload.copy()
    d.complete_transfer_ownership(1, FlowAccess.RW)
    assert dev_copy.coherency == Coherency.OWNED
    assert d.owner_device == 1
    assert d.get_copy(0).coherency == Coherency.SHARED
    v = d.version_bump(1)
    assert v == 2
    assert d.newest_copy() is dev_copy


def test_valid_copy_no_transfer_needed():
    d = data_new_with_payload(np.zeros(2))
    assert d.start_transfer_ownership(0, FlowAccess.READ) is None


def test_newest_copy_after_device_write():
    d = data_new_with_payload(np.zeros(2))
    dev = DataCopy(d, 1, payload=np.ones(2))
    d.attach_copy(dev)
    d.complete_transfer_ownership(1, FlowAccess.RW)
    d.version_bump(1)
    # host now stale: a host reader must pull from device 1
    src = d.start_transfer_ownership(0, FlowAccess.READ)
    assert src is dev


def test_arena_reuse_and_caps():
    dtt = Datatype(np.float32, (8, 8))
    ar = Arena(dtt, max_used=2, max_cached=1)
    b1 = ar.allocate()
    b2 = ar.allocate()
    assert ar.allocate(block=False) is None  # max_used cap
    ar.free(b1)
    b3 = ar.allocate()
    assert b3 is b1  # recycled
    ar.free(b2)
    ar.free(b3)
    assert ar.cached == 1  # max_cached cap
    assert ar.used == 0


def test_arena_backed_copy_recycles_on_release():
    dtt = Datatype(np.float64, (4,))
    ar = Arena(dtt, max_used=4, max_cached=4)
    d = Data()
    c = ar.new_copy(d)
    assert ar.used == 1
    c.release()
    assert ar.used == 0
    assert ar.cached == 1


def test_datatype_regions():
    dtt = Datatype(np.float32, (3, 3), region="lower")
    m = dtt.mask()
    assert m[2, 0] and m[1, 1] and not m[0, 2]
    assert dtt.nb_elts == 9
    full = dtt.contiguous()
    assert full.mask() is None
    assert not dtt.compatible_wire(full)


def test_datarepo_usage_count_reclaim():
    repo = DataRepo(nb_flows=2)
    e = repo.lookup_and_create("k")
    e.set_output(0, None)
    repo.entry_addto_usage_limit("k", 2)
    assert repo.lookup("k") is e
    repo.entry_used_once("k")
    assert repo.lookup("k") is e        # one consumer left + producer retain
    repo.entry_release("k")              # producer done
    assert repo.lookup("k") is e
    repo.entry_used_once("k")            # last consumer
    assert repo.lookup("k") is None
    assert len(repo) == 0
