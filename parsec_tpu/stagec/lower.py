"""Lowering pass: one traced function per stage (ISSUE 12 tentpole,
part 2).

A stage's tiles become a packed buffer argument list (memory-sourced
tiles first, externally-activated task-edge values second); intra-stage
dependencies become plain data flow between the members' per-example
subgraphs, which XLA is then free to schedule — the "own the whole
schedule inside one compiled unit" move of arxiv 2112.09017.  The
member walk mirrors ``dsl/ptg/capture.CapturedTaskpool._execute``
exactly (first-applicable in-dep binds, post-body flow values feed
successors, WRITE flows scatter to memory targets), so in ``unroll``
terms every member contributes the identical subgraph the per-task
interpreted path would trace — the compiled stage is bit-exact vs the
interpreted runtime on backends where per-op lowering is stable (the
same guarantee PR 5's stacked dispatch rides).

Lowered callables are AOT-cached per (spec token, NB/dtype/stage
signature) alongside the bucket cache in :mod:`..devices.batching`
(``cached_stage_callable``), so a fresh taskpool over the same spec and
problem parameters skips retrace AND recompile.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..data.data import FlowAccess
from ..dsl.ptg.runtime import _expand_args, f_prop, scratch_shape

__all__ = ["StageLayout", "build_layout", "build_stage_fn",
           "stage_signature", "spec_token", "spec_codes"]

#: compiled BODY code per parsed-spec identity (the verdict-memo
#: pattern, plan.IdKey): stage compilers and chain links come and go
#: per taskpool — the bodies must not recompile every time
_code_memo: Dict[Any, Dict[str, Any]] = {}
_CODE_MEMO_MAX = 64


def spec_codes(tp) -> Dict[str, Any]:
    """The compiled accelerator-BODY code objects of a taskpool's
    classes, memoized per parsed-spec identity."""
    from ..dsl.ptg.capture import _pick_body
    from .plan import IdKey
    key = IdKey(tp.jdf)
    codes = _code_memo.get(key)
    if codes is None:
        codes = {
            tc.ast.name: compile(_pick_body(tc.ast).code,
                                 f"<jdf:{tc.ast.name}:BODY[stagec]>",
                                 "exec")
            for tc in tp.task_classes}
        while len(_code_memo) >= _CODE_MEMO_MAX:
            _code_memo.pop(next(iter(_code_memo)))
        _code_memo[key] = codes
    return codes


class StageLayout:
    """The packed calling convention of one lowered stage.

    - ``mem_slots``: [((coll_name, coords), FlowAccess)] — one per
      distinct tile the stage reads from / writes to memory;
    - ``act_slots``: [(member_key, flow_name)] — one per externally-
      activated task-edge input (the redirect buffers the copies);
    - ``out_mem``: indices into ``mem_slots`` of written tiles, in slot
      order — the device module's written-flow outputs;
    - ``edge_outs``: [(member_key, flow_name)] post-body values some
      non-member successor consumes (stashed by the dispatch, released
      by the stage task's release walk);
    - ``goal``: external task-sourced activations to await before the
      stage is ready (the stage task's dynamic dependency counter).
    """

    __slots__ = ("mem_slots", "act_slots", "out_mem", "edge_outs", "goal",
                 "mem_index", "act_index", "release_members")

    def __init__(self) -> None:
        self.mem_slots: List[Tuple[Tuple, FlowAccess]] = []
        self.act_slots: List[Tuple[Tuple, str]] = []
        self.out_mem: List[int] = []
        self.edge_outs: List[Tuple[Tuple, str]] = []
        self.goal = 0
        self.mem_index: Dict[Tuple, int] = {}
        self.act_index: Dict[Tuple, int] = {}
        #: member keys with at least one out-edge leaving the stage
        #: (data or CTL): the ONLY members the stage task's release
        #: walk visits — interior members' successors are all fused
        #: into the same program, so walking them would emit only
        #: swallowed activations (pure overhead, O(stage size))
        self.release_members: set = set()

    @property
    def n_flows(self) -> int:
        return len(self.mem_slots) + len(self.act_slots)

    def slot_of_act(self, member_key: Tuple, flow_name: str) -> Optional[int]:
        j = self.act_index.get((member_key, flow_name))
        return None if j is None else len(self.mem_slots) + j


def _producer_locals(class_ast: Dict[str, Any], class_name: str,
                     arg_values: Tuple) -> Tuple:
    past = class_ast.get(class_name)
    if past is None:
        return tuple(arg_values)
    return past.locals_from_param_args(arg_values)


def build_layout(tp, plan, stage) -> StageLayout:
    """Walk the stage members' dependency edges once and derive the
    packed argument/output layout plus the external activation goal."""
    lay = StageLayout()
    class_ast = {tc.ast.name: tc.ast for tc in tp.task_classes}
    insts = plan.inst_by_key
    mkeys = stage.member_keys
    mem_access: Dict[Tuple, int] = {}
    mem_order: List[Tuple] = []
    edge_set = set()

    def note_mem(key: Tuple, access: FlowAccess) -> None:
        if key not in mem_access:
            mem_access[key] = FlowAccess.NONE
            mem_order.append(key)
        mem_access[key] |= access

    for inst in stage.members:
        env = inst.env
        for f in inst.tc.ast.flows:
            # inputs: every task-sourced in-dep expansion from outside
            # the stage is one awaited activation (the same counting
            # the interpreted input_goal applies, filtered to edges
            # that cross the stage boundary and producers that exist)
            for d in f.deps_in():
                t = d.resolve(env)
                if t is None:
                    continue
                if t.kind == "task":
                    for args in _expand_args(t.args, env):
                        pk = (t.task_class, _producer_locals(
                            class_ast, t.task_class, args))
                        if pk in insts and pk not in mkeys:
                            lay.goal += 1
                            if not f.is_ctl:
                                ak = (inst.key, f.name)
                                if ak not in lay.act_index:
                                    lay.act_index[ak] = len(lay.act_slots)
                                    lay.act_slots.append(ak)
                elif t.kind == "memory" and not f.is_ctl:
                    coords = tuple(int(a(env)) for a in t.args)
                    note_mem((t.collection, coords), FlowAccess.READ)
            if f.is_ctl:
                # a CTL out-edge leaving the stage still must fire its
                # (payload-less) activation at release
                for d in f.deps_out():
                    t = d.resolve(env)
                    if t is None or t.kind != "task":
                        continue
                    for args in _expand_args(t.args, env):
                        pk = (t.task_class, _producer_locals(
                            class_ast, t.task_class, args))
                        if pk not in mkeys:
                            lay.release_members.add(inst.key)
                            break
                continue
            writes = f.access in ("RW", "WRITE")
            if writes:
                for d in f.deps_out():
                    t = d.resolve(env)
                    if t is not None and t.kind == "memory":
                        coords = tuple(int(a(env)) for a in t.args)
                        note_mem((t.collection, coords), FlowAccess.WRITE)
            if not f.deps_in():
                # pure-output flow bound to its memory target's current
                # value (the interpreted _output_binding semantics)
                for d in f.deps_out():
                    t = d.resolve(env)
                    if t is not None and t.kind == "memory":
                        coords = tuple(int(a(env)) for a in t.args)
                        note_mem((t.collection, coords), FlowAccess.READ)
                        break
            # any flow value a non-member successor consumes is live-out
            for d in f.deps_out():
                t = d.resolve(env)
                if t is None or t.kind != "task":
                    continue
                for args in _expand_args(t.args, env):
                    pk = (t.task_class, _producer_locals(
                        class_ast, t.task_class, args))
                    if pk not in mkeys:
                        lay.release_members.add(inst.key)
                        ek = (inst.key, f.name)
                        if ek not in edge_set:
                            edge_set.add(ek)
                            lay.edge_outs.append(ek)
                        break

    for i, key in enumerate(mem_order):
        lay.mem_slots.append((key, mem_access[key]))
        lay.mem_index[key] = i
        if mem_access[key] & FlowAccess.WRITE:
            lay.out_mem.append(i)
    return lay


def build_stage_fn(tp, stage, layout: StageLayout,
                   codes: Dict[str, Any]):
    """The traceable fused function of one stage: packed buffers in
    (``layout`` order), written tiles + edge live-outs back.  Pure —
    safe under ``jax.jit``; untraceable bodies raise at trace time and
    the caller downgrades the stage."""
    import jax.numpy as jnp

    class_ast = {tc.ast.name: tc.ast for tc in tp.task_classes}
    members = list(stage.members)
    mkeys = stage.member_keys
    n_mem = len(layout.mem_slots)
    mem_keys = [k for k, _a in layout.mem_slots]
    rank = tp.rank

    def run(*bufs):
        tile_store: Dict[Tuple, Any] = {
            mem_keys[i]: bufs[i] for i in range(n_mem)}
        ext: Dict[Tuple, Any] = {
            ak: bufs[n_mem + j] for j, ak in enumerate(layout.act_slots)}
        out_store: Dict[Tuple, Any] = {}
        for inst in members:
            tc_ast = inst.tc.ast
            env = dict(inst.env)
            payloads: Dict[str, Any] = {}
            for f in tc_ast.flows:
                if f.is_ctl:
                    continue
                val = None
                bound = False
                for d in f.deps_in():
                    t = d.resolve(inst.env)
                    if t is None:
                        continue
                    if t.kind == "task":
                        pk = (t.task_class, _producer_locals(
                            class_ast, t.task_class,
                            tuple(a(inst.env) for a in t.args)))
                        if pk in mkeys:
                            val = out_store[(pk[0], pk[1], t.flow)]
                        else:
                            val = ext.get((inst.key, f.name))
                    elif t.kind == "memory":
                        coords = tuple(int(a(inst.env)) for a in t.args)
                        val = tile_store[(t.collection, coords)]
                    elif t.kind == "new":
                        shape = scratch_shape(f, inst.env)
                        val = jnp.zeros(shape,
                                        f_prop(f, "dtype", "float32"))
                    elif t.kind == "null":
                        val = None
                    bound = True
                    break
                if not bound and not f.deps_in():
                    # pure-output flow: its memory target's current
                    # value, else a zeroed scratch (interpreted
                    # _output_binding / new_scratch_copy semantics)
                    for d in f.deps_out():
                        t = d.resolve(inst.env)
                        if t is not None and t.kind == "memory":
                            coords = tuple(int(a(inst.env))
                                           for a in t.args)
                            val = tile_store[(t.collection, coords)]
                            break
                    else:
                        shape = scratch_shape(f, inst.env)
                        if shape is not None:
                            val = jnp.zeros(
                                shape, f_prop(f, "dtype", "float32"))
                payloads[f.name] = val
            env.update(payloads)
            env["np"] = np
            env["jnp"] = jnp
            env["es_rank"] = rank
            env["this_task"] = None
            exec(codes[tc_ast.name], env)
            for f in tc_ast.flows:
                if f.is_ctl:
                    continue
                out_store[(tc_ast.name, inst.locals, f.name)] = \
                    env.get(f.name)
                if f.access in ("RW", "WRITE"):
                    for d in f.deps_out():
                        t = d.resolve(inst.env)
                        if t is None or t.kind != "memory":
                            continue
                        coords = tuple(int(a(inst.env)) for a in t.args)
                        tile_store[(t.collection, coords)] = \
                            env.get(f.name)
        tiles = tuple(tile_store[mem_keys[i]] for i in layout.out_mem)
        edges = tuple(out_store[(mk[0], mk[1], fn)]
                      for (mk, fn) in layout.edge_outs)
        return tiles + edges

    return run


def stage_signature(stage, shapes: Tuple) -> Tuple:
    """The AOT cache key of one lowered stage: its member set (class +
    locals — NB and the tile grid are implied by the locals space) plus
    the concrete buffer shapes/dtypes."""
    return (stage.index,
            tuple((m.tc.ast.name, m.locals) for m in stage.members),
            shapes)


def spec_token(tp) -> Tuple:
    """The process-wide cache token of a taskpool's stage callables: a
    fresh taskpool over the same parsed spec, scalar globals, and
    collection geometry hits already-compiled stages (the DTD
    cache_token analog for PTG stage compilation).  The JDFFile object
    itself rides the key via the shared identity wrapper (plan.IdKey —
    a recycled id can never alias a dead spec's entries)."""
    from ..collections.collection import DataCollection
    from .plan import IdKey
    scalars = []
    colls = []
    for name, val in sorted(tp.global_env.items()):
        if isinstance(val, (int, float, str, np.integer, np.floating)):
            scalars.append((name, val))
        elif isinstance(val, DataCollection):
            # geometry AND distribution: rank_of decides stage
            # membership, so P/Q/nodes are part of the plan identity
            colls.append((name, type(val).__name__,
                          getattr(val, "mt", None), getattr(val, "nt", None),
                          getattr(val, "mb", None), getattr(val, "nb", None),
                          getattr(val, "P", None), getattr(val, "Q", None),
                          getattr(val, "nodes", None),
                          str(getattr(val, "dtype", None))))
    return (IdKey(tp.jdf), tuple(scalars), tuple(colls),
            tp.rank, tp.nb_ranks)
