"""Tile QR factorization (dgeqrf) as a PTG task graph.

The classic communication-avoiding-free flat-tree tile QR with the four
DPLASMA task classes GEQRT / UNMQR / TSQRT / TSMQR and the same dataflow
as the reference runtime executing DPLASMA's zgeqrf.jdf (the runtime under
test in the reference's apps; dataflow shape per SURVEY.md §2.6/§7.2-10).

TPU-first deviation: the reference kernels carry the compact-WY pair
(V, T) along the panel edges; applying it is a chain of nb short
reflector updates — hostile to the MXU. Here the panel tasks export the
explicit orthogonal factors (Q for the diagonal, Q2 for the stacked
triangle-on-square), so every consumer update is one large matmul. The
Q/Q2 edges are WRITE-only scratch flows, the analog of DPLASMA's side-band
descT collection.

On return descA holds R in its upper triangle (tiles (i,j), i <= j) and
zeros below: A = Q R with Q discarded (verify via R^T R == A^T A).
"""
from __future__ import annotations

from ..collections.matrix import TiledMatrix
from ..dsl import ptg

DGEQRF_JDF = """
descA [ type="collection" ]
MT [ type="int" ]
NT [ type="int" ]
KT [ type="int" ]

GEQRT(k)

k = 0 .. KT-1

: descA( k, k )

RW A <- (k == 0) ? descA( k, k ) : A2 TSMQR( k-1, k, k )
     -> (k < MT-1) ? R TSQRT( k, k+1 )
     -> (k == MT-1) ? descA( k, k )
WRITE Q -> Q UNMQR( k, k+1 .. NT-1 )  [shape="(descA.tile_shape(k, k)[0],) * 2"]

; (KT - k) * 1000

BODY [type=tpu]
{
    A, Q = ops.geqrt(A) if k < NT - 1 else ops.geqrt_r(A)
}
END

UNMQR(k, n)

k = 0 .. KT-1
n = k+1 .. NT-1

: descA( k, n )

READ Q <- Q GEQRT( k )
RW   C <- (k == 0) ? descA( k, n ) : A2 TSMQR( k-1, k, n )
       -> (k < MT-1) ? A1 TSMQR( k, k+1, n )
       -> (k == MT-1) ? descA( k, n )

; (KT - k) * 100

BODY [type=tpu]
{
    C = ops.unmqr(Q, C)
}
END

TSQRT(k, m)

k = 0 .. KT-1
m = k+1 .. MT-1

: descA( m, k )

RW R  <- (m == k+1) ? A GEQRT( k ) : R TSQRT( k, m-1 )
      -> (m == MT-1) ? descA( k, k ) : R TSQRT( k, m+1 )
RW A2 <- (k == 0) ? descA( m, k ) : A2 TSMQR( k-1, m, k )
      -> descA( m, k )
WRITE Q2 -> Q2 TSMQR( k, m, k+1 .. NT-1 )  [shape="(descA.tile_shape(k, k)[0] + descA.tile_shape(m, k)[0],) * 2"]

; (KT - k) * 1000 + (MT - m)

BODY [type=tpu]
{
    R, A2, Q2 = ops.tsqrt(R, A2) if k < NT - 1 else ops.tsqrt_r(R, A2)
}
END

TSMQR(k, m, n)

k = 0 .. KT-1
m = k+1 .. MT-1
n = k+1 .. NT-1

: descA( m, n )

READ Q2 <- Q2 TSQRT( k, m )
RW A1 <- (m == k+1) ? C UNMQR( k, n ) : A1 TSMQR( k, m-1, n )
      -> (m == MT-1) ? descA( k, n ) : A1 TSMQR( k, m+1, n )
RW A2 <- (k == 0) ? descA( m, n ) : A2 TSMQR( k-1, m, n )
      -> ((n == k+1) and (m == k+1)) ? A GEQRT( k+1 )
      -> ((n == k+1) and (m > k+1)) ? A2 TSQRT( k+1, m )
      -> ((n > k+1) and (m == k+1)) ? C UNMQR( k+1, n )
      -> ((n > k+1) and (m > k+1)) ? A2 TSMQR( k+1, m, n )

; (KT - k) * 10 + (MT - m)

BODY [type=tpu]
{
    A1, A2 = ops.tsmqr(Q2, A1, A2)
}
END
"""

_factory = None


def dgeqrf_factory() -> "ptg.JDFFactory":
    global _factory
    if _factory is None:
        _factory = ptg.compile_jdf(DGEQRF_JDF, name="dgeqrf")
    return _factory


def dgeqrf_taskpool(A: TiledMatrix, rank: int = 0, nb_ranks: int = 1):
    from .. import ops as ops_module
    kt = min(A.mt, A.nt)
    # the panel factorizations need square diagonal tiles (ragged edges
    # are fine as long as the trailing diagonal tile stays square)
    last_rows, last_cols = A.tile_shape(kt - 1, kt - 1)
    if A.mb != A.nb or last_rows != last_cols:
        raise ValueError(
            f"dgeqrf needs square diagonal tiles; got mb={A.mb} nb={A.nb}, "
            f"trailing diagonal tile {last_rows}x{last_cols}")
    tp = dgeqrf_factory().new(descA=A, MT=A.mt, NT=A.nt, KT=kt,
                              rank=rank, nb_ranks=nb_ranks)
    tp.global_env["ops"] = ops_module
    return tp


def dgeqrf(context, A: TiledMatrix, rank: int = 0, nb_ranks: int = 1) -> None:
    """Factor A = Q R in place: on return the upper triangle of A holds R
    (tiles strictly below the diagonal are zeroed); Q is not retained.
    Blocking: enqueue + wait."""
    tp = dgeqrf_taskpool(A, rank=rank, nb_ranks=nb_ranks)
    context.add_taskpool(tp)
    context.wait()
