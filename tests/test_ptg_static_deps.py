"""Static dependency management: PTG lowering + dense-counter engines
(ref: --dep-management=index-array, parsec/interfaces/ptg/ptg-compiler/
main.c:37; dense counters parsec_internal.h:173-196)."""
import numpy as np
import pytest

import parsec_tpu
from parsec_tpu.collections import TwoDimBlockCyclic
from parsec_tpu.dsl.ptg.capture import plan
from parsec_tpu.dsl.ptg.lower import PyDAG, lower, make_engine
from parsec_tpu.ops import (dgeqrf_taskpool, dgetrf_nopiv_taskpool,
                            dpotrf_taskpool, make_spd)
from parsec_tpu.utils.params import params


def _mk(n=512, nb=128, kind="potrf"):
    M = make_spd(n, dtype=np.float32)
    A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
    if kind == "potrf":
        return dpotrf_taskpool(A), A, M
    if kind == "getrf":
        return dgetrf_nopiv_taskpool(A), A, M
    return dgeqrf_taskpool(A), A, M


@pytest.mark.parametrize("kind", ["potrf", "getrf", "geqrf"])
def test_lowering_matches_capture_plan(kind):
    """The lowered edge structure must agree with the capture planner's
    independent consumer-side resolution: same task set, and each task's
    indegree equals its resolved predecessor count."""
    tp, _, _ = _mk(kind=kind)
    dag = lower(tp, use_cache=False)
    order = plan(tp)
    assert dag.n_tasks == len(order)
    pred_counts = {inst.key: len(inst.preds) for inst in order}
    for tid in range(dag.n_tasks):
        key = (dag.class_names[int(dag.class_of[tid])], dag.locals_of[tid])
        assert key in pred_counts
        assert dag.indegree[tid] == pred_counts[key], f"indegree {key}"
    assert dag.n_edges == sum(pred_counts.values())
    # startup set = zero-predecessor set
    startup = {(dag.class_names[int(dag.class_of[t])], dag.locals_of[t])
               for t in dag.startup_ids()}
    assert startup == {k for k, n in pred_counts.items() if n == 0}


def test_native_and_python_engines_agree():
    """Drive a lowered dpotrf DAG to completion through both engines in
    the same (deterministic) order; ready sets must match step for step."""
    tp, _, _ = _mk()
    dag = lower(tp, use_cache=False)
    eng_a = make_engine(dag)        # native when built
    eng_b = PyDAG(dag)
    if type(eng_a) is PyDAG:
        pytest.skip("native extension not built; single engine only")
    ra, rb = eng_a.start(), eng_b.start()
    done = 0
    while ra or rb:
        assert sorted(ra) == sorted(rb)
        frontier = sorted(ra)
        ra, rb = [], []
        for t in frontier:
            ra.extend(eng_a.complete(t))
            rb.extend(eng_b.complete(t))
            done += 1
    assert done == dag.n_tasks


def test_binding_routing_and_overrelease():
    """complete() routes the produced copy to the successor's flow slot;
    releasing past indegree raises instead of corrupting counters."""
    tp, _, _ = _mk()
    dag = lower(tp, use_cache=False)
    eng = make_engine(dag)
    start = eng.start()
    tid = start[0]
    tc = tp.task_classes[int(dag.class_of[tid])]
    sentinel = object()
    copies = tuple(sentinel for _ in tc.ast.flows)
    ready = eng.complete(tid, copies)
    # every successor of tid must now hold the sentinel in the routed slot
    lo, hi = int(dag.indptr[tid]), int(dag.indptr[tid + 1])
    routed = {(int(dag.succ[e]), int(dag.succ_flow[e]))
              for e in range(lo, hi)}
    for sid in {s for s, _ in routed}:
        b = eng.take_bindings(sid)
        for (s, f) in routed:
            if s == sid:
                assert b[f] is sentinel
    del ready
    with pytest.raises((RuntimeError, AssertionError)):
        for _ in range(dag.n_tasks + 1):
            eng.complete(tid)  # keep over-releasing until it must trip


def test_static_mode_end_to_end():
    """dpotrf through the runtime with static dep management on the
    CLASSIC dispatch (eligible pools default to the turbo native loop,
    covered by test_turbo.py): engine engaged, numerics match the hash
    path."""
    n, nb = 512, 128
    M = make_spd(n, dtype=np.float32)
    ctx = parsec_tpu.init(nb_cores=2)
    try:
        params.set_cmdline("ptg_dep_management", "static")
        params.set_cmdline("ptg_dispatch", "classic")
        A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
        tp = dpotrf_taskpool(A)
        ctx.add_taskpool(tp)
        ctx.wait()
        assert tp._engine is not None, "static engine did not engage"
        L = np.tril(A.to_numpy()).astype(np.float64)
        ref = np.linalg.cholesky(M.astype(np.float64))
        assert np.allclose(L, ref, atol=1e-2)
    finally:
        params.set_cmdline("ptg_dep_management", "hash")
        params.unset_cmdline("ptg_dispatch")
        ctx.fini()


def test_static_mode_multirank_falls_back():
    """nb_ranks > 1 must stay on the dynamic hash path (static lowering
    is single-rank)."""
    n, nb = 256, 128
    M = make_spd(n, dtype=np.float32)
    ctx = parsec_tpu.init(nb_cores=1)
    try:
        params.set_cmdline("ptg_dep_management", "static")
        A = TwoDimBlockCyclic(n, n, nb, nb, P=2, nodes=2,
                              dtype=np.float32).from_numpy(M)
        tp = dpotrf_taskpool(A, rank=0, nb_ranks=2)
        # startup path must not build an engine for a 2-rank pool; the
        # lowering itself refuses multi-rank taskpools
        from parsec_tpu.dsl.ptg.lower import lower as _lower
        with pytest.raises(ValueError):
            _lower(tp, use_cache=False)
    finally:
        params.set_cmdline("ptg_dep_management", "hash")
        ctx.fini()
