"""TCP transport: the cross-process/cross-host comm engine.

Reference behavior being replaced: the funnelled MPI engine is the only
in-tree transport and carries both the control plane (activations, GET
requests) and the data plane over two-sided MPI
(parsec/parsec_mpi_funnelled.c). Here the same activation/GET/PUT
emulation (inherited from LocalCommEngine) rides framed pickle messages
over TCP sockets — one duplex connection per rank pair, receiver
threads feeding a local inbox, callbacks dispatched from progress() on
the caller's thread (funnelled semantics preserved).

The wire fast path (framing in comm/wire.py):

- each peer has a SEND QUEUE drained by a dedicated writer thread;
  ``send_am`` serializes on the caller's thread (copy-at-enqueue for
  everything below the chunk threshold — the historical snapshot
  semantics) and returns as soon as the message fits the bounded
  per-peer send buffer (``comm_send_buffer_bytes`` — backpressure
  toward a slow link, so producers stall instead of queueing an
  epoch's traffic in RAM);
- queued small messages COALESCE into one multi-message frame per
  syscall (``comm_coalesce_max_bytes``), so on a slow DCN the control
  plane pays one syscall + one wakeup for a burst of activations;
- buffers >= ``comm_chunk_bytes`` stream as bounded CHUNK frames with
  pickle-5 zero-copy views; control messages interleave between chunks
  instead of head-of-line blocking behind a multi-MB tile (callers on
  the bulk path — GET rendezvous, wave tiles — snapshot their payloads
  already, so zero-copy is safe there);
- per-link COMPRESSION (zlib, lz4 when installed) is negotiated at the
  connection handshake and engages only when the measured link
  bandwidth EWMA drops below ``comm_compress_threshold_mbps`` (default
  0 = never) AND a sample probe shows the traffic compresses; a peer
  that never advertises codecs (HELLO missing or no common codec)
  stays uncompressed. The v2 framing itself is a breaking wire change:
  every rank of a job must run the same framing version;
- RELIABLE SESSIONS (``comm_reconnect_timeout`` > 0, HELLO ``"rs"``
  capability): each peer link is a session — data frames carry a
  per-direction ``seq`` (wire.K_SEQ envelope), the writer retains a
  bounded replay window of sent-but-unacked frames
  (``comm_replay_window_bytes``; the retained bytes also count against
  the ``comm_send_buffer_bytes`` backpressure budget), and the
  receiver acks cumulatively (K_ACK) and discards duplicates by seq.
  A socket error then marks the peer SUSPECT instead of dead: senders
  park on the bounded send buffer, in-flight GETs/rendezvous wait, and
  a reconnector re-dials with exponential backoff + jitter under the
  ``comm_reconnect_timeout`` budget. The reconnect handshake
  (K_RESUME) exchanges the session epoch and last-delivered seq both
  ways — the sender replays the gap (byte-level resume of a frame
  truncated mid-body, K_FRAG), the receiver dedups, and no active
  message is lost or delivered twice. Only budget exhaustion — or the
  heartbeat detector's independent verdict once the session is live
  again — escalates to the ``_peer_died`` → elastic/fail-fast path. A
  mixed-version peer (no ``"rs"`` in its HELLO) or an unset knob keeps
  today's fail-fast behavior bit for bit.

This is the DCN control-plane story of SURVEY.md §5.8 made concrete: on
a multi-host TPU deployment the small latency-bound messages travel this
engine while bulk tile payloads ride the ICI data plane (comm/mesh.py);
single-host multi-process runs (the tests) carry both over TCP.

Connection setup: rank r listens on ``endpoints[r]``; r dials every rank
s < r and accepts from every s > r (one connection per unordered pair),
with a rank-identifying handshake byte frame followed by a HELLO
capability frame.
"""
from __future__ import annotations

import pickle
import socket
import struct
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..core.lists import Fifo
from .engine import RankFailedError, TAG_GET_DATA, TAG_USER_BASE
from ..utils import logging as plog
from .local import LocalCommEngine, _wire_copy
from . import wire
from .wire import GOODBYE

TAG_BARRIER = TAG_USER_BASE - 1  # reserved by the transport for sync()

#: per-PROCESS random token advertised under the HELLO "xs" capability
#: (ISSUE 20): equality on the receive side proves two ranks share this
#: process — and therefore one XLA device pool, the precondition for
#: lowering a wave-front stage into ONE shard_map program across them.
#: Lazily minted so an unset knob never even generates it.
_XS_TOKEN: Optional[str] = None
_XS_TOKEN_LOCK = threading.Lock()  # lock: guards module-global _XS_TOKEN lazy init, not a class field


def _xs_proc_token() -> str:
    global _XS_TOKEN
    with _XS_TOKEN_LOCK:
        if _XS_TOKEN is None:
            import os
            import uuid
            _XS_TOKEN = f"xs-{os.getpid()}-{uuid.uuid4().hex}"
        return _XS_TOKEN

#: bandwidth EWMA smoothing and the minimum send size that counts as a
#: bandwidth sample (smaller sends measure syscall latency, not the link)
_BW_ALPHA = 0.2
_BW_SAMPLE_MIN = 1 << 15
#: compression: re-probe cadence (frames) and the engage ratio
_PROBE_EVERY = 256
_PROBE_RATIO = 0.9
#: smallest body worth compressing
_COMP_MIN_BYTES = 512
#: iovec safety cap for one sendmsg (IOV_MAX is 1024 on linux)
_MAX_BATCH_MSGS = 256
#: anti-starvation: after this many consecutive ctrl frames with bulk
#: chunks waiting, one chunk is interleaved regardless — a sustained
#: control stream must not stall an in-flight bulk transfer forever
_CTRL_STREAK_MAX = 8
#: reliable sessions: how long a writer holds DATA frames waiting for
#: the peer's HELLO before assuming a mixed-version (session-less) peer
#: — frames sent before capabilities are known cannot ride the replay
#: window, so with sessions enabled locally the first data frame waits
#: for the capability exchange (every current build HELLOs first-thing,
#: so this only delays traffic toward true pre-HELLO builds)
_HELLO_GRACE = 5.0
#: receiver ack cadence: a cumulative K_ACK at latest every this many
#: delivered data frames (the byte threshold adapts to the window cap)
_ACK_EVERY_FRAMES = 16
#: reconnect backoff ceiling (seconds; doubles from the configured
#: initial value, with multiplicative jitter against thundering herds)
_RECONNECT_BACKOFF_MAX = 2.0

#: declared lock discipline, enforced by the concurrency lint
#: (parsec_tpu/analysis/lock_check.py): per-peer send queues belong to
#: the peer's condition (writer thread vs. every sender), the peer map
#: to the connection condition (accept thread vs. everyone), wire
#: counters and barrier state to their dedicated locks.  The same lint
#: verifies no socket send/recv or sleep ever runs while one of these
#: is held — the writer drains OUTSIDE peer.cond by construction.
_GUARDED_BY = {
    "_Peer.ctrl": "cond",
    "_Peer.bulk": "cond",
    "_Peer.queued_bytes": "cond",
    # reliable-session state (ISSUE 10): suspect flag, send/receive seq
    # counters, the replay window + its byte accounting, the pending
    # replay list and the receiver's partial-frame resume buffer are
    # shared between the writer thread, the receiver thread, every
    # sender parked in backpressure, and the reconnector — all under
    # the peer's condition (resume swaps threads only after the old
    # generation has exited, but the STATE handoff itself is locked)
    # quantized wire codecs (ISSUE 14): the negotiated lossy codec and
    # the per-peer per-codec byte accounting feeding the labeled
    # COMPRESS_RATIO gauges — written by the enqueuing sender thread
    # (quantize) and the writer thread (compress), read by the obs
    # poll, all under the peer's condition
    "_Peer.qz_codec": "cond",
    "_Peer.q_pre": "cond",
    "_Peer.q_post": "cond",
    # closed-loop tuning (ISSUE 17): receive-side accounting of
    # quantized buffers that LANDED on this link (raw vs encoded bytes
    # — the de-escalation evidence the controller on the receiving
    # rank reads), written by the receiver thread, read by the
    # controller's window tick
    "_Peer.qrx_pre": "cond",
    "_Peer.qrx_post": "cond",
    "_Peer.comp_pre": "cond",
    "_Peer.comp_post": "cond",
    "_Peer.suspect": "cond",
    "_Peer.rs_epoch": "cond",
    "_Peer.rs_tx_seq": "cond",
    "_Peer.rs_rx_seq": "cond",
    "_Peer.rs_window": "cond",
    "_Peer.rs_window_bytes": "cond",
    "_Peer.rs_replay": "cond",
    "_Peer.rs_rx_partial": "cond",
    "TCPCommEngine._peers": "_conn_cond",
    "TCPCommEngine.wire_stats": "_stat_lock",
    # clock alignment (ISSUE 15): the per-peer offset EWMA + sample
    # counts — written by the receiver thread (pong arrivals), read by
    # the obs poll and the trace-metadata export
    "TCPCommEngine._clock": "_stat_lock",
    "TCPCommEngine._clock_n": "_stat_lock",
    "TCPCommEngine._rx_pending": "_stat_lock",
    # GOODBYE verdict evidence: GET tokens whose reply arrived but has
    # not been consumed — written by receiver threads, read by the
    # GOODBYE wait (shares the engine lock that guards _get_cbs/_get_srcs)
    "TCPCommEngine._rx_get_tokens": "_lock",
    "TCPCommEngine._xfer_iter": "_stat_lock",
    "TCPCommEngine._suspect_ms_total": "_stat_lock",
    "TCPCommEngine._barrier_arrived": "_barrier_lock",
    "TCPCommEngine._barrier_release": "_barrier_lock",
}


# RankFailedError moved to comm/engine.py (every transport raises it
# now, not just this one); re-exported here for back-compat importers.


def free_ports(n: int) -> List[int]:
    """Reserve n distinct free localhost ports (test/launcher helper)."""
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _sendall_vec(sock: socket.socket, pieces: List[Any]) -> None:
    """Scatter-gather sendall: one syscall per iteration over the whole
    piece list (the coalescing win — a batch of frames leaves in ONE
    sendmsg instead of one syscall per message)."""
    views = [memoryview(p) for p in pieces]
    while views:
        sent = sock.sendmsg(views)
        while sent:
            if len(views[0]) <= sent:
                sent -= len(views[0])
                views.pop(0)
            else:
                views[0] = views[0][sent:]
                sent = 0


class _FabricShim:
    """Satisfies the tiny surface LocalCommEngine expects of a fabric."""

    def __init__(self, nb_ranks: int) -> None:
        self.nb_ranks = nb_ranks
        self.msg_count = 0
        self.bytes_count = 0


class _Peer:
    """Per-peer send state: the queues the writer thread drains.

    ``ctrl`` holds coalescible message segments and standalone frames
    (chunked-transfer headers, hello); ``bulk`` holds chunk items. The
    writer always prefers ctrl, so control traffic interleaves between
    the bounded chunks of an in-flight bulk payload."""

    __slots__ = ("rank", "sock", "ctrl", "bulk", "cond", "writer",
                 "goodbye", "bw_mbps", "codec", "engaged", "frames",
                 "probe_ratio", "done", "queued_bytes", "hb_ok", "el_ok",
                 "tr_ok", "lv_ok",
                 "rs_ok", "hello_seen", "connected_at", "conn_gen",
                 "suspect", "suspect_since", "rs_epoch", "rs_tx_seq",
                 "rs_rx_seq", "rs_window", "rs_window_bytes", "rs_replay",
                 "rs_rx_unacked_frames", "rs_rx_unacked_bytes",
                 "rs_rx_partial", "rx_xfers", "recv_thread", "rs_dup_next",
                 "rs_resuming", "qz_codec", "q_pre", "q_post",
                 "comp_pre", "comp_post", "tn_ok", "qrx_pre", "qrx_post",
                 "sv_ok", "dp_ok", "xs_ok")

    def __init__(self, rank: int, sock: socket.socket) -> None:
        self.rank = rank
        self.sock = sock
        self.ctrl: deque = deque()
        self.bulk: deque = deque()
        self.queued_bytes = 0      # backpressure accounting
        self.cond = threading.Condition()
        self.writer: Optional[threading.Thread] = None
        self.goodbye = False       # enqueue-side: shutdown requested
        self.done = False          # writer exited
        self.bw_mbps: Optional[float] = None   # send-side link EWMA
        self.codec: Optional[str] = None       # negotiated at HELLO
        self.engaged = False                   # compression live now
        self.frames = 0                        # frames sent (probe clock)
        self.probe_ratio: Optional[float] = None
        # -- quantized wire codec (ISSUE 14) ----------------------------
        self.qz_codec: Optional[str] = None    # negotiated at HELLO
        self.q_pre = 0             # raw bytes of quantized buffers
        self.q_post = 0            # encoded bytes actually queued
        self.comp_pre = 0          # per-peer lossless codec accounting
        self.comp_post = 0
        self.hb_ok = False         # HELLO advertised heartbeat support
        self.el_ok = False         # HELLO advertised elastic membership
        self.tr_ok = False         # HELLO advertised flow tracing ("tr")
        self.lv_ok = False         # HELLO advertised obs_live ("lv")
        self.tn_ok = False         # HELLO advertised runtime tuning ("tn")
        self.sv_ok = False         # HELLO advertised serving ("sv")
        self.dp_ok = False         # HELLO advertised device plane ("dp")
        self.xs_ok = False         # HELLO proved co-resident xrank ("xs")
        # -- closed-loop tuning (ISSUE 17) ------------------------------
        self.qrx_pre = 0           # raw bytes of RECEIVED quantized bufs
        self.qrx_post = 0          # encoded bytes that landed for them
        # -- reliable session (ISSUE 10) --------------------------------
        self.rs_ok = False         # both ends advertised "rs"
        self.hello_seen = False    # the peer's HELLO was processed
        self.connected_at = time.monotonic()
        self.conn_gen = 0          # bumped at each resume: stale-thread guard
        self.suspect = False       # link torn, reconnect in progress
        self.suspect_since = 0.0
        self.rs_epoch = 0          # bumped at each successful resume
        self.rs_tx_seq = 0         # last seq assigned to a sent data frame
        self.rs_rx_seq = 0         # last seq DELIVERED from the peer
        self.rs_window: deque = deque()   # (seq, frame pieces, nbytes)
        self.rs_window_bytes = 0
        self.rs_replay: list = []  # resume backlog the new writer sends first
        self.rs_rx_unacked_frames = 0      # receiver-side ack cadence
        self.rs_rx_unacked_bytes = 0
        # (total body size, bytes received so far) of a frame the link
        # tore mid-body — fed to K_RESUME as the byte-level resume claim
        self.rs_rx_partial: Optional[Tuple[int, bytearray]] = None
        # receive-side chunked-transfer reassembly lives on the PEER so
        # a transfer half-landed when the link flapped completes from
        # the replayed chunks after the resume
        self.rx_xfers: Dict[int, wire.RxXfer] = {}
        self.recv_thread: Optional[threading.Thread] = None
        # chaos (ft_inject dup): duplicate the next data frame at the
        # WIRE level — same seq, so the receiver's dedup is what keeps
        # the active message exactly-once
        self.rs_dup_next = False
        # accept-side resume in flight (handshakes run on their own
        # threads now; a duplicate concurrent dial must not race one)
        self.rs_resuming = False


class TCPCommEngine(LocalCommEngine):
    #: a TCP probe only leaves when the peer's HELLO was processed
    #: (hb_ok) — its receiver thread was alive then and answers pings
    #: with no progress pumping, so probed-but-silent = genuinely dead
    ft_probe_baseline = True

    def __init__(self, rank: int, endpoints: List[Tuple[str, int]],
                 connect_timeout: float = 30.0,
                 coalesce_max_bytes: Optional[int] = None,
                 chunk_bytes: Optional[int] = None,
                 compress_threshold_mbps: Optional[float] = None,
                 reconnect_timeout: Optional[float] = None,
                 reconnect_backoff: Optional[float] = None,
                 replay_window_bytes: Optional[int] = None,
                 quantize: Optional[str] = None,
                 quantize_threshold_mbps: Optional[float] = None,
                 obs_flow: Optional[bool] = None,
                 obs_live: Optional[bool] = None,
                 tune_auto: Optional[bool] = None,
                 serve: Optional[bool] = None,
                 dplane: Optional[bool] = None,
                 xstage: Optional[bool] = None) -> None:
        from ..utils.params import params
        self._inbox: Fifo = Fifo()
        # GET tokens whose reply has ARRIVED (pushed to the inbox by a
        # receiver thread) but not yet been consumed by a worker — the
        # GOODBYE verdict uses this to tell delivered-not-consumed
        # apart from never-sent (guarded by self._lock)
        self._rx_get_tokens: set = set()
        self._peers: Dict[int, _Peer] = {}
        self._recv_threads: List[threading.Thread] = []
        self._closing = False
        # dead_peers / on_peer_failure live on the CommEngine base now
        # (uniform across transports); finished_peers is TCP's record of
        # clean GOODBYEs received
        self.finished_peers: set = set()
        self._barrier_arrived: set = set()
        self._barrier_release = 0
        self._barrier_lock = threading.Lock()
        self._stat_lock = threading.Lock()
        self._conn_cond = threading.Condition()
        self._xfer_iter = 0
        self._rx_pending: Dict[int, int] = {}  # peer -> incomplete rx xfers
        # wire knobs (constructor overrides beat the MCA layer — bench
        # and tests compare configurations inside one process)
        self.coalesce_max_bytes = (
            coalesce_max_bytes if coalesce_max_bytes is not None
            else params.get_or("comm_coalesce_max_bytes", "sizet", 1 << 16))
        self.chunk_bytes = max(
            1, chunk_bytes if chunk_bytes is not None
            else params.get_or("comm_chunk_bytes", "sizet", 1 << 17))
        self.compress_threshold_mbps = (
            compress_threshold_mbps if compress_threshold_mbps is not None
            else params.get_or("comm_compress_threshold_mbps", "int", 0))
        self.send_buffer_bytes = max(
            1, params.get_or("comm_send_buffer_bytes", "sizet", 1 << 26))
        # reliable sessions (ISSUE 10): a torn link becomes a SUSPECT
        # peer with reconnect + seq-numbered replay while the knob's
        # budget lasts; 0/unset keeps today's fail-fast bit for bit
        if reconnect_timeout is None:
            raw = str(params.get("comm_reconnect_timeout") or "").strip()
            reconnect_timeout = float(raw) if raw else 0.0
        self.reconnect_timeout = max(0.0, float(reconnect_timeout))
        self._rs_enabled = self.reconnect_timeout > 0
        if reconnect_backoff is None:
            raw = str(params.get("comm_reconnect_backoff") or "").strip()
            reconnect_backoff = float(raw) if raw else 0.05
        self.reconnect_backoff = max(1e-3, float(reconnect_backoff))
        self.replay_window_bytes = max(
            1, replay_window_bytes if replay_window_bytes is not None
            else params.get_or("comm_replay_window_bytes", "sizet", 1 << 24))
        #: ack at latest every _ACK_EVERY_FRAMES delivered data frames
        #: or this many delivered bytes, whichever first — sized so the
        #: sender's replay window drains well before it fills
        self._ack_bytes = max(1, min(1 << 18, self.replay_window_bytes // 4))
        self._suspect_ms_total = 0.0
        # quantized wire codecs (ISSUE 14): lossy blockwise encodings
        # for bulk float tile payloads the sender layer marked eligible
        # (per-flow ``_qz_ok``) — engaged per link only toward peers
        # whose HELLO advertised the codec under "qz" (both ends must
        # set the knob; the advertisement itself is gated so an unset
        # knob leaves every wire byte, HELLO included, unchanged)
        if quantize is None:
            quantize = str(params.get("comm_quantize") or "")
        self._quantize = wire.normalize_quant_codec(quantize)
        if quantize_threshold_mbps is None:
            quantize_threshold_mbps = params.get_or(
                "comm_quantize_threshold_mbps", "int", 0)
        self.quantize_threshold_mbps = float(quantize_threshold_mbps or 0)
        self._codecs = wire.available_codecs()
        # cross-rank flow tracing + clock alignment (ISSUE 15): when the
        # ``obs_flow`` knob is set, the HELLO advertises a "tr"
        # capability (symmetric like "rs"/"qz": an unset knob leaves
        # every wire byte, HELLO included, bit-for-bit unchanged), data
        # AMs toward tr-peers carry a (origin, span) trace context
        # inside their pickled payload, and heartbeat pings toward
        # tr-peers grow a trailing clock word — the pong echoes the
        # responder's monotonic clock, feeding an NTP-style midpoint
        # offset estimate per peer (EWMA, exported to the trace
        # metadata so the fleet merge can fuse rank timelines)
        if obs_flow is None:
            obs_flow = bool(params.get_or("obs_flow", "bool", False))
        # obs_live (ISSUE 16) rides the same machinery and adds its own
        # symmetric "lv" capability: toward lv-peers the stamped context
        # widens to (origin, span, pool, t_send_ns).  The knob implies
        # the obs_flow wire behavior (contexts + clock words) without
        # requiring both knobs; either knob unset on EITHER end keeps
        # that end's incoming wire bytes exactly what the unset build
        # would produce.
        if obs_live is None:
            obs_live = bool(params.get_or("obs_live", "bool", False))
        # closed-loop tuning (ISSUE 17): the controller renegotiates a
        # link's quantized codec at RUNTIME via K_TUNE frames — only
        # ever toward peers whose HELLO advertised the symmetric "tn"
        # capability (a mixed-version or knob-unset peer keeps the
        # codec its HELLO negotiated, forever).  The knob implies the
        # obs_live wire behavior: the controller's heartbeat is the
        # live monitor's window tick.
        if tune_auto is None:
            tune_auto = bool(params.get_or("tune_auto", "bool", False))
        # multi-tenant serving (ISSUE 18): SessionServer endpoints ride
        # a symmetric "sv" capability — toward sv-peers the live flow
        # context widens once more with the owning tenant's name, and
        # serve control AMs (TAG_SERVE/_REPLY) are accepted.  The knob
        # implies the obs_live wire behavior (tenant attribution rides
        # the extended contexts); unset on EITHER end keeps that end's
        # wire bytes exactly what the unset build would produce.
        if serve is None:
            serve = bool(params.get_or("serve", "bool", False))
        # device-plane transport (ISSUE 19): a symmetric "dp" capability
        # — bulk planner payloads toward dp-peers may ride an attached
        # DeviceDataPlane (descriptor/ack control stays on the session
        # wire, so replay and flap semantics are untouched).  Unset on
        # EITHER end keeps every wire byte, HELLO included, bit-for-bit
        # what the unset build would send.
        if dplane is None:
            dplane = bool(params.get_or("xfer_dplane", "bool", False))
        self._dp_enabled = bool(dplane)
        # cross-rank SPMD stages (ISSUE 20): the "xs" capability rides a
        # per-PROCESS random token, so it only negotiates between ranks
        # that share this process's XLA device pool (the one-program
        # lowering needs a common mesh); a knob-unset or mixed-version
        # peer simply never matches and keeps the activation path
        # bit-for-bit.  Symmetric like "dp": unset on EITHER end leaves
        # that end's HELLO bytes exactly what the unset build sends.
        if xstage is None:
            xstage = bool(params.get_or("stage_compile_xrank", "bool",
                                        False))
        self._xs_enabled = bool(xstage)
        self._serve_enabled = bool(serve)
        self._tune_enabled = bool(tune_auto)
        self._live_enabled = (bool(obs_live) or self._tune_enabled
                              or self._serve_enabled)
        self._flow_enabled = bool(obs_flow) or self._live_enabled
        self._clock: Dict[int, float] = {}      # peer -> offset EWMA us
        self._clock_n: Dict[int, int] = {}      # peer -> sample count
        self._clock_stop = threading.Event()
        self._clock_thread: Optional[threading.Thread] = None
        #: wire fast-path counters (plain dict: obs polls it when
        #: telemetry is on, nothing on the hot path otherwise)
        self.wire_stats = {
            "frames_sent": 0, "msgs_sent": 0, "coalesced_msgs": 0,
            "batches": 0, "chunks_sent": 0, "chunk_bytes_sent": 0,
            "frames_compressed": 0, "bytes_precompress": 0,
            "bytes_postcompress": 0, "msgs_chunked": 0,
            # quantized-codec counters (ISSUE 14): raw vs encoded bytes
            # of lossy-encoded bulk buffers (the labeled COMPRESS_RATIO
            # gauges ride the per-peer twins of these)
            "bufs_quantized": 0, "bytes_prequant": 0, "bytes_postquant": 0,
            # reliable-session counters (RECONNECTS / REPLAYED_FRAMES /
            # DUP_DROPPED gauges ride these)
            "reconnects": 0, "replayed_frames": 0, "dup_dropped": 0,
        }
        super().__init__(_FabricShim(len(endpoints)), rank)
        self.endpoints = endpoints
        self.connect_timeout = connect_timeout
        self.tag_register(TAG_BARRIER, self._on_barrier)

        host, port = endpoints[rank]
        self._listener = socket.create_server((host, port), backlog=len(endpoints))
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"tcp-accept-r{rank}")
        self._accept_thread.start()
        # dial lower ranks (they accept); retry while peers boot
        deadline = time.time() + connect_timeout
        for peer in range(rank):
            self._dial(peer, deadline)
        if self._flow_enabled and self.nb_ranks > 1:
            # clock-alignment sampler (ISSUE 15): periodic extended
            # pings toward tr-peers so offsets exist even when the
            # heartbeat detector is not installed; the detector's own
            # probes contribute extra samples for free
            self._clock_thread = threading.Thread(
                target=self._clock_loop, daemon=True,
                name=f"tcp-clock-r{rank}")
            self._clock_thread.start()

    # -- connection management ------------------------------------------
    def _dial(self, peer: int, deadline: float) -> None:
        host, port = self.endpoints[peer]
        while True:
            try:
                sock = socket.create_connection((host, port), timeout=2.0)
                break
            except OSError:
                if time.time() > deadline:
                    raise TimeoutError(
                        f"rank {self.rank}: cannot reach rank {peer} at "
                        f"{host}:{port}")
                time.sleep(0.05)
        sock.settimeout(None)  # create_connection left timeout mode on
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.sendall(struct.pack("<I", self.rank))
        self._register_conn(peer, sock)

    def _accept_loop(self) -> None:
        try:
            while not self._closing:
                sock, _addr = self._listener.accept()
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                # bounded handshake: a stray connection that never sends
                # its rank must not starve accepts from real peers
                sock.settimeout(5.0)
                try:
                    hdr = self._recv_exact(sock, 4)
                except OSError:
                    hdr = None
                if hdr is None:
                    sock.close()
                    continue
                sock.settimeout(None)
                (peer,) = struct.unpack("<I", hdr)
                with self._conn_cond:
                    known = self._peers.get(peer)
                if peer >= self.nb_ranks or peer == self.rank:
                    sock.close()
                    continue
                if known is not None:
                    # a re-dial from a known peer: a session resume when
                    # both ends negotiated "rs" (the peer may have seen
                    # the link fault before we did), else a stray
                    # duplicate that must never displace a real socket.
                    # Handled OFF the accept thread: one peer's slow
                    # handshake (or the thread joins inside the resume)
                    # must not stall every other peer's reconnect past
                    # its budget.
                    threading.Thread(
                        target=self._accept_resume, args=(known, sock),
                        daemon=True,
                        name=f"tcp-resume-r{self.rank}p{peer}").start()
                    continue
                self._register_conn(peer, sock)
        except OSError:
            return  # listener closed during fini

    def _register_conn(self, peer: int, sock: socket.socket) -> None:
        p = _Peer(peer, sock)
        with self._conn_cond:
            self._peers[peer] = p
            self._conn_cond.notify_all()
        p.writer = threading.Thread(
            target=self._writer_loop, args=(p, 0), daemon=True,
            name=f"tcp-send-r{self.rank}p{peer}")
        p.writer.start()
        t = threading.Thread(target=self._recv_loop, args=(p, sock, 0),
                             daemon=True, name=f"tcp-recv-r{self.rank}p{peer}")
        p.recv_thread = t
        t.start()
        with self._conn_cond:
            self._recv_threads.append(t)
        # capability advertisement: the receiving end only ever
        # compresses toward us after seeing this (mixed-version peers
        # never send one and stay on the uncompressed path); "rs" is
        # advertised only when reconnect sessions are enabled locally,
        # so a peer with the knob unset keeps fail-fast on BOTH ends
        info = {"ver": wire.WIRE_VERSION,
                "rank": self.rank,
                "codecs": self._codecs,
                "hb": True,
                "el": True,
                "rs": self._rs_enabled}
        if self._flow_enabled:
            # flow tracing is advertised ONLY when the local knob is
            # set (symmetric like "qz"): a knob-unset build keeps every
            # wire byte — this HELLO included — bit-for-bit, and a
            # mixed-version peer simply never negotiates, so neither
            # trace contexts nor extended pings travel toward it
            info["tr"] = True
        if self._live_enabled:
            # obs_live (ISSUE 16): extended (pool, send-instant) flow
            # contexts — gated like "tr", so an unset knob's HELLO is
            # bit-identical and obs_flow-only peers keep 2-tuples
            info["lv"] = True
        if self._tune_enabled:
            # runtime tuning (ISSUE 17): this end accepts K_TUNE codec
            # renegotiation frames — gated like "tr"/"lv" so an unset
            # knob's HELLO stays bit-identical and a mixed-version peer
            # is never renegotiated
            info["tn"] = True
        if self._serve_enabled:
            # multi-tenant serving (ISSUE 18): this end hosts/uses
            # SessionServer endpoints and accepts tenant-extended flow
            # contexts — gated like "tr"/"lv"/"tn" so an unset knob's
            # HELLO stays bit-identical and a mixed-version peer never
            # sees a 5-tuple or a serve control frame
            info["sv"] = True
        if self._dp_enabled:
            # device-plane transport (ISSUE 19): this end may pull bulk
            # planner payloads over an attached DeviceDataPlane — gated
            # like "tr"/"lv"/"tn"/"sv" so an unset knob's HELLO stays
            # bit-identical and a mixed-version peer's bulk bytes stay
            # on the session wire
            info["dp"] = True
        if self._xs_enabled:
            # cross-rank SPMD stages (ISSUE 20): the advertised value is
            # a per-process random token, not a bare True — the receive
            # side negotiates "xs" only on token EQUALITY, which proves
            # both ranks live in THIS process (shared XLA device pool,
            # the precondition for lowering one program across them).
            # Gated like "dp" so an unset knob's HELLO stays
            # bit-identical and a mixed-version peer never negotiates.
            info["xs"] = _xs_proc_token()
        if self._quantize is not None:
            # quantized codecs are advertised ONLY when the local knob
            # is set — symmetric like "rs", so a knob-unset build keeps
            # every wire byte (this HELLO included) bit-for-bit, and a
            # mixed-version peer (no "qz") negotiates down to lossless
            info["qz"] = wire.available_quant_codecs()
        hello = wire.pack_hello(info)
        with p.cond:
            p.ctrl.append(("frame", hello))
            p.queued_bytes += len(hello)
            p.cond.notify()

    def _peer_to(self, peer: int) -> _Peer:
        with self._conn_cond:
            ok = self._conn_cond.wait_for(lambda: peer in self._peers,
                                          timeout=self.connect_timeout)
            if not ok:
                raise TimeoutError(
                    f"rank {self.rank}: no connection from rank {peer}")
            return self._peers[peer]

    # kept for tests/back-compat: peer -> socket view
    @property
    def _conns(self) -> Dict[int, socket.socket]:
        with self._conn_cond:
            return {r: p.sock for r, p in self._peers.items()}

    def link_bw_mbps(self, peer: int) -> Optional[float]:
        """Send-side bandwidth EWMA toward ``peer`` in MB/s (None until
        a large-enough send has been measured). Feeds the adaptive
        eager/rendezvous cutoff (remote_dep) and the LINK_BW gauges."""
        with self._conn_cond:
            p = self._peers.get(peer)
        return p.bw_mbps if p is not None else None

    def chunks_inflight(self) -> int:
        """Queued-but-unsent chunk SEGMENTS plus receive-side
        incomplete TRANSFERS (the CHUNKS_INFLIGHT gauge; transfer
        headers riding the bulk lane are not counted)."""
        n = 0
        with self._conn_cond:
            peers = list(self._peers.values())
        for p in peers:
            # under p.cond: the writer mutates the deque concurrently,
            # and iterating a mutating deque raises RuntimeError
            with p.cond:
                n += sum(1 for it in p.bulk if it[0] == "chunk")
        with self._stat_lock:
            n += sum(self._rx_pending.values())
        return n

    def compress_ratio(self) -> Optional[float]:
        """Cumulative post/pre compression byte ratio (None: nothing
        was ever compressed)."""
        with self._stat_lock:
            pre = self.wire_stats["bytes_precompress"]
            post = self.wire_stats["bytes_postcompress"]
        return (post / pre) if pre else None

    # -- quantized wire codecs (ISSUE 14) -------------------------------
    def _quant_codec_for(self, peer: _Peer) -> Optional[str]:
        """The quantized codec to apply toward ``peer`` right now:
        None unless the HELLO negotiation succeeded (both knobs set,
        codec common) AND the link sits below the bandwidth-EWMA
        threshold (``comm_quantize_threshold_mbps``; 0 = engage
        whenever the knob is set — the same per-link EWMA policy the
        lossless compressor uses, with an always-on default because
        the knob itself is the lossy opt-in)."""
        with peer.cond:
            codec = peer.qz_codec
        if codec is None:
            return None
        thr = self.quantize_threshold_mbps
        if thr:
            bw = peer.bw_mbps
            if bw is None or bw >= thr:
                return None
        return codec

    def quantize_ratio(self) -> Optional[float]:
        """Cumulative raw/encoded byte RATIO of quantized buffers
        (> 1 = the wire moved fewer bytes; None: nothing quantized)."""
        with self._stat_lock:
            pre = self.wire_stats["bytes_prequant"]
            post = self.wire_stats["bytes_postquant"]
        return (pre / post) if post else None

    def wire_codec_names(self):
        """Every registered codec name (lossless + quantized) — the
        label set of the per-peer COMPRESS_RATIO gauges."""
        return sorted(wire.CODECS)

    def codec_ratio(self, peer: int, codec: str) -> float:
        """Per-link per-codec byte-reduction factor raw/encoded (the
        labeled ``COMPRESS_RATIO::R<peer>::<codec>`` gauge): > 1 once
        that codec engaged on the link, 1.0 while it has not (not
        negotiated, threshold not crossed, or nothing sent yet)."""
        with self._conn_cond:
            p = self._peers.get(peer)
        if p is None:
            return 1.0
        ent = wire.CODECS.get(codec)
        with p.cond:
            if ent is not None and not ent.lossless:
                pre, post = ((p.q_pre, p.q_post)
                             if p.qz_codec == codec else (0, 0))
            else:
                pre, post = ((p.comp_pre, p.comp_post)
                             if p.codec == codec else (0, 0))
        return round(pre / post, 4) if post else 1.0

    # -- clock alignment + flow tracing (ISSUE 15) ----------------------
    #: EWMA smoothing of the per-peer offset estimate, and the sampler
    #: thread's cadence: a quick burst for fresh links (offsets exist
    #: within ~a second of the HELLO), then a slow steady trickle
    _CLOCK_ALPHA = 0.25
    _CLOCK_BURST = 4
    _CLOCK_BURST_INTERVAL = 0.05
    _CLOCK_INTERVAL = 0.25

    def _note_clock(self, peer: int, offset_us: float) -> None:
        with self._stat_lock:
            cur = self._clock.get(peer)
            self._clock[peer] = (offset_us if cur is None else
                                 (1 - self._CLOCK_ALPHA) * cur
                                 + self._CLOCK_ALPHA * offset_us)
            self._clock_n[peer] = self._clock_n.get(peer, 0) + 1

    def clock_offset_us(self, peer: int) -> Optional[float]:
        """NTP-style estimate of ``peer_clock - my_clock`` in µs (the
        ``PARSEC::OBS::CLOCK_OFFSET_US::R<peer>`` gauge; None until a
        clock-extended pong has been measured)."""
        with self._stat_lock:
            off = self._clock.get(peer)
        return None if off is None else round(off, 3)

    def clock_offsets_us(self) -> Dict[int, float]:
        """Every measured per-peer offset — stamped into the trace
        metadata at export so tools/obs_trace_merge.py can fuse the
        rank timelines onto one reference clock."""
        with self._stat_lock:
            return {p: round(v, 3) for p, v in self._clock.items()}

    def _clock_loop(self) -> None:
        """Dedicated sampler: one extended ping per tr-peer per tick.
        Rides ``ft_ping`` (ctrl lane, receiver-thread pong), so the
        chaos layer's ``hb=1`` directives shape these probes exactly
        like detector probes — the clock-error-under-asymmetric-delay
        tests inject through the same seam."""
        seq = 1 << 24   # distinct range from the detector's seqs
        rounds = 0
        while not self._clock_stop.wait(
                self._CLOCK_BURST_INTERVAL if rounds < self._CLOCK_BURST
                else self._CLOCK_INTERVAL):
            if self._closing or self._ft_silenced:
                return
            rounds += 1
            with self._conn_cond:
                peers = list(self._peers.values())
            for p in peers:
                if not p.tr_ok or p.done or p.rank in self.dead_peers \
                        or p.rank in self.finished_peers:
                    continue
                seq += 1
                try:
                    self.ft_ping(p.rank, seq, time.monotonic_ns())
                except Exception:  # noqa: BLE001 - sampling must not die
                    pass

    def mesh_local_with(self, peer: int) -> bool:
        """Cross-process ranks NEVER share an XLA client — the
        in-process fabric's ship-by-reference fast path (inherited from
        LocalCommEngine) must not fire here, or device-array payloads
        get silently pickled inside the activation instead of riding
        the device plane / GET rendezvous."""
        return False

    def flow_to(self, dst: int) -> bool:
        """Trace contexts travel only toward peers whose HELLO
        advertised ``"tr"`` — a mixed-version (or knob-unset) peer
        receives byte-identical data-plane traffic."""
        with self._conn_cond:
            p = self._peers.get(dst)
        return p is not None and p.tr_ok

    def live_to(self, dst: int) -> bool:
        """Extended obs_live contexts travel only toward peers whose
        HELLO advertised ``"lv"`` — an obs_flow-only (or older) peer
        keeps receiving the plain 2-tuple its unpacking expects."""
        with self._conn_cond:
            p = self._peers.get(dst)
        return p is not None and p.lv_ok

    def serve_to(self, dst: int) -> bool:
        """Tenant-extended serve contexts (and serve control AMs,
        ISSUE 18) travel only toward peers whose HELLO advertised
        ``"sv"`` — a live-only (or older) peer keeps receiving the
        4-tuple its unpacking expects."""
        with self._conn_cond:
            p = self._peers.get(dst)
        return p is not None and p.sv_ok

    def dplane_to(self, dst: int) -> bool:
        """Bulk planner payloads toward ``dst`` may leave the session
        wire for the device plane only when a plane is attached AND the
        peer's HELLO advertised ``"dp"`` (ISSUE 19) — a mixed-version
        or knob-unset peer keeps receiving the full payload on the
        session wire, byte-identical to an unset build."""
        if getattr(self, "device_plane", None) is None:
            return False
        with self._conn_cond:
            p = self._peers.get(dst)
        return p is not None and p.dp_ok

    def xstage_to(self, dst: int, wait_s: float = 5.0) -> bool:
        """Cross-rank SPMD stages may span ``dst`` only when the peer's
        HELLO carried THIS process's "xs" token (ISSUE 20) — i.e. both
        ends run with ``stage_compile_xrank`` set AND share one XLA
        device pool.  A mixed-version or knob-unset peer keeps today's
        activation path bit-for-bit.  The HELLO is the link's first
        frame but lands on the receiver thread, so a caller racing the
        dial waits (bounded) for it — answering from a not-yet-seen
        HELLO would negotiate DOWN spuriously and strand the peers on
        asymmetric plans until the install timeout."""
        with self._conn_cond:
            p = self._peers.get(dst)
        if p is None:
            return False
        if self._xs_enabled and not p.hello_seen:
            deadline = time.time() + wait_s
            with p.cond:
                while not p.hello_seen:
                    left = deadline - time.time()
                    if left <= 0:
                        break
                    p.cond.wait(min(0.1, left))
        return p.xs_ok

    # -- reliable sessions (ISSUE 10) -----------------------------------
    def peer_suspect(self, peer: int) -> bool:
        """True while ``peer``'s link is torn but its session is still
        inside the reconnect budget — the transient-vs-permanent
        distinction consumers park on (detector deferral, prefetch
        throttling) instead of treating every socket error as death."""
        with self._conn_cond:
            p = self._peers.get(peer)
        if p is None:
            return False
        with p.cond:
            return p.suspect

    def suspect_ms(self) -> float:
        """Cumulative milliseconds peers of this rank have spent in
        SUSPECT (completed episodes plus any live one) — the
        COMM::SUSPECT_MS gauge."""
        with self._stat_lock:
            total = self._suspect_ms_total
        now = time.monotonic()
        with self._conn_cond:
            peers = list(self._peers.values())
        for p in peers:
            with p.cond:
                if p.suspect:
                    total += (now - p.suspect_since) * 1e3
        return round(total, 3)

    def _session_suspect(self, p: _Peer, gen: int, reason: str) -> bool:
        """A writer/receiver of connection generation ``gen`` hit a
        socket fault. Returns True when the fault is ABSORBED by the
        session layer (peer parked as SUSPECT, reconnector running —
        or the fault belongs to an already-replaced generation); False
        means no session covers this link and the caller must take the
        fail-fast ``_peer_died`` path."""
        if not self._rs_enabled:
            return False
        with p.cond:
            if p.conn_gen != gen:
                return True   # stale thread of a resumed connection
            if not p.rs_ok or p.done:
                return False
        if self._closing or self._ft_silenced \
                or p.rank in self.dead_peers \
                or p.rank in self.finished_peers:
            return False
        first = False
        with p.cond:
            if not p.suspect:
                p.suspect = True
                p.suspect_since = time.monotonic()
                first = True
            p.cond.notify_all()
        if first:
            # kick the other thread of this generation out of its
            # blocking socket call so both land here (idempotent)
            try:
                p.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                p.sock.close()
            except OSError:
                pass
            plog.warning(
                "tcp rank %d: peer %d SUSPECT (%s) — reconnecting for "
                "up to %.1fs", self.rank, p.rank, reason,
                self.reconnect_timeout)
            threading.Thread(
                target=self._reconnector, args=(p, gen), daemon=True,
                name=f"tcp-reconnect-r{self.rank}p{p.rank}").start()
        return True

    def _reconnector(self, p: _Peer, gen: int) -> None:
        """Drive one SUSPECT episode: the side that originally dialed
        (the higher rank) re-dials with exponential backoff + jitter;
        the accepting side waits passively (``_accept_resume`` does the
        work when the peer's dial lands). Either way the episode is
        bounded by ``comm_reconnect_timeout``: expiry escalates to the
        fail-fast path with ``lost_sends`` (the replay window holds
        accepted frames that will now never be delivered)."""
        import random
        with p.cond:
            deadline = p.suspect_since + self.reconnect_timeout
        delay = self.reconnect_backoff
        rng = random.Random((self.rank << 16) ^ p.rank ^ id(p))
        while True:
            if self._closing or self._ft_silenced \
                    or p.rank in self.dead_peers \
                    or p.rank in self.finished_peers:
                return
            with p.cond:
                if not p.suspect or p.conn_gen != gen or p.done:
                    return   # resumed (or escalated elsewhere)
            now = time.monotonic()
            if now >= deadline:
                with p.cond:
                    if not p.suspect or p.conn_gen != gen or p.done:
                        return
                    p.done = True   # tombstone: no late resume may land
                    p.suspect = False
                    dur_ms = (now - p.suspect_since) * 1e3
                with self._stat_lock:
                    self._suspect_ms_total += dur_ms
                self._peer_died(
                    p.rank,
                    f"reconnect budget exhausted "
                    f"({self.reconnect_timeout:.1f}s)", lost_sends=True)
                return
            ft = self._ft
            link_down = ft is not None and ft.link_down(p.rank)
            if self.rank > p.rank and not link_down:
                try:
                    self._dial_resume(p, gen)
                    return
                except (OSError, ValueError):
                    pass   # next attempt after backoff
            time.sleep(min(delay, max(0.0, deadline - time.monotonic())))
            delay = min(delay * 2.0, _RECONNECT_BACKOFF_MAX) \
                * (1.0 + 0.25 * rng.random())

    def _send_frame_direct(self, sock: socket.socket, body: bytes) -> None:
        sock.sendall(struct.pack("<Q", len(body)) + body)

    def _recv_frame_direct(self, sock: socket.socket) -> memoryview:
        hdr = self._recv_exact(sock, 8)
        if hdr is None:
            raise OSError("connection closed during session resume")
        (size,) = struct.unpack("<Q", hdr)
        if size > (1 << 20):
            raise ValueError(f"oversized resume frame ({size} bytes)")
        body = self._recv_exact(sock, size)
        if body is None:
            raise OSError("connection closed during session resume")
        return memoryview(body)

    def _partial_claim_locked(self, p: _Peer) -> Optional[Dict[str, int]]:
        # holds: p.cond
        """The byte-level resume claim for K_RESUME: only a partial
        body that provably is the NEXT expected data frame (a complete
        K_SEQ header with seq == last delivered + 1) can resume
        mid-frame; anything else (truncated header, a torn session-less
        frame) is discarded and the sender replays whole frames."""
        part = p.rs_rx_partial
        if part is None:
            return None
        size, buf = part
        pref = wire.parse_seq_prefix(buf)
        if pref is not None and pref[1] == p.rs_rx_seq + 1 \
                and 0 < len(buf) < size:
            return {"seq": pref[1], "off": len(buf)}
        p.rs_rx_partial = None
        return None

    def _dial_resume(self, p: _Peer, gen: int) -> None:
        """One reconnect attempt from the dialing side; raises
        OSError/ValueError on any failure (the reconnector retries)."""
        host, port = self.endpoints[p.rank]
        sock = socket.create_connection((host, port), timeout=2.0)
        ok = False
        try:
            sock.settimeout(5.0)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.sendall(struct.pack("<I", self.rank))
            with p.cond:
                epoch = p.rs_epoch + 1
                info = {"rank": self.rank, "epoch": epoch,
                        "ack": p.rs_rx_seq,
                        "partial": self._partial_claim_locked(p)}
            self._send_frame_direct(sock, wire.pack_resume(info))
            body = self._recv_frame_direct(sock)
            if body[0] != wire.K_RESUME:
                raise ValueError("peer did not answer the session resume")
            reply = wire.parse_resume(body)
            if int(reply.get("epoch", -1)) != epoch:
                raise ValueError("session epoch mismatch at resume")
            sock.settimeout(None)
            self._session_resume(p, sock, epoch, int(reply["ack"]),
                                 reply.get("partial"))
            ok = True
        finally:
            if not ok:
                try:
                    sock.close()
                except OSError:
                    pass

    def _accept_resume(self, p: _Peer, sock: socket.socket) -> None:
        """The accepting half of a session resume (a known peer
        re-dialed us). Anything short of a valid K_RESUME from a
        session-capable peer is a stray duplicate connection and is
        closed, exactly as before."""
        ft = self._ft
        if not (self._rs_enabled and not self._closing) \
                or p.rank in self.dead_peers \
                or p.rank in self.finished_peers \
                or (ft is not None and ft.link_down(p.rank)):
            sock.close()
            return
        with p.cond:
            rs_ok = p.rs_ok and not p.done and not p.rs_resuming
            if rs_ok:
                p.rs_resuming = True
        if not rs_ok:
            sock.close()
            return
        try:
            sock.settimeout(5.0)
            body = self._recv_frame_direct(sock)
            if body[0] != wire.K_RESUME:
                raise ValueError("known peer re-dialed without K_RESUME")
            info = wire.parse_resume(body)
            epoch = int(info["epoch"])
            with p.cond:
                gen = p.conn_gen
                # equal epochs are RESUMABLE, not stale: if our side
                # committed epoch N but the dialer's half of that
                # handshake failed (link tore again around the reply),
                # its retries keep proposing N — rejecting them would
                # dead-end a healthy link until the budget expires.
                # Only a strictly OLDER epoch is a stray duplicate.
                if epoch < p.rs_epoch:
                    raise ValueError("stale session epoch at resume")
            # the peer noticed the fault first: tear our half down too
            # so the old generation's threads exit before the handoff
            if not self._session_suspect(p, gen,
                                         "peer initiated session resume"):
                raise ValueError("session no longer resumable")
            with p.cond:
                reply = {"rank": self.rank, "epoch": epoch,
                         "ack": p.rs_rx_seq,
                         "partial": self._partial_claim_locked(p)}
            self._send_frame_direct(sock, wire.pack_resume(reply))
            sock.settimeout(None)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._session_resume(p, sock, epoch, int(info["ack"]),
                                 info.get("partial"))
        except (OSError, ValueError) as exc:
            plog.debug.verbose(
                1, "tcp rank %d: resume from peer %d rejected (%s)",
                self.rank, p.rank, exc)
            try:
                sock.close()
            except OSError:
                pass
        finally:
            with p.cond:
                p.rs_resuming = False

    def _session_resume(self, p: _Peer, sock: socket.socket, epoch: int,
                        their_ack: int,
                        their_partial: Optional[Dict[str, int]]) -> None:
        """Install a re-established connection: trim the replay window
        to the peer's cumulative ack, stage the unacked gap (byte-level
        frag of a mid-frame truncation first, then whole frames) for
        the new writer, bump the generation so stale threads stand
        down, and start fresh writer/receiver threads."""
        # the old generation's threads saw their socket die when the
        # suspect transition closed it; wait for them so no stale
        # writer can interleave on the NEW socket (thread joins are
        # blocking — strictly outside every lock)
        old_writer, old_recv = p.writer, p.recv_thread
        for t in (old_writer, old_recv):
            if t is not None and t is not threading.current_thread():
                t.join(timeout=10.0)
                if t.is_alive():  # pragma: no cover - wedged handler
                    raise ValueError("previous connection generation "
                                     "did not exit; resume aborted")
        with p.cond:
            if p.done or p.rank in self.dead_peers:
                raise ValueError("session escalated before resume landed")
            while p.rs_window and p.rs_window[0][0] <= their_ack:
                _seq, _pieces, nb = p.rs_window.popleft()
                p.rs_window_bytes -= nb
            replay: list = []
            entries = list(p.rs_window)
            if entries and their_partial:
                seq0, pieces0, _nb0 = entries[0]
                off = int(their_partial.get("off", 0))
                if int(their_partial.get("seq", -1)) == seq0:
                    body0 = b"".join(bytes(x) for x in pieces0)
                    if 0 < off < len(body0):
                        replay.append([wire.pack_frag(epoch, seq0, off),
                                       body0[off:]])
                        entries = entries[1:]
            for _seq, pieces, _nb in entries:
                replay.append(list(pieces))
            p.rs_replay = replay
            p.rs_epoch = epoch
            p.conn_gen += 1
            gen = p.conn_gen
            p.sock = sock
            p.suspect = False
            dur_ms = (time.monotonic() - p.suspect_since) * 1e3
            nreplay = len(replay)
            p.cond.notify_all()
        with self._stat_lock:
            self.wire_stats["reconnects"] += 1
            self.wire_stats["replayed_frames"] += nreplay
            self._suspect_ms_total += dur_ms
        # a completed resume handshake is proof of life: reset the
        # heartbeat silence baseline so the detector does not evict the
        # peer in the race between the resume and its first fresh pong
        det = self.ft_detector
        if det is not None:
            det.note_alive(p.rank)
        plog.warning(
            "tcp rank %d: session to peer %d RESUMED after %.0f ms "
            "(epoch %d, replaying %d frame(s))", self.rank, p.rank,
            dur_ms, epoch, nreplay)
        p.writer = threading.Thread(
            target=self._writer_loop, args=(p, gen), daemon=True,
            name=f"tcp-send-r{self.rank}p{p.rank}g{gen}")
        p.writer.start()
        t = threading.Thread(
            target=self._recv_loop, args=(p, sock, gen), daemon=True,
            name=f"tcp-recv-r{self.rank}p{p.rank}g{gen}")
        p.recv_thread = t
        t.start()
        # prune dead generations while appending (under the connection
        # lock: concurrent resumes of DIFFERENT peers rebuild this list
        # too): a long soak of flaps must not grow it without bound
        with self._conn_cond:
            self._recv_threads = [x for x in self._recv_threads
                                  if x.is_alive()] + [t]

    def ft_link_fault(self, dst: int) -> None:
        """Chaos hook (ft/inject.py ``flap:``/``disconnect:``): tear
        this rank's socket(s) to every peer the injector marked
        link-down (always including ``dst``, the triggering send's
        target) WITHOUT killing the process — both ends see a torn
        connection, which is a SUSPECT transition under a session and
        instant death without one.

        The tear is a WRITE-half shutdown, not a close: the next local
        write fails at once (the triggering frame — enqueued right
        after this hook — is picked up by the writer, retained in the
        replay window, and its send fails, so a session flap provably
        exercises the replay path), the peer sees EOF and parks its own
        half, and the suspect/death transition closes the socket
        fully."""
        ft = self._ft
        with self._conn_cond:
            peers = list(self._peers.values())
        for p in peers:
            if p.rank != dst and not (ft is not None
                                      and ft.link_down(p.rank)):
                continue
            try:
                p.sock.shutdown(socket.SHUT_WR)
            except OSError:
                pass

    # -- fault tolerance ------------------------------------------------
    def ft_ping(self, peer: int, seq: int, t_ns: int) -> bool:
        """Wire-level heartbeat probe (K_PING): enqueued straight onto
        the peer's ctrl lane and answered by the peer's receiver
        thread. Never sent toward a peer whose HELLO did not advertise
        heartbeat support — a mixed-version peer is never probed, so
        the detector can never (wrongly) declare it dead."""
        if self._ft_silenced or peer in self.dead_peers \
                or peer in self.finished_peers:
            return False
        with self._conn_cond:
            p = self._peers.get(peer)
        if p is None or not p.hb_ok or p.done:
            return False
        with p.cond:
            if p.suspect:
                # the link is torn and the session layer owns the
                # verdict: a probe could not leave anyway, and the
                # detector must not count this interval as silence
                return False
        # probe frames bypass _transport_post, so consult the chaos
        # layer here too — ft_inject directives with hb=1 must be able
        # to drop/duplicate heartbeats on this transport as well
        from .engine import TAG_HEARTBEAT
        copies = self.ft_outbound(peer, TAG_HEARTBEAT)
        if copies == 0:
            return False
        # clock-alignment extension (ISSUE 15): extended pings only
        # toward peers that negotiated "tr" — the responding pong
        # carries the peer's clock, the midpoint-method sample
        frame = wire.pack_ping(
            seq, t_ns, clock_ns=0 if p.tr_ok else None)
        with p.cond:
            for _ in range(copies):
                p.ctrl.append(("frame", frame))
                p.queued_bytes += len(frame)
            p.cond.notify()
        return True

    def ft_elastic_send(self, peer: int, payload) -> bool:
        """Wire-level elastic membership frame (K_ELASTIC): like
        ``ft_ping``, enqueued on the ctrl lane and delivered by the
        peer's receiver thread — a resize proposal or join
        announcement lands even while every worker is wedged in a long
        kernel. Gated on the HELLO ``el`` capability: a pre-elastic
        peer is never drawn into an agreement it cannot answer.
        Exempt from the chaos layer (control plane, like heartbeats
        without ``hb=1``); the coordinator's resend tick covers real
        frame loss."""
        if self._ft_silenced or peer in self.dead_peers \
                or peer in self.finished_peers:
            return False
        with self._conn_cond:
            p = self._peers.get(peer)
        if p is None or not p.el_ok or p.done:
            return False
        frame = wire.pack_elastic(dict(payload))
        with p.cond:
            p.ctrl.append(("frame", frame))
            p.queued_bytes += len(frame)
            p.cond.notify()
        return True

    # -- closed-loop tuning (ISSUE 17) ----------------------------------
    def tune_to(self, dst: int) -> bool:
        """K_TUNE renegotiation frames travel only toward peers whose
        HELLO advertised ``"tn"`` — a mixed-version (or knob-unset)
        peer keeps the codec its HELLO negotiated, forever."""
        with self._conn_cond:
            p = self._peers.get(dst)
        return p is not None and p.tn_ok

    def tune_send(self, peer: int, payload) -> bool:
        """Wire-level runtime-tuning frame (K_TUNE): like
        ``ft_elastic_send``, enqueued on the ctrl lane and applied by
        the peer's receiver thread — a codec renegotiation lands even
        while the peer's workers are wedged in a long kernel.  Gated on
        the HELLO ``tn`` capability: a mixed-version peer is never
        renegotiated.  Exempt from the chaos layer (control plane);
        the controller re-decides every window, so a lost frame is
        re-issued by the next tick."""
        if self._ft_silenced or peer in self.dead_peers \
                or peer in self.finished_peers:
            return False
        with self._conn_cond:
            p = self._peers.get(peer)
        if p is None or not p.tn_ok or p.done:
            return False
        frame = wire.pack_tune(dict(payload))
        with p.cond:
            p.ctrl.append(("frame", frame))
            p.queued_bytes += len(frame)
            p.cond.notify()
        return True

    def set_quant_codec(self, peer: int, codec: Optional[str]) -> bool:
        """Local half of a codec renegotiation: install ``codec`` (a
        registered quantized codec name, or None for lossless) as THIS
        rank's active encoding toward ``peer``, exactly as if the HELLO
        had negotiated it.  Quantization applies at enqueue, so frames
        already queued (and the replay window) keep the bytes encoded
        under the codec active when they were accepted — a replay stays
        bit-identical across the switch.  Resets the per-codec byte
        accounting so the COMPRESS_RATIO gauge reflects the NEW codec.
        Returns False (no change) toward an unknown peer or a codec
        name that is not registered."""
        if codec is not None and codec not in wire.available_quant_codecs():
            return False
        with self._conn_cond:
            p = self._peers.get(peer)
        if p is None:
            return False
        with p.cond:
            if p.qz_codec != codec:
                p.qz_codec = codec
                p.q_pre = 0
                p.q_post = 0
        return True

    def active_quant_codec(self, peer: int) -> Optional[str]:
        """The quantized codec THIS rank currently encodes with toward
        ``peer`` (HELLO-negotiated or runtime-renegotiated)."""
        with self._conn_cond:
            p = self._peers.get(peer)
        if p is None:
            return None
        with p.cond:
            return p.qz_codec

    def rx_quant_ratio(self, peer: int) -> Tuple[int, int]:
        """Receive-side quantized-buffer accounting for the inbound
        link from ``peer``: (raw bytes, encoded bytes) of quantized
        buffers that LANDED here.  The controller on the receiving
        rank reads the deltas: an escalated link whose encoded count
        stops moving carries no eligible traffic — the codec shows no
        win and the ladder steps back down."""
        with self._conn_cond:
            p = self._peers.get(peer)
        if p is None:
            return (0, 0)
        with p.cond:
            return (p.qrx_pre, p.qrx_post)

    def _on_tune(self, p: _Peer, msg: Dict[str, Any]) -> None:
        """Apply one runtime-tuning directive from the controller on
        the RECEIVING end of this link (it watches its inbound
        exposed-wait; we hold the actuator — the send-side codec).
        Only honored between ends that both advertised "tn"; an
        unknown op or codec name is dropped, never fatal (the two ends
        may trail each other by a release)."""
        if not (self._tune_enabled and p.tn_ok):
            return
        if msg.get("op") != "codec":
            plog.debug.verbose(
                1, "tcp rank %d: ignoring unknown tune op %r from "
                "peer %d", self.rank, msg.get("op"), p.rank)
            return
        codec = msg.get("codec")
        if not self.set_quant_codec(p.rank, codec):
            plog.warning(
                "tcp rank %d: peer %d requested unknown quantized "
                "codec %r — keeping %r", self.rank, p.rank, codec,
                self.active_quant_codec(p.rank))

    def report_peer_failure(self, peer: int, reason: str) -> None:
        """Uniform failure funnel (base-class API): a proactive
        (heartbeat) eviction is unconditional — the peer is SILENT, so
        unlike a torn connection there is no may-have-finished
        ambiguity for the reporting policy to weigh."""
        self._peer_died(peer, reason, lost_sends=True)

    def ft_silence(self) -> None:
        """Injected kill: beyond the base flag, wake every writer so it
        exits WITHOUT flushing its queue — a real SIGKILL drops queued
        frames, and survivors must not observe a message sequence that
        is impossible under a real crash."""
        super().ft_silence()
        with self._conn_cond:
            peers = list(self._peers.values())
        for p in peers:
            with p.cond:
                p.cond.notify_all()

    def peer_finished(self, peer: int) -> bool:
        return peer in self.finished_peers

    # -- send path ------------------------------------------------------
    def send_am(self, dst: int, tag: int, payload: Any) -> None:
        # remote sends serialize via pickle (its own copy); only loopback
        # needs the anti-aliasing wire copy the local fabric applies
        if dst == self.rank:
            payload = _wire_copy(payload)
        obs = self._obs
        ctx = None
        if self._flow is not None or self._flow_enabled:
            # _flow_enabled without an armed allocator (knob on,
            # telemetry off): the stamp declines but still STRIPS a
            # re-forwarded inbound "_tr" — this rank advertised "tr",
            # so upstream contexts reach it and must not leak onward
            payload, ctx = self._flow_stamp(dst, tag, payload)
        if obs is None:
            self._transport_post(dst, self.rank, tag, payload)
            return
        t0 = time.monotonic_ns()
        self._transport_post(dst, self.rank, tag, payload)
        obs.am_sent(self.rank, dst, tag, payload, t0)
        if ctx is not None:
            obs.flow_sent(dst, tag, ctx, t0)

    def _transport_post(self, dst: int, src: int, tag: int, payload: Any) -> None:
        copies = self.ft_outbound(dst, tag)
        if copies <= 0:
            return
        self._transport_post_live(dst, src, tag, payload)
        if copies <= 1:
            return
        # injected duplicate: on a session link the duplicate happens
        # at the WIRE level (same frame, same seq — the receiver's
        # seq dedup must keep the AM exactly-once); without a session
        # it stays a double post, the historical deliver-twice chaos
        rs = False
        if dst != self.rank:
            with self._conn_cond:
                p = self._peers.get(dst)
            if p is not None:
                with p.cond:
                    rs = p.rs_ok
                    if rs:
                        p.rs_dup_next = True
        if not rs:
            for _ in range(copies - 1):
                self._transport_post_live(dst, src, tag, payload)

    def _transport_post_live(self, dst: int, src: int, tag: int,
                             payload: Any) -> None:
        self._check_live(dst)
        if dst == self.rank:
            with self._stat_lock:
                self.fabric.msg_count += 1
            self._inbox.push((src, tag, payload))
            self._notify_arrival()
            return
        # protocol-5 out-of-band pickling: ndarray payloads are NOT
        # serialized into the frame — their buffers are collected as
        # views. Buffers below the chunk threshold are COPIED into the
        # queued segment here, on the caller's thread (the historical
        # copy-at-send snapshot semantics: inline activation payloads
        # may be mutated by a local successor right after this call
        # returns). Buffers >= the threshold stream as chunks; they
        # stay zero-copy ONLY when provably immutable (a read-only
        # buffer export — the rendezvous/wave producers mark their
        # snapshots so), else they too are copied at enqueue: the
        # writer drains asynchronously, and a live host tile mutated
        # after send_am returns must not tear on the wire.
        raw_bufs: list = []
        frame = pickle.dumps((src, tag, payload), protocol=5,
                             buffer_callback=raw_bufs.append)
        try:
            views = [b.raw() for b in raw_bufs]
        except BufferError:
            # a custom buffer-exporting type emitted a discontiguous
            # PickleBuffer (numpy in-bands those itself): fall back to
            # fully in-band pickling for this message
            frame = pickle.dumps((src, tag, payload), protocol=4)
            views = []
        nbytes = len(frame) + sum(v.nbytes for v in views)
        with self._stat_lock:
            self.fabric.msg_count += 1
            self.fabric.bytes_count += nbytes
        peer = self._peer_to(dst)
        chunk = self.chunk_bytes
        if all(v.nbytes < chunk for v in views):
            seg = wire.pack_segment(frame, views)  # copies the views
            with peer.cond:
                self._backpressure_wait(peer, dst, len(seg))
                peer.ctrl.append(("msg", seg))
                peer.queued_bytes += len(seg)
                peer.cond.notify()
            return
        # chunked path: the header (pickle + small buffers) leads the
        # BULK lane, followed by each large buffer as bounded chunk
        # frames — the hdr-before-first-chunk invariant is structural
        # (bulk is FIFO), never a property of lane priorities.
        with self._stat_lock:
            self._xfer_iter += 1
            xid = (self.rank << 40) | self._xfer_iter
            self.wire_stats["msgs_chunked"] += 1
        # quantized wire codec (ISSUE 14): a bulk FLOAT buffer of a
        # message the sender layer marked eligible (``_qz_ok`` on the
        # payload dict — tile payloads only; control AMs and lossless
        # flows never carry the mark) encodes lossily HERE, at enqueue
        # — before the K_SEQ envelope, so the replay window retains the
        # encoded bytes and a post-flap replay stays bit-identical.
        q_codec = self._quant_codec_for(peer) if (
            isinstance(payload, dict) and payload.get("_qz_ok")) else None
        qfmts = [None] * len(raw_bufs)
        if q_codec is not None:
            for i, b in enumerate(raw_bufs):
                try:
                    qfmts[i] = memoryview(b).format
                except (BufferError, TypeError):  # pragma: no cover
                    qfmts[i] = None
        specs: list = []
        chunked_views: Dict[int, Any] = {}
        q_pre = q_post = q_bufs = 0
        for bidx, v in enumerate(views):
            if v.nbytes < chunk:
                specs.append((0, v.nbytes, v))
                continue
            if q_codec is not None and qfmts[bidx] in ("d", "f"):
                # fresh encoded bytes: immutable by construction, no
                # snapshot needed whatever the source's writability
                enc = memoryview(wire.quantize_buffer(
                    v, qfmts[bidx], q_codec))
                q_pre += v.nbytes
                q_post += enc.nbytes
                q_bufs += 1
                specs.append((wire.BUF_CHUNKED | wire.BUF_QUANT,
                              enc.nbytes, None))
                chunked_views[bidx] = enc
                continue
            if not v.readonly:
                v = memoryview(bytes(v))   # snapshot mutable bulk now
            specs.append((wire.BUF_CHUNKED, v.nbytes, None))
            chunked_views[bidx] = v
        if q_pre:
            with self._stat_lock:
                self.wire_stats["bufs_quantized"] += q_bufs
                self.wire_stats["bytes_prequant"] += q_pre
                self.wire_stats["bytes_postquant"] += q_post
            with peer.cond:
                peer.q_pre += q_pre
                peer.q_post += q_post
        hdr = wire.pack_xfer_hdr(xid, frame, specs)
        items = [("frame", hdr)]
        qbytes = len(hdr)
        for bidx, v in sorted(chunked_views.items()):
            for off in range(0, v.nbytes, chunk):
                items.append(("chunk", xid, bidx, off,
                              v[off:off + chunk]))
                qbytes += min(chunk, v.nbytes - off)
        with peer.cond:
            self._backpressure_wait(peer, dst, qbytes)
            peer.bulk.extend(items)
            peer.queued_bytes += qbytes
            peer.cond.notify()

    def _check_live(self, dst: int) -> None:
        if dst in self.dead_peers:
            raise RankFailedError(dst, "send to failed rank")
        if dst in self.finished_peers:
            raise RankFailedError(dst, "send to peer after its clean shutdown")

    def _backpressure_wait(self, peer: _Peer, dst: int,
                           nbytes: int) -> None:  # holds: peer.cond
        """Bounded send buffer (call with ``peer.cond`` held): block
        while the peer's queued bytes would exceed
        ``comm_send_buffer_bytes`` — the v1 synchronous-sendall
        backpressure with a buffer instead of O(one message), so a
        producer outpacing a slow link stalls instead of queueing an
        epoch's traffic in RAM. A message larger than the whole buffer
        is admitted alone into an empty queue. Aborts with
        RankFailedError when the peer dies while we wait."""
        limit = self.send_buffer_bytes
        # the replay window's retained (sent-but-unacked) bytes count
        # against the same budget: a flapping link's unacked backlog
        # spills into backpressure instead of unbounded RAM. The escape
        # for an oversized message keys on UNSENT bytes only, so a
        # residue of lazily-acked frames cannot park a producer forever.
        while peer.queued_bytes > 0 \
                and peer.queued_bytes + peer.rs_window_bytes \
                + nbytes > limit:
            self._check_live(dst)
            if peer.done:
                raise RankFailedError(dst, "send to failed rank")
            peer.cond.wait(0.1)
        self._check_live(dst)

    # -- writer thread --------------------------------------------------
    def _writer_can_data_locked(self, peer: _Peer) -> bool:
        # holds: peer.cond
        """May a DATA frame (batch / transfer header / chunk) leave
        right now? Not before capabilities are known when sessions are
        enabled locally (an unwrapped frame could never be replayed),
        and not while the replay window is at its byte cap (the window
        drains as the peer's cumulative acks arrive)."""
        if self._rs_enabled and not peer.hello_seen \
                and time.monotonic() - peer.connected_at < _HELLO_GRACE:
            return False
        if peer.rs_ok and peer.rs_window_bytes > 0 \
                and peer.rs_window_bytes >= self.replay_window_bytes:
            return False
        return True

    def _writer_ready_locked(self, peer: _Peer, gen: int) -> bool:
        # holds: peer.cond
        if peer.conn_gen != gen or peer.suspect:
            return True
        if peer.rank in self.dead_peers or self._ft_silenced:
            return True
        if peer.rs_replay:
            return True
        # session-less control frames (hello, pong, ack, elastic) stay
        # sendable even while data is gated — an ack-starved window on
        # BOTH ends would otherwise deadlock waiting for each other's
        # acks to drain through the blocked data lane
        if any(it[0] == "frame" for it in peer.ctrl):
            return True
        if (peer.ctrl or peer.bulk) and self._writer_can_data_locked(peer):
            return True
        return bool(peer.goodbye and not peer.ctrl and not peer.bulk)

    def _writer_loop(self, peer: _Peer, gen: int) -> None:
        """Drain one peer's queues: coalesce ctrl messages into batch
        frames (one syscall each), interleave one bulk chunk whenever
        the ctrl lane is idle, send the GOODBYE sentinel last. With a
        negotiated session, data frames are wrapped in a K_SEQ envelope
        and retained in the replay window until the peer acks them; a
        resume stages the unacked gap in ``rs_replay``, which the next
        writer generation sends before anything new."""
        coalesce = self.coalesce_max_bytes
        ctrl_streak = 0
        handoff = False   # SUSPECT/stale exit: queues + window survive
        try:
            while True:
                pieces: Optional[List[Any]] = None
                nmsgs = 0
                deq_bytes = 0
                is_goodbye = False
                sequenced = False
                replaying = False
                with peer.cond:
                    while not self._writer_ready_locked(peer, gen):
                        # timed wait ONLY while a clock-based gate is
                        # live (the pre-HELLO grace); every other gate
                        # change (enqueue, ack, suspect, resume, death)
                        # notifies — the default config keeps the
                        # wake-on-notify idle behavior
                        peer.cond.wait(
                            0.1 if self._rs_enabled
                            and not peer.hello_seen else None)
                    if peer.conn_gen != gen or peer.suspect:
                        handoff = True
                        return   # a resume (or the receiver's fault)
                        #          replaced this generation: the state
                        #          lives on for the next writer
                    if peer.rank in self.dead_peers or self._ft_silenced:
                        return   # _peer_died/ft_silence notified us:
                        #          stop (finally drops whatever is
                        #          still queued — a crash sends nothing)
                    if peer.rs_replay:
                        pieces = peer.rs_replay.pop(0)
                        replaying = True
                    elif peer.goodbye and not peer.ctrl and not peer.bulk:
                        # handled BEFORE the data gate: the sentinel is
                        # not a data frame, and waiting out a closed
                        # gate here would spin the thread hot
                        is_goodbye = True
                    else:
                        can_data = self._writer_can_data_locked(peer)
                        if not can_data:
                            idx = next((i for i, it in enumerate(peer.ctrl)
                                        if it[0] == "frame"), None)
                            if idx is None:
                                continue   # raced the gate: re-wait
                            body = peer.ctrl[idx][1]
                            del peer.ctrl[idx]
                            pieces = [body]
                            deq_bytes = len(body)
                            ctrl_streak = (ctrl_streak + 1
                                           if peer.bulk else 0)
                        else:
                            take_ctrl = bool(peer.ctrl) and (
                                not peer.bulk
                                or ctrl_streak < _CTRL_STREAK_MAX)
                            if take_ctrl:
                                kind = peer.ctrl[0][0]
                                if kind == "msg":
                                    segs = [peer.ctrl.popleft()[1]]
                                    total = len(segs[0])
                                    while (peer.ctrl
                                           and peer.ctrl[0][0] == "msg"
                                           and len(segs) < _MAX_BATCH_MSGS
                                           and total + len(peer.ctrl[0][1])
                                           <= coalesce):
                                        seg = peer.ctrl.popleft()[1]
                                        segs.append(seg)
                                        total += len(seg)
                                    pieces = wire.pack_batch(segs)
                                    nmsgs = len(segs)
                                    deq_bytes = total
                                    sequenced = peer.rs_ok
                                else:  # standalone frame (hello, pong)
                                    body = peer.ctrl.popleft()[1]
                                    pieces = [body]
                                    deq_bytes = len(body)
                                # the streak only counts ctrl frames sent
                                # WHILE bulk was waiting (the starvation
                                # being bounded)
                                ctrl_streak = (ctrl_streak + 1
                                               if peer.bulk else 0)
                            elif peer.bulk:
                                item = peer.bulk.popleft()
                                ctrl_streak = 0
                                sequenced = peer.rs_ok
                                if item[0] == "frame":  # chunked-xfer hdr
                                    pieces = [item[1]]
                                    deq_bytes = len(item[1])
                                else:
                                    _k, xid, bidx, off, view = item
                                    pieces = [wire.pack_chunk_hdr(
                                        xid, bidx, off), view]
                                    deq_bytes = view.nbytes
                                    with self._stat_lock:
                                        self.wire_stats["chunks_sent"] += 1
                                        self.wire_stats[
                                            "chunk_bytes_sent"] += \
                                            view.nbytes
                            else:  # raced both queues away: re-wait
                                continue
                if is_goodbye:
                    try:
                        peer.sock.sendall(struct.pack("<Q", GOODBYE))
                    except OSError:
                        pass
                    return
                if not replaying:
                    pieces = self._maybe_compress(peer, pieces)
                    # release the backpressure budget BEFORE the send:
                    # a sequenced frame's bytes move to the replay-
                    # window accounting (still backpressure-counted via
                    # rs_window_bytes), and a send that FAILS into the
                    # SUSPECT path must not strand its bytes in
                    # queued_bytes forever (the replay re-sends with
                    # deq_bytes already released)
                    with peer.cond:
                        if sequenced:
                            # number the frame and retain it (post-
                            # compression, so a replay is byte-
                            # identical) until the peer's cumulative
                            # ack releases it
                            peer.rs_tx_seq += 1
                            pieces = [wire.pack_seq(peer.rs_epoch,
                                                    peer.rs_tx_seq)] \
                                + list(pieces)
                            peer.rs_window.append(
                                (peer.rs_tx_seq, pieces, deq_bytes))
                            peer.rs_window_bytes += deq_bytes
                        peer.queued_bytes -= deq_bytes
                        peer.cond.notify_all()
                body_len = sum(len(p) if isinstance(p, (bytes, bytearray))
                               else p.nbytes for p in pieces)
                t0 = time.monotonic()
                _sendall_vec(peer.sock,
                             [struct.pack("<Q", body_len)] + pieces)
                dt = time.monotonic() - t0
                if sequenced:
                    with peer.cond:
                        dup = peer.rs_dup_next
                        peer.rs_dup_next = False
                    if dup:  # injected wire-level duplicate (same seq)
                        _sendall_vec(peer.sock,
                                     [struct.pack("<Q", body_len)] + pieces)
                if body_len >= _BW_SAMPLE_MIN and dt > 0:
                    inst = body_len / dt / 1e6
                    peer.bw_mbps = (inst if peer.bw_mbps is None else
                                    (1 - _BW_ALPHA) * peer.bw_mbps
                                    + _BW_ALPHA * inst)
                with self._stat_lock:
                    peer.frames += 1
                    self.wire_stats["frames_sent"] += 1
                    if nmsgs:
                        self.wire_stats["msgs_sent"] += nmsgs
                        self.wire_stats["batches"] += 1
                        if nmsgs > 1:
                            self.wire_stats["coalesced_msgs"] += nmsgs
        except OSError as exc:
            # with a negotiated session the fault is TRANSIENT until
            # proven otherwise: park the peer as SUSPECT (queues and
            # replay window intact — the frame that just failed is
            # unacked and will be replayed) and let the reconnector
            # decide. Without one, the send side can see the crash
            # before the receiver thread does — later sends raise
            # RankFailedError via dead_peers. send_am already returned
            # for the frame that just failed (and anything still
            # queued): an ACCEPTED send was LOST, so the death is
            # reported to the runtime unconditionally (lost_sends) —
            # the v1 path raised RankFailedError to the caller here, and
            # a silent drop would trade that loud abort for a termdet
            # hang.
            if self._session_suspect(peer, gen, f"send failed: {exc}"):
                handoff = True
                return
            self._peer_died(peer.rank, f"send failed: {exc}",
                            lost_sends=True)
        finally:
            if not handoff:
                peer.done = True
                with peer.cond:
                    dropped = len(peer.ctrl) + len(peer.bulk)
                    peer.ctrl.clear()
                    peer.bulk.clear()
                    peer.queued_bytes = 0
                    peer.rs_window.clear()
                    peer.rs_window_bytes = 0
                    peer.rs_replay = []
                    peer.cond.notify_all()
                if dropped and not self._closing and not self._ft_silenced:
                    plog.warning(
                        "tcp rank %d: dropped %d queued frame(s)/chunk(s) "
                        "to dead peer %d", self.rank, dropped, peer.rank)

    def _maybe_compress(self, peer: _Peer, pieces: List[Any]) -> List[Any]:
        """Engage per-link compression when (a) the peer advertised a
        common codec, (b) the measured bandwidth EWMA sits below the
        MCA threshold (default 0 = never), and (c) a sample probe shows
        the traffic actually compresses. Re-probes periodically so a
        shift to incompressible payloads backs off."""
        threshold = self.compress_threshold_mbps
        codec = peer.codec
        if not threshold or codec is None:
            return pieces
        bw = peer.bw_mbps
        if bw is None or bw >= threshold:
            return pieces
        body_len = sum(len(p) if isinstance(p, (bytes, bytearray))
                       else p.nbytes for p in pieces)
        if body_len < _COMP_MIN_BYTES:
            return pieces
        probing = (peer.probe_ratio is None
                   or peer.frames % _PROBE_EVERY == 0)
        if not probing and not peer.engaged:
            return pieces   # before the join: no copy between probes
        body = b"".join(bytes(p) for p in pieces)
        out = wire.compress_body(body, codec)
        if probing:
            # the probe IS this frame's compression — measured once,
            # reused as the payload when it engages
            peer.probe_ratio = (sum(len(p) for p in out) / len(body)
                                if out is not None else 1.0)
            peer.engaged = peer.probe_ratio <= _PROBE_RATIO
            if not peer.engaged:
                return pieces
        if out is None:
            return pieces
        post = sum(len(p) for p in out)
        with self._stat_lock:
            self.wire_stats["frames_compressed"] += 1
            self.wire_stats["bytes_precompress"] += len(body)
            self.wire_stats["bytes_postcompress"] += post
        with peer.cond:   # per-peer twin: the labeled ratio gauge
            peer.comp_pre += len(body)
            peer.comp_post += post
        return out

    # -- receive path ---------------------------------------------------
    @staticmethod
    def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    @staticmethod
    def _recv_body(sock: socket.socket, n: int) -> Tuple[bytearray, bool]:
        """Read one frame body, KEEPING whatever landed when the
        connection tears mid-frame: (bytes so far, complete?). The
        partial body seeds the session layer's byte-level resume claim
        instead of being discarded (a torn multi-MB chunk resumes at
        the truncation offset, not from byte 0)."""
        buf = bytearray()
        try:
            while len(buf) < n:
                chunk = sock.recv(n - len(buf))
                if not chunk:
                    return buf, False
                buf += chunk
        except OSError:
            return buf, False
        return buf, True

    def _note_get_reply(self, tag: int, payload: Any) -> None:
        """Receiver-thread bookkeeping for the GOODBYE verdict: record
        which outstanding GET tokens have their reply ARRIVED (parked
        in the inbox, waiting for a worker to pump progress()).  A
        token still owed at GOODBYE with no arrived reply provably
        never got one — frames are FIFO, the sentinel is the stream's
        last — so the verdict need not wait for it."""
        if tag != TAG_GET_DATA:
            return
        items = payload.get("items") if isinstance(payload, dict) else None
        if not items:
            return
        with self._lock:
            for item in items:
                self._rx_get_tokens.add(item["token"])
            # consumed tokens left _get_cbs — prune so the set tracks
            # only the in-flight window, not the engine's lifetime
            self._rx_get_tokens.intersection_update(self._get_cbs)

    def _await_owed_gets(self, peer: int, timeout: float = 30.0) -> None:
        """Park this receiver thread (it has nothing left to read —
        the GOODBYE sentinel is the stream's last frame) until the
        workers CONSUME every outstanding GET toward ``peer`` whose
        reply already arrived, or the budget expires.  Returns at once
        when some owed token has no arrived reply: that reply provably
        never left the peer (frames are FIFO), so the peer is a
        definite failure and the verdict must not stall on it.  The
        arrived replies were pushed with an arrival notification, so a
        parked worker is already waking to consume them."""
        deadline = time.monotonic() + timeout
        while not self._closing and peer not in self.dead_peers:
            with self._lock:
                owed = [t for t, s in self._get_srcs.items() if s == peer]
                arrived = all(t in self._rx_get_tokens for t in owed)
            if not owed:
                return
            if not arrived or time.monotonic() >= deadline:
                return
            time.sleep(0.001)

    def _recv_fault(self, p: _Peer, gen: int, reason: str) -> None:
        """A receiver-side connection fault: absorbed as SUSPECT when a
        session covers the link, fail-fast ``_peer_died`` otherwise."""
        if self._session_suspect(p, gen, reason):
            return   # rx state (seq, partial, half-landed transfers)
            #          survives for the resume
        self._peer_died(p.rank, reason)
        with self._stat_lock:
            self._rx_pending.pop(p.rank, None)

    def _recv_loop(self, p: _Peer, sock: socket.socket, gen: int) -> None:
        peer = p.rank
        try:
            while True:
                hdr = self._recv_exact(sock, 8)
                if hdr is None:
                    self._recv_fault(p, gen, "peer closed the connection")
                    return
                (size,) = struct.unpack("<Q", hdr)
                if size == GOODBYE:
                    # a clean shutdown is honored only after the
                    # rendezvous replies the peer already delivered are
                    # CONSUMED: frames are FIFO, so every reply to a
                    # served GET precedes this sentinel in this very
                    # stream and is parked in the inbox — but
                    # _get_srcs is only cleared when a worker pumps
                    # progress(), so the verdict below would race the
                    # delivery it is checking for.  (An incomplete
                    # chunked transfer is different: its missing bytes
                    # provably never left, no point waiting.)
                    with p.cond:
                        mid_xfer = bool(p.rx_xfers)
                    if not mid_xfer:
                        self._await_owed_gets(peer)
                    with self._lock:
                        owes_us = peer in self._get_srcs.values()
                    with p.cond:
                        owes_us = owes_us or bool(p.rx_xfers)
                    if owes_us:
                        # "clean" exit while owing rendezvous data or
                        # mid-chunked-transfer is a protocol violation —
                        # treat as a failure
                        self._peer_died(
                            peer, "shut down owing rendezvous data")
                        with self._stat_lock:
                            self._rx_pending.pop(peer, None)
                        return
                    # orderly shutdown: the peer fini'd after completing
                    # its work — not a failure, no scary warnings
                    self.finished_peers.add(peer)
                    return
                buf, complete = self._recv_body(sock, size)
                if not complete:
                    with p.cond:
                        # record WHERE the truncation happened — the
                        # resume claim lets the sender continue this
                        # frame from the landed offset (K_FRAG)
                        p.rs_rx_partial = (size, buf) if buf else None
                    self._recv_fault(
                        p, gen, f"connection truncated mid-frame "
                                f"({len(buf)}/{size} bytes)")
                    return
                # read-only view, zero copy: reconstructed arrays alias
                # the received body and must not be host-mutable
                self._dispatch_body(p, memoryview(buf).toreadonly())
        except OSError as exc:
            self._recv_fault(p, gen, f"socket error: {exc}")
            return
        except Exception as exc:  # frame desync / unpickle failure: a
            # silent receiver death would hang both ranks — make it loud
            # (never SUSPECT: a protocol violation is not transient)
            self._peer_died(peer, f"receiver died: {exc!r}")
            with self._stat_lock:
                self._rx_pending.pop(peer, None)
            return

    def _dispatch_body(self, p: _Peer, body: memoryview) -> None:
        if self._ft_silenced:
            return   # injected kill: inbound traffic is never delivered
        peer = p.rank
        xfers = p.rx_xfers
        kind = body[0]
        if kind == wire.K_BATCH:
            for frame, bufs in wire.parse_batch(body):
                # out-of-band buffers alias the received body (zero
                # extra copy); arrays reconstructed over them are
                # read-only — host mutators copy-on-write via
                # Data.materialize_host
                src, tag, payload = wire.load_message(frame, bufs)
                self._note_get_reply(tag, payload)
                self._inbox.push((src, tag, payload))
                self._notify_arrival()  # wake a parked worker now
        elif kind == wire.K_XFER_HDR:
            xid, frame, specs = wire.parse_xfer_hdr(body)
            rx = wire.RxXfer(frame, specs)
            if rx.remaining <= 0:
                src, tag, payload = rx.message()
                self._note_get_reply(tag, payload)
                self._inbox.push((src, tag, payload))
                self._notify_arrival()
                return
            xfers[xid] = rx
            with self._stat_lock:
                self._rx_pending[peer] = len(xfers)
        elif kind == wire.K_CHUNK:
            xid, bidx, off, data = wire.parse_chunk(body)
            rx = xfers.get(xid)
            if rx is None:
                raise ValueError(f"chunk for unknown transfer {xid}")
            if rx.feed(bidx, off, data):
                del xfers[xid]
                with self._stat_lock:
                    self._rx_pending[peer] = len(xfers)
                if any(rx.quant):
                    # controller evidence (ISSUE 17): how many raw
                    # bytes this link's quantized buffers stood for vs
                    # the encoded bytes that actually landed
                    pre = post = 0
                    for b, q in zip(rx.bufs, rx.quant):
                        if q:
                            pre += wire.quant_raw_len(b)
                            post += len(b)
                    with p.cond:
                        p.qrx_pre += pre
                        p.qrx_post += post
                src, tag, payload = rx.message()
                self._note_get_reply(tag, payload)
                self._inbox.push((src, tag, payload))
                self._notify_arrival()
        elif kind == wire.K_HELLO:
            info = wire.parse_hello(body)
            p.codec = wire.negotiate_codec(
                self._codecs, info.get("codecs", ()))
            p.hb_ok = bool(info.get("hb"))
            p.el_ok = bool(info.get("el"))
            # flow tracing negotiates SYMMETRICALLY like "rs": both
            # ends must run with obs_flow set or neither stamps
            p.tr_ok = bool(info.get("tr")) and self._flow_enabled
            # obs_live's extended contexts are symmetric the same way:
            # both ends must run with obs_live set or senders keep the
            # plain (origin, span) pair
            p.lv_ok = bool(info.get("lv")) and self._live_enabled
            # runtime tuning is symmetric too: only a link whose BOTH
            # ends run with tune_auto ever renegotiates its codec —
            # a mixed-version peer stays on its HELLO negotiation
            p.tn_ok = bool(info.get("tn")) and self._tune_enabled
            # serving is symmetric the same way: tenant-extended flow
            # contexts (and serve control AMs) travel only on links
            # whose BOTH ends run with the serve knob set
            p.sv_ok = bool(info.get("sv")) and self._serve_enabled
            # the device plane is symmetric the same way: bulk planner
            # payloads leave the session wire only on links whose BOTH
            # ends run with xfer_dplane set (and a plane attached)
            p.dp_ok = bool(info.get("dp")) and self._dp_enabled
            # "xs" negotiates on token EQUALITY, not truthiness: equal
            # tokens prove the peer lives in THIS process (shared XLA
            # device pool — the cross-rank lowering precondition); a
            # separate-process, mixed-version, or knob-unset peer never
            # matches and keeps the activation path bit-for-bit
            p.xs_ok = (self._xs_enabled
                       and info.get("xs") == _xs_proc_token())
            with p.cond:
                # quantize capability is symmetric like "rs": only a
                # peer that advertised the requested codec under "qz"
                # ever receives quantized buffers
                p.qz_codec = wire.negotiate_quant_codec(
                    self._quantize, info.get("qz", ()))
                # session capability is SYMMETRIC: both ends must run
                # with the knob set, or neither retains/replays
                p.rs_ok = bool(info.get("rs")) and self._rs_enabled
                p.hello_seen = True
                p.cond.notify_all()   # the writer may be holding data
        elif kind == wire.K_SEQ:
            # session data frame: deliver IN ORDER exactly once — a
            # replayed frame the old connection already delivered is
            # dropped here by seq, so no active message ever runs twice
            _epoch, seq, inner = wire.parse_seq(body)
            deliver = False
            with p.cond:
                if seq <= p.rs_rx_seq:
                    pass   # duplicate from a replay overlap
                elif seq != p.rs_rx_seq + 1:
                    raise ValueError(
                        f"session desync: frame seq {seq} after "
                        f"{p.rs_rx_seq}")
                else:
                    p.rs_rx_seq = seq
                    p.rs_rx_partial = None
                    deliver = True
                    p.rs_rx_unacked_frames += 1
                    p.rs_rx_unacked_bytes += len(body)
                    if p.rs_rx_unacked_frames >= _ACK_EVERY_FRAMES \
                            or p.rs_rx_unacked_bytes >= self._ack_bytes:
                        ack = wire.pack_ack(p.rs_epoch, seq)
                        p.rs_rx_unacked_frames = 0
                        p.rs_rx_unacked_bytes = 0
                        p.ctrl.append(("frame", ack))
                        p.queued_bytes += len(ack)
                        p.cond.notify()
            if not deliver:
                with self._stat_lock:
                    self.wire_stats["dup_dropped"] += 1
                return
            self._dispatch_body(p, inner)
        elif kind == wire.K_ACK:
            # cumulative delivery ack: release the replay window (and
            # the backpressure budget the retained bytes counted
            # against) up to the acked seq
            _epoch, seq = wire.parse_ack(body)
            with p.cond:
                while p.rs_window and p.rs_window[0][0] <= seq:
                    _seq, _pieces, nb = p.rs_window.popleft()
                    p.rs_window_bytes -= nb
                p.cond.notify_all()
        elif kind == wire.K_FRAG:
            # byte-level resume of the frame the link tore mid-body:
            # stitch our kept partial + the sender's remainder, then
            # dispatch the whole as the K_SEQ frame it always was
            _epoch, seq, offset, data = wire.parse_frag(body)
            with p.cond:
                part = p.rs_rx_partial
                if seq <= p.rs_rx_seq:
                    part = None   # somehow already delivered: dup
                elif part is None or len(part[1]) != offset:
                    raise ValueError(
                        f"frag resume mismatch: offset {offset}, held "
                        f"{len(part[1]) if part else 'no'} partial bytes")
                else:
                    full = bytes(part[1]) + bytes(data)
                    if len(full) != part[0]:
                        raise ValueError(
                            f"frag resume size mismatch: {len(full)} != "
                            f"{part[0]}")
                    p.rs_rx_partial = None
            if part is None:
                with self._stat_lock:
                    self.wire_stats["dup_dropped"] += 1
                return
            self._dispatch_body(p, memoryview(full))
        elif kind == wire.K_PING:
            # answered HERE, on the receiver thread (like K_HELLO): a
            # rank whose workers are all stuck in a long kernel still
            # proves liveness — the detector judges the TRANSPORT, not
            # the progress cadence
            seq, t_ns = wire.parse_ping(body)
            det = self.ft_detector
            if det is not None:
                det.note_alive(peer)
            if not p.done:
                # an EXTENDED ping requests clock alignment (ISSUE 15):
                # the pong echoes (seq, t_ns) and stamps THIS rank's
                # monotonic clock in the trailing word — only ever in
                # answer to an extension only tr-enabled peers send, so
                # pongs toward mixed-version/knob-unset peers stay
                # byte-identical
                ext = wire.ping_clock(body)
                pong = wire.pack_ping(
                    seq, t_ns, pong=True,
                    clock_ns=(time.monotonic_ns()
                              if ext is not None else None))
                with p.cond:
                    p.ctrl.append(("frame", pong))
                    p.queued_bytes += len(pong)
                    p.cond.notify()
        elif kind == wire.K_PONG:
            seq, t_ns = wire.parse_ping(body)
            now_ns = time.monotonic_ns()
            t_peer = wire.ping_clock(body)
            if t_peer:
                # midpoint method: the responder stamped its clock
                # mid-round-trip — offset = peer_clock - my_clock
                # assuming symmetric legs (error bounded by half the
                # path asymmetry), folded into a per-peer EWMA
                self._note_clock(
                    peer, (t_peer - (t_ns + now_ns) / 2.0) / 1e3)
            det = self.ft_detector
            if det is not None:
                det.note_alive(peer, rtt=(now_ns - t_ns) / 1e9)
        elif kind == wire.K_ELASTIC:
            # delivered HERE, on the receiver thread (like K_PING): a
            # resize proposal or join announcement must reach the
            # coordinator even while every worker is wedged in a long
            # kernel — elastic agreement is progress-cadence-free on TCP
            self._on_elastic(peer, wire.parse_elastic(body))
        elif kind == wire.K_TUNE:
            # applied HERE, on the receiver thread (like K_ELASTIC): a
            # codec renegotiation takes effect at the next enqueue, not
            # at the next progress pump — the controller's window
            # cadence stays decoupled from the workers'
            self._on_tune(p, wire.parse_tune(body))
        elif kind == wire.K_COMP:
            self._dispatch_body(p, memoryview(
                wire.decompress_body(body)))
        else:
            raise ValueError(f"unknown frame kind {kind}")

    def _peer_died(self, peer: int, reason: str,
                   lost_sends: bool = False) -> None:
        """Failure detector: a torn connection while we're live marks the
        peer dead (SURVEY.md §5.3 — the reference has nothing; a dead MPI
        rank hangs the job). Reporting policy:

        - any later SEND to the peer raises RankFailedError (always);
        - the death is reported to the runtime immediately when the peer
          provably owes us data (a pending rendezvous GET), when
          accepted-but-unsent frames were LOST with it (``lost_sends``
          — the writer path; the caller already returned believing the
          send succeeded), or always under ``comm_failure_strict`` —
          strict is off by default because with local termination
          detection a peer may legitimately fini before our local tail
          work finishes."""
        if self._closing or peer in self.dead_peers \
                or peer in self.finished_peers:
            return  # clean teardown (ours or theirs), or already reported
        self.dead_peers.add(peer)
        with self._conn_cond:
            p = self._peers.get(peer)
        if p is not None:
            dur_ms = 0.0
            with p.cond:  # unblock anything parked on the writer
                if p.suspect:
                    # a SUSPECT episode ends in escalation: close its
                    # accounting and stand the reconnector down
                    p.suspect = False
                    p.done = True
                    dur_ms = (time.monotonic() - p.suspect_since) * 1e3
                p.cond.notify_all()
            if dur_ms:
                with self._stat_lock:
                    self._suspect_ms_total += dur_ms
        plog.warning("tcp rank %d: peer %d presumed FAILED (%s)",
                     self.rank, peer, reason)
        cb = self.on_peer_failure
        if cb is None:
            return
        from ..utils.params import params
        with self._lock:
            owes_us = peer in self._get_srcs.values()
        if owes_us or lost_sends or params.get("comm_failure_strict"):
            cb(peer, reason)

    def _transport_drain(self):
        while True:
            item = self._inbox.pop()
            if item is None:
                return
            yield item

    # -- barrier over AMs (ref: ce.sync) --------------------------------
    def _on_barrier(self, src: int, payload: Any) -> None:
        # progress() runs on every scheduler thread: updates must be
        # atomic or arrivals are lost and sync() deadlocks
        with self._barrier_lock:
            if payload == "arrive":
                self._barrier_arrived.add(src)
            else:
                self._barrier_release += 1

    def _barrier_wait(self, check_and_consume, required_fn) -> None:
        """Spin on progress() until ``check_and_consume`` succeeds; raise
        RankFailedError when a still-required participant is gone
        (crashed OR cleanly fini'd without arriving) — a barrier can
        never complete then, and spinning until an external timeout is
        the hang this detector exists to eliminate. A peer that already
        arrived may fini freely; its flag is set by the recv thread only
        AFTER every preceding frame was queued, so one extra drain before
        raising rules out a queued-but-unprocessed barrier message."""
        while True:
            if check_and_consume():
                return
            if self.progress():
                continue
            gone = [p for p in required_fn()
                    if p in self.dead_peers or p in self.finished_peers]
            if gone:
                self.progress()  # final drain (see docstring)
                if check_and_consume():
                    return
                peer = gone[0]
                reason = ("rank failed during barrier"
                          if peer in self.dead_peers else
                          "rank shut down without joining the barrier")
                raise RankFailedError(peer, reason)
            time.sleep(0.001)

    def sync(self) -> None:
        if self.nb_ranks == 1:
            return
        if self.rank == 0:
            everyone = set(range(1, self.nb_ranks))

            def got_all_arrivals() -> bool:
                with self._barrier_lock:
                    if self._barrier_arrived >= everyone:
                        self._barrier_arrived -= everyone
                        return True
                    return False

            def still_missing():
                with self._barrier_lock:
                    return everyone - self._barrier_arrived

            self._barrier_wait(got_all_arrivals, still_missing)
            for peer in range(1, self.nb_ranks):
                self.send_am(peer, TAG_BARRIER, "release")
        else:
            self.send_am(0, TAG_BARRIER, "arrive")

            def got_release() -> bool:
                with self._barrier_lock:
                    if self._barrier_release >= 1:
                        self._barrier_release -= 1
                        return True
                    return False

            self._barrier_wait(got_release, lambda: (0,))

    def fini(self) -> None:
        self._closing = True
        self._clock_stop.set()   # stand the clock sampler down first
        t = self._clock_thread
        if t is not None:
            t.join(timeout=2.0)
        if self._ft_silenced:
            # injected kill: die WITHOUT a goodbye and WITHOUT flushing
            # — peers must learn of the death proactively (heartbeat) or
            # reactively (torn socket), exactly like a real crash
            try:
                self._listener.close()
            except OSError:
                pass
            with self._conn_cond:
                peers = dict(self._peers)
            for p in peers.values():
                try:
                    p.sock.close()
                except OSError:
                    pass
            return
        # clean goodbye so live peers see an orderly shutdown, not a
        # crash. The writer sends it only after BOTH queues drain (the
        # final results / termdet messages must precede it), so fini
        # waits for the writers to flush before tearing sockets down.
        with self._conn_cond:
            peers = dict(self._peers)
        for rank_, p in peers.items():
            if rank_ in self.dead_peers or rank_ in self.finished_peers:
                continue
            with p.cond:
                p.goodbye = True
                p.cond.notify()
        # progress-aware flush: a slow link draining a large bulk
        # backlog gets as long as it keeps moving bytes (the links this
        # wire targets run at single-digit MB/s); only a STALLED writer
        # (15 s with zero queue progress) is abandoned
        live = [p for r, p in peers.items()
                if r not in self.dead_peers
                and r not in self.finished_peers and p.writer is not None]
        prev = None
        stall = time.time() + 15.0
        while True:
            live = [p for p in live if p.writer.is_alive()]
            if not live:
                break
            cur = 0
            for p in live:
                with p.cond:
                    cur += len(p.ctrl) + len(p.bulk)
            if prev is None or cur < prev:
                prev = cur
                stall = time.time() + 15.0
            if time.time() > stall:
                plog.warning(
                    "tcp rank %d: %d writer(s) stalled with %d queued "
                    "frame(s) at shutdown", self.rank, len(live), cur)
                break
            time.sleep(0.02)
        try:
            self._listener.close()
        except OSError:
            pass
        for p in peers.values():
            try:
                p.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                p.sock.close()
            except OSError:
                pass
