"""Wave execution: lowered PTG DAGs as batched per-class XLA calls
(dsl/ptg/wave.py). Correctness vs numpy references, WAR frontier
splitting, static body-local sub-chunking, and the structural dispatch
gate (kernel calls must scale with waves, not tasks)."""
import numpy as np
import pytest

from parsec_tpu.collections import TwoDimBlockCyclic
from parsec_tpu.dsl import ptg
from parsec_tpu.dsl.ptg.wave import WaveError, WaveRunner, wave
from parsec_tpu.ops import (dgetrf_nopiv_taskpool, dpotrf_taskpool,
                            pdgemm_taskpool, make_spd)


def _spd_coll(n, nb):
    M = make_spd(n, dtype=np.float32)
    A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
    return A, M


def test_wave_dpotrf_matches_numpy():
    A, M = _spd_coll(1024, 128)
    w = wave(dpotrf_taskpool(A), max_chunk=64)
    w.run()
    L = np.tril(A.to_numpy()).astype(np.float64)
    assert np.allclose(L, np.linalg.cholesky(M.astype(np.float64)),
                       atol=1e-3)


def test_wave_dgetrf_matches_numpy():
    A, M = _spd_coll(768, 128)
    wave(dgetrf_nopiv_taskpool(A), max_chunk=32).run()
    LU = A.to_numpy().astype(np.float64)
    L = np.tril(LU, -1) + np.eye(768)
    U = np.triu(LU)
    assert np.abs(L @ U - M).max() / np.abs(M).max() < 1e-5


def test_wave_pdgemm_static_body_locals():
    """pdgemm's GEMM body branches on local k in Python (`BETA if k == 0
    else 1.0`): wave mode must sub-chunk on it, not trace it."""
    n, nb = 512, 128
    rng = np.random.RandomState(2)
    Am, Bm = rng.rand(n, n).astype(np.float32), rng.rand(n, n).astype(np.float32)
    A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(Am)
    B = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(Bm)
    C = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(
        np.zeros((n, n), np.float32))
    w = wave(pdgemm_taskpool(A, B, C), max_chunk=16)
    gemm_plan = next(p for p in w.plans if p.ast.name == "GEMM")
    assert gemm_plan.body_locals, "k should be detected as a body local"
    w.run()
    ref = Am.astype(np.float64) @ Bm.astype(np.float64)
    assert np.abs(C.to_numpy().astype(np.float64) - ref).max() / n < 1e-6


def test_wave_dispatch_scales_with_waves_not_tasks():
    """The point of wave mode: kernel-call count must be far below task
    count (per-task dispatch is what it eliminates)."""
    A, _ = _spd_coll(2048, 128)   # NT=16: 816 tasks
    w = wave(dpotrf_taskpool(A), max_chunk=256)
    calls = 0
    orig = w._kernel

    def counting(*kargs):
        fn = orig(*kargs)

        def wrapped(*a):
            nonlocal calls
            calls += 1
            return fn(*a)
        return wrapped

    w._kernel = counting
    w.run()
    assert w.nb_tasks == 816
    assert calls < w.nb_tasks / 3, f"{calls} kernel calls for 816 tasks"


def test_wave_war_frontier_split():
    """A frontier holding a reader of a tile and an independent writer of
    the same tile must not let the in-place scatter clobber the read."""
    jdf = """
descA [ type="collection" ]
descB [ type="collection" ]
NT [ type="int" ]

READER(k)

k = 0 .. NT-1

: descB( k, 0 )

READ  X <- descA( 0, 0 )
RW    Y <- descB( k, 0 )
      -> descB( k, 0 )

BODY
{
    Y = X + Y
}
END

WRITER(j)

j = 0 .. 0

: descA( 0, 0 )

RW    Z <- descA( 0, 0 )
      -> descA( 0, 0 )

BODY
{
    Z = Z * 0.0
}
END
"""
    fac = ptg.compile_jdf(jdf, name="war")
    nt = 4
    descA = TwoDimBlockCyclic(4, 4, 4, 4, dtype=np.float32).from_numpy(
        np.full((4, 4), 7.0, np.float32))
    descB = TwoDimBlockCyclic(4 * nt, 4, 4, 4, dtype=np.float32).from_numpy(
        np.zeros((4 * nt, 4), np.float32))
    tp = fac.new(NT=nt, descA=descA, descB=descB)
    w = wave(tp)
    # all instances are startup tasks: one frontier with readers of
    # descA(0) and its writer
    w.run()
    out = descB.to_numpy()
    assert np.allclose(out, 7.0), f"reader saw the clobbered tile: {out}"
    assert np.allclose(descA.to_numpy(), 0.0)


def test_wave_new_scratch_flows():
    """NEW scratch sources live in per-class zero-initialized scratch
    pools (round-2 VERDICT item 5: previously rejected)."""
    jdf = """
descA [ type="collection" ]
NT [ type="int" ]

T(k)

k = 0 .. NT-1

: descA( k, 0 )

RW   A <- descA( k, 0 )
     -> descA( k, 0 )
READ S <- NEW  [shape=4 dtype=float32]

BODY
{
    A = A + S + 1.0
}
END
"""
    fac = ptg.compile_jdf(jdf, name="newflow")
    descA = TwoDimBlockCyclic(8, 4, 4, 4, dtype=np.float32).from_numpy(
        np.zeros((8, 4), np.float32))
    WaveRunner(fac.new(NT=2, descA=descA)).run()
    # scratch arrives zeroed (the runtime's NEW tiles are zeroed too)
    assert np.allclose(descA.to_numpy(), 1.0)


def test_chunk_decomposition():
    from parsec_tpu.dsl.ptg.wave import WaveRunner as W
    assert W._chunks(0, 256) == []
    assert W._chunks(1, 256) == [1]
    assert W._chunks(7, 256) == [1, 2, 4]
    assert W._chunks(300, 256) == [256, 4, 8, 32]
    assert sum(W._chunks(300, 256)) == 300
    assert sum(W._chunks(1023, 64)) == 1023


def test_wave_cyclic_war():
    """Two co-ready tasks each reading the tile the other writes (a
    swap): fused waves gather every input before any scatter, so both
    read pre-wave values and the swap is exact (the per-task runtime's
    copy semantics). With fusion disabled the layered in-place scatters
    cannot serve it — must raise, not corrupt."""
    jdf = """
descA [ type="collection" ]
NT [ type="int" ]

SWAPA(j)

j = 0 .. 0

: descA( 0, 0 )

READ  X <- descA( 1, 0 )
RW    Z <- descA( 0, 0 )
      -> descA( 0, 0 )

BODY
{
    Z = X
}
END

SWAPB(j)

j = 0 .. 0

: descA( 1, 0 )

READ  X <- descA( 0, 0 )
RW    Z <- descA( 1, 0 )
      -> descA( 1, 0 )

BODY
{
    Z = X
}
END
"""
    fac = ptg.compile_jdf(jdf, name="swap")
    M0 = np.arange(32, dtype=np.float32).reshape(8, 4)
    descA = TwoDimBlockCyclic(8, 4, 4, 4, dtype=np.float32).from_numpy(
        M0.copy())
    w = wave(fac.new(NT=1, descA=descA))
    assert w._fuse
    w.run()
    swapped = np.vstack([M0[4:], M0[:4]])
    np.testing.assert_array_equal(descA.to_numpy(), swapped)

    from parsec_tpu.utils.params import params
    params.set_cmdline("wave_fuse", "0")
    try:
        descB = TwoDimBlockCyclic(8, 4, 4, 4, dtype=np.float32).from_numpy(
            M0.copy())
        w2 = wave(fac.new(NT=1, descA=descB))
        assert not w2._fuse
        with pytest.raises(WaveError, match="cyclic"):
            w2.run()
    finally:
        params.unset_cmdline("wave_fuse")


def test_lowering_cache_evicts_with_jdf():
    """The lowering cache is scoped to the JDF's lifetime: a dead JDF's
    entries are purged (no id-reuse aliasing, no unbounded growth)."""
    import gc
    import importlib
    lower_mod = importlib.import_module("parsec_tpu.dsl.ptg.lower")

    A, _ = _spd_coll(256, 128)
    tp = dpotrf_taskpool(A)
    dag = lower_mod.lower(tp)
    jid = id(tp.jdf)
    assert any(k[0] == jid for k in lower_mod._cache)
    del tp, dag
    # the taskpool holds the only strong ref to this factory's jdf? No —
    # the factory is module-cached; force a fresh one to test eviction
    fac = ptg.compile_jdf("""
descA [ type="collection" ]
NT [ type="int" ]

T(k)

k = 0 .. NT-1

: descA( k, 0 )

RW   A <- descA( k, 0 )
     -> descA( k, 0 )

BODY
{
    A = A * 2.0
}
END
""", name="evict")
    descA = TwoDimBlockCyclic(8, 4, 4, 4, dtype=np.float32).from_numpy(
        np.ones((8, 4), np.float32))
    tp2 = fac.new(NT=2, descA=descA)
    lower_mod.lower(tp2)
    jid2 = id(fac.jdf)
    assert any(k[0] == jid2 for k in lower_mod._cache)
    del tp2, fac
    gc.collect()
    assert not any(k[0] == jid2 for k in lower_mod._cache)


def test_wave_sharded_over_mesh():
    """Wave kernels run SPMD when pools carry a NamedSharding: GSPMD
    partitions each batched tile op over the mesh (tp x sp here) and the
    result matches the single-device run."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from parsec_tpu.parallel import make_mesh

    A, M = _spd_coll(512, 128)
    w = wave(dpotrf_taskpool(A), max_chunk=32)
    mesh = make_mesh(sizes={"tp": 2, "sp": 2}, devices=jax.devices("cpu")[:4])
    sh = NamedSharding(mesh, P(None, "tp", "sp"))
    pools = w.build_pools(sharding=sh)
    assert pools[0].sharding.is_equivalent_to(sh, pools[0].ndim)
    out = w.execute(pools)
    jax.block_until_ready(out)
    w.scatter_pools(out)
    L = np.tril(A.to_numpy()).astype(np.float64)
    assert np.allclose(L, np.linalg.cholesky(M.astype(np.float64)),
                       atol=1e-3)


def test_wave_reshape_properties_masked_writeback():
    """[type_data=lower] in/out: the body sees the masked read, the
    writeback preserves the upper region (round-2 VERDICT item 5:
    previously rejected; full parity suite in test_wave_reshape.py)."""
    jdf = """
descA [ type="collection" ]

T(k)

k = 0 .. 0

: descA( 0, 0 )

RW   A <- descA( 0, 0 )    [type_data=lower]
     -> descA( 0, 0 )      [type_data=lower]

BODY
{
    A = A * 2.0
}
END
"""
    fac = ptg.compile_jdf(jdf, name="reshapey")
    base = np.arange(16, dtype=np.float32).reshape(4, 4) + 1.0
    descA = TwoDimBlockCyclic(4, 4, 4, 4, dtype=np.float32).from_numpy(
        base.copy())
    WaveRunner(fac.new(descA=descA)).run()
    expect = np.where(np.tril(np.ones((4, 4), bool)), 2.0 * base, base)
    assert np.allclose(descA.to_numpy(), expect), descA.to_numpy()


def test_wave_rejects_waw_frontier():
    """Two co-ready writers of one tile (a racy DAG) must raise, not
    keep an arbitrary winner."""
    jdf = """
descA [ type="collection" ]

W1(k)

k = 0 .. 0

: descA( 0, 0 )

RW   A <- descA( 0, 0 )
     -> descA( 0, 0 )

BODY
{
    A = A + 1.0
}
END

W2(k)

k = 0 .. 0

: descA( 0, 0 )

RW   A <- descA( 0, 0 )
     -> descA( 0, 0 )

BODY
{
    A = A + 2.0
}
END
"""
    fac = ptg.compile_jdf(jdf, name="waw")
    descA = TwoDimBlockCyclic(4, 4, 4, 4, dtype=np.float32).from_numpy(
        np.zeros((4, 4), np.float32))
    w = wave(fac.new(descA=descA))
    with pytest.raises(WaveError, match="two writers"):
        w.run()


def test_wave_sharded_dpotrf_at_size():
    """End-to-end SHARDED dpotrf at meaningful size (round-2 VERDICT
    item 10: the sharded path was only toy-tested): NT=16 (1024/64)
    over the full 8-device virtual mesh, every wave kernel GSPMD-
    partitioned, numerics vs numpy Cholesky."""
    import time

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from parsec_tpu.parallel import make_mesh

    # NT=16: 816 tasks, 31 waves. nb=64 (not 128): the 1-core CI host
    # cannot get all 8 device threads into XLA's collective rendezvous
    # within its fixed 20 s window when per-kernel work grows — at
    # nb=128 the warm run trips the rendezvous watchdog (real-chip
    # meshes schedule devices in parallel and don't have this limit)
    n, nb = 1024, 64
    A, M = _spd_coll(n, nb)
    w = wave(dpotrf_taskpool(A), max_chunk=32)
    mesh = make_mesh(sizes={"tp": 4, "sp": 2},
                     devices=jax.devices("cpu")[:8])
    sh = NamedSharding(mesh, P(None, "tp", "sp"))
    pools = w.execute(w.build_pools(sharding=sh))   # warm kernels
    jax.block_until_ready(pools)
    pools = w.build_pools(sharding=sh)
    jax.block_until_ready(pools)
    t0 = time.perf_counter()
    pools = w.execute(pools)
    jax.block_until_ready(pools)
    dt = time.perf_counter() - t0
    print(f"SHARDED_WAVE_DPOTRF n={n} nb={nb} 8dev: "
          f"{(n ** 3 / 3.0) / dt / 1e9:.1f} gflops")
    w.scatter_pools(pools)
    L = np.tril(A.to_numpy()).astype(np.float64)
    ref = np.linalg.cholesky(M.astype(np.float64))
    assert np.allclose(L, ref, atol=1e-3), \
        f"max err {np.abs(L - ref).max()}"


def test_wave_stats():
    """execute() leaves engineering counters on the runner (the wave
    path bypasses PINS by design — dispatch is what it amortizes; the
    stats are its observability surface)."""
    A, _ = _spd_coll(512, 128)
    w = wave(dpotrf_taskpool(A))
    w.run()
    s = w.stats
    assert s["tasks"] == 20 and s["waves"] > 1
    assert 0 < s["kernel_calls"] < s["tasks"]
    assert s["dispatch_secs"] > 0 and s["compiled_kernels"] > 0


# --------------------------------------------------------------------- #
# ragged tilings: N not divisible by NB rides the wave engine through   #
# shape-split pools (interior/edge/corner stacks, exact tile shapes —   #
# the reference's lm%mb edge-tile contract, matrix.c:106,116)           #
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("n,nb", [(1000, 128), (520, 128), (136, 64)])
def test_wave_dpotrf_ragged(n, nb):
    A, M = _spd_coll(n, nb)
    w = wave(dpotrf_taskpool(A), max_chunk=64)
    # the ragged tiling must split into >1 pool for the one collection
    assert len(w.pool_names) > len(w.coll_names)
    assert all(tuple(np.asarray(
        A.tile_shape(*c))) == tuple(w._pool_shapes[pid])
        for pid in range(len(w.pool_names))
        for c in w._pool_coords[pid])
    w.run()
    L = np.tril(A.to_numpy()).astype(np.float64)
    assert np.allclose(L, np.linalg.cholesky(M.astype(np.float64)),
                       atol=1e-3)


def test_wave_dgetrf_ragged():
    n, nb = 840, 128        # 840 = 6*128 + 72: bottom/right/corner pools
    A, M = _spd_coll(n, nb)
    wave(dgetrf_nopiv_taskpool(A), max_chunk=32).run()
    LU = A.to_numpy().astype(np.float64)
    L = np.tril(LU, -1) + np.eye(n)
    U = np.triu(LU)
    assert np.abs(L @ U - M).max() / np.abs(M).max() < 1e-5


def test_wave_pdgemm_ragged():
    n, nb = 600, 128        # 600 = 4*128 + 88
    rng = np.random.RandomState(7)
    Am = rng.rand(n, n).astype(np.float32)
    Bm = rng.rand(n, n).astype(np.float32)
    A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(Am)
    B = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(Bm)
    C = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(
        np.zeros((n, n), np.float32))
    wave(pdgemm_taskpool(A, B, C), max_chunk=16).run()
    ref = Am.astype(np.float64) @ Bm.astype(np.float64)
    assert np.abs(C.to_numpy().astype(np.float64) - ref).max() / n < 1e-6


def test_synth_pools_parity_and_subset_coords():
    """On-device pool synthesis (zero-H2D staging, bench/demo path):
    the vectorized whole-pool builder (bench.synth_spd_pool_fn) must
    produce exactly the per-tile _synth_lower values in build_pools'
    layout, both granularities must agree, and a SUBSET coordinate set
    (e.g. a lower-uplo pool) must not clobber row 0 with dropped
    scatter writes (the pos-default bug class)."""
    import os
    import sys

    import jax
    import jax.numpy as jnp

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench import _synth_lower, synth_spd_pool_fn

    n, nb = 128, 32
    nt = n // nb
    key = jax.random.PRNGKey(23)
    A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32)
    w = wave(dpotrf_taskpool(A))
    pool_fn = synth_spd_pool_fn(key, nt, nb, n, jnp.float32)

    def tile_fn(_name, c):
        low = _synth_lower(key, nt, nb, n, jnp.float32)
        return low[c] if c[0] >= c[1] else jnp.zeros((nb, nb),
                                                     jnp.float32)

    by_pool = w.synth_pools(pool_fn=pool_fn)
    by_tile = w.synth_pools(tile_fn)
    assert len(by_pool) == len(by_tile) == len(w.build_pools())
    for a, b in zip(by_pool, by_tile):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # subset coords: lower triangle only — absent uppers must be
    # DROPPED, not scattered onto row 0
    coords = [(m, k) for m in range(nt) for k in range(m + 1)]
    sub = np.asarray(jax.jit(lambda: pool_fn("descA", coords))())
    low = {c: np.asarray(v) for c, v in
           jax.jit(lambda: _synth_lower(key, nt, nb, n,
                                        jnp.float32))().items()}
    for i, c in enumerate(coords):
        np.testing.assert_array_equal(sub[i], low[c])
