"""Device data plane: cross-process device-to-device tile transfers.

Reference behavior being replaced: on multi-node runs the reference
moves tile payloads over MPI alongside the control traffic
(parsec/parsec_mpi_funnelled.c:245-365 — GET emulation over two-sided
sends through HOST buffers). On TPU pods the idiomatic data plane is the
interconnect fabric itself: this module wires jax's transfer server
(``jax.experimental.transfer`` — the DCN/ICI point-to-point pull API)
into the comm-engine as a side channel, so a cross-rank dataflow edge
whose payload already lives in device memory is pulled device-to-device
by the consumer, never round-tripping through host pickling.

Division of labor (SURVEY.md §5.8): the CommEngine (TCP across
processes) stays the CONTROL plane — activations, GET requests, termdet;
bulk tile payloads ride this plane whenever both ends have one. Host
payloads keep using the classic CE rendezvous.

Address exchange is SPMD: every rank broadcasts its transfer-server
address over a reserved AM tag at attach time; `exchange()` progresses
the CE until all peers are known.

CROSS-PROCESS ONLY: two transfer servers in one OS process trip the
runtime's local-bulk-transport CHECK (observed: abseil fatal in
streaming.cc). In-process rank fabrics (LocalFabric/MeshFabric) already
share an address space — they don't need this plane.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ..utils import logging as plog
from .engine import TAG_USER_BASE

TAG_XFER_ADDR = TAG_USER_BASE - 2  # reserved (transport sync uses -1)
TAG_XFER_ACK = TAG_USER_BASE - 3   # consumer pulled: release the park


# re-export (transport modules and the PTG runtime both test payloads)
from ..data.data import is_device_array as _is_device_array  # noqa: E402,F401


def _resolve_backend(backend: Optional[str] = None) -> Tuple[Any, str]:
    """Pick the transfer-server implementation.  MCA ``xfer_backend``:
    ``native`` requires ``jax.experimental.transfer`` (TPU/GPU builds),
    ``loopback`` forces the in-process socket backend
    (parsec_tpu/xfer/loopback.py — what CI runs), ``auto`` (default)
    prefers native and falls back exactly when the jax API is absent,
    so the same DeviceDataPlane code path runs everywhere."""
    if backend is None:
        from ..utils.params import params
        backend = str(params.get_or("xfer_backend", "string", "auto"))
    if backend not in ("auto", "native", "loopback"):
        raise ValueError(f"xfer_backend={backend!r}: expected "
                         f"auto/native/loopback")
    if backend != "loopback":
        try:
            from jax.experimental import transfer
            return transfer, "native"
        except ImportError:
            if backend == "native":
                raise
    from ..xfer import loopback
    return loopback, "loopback"


class DeviceDataPlane:
    """One per rank: a transfer server + connections to the peers.

    uuids are partitioned by rank (rank in the high bits) so producers
    never collide. ``register`` parks a device array for one remote pull;
    ``pull`` fetches a peer's parked array straight into local device
    memory (async — jax arrays materialize when the transfer lands).
    """

    def __init__(self, ce, device=None, host: str = "127.0.0.1",
                 backend: Optional[str] = None) -> None:
        import jax

        transfer, self.backend_name = _resolve_backend(backend)
        self.ce = ce
        self.device = device if device is not None else jax.devices()[0]
        # separate bulk-transport sockets are REQUIRED: without explicit
        # transport addresses the cross-process pull dies with a torn
        # connection (errno 107) or an aborted local-transport check
        self.server = transfer.start_transfer_server(
            self.device.client, f"{host}:0", [f"{host}:0"])
        self.addresses: Dict[int, str] = {ce.rank: self.server.address()}
        self._conns: Dict[int, Any] = {}
        self._uuid_next = 1
        self._parked: Dict[int, Any] = {}   # uuid -> array (keep-alive)
        self._lock = threading.Lock()
        self.stats = {"pulls": 0, "serves": 0, "bytes_pulled": 0}
        ce.tag_register(TAG_XFER_ADDR, self._on_addr)
        for r in range(ce.nb_ranks):
            if r != ce.rank:
                ce.send_am(r, TAG_XFER_ADDR,
                           {"rank": ce.rank, "addr": self.server.address()})
        ce.device_plane = self

    def _on_addr(self, src: int, payload: Dict) -> None:
        self.addresses[payload["rank"]] = payload["addr"]

    def exchange(self, timeout: float = 30.0) -> None:
        """Progress the CE until every peer's address arrived."""
        import time
        t0 = time.monotonic()
        while len(self.addresses) < self.ce.nb_ranks:
            self.ce.progress()
            if time.monotonic() - t0 > timeout:
                missing = [r for r in range(self.ce.nb_ranks)
                           if r not in self.addresses]
                raise TimeoutError(
                    f"no transfer address from ranks {missing}")
            time.sleep(0.001)

    # ------------------------------------------------------------------ #
    def register(self, arr: Any) -> Tuple[int, Tuple, str]:
        """Park a device array for one remote pull; returns the wire
        descriptor (uuid, shape, dtype_name)."""
        with self._lock:
            uuid = (self.ce.rank << 40) | self._uuid_next
            self._uuid_next += 1
            self._parked[uuid] = arr
            self.stats["serves"] += 1
        self.server.await_pull(uuid, [arr])
        return uuid, tuple(arr.shape), str(arr.dtype)

    def release(self, uuid: int) -> None:
        """Drop the keep-alive once the consumer confirmed the pull."""
        with self._lock:
            self._parked.pop(uuid, None)

    def is_parked(self, uuid: int) -> bool:
        with self._lock:
            return uuid in self._parked

    def pull(self, src_rank: int, uuid: int, shape: Tuple,
             dtype: str, device=None) -> Any:
        """Fetch a parked array from ``src_rank`` device-to-device;
        returns a local device array (materializes asynchronously).
        ``device`` selects the landing device for multi-device ranks
        (default: the plane's primary device)."""
        import jax
        from jax.sharding import SingleDeviceSharding

        # connect() blocks on the network: holding self._lock across it
        # would wedge register()/release() — including the ACK path that
        # frees producer parks — behind a slow or dead peer. Double-checked
        # insert instead (a raced duplicate connection is dropped).
        conn = self._conns.get(src_rank)
        if conn is None:
            with self._lock:
                addr = self.addresses.get(src_rank)
            if addr is None:
                raise RuntimeError(
                    f"no transfer address for rank {src_rank} "
                    f"(exchange() not run?)")
            new_conn = self.server.connect(addr)
            with self._lock:
                conn = self._conns.setdefault(src_rank, new_conn)
            if conn is not new_conn:
                # lost the race: close the duplicate if the transfer API
                # exposes close (it may not — then the object just drops)
                closer = getattr(new_conn, "close", None)
                if callable(closer):
                    closer()
        spec = jax.ShapeDtypeStruct(
            shape, np.dtype(dtype),
            sharding=SingleDeviceSharding(
                device if device is not None else self.device))
        out = conn.pull(uuid, [spec])[0]
        nbytes = int(np.prod(shape)) * np.dtype(dtype).itemsize
        with self._lock:
            self.stats["pulls"] += 1
            self.stats["bytes_pulled"] += nbytes
        # DPLANE_BYTES / DPLANE_XFERS gauges (obs.register_engine_gauges
        # polls the engine-owned dict; observability only — no wire bytes)
        ds = getattr(self.ce, "dplane_stats", None)
        if ds is not None:
            ds["dplane_xfers"] += 1
            ds["dplane_bytes"] += nbytes
        return out

    def fini(self) -> None:
        with self._lock:
            self._parked.clear()
        self._conns.clear()
        closer = getattr(self.server, "close", None)
        if callable(closer):   # the native server may not expose close
            closer()
        plog.debug.verbose(3, "device plane rank %d: %s", self.ce.rank,
                           self.stats)
