#!/usr/bin/env python
"""North-star-scale dist-wave dpotrf: the reference's flagship graph
shape (N=65536, NB=512 -> NT=128: 357,760 tasks) executed END TO END
across SPMD ranks on the virtual CPU mesh.

Small nb keeps per-tile compute tiny so the run exercises the ENGINE at
scale, which is the point (round-4 VERDICT Missing #1: the graph had
been lowered but never executed): Python-side lowering of the 357k-task
space, the per-rank static exchange schedules, broadcast trees, the
lowering cache shared across ranks (one enumeration, 8 consumers — the
in-process analog of the reference's per-process jdf2c tables,
/root/reference/parsec/parsec.c:688-694 startup chunking), and memory
behavior, all through the same code path the TPU perf story rides.

Usage: python tools/northstar_dist.py [NT [nb [ranks]]]
         (defaults 128 16 8)
Env:   NORTHSTAR_SHARDING=hybrid  -> each rank's pools shard over its
       own sub-mesh of the virtual devices (process x mesh GSPMD);
       needs ranks * submesh <= device count.
       NORTHSTAR_SHARDING=mesh    -> same layout through the ISSUE-6
       mesh machinery: rank_mesh_sharding carves each rank's chip
       sub-mesh (NORTHSTAR_MESH_SHAPE, default 2x2) with the same
       offsets the device layer uses, so intra-mesh dependencies ride
       XLA sharding instead of the exchange.
       NORTHSTAR_BCAST=binomial|chain|star (default binomial).
       NORTHSTAR_COLLECTIVE=on -> broadcast groups (full AND
       partial member sets — any P x Q grid) ride the compiled
       collective lane (wave_dist_collective; in-process substrate).
       NORTHSTAR_GRID=PxQ -> override the process grid (default: most
       square).

Self-relaunches with a CPU-pinned env (8 virtual devices) when invoked
under the TPU plugin. Prints one JSON line with the full report.
"""
import json
import os
import resource
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _relaunch_cpu(n_devices: int) -> int:
    keep = ("PATH", "HOME", "LANG", "LC_ALL", "TERM", "TMPDIR", "USER",
            "SHELL", "HOSTNAME")
    env = {k: os.environ[k] for k in keep if k in os.environ}
    for k in os.environ:
        if k.startswith("NORTHSTAR_"):
            env[k] = os.environ[k]
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_devices}"
    env["PYTHONPATH"] = ROOT
    env["PARSEC_MCA_device_tpu_platform"] = "cpu"
    env["_NORTHSTAR_INNER"] = "1"
    return subprocess.call([sys.executable, os.path.abspath(__file__)]
                           + sys.argv[1:], env=env)


def main() -> int:
    nt = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    nb = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    ranks = int(sys.argv[3]) if len(sys.argv) > 3 else 8
    if "_NORTHSTAR_INNER" not in os.environ:
        return _relaunch_cpu(max(8, ranks))

    import threading

    import numpy as np

    from parsec_tpu.collections import TwoDimBlockCyclic
    from parsec_tpu.comm import LocalFabric
    from parsec_tpu.dsl import ptg
    import importlib
    lower_mod = importlib.import_module("parsec_tpu.dsl.ptg.lower")
    from parsec_tpu.ops import dpotrf_taskpool, make_spd
    from parsec_tpu.utils.params import params

    n = nt * nb
    sharding = os.environ.get("NORTHSTAR_SHARDING", "")
    bcast = os.environ.get("NORTHSTAR_BCAST", "binomial")
    params.set_cmdline("wave_dist_bcast", bcast)
    if os.environ.get("NORTHSTAR_COLLECTIVE") == "on":
        params.set_cmdline("wave_dist_collective", "on")

    def log(msg):
        print(f"[{time.strftime('%H:%M:%S')}] {msg}", flush=True)

    t0 = time.perf_counter()
    M = make_spd(n, dtype=np.float64)
    log(f"input N={n} built ({time.perf_counter() - t0:.1f}s)")

    # one symbolic lowering of the full task space, shared by every
    # rank through the process lowering cache (keyed on the module-
    # cached JDF + shape signature, lower.py:125-175)
    proto = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float64,
                              P=1, Q=1, nodes=ranks, rank=0)
    proto.name = "descA"
    t0 = time.perf_counter()
    dag = lower_mod.lower(dpotrf_taskpool(proto, rank=0, nb_ranks=ranks),
                          allow_multirank=True)
    t_lower = time.perf_counter() - t0
    log(f"lowered {dag.n_tasks} tasks ({t_lower:.1f}s)")
    t0 = time.perf_counter()
    hit = lower_mod.lower(dpotrf_taskpool(proto, rank=0, nb_ranks=ranks),
                          allow_multirank=True)
    t_relower = time.perf_counter() - t0
    assert hit is dag, "lowering cache missed on identical shape"

    fabric = LocalFabric(ranks)
    grid = os.environ.get("NORTHSTAR_GRID")
    if grid:
        P = int(grid.lower().split("x")[0])
        assert ranks % P == 0, f"grid {grid} does not divide {ranks} ranks"
    else:
        P = max(p for p in range(1, int(ranks ** 0.5) + 1)
                if ranks % p == 0)
    barrier = threading.Barrier(ranks)

    def rank_main(r, fab):
        import jax
        ce = fab.engine(r)
        coll = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float64,
                                 P=P, Q=ranks // P,
                                 nodes=ranks, rank=r)
        coll.name = "descA"
        coll.from_numpy(M)   # local tiles only are materialized
        tp = dpotrf_taskpool(coll, rank=r, nb_ranks=ranks)
        t0 = time.perf_counter()
        w = ptg.wave(tp, comm=ce)
        t_plan = time.perf_counter() - t0
        cpus = jax.devices("cpu")
        if sharding == "mesh":
            from parsec_tpu.dsl.ptg.wave_dist import rank_mesh_sharding
            sh = rank_mesh_sharding(
                r, shape=os.environ.get("NORTHSTAR_MESH_SHAPE", "2x2"),
                devices=cpus)
            assert sh is not None, "mesh sharding needs a PxQ > 1 shape"
            pools = w.build_pools(sharding=sh)
        elif sharding == "hybrid":
            from jax.sharding import (Mesh, NamedSharding,
                                      PartitionSpec as Psp)
            sub = len(cpus) // ranks
            assert sub >= 2, "hybrid needs >=2 devices per rank"
            side = max(d for d in range(1, int(sub ** 0.5) + 1)
                       if sub % d == 0)
            mesh = Mesh(np.array(cpus[r * sub:(r + 1) * sub])
                        .reshape(side, sub // side), ("tp", "sp"))
            pools = w.build_pools(
                sharding=NamedSharding(mesh, Psp(None, "tp", "sp")))
        else:
            pools = w.build_pools(device=cpus[r % len(cpus)])
        jax.block_until_ready(pools)
        barrier.wait(600)            # all ranks staged
        t0 = time.perf_counter()
        pools = w.execute(pools)
        jax.block_until_ready(pools)
        t_exec = time.perf_counter() - t0
        w.scatter_pools(pools)
        owned = {c: np.asarray(coll.data_of(*c).sync_to_host().payload)
                 for c in coll.tiles() if coll.rank_of(*c) == r}
        return (t_plan, t_exec, w.stats, owned)

    from parsec_tpu.utils.spmd import spmd_threads
    t_all0 = time.perf_counter()
    results, _ = spmd_threads(ranks, rank_main, timeout=7200,
                              fabric=fabric)
    t_wall = time.perf_counter() - t_all0
    log(f"all ranks done ({t_wall:.1f}s)")

    L = np.zeros((n, n))
    for (_tp, _te, _st, owned) in results:
        for (m, k), t in owned.items():
            L[m * nb:m * nb + t.shape[0], k * nb:k * nb + t.shape[1]] = t
    Lt = np.tril(L)
    resid = float(np.abs(Lt @ Lt.T - M).max() / np.abs(M).max())
    stats = [st for (_tp, _te, st, _o) in results]
    report = {
        "metric": f"northstar_dist_dpotrf(NT={nt},nb={nb},ranks={ranks}"
                  + (f",{sharding}" if sharding else "") + ")",
        "tasks": dag.n_tasks,
        "waves": stats[0]["waves"],
        "residual": resid,
        "numerics_ok": resid < 1e-5,
        "t_lower_secs": round(t_lower, 2),
        "t_relower_secs": round(t_relower, 4),   # cache-hit cost
        "lowering_cache_shared": True,
        "t_plan_secs_max": round(max(tp for (tp, _e, _s, _o)
                                     in results), 2),
        "t_exec_secs_max": round(max(te for (_p, te, _s, _o)
                                     in results), 2),
        "wall_secs": round(t_wall, 2),
        "kernel_calls": sum(s["kernel_calls"] for s in stats),
        "compiled_kernels": sum(s["compiled_kernels"] for s in stats),
        "transfers_scheduled": sum(s["transfers_scheduled"]
                                   for s in stats),
        "tiles_sent": sum(s["tiles_sent"] for s in stats),
        "tiles_recv": sum(s["tiles_recv"] for s in stats),
        "tiles_forwarded": sum(s["tiles_forwarded"] for s in stats),
        "bcast_topology": stats[0]["bcast_topology"],
        "collective_lane": stats[0].get("collective_lane"),
        "collective_calls": sum(s.get("collective_calls", 0)
                                for s in stats),
        "collective_tiles": sum(s.get("collective_tiles", 0)
                                for s in stats),
        "peak_rss_mb": round(resource.getrusage(
            resource.RUSAGE_SELF).ru_maxrss / 1024.0, 1),
    }
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
