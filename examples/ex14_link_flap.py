"""Ex14: link-fault resilience — 3-rank checkpointed dpotrf over REAL
TCP sockets that survives a link flap WITHOUT any grid resize
(ISSUE 10).

The same scenario as ex13 (three ranks, ``ft.run_with_restart``,
snapshots every stage) but the ranks talk over the TCP comm engine on
localhost, so the reliable-session layer has an actual wire to tear.
Run it under ``tools/chaos_run.py``:

- a ``flap:`` inside the ``--reconnect`` budget is ABSORBED: the torn
  link goes SUSPECT, reconnects, replays the unacked frames, and the
  factorization completes on the FULL grid with zero evictions and
  zero elastic resizes (``RECONNECTS >= 1``, ``REPLAYED > 0``);
- a ``disconnect:`` (the link never comes back) exhausts the budget
  and escalates through the ordinary rank-failure path: with
  ``ft_elastic=shrink`` the majority side reshards onto the reduced
  grid (the PR 9 machinery), while the isolated minority rank refuses
  a split-brain resize and aborts.

Run::

    # transient: completes on the full grid, exit 0, no resizes
    PARSEC_MCA_ft_elastic=shrink python tools/chaos_run.py \\
        --reconnect 10 --inject "flap:rank=2:nth=8:duration=0.2" \\
        --heartbeat 0.05 --timeout 3 -- examples/ex14_link_flap.py

    # permanent: survivors shrink to (0, 1), rank 2 aborts, exit 0
    PARSEC_MCA_ft_elastic=shrink python tools/chaos_run.py \\
        --reconnect 1.5 --inject "disconnect:rank=2:nth=8" \\
        --heartbeat 0.05 --timeout 3 -- examples/ex14_link_flap.py
"""
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import parsec_tpu  # noqa: E402
from parsec_tpu.comm import RemoteDepEngine  # noqa: E402
from parsec_tpu.comm.tcp import TCPCommEngine, free_ports  # noqa: E402
from parsec_tpu.ft import (ElasticPolicy, RestartPolicy,  # noqa: E402
                           run_with_restart)
from parsec_tpu.ft.elastic import GridSpec, plan_grid  # noqa: E402
from parsec_tpu.ops import dpotrf_taskpool, make_spd  # noqa: E402
from parsec_tpu.utils.spmd import spmd_threads  # noqa: E402

NB_RANKS, N, NB = 3, 256, 32


def _establish_all(ctx, eng, nb_ranks, rank):
    """Heartbeat contact with every peer before the workload (the
    steady state a long-running job is in when a link tears)."""
    det = ctx._ft_detector
    if det is None:
        return
    deadline = time.monotonic() + 30.0
    while any(not det.is_established(p)
              for p in range(nb_ranks) if p != rank):
        assert time.monotonic() < deadline, "heartbeat never established"
        eng.ce.progress()
        time.sleep(0.002)
    eng.ce.sync()


def run_rank(rank, eps, M, prefix):
    ce = TCPCommEngine(rank, eps)
    eng = RemoteDepEngine(ce)
    ctx = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
    try:
        def rebuild(grid: GridSpec):
            A = grid.collection(N, N, NB, NB, dtype=np.float32)
            A.name = "descA"
            for (i, j) in A.local_tiles():
                np.copyto(A.tile(i, j),
                          M[i * NB:(i + 1) * NB, j * NB:(j + 1) * NB])
            stages = [lambda: dpotrf_taskpool(A, rank=rank,
                                              nb_ranks=NB_RANKS)]
            return stages, [A]

        _establish_all(ctx, eng, NB_RANKS, rank)
        policy = RestartPolicy("restart", retries=1, every=1)
        pol = ElasticPolicy(rebuild)
        try:
            if pol.mode:
                stats = run_with_restart(ctx, None, None, prefix,
                                         policy=policy, elastic=pol)
                grid = plan_grid(stats["grid"], NB_RANKS, rank)
                _, (A,) = rebuild(grid)  # same layout the run ended on
                # rebuild reinitialized tiles: pull the FINAL state back
                from parsec_tpu.utils import checkpoint as ckpt
                ckpt.restore_collection(
                    A, f"{prefix}.stage{stats['stages']}.c0",
                    reshard=True, context=ctx)
            else:
                stages, (A,) = rebuild(plan_grid(
                    tuple(range(NB_RANKS)), NB_RANKS, rank))
                stats = run_with_restart(ctx, stages, [A], prefix,
                                         policy=policy)
            local = {t: np.array(A.tile(*t)) for t in A.local_tiles()
                     if A.rank_of(*t) == rank}
            return ("ok", local, stats, dict(ce.elastic_stats),
                    dict(ce.wire_stats))
        except RuntimeError as e:
            root = e.__cause__ or e
            return (type(root).__name__, None, None,
                    dict(ce.elastic_stats), dict(ce.wire_stats))
    finally:
        ctx.clear_task_errors()
        ctx.fini()


def main() -> int:
    M = make_spd(N)
    ports = free_ports(NB_RANKS)
    eps = [("127.0.0.1", p) for p in ports]
    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "ck")
        results, _ = spmd_threads(
            NB_RANKS, lambda r, f: run_rank(r, eps, M, prefix),
            timeout=600)

    ok = [r for r, out in enumerate(results) if out[0] == "ok"]
    dead = [r for r, out in enumerate(results) if out[0] != "ok"]
    for r, out in enumerate(results):
        es = out[3] or {}
        ws = out[4] or {}
        print(f"rank {r}: {out[0]} stats={out[2]} "
              f"ELASTIC_RESIZES={es.get('elastic_resizes', 0)} "
              f"RESHARD_BYTES={es.get('reshard_bytes', 0)} "
              f"RECONNECTS={ws.get('reconnects', 0)} "
              f"REPLAYED={ws.get('replayed_frames', 0)} "
              f"DUP_DROPPED={ws.get('dup_dropped', 0)}")
    if not ok:
        print("ex14: every rank aborted")
        return 1

    # the completed ranks must agree on the final grid and hold ALL
    # tiles of a verifiable Cholesky factor between them
    grids = {results[r][2]["grid"] for r in ok}
    if len(grids) != 1:
        print(f"ex14: completed ranks disagree on the final grid: {grids}")
        return 1
    (grid,) = grids
    if grid is None:               # strict path reports no grid
        grid = tuple(range(NB_RANKS))
    if set(grid) != set(ok):
        print(f"ex14: final grid {grid} != completed ranks {ok}")
        return 1
    L = np.zeros_like(M)
    for r in ok:
        for (i, j), tile in results[r][1].items():
            L[i * NB:(i + 1) * NB, j * NB:(j + 1) * NB] = tile
    L = np.tril(L)
    resid = (np.abs(L @ L.T - M).max()
             / (np.abs(M).max() * N))
    print(f"ex14: dpotrf n={N} nb={NB} finished on grid {grid} "
          f"(lost: {dead}); residual {resid:.2e}")
    if resid >= 1e-5:
        print("ex14: residual above the dpotrf gate")
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
