"""CI smoke: ex02_chain runs with tracing + metrics enabled, its
exported trace validates against the minimal Chrome-trace schema, and
tools/obs_report.py produces the critical-path / breakdown / overlap
report from it — so a telemetry regression fails tier-1."""
import json
import os
import sys

import pytest

import parsec_tpu
from parsec_tpu.obs import validate_chrome_trace

TOOLS = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)

import obs_report  # noqa: E402


@pytest.fixture
def traced_ex02(tmp_path):
    """Run examples/ex02_chain.py with profile + DOT + metrics on;
    yields (trace_path, dot_path)."""
    prefix = str(tmp_path / "smoke")
    parsec_tpu.params.set_cmdline("profile", prefix)
    parsec_tpu.params.set_cmdline("profiling_dot", prefix)
    parsec_tpu.params.set_cmdline("metrics", "1")
    try:
        from examples import ex02_chain
        assert ex02_chain.main(6) == 0
    finally:
        parsec_tpu.params.unset_cmdline("profile")
        parsec_tpu.params.unset_cmdline("profiling_dot")
        parsec_tpu.params.unset_cmdline("metrics")
    trace = tmp_path / "smoke.rank0.trace.json"
    dot = tmp_path / "smoke.rank0.dot"
    assert trace.exists(), "profile prefix did not produce a trace file"
    assert dot.exists(), "profiling_dot did not produce a DOT file"
    return str(trace), str(dot)


def test_ex02_trace_validates_and_reports(traced_ex02, capsys):
    trace, dot = traced_ex02
    with open(trace) as fh:
        doc = json.load(fh)
    summary = validate_chrome_trace(doc)
    assert summary["spans"] >= 7          # one exec span per chain task
    assert summary["metadata"] >= 2       # process_name + thread_name
    names = {e["name"] for e in doc["traceEvents"]}
    assert "process_name" in names and "thread_name" in names
    assert any(n.startswith("exec:") for n in names)
    # SDE counters were sampled into the trace at fini
    assert any(e.get("ph") == "C" for e in doc["traceEvents"])

    # the report end-to-end: critical path + breakdown + overlap
    assert obs_report.main([trace, "--dot", dot]) == 0
    out = capsys.readouterr().out
    assert "critical path:" in out
    assert "per-task-class breakdown:" in out
    assert "overlap" in out
    # the chain is sequential: critical path == total exec (7 tasks)
    report = _report_json(trace, dot, capsys)
    cp = report["critical_path"]
    assert cp["nb_tasks"] == 7
    assert cp["length_us"] == pytest.approx(cp["total_exec_us"], rel=1e-6)


def _report_json(trace, dot, capsys):
    assert obs_report.main([trace, "--dot", dot, "--json"]) == 0
    return json.loads(capsys.readouterr().out)


def test_binary_trace_roundtrip_with_obs_streams(tmp_path):
    """The .ptt binary dump must survive the new comm/device streams and
    non-JSON info payloads (repr fallback)."""
    import numpy as np
    from parsec_tpu.profiling.binfmt import read_profile
    from parsec_tpu.profiling.trace import Profile
    p = Profile(rank=0)
    st = p.stream(1 << 20, "comm")
    st.begin("comm:send", info={"arr": np.zeros(3)})  # not JSON-serializable
    st.end("comm:send")
    # complete ("X") span, 4000 ns long (timestamps on the profile base)
    st.span("comm:get", p._t0 + 1000, p._t0 + 5000, {"bytes": 64})
    out = p.dump_binary(str(tmp_path / "t"))
    rp = read_profile(out)  # rebased at t0=0
    assert rp.nb_events() == 3
    # the .ptt toolchain sees the X span as an interval of its duration
    import ptt_dump
    ivs = ptt_dump.intervals_of(list(rp._streams.values())[0])
    assert ("comm:get", 1000, 5000, {"bytes": 64, "dur_ns": 4000}) in ivs
    # chrome export with the same payload must not crash either
    out_json = p.dump(str(tmp_path / "t.json"))
    with open(out_json) as fh:
        doc = json.load(fh)
    validate_chrome_trace(doc)
