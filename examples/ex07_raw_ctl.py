"""Ex07: CTL flows — ordering without data.

Teaches: a CTL (control) flow carries no payload, only an ordering edge:
every TaskRecv signals TaskUpdate's ctl input, so the update cannot start
until all readers finished — the RAW hazard of Ex06 is now an enforced
readers-then-writer schedule (ref: examples/Ex07_RAW_CTL.jdf; CTL
semantics parsec.y control-flow rules).
"""
import os, sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import parsec_tpu
from parsec_tpu.collections import LocalArrayCollection
from parsec_tpu.dsl import ptg

RAW_CTL_JDF = """
mydata [ type="collection" ]
NB     [ type="int" ]

TaskBcast(k)

k = 0 .. 0

: mydata( k )

RW  A <- mydata( k )
      -> A TaskUpdate( k )
      -> A TaskRecv( k, 0 .. NB .. 2 )

BODY
{
    A[...] = k + 1
}
END

TaskRecv(k, n)

k = 0 .. 0
n = 0 .. NB .. 2
loc = k + n

: mydata( loc )

READ A <- A TaskBcast( k )

CTL ctl -> ctl TaskUpdate( k )

BODY
{
    order.append(("recv", loc))
}
END

TaskUpdate(k)

k = 0 .. 0

: mydata( k )

RW  A <- A TaskBcast( k )
      -> mydata( k )

CTL ctl <- ctl TaskRecv( k, 0 .. NB .. 2 )

BODY
{
    A[...] += 100
    order.append(("update", k))
}
END
"""


def main(NB: int = 6) -> int:
    order = []
    ctx = parsec_tpu.init(nb_cores=2)
    try:
        mydata = LocalArrayCollection(np.zeros((NB + 1, 1), dtype=np.int64),
                                      NB + 1)
        factory = ptg.compile_jdf(RAW_CTL_JDF, name="rawctl")
        tp = factory.new(mydata=mydata, NB=NB)
        # taskpool globals are visible in BODY scope: share the order log
        tp.global_env["order"] = order
        ctx.add_taskpool(tp)
        ctx.wait()
    finally:
        ctx.fini()
    # the CTL edge guarantees every recv precedes the update
    upd = order.index(("update", 0))
    recvs = [i for i, e in enumerate(order) if e[0] == "recv"]
    assert len(recvs) == NB // 2 + 1 and all(i < upd for i in recvs), order
    print(f"order: {order} — all recvs before update, as forced by CTL")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
