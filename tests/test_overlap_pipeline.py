"""Overlap-aware execution (ISSUE 7): segmented flush bit-exactness +
fallback, remote-GET prefetch for early activations, and the live
overlap tracker's interval algebra."""
import os
import sys
import time

import numpy as np
import pytest

import parsec_tpu
from conftest import spmd
from parsec_tpu import dtd
from parsec_tpu.collections import TwoDimBlockCyclic
from parsec_tpu.comm import RemoteDepEngine
from parsec_tpu.dsl import ptg
from parsec_tpu.dsl.dtd import INOUT, INPUT
from parsec_tpu.ops import dpotrf_taskpool, make_spd
from parsec_tpu.utils.params import params


def _tpu_devs(ctx):
    return [d for d in ctx.devices if d.device_type == "tpu"]


# --------------------------------------------------------------------- #
# segmented flush: bit-exact differential + counters + fallback         #
# --------------------------------------------------------------------- #
def _run_dpotrf(segments: int):
    """One classic-runtime dpotrf (POTRF/TRSM/SYRK/GEMM classes) with
    the given device_flush_segments; returns (L, segment stats)."""
    M = make_spd(256)
    with params.cmdline_override("device_tpu_max", "1"), \
         params.cmdline_override("device_flush_segments", str(segments)):
        ctx = parsec_tpu.Context(nb_cores=2)
        try:
            A = TwoDimBlockCyclic(256, 256, 32, 32,
                                  dtype=np.float32).from_numpy(M)
            ctx.add_taskpool(dpotrf_taskpool(A))
            ctx.wait()
            devs = _tpu_devs(ctx)
            st = {k: sum(d.stats[k] for d in devs)
                  for k in ("segmented_flushes", "flush_segments",
                            "batches", "batched_tasks")}
            return A.to_numpy().copy(), st
        finally:
            ctx.fini()


def test_segmented_flush_bit_exact_dpotrf():
    """Acceptance: segmented flush is BIT-EXACT vs whole-batch unroll
    dispatch for the cholesky/trsm/syrk/gemm classes, and the segment
    counters prove the pipelined path really ran."""
    L_whole, st_whole = _run_dpotrf(1)
    L_seg, st_seg = _run_dpotrf(4)
    assert st_whole["segmented_flushes"] == 0
    assert st_whole["flush_segments"] == 0
    assert st_seg["segmented_flushes"] > 0
    # every carved group produced >= 2 sub-calls
    assert st_seg["flush_segments"] >= 2 * st_seg["segmented_flushes"]
    assert st_seg["batches"] > st_whole["batches"]  # more, smaller calls
    assert np.array_equal(L_whole, L_seg), \
        "segmented flush is not bit-exact vs whole-batch dispatch"


def _run_dtd_burst(segments: int, kern, burst=32, nb=48):
    with params.cmdline_override("device_tpu_max", "1"), \
         params.cmdline_override("device_flush_segments", str(segments)):
        ctx = parsec_tpu.init(nb_cores=2)
        try:
            tp = dtd.taskpool_new()
            ctx.add_taskpool(tp)

            def body(es, task):   # host fallback
                c, a, b = dtd.unpack_args(task)
                c -= a @ b.T

            boot = tp.tile_of_array(np.zeros((nb, nb), np.float32))
            tp.insert_task(body, (boot, INOUT), (boot, INPUT),
                           (boot, INPUT))
            tp.add_chore(body, "tpu", kern)
            rng = np.random.RandomState(7)
            tiles = [[tp.tile_of_array(rng.rand(nb, nb).astype(np.float32))
                      for _ in range(3)] for _ in range(burst)]
            for c, a, b in tiles:
                tp.insert_task(body, (c, INOUT), (a, INPUT), (b, INPUT))
            tp.wait()
            devs = _tpu_devs(ctx)
            st = {k: sum(d.stats[k] for d in devs)
                  for k in ("segmented_flushes", "flush_segments",
                            "batches")}
            out = [np.asarray(c.data.sync_to_host().payload)
                   for c, _a, _b in tiles]
            return out, st
        finally:
            ctx.fini()


def test_segmented_flush_bit_exact_dtd_burst():
    import jax
    import jax.numpy as jnp
    kern = jax.jit(lambda c, a, b:
                   c - jnp.dot(a, b.T,
                               preferred_element_type=jnp.float32))
    out_whole, st_whole = _run_dtd_burst(1, kern)
    out_seg, st_seg = _run_dtd_burst(4, kern)
    assert st_seg["segmented_flushes"] > 0 >= st_whole["segmented_flushes"]
    assert all(np.array_equal(a, b) for a, b in zip(out_whole, out_seg))


def test_segmented_flush_untraceable_falls_back_per_task():
    """A trace failure inside the FIRST segment must downgrade the class
    and finish the whole group per-task — same transparent fallback as
    the whole-batch path, results unchanged."""
    def kern(c, a, b):   # np.asarray on a tracer raises under jit
        return c - np.asarray(a) @ np.asarray(b).T

    out, st = _run_dtd_burst(4, kern, burst=16)
    assert st["batches"] == 0, "untraceable body must not batch"
    rng = np.random.RandomState(7)
    tiles = [[rng.rand(48, 48).astype(np.float32) for _ in range(3)]
             for _ in range(16)]
    for got, (c, a, b) in zip(out, tiles):
        np.testing.assert_allclose(got, c - a @ b.T, atol=1e-4)


# --------------------------------------------------------------------- #
# remote-GET prefetch: an activation racing ahead of registration       #
# --------------------------------------------------------------------- #
PREFETCH_JDF = """
descX [ type="collection" ]

PROD(k)

k = 0 .. 0

: descX( 0, 0 )

RW X <- descX( 0, 0 )
     -> X CONS( 0 )
     -> descX( 0, 0 )

BODY
{
    X[:, :] = X + 1.0
}
END

CONS(k)

k = 0 .. 0

: descX( 1, 0 )

READ X <- X PROD( 0 )
RW   Y <- descX( 1, 0 )
       -> descX( 1, 0 )

BODY
{
    Y[:, :] = X * 2.0
}
END
"""


def test_remote_get_prefetch_early_activation():
    """Rank 1 delays its taskpool registration while rank 0 completes
    PROD and ships the activation: the 32 KB payload (> short_limit)
    rides a rendezvous handle, the activation is buffered early, and
    the GET must be PREFETCHED while buffered — the replayed delivery
    then hits the prefetched payload, never issuing a second GET."""
    nb_ranks, mb = 2, 64   # 64x64 f64 = 32 KB > 4096 (rendezvous)
    A0 = np.random.RandomState(3).rand(2 * mb, mb)

    def rank_fn(rank, fabric):
        eng = RemoteDepEngine(fabric.engine(rank))
        ctx = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
        try:
            coll = TwoDimBlockCyclic(2 * mb, mb, mb, mb, P=2, Q=1,
                                     nodes=2, rank=rank, dtype=np.float64)
            coll.name = "descX"
            coll.from_numpy(A0.copy())
            tp = ptg.compile_jdf(PREFETCH_JDF, name="prefetch_jdf").new(
                descX=coll, rank=rank, nb_ranks=nb_ranks)
            if rank == 1:
                # hold registration: rank 0's activation must arrive
                # FIRST and be buffered as an early activation
                deadline = time.time() + 60
                while time.time() < deadline \
                        and not eng._early_activations:
                    eng.ce.progress()
                    time.sleep(0.001)
                assert eng._early_activations, \
                    "activation never buffered ahead of registration"
                assert eng.stats["prefetch_gets"] == 1, eng.stats
                # let the prefetched payload land before registering,
                # so the hit is the already-done flavor
                while time.time() < deadline and not any(
                        r.done for r in eng._prefetched_gets.values()):
                    eng.ce.progress()
                    time.sleep(0.001)
                assert any(r.done
                           for r in eng._prefetched_gets.values())
            ctx.add_taskpool(tp)
            ctx.wait()
            stats = dict(eng.stats)
            out = (np.asarray(coll.data_of(1, 0).sync_to_host().payload)
                   if rank == 1 else None)
            return stats, out
        finally:
            ctx.fini()

    results, _fabric = spmd(nb_ranks, rank_fn, timeout=120)
    stats1, out1 = results[1]
    assert stats1["prefetch_gets"] == 1
    assert stats1["prefetch_hits"] == 1
    assert stats1["prefetch_misses"] == 0
    assert stats1["prefetch_cancels"] == 0
    assert results[0][0]["prefetch_gets"] == 0   # rank 0 never buffered
    np.testing.assert_allclose(out1, (A0[:mb] + 1.0) * 2.0, rtol=1e-12)


def test_prefetch_budget_zero_counts_miss():
    """With comm_prefetch_inflight=0 nothing is prefetched and nothing
    is counted — the off switch restores the pre-overlap behavior."""
    nb_ranks, mb = 2, 64
    A0 = np.random.RandomState(4).rand(2 * mb, mb)

    def rank_fn(rank, fabric):
        eng = RemoteDepEngine(fabric.engine(rank))
        ctx = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
        try:
            coll = TwoDimBlockCyclic(2 * mb, mb, mb, mb, P=2, Q=1,
                                     nodes=2, rank=rank, dtype=np.float64)
            coll.name = "descX"
            coll.from_numpy(A0.copy())
            tp = ptg.compile_jdf(PREFETCH_JDF, name="prefetch_jdf").new(
                descX=coll, rank=rank, nb_ranks=nb_ranks)
            if rank == 1:
                deadline = time.time() + 60
                while time.time() < deadline \
                        and not eng._early_activations:
                    eng.ce.progress()
                    time.sleep(0.001)
                assert eng._early_activations
            ctx.add_taskpool(tp)
            ctx.wait()
            return dict(eng.stats)
        finally:
            ctx.fini()

    with params.cmdline_override("comm_prefetch_inflight", "0"):
        results, _fabric = spmd(nb_ranks, rank_fn, timeout=120)
    stats1 = results[1]
    assert stats1["prefetch_gets"] == 0
    assert stats1["prefetch_hits"] == 0
    # budget 0 = feature off: not even a miss is charged
    assert stats1["prefetch_misses"] == 0


def test_prefetch_late_reply_after_cancel_releases_budget_once():
    """A cancel (peer death / fini) racing a GET reply already sitting
    in the receive queue must release the budget slot exactly ONCE —
    a double decrement would let _plan_get_prefetch_locked admit more
    than comm_prefetch_inflight concurrent prefetches forever after."""
    from parsec_tpu.comm import LocalFabric
    from parsec_tpu.comm.remote_dep import _PrefetchedGet

    fabric = LocalFabric(2)
    eng = RemoteDepEngine(fabric.engine(1))
    captured = []
    eng._timed_get = lambda peer, handle, cb: captured.append(cb)
    key = (0, 7)
    with eng._lock:
        eng._prefetched_gets[key] = _PrefetchedGet()
        eng._prefetch_inflight += 1
    eng._issue_get_prefetch(*key)
    assert captured and eng._prefetch_inflight == 1
    eng._cancel_prefetches(0)            # the cancel releases the slot
    assert eng._prefetch_inflight == 0
    assert eng.stats["prefetch_cancels"] == 1
    captured[0](np.zeros(1))             # late reply: record is gone
    assert eng._prefetch_inflight == 0   # NOT -1


def test_prefetch_issue_failure_falls_back_to_latched_delivery():
    """If the prefetch GET fails to issue AFTER a replayed delivery
    already latched onto the record (set rec.cb, issued no GET of its
    own), the cleanup must not strand that delivery — it falls back to
    a plain GET for the latched callback instead of raising."""
    from parsec_tpu.comm import LocalFabric
    from parsec_tpu.comm.remote_dep import _PrefetchedGet

    fabric = LocalFabric(2)
    eng = RemoteDepEngine(fabric.engine(1))
    calls = []

    def timed_get(peer, handle, cb):
        calls.append(cb)
        if len(calls) == 1:
            raise RuntimeError("transport burp")

    eng._timed_get = timed_get
    key = (0, 9)
    rec = _PrefetchedGet()
    delivered = []
    rec.cb = delivered.append            # the replayed delivery's hook
    with eng._lock:
        eng._prefetched_gets[key] = rec
        eng._prefetch_inflight += 1
    eng._issue_get_prefetch(*key)        # must NOT raise: falls back
    assert len(calls) == 2 and calls[1] is rec.cb
    assert eng._prefetch_inflight == 0
    assert key not in eng._prefetched_gets
    assert eng.stats["prefetch_cancels"] == 1


# --------------------------------------------------------------------- #
# the live overlap tracker                                              #
# --------------------------------------------------------------------- #
def test_overlap_tracker_interval_algebra():
    from parsec_tpu.obs import OverlapTracker
    tr = OverlapTracker()
    # zero comm: perfect overlap by definition (gate-safe)
    assert tr.snapshot()["overlap_fraction"] == 1.0
    tr.note("compute", 0, 100_000)            # [0, 100] us
    assert tr.snapshot()["overlap_fraction"] == 1.0
    tr.note("comm", 50_000, 150_000)          # [50, 150] us: half hidden
    snap = tr.snapshot()
    assert snap["comm_us"] == pytest.approx(100.0)
    assert snap["overlap_fraction"] == pytest.approx(0.5)
    assert tr.exposed_us() == pytest.approx(50.0)
    tr.note("compute", 100_000, 150_000)      # cover the rest
    assert tr.fraction() == pytest.approx(1.0)


def test_overlap_tracker_coalesces_bounded():
    from parsec_tpu.obs import OverlapTracker
    tr = OverlapTracker()
    for i in range(3 * tr.COALESCE_AT):
        tr.note("comm", 1000 * i, 1000 * i + 500)
    assert len(tr._iv["comm"]) <= 2 * tr.COALESCE_AT
    # nothing lost to the coalescing
    assert tr.snapshot()["comm_us"] == pytest.approx(
        3 * tr.COALESCE_AT * 0.5)


# --------------------------------------------------------------------- #
# obs_report --gate-overlap (satellite)                                 #
# --------------------------------------------------------------------- #
def test_obs_report_gate_overlap(tmp_path, capsys):
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    import obs_report

    def doc(events):
        return {"traceEvents": events, "metadata": {}}

    exposed = [
        {"name": "exec:K", "ph": "X", "pid": 0, "tid": 0, "ts": 0,
         "dur": 100.0, "args": {"task": "K(0)"}},
        {"name": "comm:get", "ph": "X", "pid": 0, "tid": 9, "ts": 200.0,
         "dur": 100.0},
    ]
    p_bad = tmp_path / "bad.trace.json"
    p_bad.write_text(__import__("json").dumps(doc(exposed)))
    assert obs_report.main([str(p_bad), "--gate-overlap", "0.5"]) == 2
    # zero-comm rank reports 1.0 and passes any gate
    p_ok = tmp_path / "ok.trace.json"
    p_ok.write_text(__import__("json").dumps(doc(exposed[:1])))
    assert obs_report.main([str(p_ok), "--gate-overlap", "0.99"]) == 0
    capsys.readouterr()
