"""Python side of the C embedding API (imported by parsec_tpu_c.c).

The C shim keeps opaque PyObject handles and calls these functions; task
bodies are C function pointers invoked through ctypes with raw tile
buffer addresses (ref: the Fortran bindings delegate the same way into
the C runtime, parsec/fortran/parsecf.F90).
"""
from __future__ import annotations

import ctypes
from typing import Any, Dict, Tuple

import numpy as np

import parsec_tpu
from parsec_tpu import dtd
from parsec_tpu.dsl.dtd import INOUT, INPUT, OUTPUT, unpack_args

_MODES = {0: INPUT, 1: OUTPUT, 2: INOUT}
_BODYT = ctypes.CFUNCTYPE(None, ctypes.POINTER(ctypes.c_void_p),
                          ctypes.c_int, ctypes.c_void_p)
_bodies: Dict[Tuple[int, int], Any] = {}


def init(nb_cores: int):
    return parsec_tpu.Context(nb_cores=nb_cores if nb_cores > 0 else None)


def fini(ctx) -> None:
    ctx.fini()


def taskpool_new(ctx):
    tp = dtd.taskpool_new()
    ctx.add_taskpool(tp)
    return tp


def tile_of_dense(tp, addr: int, rows: int, cols: int):
    buf = (ctypes.c_float * (rows * cols)).from_address(addr)
    arr = np.frombuffer(buf, dtype=np.float32).reshape(rows, cols)
    return tp.tile_of_array(arr)


def _body_of(fn_addr: int, user_addr: int):
    """One DTD task class per distinct C (fn, user) pair — cached so
    repeated inserts reuse the class (ref: DTD task-class hash)."""
    key = (fn_addr, user_addr)
    body = _bodies.get(key)
    if body is None:
        cfn = _BODYT(fn_addr)
        user = ctypes.c_void_p(user_addr)

        def body(es, task):
            args = unpack_args(task)
            ptrs = (ctypes.c_void_p * len(args))(
                *[a.ctypes.data for a in args])
            cfn(ptrs, len(args), user)

        body.__name__ = f"c_body_{fn_addr:#x}"
        _bodies[key] = body
    return body


def insert_task(tp, fn_addr: int, user_addr: int, tiles, modes) -> int:
    args = [(t, _MODES[int(m)]) for t, m in zip(tiles, modes)]
    tp.insert_task(_body_of(fn_addr, user_addr), *args)
    return 0


def data_flush_all(tp) -> int:
    tp.data_flush_all()
    return 0


def taskpool_wait(tp) -> int:
    tp.wait()
    return 0


def version() -> str:
    return getattr(parsec_tpu, "__version__", "0.1")
