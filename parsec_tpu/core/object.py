"""Refcounted object base with constructor/destructor chains.

Reference behavior: PARSEC_OBJ_NEW/RETAIN/RELEASE refcounted object system
(ref: parsec/class/parsec_object.h:89-190). Python has its own GC, but the
runtime still needs *deterministic* lifetime events: data copies must be
returned to arenas, device buffers freed, repo entries recycled — at the
moment the last logical owner lets go, not when the GC runs. Obj keeps an
explicit refcount with an ``_on_destruct`` chain for that.
"""
from __future__ import annotations

import itertools
import threading


class Obj:
    """Explicitly refcounted object. obj_ref/obj_unref manage lifetime."""

    __slots__ = ("_refcount", "_lock", "__weakref__")
    _id_iter = itertools.count()

    def __init__(self) -> None:
        self._refcount = 1
        self._lock = threading.Lock()

    # PARSEC_OBJ_RETAIN
    def retain(self) -> "Obj":
        with self._lock:
            assert self._refcount > 0, "retain on destructed object"
            self._refcount += 1
        return self

    # PARSEC_OBJ_RELEASE
    def release(self) -> bool:
        """Drop one reference; run destructor chain when it hits zero.

        Returns True when the object was destructed.
        """
        with self._lock:
            assert self._refcount > 0, "release on destructed object"
            self._refcount -= 1
            dead = self._refcount == 0
        if dead:
            self._destruct()
        return dead

    @property
    def refcount(self) -> int:
        return self._refcount

    def _destruct(self) -> None:
        """Destructor chain hook; subclasses override and call super()."""
