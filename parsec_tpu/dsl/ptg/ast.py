"""JDF AST + expression compilation.

Reference behavior: the PTG compiler parses ``.jdf`` files — globals with
properties, task classes with parameter ranges, derived locals, affinity,
guarded dataflow (incl. broadcast ranges), CTL flows, per-device BODY
sections, priority expressions — into an AST (``jdf.h``) checked by ``jdf.c``
(ref: parsec/interfaces/ptg/ptg-compiler/parsec.y:1-1345, jdf.h).

TPU-native re-design: expressions are Python (the reference embeds C and
compiles it; we embed Python and ``compile()`` it once per expression —
the "inline function" analog, ref jdf2c.c:8038). C-style ``&&``, ``||``,
``!`` are transliterated so reference-style guards read naturally.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

_C2PY = [
    (re.compile(r"&&"), " and "),
    (re.compile(r"\|\|"), " or "),
    (re.compile(r"!(?![=])"), " not "),
    (re.compile(r"%\{\s*return\s+(.*?);?\s*%\}", re.S), r"(\1)"),
]


def c2py(expr: str) -> str:
    expr = expr.strip()
    for pat, rep in _C2PY:
        expr = pat.sub(rep, expr)
    return expr.strip()


class Expr:
    """One compiled expression evaluated against {globals+locals}.

    ``origin`` is the source context the parser threads through
    (``file:line task.flow``); it becomes the compile filename, so both
    compile-time SyntaxErrors and runtime tracebacks point at the JDF
    line that wrote the expression instead of a truncated ``<jdf:...>``
    tag."""

    __slots__ = ("src", "origin", "_code")

    def __init__(self, src: str, origin: Optional[str] = None) -> None:
        self.src = c2py(src)
        self.origin = origin
        try:
            self._code = compile(self.src, origin or f"<jdf:{self.src[:40]}>",
                                 "eval")
        except SyntaxError as e:
            where = f"{origin}: " if origin else ""
            raise SyntaxError(
                f"{where}bad JDF expression {src!r}: {e}") from None

    def __call__(self, env: Dict[str, Any]) -> Any:
        try:
            return eval(self._code, {"__builtins__": _SAFE_BUILTINS}, env)
        except NameError as e:
            # rewrap only when the name is missing from the expression's
            # own eval frame (tb: __call__ -> eval'd code, nothing
            # deeper); a NameError raised inside a helper the expression
            # calls keeps its real traceback pointing at the helper
            tb = e.__traceback__
            if self.origin is None or tb is None or tb.tb_next is None \
                    or tb.tb_next.tb_next is not None:
                raise
            raise NameError(f"{self.origin}: {e} in {self.src!r}") from None

    def __repr__(self) -> str:  # pragma: no cover
        return f"Expr({self.src!r})"


_SAFE_BUILTINS = {
    "abs": abs, "min": min, "max": max, "int": int, "float": float,
    "range": range, "len": len, "divmod": divmod, "round": round,
    "True": True, "False": False, "None": None,
}


def split_top(s: str, sep: str) -> List[str]:
    """Split on sep at paren/bracket depth 0."""
    parts, depth, cur, i = [], 0, [], 0
    n, ls = len(s), len(sep)
    while i < n:
        ch = s[i]
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        if depth == 0 and s.startswith(sep, i):
            parts.append("".join(cur))
            cur = []
            i += ls
            continue
        cur.append(ch)
        i += 1
    parts.append("".join(cur))
    return parts


@dataclass
class RangeExpr:
    """``lo .. hi [.. step]`` — inclusive, like JDF ranges."""
    lo: Expr
    hi: Expr
    step: Optional[Expr] = None

    def values(self, env: Dict[str, Any]):
        lo, hi = self.lo(env), self.hi(env)
        st = self.step(env) if self.step is not None else 1
        return range(lo, hi + (1 if st > 0 else -1), st)

    @staticmethod
    def parse(src: str, origin: Optional[str] = None) -> "RangeExpr | Expr":
        parts = split_top(src, "..")
        if len(parts) == 1:
            return Expr(src, origin)
        if len(parts) == 2:
            return RangeExpr(Expr(parts[0], origin), Expr(parts[1], origin))
        if len(parts) == 3:
            return RangeExpr(Expr(parts[0], origin), Expr(parts[1], origin),
                             Expr(parts[2], origin))
        raise SyntaxError(f"{origin + ': ' if origin else ''}bad range: {src!r}")


@dataclass
class GlobalDef:
    name: str
    properties: Dict[str, str] = field(default_factory=dict)

    @property
    def hidden(self) -> bool:
        return self.properties.get("hidden", "").lower() in ("on", "true", "1")

    @property
    def default(self) -> Optional[Expr]:
        d = self.properties.get("default")
        return Expr(d) if d is not None else None


@dataclass
class LocalDef:
    """``k = 0 .. NB [.. step]`` (a parameter range) or ``loc = expr``
    (a derived local)."""
    name: str
    range: Optional[RangeExpr]    # None for derived locals
    expr: Optional[Expr] = None   # set for derived locals


@dataclass
class DepTarget:
    """Where a dependency edge points."""
    kind: str                     # "task" | "memory" | "new" | "null"
    collection: Optional[str] = None     # memory: global name of collection
    task_class: Optional[str] = None     # task: peer class name
    flow: Optional[str] = None           # task: peer flow name
    args: List[Any] = field(default_factory=list)  # Expr | RangeExpr


@dataclass
class DepAST:
    """``[guard ?] target [: alt_target]`` with optional [type=...] props."""
    direction: str                # "in" | "out"
    guard: Optional[Expr]
    target: DepTarget
    alt_target: Optional[DepTarget] = None
    properties: Dict[str, str] = field(default_factory=dict)

    def resolve(self, env: Dict[str, Any]) -> Optional[DepTarget]:
        """Pick the applicable target for this instance (None == no edge)."""
        if self.guard is None:
            return self.target
        if self.guard(env):
            return self.target
        return self.alt_target  # may be None: guarded single-target dep


@dataclass
class FlowAST:
    name: str
    access: str                   # "RW" | "READ" | "WRITE" | "CTL"
    deps: List[DepAST] = field(default_factory=list)

    @property
    def is_ctl(self) -> bool:
        return self.access == "CTL"

    def deps_in(self) -> List[DepAST]:
        return [d for d in self.deps if d.direction == "in"]

    def deps_out(self) -> List[DepAST]:
        return [d for d in self.deps if d.direction == "out"]


@dataclass
class BodyAST:
    code: str
    properties: Dict[str, str] = field(default_factory=dict)
    # compiled lazily by the runtime
    _compiled: Any = None
    # 1-based source line of the BODY keyword (0 = unknown): threaded by
    # the parser so body lints report real spec lines
    line: int = 0

    @property
    def device_type(self) -> str:
        return self.properties.get("type", "cpu").lower()


@dataclass
class TaskClassAST:
    name: str
    params: List[str]
    properties: Dict[str, str] = field(default_factory=dict)
    locals: List[LocalDef] = field(default_factory=list)
    affinity_collection: Optional[str] = None
    affinity_args: List[Expr] = field(default_factory=list)
    flows: List[FlowAST] = field(default_factory=list)
    priority: Optional[Expr] = None
    bodies: List[BodyAST] = field(default_factory=list)

    def locals_from_param_args(self, arg_values) -> tuple:
        """Translate positional dep-target args (which follow this class's
        PARAM list, e.g. ``P RPANEL( m, k )``) into the locals tuple
        (range definitions in declaration order). The two orders can
        differ; producer-driven activation never notices, but any
        consumer-side instance lookup must translate."""
        arg_values = tuple(arg_values)
        if len(self.params) != len(arg_values):
            return arg_values
        by_name = dict(zip(self.params, arg_values))
        out = []
        for ld in self.locals:
            if ld.range is None:
                continue
            if ld.name not in by_name:
                return arg_values  # non-param range local: keep positional
            out.append(by_name[ld.name])
        return tuple(out)

    def flow_by_name(self, name: str) -> FlowAST:
        for f in self.flows:
            if f.name == name:
                return f
        raise KeyError(f"{self.name}: no flow named {name}")


@dataclass
class JDFFile:
    name: str
    prologue: List[str] = field(default_factory=list)   # python code blocks
    epilogue: List[str] = field(default_factory=list)
    globals: List[GlobalDef] = field(default_factory=list)
    task_classes: List[TaskClassAST] = field(default_factory=list)

    def task_class_by_name(self, name: str) -> TaskClassAST:
        for tc in self.task_classes:
            if tc.name == name:
                return tc
        raise KeyError(f"no task class {name} in {self.name}")


def parse_properties(src: str) -> Dict[str, str]:
    """``[ key=value key2="value" ]`` property lists."""
    props: Dict[str, str] = {}
    src = src.strip()
    if src.startswith("["):
        src = src[1:]
    if src.endswith("]"):
        src = src[:-1]
    for m in re.finditer(r'(\w+)\s*=\s*("([^"]*)"|\S+)', src):
        key = m.group(1)
        val = m.group(3) if m.group(3) is not None else m.group(2)
        props[key] = val
    return props
