"""Collection checkpoint/resume.

The reference has NO runtime-level checkpointing (SURVEY.md §5.4 —
"absent"; apps re-run from user data, with parsec_dtd_data_flush as the
only return-data-to-home building block). This module is the TPU-native
answer the survey calls for: since all application state lives in data
collections (tiles), a checkpoint is a consistent snapshot of a
collection's local tiles taken between taskpools (when no DAG is in
flight), and resume rebuilds the collection tile-by-tile. SPMD: each
rank writes only the tiles it owns; a restore on R ranks reads each
rank's own shard file set.

Format: one ``.npz`` per (collection, rank) holding tile arrays keyed
``t<m>_<n>`` plus a JSON-encoded manifest (geometry, dtype, distribution
parameters) used to validate compatibility at restore time.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import numpy as np


class CheckpointMismatchError(ValueError):
    """The snapshot's manifest does not match the restoring collection
    (different geometry, rank count, or process grid). Raised BEFORE
    any tile is loaded: a rank file holds only the tiles the saving
    rank owned under ITS distribution, so restoring under a different
    grid would silently leave foreign tiles empty / place tiles on the
    wrong ranks."""


def _manifest_of(coll: Any) -> Dict[str, Any]:
    man = {"lm": coll.lm, "ln": coll.ln, "mb": coll.mb, "nb": coll.nb,
           "dtype": np.dtype(coll.dtype).name,
           "kind": type(coll).__name__,
           # distribution identity: the shard set is only meaningful on
           # the identical rank count / process grid it was written with
           "nodes": getattr(coll, "nodes", 1),
           "rank": getattr(coll, "rank", 0)}
    for attr in ("P", "Q", "krows", "kcols", "uplo"):
        if hasattr(coll, attr):
            man[attr] = getattr(coll, attr)
    return man


def _grid_str(man: Dict[str, Any]) -> str:
    grid = ""
    if "P" in man and "Q" in man:
        grid = f", grid {man['P']}x{man['Q']}"
    return f"{man.get('nodes', '?')} rank(s){grid}"


def checkpoint_path(prefix: str, rank: int) -> str:
    return f"{prefix}.rank{rank}.npz"


def save_collection(coll: Any, prefix: str, context: Optional[Any] = None) -> str:
    """Write this rank's local tiles. Call between taskpools (quiescent
    point); device-resident newest copies are pulled back first."""
    tiles: Dict[str, Any] = {}
    for (m, n) in coll.local_tiles():
        copy = coll.data_of(m, n).sync_to_host(
            context.devices if context is not None else None)
        if copy.payload is not None:
            tiles[f"t{m}_{n}"] = np.asarray(copy.payload)
    path = checkpoint_path(prefix, coll.rank)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.savez(path, __manifest__=json.dumps(_manifest_of(coll)), **tiles)
    return path


def restore_collection(coll: Any, prefix: str) -> int:
    """Load this rank's tiles back into ``coll``; returns #tiles restored.
    Geometry must match the manifest (same tiling and dtype)."""
    path = checkpoint_path(prefix, coll.rank)
    with np.load(path, allow_pickle=False) as z:
        man = json.loads(str(z["__manifest__"]))
        ours = _manifest_of(coll)
        # geometry AND distribution must match: a rank file holds only
        # the tiles the saving rank owned, so restoring under a
        # different kind/grid/rank-count would silently leave foreign
        # tiles empty or place tiles on the wrong ranks. Collect EVERY
        # mismatch (one clear error beats a fix-one-rerun loop).
        # "nodes"/"rank" are absent from pre-ft manifests: only compared
        # when the snapshot recorded them.
        keys = ["lm", "ln", "mb", "nb", "dtype", "kind", "P", "Q",
                "krows", "kcols", "uplo"]
        keys += [k for k in ("nodes", "rank") if k in man]
        bad = [f"{k}: snapshot {man.get(k)!r} != ours {ours.get(k)!r}"
               for k in keys if man.get(k) != ours.get(k)]
        if bad:
            raise CheckpointMismatchError(
                f"checkpoint {path} is incompatible with the restoring "
                f"collection ({'; '.join(bad)}). The snapshot was "
                f"written on {_grid_str(man)}; this collection spans "
                f"{_grid_str(ours)} — restore requires the identical "
                f"tiling, dtype, rank count, and process grid.")
        n = 0
        for name in z.files:
            if not name.startswith("t"):
                continue
            m_, n_ = (int(x) for x in name[1:].split("_"))
            coll.set_tile(m_, n_, z[name])
            n += 1
    return n


def arrays_path(prefix: str, rank: int) -> str:
    """Namespaced separately from collection shards so the two can share
    one prefix without clobbering each other."""
    return f"{prefix}.arrays.rank{rank}.npz"


def save_arrays(prefix: str, rank: int = 0, **arrays: Any) -> str:
    """Checkpoint loose named arrays (e.g. model/optimizer state from
    parallel/ training) alongside collections."""
    path = arrays_path(prefix, rank)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.savez(path, **{k: np.asarray(v) for k, v in arrays.items()})
    return path


def load_arrays(prefix: str, rank: int = 0) -> Dict[str, np.ndarray]:
    with np.load(arrays_path(prefix, rank), allow_pickle=False) as z:
        return {k: z[k] for k in z.files}
