"""Redistribution engine tests (ref coverage model:
tests/collections/redistribute/ — PTG redistribution with checking
variants incl. random sizes, SURVEY.md §4).
"""
import numpy as np
import pytest

import parsec_tpu
from parsec_tpu.collections import (TwoDimBlockCyclic, TwoDimTabular,
                                    redistribute, reshard_array)
from parsec_tpu.comm import RemoteDepEngine

from test_comm_multirank import spmd


def _check(source_np, target_np_before, target_after,
           size_row, size_col, diY, djY, diT, djT):
    expect = target_np_before.copy()
    expect[diT:diT + size_row, djT:djT + size_col] = \
        source_np[diY:diY + size_row, djY:djY + size_col]
    np.testing.assert_array_equal(target_after, expect)


@pytest.mark.parametrize("geometry", [
    # (lmY, lnY, mbY, nbY, lmT, lnT, mbT, nbT, M, N, diY, djY, diT, djT)
    (8, 8, 4, 4, 8, 8, 4, 4, 8, 8, 0, 0, 0, 0),        # aligned same-tile
    (12, 12, 4, 4, 12, 12, 3, 3, 12, 12, 0, 0, 0, 0),  # different tile sizes
    (16, 12, 5, 4, 12, 16, 3, 5, 7, 9, 2, 1, 3, 4),    # unaligned submatrix
])
def test_redistribute_single_process(ctx, geometry):
    (lmY, lnY, mbY, nbY, lmT, lnT, mbT, nbT,
     M, N, diY, djY, diT, djT) = geometry
    rng = np.random.RandomState(42)
    src_np = rng.rand(lmY, lnY)
    tgt_np = rng.rand(lmT, lnT)
    Y = TwoDimBlockCyclic(lmY, lnY, mbY, nbY, dtype=np.float64).from_numpy(src_np)
    T = TwoDimBlockCyclic(lmT, lnT, mbT, nbT, dtype=np.float64).from_numpy(tgt_np)
    redistribute(Y, T, M, N, diY, djY, diT, djT, context=ctx)
    _check(src_np, tgt_np, T.to_numpy(), M, N, diY, djY, diT, djT)


def test_redistribute_random_sizes(ctx):
    rng = np.random.RandomState(7)
    for trial in range(4):
        lmY, lnY = rng.randint(6, 20, size=2)
        lmT, lnT = rng.randint(6, 20, size=2)
        mbY, nbY = rng.randint(2, 6, size=2)
        mbT, nbT = rng.randint(2, 6, size=2)
        M = rng.randint(1, min(lmY, lmT) + 1)
        N = rng.randint(1, min(lnY, lnT) + 1)
        diY = rng.randint(0, lmY - M + 1)
        djY = rng.randint(0, lnY - N + 1)
        diT = rng.randint(0, lmT - M + 1)
        djT = rng.randint(0, lnT - N + 1)
        src_np = rng.rand(lmY, lnY)
        tgt_np = rng.rand(lmT, lnT)
        Y = TwoDimBlockCyclic(int(lmY), int(lnY), int(mbY), int(nbY),
                              dtype=np.float64).from_numpy(src_np)
        T = TwoDimBlockCyclic(int(lmT), int(lnT), int(mbT), int(nbT),
                              dtype=np.float64).from_numpy(tgt_np)
        redistribute(Y, T, int(M), int(N), int(diY), int(djY),
                     int(diT), int(djT), context=ctx)
        _check(src_np, tgt_np, T.to_numpy(), M, N, diY, djY, diT, djT)


@pytest.mark.parametrize("nb_ranks", [2, 4])
def test_redistribute_multirank(nb_ranks):
    """Block-cyclic P×1 source -> 1×Q target with different tile sizes:
    most fragments cross ranks."""
    lm = ln = 12
    rng = np.random.RandomState(3)
    src_np = rng.rand(lm, ln)
    tgt_np = rng.rand(lm, ln)

    def rank_fn(rank, fabric):
        eng = RemoteDepEngine(fabric.engine(rank))
        ctx = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
        try:
            Y = TwoDimBlockCyclic(lm, ln, 4, 4, P=nb_ranks, Q=1,
                                  nodes=nb_ranks, rank=rank,
                                  dtype=np.float64).from_numpy(src_np)
            T = TwoDimBlockCyclic(lm, ln, 3, 3, P=1, Q=nb_ranks,
                                  nodes=nb_ranks, rank=rank,
                                  dtype=np.float64).from_numpy(tgt_np)
            redistribute(Y, T, 10, 10, disi_Y=1, disj_Y=2,
                         disi_T=2, disj_T=1, context=ctx)
            # collect this rank's local target tiles
            out = {}
            for (m, n) in T.local_tiles():
                out[(m, n)] = np.array(T.tile(m, n))
            return out
        finally:
            ctx.fini()

    results, _ = spmd(nb_ranks, rank_fn)
    # assemble the distributed result
    expect = tgt_np.copy()
    expect[2:12, 1:11] = src_np[1:11, 2:12]
    got = np.zeros_like(expect)
    T_geom = TwoDimBlockCyclic(lm, ln, 3, 3, P=1, Q=nb_ranks, nodes=nb_ranks)
    for r, tiles in enumerate(results):
        for (m, n), arr in tiles.items():
            tm, tn = T_geom.tile_shape(m, n)
            got[m * 3:m * 3 + tm, n * 3:n * 3 + tn] = arr
    np.testing.assert_array_equal(got, expect)


def test_redistribute_tabular_target(ctx):
    """Irregular per-tile rank table target (single process)."""
    lm = ln = 10
    rng = np.random.RandomState(11)
    src_np = rng.rand(lm, ln)
    Y = TwoDimBlockCyclic(lm, ln, 3, 3, dtype=np.float64).from_numpy(src_np)
    T = TwoDimTabular.random(lm, ln, 4, 4, nodes=1, dtype=np.float64)
    tgt_np = np.zeros((lm, ln))
    T.from_numpy(tgt_np)
    redistribute(Y, T, lm, ln, context=ctx)
    np.testing.assert_array_equal(T.to_numpy(), src_np)


def test_reshard_array_roundtrip():
    import jax
    from jax.sharding import PartitionSpec as P
    from parsec_tpu.parallel import make_mesh
    mesh = make_mesh(sizes={"dp": 2, "tp": 2},
                     devices=jax.devices("cpu")[:4])
    x = np.arange(64, dtype=np.float32).reshape(8, 8)
    a = reshard_array(jax.numpy.asarray(x), mesh, P("dp", "tp"))
    b = reshard_array(a, mesh, P("tp", "dp"))
    c = reshard_array(b, mesh, P())
    np.testing.assert_array_equal(np.asarray(c), x)


# --------------------------------------------------------------------- #
# optimized reshuffle path (ref: the dedicated reshuffle JDF selected   #
# by redistribute_wrapper.c:185 when grids align)                       #
# --------------------------------------------------------------------- #
def test_reshuffle_fast_path_equivalence(ctx):
    """Aligned same-grid case: the reshuffle path (1 whole-tile task per
    tile) must produce exactly what the general fragment path does, with
    fewer tasks."""
    rng = np.random.RandomState(3)
    lm, nb = 48, 8
    src_np = rng.rand(lm, lm)
    Y = TwoDimBlockCyclic(lm, lm, nb, nb, dtype=np.float64).from_numpy(src_np)
    Y.name = "rsY"
    T1 = TwoDimBlockCyclic(lm, lm, nb, nb, dtype=np.float64).from_numpy(
        np.zeros((lm, lm)))
    T1.name = "rsT1"
    T2 = TwoDimBlockCyclic(lm, lm, nb, nb, dtype=np.float64).from_numpy(
        np.zeros((lm, lm)))
    T2.name = "rsT2"
    M = N = 32
    tp1 = redistribute(Y, T1, M, N, disi_Y=8, disj_Y=0, disi_T=16,
                       disj_T=8, context=ctx)                 # reshuffle
    tp2 = redistribute(Y, T2, M, N, disi_Y=8, disj_Y=0, disi_T=16,
                       disj_T=8, context=ctx, allow_reshuffle=False)
    expect = np.zeros((lm, lm))
    expect[16:16 + M, 8:8 + N] = src_np[8:8 + M, 0:N]
    np.testing.assert_array_equal(T1.to_numpy(), expect)
    np.testing.assert_array_equal(T2.to_numpy(), expect)
    # the aligned case degenerates to one whole-tile task per target
    # tile on BOTH paths (the general enumerator already collapses);
    # equal task counts, identical results — the reshuffle path's value
    # is the guaranteed 1:1 permutation structure the PTG variant builds
    # on (see redistribute.py docstring for the measured comparison)
    assert tp1._inserted == tp2._inserted


def test_reshuffle_not_applied_when_unaligned(ctx):
    rng = np.random.RandomState(4)
    lm, nb = 32, 8
    src_np = rng.rand(lm, lm)
    Y = TwoDimBlockCyclic(lm, lm, nb, nb, dtype=np.float64).from_numpy(src_np)
    Y.name = "ruY"
    T = TwoDimBlockCyclic(lm, lm, nb, nb, dtype=np.float64).from_numpy(
        np.zeros((lm, lm)))
    T.name = "ruT"
    redistribute(Y, T, 16, 16, disi_Y=3, disj_Y=5, disi_T=1, disj_T=2,
                 context=ctx)   # unaligned: general fragment path
    expect = np.zeros((lm, lm))
    expect[1:17, 2:18] = src_np[3:19, 5:21]
    np.testing.assert_array_equal(T.to_numpy(), expect)


def test_redistribute_ptg_single_rank(ctx):
    from parsec_tpu.collections import redistribute_ptg
    rng = np.random.RandomState(5)
    lm, nb = 40, 8
    src_np = rng.rand(lm, lm)
    Y = TwoDimBlockCyclic(lm, lm, nb, nb, dtype=np.float64).from_numpy(src_np)
    T = TwoDimBlockCyclic(lm, lm, nb, nb, dtype=np.float64).from_numpy(
        np.zeros((lm, lm)))
    tp = redistribute_ptg(Y, T, 24, 24, disi_Y=8, disj_Y=8,
                          disi_T=16, disj_T=0)
    ctx.add_taskpool(tp)
    ctx.wait()
    expect = np.zeros((lm, lm))
    expect[16:40, 0:24] = src_np[8:32, 8:32]
    np.testing.assert_array_equal(T.to_numpy(), expect)


@pytest.mark.parametrize("nb_ranks", [2])
def test_redistribute_ptg_multirank(nb_ranks):
    from parsec_tpu.collections import redistribute_ptg
    rng = np.random.RandomState(6)
    lm, nb = 32, 8
    src_np = rng.rand(lm, lm)
    results = [None] * nb_ranks

    def rank_fn(rank, fabric):
        eng = RemoteDepEngine(fabric.engine(rank))
        ctx2 = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
        try:
            Y = TwoDimBlockCyclic(lm, lm, nb, nb, P=nb_ranks, Q=1,
                                  nodes=nb_ranks, rank=rank,
                                  dtype=np.float64).from_numpy(src_np)
            Y.name = "pY"
            T = TwoDimBlockCyclic(lm, lm, nb, nb, P=1, Q=nb_ranks,
                                  nodes=nb_ranks, rank=rank,
                                  dtype=np.float64).from_numpy(
                np.zeros((lm, lm)))
            T.name = "pT"
            tp = redistribute_ptg(Y, T, 16, 16, disi_Y=0, disj_Y=8,
                                  disi_T=8, disj_T=0,
                                  rank=rank, nb_ranks=nb_ranks)
            ctx2.add_taskpool(tp)
            ctx2.wait()
            results[rank] = {c: np.array(
                T.data_of(*c).host_copy().payload)
                for c in T.tiles() if T.rank_of(*c) == rank}
        finally:
            ctx2.fini()

    spmd(nb_ranks, rank_fn)
    expect = np.zeros((lm, lm))
    expect[8:24, 0:16] = src_np[0:16, 8:24]
    nt = lm // nb
    for m in range(nt):
        for n in range(nt):
            owner = n % nb_ranks   # P=1, Q=nb_ranks target
            got = results[owner][(m, n)]
            np.testing.assert_array_equal(
                got, expect[m * nb:(m + 1) * nb, n * nb:(n + 1) * nb],
                err_msg=f"tile ({m},{n})")
