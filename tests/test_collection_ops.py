"""Collection-wide operations (apply / map_operator / tree reductions /
broadcast / diag_band_to_rect) — numerics vs numpy, including
non-power-of-two tile grids (the reference's reduce JDFs are tested at
power-of-two extents only; ours must pass both).

Reference analogs: parsec/data_dist/matrix/{apply,reduce,reduce_col,
reduce_row,broadcast,diag_band_to_rect}.jdf, map_operator.c;
tests/collections/reduce.
"""
import numpy as np
import pytest

from parsec_tpu.collections import TwoDimBlockCyclic
from parsec_tpu.collections import ops as cops

TILE = 4


def _mk(mt, nt, seed=0):
    rng = np.random.RandomState(seed)
    M = rng.rand(mt * TILE, nt * TILE).astype(np.float32)
    A = TwoDimBlockCyclic(mt * TILE, nt * TILE, TILE, TILE).from_numpy(M)
    return M, A


def _add(a, b, _args):
    return a + b


def test_apply_full(ctx):
    M, A = _mk(3, 3)
    cops.apply(ctx, A, lambda t, region, m, n, args: t * 2.0)
    np.testing.assert_allclose(A.to_numpy(), M * 2.0, rtol=1e-6)


def test_apply_lower(ctx):
    M, A = _mk(3, 3, seed=1)
    cops.apply(ctx, A, lambda t, region, m, n, args: t + 1.0, uplo="lower")
    got = A.to_numpy()
    exp = M.copy()
    for m in range(3):
        for n in range(3):
            if n <= m:
                exp[m * TILE:(m + 1) * TILE, n * TILE:(n + 1) * TILE] += 1.0
    np.testing.assert_allclose(got, exp, rtol=1e-6)


def test_apply_upper_region_arg(ctx):
    """The diagonal task receives region=uplo so ops can mask."""
    seen = []

    def op(t, region, m, n, args):
        seen.append((region, m, n))
        return t

    _, A = _mk(2, 2, seed=2)
    cops.apply(ctx, A, op, uplo="upper")
    regions = {s[0] for s in seen if s[1] == s[2]}
    assert regions == {"upper"}
    assert ("full", 0, 1) in seen
    assert all(not (m > n) for (_, m, n) in seen)


def test_map_operator(ctx):
    Ms, S = _mk(2, 3, seed=3)
    Md, D = _mk(2, 3, seed=4)
    cops.map_operator(ctx, S, D, lambda s, d, m, n, args: s * d + m + n)
    exp = np.empty_like(Md)
    for m in range(2):
        for n in range(3):
            sl = np.s_[m * TILE:(m + 1) * TILE, n * TILE:(n + 1) * TILE]
            exp[sl] = Ms[sl] * Md[sl] + m + n
    np.testing.assert_allclose(D.to_numpy(), exp, rtol=1e-6)


@pytest.mark.parametrize("mt", [1, 2, 3, 5, 8])
def test_reduce_col(ctx, mt):
    M, A = _mk(mt, 2, seed=mt)
    dest = cops.reduce_col(ctx, A, _add)
    exp = sum(M[m * TILE:(m + 1) * TILE] for m in range(mt))
    np.testing.assert_allclose(dest.to_numpy(), exp, rtol=1e-5)
    # source untouched by the reduction
    np.testing.assert_allclose(A.to_numpy(), M, rtol=0)


@pytest.mark.parametrize("nt", [1, 3, 4, 7])
def test_reduce_row(ctx, nt):
    M, A = _mk(2, nt, seed=10 + nt)
    dest = cops.reduce_row(ctx, A, _add)
    exp = sum(M[:, n * TILE:(n + 1) * TILE] for n in range(nt))
    np.testing.assert_allclose(dest.to_numpy(), exp, rtol=1e-5)


@pytest.mark.parametrize("mt,nt", [(1, 1), (2, 2), (3, 5)])
def test_reduce_all(ctx, mt, nt):
    M, A = _mk(mt, nt, seed=20 + mt + nt)
    dest = cops.reduce_all(ctx, A, _add)
    exp = np.zeros((TILE, TILE), dtype=np.float32)
    for m in range(mt):
        for n in range(nt):
            exp += M[m * TILE:(m + 1) * TILE, n * TILE:(n + 1) * TILE]
    np.testing.assert_allclose(dest.to_numpy(), exp, rtol=1e-5)


def test_reduce_max_op(ctx):
    """Non-additive fold: elementwise max."""
    M, A = _mk(5, 1, seed=42)
    dest = cops.reduce_col(ctx, A, lambda a, b, _: np.maximum(a, b))
    exp = np.max(M.reshape(5, TILE, TILE), axis=0)
    np.testing.assert_allclose(dest.to_numpy(), exp, rtol=0)


def test_broadcast(ctx):
    Ms, S = _mk(2, 2, seed=7)
    _, D = _mk(3, 3, seed=8)
    cops.broadcast(ctx, S, D, root=(1, 0))
    root = Ms[TILE:2 * TILE, 0:TILE]
    got = D.to_numpy()
    for m in range(3):
        for n in range(3):
            np.testing.assert_allclose(
                got[m * TILE:(m + 1) * TILE, n * TILE:(n + 1) * TILE], root,
                rtol=0)


def test_band_to_rect(ctx):
    M, A = _mk(4, 4, seed=9)
    rect = TwoDimBlockCyclic(2 * TILE, 4 * TILE, TILE, TILE)
    tp = cops.band_to_rect_taskpool(A, rect)
    ctx.add_taskpool(tp)
    ctx.wait()
    got = rect.to_numpy()
    for k in range(4):
        sl = np.s_[k * TILE:(k + 1) * TILE]
        np.testing.assert_allclose(got[0:TILE, sl],
                                   M[sl, k * TILE:(k + 1) * TILE], rtol=0)
        if k >= 1:
            np.testing.assert_allclose(
                got[TILE:2 * TILE, sl],
                M[(k - 1) * TILE:k * TILE, k * TILE:(k + 1) * TILE], rtol=0)


def test_allreduce_in_place(ctx):
    """reduce+broadcast composition: every tile ends with the global fold
    (the reference's DTD allreduce pattern as one compound taskpool)."""
    import numpy as np
    from parsec_tpu.collections import TwoDimBlockCyclic
    from parsec_tpu.collections.ops import allreduce

    rng = np.random.RandomState(5)
    M = rng.rand(6 * 4, 4 * 4).astype(np.float32)
    A = TwoDimBlockCyclic(6 * 4, 4 * 4, 4, 4, dtype=np.float32).from_numpy(M)
    allreduce(ctx, A, lambda a, b, args: np.maximum(a, b))
    # per-tile elementwise max across all 24 tiles
    ref = M.reshape(6, 4, 4, 4).transpose(0, 2, 1, 3).reshape(24, 4, 4)
    expect = np.maximum.reduce(ref)
    out = A.to_numpy()
    for i in range(6):
        for j in range(4):
            np.testing.assert_allclose(out[i*4:(i+1)*4, j*4:(j+1)*4], expect)
