"""Distributed wave execution: one lowered PTG DAG over many ranks.

The reference dispatches distributed tasks from a ~us C progress loop,
overlapping per-task sends with compute (parsec/scheduling.c:586-625 +
remote_dep_mpi.c). A Python per-task loop cannot reach that rate, and on
TPU the idiomatic answer is different anyway: batch compute onto the MXU
(wave.py) and batch communication into a few bulk exchanges per wave.
This module is the multi-rank half of that answer — the two properties
the round-2 review found living in different engines (wave throughput,
distribution) in ONE engine:

- every rank lowers the same JDF to the same full DAG (SPMD, like the
  reference: each rank evaluates the PTG locally, README.rst:23-27) and
  walks the same wave schedule = dependence levels of the DAG;
- each rank executes only the tasks its data distribution maps to it
  (owner-computes over ``rank_of`` affinity), as batched chunk kernels
  over its local device tile pools;
- the communication schedule is computed STATICALLY at build time: for
  every tile interval between two writes, any reader on another rank
  gets the tile pushed right after the wave that wrote it, deduped per
  (wave, src, dst); pre-exchange (wave 0) ships home tiles to remote
  first readers, and final writes ship back to the tile's home rank.
  Both ends derive the identical schedule from the identical DAG, so no
  control messages, tags negotiation, or rendezvous are needed at all —
  the data messages themselves are the entire protocol;
- cross-rank write-after-read needs no handling: a remote write only
  reaches this rank's staged copy of the tile in the post-wave
  exchange, which runs after local execution — the reader batched in
  the same wave saw the old value, exactly WAR semantics. (Local
  same-wave WAR is layered by WaveRunner._split_war as before; two
  same-wave writers of one tile are rejected statically — racy DAG.)

Memory model: pools are SLICED — each rank stages only the tiles its
tasks touch plus its transfer endpoints (the halo), O(local tiles)
HBM instead of O(matrix) per rank. The exchange schedule speaks global
tile indices on the wire; gathers/scatters translate them to local
pool rows (``_g2l``). Owned tiles no local task touches are never
staged and their home copies stand.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from ...comm.engine import TAG_USER_BASE
from ...comm.remote_dep import bcast_children
from ...data.datatype import Datatype
from ...utils import logging as plog
from .wave import WaveError, WaveRunner

__all__ = ["TAG_WAVE", "DistWaveRunner", "rank_mesh_sharding"]

TAG_WAVE = TAG_USER_BASE - 4
TAG_WAVE_CFG = TAG_USER_BASE - 5
_LANE_RDV_LOCK = threading.Lock()


def _ensure_cfg_inbox(ce):
    """Per-CE store for lane-config digests ((src, seq) -> digest)."""
    ent = getattr(ce, "_wave_cfg_inbox", None)
    if ent is None:
        cv = threading.Condition()
        vals: Dict[Tuple[int, int], str] = {}
        ent = ce._wave_cfg_inbox = (vals, cv)
        ce._wave_cfg_seq = 0

        def _on_cfg(src: int, msg: Dict) -> None:
            with cv:
                vals[(src, msg["seq"])] = msg["digest"]
                cv.notify_all()

        ce.tag_register(TAG_WAVE_CFG, _on_cfg)
    return ent


def check_lane_schedule_uniformity(ce, digest: str,
                                   timeout: float = 30.0) -> None:
    """All-exchange a hash of the lane-scheduling params and fail fast
    on divergence (ADVICE r5): multiproc lane schedules are a pure
    function of (``wave_dist_collective``, ``wave_dist_collective_min_pct``)
    — if any process resolves them differently it skips a global
    all-reduce the others block in, a distributed hang until timeout.
    A digest mismatch (or a peer that never answers because its params
    routed it elsewhere) raises WaveError at runner setup instead."""
    if ce.nb_ranks < 2:
        return
    vals, cv = _ensure_cfg_inbox(ce)
    with cv:   # seq per exchange: runners are constructed SPMD, so the
        seq = ce._wave_cfg_seq   # n-th exchange pairs up on every rank
        ce._wave_cfg_seq = seq + 1
    for r in range(ce.nb_ranks):
        if r != ce.rank:
            ce.send_am(r, TAG_WAVE_CFG, {"seq": seq, "digest": digest})
    deadline = time.monotonic() + timeout
    for r in range(ce.nb_ranks):
        if r == ce.rank:
            continue
        while True:
            with cv:
                got = vals.get((r, seq))
            if got is not None:
                break
            if time.monotonic() > deadline:
                raise WaveError(
                    f"rank {ce.rank}: no lane-schedule config from rank "
                    f"{r} within {timeout}s — wave_dist_collective / "
                    f"wave_dist_collective_min_pct likely diverge "
                    f"across processes (they must be identical "
                    f"everywhere)")
            ce.progress()
            with cv:
                cv.wait(0.0005)
        if got != digest:
            raise WaveError(
                f"rank {ce.rank}: lane-schedule params diverge from "
                f"rank {r} (hash {got!r} != {digest!r}): "
                f"wave_dist_collective and wave_dist_collective_min_pct "
                f"must be identical on every process")
    with cv:
        for r in range(ce.nb_ranks):
            vals.pop((r, seq), None)


def _ensure_wave_inbox(ce):
    """Per-CE shared inbox for wave-exchange messages. One handler per
    CE regardless of how many runners/pools exist; keys carry the pool
    name + run epoch so concurrent or back-to-back runs can't alias.
    Messages for an epoch older than the pool's current one are dropped
    on arrival (their run already finished or failed). Park-release
    acks (device-plane payload hop) ride the same tag."""
    cv = getattr(ce, "_wave_inbox_cv", None)
    if cv is None:
        ce._wave_inbox = {}
        ce._wave_epochs = getattr(ce, "_wave_epochs", {})
        ce._wave_parks = set()
        cv = ce._wave_inbox_cv = threading.Condition()

        def _on_msg(src: int, msg: Dict) -> None:
            if "ack_uuids" in msg:
                plane = getattr(ce, "device_plane", None)
                for u in msg["ack_uuids"]:
                    if plane is not None:
                        plane.release(u)
                with cv:
                    for u in msg["ack_uuids"]:
                        ce._wave_parks.discard(u)
                    cv.notify_all()
                return
            key = (msg["pool"], msg["epoch"], src, msg["wave"],
                   msg.get("gen", 0))
            with cv:
                if msg["epoch"] < ce._wave_epochs.get(msg["pool"], 0):
                    return   # stale epoch: its run is over
                ce._wave_inbox[key] = msg
                cv.notify_all()

        ce.tag_register(TAG_WAVE, _on_msg)
    return ce._wave_inbox, cv


def _is_single_device(arr) -> bool:
    try:
        return len(arr.devices()) == 1
    except Exception:  # numpy or committed-less tracer output
        return False


def _lane_local_devices(nb_ranks: int):
    """Device pool for the in-process lane: the default platform's local
    devices when it can seat one per rank, else the virtual CPU mesh.
    An accelerator plugin that force-prepends itself (the tunnel's axon
    platform exposes ONE chip) must not hide the 8-device CPU substrate
    the SPMD tests and the driver's dryrun run on."""
    import jax

    devs = jax.local_devices()
    if len(devs) < nb_ranks:
        try:
            devs = jax.devices("cpu")
        except RuntimeError:
            pass
    return devs


def _rank_mesh_geometry():
    """(gp, gq) of the per-rank chip mesh from ``device_mesh_shape``,
    or None when no mesh is configured. A pure function of params, so
    every SPMD rank derives the same geometry."""
    from ...utils.params import params
    try:
        from ...devices.tpu import parse_mesh_shape
        gp, gq = parse_mesh_shape(
            params.get_or("device_mesh_shape", "string", "") or "")
    except (ValueError, TypeError):
        return None
    return (gp, gq) if gp * gq > 1 else None


def _lane_device_pool(nb_ranks: int):
    """rank -> lane device. Without rank meshes: the first nb_ranks
    local devices (the pre-mesh layout). With ``device_mesh_shape``
    set, rank r's ranks own disjoint chip slices (devices/__init__
    carves them at rank*chips), so the lane REUSES each rank's mesh —
    its lane device is chip 0 of that rank's slice — instead of
    parking every rank's collective on chips that all belong to rank
    0's mesh (ISSUE 6 satellite: no ad-hoc foreign-chip meshes)."""
    devs = _lane_local_devices(nb_ranks)
    geom = _rank_mesh_geometry()
    if geom is not None:
        k = geom[0] * geom[1]
        # rank slices must be DISJOINT or the lane mesh would repeat a
        # device (jax Mesh rejects duplicates): with fewer devices than
        # ranks*chips the mesh carving wrapped, so the lane keeps the
        # pre-mesh one-device-per-rank layout
        if len(devs) >= nb_ranks * k:
            return [devs[r * k] for r in range(nb_ranks)]
    return devs[:nb_ranks]


def lane_device_pool(nb_ranks: int):
    """Public seam over the lane's rank -> device mapping: the
    cross-rank stage compiler (stagec/xrank.py, ISSUE 20) builds its
    one-axis global mesh from the SAME pool the two-level collective
    lane rides, so a wave's rank positions and the lane's agree on
    which device each in-process rank owns."""
    return _lane_device_pool(nb_ranks)


def rank_mesh_sharding(rank: int, shape: Optional[str] = None,
                       devices: Optional[List] = None):
    """NamedSharding spreading a rank's sliced tile pools over its OWN
    chip sub-mesh (built on parallel.mesh.make_mesh): tile dims shard
    over the ("tp", "sp") mesh axes, the leading tile-index dim stays
    replicated. The chip slice matches the device layer's carving
    (rank*chips offset), so wave pools and the classic runtime's mesh
    device agree on which chips a rank owns. Returns None when no mesh
    is configured — callers fall back to single-device placement."""
    from jax.sharding import NamedSharding, PartitionSpec

    from ...parallel.mesh import make_mesh
    if shape is not None:
        from ...devices.tpu import parse_mesh_shape
        gp, gq = parse_mesh_shape(shape)
        geom = (gp, gq) if gp * gq > 1 else None
    else:
        geom = _rank_mesh_geometry()
    if geom is None:
        return None
    gp, gq = geom
    k = gp * gq
    devs = list(devices) if devices is not None \
        else list(_lane_local_devices(k))
    if len(devs) < k:
        return None
    off = (rank * k) % len(devs)
    chips = (devs * 2)[off:off + k]
    mesh = make_mesh(sizes={"tp": gp, "sp": gq}, devices=chips)
    return NamedSharding(mesh, PartitionSpec(None, "tp", "sp"))


class _CollectiveLane:
    """ONE compiled XLA collective per broadcast group instead of P
    descriptor sends (SURVEY §5.8's TPU-native target; the reference's
    dynamic trees are /root/reference/parsec/remote_dep.c:272-358).

    A broadcast tile group becomes a single all-reduce over a mesh with
    one device per participating rank: every participant contributes a
    stacked array that is ZERO except at rows it sources, so the sum
    over the rank axis IS the broadcast — XLA compiles the data
    movement (psum over ICI on real hardware), no per-destination
    messages at all.

    Substrates:
    - multi-process (launcher --jax-distributed): every rank holds one
      shard of a global array and calls the same jitted reduction —
      multi-controller SPMD, XLA's distributed runtime moves the bytes.
      Only FULL broadcasts ride this mode: a multi-controller
      computation needs every process in the call.
    - in-process (SPMD rank threads in one process, >= nb_ranks local
      devices): participants deposit their shard at a rendezvous keyed
      by (pool, epoch, wave, cid, members); the LAST depositor issues
      the one multi-device call and everyone picks the replicated
      result up. PARTIAL groups (``members`` = any >= 3 ranks, e.g. a
      2D block-cyclic panel's column readers) reduce over a sub-mesh of
      just the member devices — the common case for P x Q
      distributions, where no tile is read by ALL other ranks.
    """

    def __init__(self, mode: str, nb_ranks: int, rank: int,
                 rendezvous=None, timeout: float = 120.0,
                 dead_fn=None, devices=None,
                 reduce_dtype: Optional[str] = None,
                 shared_feedback=None, stats=None) -> None:
        import jax

        self.mode = mode
        self.nb_ranks = nb_ranks
        self.rank = rank
        self.timeout = timeout
        # reduced-precision lane (ISSUE 14, ``wave_reduce_dtype``):
        # each rank's contribution quantizes AT THE BOUNDARY (blockwise
        # bf16/int8 — the exact wire codecs, wire.qdq_array) before the
        # deposit; the sum itself stays full precision. A pure function
        # of params, so every SPMD rank quantizes identically. Error
        # feedback (parallel/mesh.ErrorFeedback) engages only for
        # callers that pass a stable ``fb_key`` naming a recurring
        # logical buffer — the broadcast-by-sum wave steps carry
        # DIFFERENT tiles every wave, so feeding one wave's residual
        # into the next would corrupt unrelated data; iterative
        # all-reduce users (and the EF tests) name their buffers.
        from ...comm import wire as _wire
        from ...parallel.mesh import ErrorFeedback
        self._qcodec = _wire.normalize_quant_codec(reduce_dtype or "")
        self._efb = ErrorFeedback()
        self.quantized_reduces = 0
        # hierarchical reduction (ISSUE 19, ``xfer_collective_redist``):
        # instead of quantizing EVERY contribution at the boundary,
        # deposits stay full precision and the issuer reduces through
        # parallel/mesh.two_level_allreduce — full-precision partial
        # sums inside each ``xfer_group_size``-wide group (the intra-
        # mesh psum on real chips), one jit-native qdq hop per GROUP at
        # the inter-group boundary. Fewer quantize events, strictly
        # less rounding, same wire-exact codec. A pure function of
        # params + contribution dtype + member count, so every SPMD
        # depositor derives the same routing for the same collective.
        # ``shared_feedback`` (fabric-owned, _setup_collective_lane)
        # keeps the per-group residual in ONE place no matter which
        # rank thread happens to issue; ``stats`` mirrors issue counts
        # into the engine-owned dplane_stats gauges.
        from ...utils.params import params as _params
        self._two_level = bool(_params.get_or(
            "xfer_collective_redist", "bool", False))
        gs = int(_params.get_or("xfer_group_size", "int", 0))
        if gs <= 0:
            geom = _rank_mesh_geometry()
            gs = geom[0] * geom[1] if geom is not None else 2
        self._group_size = max(2, gs)
        self._efb_shared = (shared_feedback if shared_feedback
                            is not None else ErrorFeedback())
        self._stats = stats
        self.two_level_reduces = 0
        # liveness probe for the rendezvous wait (ft/): a callable
        # returning the CE's dead_peers so an evicted member aborts the
        # collective NOW instead of burning the whole timeout
        self.dead_fn = dead_fn or (lambda: ())
        if mode == "multiproc":
            by_proc = {}
            for d in jax.devices():
                by_proc.setdefault(d.process_index, d)
            devs = [by_proc[p] for p in sorted(by_proc)]
            self.device = by_proc[jax.process_index()]
        else:
            # ``devices`` (rank -> lane device) reuses each rank's OWN
            # chip mesh when device_mesh_shape carves one per rank —
            # sub-mesh all-reduces then run over chips the member ranks
            # actually own (_lane_device_pool), not ad-hoc ones
            devs = (list(devices) if devices is not None
                    else _lane_local_devices(nb_ranks))[:nb_ranks]
            self.device = devs[rank]
        self.devs = devs                     # rank -> lane device
        self._rdv = rendezvous   # shared dict+condvar for in-process
        # (members tuple) -> (in_sh, sum_fn) over the member-device
        # (sub-)mesh; jax.jit specializes per input shape/dtype
        # internally, so one wrapper covers every pool/pad bucket
        self._group_sh: Dict[Tuple[int, ...], Tuple] = {}
        # the full-mesh entry doubles as the fast path in reduce();
        # _sum stays an attribute so tests can fault-inject the issuer
        self._in_sh, self._sum = self._group_sharding(
            tuple(range(nb_ranks)))

    def _group_sharding(self, members: Tuple[int, ...]) -> Tuple:
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        ent = self._group_sh.get(members)
        if ent is None:
            mesh = Mesh(np.array([self.devs[r] for r in members]), ("r",))
            in_sh = NamedSharding(mesh, PartitionSpec("r"))
            out_sh = NamedSharding(mesh, PartitionSpec())
            fn = jax.jit(lambda g: g.sum(axis=0), out_shardings=out_sh)
            ent = (in_sh, fn)
            self._group_sh[members] = ent
        return ent

    def _quantize_contrib(self, contrib, fb_key):
        """Quantize one contribution at the reduction boundary (host-
        side, through the shared wire codec so lane and wire round
        identically); dtype and shape are preserved — the compiled
        sum and the rendezvous bookkeeping see no difference."""
        from ...comm import wire as _wire
        arr = np.asarray(contrib)
        if arr.dtype.name not in ("float32", "float64"):
            # int/bool/f16 pools stay exact — and must not count as
            # quantized (qdq_array would pass them through unchanged)
            return contrib
        if fb_key is not None:
            out = self._efb.compensate(fb_key, arr, self._qcodec,
                                       _wire.qdq_array)
        else:
            out = _wire.qdq_array(arr, self._qcodec)
        self.quantized_reduces += 1
        return out

    def _two_level_issue(self, deposits, fb_key):
        """Issuer-side hierarchical reduction: strip the rank axis off
        every deposit, partial-sum full precision inside each group,
        quantize once per group at the boundary through the jit-native
        qdq hop, sum the partials. Error feedback keys per (fb_key,
        group) live in the FABRIC-shared accumulator, so the residual
        carry is identical no matter which rank thread issues."""
        from ...parallel.mesh import two_level_allreduce
        shards = [np.asarray(d)[0] for d in deposits]
        return two_level_allreduce(
            shards, self._group_size, self._qcodec,
            feedback=self._efb_shared if fb_key is not None else None,
            key=fb_key, native=True)

    def reduce(self, key: Tuple, contrib,
               members: Optional[Tuple[int, ...]] = None,
               fb_key=None) -> Any:
        """All-reduce one padded contribution stack; returns the
        replicated result's shard on this rank's lane device.

        ``members``: sorted tuple of participating ranks for a PARTIAL
        group (in-process substrate only — a multi-controller
        computation needs every process); None = all ranks.
        ``fb_key``: stable name of a RECURRING logical buffer — opts
        this contribution into error-feedback accumulation under the
        reduced-precision lane (see __init__; None = quantize-only)."""
        import jax

        full = members is None or len(members) == self.nb_ranks
        parts = tuple(range(self.nb_ranks)) if full else members
        # two-level routing decision — SPMD-pure (params + dtype +
        # member count), so depositors and issuer always agree on
        # whether deposits are full precision or pre-quantized
        two_level = (self._qcodec is not None and self._two_level
                     and self.mode != "multiproc"
                     and np.dtype(getattr(contrib, "dtype",
                                          np.float32)).name
                     in ("float32", "float64")
                     and len(parts) > self._group_size)
        if self._qcodec is not None and not two_level:
            contrib = self._quantize_contrib(contrib, fb_key)
        in_sh, sum_fn = ((self._in_sh, self._sum) if full
                         else self._group_sharding(parts))
        # each rank's deposit is its slice of the [participants, ...]
        # global array: shard shape carries the leading rank axis
        contrib = jax.device_put(contrib[None], self.device)
        gshape = (len(parts),) + tuple(contrib.shape[1:])
        if self.mode == "multiproc":
            assert full, "multiproc lane schedules full broadcasts only"
            garr = jax.make_array_from_single_device_arrays(
                gshape, in_sh, [contrib])
            out = sum_fn(garr)
            return next(s.data for s in out.addressable_shards
                        if s.device == self.device)
        # in-process rendezvous: last depositor issues the single call
        key = key + (parts,)
        slots, results, cv = self._rdv
        with cv:
            mine = slots.setdefault(key, {})
            mine[self.rank] = contrib
            if len(mine) == len(parts):
                try:
                    if two_level:
                        results[key] = [self._two_level_issue(
                            [mine[r] for r in parts], fb_key),
                            len(parts)]
                    else:
                        garr = jax.make_array_from_single_device_arrays(
                            gshape, in_sh, [mine[r] for r in parts])
                        results[key] = [sum_fn(garr), len(parts)]
                except BaseException:
                    # peers-only refcount: the issuer re-raises and
                    # never reaches the pickup decrement below
                    results[key] = [None, len(parts) - 1]
                    raise
                finally:
                    del slots[key]
                    cv.notify_all()
            else:
                deadline = time.monotonic() + self.timeout
                while key not in results:
                    # collective abort on eviction (ft/): a member the
                    # failure detector declared dead will never deposit
                    # — raise the same RankFailedError every other wait
                    # path raises instead of hanging out the timeout
                    dead = self.dead_fn()
                    gone = [r for r in parts
                            if r != self.rank and r in dead]
                    if gone or time.monotonic() > deadline:
                        # withdraw the deposit so a late issuer can't
                        # fire with this rank's share unaccounted
                        ours = slots.get(key)
                        if ours is not None:
                            ours.pop(self.rank, None)
                            if not ours:
                                del slots[key]
                        if gone:
                            from ...comm.engine import RankFailedError
                            raise RankFailedError(
                                gone[0], f"evicted during collective-"
                                f"lane rendezvous {key}")
                        raise WaveError(
                            f"rank {self.rank}: collective-lane "
                            f"rendezvous {key} timed out")
                    cv.wait(0.1)
            ent = results[key]
            ent[1] -= 1
            out = ent[0]
            if ent[1] <= 0:
                del results[key]
        if out is None:
            raise WaveError(f"rank {self.rank}: collective-lane issuer "
                            f"failed for {key}")
        if two_level:
            # host-reduced replicated result: every member lands its
            # own device copy; count per member so the per-rank
            # TWO_LEVEL_REDUCES gauge stays comparable across ranks
            self.two_level_reduces += 1
            if self._stats is not None:
                self._stats["two_level_reduces"] += 1
            return jax.device_put(out, self.device)
        return next(s.data for s in out.addressable_shards
                    if s.device == self.device)


class DistWaveRunner(WaveRunner):
    """Wave executor for a multi-rank PTG taskpool.

    ``comm`` is a RemoteDepEngine or a raw CommEngine; defaults to the
    taskpool's attached engine (``tp.comm``). Payload hop: cross-process
    transports get a DeviceDataPlane attached BY DEFAULT (tiles move
    device-to-device, the message carries only a descriptor; MCA
    ``wave_dist_plane`` = auto/on/off); otherwise exchanged tiles ride
    the CE's active messages as host bytes. Multi-destination tiles
    propagate along static broadcast trees (``wave_dist_bcast`` =
    binomial/chain/star) with in-step re-forwarding.
    """

    _multirank = True

    def __init__(self, tp, max_chunk: int = 256, comm=None,
                 comm_timeout: float = 120.0) -> None:
        comm = comm if comm is not None else getattr(tp, "comm", None)
        if comm is None:
            raise WaveError(
                "distributed wave needs a comm engine: pass comm= or "
                "attach the taskpool to a context with one")
        self.ce = getattr(comm, "ce", comm)
        if self.ce.nb_ranks != tp.nb_ranks:
            raise WaveError(
                f"comm engine spans {self.ce.nb_ranks} ranks but the "
                f"taskpool declares {tp.nb_ranks}")
        self.comm_timeout = comm_timeout
        super().__init__(tp, max_chunk=max_chunk)
        self.rank = int(tp.rank)
        self.nb_ranks = int(tp.nb_ranks)
        self._rank_of_task = self._compute_task_ranks()
        self._levels = self._compute_levels()
        self._setup_collective_lane()
        self._check_lane_uniformity()
        self._build_comm_schedule()
        self._build_local_maps()
        self._scatter_kerns: Dict[int, Any] = {}
        _ensure_wave_inbox(self.ce)
        self._auto_device_plane()

    def _auto_device_plane(self) -> None:
        """Default the payload hop to the device plane (VERDICT r3 weak
        #6: on real multi-chip hardware a naive user must get the fast
        path). MCA ``wave_dist_plane``: auto (attach on cross-process
        transports; in-process fabrics share an address space and two
        transfer servers per OS process trip the runtime's local-bulk
        check, xfer.py:24-27), on (force), off. All ranks build the
        runner SPMD, so the address exchange converges."""
        from ...utils.params import params
        mode = str(params.get_or("wave_dist_plane", "string", "auto"))
        # the lane's blocking XLA collective and the transfer plane
        # share the PJRT client: a pull parked behind a peer's
        # in-flight all-reduce deadlocks (observed on the CPU
        # substrate). With the lane carrying the broadcast volume, the
        # p2p remainder rides host-byte TCP, which only needs socket
        # threads. A lane with NOTHING scheduled (e.g. 2 ranks: no
        # multi-dst edge exists) keeps the plane. wave_dist_plane=on
        # forces the plane anyway (real multi-host TPU: separate
        # hardware queues). _plane_ok gates USE in _comm_step, not just
        # attachment — a plane attached by an earlier runner on this
        # long-lived CE must not be used either (same deadlock); it is
        # a pure function of the static schedule + params, so all SPMD
        # ranks route the same way.
        hazard = (self._lane is not None
                  and self._lane.mode == "multiproc"
                  and bool(self._lane_sched))
        self._plane_ok = (not hazard) or mode == "on"
        if mode == "off" or \
                getattr(self.ce, "device_plane", None) is not None:
            return
        if mode == "auto":
            from ...comm.tcp import TCPCommEngine
            if not isinstance(self.ce, TCPCommEngine):
                return
            if hazard:
                return
        from ...comm.xfer import DeviceDataPlane
        DeviceDataPlane(self.ce).exchange(timeout=self.comm_timeout)

    def _setup_collective_lane(self) -> None:
        """MCA ``wave_dist_collective`` = auto/on/off. auto: attach the
        compiled-collective lane when this is a multi-controller jax
        runtime with exactly one process per rank (the launcher's
        --jax-distributed global mesh). on: additionally allow the
        in-process substrate (one process owning >= nb_ranks devices,
        SPMD rank threads — the virtual-mesh test/dryrun layout). The
        decision is a pure function of process topology + params, so
        all SPMD ranks agree."""
        from ...utils.params import params
        self._lane: Optional[_CollectiveLane] = None
        mode = str(params.get_or("wave_dist_collective", "string", "auto"))
        if mode == "off" or self.nb_ranks < 2:
            return
        # reduced-precision lane (ISSUE 14): a pure function of params,
        # so every SPMD rank derives the same codec (the multiproc
        # uniformity hash covers it too). Validated HERE, before the
        # swallowing try below: a typo'd knob must fail loudly, not
        # silently disable the whole lane under mode=auto
        reduce_dtype = str(params.get_or(
            "wave_reduce_dtype", "string", ""))
        from ...comm import wire as _wire
        _wire.normalize_quant_codec(reduce_dtype)   # raises on typos
        try:
            import jax
            if jax.process_count() == self.nb_ranks:
                self._lane = _CollectiveLane(
                    "multiproc", self.nb_ranks, self.rank,
                    timeout=self.comm_timeout,
                    reduce_dtype=reduce_dtype,
                    stats=getattr(self.ce, "dplane_stats", None))
            elif mode == "on" and jax.process_count() == 1 and \
                    len(_lane_local_devices(self.nb_ranks)) >= self.nb_ranks:
                from ...parallel.mesh import ErrorFeedback
                fab = getattr(self.ce, "fabric", None) or self.ce
                with _LANE_RDV_LOCK:   # SPMD threads race the attach
                    rdv = getattr(fab, "_lane_rdv", None)
                    if rdv is None:
                        rdv = ({}, {}, threading.Condition())
                        fab._lane_rdv = rdv
                    # two-level residuals are per GROUP, applied by
                    # whichever rank thread issues — one fabric-owned
                    # accumulator keeps the carry deterministic
                    efb = getattr(fab, "_lane_efb", None)
                    if efb is None:
                        efb = ErrorFeedback()
                        fab._lane_efb = efb
                self._lane = _CollectiveLane(
                    "inproc", self.nb_ranks, self.rank, rendezvous=rdv,
                    timeout=self.comm_timeout,
                    dead_fn=lambda ce=self.ce: getattr(
                        ce, "dead_peers", ()),
                    devices=_lane_device_pool(self.nb_ranks),
                    reduce_dtype=reduce_dtype,
                    shared_feedback=efb,
                    stats=getattr(self.ce, "dplane_stats", None))
        except Exception:
            if mode == "on":
                raise
            self._lane = None   # auto: no usable substrate -> trees

    def _check_lane_uniformity(self) -> None:
        """Enforce SPMD-identical lane scheduling on MULTIPROC
        deployments (one jax process per rank): exchange a hash of the
        lane params over the CE and fail fast on mismatch instead of
        hanging in a half-joined all-reduce. In-process SPMD rank
        threads share one params registry, so uniformity holds by
        construction and the exchange is skipped."""
        if self.nb_ranks < 2:
            return
        try:
            import jax
            if jax.process_count() != self.nb_ranks:
                return
        except Exception:
            return
        import hashlib
        from ...utils.params import params
        mode = str(params.get_or("wave_dist_collective", "string", "auto"))
        min_pct = int(params.get_or(
            "wave_dist_collective_min_pct", "int", 50))
        # the reduce dtype rides the digest too: a process quantizing
        # its lane contributions while a peer does not silently skews
        # results — better a loud setup failure
        rdt = str(params.get_or("wave_reduce_dtype", "string", ""))
        sig = (mode, min_pct, rdt)
        # the two-level knob changes what every depositor contributes
        # (full precision vs pre-quantized) — it must ride the digest.
        # Appended ONLY when set, so an unset knob leaves the exchanged
        # bytes bit-for-bit identical to the pre-ISSUE-19 wire.
        if bool(params.get_or("xfer_collective_redist", "bool", False)):
            sig = sig + (True,
                         int(params.get_or("xfer_group_size", "int", 0)))
        digest = hashlib.sha1(repr(sig).encode()).hexdigest()
        check_lane_schedule_uniformity(
            self.ce, digest, timeout=min(30.0, self.comm_timeout))

    # ------------------------------------------------------------------ #
    # static analysis                                                    #
    # ------------------------------------------------------------------ #
    def _compute_task_ranks(self) -> np.ndarray:
        dag = self.dag
        out = np.zeros(dag.n_tasks, np.int32)
        for ci, p in enumerate(self.plans):
            if p.ast.affinity_collection is None:
                raise WaveError(
                    f"{p.ast.name}: no affinity (': desc(...)') — every "
                    f"class needs one in distributed wave mode (task "
                    f"ownership IS the affinity)")
        for t in range(dag.n_tasks):
            tc = self.plans[int(dag.class_of[t])].tc
            out[t] = tc.rank_of_instance(tc.env_of(dag.locals_of[t]))
        return out

    def _wire_tname_of(self, tc, f, env):
        """[type_remote] on the instance's bound in-dep applies when
        the producer lives on ANOTHER rank (consumer-side resolution,
        the remote_dep_mpi.c:766 datatype lookup; parsec_reshape.c):
        the exchange still ships the raw tile — the masked wire cast
        runs inside the consumer's kernel, per instance (local edges
        ignore it, the local_no_reshape semantics). Both ends derive
        ranks from the same static affinity, so the decision is
        SPMD-consistent."""
        for d in f.deps_in():
            t = d.resolve(env)
            if t is None:
                continue
            if t.kind != "task" or d.properties.get("type") is not None:
                return None   # the local [type] rule already applies
            nm = d.properties.get("type_remote")
            if nm is None or nm == "full":
                return None
            prank = tc.producer_rank_of(t, env)
            if prank is None or prank == tc.rank_of_instance(env):
                return None   # local edge: wire type never applies
            val = self.tp.global_env.get(nm)
            if not isinstance(val, Datatype) and \
                    nm not in ("lower", "upper", "full"):
                raise WaveError(
                    f"{tc.ast.name}.{f.name}: [type_remote={nm}] is "
                    f"neither a Datatype global nor a region shorthand")
            return nm
        return None

    def _compute_levels(self) -> List[np.ndarray]:
        """Dependence levels of the DAG = the wave schedule (a task's
        wave is 1 + the max wave of its predecessors; level i executes
        as wave i+1, wave 0 is the pre-exchange)."""
        dag = self.dag
        indeg = dag.indegree.copy()
        frontier = [int(t) for t in np.nonzero(indeg == 0)[0]]
        levels: List[np.ndarray] = []
        seen = 0
        while frontier:
            levels.append(np.asarray(sorted(frontier), np.int32))
            seen += len(frontier)
            nxt: List[int] = []
            for t in frontier:
                for e in range(int(dag.indptr[t]), int(dag.indptr[t + 1])):
                    s = int(dag.succ[e])
                    indeg[s] -= 1
                    if indeg[s] == 0:
                        nxt.append(s)
            frontier = nxt
        if seen != dag.n_tasks:
            raise WaveError("cycle in lowered DAG")
        return levels

    def _home_rank(self, cid: int, idx: int) -> int:
        coll = self.collections[self.pool_names[cid]]
        return int(coll.rank_of(*self._pool_coords[cid][idx]))

    def _build_comm_schedule(self) -> None:
        """Derive the full exchange schedule from the slot table.

        Timeline semantics (identical to what pool execution does): a
        read at wave w sees the last write at any wave < w, else the
        home/staged value. Every (reader rank != value-source rank)
        pair becomes one pushed tile after the source wave; last writes
        additionally push home. The schedule is a pure function of the
        DAG + distribution, so all SPMD ranks compute the same one.
        """
        dag = self.dag
        wave_of = np.zeros(dag.n_tasks, np.int32)
        for lv, members in enumerate(self._levels):
            wave_of[members] = lv + 1
        self._wave_of = wave_of

        writers: Dict[Tuple[int, int], List[Tuple[int, int, int]]] = {}
        readers: Dict[Tuple[int, int], List[Tuple[int, int, int]]] = {}
        for t in range(dag.n_tasks):
            p = self.plans[int(dag.class_of[t])]
            w, r = int(wave_of[t]), int(self._rank_of_task[t])
            for k in range(len(p.flow_idx)):
                if p.written[k]:
                    key = (int(self._slot_out_coll[t, k]),
                           int(self._slot_out[t, k]))
                    writers.setdefault(key, []).append((w, t, r))
                    if p.wb_name[k] is not None and self._wb_apply[t, k]:
                        # a masked writeback READS the destination tile
                        # (out-of-region merge) — its current value must
                        # be local even for WRITE-only flows
                        readers.setdefault(key, []).append((w, t, r))
                    if int(self._wbx_cid[t, k]) >= 0:
                        # dual-output flow: the extra masked scatter both
                        # reads and writes its memory target
                        keyx = (int(self._wbx_cid[t, k]),
                                int(self._wbx_idx[t, k]))
                        writers.setdefault(keyx, []).append((w, t, r))
                        readers.setdefault(keyx, []).append((w, t, r))
                if p.reads[k]:
                    key = (int(self._slot_coll[t, k]), int(self._slot[t, k]))
                    readers.setdefault(key, []).append((w, t, r))

        transfers: Set[Tuple[int, int, int, int, int]] = set()
        ws_sorted: Dict[Tuple[int, int], List[Tuple[int, int, int]]] = {}
        for key, wl in writers.items():
            ws = sorted(wl)
            for a, b in zip(ws, ws[1:]):
                if a[0] == b[0] and a[1] != b[1]:
                    cid, idx = key
                    raise WaveError(
                        f"two writers of tile {self._pool_coords[cid][idx]}"
                        f" in {self.pool_names[cid]} share wave {a[0]} "
                        f"(tasks {a[1]}, {b[1]}): the DAG races")
            ws_sorted[key] = ws

        for key, rl in readers.items():
            ws = ws_sorted.get(key, ())
            # scratch pools (NEW flows) have no home: pre-write reads
            # see zeros on every rank — consistent without a transfer
            is_scratch = key[0] >= self._n_real_colls
            home = None if is_scratch else self._home_rank(*key)
            for (w, _t, r) in rl:
                src_wave, src_rank = 0, home
                for (ww, _wt, wr) in ws:
                    if ww >= w:
                        break
                    src_wave, src_rank = ww, wr
                if src_rank is not None and src_rank != r:
                    transfers.add((src_wave, src_rank, r) + key)

        for key, ws in ws_sorted.items():
            if key[0] >= self._n_real_colls:
                continue   # scratch: nothing to return home
            w, _t, r = ws[-1]
            home = self._home_rank(*key)
            if r != home:
                transfers.add((w, r, home) + key)

        # Collective propagation (the reference's remote_dep.c:272-358
        # re-forward): a tile with several same-wave destinations ships
        # along a STATIC broadcast tree instead of P point-to-point
        # sends from the source. Every edge carries its sender's tree
        # depth ("gen"); a comm step processes gens in order — send
        # gen g (g=0 from my pools, g>0 from tiles just received),
        # then absorb gen-g arrivals — so forwards are deadlock-free
        # by construction (gen-g messages depend only on gens < g).
        from ...utils.params import params
        topo = str(params.get_or(
            "wave_dist_bcast", "string", "binomial"))
        grouped: Dict[Tuple[int, int, int, int], List[int]] = {}
        for (w, src, dst, cid, idx) in transfers:
            grouped.setdefault((w, src, cid, idx), []).append(dst)
        edges: Set[Tuple[int, int, int, int, int, int]] = set()
        # lane_sched[wave][(cid, members)] -> sorted [(idx, src)]:
        # broadcast groups ride ONE compiled collective per (wave, pool,
        # member set) instead of a descriptor tree. members is the
        # sorted participant tuple ({src} | dsts) — identical on every
        # rank, so the rendezvous and the reduce order agree globally.
        lane_sched: Dict[int, Dict[Tuple[int, Tuple[int, ...]],
                                   List[Tuple[int, int]]]] = {}
        # multiproc partial groups synchronize EVERY process on the
        # global mesh; below this member share the |dsts| p2p sends are
        # cheaper than a full-mesh barrier + O(nb_ranks x tile) traffic
        # (an SPMD-consistent pure function of the static schedule +
        # params, so all ranks agree). In-process sub-mesh groups cost
        # only their members and take no threshold.
        min_pct = int(params.get_or(
            "wave_dist_collective_min_pct", "int", 50))
        for (w, src, cid, idx), dsts in grouped.items():
            dsts = sorted(set(dsts))
            # never for a single destination (a 1-dst collective loses
            # to one send). PARTIAL groups (>= 2 dsts but not all ranks
            # — the 2D block-cyclic panel case) ride both substrates:
            # in-process reduces over a sub-mesh of just the member
            # devices; multiproc keeps the global mesh — a
            # multi-controller computation needs every process in the
            # call, so non-members join with zero contributions and
            # discard the result (_lane_step).
            if self._lane is not None and len(dsts) >= 2:
                members = tuple(sorted({src, *dsts}))
                if (self._lane.mode == "multiproc"
                        and len(dsts) < self.nb_ranks - 1
                        and len(members) * 100 < self.nb_ranks * min_pct):
                    pass   # small group on a big mesh: trees win
                else:
                    lane_sched.setdefault(w, {}).setdefault(
                        (cid, members), []).append((idx, src))
                    continue
            if topo == "star" or len(dsts) == 1:
                for d in dsts:
                    edges.add((w, src, d, cid, idx, 0))
                continue
            parts = [src] + dsts          # identical on every rank
            frontier = [(0, 0)]
            while frontier:
                nxt = []
                for pos, depth in frontier:
                    for cpos in bcast_children(pos, len(parts), topo):
                        edges.add((w, parts[pos], parts[cpos],
                                   cid, idx, depth))
                        nxt.append((cpos, depth + 1))
                frontier = nxt

        # sends[wave][gen][dst][cid] -> sorted idx list (src == me);
        # recvs[wave][gen] -> sorted src list
        sends: Dict[int, Dict[int, Dict[int, Dict[int, List[int]]]]] = {}
        recvs: Dict[int, Dict[int, Set[int]]] = {}
        for (w, src, dst, cid, idx, g) in edges:
            if src == self.rank:
                (sends.setdefault(w, {}).setdefault(g, {})
                 .setdefault(dst, {}).setdefault(cid, [])).append(idx)
            if dst == self.rank:
                recvs.setdefault(w, {}).setdefault(g, set()).add(src)
        for by_gen in sends.values():
            for by_dst in by_gen.values():
                for by_coll in by_dst.values():
                    for lst in by_coll.values():
                        lst.sort()
        self._sends = sends
        self._recvs = {w: {g: sorted(s) for g, s in by_gen.items()}
                       for w, by_gen in recvs.items()}
        self._bcast_topo = topo
        self._lane_sched = {w: {c: sorted(v) for c, v in by_c.items()}
                            for w, by_c in lane_sched.items()}
        self._transfers = {(w, s, d, c, i)
                           for (w, s, d, c, i, _g) in edges}
        self._n_transfers = len(self._transfers)

    def _build_local_maps(self) -> None:
        """SLICED pools: this rank stages only the tiles it touches —
        local task slots plus the endpoints of transfers it takes part
        in. Memory per rank becomes O(local tiles + halo) instead of
        O(whole matrix); the exchange schedule keeps speaking GLOBAL
        tile indices on the wire, translated to pool rows at gathers
        and scatters (wave.py does the same for kernel indices via
        self._g2l)."""
        n_pools = self._n_real_colls + len(self._scratch)
        sizes = [len(self._pool_coords[c])
                 for c in range(self._n_real_colls)]
        for sp in sorted(self._scratch.values(), key=lambda s: s["cid"]):
            sizes.append(sp["n"])
        touched: List[set] = [set() for _ in range(n_pools)]
        for t in np.nonzero(self._rank_of_task == self.rank)[0]:
            p = self.plans[int(self.dag.class_of[t])]
            for k in range(len(p.flow_idx)):
                touched[int(self._slot_coll[t, k])].add(
                    int(self._slot[t, k]))
                if p.written[k]:
                    touched[int(self._slot_out_coll[t, k])].add(
                        int(self._slot_out[t, k]))
                    if int(self._wbx_cid[t, k]) >= 0:
                        touched[int(self._wbx_cid[t, k])].add(
                            int(self._wbx_idx[t, k]))
        for (w, src, dst, cid, idx) in self._transfers:
            if src == self.rank or dst == self.rank:
                touched[cid].add(idx)
        for by_grp in self._lane_sched.values():
            # lane tiles: every group MEMBER is an endpoint
            for (cid, members), entries in by_grp.items():
                if self.rank in members:
                    touched[cid].update(i for (i, _s) in entries)
        self._l2g = [np.asarray(sorted(s), np.int32) for s in touched]
        g2l = []
        for c in range(n_pools):
            m = np.full(max(sizes[c], 1), -1, np.int32)
            if len(self._l2g[c]):
                m[self._l2g[c]] = np.arange(len(self._l2g[c]),
                                            dtype=np.int32)
            g2l.append(m)
        self._g2l = g2l

    def _pool_tile_spec(self, cid: int):
        """(tile_shape, dtype) of one pool, without staging it. NOT the
        (mb, nb) block size — edge tiles of a short matrix can be
        smaller than the block while still uniform across the pool."""
        if cid < self._n_real_colls:
            coll = self.collections[self.pool_names[cid]]
            sh = self._pool_shapes[cid]
            dt = getattr(coll, "dtype", None)
            if sh is None or dt is None:
                # materialize a LOCALLY-OWNED tile only: on multiproc a
                # non-member rank reaches this for pools it stages
                # nothing of, and the pool's first global coord may
                # live on another rank — data_of there would fail or
                # fetch remote bytes. Without an owned coord the
                # collection must declare the static contract.
                c0 = next(
                    (c for c in self._pool_coords[cid]
                     if int(coll.rank_of(*c)) == self.rank), None)
                if c0 is None:
                    raise WaveError(
                        f"rank {self.rank}: collection "
                        f"{self.pool_names[cid]!r} declares no static "
                        f"tile_shape/dtype and this rank owns no tile "
                        f"of the pool — the collective lane requires "
                        f"the static contract on non-member ranks (set "
                        f"tile_shape/dtype on the collection)")
                arr = np.asarray(
                    coll.data_of(*c0).sync_to_host().payload)
                sh = tuple(arr.shape) if sh is None else sh
                dt = arr.dtype if dt is None else dt
            return tuple(sh), np.dtype(dt)
        sp = next(s for s in self._scratch.values() if s["cid"] == cid)
        if sp["shape"] is not None:
            return tuple(sp["shape"]), np.dtype(sp["dtype"])
        return self._pool_tile_spec(sp["like"])

    def build_pools(self, device=None, sharding=None) -> Tuple:
        """Stage only this rank's slice of every pool (see
        _build_local_maps).

        ``sharding`` enables the HYBRID process x mesh layout: each
        rank's sliced pools shard over its OWN local sub-mesh (a
        jax.sharding.Sharding over the tile dims), so wave kernels run
        GSPMD across the rank's chips while the static exchange
        schedule still moves tiles between ranks. Gathered exchange
        tiles from sharded pools are multi-device, so payloads take the
        host-byte hop automatically (the device plane requires
        single-device arrays — _comm_step's _is_single_device check);
        pools whose tile shape the spec cannot divide replicate on the
        sub-mesh, like the single-rank path."""
        import jax
        import jax.numpy as jnp

        def put(z):
            if sharding is not None:
                return self._put_sharded(z, sharding)
            return jax.device_put(z, device) if device is not None \
                else jnp.asarray(z)

        pools: List[Any] = []
        for cid, name in enumerate(self.pool_names):
            loc = self._l2g[cid]
            if cid not in self._used_colls or not len(loc):
                pools.append(jnp.zeros((0,), np.float32))
                continue
            coll = self.collections[name]
            coords = self._pool_coords[cid]
            tiles = [np.asarray(
                coll.data_of(*coords[int(g)]).sync_to_host().payload)
                for g in loc]
            pools.append(put(np.stack(tiles)))
        for sp in sorted(self._scratch.values(), key=lambda s: s["cid"]):
            loc = self._l2g[sp["cid"]]
            if not len(loc):
                pools.append(jnp.zeros((0,), np.float32))
                continue
            shape, dt = self._pool_tile_spec(sp["cid"])
            z = np.zeros((len(loc),) + shape, dt)
            # scratch replicates on the sub-mesh (a tile-dim spec need
            # not fit scratch ranks), exactly like the single-rank path
            pools.append(self._put_replicated(z, sharding)
                         if sharding is not None else put(z))
        return tuple(pools)

    # ------------------------------------------------------------------ #
    # execution                                                          #
    # ------------------------------------------------------------------ #
    def execute(self, pools: Tuple) -> Tuple:
        ce = self.ce
        inbox, cv = _ensure_wave_inbox(ce)
        pool_name = self.tp.name
        with cv:
            epoch = ce._wave_epochs[pool_name] = (
                ce._wave_epochs.get(pool_name, 0) + 1)
        self._cur = (pool_name, epoch)
        self._sent_tiles = 0
        self._recv_tiles = 0
        self._fwd_tiles = 0
        self._fwd_host_stacks = 0
        self._fwd_device_stacks = 0
        self._lane_calls = 0
        self._lane_joins = 0
        self._lane_tiles = 0

        ok = False
        t0 = time.perf_counter()
        try:
            pools = self._comm_step(0, pools)
            n_calls = 0
            for lv, members in enumerate(self._levels):
                mine = members[self._rank_of_task[members] == self.rank]
                if mine.size:
                    pools, nc = self._execute_frontier(
                        mine, self.dag.class_of[mine], pools)
                    n_calls += nc
                pools = self._comm_step(lv + 1, pools)
            ok = True
            # same schema as WaveRunner.stats plus the exchange counters
            self.stats = {
                "tasks": self.dag.n_tasks,
                "waves": len(self._levels),
                "kernel_calls": n_calls,
                "dispatch_secs": round(time.perf_counter() - t0, 6),
                "compiled_kernels": sum(len(p.kernels)
                                        for p in self.plans)
                + len(self._fused_kerns),
                "local_tasks": int((self._rank_of_task == self.rank).sum()),
                "transfers_scheduled": self._n_transfers,
                "tiles_sent": self._sent_tiles,
                "tiles_recv": self._recv_tiles,
                "tiles_forwarded": self._fwd_tiles,
                "fwd_host_stacks": self._fwd_host_stacks,
                "fwd_device_stacks": self._fwd_device_stacks,
                "bcast_topology": self._bcast_topo,
                "collective_lane": (self._lane.mode
                                    if self._lane is not None else None),
                "collective_calls": self._lane_calls,
                "collective_joins": self._lane_joins,
                "collective_tiles": self._lane_tiles,
                "collective_reduce_dtype": (
                    self._lane._qcodec if self._lane is not None
                    else None),
                "collective_quantized": (
                    self._lane.quantized_reduces
                    if self._lane is not None else 0),
                "collective_two_level": (
                    self._lane.two_level_reduces
                    if self._lane is not None else 0),
                "device_plane": (getattr(self.ce, "device_plane",
                                         None) is not None
                                 and self._plane_ok),
                "local_tiles": int(sum(len(g) for g in self._l2g)),
            }
        finally:
            # drop anything still keyed to this run (abort/timeout paths
            # must not leak tile payloads on the long-lived CE), and
            # wait out the consumers' park acks (device-plane hop). On
            # the exception path acks may never come (the peer that
            # would send them is likely the failure) — don't stall the
            # real error behind a second full timeout
            with cv:
                for k in [k for k in inbox
                          if k[0] == pool_name and k[1] == epoch]:
                    del inbox[k]
            self._drain_parks(timeout=self.comm_timeout if ok else 1.0)
        plog.debug.verbose(
            3, "dist wave %s rank %d: %d/%d tasks in %d waves, %d kernel "
            "calls, %d transfers scheduled", pool_name, self.rank,
            int((self._rank_of_task == self.rank).sum()), self.dag.n_tasks,
            len(self._levels), n_calls, self._n_transfers)
        return pools

    def _lane_step(self, w: int, pools: Tuple) -> Tuple:
        """Execute this wave's broadcast groups as ONE compiled
        collective per (wave, pool, member set): gather my sourced rows
        into a zero-padded contribution stack, all-reduce over the
        group's lane (sub-)mesh (sum == broadcast), scatter the
        replicated result into my staged pool rows. Groups this rank is
        not a member of are skipped — their members rendezvous without
        us. Counts ride stats as collective_calls / collective_tiles;
        none of these tiles appear in _sends."""
        sched = self._lane_sched.get(w)
        if not sched:
            return pools
        import jax
        import jax.numpy as jnp

        pool_name, epoch = self._cur
        plist = list(pools)
        multiproc = self._lane.mode == "multiproc"
        # sorted keys: every rank walks its shared groups in the same
        # global order, so the blocking rendezvous can never cycle —
        # and on multiproc every PROCESS issues the same global calls
        # in the same order, which multi-controller XLA requires
        for cid, members in sorted(sched):
            member = self.rank in members
            if not member and not multiproc:
                continue   # in-process: their rendezvous excludes us
            entries = sched[(cid, members)]
            idxs = np.asarray([i for (i, _s) in entries], np.int32)
            srcs = np.asarray([s for (_i, s) in entries], np.int32)
            n = len(entries)
            npad = 1 << max(0, (n - 1).bit_length())   # bucket compiles
            shape, _dt = self._pool_tile_spec(cid)
            if multiproc:
                # the dtype must be an SPMD-consistent pure function of
                # the spec: a non-member process whose sliced pool is
                # the (0,) float32 placeholder would otherwise compile
                # a different-width program for the SAME global
                # collective. canonicalize applies the x64 downcast
                # rule build_pools' staging applies.
                dt = jax.dtypes.canonicalize_dtype(_dt)
            else:
                # dtype from the STAGED pool, not the collection spec:
                # with x64 off an f64 collection stages f32 pools
                dt = (plist[cid].dtype if hasattr(plist[cid], "dtype")
                      else _dt)
            mine = (np.nonzero(srcs == self.rank)[0] if member
                    else np.empty(0, np.intp))
            lidx = self._g2l[cid][idxs] if member else None
            contrib = jnp.zeros((npad,) + tuple(shape), dt)
            if len(mine):
                rows = plist[cid][lidx[mine]]
                if not _is_single_device(rows):
                    rows = np.asarray(rows)   # sharded pools: host hop
                contrib = contrib.at[np.asarray(mine, np.int32)].set(
                    jax.device_put(rows, self._lane.device))
            out = self._lane.reduce(
                (pool_name, epoch, w, cid), contrib,
                # multiproc: the global mesh — non-members contributed
                # zeros and drop the result below
                members=None if multiproc else members)
            if not member:
                # joined the SPMD call with zero contributions (ADVICE
                # r5): counted apart so collective_calls keeps meaning
                # 'collectives that carried MY tiles'
                self._lane_joins += 1
                continue
            self._lane_calls += 1
            vals = out[:n]
            if _is_single_device(plist[cid]):
                dev = next(iter(plist[cid].devices()))
                vals = jax.device_put(vals, dev)
            else:
                vals = np.asarray(vals)       # sharded pools
            plist[cid] = self._scatter_kernel(n)(plist[cid], lidx, vals)
            self._lane_tiles += n
        return tuple(plist)

    def _comm_step(self, w: int, pools: Tuple) -> Tuple:
        """Push my wave-w writes to their remote readers, then absorb
        what wave w wrote elsewhere that I will read.

        Payload hop: with a DeviceDataPlane attached on both ends, the
        gathered tiles stay ONE stacked DEVICE array — the producer
        parks it, the message carries only the descriptor, and the
        consumer pulls device-to-device then acks the park (the
        schedule is unchanged; only the bytes' route differs). Without
        a plane (or for multi-device/sharded pools) tiles ride the CE
        as host bytes."""
        import jax
        import jax.numpy as jnp

        pools = self._lane_step(w, pools)
        pool_name, epoch = self._cur
        # _plane_ok: never park payloads on the plane while the lane
        # issues blocking collectives on the same PJRT client (set in
        # _auto_device_plane; covers planes attached by earlier runners)
        plane = (getattr(self.ce, "device_plane", None)
                 if self._plane_ok else None)
        send_gens = self._sends.get(w, {})
        recv_gens = self._recvs.get(w, {})
        if not send_gens and not recv_gens:
            return pools
        max_gen = max(list(send_gens) + list(recv_gens))
        # batch ALL of this wave's incoming tiles per collection and
        # apply them as ONE donated jitted scatter per pool: an eager
        # .at[].set() per (src, coll) would copy the whole stacked pool
        # each time (pools are O(matrix) — tens of copies per run)
        upd: Dict[int, Tuple[List[int], List[Any]]] = {}
        pulled: List[Tuple[int, int, Any]] = []   # (src, uuid, array)
        # tiles received at gen < g, kept for my gen-g re-forwards
        fwd_cache: Dict[Tuple[int, int], Any] = {}
        for g in range(max_gen + 1):
            for dst in sorted(send_gens.get(g, ())):
                colls = []
                for cid in sorted(send_gens[g][dst]):
                    idxs = send_gens[g][dst][cid]  # GLOBAL on the wire
                    if g == 0:
                        # I am the tree root: the value is in my pools
                        gathered = pools[cid][self._g2l[cid][
                            np.asarray(idxs, np.int32)]]
                    else:
                        # re-forward what a parent just sent me. Rows
                        # stay DEVICE-resident whenever any row is a
                        # device array (plane pulls); the host np.stack
                        # is only for payloads that genuinely arrived
                        # as host bytes (round-4 VERDICT Weak #5:
                        # a single host row must not demote device
                        # siblings through a host round-trip)
                        rows = [fwd_cache[(cid, i)] for i in idxs]
                        if all(isinstance(r, np.ndarray) for r in rows):
                            gathered = np.stack(rows)
                            self._fwd_host_stacks += 1
                        else:
                            gathered = jnp.stack(
                                [jnp.asarray(r) for r in rows])
                            self._fwd_device_stacks += 1
                        self._fwd_tiles += len(idxs)
                    if plane is not None and _is_single_device(gathered):
                        jax.block_until_ready(gathered)
                        u, shape, dt = plane.register(gathered)
                        _ib, cv = _ensure_wave_inbox(self.ce)
                        with cv:
                            self.ce._wave_parks.add(u)
                        colls.append((cid, idxs,
                                      {"xfer": (u, tuple(shape), dt)}))
                    else:
                        payload = np.asarray(gathered)
                        try:
                            # fresh gathered stack, mutated by no one:
                            # read-only lets the TCP chunk path send it
                            # zero-copy instead of re-snapshotting
                            payload.setflags(write=False)
                        except ValueError:
                            pass   # foreign-base view: already safe
                        colls.append((cid, idxs, payload))
                    self._sent_tiles += len(idxs)
                # tile payload message: eligible for the lossy
                # quantized wire codecs (ISSUE 14) — the transport
                # quantizes the bulk float stacks toward peers that
                # negotiated one; descriptors/control stay exact
                self.ce.send_am(dst, TAG_WAVE,
                                {"pool": pool_name, "epoch": epoch,
                                 "wave": w, "gen": g, "colls": colls,
                                 "_qz_ok": True})
            for src in recv_gens.get(g, ()):
                msg = self._await_msg(src, w, g)
                for cid, idxs, payload in msg["colls"]:
                    if isinstance(payload, dict):
                        if plane is None:  # not assert: survive python -O
                            raise WaveError(
                                f"rank {self.rank}: peer {src} sent a "
                                f"device-plane transfer descriptor but "
                                f"this rank has no DeviceDataPlane "
                                f"attached (attach one on every rank)")
                        u, shape, dt = payload["xfer"]
                        arr = plane.pull(src, u, tuple(shape), dt)
                        pulled.append((src, u, arr))
                    else:
                        arr = np.asarray(payload)
                    lst = upd.setdefault(cid, ([], []))
                    lst[0].extend(idxs)
                    lst[1].append(arr)
                    self._recv_tiles += len(idxs)
                    if g < max_gen:
                        for i, idx in enumerate(idxs):
                            fwd_cache[(cid, idx)] = arr[i]
        if pulled:
            # the ack releases the producer's park: only after the
            # bytes actually landed
            jax.block_until_ready([a for (_s, _u, a) in pulled])
            by_src: Dict[int, List[int]] = {}
            for (s, u, _a) in pulled:
                by_src.setdefault(s, []).append(u)
            for s, uuids in by_src.items():
                self.ce.send_am(s, TAG_WAVE, {"ack_uuids": uuids})
        plist = list(pools)
        for cid, (idxs, arrs) in upd.items():
            vals = (jnp.concatenate([jnp.asarray(a) for a in arrs], axis=0)
                    if len(arrs) > 1 else jnp.asarray(arrs[0]))
            lidx = self._g2l[cid][np.asarray(idxs, np.int32)]
            plist[cid] = self._scatter_kernel(len(idxs))(
                plist[cid], lidx, vals)
        return tuple(plist)

    def _drain_parks(self, timeout: float) -> None:
        """Wait for consumers' park acks so no transfer buffers leak on
        the long-lived CE (warn instead of failing a completed run)."""
        _ib, cv = _ensure_wave_inbox(self.ce)
        deadline = time.monotonic() + timeout
        while True:
            with cv:
                n = len(self.ce._wave_parks)
            if n == 0:
                return
            if time.monotonic() > deadline:
                plog.warning("rank %d: %d wave transfer park(s) never "
                             "acked within %.0fs", self.rank, n, timeout)
                return
            self.ce.progress()
            with cv:
                cv.wait(0.0005)

    def _scatter_kernel(self, k: int):
        """Donated jitted pool scatter for k tiles (cached per count —
        waves reuse the same few counts, so compiles amortize)."""
        kern = self._scatter_kerns.get(k)
        if kern is None:
            import jax

            kern = jax.jit(lambda pool, idx, vals: pool.at[idx].set(vals),
                           donate_argnums=(0,))
            self._scatter_kerns[k] = kern
        return kern

    def _await_msg(self, src: int, w: int, gen: int = 0) -> Dict:
        pool_name, epoch = self._cur
        key = (pool_name, epoch, src, w, gen)
        inbox, cv = _ensure_wave_inbox(self.ce)
        deadline = time.monotonic() + self.comm_timeout
        while True:
            with cv:
                msg = inbox.pop(key, None)
            if msg is not None:
                return msg
            self.ce.progress()
            # failure detection AFTER the drain: the peer's final
            # message may have been queued by the recv thread right
            # before it died — progress() just delivered it (same
            # final-drain-then-raise order as tcp._barrier_wait). A
            # cleanly finished peer can't send the owed message either.
            gone = (src in getattr(self.ce, "dead_peers", ())
                    or src in getattr(self.ce, "finished_peers", ()))
            if gone:
                with cv:
                    msg = inbox.pop(key, None)
                if msg is not None:
                    return msg
                from ...comm.tcp import RankFailedError
                raise RankFailedError(
                    src, f"gone owing wave-{w} exchange for {pool_name}")
            with cv:
                if key in inbox:
                    continue
                cv.wait(0.0005)
            if time.monotonic() > deadline:
                raise WaveError(
                    f"rank {self.rank}: no wave-exchange message "
                    f"{key} within {self.comm_timeout}s (peer dead or "
                    f"schedules diverged)")

    # ------------------------------------------------------------------ #
    # pool staging                                                       #
    # ------------------------------------------------------------------ #
    def scatter_pools(self, pools: Tuple) -> None:
        """Register this rank's results: only tiles it OWNS **and
        staged** (their home is here and some task touched them —
        untouched owned tiles were never staged and their home copies
        stand); the final-state transfers brought every last write home
        first, so owned tiles are current on their owner.

        Writeback is LAZY by default (VERDICT r3 weak #7): each owned
        tile's newest copy becomes a LazyPoolCopy slicing the device
        pool on first read, so a single-tile host read pulls exactly
        one tile instead of the eager owned-slice D2H + per-row copy
        loop (the never-bulk-pull lesson — a 1 GB pull at this
        tunnel's 3-4 MB/s D2H is ~5 min). MCA ``wave_lazy_writeback=0``
        restores the eager host loop."""
        from ...utils.params import params
        if not bool(params.get_or("wave_lazy_writeback", "bool", True)):
            return self._scatter_pools_eager(pools)
        from .turbo import LazyPoolCopy, _PoolHolder
        from ...data.data import Coherency
        holder = _PoolHolder()
        holder.pools = pools
        self._wb_holder = holder   # pools live as long as the copies
        did = self._writeback_device_id()
        for cid, name in enumerate(self.pool_names):
            if cid not in self._written_colls:
                continue
            coll = self.collections[name]
            coords = self._pool_coords[cid]
            for j, g in enumerate(self._l2g[cid]):
                c = coords[int(g)]
                if int(coll.rank_of(*c)) != self.rank:
                    continue
                data = coll.data_of(*c)
                old = data.get_copy(did)
                if old is not None:
                    data._detach_copy(old)
                h0 = data.get_copy(0)
                lazy = LazyPoolCopy(data, did, holder, cid, j,
                                    dtt=None if h0 is None else h0.dtt)
                data.attach_copy(lazy)
                lazy.coherency = Coherency.OWNED
                data.version_bump(did)

    def _writeback_device_id(self) -> int:
        """Device slot for the lazy result copies: the context's
        accelerator module when one is attached, else slot 1 (any
        non-host id works — sync_to_host without a device list converts
        directly)."""
        ctx = getattr(self.tp, "context", None)
        if ctx is not None:
            for d in getattr(ctx, "devices", ()):
                if d.device_type == "tpu":
                    return d.device_index
        return 1

    def _scatter_pools_eager(self, pools: Tuple) -> None:
        for cid, name in enumerate(self.pool_names):
            if cid not in self._written_colls:
                continue
            coll = self.collections[name]
            coords = self._pool_coords[cid]
            owned = [(j, int(g)) for j, g in enumerate(self._l2g[cid])
                     if int(coll.rank_of(*coords[int(g)])) == self.rank]
            if not owned:
                continue
            host = np.asarray(
                pools[cid][np.asarray([j for j, _g in owned], np.int32)])
            for row, (_j, g) in enumerate(owned):
                data = coll.data_of(*coords[g])
                hc = data.host_copy()
                if hc.payload is None:
                    hc.payload = host[row].copy()
                else:
                    np.copyto(hc.payload, host[row])
                data.version_bump(0)
