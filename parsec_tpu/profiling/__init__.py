"""profiling subpackage (SURVEY.md §5.1/§5.5)."""
from .grapher import Grapher, grapher
from .pins import (PINS, AlperfModule, IteratorsCheckerModule, PinsEvent,
                   PinsModule, PrintStealsModule, TaskProfilerModule,
                   TaskTimeModule, pins_is_active)
from .sde import (PENDING_TASKS, TASKS_ENABLED, TASKS_RETIRED, SDERegistry,
                  sde)
from .trace import Dictionary, Profile, ThreadStream

__all__ = [
    "PINS", "PinsEvent", "PinsModule", "pins_is_active",
    "TaskProfilerModule", "PrintStealsModule", "AlperfModule",
    "IteratorsCheckerModule", "TaskTimeModule",
    "Grapher", "grapher", "SDERegistry", "sde",
    "TASKS_ENABLED", "TASKS_RETIRED", "PENDING_TASKS",
    "Dictionary", "Profile", "ThreadStream",
]
