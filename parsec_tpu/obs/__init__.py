"""obs — runtime-wide telemetry: one façade over the profiling islands.

The repo grew three observability islands — ``profiling.trace`` (span
traces), ``profiling.pins`` (hot-path callback sites), ``profiling.sde``
(software counters) — plus ad-hoc ``stats`` dicts on the comm engine and
devices. This package unifies them:

- :mod:`obs.metrics` — ``MetricsRegistry``: counters/gauges (wrapping the
  per-context SDE registry) + latency histograms, fed by a PINS module;
- :mod:`obs.spans` — ``CommObs``/``DeviceObs``: span tracing + byte
  counters for the comm engine and device transfers (a single
  ``_obs is None`` check on the hot path, the PINS ``_active == 0``
  pattern);
- :mod:`obs.prometheus` — text exposition + strict line-format parser;
- :mod:`obs.critpath` — offline critical-path / per-class breakdown /
  compute-comm overlap analysis (CLI: ``tools/obs_report.py``).

Enable per run with ``Context(profile=True)`` (spans + counters) and/or
the ``metrics`` MCA param (histograms + counters without trace
collection). ``ContextObs`` is the per-context wiring object; the
runtime creates one in ``Context.__init__``.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from .critpath import (analyze, critical_path, distributed_critical_path,
                       format_report, load_flow_events, merge_trace_docs,
                       parse_dot, per_link_exposed_wait, rank_clock_shifts,
                       stitch_flows)
from .live import (LiveHealth, RollingStat, fleet_health, format_health,
                   register_health_gauges)
from .metrics import (COMM_XFER_SECONDS, TASK_EXEC_SECONDS, Histogram,
                      MetricsRegistry, MetricsTaskModule)
from .prometheus import (fleet_to_prometheus, parse_exposition, render,
                         sanitize_name)
from .spans import (COMM_ACTIVE_TRANSFERS, COMM_BYTES_RECEIVED,
                    COMM_BYTES_SENT, COMM_CHUNKS_INFLIGHT, COMM_COALESCED,
                    COMM_COMPRESS_RATIO, COMM_DUP_DROPPED,
                    COMM_LINK_BW_PREFIX,
                    COMM_MSGS_RECEIVED, COMM_MSGS_SENT,
                    COMM_PENDING_MESSAGES, COMM_RECONNECTS,
                    COMM_REPLAYED_FRAMES, COMM_SUSPECT_MS,
                    CommObs, DeviceObs, HEALTH_STREAM_TID,
                    FT_ELASTIC_JOINS, FT_ELASTIC_RESIZES, FT_HB_RTT_PREFIX,
                    FT_PEER_ALIVE, FT_RESHARD_BYTES, FT_RESHARD_US,
                    OBS_CLOCK_OFFSET_PREFIX, OBS_EXPOSED_COMM_US,
                    OBS_FLOW_RECV, OBS_FLOW_SENT,
                    OBS_HEALTH_DEGRADED, OBS_HEALTH_FIRINGS,
                    OBS_HEALTH_STATUS, OBS_HEALTH_STRAGGLER,
                    OBS_HEALTH_STUCK, OBS_HEALTH_WINDOWS,
                    OBS_HEALTH_WORST_LINK_US, OBS_OVERLAP_FRACTION,
                    OverlapTracker, SERVE_ADMITTED, SERVE_INFLIGHT_PREFIX,
                    SERVE_P99_LATENCY_PREFIX, SERVE_QUEUED,
                    SERVE_QUOTA_BYTES_PREFIX, SERVE_REJECTED, SERVE_TENANTS,
                    TUNE_ACTIVE_CODEC_PREFIX,
                    TUNE_DECISIONS, TUNE_OBJECTIVE_US, TUNE_REVERTS,
                    flow_event_id, inbound_flow_ctx,
                    payload_nbytes, register_device_gauges)

__all__ = [
    "MetricsRegistry", "Histogram", "MetricsTaskModule", "ContextObs",
    "CommObs", "DeviceObs", "OverlapTracker", "payload_nbytes",
    "COMM_BYTES_SENT", "COMM_BYTES_RECEIVED", "COMM_MSGS_SENT",
    "COMM_MSGS_RECEIVED", "COMM_ACTIVE_TRANSFERS", "COMM_PENDING_MESSAGES",
    "COMM_COALESCED", "COMM_CHUNKS_INFLIGHT", "COMM_COMPRESS_RATIO",
    "COMM_LINK_BW_PREFIX", "COMM_RECONNECTS", "COMM_REPLAYED_FRAMES",
    "COMM_DUP_DROPPED", "COMM_SUSPECT_MS",
    "FT_PEER_ALIVE", "FT_HB_RTT_PREFIX",
    "FT_ELASTIC_RESIZES", "FT_ELASTIC_JOINS", "FT_RESHARD_BYTES",
    "FT_RESHARD_US",
    "OBS_OVERLAP_FRACTION", "OBS_EXPOSED_COMM_US",
    "OBS_FLOW_SENT", "OBS_FLOW_RECV", "OBS_CLOCK_OFFSET_PREFIX",
    "OBS_HEALTH_STATUS", "OBS_HEALTH_WINDOWS", "OBS_HEALTH_FIRINGS",
    "OBS_HEALTH_STRAGGLER", "OBS_HEALTH_DEGRADED", "OBS_HEALTH_STUCK",
    "OBS_HEALTH_WORST_LINK_US",
    "TUNE_DECISIONS", "TUNE_REVERTS", "TUNE_ACTIVE_CODEC_PREFIX",
    "TUNE_OBJECTIVE_US",
    "SERVE_TENANTS", "SERVE_ADMITTED", "SERVE_REJECTED", "SERVE_QUEUED",
    "SERVE_INFLIGHT_PREFIX", "SERVE_QUOTA_BYTES_PREFIX",
    "SERVE_P99_LATENCY_PREFIX",
    "LiveHealth", "RollingStat", "fleet_health", "format_health",
    "register_health_gauges",
    "flow_event_id", "inbound_flow_ctx",
    "TASK_EXEC_SECONDS", "COMM_XFER_SECONDS",
    "render", "parse_exposition", "sanitize_name", "fleet_to_prometheus",
    "analyze", "critical_path", "format_report", "parse_dot",
    "merge_trace_docs", "rank_clock_shifts", "stitch_flows",
    "load_flow_events", "distributed_critical_path",
    "per_link_exposed_wait",
    "validate_chrome_trace",
]


class ContextObs:
    """Per-context telemetry wiring. Constructed by ``Context.__init__``
    once the SDE registry, profile, comm engine, and devices exist.

    Pull gauges (device memory/load, pending comm queues) are registered
    unconditionally — they cost nothing until something reads them. The
    hot-path hooks (comm spans/byte counters, device transfer spans, the
    task-latency PINS module) are installed only when tracing or metrics
    collection is on, so a bare run keeps the near-free fast path."""

    def __init__(self, ctx: Any) -> None:
        self.metrics = MetricsRegistry(ctx.sde)
        tune_on = _tune_param()
        # tune_auto (ISSUE 17) implies obs_live: the controller's only
        # input is the monitor's window digest, so the knob pulls the
        # whole monitor up with it (mirroring obs_live implying the
        # span sinks below)
        # serve (ISSUE 18) implies obs_live the same way: per-tenant
        # SLO attribution lives in the monitor's window digests, so a
        # serving context always carries the monitor
        live_on = _live_param() or tune_on or _serve_param()
        # obs_live (ISSUE 16) implies the span sinks: the streaming
        # monitor's feeds ARE the comm/device/exec hooks, so the knob
        # alone turns telemetry on even without profile= or metrics
        self.enabled = bool(ctx.profile is not None or _metrics_param()
                            or live_on)
        self._engines: List[Any] = []
        self._devices: List[Any] = []
        self._task_module: Optional[MetricsTaskModule] = None
        self._profiler_with_hist: Optional[Any] = None
        # streaming health monitor (obs/live.py): rolling per-link
        # exposure / overlap / lag + anomaly detectors, constructed
        # ONLY under the knob — unset means no object, no thread, no
        # gauges (the inertness contract)
        self.live: Optional[LiveHealth] = None
        if live_on:
            from ..utils.params import params
            self.live = LiveHealth(
                ctx.rank,
                window_ms=params.get_or("obs_live_window_ms", "int", 250),
                stream=(ctx.profile.stream(HEALTH_STREAM_TID, "health")
                        if ctx.profile is not None else None),
                pending_fn=getattr(ctx, "_pending_gauge", None))
            register_health_gauges(ctx.sde, self.live)
        # live T3 overlap gauge (ISSUE 7): compute/comm interval
        # accumulator behind PARSEC::OBS::OVERLAP_FRACTION — only with
        # telemetry on (its feeds are the span sinks below)
        self.overlap: Optional[OverlapTracker] = None
        if self.enabled:
            self.overlap = OverlapTracker()
            ctx.sde.register_poll(OBS_OVERLAP_FRACTION, self.overlap.fraction)
            ctx.sde.register_poll(OBS_EXPOSED_COMM_US, self.overlap.exposed_us)
        # stage-compile gauges (stagec/, ISSUE 12; guide §9.1):
        # poll-only over the context's stage counters
        ss = getattr(ctx, "stage_stats", None)
        if isinstance(ss, dict):
            ctx.sde.register_poll("PARSEC::STAGEC::STAGE_COMPILES",
                                  lambda s=ss: s["stage_compiles"])
            ctx.sde.register_poll("PARSEC::STAGEC::STAGE_TASKS",
                                  lambda s=ss: s["stage_tasks"])
            ctx.sde.register_poll("PARSEC::STAGEC::STAGE_FALLBACKS",
                                  lambda s=ss: s["stage_fallbacks"])
            ctx.sde.register_poll(
                "PARSEC::STAGEC::STAGE_COMPILE_US",
                lambda s=ss: round(s["stage_compile_ns"] / 1e3, 1))
            # ISSUE 13 gauges: prestage/execute overlap, cross-pool
            # chaining, compiled residue schedule (guide §9.1)
            ctx.sde.register_poll("PARSEC::STAGEC::PRESTAGE_ISSUED",
                                  lambda s=ss: s["prestage_issued"])
            ctx.sde.register_poll("PARSEC::STAGEC::PRESTAGE_HITS",
                                  lambda s=ss: s["prestage_hits"])
            ctx.sde.register_poll("PARSEC::STAGEC::CHAIN_LINKS",
                                  lambda s=ss: s["chain_links"])
            ctx.sde.register_poll("PARSEC::STAGEC::CHAIN_FALLBACKS",
                                  lambda s=ss: s["chain_fallbacks"])
            ctx.sde.register_poll("PARSEC::STAGEC::RESIDUE_BATCHES",
                                  lambda s=ss: s["residue_batches"])
            ctx.sde.register_poll(
                "PARSEC::STAGEC::RESIDUE_BATCH_TASKS",
                lambda s=ss: s["residue_batch_tasks"])
            # ISSUE 20 gauges: cross-rank SPMD stages (guide §9.1)
            ctx.sde.register_poll("PARSEC::STAGEC::XSTAGE_COMPILES",
                                  lambda s=ss: s["xstage_compiles"])
            ctx.sde.register_poll("PARSEC::STAGEC::XSTAGE_TASKS",
                                  lambda s=ss: s["xstage_tasks"])
            ctx.sde.register_poll(
                "PARSEC::STAGEC::XSTAGE_COLLECTIVE_BYTES",
                lambda s=ss: s["xstage_collective_bytes"])
            ctx.sde.register_poll("PARSEC::STAGEC::XSTAGE_FALLBACKS",
                                  lambda s=ss: s["xstage_fallbacks"])
        # device pull gauges always (poll-only, no hot-path cost); the
        # span/histogram sink only when telemetry is on
        for dev in ctx.devices:
            register_device_gauges(ctx.sde, dev)
            if self.enabled:
                dev._obs = DeviceObs(self.metrics, dev, profile=ctx.profile,
                                     tracker=self.overlap, live=self.live)
                self._devices.append(dev)
        ce = getattr(ctx.comm, "ce", ctx.comm) if ctx.comm is not None else None
        if ce is not None:
            comm_obs = CommObs(self.metrics,
                               profile=ctx.profile if self.enabled else None,
                               tracker=self.overlap if self.enabled else None,
                               live=self.live)
            comm_obs.register_engine_gauges(ce)
            if self.enabled:
                ce._obs = comm_obs
                self._engines.append(ce)
                # cross-rank flow tracing (ISSUE 15): arm the wire
                # trace-context allocator — sends toward negotiated
                # peers stamp a (origin, span) context and emit the
                # "s" half of the flow edge; deliver_message emits the
                # "f" half on the receiver.  A transport that resolved
                # the knob itself (TCPCommEngine's obs_flow ctor
                # override) is the source of truth — it already
                # advertised (or withheld) the "tr" capability
                flow_on = getattr(ce, "_flow_enabled", None)
                if flow_on is None:
                    # in-process fabrics: either knob arms the
                    # allocator (obs_live rides the flow machinery)
                    flow_on = _flow_param() or self.live is not None
                if flow_on:
                    from ..comm.engine import FlowIds
                    ce._flow = FlowIds(ce.rank)
                    if self.live is not None:
                        # obs_live: widen stamped contexts toward
                        # lv-negotiated peers with (pool, t_send_ns)
                        ce._flow.live = True
            if self.live is not None:
                # late-bind the transport's live estimators: clock
                # offsets (flow-lag conversion) + link-bandwidth EWMA
                # (the degraded-link detector's second signal)
                self.live.bind_engine(ce)
            # remote-dep protocol counters as pull gauges
            stats = getattr(ctx.comm, "stats", None)
            if isinstance(stats, dict):
                for key in stats:
                    self.metrics.gauge(
                        f"PARSEC::COMM::{key.upper()}",
                        lambda s=stats, k=key: s[k])
        if self.enabled:
            profiler = getattr(ctx, "_task_profiler", None)
            if profiler is not None:
                # profiling on: the task profiler already hooks EXEC
                # begin/end — feed the histogram from it instead of
                # registering a second PINS callback on the hot path
                from .metrics import ExecTimer
                profiler.exec_timer = ExecTimer(
                    self.metrics.histogram(TASK_EXEC_SECONDS),
                    tracker=self.overlap, live=self.live)
                self._profiler_with_hist = profiler
            else:
                self._task_module = MetricsTaskModule(self.metrics,
                                                      context=ctx,
                                                      tracker=self.overlap,
                                                      live=self.live)
                self._task_module.enable()
        # closed-loop self-tuning (ISSUE 17, tune/controller.py): the
        # controller rides the monitor's window-tick subscriber seam —
        # constructed ONLY under tune_auto, after every actuation
        # target (transport, devices, overlap tracker) exists, before
        # the monitor thread starts ticking
        self.tuner = None
        if tune_on and self.live is not None:
            from ..tune import Controller, register_tune_gauges
            from ..utils.params import params
            try:
                budget = float(params.get_or(
                    "tune_residual_budget", "string", "1e-2") or 0.0)
            except (TypeError, ValueError):
                budget = 1e-2
            self.tuner = Controller(
                ctx.rank, self.live,
                engine=ce,
                devices=tuple(ctx.devices),
                residual_budget=budget,
                hysteresis=params.get_or("tune_hysteresis_windows",
                                         "int", 2),
                z_thresh=self.live.z_thresh,
                overlap_fn=(self.overlap.fraction
                            if self.overlap is not None else None),
                stage_classes_fn=lambda c=ctx: _compiled_stage_classes(c))
            register_tune_gauges(ctx.sde, self.tuner)
            self.live.subscribe(self.tuner.on_window)
        if self.live is not None:
            # the rolling-window monitor thread (detectors + window
            # folds) — the last thing started, so every feed is wired
            self.live.start()

    def fini(self) -> None:
        """Unhook from global PINS sites and the engine/device sinks (a
        later context must not feed this context's histograms)."""
        if self.live is not None:
            self.live.stop()
        if self._task_module is not None:
            self._task_module.disable()
            self._task_module = None
        if self._profiler_with_hist is not None:
            self._profiler_with_hist.exec_timer = None
            self._profiler_with_hist = None
        for ce in self._engines:
            ce._obs = None
            ce._flow = None
        self._engines.clear()
        for dev in self._devices:
            dev._obs = None
        self._devices.clear()

    def render_prometheus(self, labels: Optional[Dict[str, str]] = None) -> str:
        from ..profiling.sde import sde as global_sde
        # include the process-global registry (named mempools, user
        # counters) so every documented name appears in one exposition
        return render(self.metrics, labels=labels, extra_sde=global_sde)


def _metrics_param() -> bool:
    from ..utils.params import params
    try:
        return bool(params.get("metrics"))
    except KeyError:  # pragma: no cover - param registered at import
        return False


def _flow_param() -> bool:
    from ..utils.params import params
    return bool(params.get_or("obs_flow", "bool", False))


def _live_param() -> bool:
    from ..utils.params import params
    return bool(params.get_or("obs_live", "bool", False))


def _tune_param() -> bool:
    from ..utils.params import params
    return bool(params.get_or("tune_auto", "bool", False))


def _serve_param() -> bool:
    from ..utils.params import params
    return bool(params.get_or("serve", "bool", False))


def _compiled_stage_classes(ctx: Any) -> List[str]:
    """Class names with a live compiled stage on this context, in plan
    order — the stagec-exclusion family's attribution source (best
    effort: an interpreted-only context returns [])."""
    names: List[str] = []
    for tp in list(getattr(ctx, "taskpools", {}).values()):
        sc = getattr(tp, "_stagec", None)
        if sc is None:
            continue
        for stage in getattr(sc.plan, "stages", ()):
            for m in stage.members:
                n = m.tc.name
                if n not in names:
                    names.append(n)
    return names


# ---------------------------------------------------------------------- #
# minimal Chrome-trace schema check (used by the CI smoke test)          #
# ---------------------------------------------------------------------- #
def validate_chrome_trace(doc: Any) -> Dict[str, int]:
    """Validate the exported trace against the minimal schema Perfetto
    needs: a ``traceEvents`` list of dicts, each with a string ``name``
    and ``ph``, numeric ``ts`` for non-metadata events, per
    (pid, tid, name) matched B/E counts, and — for flow events
    (``ph:"s"``/``"f"``, ISSUE 15) — a flow ``id`` per event with
    start/finish PAIRING accounted order-independently (the receiver
    half of an edge may precede the sender half in a merged list).
    Returns summary counts including matched ``flows`` and the
    ``unmatched_flows`` remainder (one-sided edges are a lost-message
    or truncated-trace signal, not a schema violation); raises
    ValueError on any violation."""
    if not isinstance(doc, dict) or not isinstance(
            doc.get("traceEvents"), list):
        raise ValueError("trace must be an object with a traceEvents list")
    opens: Dict[tuple, int] = {}
    flow_s: Dict[Any, int] = {}
    flow_f: Dict[Any, int] = {}
    n_spans = n_meta = n_counter = 0
    for i, ev in enumerate(doc["traceEvents"]):
        if not isinstance(ev, dict):
            raise ValueError(f"event {i} is not an object")
        if not isinstance(ev.get("name"), str) or not isinstance(
                ev.get("ph"), str):
            raise ValueError(f"event {i} missing name/ph")
        ph = ev["ph"]
        if ph == "M":
            n_meta += 1
            continue
        if not isinstance(ev.get("ts"), (int, float)):
            raise ValueError(f"event {i} ({ev['name']}) missing numeric ts")
        key = (ev.get("pid", 0), ev.get("tid", 0), ev["name"])
        if ph == "B":
            opens[key] = opens.get(key, 0) + 1
            n_spans += 1
        elif ph == "E":
            if opens.get(key, 0) <= 0:
                raise ValueError(f"event {i}: E without matching B for {key}")
            opens[key] -= 1
        elif ph == "X":
            if not isinstance(ev.get("dur"), (int, float)):
                raise ValueError(
                    f"event {i} ({ev['name']}): X event missing numeric dur")
            n_spans += 1
        elif ph == "C":
            n_counter += 1
        elif ph in ("s", "f"):
            if not isinstance(ev.get("id"), (int, str)):
                raise ValueError(
                    f"event {i} ({ev['name']}): flow event missing id")
            side = flow_s if ph == "s" else flow_f
            side[ev["id"]] = side.get(ev["id"], 0) + 1
    unclosed = {k: v for k, v in opens.items() if v}
    if unclosed:
        raise ValueError(f"unclosed spans: {sorted(unclosed)[:5]}")
    matched = sum(min(n, flow_f.get(fid, 0)) for fid, n in flow_s.items())
    total_flow_ev = sum(flow_s.values()) + sum(flow_f.values())
    return {"spans": n_spans, "metadata": n_meta, "counters": n_counter,
            "flows": matched, "unmatched_flows": total_flow_ev - 2 * matched,
            "events": len(doc["traceEvents"])}
