"""Multi-rank tests over the in-process fabric (the reference's analog:
every distributed behavior validated by oversubscribed mpiexec on one node,
SURVEY.md §4). SPMD: one thread per rank, each with its own Context.
"""
import threading

import numpy as np
import pytest

import parsec_tpu
from parsec_tpu.comm import LocalFabric, RemoteDepEngine, bcast_children
from parsec_tpu.collections import DictCollection, TwoDimBlockCyclic
from parsec_tpu.dsl import dtd, ptg
from parsec_tpu.dsl.dtd import AFFINITY, INOUT, INPUT, VALUE, unpack_args


def spmd(nb_ranks, fn, timeout=60):
    """Run fn(rank, fabric) on one thread per rank; propagate exceptions.
    Delegates to the canonical harness (parsec_tpu/utils/spmd.py)."""
    from parsec_tpu.utils.spmd import spmd_threads

    return spmd_threads(nb_ranks, fn, timeout=timeout)


def test_bcast_children_topologies():
    # star: root sends to everyone
    assert bcast_children(0, 5, "star") == [1, 2, 3, 4]
    assert bcast_children(2, 5, "star") == []
    # chain: each forwards to the next
    assert bcast_children(0, 4, "chain") == [1]
    assert bcast_children(2, 4, "chain") == [3]
    assert bcast_children(3, 4, "chain") == []
    # binomial: tree coverage — every position reached exactly once
    for nb in (2, 3, 4, 5, 8, 13):
        reached = {0}
        frontier = [0]
        while frontier:
            nxt = []
            for p in frontier:
                for c in bcast_children(p, nb, "binomial"):
                    assert c not in reached, f"nb={nb}: {c} reached twice"
                    reached.add(c)
                    nxt.append(c)
            frontier = nxt
        assert reached == set(range(nb)), f"nb={nb}: {sorted(reached)}"


CHAIN_JDF = """
descA [ type="collection" ]
NB [ type="int" ]

Step(k)

k = 0 .. NB

: descA( k, 0 )

RW A <- (k == 0) ? descA( k, 0 ) : A Step( k-1 )
     -> (k == NB) ? descA( k, 0 ) : A Step( k+1 )

BODY
{
    A[0, 0] += 1.0
}
END
"""


def _ptg_chain_rank(rank, fabric, nb_ranks, NB, tile=4):
    eng = RemoteDepEngine(fabric.engine(rank))
    ctx = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
    try:
        coll = TwoDimBlockCyclic((NB + 1) * tile, tile, tile, tile,
                                 P=nb_ranks, Q=1, nodes=nb_ranks, rank=rank)
        coll.name = "descA"
        tp = ptg.compile_jdf(CHAIN_JDF, name="chain").new(
            descA=coll, NB=NB, rank=rank, nb_ranks=nb_ranks)
        ctx.add_taskpool(tp)
        ctx.wait()
        # collect final values of my local tiles
        out = {}
        for k in range(NB + 1):
            if coll.rank_of(k, 0) == rank:
                out[k] = float(coll.tile(k, 0)[0, 0])
        return out
    finally:
        ctx.fini()


@pytest.mark.parametrize("nb_ranks", [2, 4])
def test_ptg_chain_across_ranks(nb_ranks):
    """Ex04-style chain where consecutive tasks live on different ranks:
    every hop is a remote dep (activation + data)."""
    NB = 7
    results, fabric = spmd(nb_ranks,
                           lambda r, f: _ptg_chain_rank(r, f, nb_ranks, NB))
    merged = {}
    for r in results:
        merged.update(r)
    # the datum flows through task copies: tile 0 was incremented in place
    # by task 0, tiles 1..NB-1 are untouched, tile NB gets the final
    # writeback after NB+1 increments
    expect = {k: 0.0 for k in range(NB + 1)}
    expect[0] = 1.0
    expect[NB] = float(NB + 1)
    assert merged == expect
    assert fabric.msg_count > 0


BCAST_JDF = """
descA [ type="collection" ]
NR [ type="int" ]

Root(k)
k = 0 .. 0
: descA( 0, 0 )
RW A <- descA( 0, 0 )
     -> A Leaf( 1 .. NR-1 )
BODY
{
    A[0, 0] = 77.0
}
END

Leaf(r)
r = 1 .. NR-1
: descA( r, 0 )
READ A <- A Root( 0 )
BODY
{
    got.append(float(A[0, 0]))
}
END
"""


@pytest.mark.parametrize("topology", ["star", "chain", "binomial"])
def test_ptg_broadcast_topologies(topology):
    """One root datum broadcast to every other rank under each topology
    (ref: runtime_comm_coll_bcast, remote_dep.c:272-295)."""
    nb_ranks = 4
    parsec_tpu.params.reset()
    parsec_tpu.params.set_cmdline("runtime_comm_coll_bcast", topology)

    got_all = [[] for _ in range(nb_ranks)]

    def rank_fn(rank, fabric):
        eng = RemoteDepEngine(fabric.engine(rank))
        ctx = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
        try:
            coll = TwoDimBlockCyclic(nb_ranks * 4, 4, 4, 4, P=nb_ranks, Q=1,
                                     nodes=nb_ranks, rank=rank)
            coll.name = "descA"
            tp = ptg.compile_jdf(BCAST_JDF, name="bcast").new(
                descA=coll, NR=nb_ranks, rank=rank, nb_ranks=nb_ranks)
            tp.global_env["got"] = got_all[rank]
            ctx.add_taskpool(tp)
            ctx.wait()
        finally:
            ctx.fini()

    spmd(nb_ranks, rank_fn)
    parsec_tpu.params.reset()
    assert got_all[0] == []
    for r in range(1, nb_ranks):
        assert got_all[r] == [77.0], f"rank {r}: {got_all[r]}"


def test_ptg_rendezvous_large_payload():
    """Payloads over the short limit must travel via the GET rendezvous."""
    nb_ranks = 2
    parsec_tpu.params.reset()
    parsec_tpu.params.set_cmdline("runtime_comm_short_limit", "64")

    def rank_fn(rank, fabric):
        eng = RemoteDepEngine(fabric.engine(rank))
        ctx = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
        try:
            coll = TwoDimBlockCyclic(2 * 64, 64, 64, 64, P=2, Q=1,
                                     nodes=2, rank=rank)
            coll.name = "descA"
            tp = ptg.compile_jdf(CHAIN_JDF, name="chain").new(
                descA=coll, NB=1, rank=rank, nb_ranks=2)
            ctx.add_taskpool(tp)
            ctx.wait()
            if coll.rank_of(1, 0) == rank:
                return float(coll.tile(1, 0)[0, 0])
        finally:
            ctx.fini()

    results, fabric = spmd(nb_ranks, rank_fn)
    parsec_tpu.params.reset()
    assert 2.0 in [r for r in results if r is not None]


def test_dtd_cross_rank_chain():
    """DTD chain on one tile with tasks alternating between 2 ranks: every
    edge is a cross-rank RAW resolved by (tile, seq) matching."""
    nb_ranks = 2
    N = 6

    def rank_fn(rank, fabric):
        eng = RemoteDepEngine(fabric.engine(rank))
        ctx = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
        try:
            # tile homed on rank 0
            coll = DictCollection(nodes=nb_ranks, rank=rank)
            coll.name = "C"
            coll.add("x", 0, np.zeros(2) if rank == 0 else None)
            # per-rank anchor tiles to place tasks via AFFINITY
            anchors = {}
            for r in range(nb_ranks):
                a = DictCollection(nodes=nb_ranks, rank=rank)
                a.name = f"anchor{r}"
                a.add("a", r, np.zeros(1) if r == rank else None)
                anchors[r] = a
            tp = dtd.taskpool_new("xchain")
            ctx.add_taskpool(tp)
            tile = tp.tile_of(coll, "x")
            history = []

            def bump(es, task):
                x, anchor, k = unpack_args(task)
                assert x[0] == k, f"task {k} saw {x[0]}"
                x[0] += 1.0
                history.append(k)

            for k in range(N):
                owner = k % nb_ranks
                at = tp.tile_of(anchors[owner], "a")
                tp.insert_task(bump, (tile, INOUT),
                               (at, INPUT | AFFINITY), (k, VALUE))
            tp.data_flush_all()
            tp.wait()
            ctx.wait()
            final = None
            if rank == 0:
                final = float(coll.data_of("x").get_copy(0).payload[0])
            return (history, final)
        finally:
            ctx.fini()

    results, fabric = spmd(nb_ranks, rank_fn)
    hist0, final0 = results[0]
    hist1, _ = results[1]
    assert hist0 == [0, 2, 4]
    assert hist1 == [1, 3, 5]
    assert final0 == float(N)  # flushed back home
    assert fabric.msg_count > 0


def test_dedicated_comm_thread_drains_progress():
    """--mca comm_thread 1: the funnelled progress thread (ref: the
    remote_dep comm thread, optionally bound via -C) drives the dataflow
    even though every worker is parked; the run completes and the thread
    is joined at fini."""
    import parsec_tpu
    from conftest import spmd
    from parsec_tpu.comm import RemoteDepEngine
    from parsec_tpu.collections import DictCollection
    from parsec_tpu import dtd
    from parsec_tpu.dsl.dtd import AFFINITY, INOUT, VALUE, unpack_args

    parsec_tpu.params.set_cmdline("comm_thread", "1")
    try:
        def rank_fn(rank, fabric):
            eng = RemoteDepEngine(fabric.engine(rank))
            c = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
            try:
                assert c._comm_thread is not None
                assert c._comm_thread.is_alive()
                coll = DictCollection(nodes=2, rank=rank)
                coll.name = "C"
                coll.add("x", 0, np.full((64,), 1.0, np.float32)
                         if rank == 0 else None)
                tp = dtd.taskpool_new()
                c.add_taskpool(tp)
                tile = tp.tile_of(coll, "x")

                def bump(es, task):
                    x, a = unpack_args(task)
                    x += a

                for _ in range(6):
                    tp.insert_task(bump, (tile, INOUT), (1.0, VALUE))
                tp.data_flush_all()
                tp.wait()
                thread = c._comm_thread
            finally:
                c.fini()
            assert not thread.is_alive()  # joined at fini
            if rank == 0:
                return float(np.asarray(
                    coll.data_of("x").newest_copy().payload)[0])
            return None

        results, _ = spmd(2, rank_fn)
        assert 7.0 in results  # 1 + 6 bumps
    finally:
        parsec_tpu.params.reset()


def test_dead_consumer_parks_reclaimed():
    """A consumer rank that dies owing device-plane ACKs must not hang
    the producer: _release_parks_for reclaims exactly its parks and
    retires the pending actions (round-2 VERDICT item 7 — the failure
    path is wired into on_peer_failure in attach())."""
    class FakeTp:
        def __init__(self):
            self.pending = 0

        def add_pending_action(self, n):
            self.pending += n

        def pending_action_done(self, n):
            self.pending -= n

    class FakePlane:
        def __init__(self):
            self.released = []

        def release(self, u):
            self.released.append(u)

    fabric = LocalFabric(2)
    eng = RemoteDepEngine(fabric.engine(0))
    plane = FakePlane()
    eng.ce.device_plane = plane
    tp = FakeTp()
    tp.add_pending_action(3)
    with eng._lock:
        eng._pending_xfers[11] = (tp, 1)
        eng._pending_xfers[12] = (tp, 1)
        eng._pending_xfers[13] = (tp, 0)   # other consumer: must stay

    eng._release_parks_for(1)
    assert sorted(plane.released) == [11, 12]
    assert tp.pending == 1
    with eng._lock:
        assert list(eng._pending_xfers) == [13]
    # idempotent: a second failure report finds nothing
    eng._release_parks_for(1)
    assert tp.pending == 1


def test_activation_gated_until_counts_ready():
    """An activation that lands after taskpool registration but BEFORE
    startup credits nb_tasks must stay buffered: delivering it early can
    release — and complete — a task while nb_tasks is still 0, tripping
    the termdet >=0 assertion or overwriting the decrement into a hang
    (the full-suite all2all flake, round 5). Delivery happens only at
    counts_ready()."""
    from parsec_tpu.comm.engine import TAG_ACTIVATE

    fabric = LocalFabric(2)
    e0 = RemoteDepEngine(fabric.engine(0))
    e1 = RemoteDepEngine(fabric.engine(1))

    class StubTP:
        pass

    tp = StubTP()
    e1.taskpool_register(tp)           # registered, counts NOT credited
    msg = {"tp_id": tp.comm_tp_id, "root": 0, "ranks": [1],
           "edges": {1: []}, "src_task": None, "dtt": None, "data": None}
    e0.ce.send_am(1, TAG_ACTIVATE, msg)
    e1.progress(None)                  # handler must buffer, not deliver
    assert list(e1._early_activations) == [tp.comm_tp_id]

    delivered = []
    e1._on_activate = lambda src, m, replay=False: delivered.append(m)
    e1.counts_ready(tp)
    assert [m["tp_id"] for m in delivered] == [tp.comm_tp_id]
    assert not e1._early_activations


def test_arrival_wakeup_during_context_init():
    """A peer's message can land the instant attach() installs the
    arrival callback — while Context.__init__ is still running (the
    LocalFabric fires on_arrival from the SENDER's thread). The wakeup
    must find the park/wake state already initialized (round-5 fix:
    AttributeError '_work_cond' surfacing as a task-body failure)."""
    import types

    import parsec_tpu

    fabric = LocalFabric(1)
    eng = RemoteDepEngine(fabric.engine(0))
    orig_attach = RemoteDepEngine.attach
    fired = []

    def attach_and_fire(self, context):
        orig_attach(self, context)
        self.ce._notify_arrival()      # simulated racing arrival
        fired.append(True)

    eng.attach = types.MethodType(attach_and_fire, eng)
    ctx = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
    try:
        assert fired
    finally:
        ctx.fini()
