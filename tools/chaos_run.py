#!/usr/bin/env python
"""chaos_run — run any example/script under deterministic fault injection.

Wires the ft/ knobs (injection spec, heartbeat detection, restart
policy) into the MCA environment and executes the target script in this
process, so a robustness claim can be exercised against any entry point
without editing it::

    # kill rank 1 after 5 tasks, detect within 0.5 s
    python tools/chaos_run.py --inject "kill:rank=1:after=5" \\
        --heartbeat 0.05 --timeout 0.5 -- examples/ex03_chain_multirank.py

    # 2%% frame drop, reproducible
    python tools/chaos_run.py --inject "drop:pct=2:seed=7" -- \\
        examples/ex05_broadcast.py

    # transient task fault + automatic rollback/retry
    python tools/chaos_run.py --inject "taskfail:nth=3" \\
        --restart "restart:retries=2:backoff=0.1" -- \\
        examples/ex08_dposv_checkpoint.py

Everything after ``--`` is the script and ITS argv. Exit status: the
script's (an uncaught injected failure exits non-zero — which is the
point: chaos_run makes "does it fail loudly instead of hanging?"
a one-liner).
"""
import argparse
import os
import runpy
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="chaos_run.py",
        description="run a script under ft/ fault injection")
    ap.add_argument("--inject", default="",
                    help="ft_inject spec (see parsec_tpu/ft/inject.py), "
                         "e.g. 'kill:rank=1:after=5,drop:pct=2:seed=7'")
    ap.add_argument("--heartbeat", type=float, default=0.0, metavar="SECS",
                    help="enable the proactive detector with this probe "
                         "interval")
    ap.add_argument("--timeout", type=float, default=0.0, metavar="SECS",
                    help="heartbeat eviction deadline (default 8x the "
                         "interval)")
    ap.add_argument("--restart", default="", metavar="POLICY",
                    help="ft_restart_policy, e.g. "
                         "'restart:retries=2:backoff=0.25:every=1'")
    ap.add_argument("script", help="python script to run")
    ap.add_argument("args", nargs=argparse.REMAINDER,
                    help="argv for the script (prefix with --)")
    ns = ap.parse_args(argv)

    directives = []
    if ns.inject:
        # validate the spec HERE so a typo is a chaos_run error, not a
        # silent no-op inside the target
        from parsec_tpu.ft.inject import parse_inject_spec
        directives = parse_inject_spec(ns.inject)
        os.environ["PARSEC_MCA_ft_inject"] = ns.inject
    if ns.timeout > 0 and ns.heartbeat <= 0:
        # --timeout alone would export a deadline nobody enforces (no
        # detector without an interval): derive the probe cadence
        ns.heartbeat = ns.timeout / 8.0
    if any(d["op"] == "kill" for d in directives) and ns.heartbeat <= 0:
        ap.error("--inject kill:... without --heartbeat/--timeout would "
                 "hang the survivors (no detector to evict the silenced "
                 "rank) — pass --heartbeat SECS")
    if ns.heartbeat > 0:
        os.environ["PARSEC_MCA_ft_heartbeat_interval"] = str(ns.heartbeat)
    if ns.timeout > 0:
        os.environ["PARSEC_MCA_ft_heartbeat_timeout"] = str(ns.timeout)
    if ns.restart:
        from parsec_tpu.ft.restart import RestartPolicy
        RestartPolicy.parse(ns.restart)
        os.environ["PARSEC_MCA_ft_restart_policy"] = ns.restart

    script = os.path.abspath(ns.script)
    # drop only the LEADING separator: a later "--" belongs to the
    # target script's own argv
    args = ns.args[1:] if ns.args[:1] == ["--"] else ns.args
    sys.argv = [script] + args
    sys.path.insert(0, os.path.dirname(script))
    runpy.run_path(script, run_name="__main__")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
