"""Gradient correctness of the 5-axis-parallel training step.

The sharded loss runs under shard_map with manual collectives; replicated
leaves get their gradients psum'd over sync_axes. This test checks the
resulting GLOBAL gradients numerically against plain single-device
autodiff of an independently-written reference implementation of the same
math — the only way to catch over-counting across axes where compute is
redundant (e.g. the whole forward across ep for a dense model, the
residual stream across tp).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

if not hasattr(jax, "shard_map"):
    # see test_parallel.py: the VMA-tracking shard_map is load-bearing
    # for the psum-transpose rule these gradient checks validate
    pytest.skip("jax.shard_map (VMA tracking) not available in this jax",
                allow_module_level=True)

from parsec_tpu.models import TransformerConfig, init_params, param_specs
from parsec_tpu.models.transformer import loss_shard
from parsec_tpu.parallel import make_mesh, shard_map_compat, sync_axes
from parsec_tpu.parallel.moe import load_balance_loss
from parsec_tpu.parallel.ring_attention import local_attention


def _rmsnorm(x, g):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * g


def ref_loss(cfg: TransformerConfig, params, tokens, labels,
             aux_blocks=(1, 1)):
    """Single-device reference of the flagship model's loss.

    aux_blocks=(dp, sp): the sharded Switch-aux is estimated per
    (batch-shard, sequence-shard) token block then averaged; the
    reference reproduces that estimator (it differs from the whole-batch
    one because the load-balance loss is nonlinear in token statistics).
    """
    x = params["embed"][tokens] + params["pos"][jnp.arange(cfg.seq_len)][None]
    x = x.astype(cfg.dtype)
    st = params["stages"]
    aux_total = jnp.zeros((), jnp.float32)
    for s in range(cfg.n_stages):
        for l in range(cfg.layers_per_stage):
            h = _rmsnorm(x, st["ln1"][s, l])
            qkv = jnp.einsum("btd,dchn->bcthn", h, st["wqkv"][s, l],
                             preferred_element_type=jnp.float32).astype(x.dtype)
            q = qkv[:, 0].transpose(0, 2, 1, 3)
            k = qkv[:, 1].transpose(0, 2, 1, 3)
            v = qkv[:, 2].transpose(0, 2, 1, 3)
            a = local_attention(q, k, v, causal=True)
            o = jnp.einsum("bhtd,hdD->btD", a, st["wo"][s, l],
                           preferred_element_type=jnp.float32).astype(x.dtype)
            x = x + o
            h2 = _rmsnorm(x, st["ln2"][s, l])
            if cfg.n_experts:
                gl = jnp.einsum("btd,de->bte", h2, st["gate"][s, l])
                probs = jax.nn.softmax(gl, axis=-1)
                if cfg.moe_top_k < cfg.n_experts:
                    thresh = jax.lax.top_k(probs, cfg.moe_top_k)[0][..., -1:]
                    m = probs >= thresh
                    probs = probs * m
                    probs = probs / (probs.sum(-1, keepdims=True) + 1e-9)
                he = jnp.einsum("...d,edf->...ef", h2, st["w1e"][s, l],
                                preferred_element_type=jnp.float32)
                he = jax.nn.gelu(he)
                ye = jnp.einsum("...ef,efd->...ed", he, st["w2e"][s, l],
                                preferred_element_type=jnp.float32)
                f = jnp.einsum("...ed,...e->...d", ye,
                               probs.astype(ye.dtype)).astype(x.dtype)
                dp_b, sp_b = aux_blocks
                B, T, E = gl.shape
                blocks = gl.reshape(dp_b, B // dp_b, sp_b, T // sp_b, E)
                aux_blk = jnp.mean(jnp.stack([
                    load_balance_loss(blocks[d, :, s])
                    for d in range(dp_b) for s in range(sp_b)]))
                aux_total = aux_total + aux_blk
            else:
                u = jnp.einsum("btd,df->btf", h2, st["w1"][s, l],
                               preferred_element_type=jnp.float32)
                u = jax.nn.gelu(u).astype(x.dtype)
                f = jnp.einsum("btf,fD->btD", u, st["w2"][s, l],
                               preferred_element_type=jnp.float32).astype(x.dtype)
            x = x + f
    y = _rmsnorm(x, params["ln_f"])
    logits = jnp.einsum("btd,vd->btv", y.astype(jnp.float32),
                        params["embed"].astype(jnp.float32))
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    loss = nll.mean()
    if cfg.n_experts:
        loss = loss + cfg.aux_loss_weight * aux_total / cfg.n_layers
    return loss


def _sharded_loss_and_grads(cfg, mesh, params, tokens, labels):
    pspecs = param_specs(cfg)

    def shard(p, t, y):
        # VMA-checked shard_map: grads of replicated leaves come out
        # already reduced over the correct axes (no manual sync psum)
        loss, grads = jax.value_and_grad(
            lambda pp: loss_shard(cfg, pp, t, y))(p)
        return loss, grads

    fn = shard_map_compat(shard, mesh,
                          in_specs=(pspecs, P("dp", "sp"), P("dp", "sp")),
                          out_specs=(P(), pspecs))
    return fn(params, tokens, labels)


@pytest.mark.parametrize("case", ["dense_ep2_tp2", "moe_ep2", "pp2_sp2"])
def test_sharded_grads_match_reference(case):
    if case == "dense_ep2_tp2":
        # the killer config: ep is completely unused by a dense model, and
        # the residual stream is redundant across tp
        sizes = {"dp": 2, "tp": 2, "ep": 2}
        cfg = TransformerConfig(vocab=17, d_model=8, n_heads=4, d_head=4,
                                d_ff=8, seq_len=8, batch=4, n_experts=0)
    elif case == "moe_ep2":
        sizes = {"dp": 2, "tp": 2, "ep": 2}
        cfg = TransformerConfig(vocab=17, d_model=8, n_heads=4, d_head=4,
                                d_ff=8, seq_len=8, batch=4, n_experts=4,
                                moe_top_k=2)
    else:
        sizes = {"pp": 2, "sp": 2, "ep": 2}
        cfg = TransformerConfig(vocab=17, d_model=8, n_heads=4, d_head=4,
                                d_ff=8, seq_len=8, batch=4, n_experts=0,
                                n_stages=2, layers_per_stage=1, n_micro=2)
    devs = jax.devices("cpu")
    mesh = make_mesh(sizes=sizes, devices=devs[:int(np.prod(list(sizes.values())))])

    params = init_params(cfg, seed=3)
    rng = np.random.RandomState(7)
    tokens = rng.randint(0, cfg.vocab, size=(cfg.batch, cfg.seq_len)).astype(np.int32)
    labels = np.roll(tokens, -1, axis=1).astype(np.int32)

    loss_s, grads_s = _sharded_loss_and_grads(cfg, mesh, params, tokens, labels)
    blocks = (sizes.get("dp", 1), sizes.get("sp", 1))
    ref = jax.jit(jax.value_and_grad(
        lambda p: ref_loss(cfg, p, jnp.asarray(tokens), jnp.asarray(labels),
                           aux_blocks=blocks)))
    loss_r, grads_r = ref(params)

    np.testing.assert_allclose(float(loss_s), float(loss_r), rtol=1e-5)
    flat_s = jax.tree.leaves_with_path(grads_s)
    flat_r = dict(jax.tree.leaves_with_path(grads_r))
    assert flat_s and len(flat_s) == len(flat_r)
    for path, g in flat_s:
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(flat_r[path]), rtol=5e-4, atol=5e-5,
            err_msg=f"gradient mismatch at {jax.tree_util.keystr(path)}")
