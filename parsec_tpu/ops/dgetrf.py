"""Tile LU factorization without pivoting (dgetrf_nopiv) as a PTG graph.

The right-looking tile LU with the DPLASMA task classes GETRF / TRSM_L
(row panel, yields U(k,n)) / TRSM_U (column panel, yields L(m,k)) / GEMM
(trailing update) — the dataflow of DPLASMA's zgetrf_nopiv.jdf on the
reference runtime (SURVEY.md §2.6, §7.2-10). No pivoting: intended for
diagonally-dominant or otherwise LU-stable matrices, as in the reference's
nopiv variant.

The diagonal-tile kernel is a fully static-shape masked update loop
(ops.getrf_nopiv) so XLA compiles one executable per tile shape; panel and
trailing updates are triangular solves and one GEMM per tile — all
MXU-shaped.

On return descA holds unit-lower L strictly below the diagonal and U on
and above: A = L U (verify by reconstruction).
"""
from __future__ import annotations

import functools

import numpy as np

from ..collections.matrix import TiledMatrix
from ..dsl import ptg

DGETRF_JDF = """
descA [ type="collection" ]
MT [ type="int" ]
NT [ type="int" ]
KT [ type="int" ]

GETRF(k)

k = 0 .. KT-1

: descA( k, k )

RW A <- (k == 0) ? descA( k, k ) : C GEMM( k-1, k, k )
     -> descA( k, k )
     -> T TRSM_L( k, k+1 .. NT-1 )
     -> T TRSM_U( k, k+1 .. MT-1 )

; (KT - k) * 1000

BODY [type=tpu]
{
    A = ops.getrf_nopiv(A)
}
END

TRSM_L(k, n)

k = 0 .. KT-1
n = k+1 .. NT-1

: descA( k, n )

READ T <- A GETRF( k )
RW   C <- (k == 0) ? descA( k, n ) : C GEMM( k-1, k, n )
       -> descA( k, n )
       -> B GEMM( k, k+1 .. MT-1, n )

; (KT - k) * 100

BODY [type=tpu]
{
    C = ops.trsm_lower_unit(T, C)
}
END

TRSM_U(k, m)

k = 0 .. KT-1
m = k+1 .. MT-1

: descA( m, k )

READ T <- A GETRF( k )
RW   C <- (k == 0) ? descA( m, k ) : C GEMM( k-1, m, k )
       -> descA( m, k )
       -> A GEMM( k, m, k+1 .. NT-1 )

; (KT - k) * 100

BODY [type=tpu]
{
    C = ops.trsm_upper_right(T, C)
}
END

GEMM(k, m, n)

k = 0 .. KT-1
m = k+1 .. MT-1
n = k+1 .. NT-1

: descA( m, n )

READ A <- C TRSM_U( k, m )
READ B <- C TRSM_L( k, n )
RW   C <- (k == 0) ? descA( m, n ) : C GEMM( k-1, m, n )
       -> ((m == k+1) and (n == k+1)) ? A GETRF( k+1 )
       -> ((m == k+1) and (n > k+1)) ? C TRSM_L( k+1, n )
       -> ((m > k+1) and (n == k+1)) ? C TRSM_U( k+1, m )
       -> ((m > k+1) and (n > k+1)) ? C GEMM( k+1, m, n )

; (KT - k) * 10

BODY [type=tpu]
{
    C = ops.gemm_nn_sub(C, A, B)
}
END
"""

_factory = None


def dgetrf_factory() -> "ptg.JDFFactory":
    global _factory
    if _factory is None:
        _factory = ptg.compile_jdf(DGETRF_JDF, name="dgetrf_nopiv")
    return _factory


def dgetrf_nopiv_taskpool(A: TiledMatrix, rank: int = 0, nb_ranks: int = 1):
    from .. import ops as ops_module
    kt = min(A.mt, A.nt)
    # every diagonal tile must be square (triangular solves need a square
    # factor): square full tiles, and a square trailing tile if partial
    last_rows = A.lm - (kt - 1) * A.mb
    last_cols = A.ln - (kt - 1) * A.nb
    if A.mb != A.nb or min(last_rows, A.mb) != min(last_cols, A.nb):
        raise ValueError(
            f"dgetrf_nopiv needs square diagonal tiles; got mb={A.mb} "
            f"nb={A.nb}, trailing diagonal tile "
            f"{min(last_rows, A.mb)}x{min(last_cols, A.nb)}")
    tp = dgetrf_factory().new(descA=A, MT=A.mt, NT=A.nt, KT=kt,
                              rank=rank, nb_ranks=nb_ranks)
    tp.global_env["ops"] = ops_module
    return tp


def dgetrf_nopiv(context, A: TiledMatrix, rank: int = 0,
                 nb_ranks: int = 1) -> None:
    """Factor A = L U in place (no pivoting): unit-lower L strictly below
    the diagonal, U on and above. Blocking: enqueue + wait."""
    tp = dgetrf_nopiv_taskpool(A, rank=rank, nb_ranks=nb_ranks)
    context.add_taskpool(tp)
    context.wait()


def dgetrf(A: np.ndarray, nb: int = 256):
    """Blocked LU with partial pivoting: ``A = P L U`` (general matrices,
    no diagonal-dominance requirement — the DPLASMA dgetrf-parity op the
    nopiv PTG variant cannot cover).

    TPU-native design: pivoting's data-dependent row swaps do not fit an
    affine PTG, so this is a single jitted XLA program — LAPACK-grade
    panel factorization via ``lax.linalg.lu`` (XLA's pivoted LU custom
    call), triangular solves for the block row, and one large MXU GEMM
    per trailing update; the panel loop is unrolled at trace time
    (problem-size-static, like a captured taskpool).

    Returns ``(LU, piv)``: packed factors (unit-lower L strictly below
    the diagonal, U on/above) and the pivot ROW PERMUTATION vector —
    ``A[piv] == L @ U``.

    Compile-time caveat: the panel loop is unrolled at trace time, so
    trace+compile cost and program size grow linearly with
    ``kt = ceil(min(m, n)/nb)`` (each step carries O(N^2) gather/scatter
    updates). Keep kt modest (tens, not hundreds) — e.g. raise ``nb``
    with N; ``_dgetrf_jit``'s lru_cache only hides *repeat* costs per
    distinct (shape, nb, dtype).
    """
    LU, perm = _dgetrf_jit(A.shape, nb, np.dtype(A.dtype).name)(A)
    return LU, perm


@functools.lru_cache(maxsize=64)
def _dgetrf_jit(shape, nb: int, dtype_name: str):
    import jax
    import jax.numpy as jnp
    from jax import lax

    n_rows, n_cols = shape
    kt = (min(n_rows, n_cols) + nb - 1) // nb

    def fac(M):
        LU = M
        perm = jnp.arange(n_rows)
        for k in range(kt):
            k0 = k * nb
            # panel columns stop at the diagonal extent: for wide
            # matrices (n_rows < n_cols) the columns beyond row count
            # belong to the block row, not the factored panel
            k1 = min(k0 + nb, n_rows, n_cols)
            # panel: all rows below k0, this block column
            panel = LU[k0:, k0:k1]
            p_lu, p_piv, p_perm = lax.linalg.lu(panel)
            # apply the panel's row permutation to the whole trailing
            # rows (left factors + trailing columns) and the perm vector
            rows = LU[k0:]
            rows = rows.at[:, k0:k1].set(p_lu)
            rows = rows.at[:, :k0].set(rows[:, :k0][p_perm])
            rows = rows.at[:, k1:].set(rows[:, k1:][p_perm])
            LU = LU.at[k0:].set(rows)
            perm = perm.at[k0:].set(perm[k0:][p_perm])
            if k1 < n_cols:
                L11 = jnp.tril(LU[k0:k1, k0:k1], -1) + jnp.eye(
                    k1 - k0, dtype=M.dtype)
                U12 = lax.linalg.triangular_solve(
                    L11, LU[k0:k1, k1:], left_side=True, lower=True,
                    unit_diagonal=True)
                LU = LU.at[k0:k1, k1:].set(U12)
                if k1 < n_rows:
                    L21 = LU[k1:, k0:k1]
                    # true-f32 inputs (HIGHEST): unlike a lone GEMM,
                    # LU feeds each update into the next panel, so the
                    # MXU's default bf16-input pass compounds to ~1e-1
                    # relative error at n=4096 (measured)
                    acc = jnp.promote_types(M.dtype, jnp.float32)
                    LU = LU.at[k1:, k1:].add(
                        -jnp.matmul(L21, U12,
                                    precision=lax.Precision.HIGHEST,
                                    preferred_element_type=acc)
                        .astype(M.dtype))
        return LU, perm

    return jax.jit(fac)


def make_diag_dominant(m: int, n: int = None, dtype=np.float32,
                       seed: int = 0) -> np.ndarray:
    """A diagonally-dominant matrix — LU-stable without pivoting."""
    n = m if n is None else n
    rng = np.random.RandomState(seed)
    A = rng.rand(m, n).astype(np.float64) - 0.5
    for i in range(min(m, n)):
        A[i, i] = np.sum(np.abs(A[i])) + 1.0
    return A.astype(dtype)
