"""Tiled matrix collections: 2D block-cyclic and friends.

Reference behavior: ``parsec_tiled_matrix_t`` (mtype/storage/mb/nb/lm/ln,
submatrix view i,j,m,n, uplo — ref: parsec/data_dist/matrix/matrix.h:98-125)
with distributions: 2D block-cyclic over a P×Q grid with krows/kcols
cyclicity (ref: parsec/data_dist/matrix/two_dim_rectangle_cyclic.h:73,
grid_2Dcyclic.c), symmetric/triangular storage variant
(sym_two_dim_rectangle_cyclic.c), arbitrary per-tile rank table
(two_dim_tabular.c), and 1-D cyclic vector (vector_two_dim_cyclic.c).

TPU-native notes: tiles are host numpy arrays created lazily; the device
module stages them into HBM on demand. ``to_jax_array`` /
``from_jax_array`` bridge a whole collection to a sharded jax.Array for
interop with mesh-level compute (SURVEY.md §7.1 "interop view").
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from ..data.data import Data, data_new_with_payload
from ..data.datatype import Datatype
from .collection import DataCollection


class TiledMatrix(DataCollection):
    """Base tiled matrix: (mt × nt) tiles of (mb × nb) elements."""

    def __init__(self, lm: int, ln: int, mb: int, nb: int,
                 dtype=np.float32, nodes: int = 1, rank: int = 0,
                 uplo: str = "full") -> None:
        super().__init__(nodes, rank)
        assert uplo in ("full", "lower", "upper")
        self.lm, self.ln = lm, ln
        self.mb, self.nb = mb, nb
        self.mt = (lm + mb - 1) // mb
        self.nt = (ln + nb - 1) // nb
        self.dtype = np.dtype(dtype)
        self.uplo = uplo
        self.dtt = Datatype(self.dtype, (mb, nb))
        self._tiles: Dict[Tuple[int, int], Data] = {}
        self._tlock = threading.Lock()

    # -- tile geometry ------------------------------------------------------
    def tile_shape(self, m: int, n: int) -> Tuple[int, int]:
        """Edge tiles may be partial."""
        tm = self.mb if (m + 1) * self.mb <= self.lm else self.lm - m * self.mb
        tn = self.nb if (n + 1) * self.nb <= self.ln else self.ln - n * self.nb
        return tm, tn

    def tiles(self) -> Iterable[Tuple[int, int]]:
        for m in range(self.mt):
            for n in range(self.nt):
                if self.uplo == "lower" and n > m:
                    continue
                if self.uplo == "upper" and n < m:
                    continue
                yield (m, n)

    def local_tiles(self) -> Iterable[Tuple[int, int]]:
        return (t for t in self.tiles() if self.rank_of(*t) == self.rank)

    # -- DataCollection interface ------------------------------------------
    def data_key(self, m: int, n: int) -> Tuple[int, int]:
        return (m, n)

    def data_of(self, m: int, n: int) -> Data:
        assert 0 <= m < self.mt and 0 <= n < self.nt, f"tile ({m},{n}) out of range"
        if self.uplo == "lower":
            assert n <= m, f"tile ({m},{n}) outside lower storage"
        if self.uplo == "upper":
            assert n >= m, f"tile ({m},{n}) outside upper storage"
        with self._tlock:
            d = self._tiles.get((m, n))
            if d is None:
                payload = np.zeros(self.tile_shape(m, n), dtype=self.dtype)
                d = data_new_with_payload(payload, device_id=0,
                                          key=(id(self), m, n))
                d.collection = self
                d.mesh_coords = (m, n)   # chip placement within a rank's
                self._tiles[(m, n)] = d  # device mesh (mesh_position_of)
            return d

    def mesh_position_of(self, m: int, n: int,
                         grid: Tuple[int, int]) -> Tuple[int, int]:
        """Chip-grid position of tile (m, n) within the owning rank's
        DEVICE MESH (``device_mesh_shape``; ISSUE 6): one level below
        ``rank_of`` — ranks own tiles, chips within a rank's mesh own
        the rank's tiles.  Generic tiled matrices spread tiles
        round-robin over the chip grid."""
        gp, gq = grid
        return (m % gp, n % gq)

    # -- whole-matrix interop ----------------------------------------------
    def set_tile(self, m: int, n: int, values: np.ndarray) -> None:
        d = self.data_of(m, n)
        np.copyto(d.get_copy(0).payload, values)
        d.version_bump(0)

    def tile(self, m: int, n: int) -> np.ndarray:
        """Host view of the tile, synced from the newest device copy."""
        return self.data_of(m, n).sync_to_host().payload

    def to_numpy(self) -> np.ndarray:
        """Assemble the full (local) matrix; missing symmetric tiles are
        mirrored when uplo != full."""
        out = np.zeros((self.lm, self.ln), dtype=self.dtype)
        for m in range(self.mt):
            for n in range(self.nt):
                sm, sn = m * self.mb, n * self.nb
                tm, tn = self.tile_shape(m, n)
                if self.uplo == "lower" and n > m:
                    out[sm:sm + tm, sn:sn + tn] = self.tile(n, m).T[:tm, :tn]
                    continue
                if self.uplo == "upper" and n < m:
                    out[sm:sm + tm, sn:sn + tn] = self.tile(n, m).T[:tm, :tn]
                    continue
                out[sm:sm + tm, sn:sn + tn] = self.tile(m, n)
        return out

    def from_numpy(self, a: np.ndarray) -> "TiledMatrix":
        assert a.shape == (self.lm, self.ln)
        for (m, n) in self.tiles():
            sm, sn = m * self.mb, n * self.nb
            tm, tn = self.tile_shape(m, n)
            self.set_tile(m, n, a[sm:sm + tm, sn:sn + tn].astype(self.dtype))
        return self

    def to_jax_array(self, device=None):
        """Interop: materialize as one jax array (host assembles)."""
        import jax
        return jax.device_put(self.to_numpy(), device)


class TwoDimBlockCyclic(TiledMatrix):
    """P×Q block-cyclic with k-cyclicity
    (ref: parsec_matrix_block_cyclic_t, two_dim_rectangle_cyclic.h:73)."""

    def __init__(self, lm: int, ln: int, mb: int, nb: int,
                 P: int = 1, Q: int = 1, krows: int = 1, kcols: int = 1,
                 dtype=np.float32, nodes: Optional[int] = None, rank: int = 0,
                 uplo: str = "full") -> None:
        nodes = nodes if nodes is not None else P * Q
        assert P * Q <= nodes, f"grid {P}x{Q} needs {P*Q} ranks, have {nodes}"
        super().__init__(lm, ln, mb, nb, dtype, nodes, rank, uplo)
        self.P, self.Q = P, Q
        self.krows, self.kcols = krows, kcols

    def rank_of(self, m: int, n: int) -> int:
        pr = (m // self.krows) % self.P
        pc = (n // self.kcols) % self.Q
        return pr * self.Q + pc

    def mesh_position_of(self, m: int, n: int,
                         grid: Tuple[int, int]) -> Tuple[int, int]:
        """Block-cyclic over the chip grid in LOCAL block coordinates:
        a rank owns every P-th block row (Q-th block column), so
        dividing by the rank grid first makes the rank's consecutive
        local tiles land on consecutive chips — the same distribution
        ``rank_of`` applies one level up.  The effective executor grid
        is therefore (P*gp) x (Q*gq) without any rank seeing a foreign
        tile."""
        gp, gq = grid
        return ((m // self.krows // self.P) % gp,
                (n // self.kcols // self.Q) % gq)

    def vpid_of(self, m: int, n: int) -> int:
        return 0


class SymTwoDimBlockCyclic(TwoDimBlockCyclic):
    """Triangular/symmetric storage block-cyclic
    (ref: sym_two_dim_rectangle_cyclic.c)."""

    def __init__(self, lm: int, ln: int, mb: int, nb: int, uplo: str = "lower",
                 **kw) -> None:
        assert uplo in ("lower", "upper")
        super().__init__(lm, ln, mb, nb, uplo=uplo, **kw)


class TwoDimBlockCyclicBand(TwoDimBlockCyclic):
    """Band distribution: tiles within the band are distributed block-
    cyclically; out-of-band tiles have no storage
    (ref: two_dim_rectangle_cyclic_band.c)."""

    def __init__(self, lm: int, ln: int, mb: int, nb: int, band_size: int,
                 **kw) -> None:
        super().__init__(lm, ln, mb, nb, **kw)
        self.band_size = band_size

    def in_band(self, m: int, n: int) -> bool:
        return abs(m - n) < self.band_size

    def tiles(self):
        for (m, n) in super().tiles():
            if self.in_band(m, n):
                yield (m, n)

    def data_of(self, m: int, n: int) -> Data:
        assert self.in_band(m, n), f"tile ({m},{n}) outside band"
        return super().data_of(m, n)


class SymTwoDimBlockCyclicBand(TwoDimBlockCyclicBand):
    """Band + triangular storage: only in-band tiles on the stored side
    (ref: sym_two_dim_rectangle_cyclic_band.c)."""

    def __init__(self, lm: int, ln: int, mb: int, nb: int, band_size: int,
                 uplo: str = "lower", **kw) -> None:
        assert uplo in ("lower", "upper")
        super().__init__(lm, ln, mb, nb, band_size, uplo=uplo, **kw)


class TwoDimTabular(TiledMatrix):
    """Arbitrary per-tile rank table (ref: two_dim_tabular.c)."""

    def __init__(self, lm: int, ln: int, mb: int, nb: int,
                 rank_table: np.ndarray, **kw) -> None:
        super().__init__(lm, ln, mb, nb, **kw)
        rank_table = np.asarray(rank_table)
        assert rank_table.shape == (self.mt, self.nt), \
            f"rank table {rank_table.shape} != tile grid {(self.mt, self.nt)}"
        self.rank_table = rank_table

    def rank_of(self, m: int, n: int) -> int:
        return int(self.rank_table[m, n])

    @staticmethod
    def random(lm, ln, mb, nb, nodes: int, seed: int = 0, **kw) -> "TwoDimTabular":
        mt, nt = (lm + mb - 1) // mb, (ln + nb - 1) // nb
        rng = np.random.RandomState(seed)
        table = rng.randint(0, nodes, size=(mt, nt))
        return TwoDimTabular(lm, ln, mb, nb, table, nodes=nodes, **kw)


class VectorTwoDimCyclic(TiledMatrix):
    """1-D cyclic vector of segments (ref: vector_two_dim_cyclic.c)."""

    def __init__(self, lm: int, mb: int, P: int = 1, dtype=np.float32,
                 nodes: Optional[int] = None, rank: int = 0) -> None:
        nodes = nodes if nodes is not None else P
        super().__init__(lm, 1, mb, 1, dtype, nodes, rank)
        self.P = P

    def rank_of(self, m: int, n: int = 0) -> int:
        return m % self.P

    def data_of(self, m: int, n: int = 0) -> Data:
        return super().data_of(m, 0)
