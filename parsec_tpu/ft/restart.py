"""Checkpoint-integrated restart: the recovery pillar of ft/.

``utils/checkpoint`` gives quiescent-point snapshots (a consistent
per-rank tile dump between taskpools); this module adds the POLICY that
turns snapshots into recovery: run a sequence of taskpool stages,
snapshot every K completed stages, and on failure either abort cleanly
(the pre-ft behavior, now guaranteed rather than best-effort) or roll
the collections back to the last snapshot and re-run from there, with
bounded, exponentially backed-off retries.

Scope: rollback-and-retry recovers IN PROCESS from transient faults
(an injected task fault, a failed send that aborted one stage) on
SINGLE-RANK contexts. A hard rank loss (``RankFailedError``, or this
rank's own ``InjectedKill``) cannot be re-run inside the same comm
world — the dead rank is gone (or IS us) — and on a multi-rank run
even a transient fault aborts: rollback is a local act the surviving
peers cannot observe, so a lone retry would leave them waiting on the
original taskpool forever. In both cases the driver aborts after
restoring a consistent snapshot set; a fresh incarnation of the job
(relaunched processes, or a fresh fabric in tests) then calls
:func:`run_with_restart` with ``resume_from`` pointing at the same
prefix and continues from the last completed stage. Either way the
guarantee is the same: the ON-DISK snapshot set is always a consistent
stage boundary, never a half-written DAG (the abort path also rolls
the in-memory collections back best-effort).

Policy grammar (``--mca ft_restart_policy``)::

    abort                              # snapshot, but never retry
    restart:retries=2:backoff=0.25:every=1

`every=K` snapshots after every K completed stages (the last stage is
always snapshotted).
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Sequence

from ..utils import checkpoint as ckpt
from ..utils import logging as plog
from ..utils.params import params

__all__ = ["RestartPolicy", "run_with_restart"]


class RestartPolicy:
    """mode="abort" | "restart"; retries/backoff/every as in the
    module docstring."""

    def __init__(self, mode: str = "restart", retries: int = 2,
                 backoff: float = 0.25, every: int = 1) -> None:
        if mode not in ("abort", "restart"):
            raise ValueError(f"unknown restart mode {mode!r}")
        if every < 1:
            raise ValueError("snapshot cadence `every` must be >= 1")
        self.mode = mode
        self.retries = int(retries)
        self.backoff = float(backoff)
        self.every = int(every)

    @classmethod
    def parse(cls, spec: str) -> "RestartPolicy":
        parts = [p for p in spec.strip().split(":") if p]
        if not parts:
            return cls()
        kw: Dict[str, Any] = {"mode": parts[0]}
        for kv in parts[1:]:
            k, v = kv.split("=", 1)
            if k == "retries":
                kw["retries"] = int(v)
            elif k == "backoff":
                kw["backoff"] = float(v)
            elif k == "every":
                kw["every"] = int(v)
            else:
                raise ValueError(
                    f"ft_restart_policy: unknown key {k!r}")
        return cls(**kw)

    @classmethod
    def from_params(cls) -> "RestartPolicy":
        spec = str(params.get("ft_restart_policy") or "").strip()
        return cls.parse(spec) if spec else cls()

    def __repr__(self) -> str:  # pragma: no cover
        return (f"RestartPolicy({self.mode}, retries={self.retries}, "
                f"backoff={self.backoff}, every={self.every})")


def _stage_prefix(prefix: str, stage: int) -> str:
    return f"{prefix}.stage{stage}"


def _save(collections: Sequence[Any], prefix: str, stage: int,
          context: Any) -> None:
    for i, coll in enumerate(collections):
        ckpt.save_collection(coll, f"{_stage_prefix(prefix, stage)}.c{i}",
                             context=context)


def _restore(collections: Sequence[Any], prefix: str, stage: int) -> None:
    for i, coll in enumerate(collections):
        ckpt.restore_collection(coll, f"{_stage_prefix(prefix, stage)}.c{i}")


def run_with_restart(ctx: Any, stages: Sequence[Callable[[], Any]],
                     collections: Sequence[Any], prefix: str,
                     policy: Optional[RestartPolicy] = None,
                     resume_from: Optional[int] = None) -> Dict[str, Any]:
    """Run ``stages`` (zero-arg factories, each returning a FRESH
    taskpool — a taskpool object cannot be re-enqueued) under the
    snapshot/rollback policy. ``collections`` is the application state
    the stages mutate; ``prefix`` names the snapshot files
    (``<prefix>.stage<k>.c<i>.rank<r>.npz``).

    Returns ``{"stages", "retries", "snapshots", "last_snapshot"}``.
    ``resume_from=k`` skips the initial snapshot, restores the stage-k
    snapshot set, and continues with stage k — the fresh-incarnation
    entry point after a hard rank loss.
    """
    policy = policy or RestartPolicy.from_params()
    n = len(stages)
    retries_total = snapshots = 0
    if resume_from is None:
        _save(collections, prefix, 0, ctx)
        snapshots += 1
        i = last_snap = 0
    else:
        _restore(collections, prefix, resume_from)
        i = last_snap = resume_from
    # per-STAGE attempt counters: with every>1 a rollback replays
    # earlier (succeeding) stages, and a single shared counter reset on
    # their completion would let a persistently failing stage retry
    # forever with the backoff stuck at its first step
    attempts: Dict[int, int] = {}
    while i < n:
        try:
            tp = stages[i]()
            ctx.add_taskpool(tp)
            ctx.wait()
        except Exception as exc:  # noqa: BLE001 - the policy decides
            root = exc.__cause__ or exc
            from ..comm.engine import RankFailedError
            from .inject import InjectedKill
            # hard = unrecoverable in this incarnation: a peer is gone
            # (RankFailedError) or THIS rank was killed (InjectedKill —
            # its engine is permanently dark; retrying a stage on it
            # would hang termdet, the exact failure ft/ exists to stop)
            hard = isinstance(root, (RankFailedError, InjectedKill))
            # in-world rollback is a LOCAL act: on a multi-rank run the
            # peers saw no error and keep waiting on the original
            # taskpool (whose wire id a lone re-registration would
            # shift), so an uncoordinated retry deadlocks them — on
            # multi-rank, every failure aborts to a consistent snapshot
            # and recovery is a fresh incarnation (resume_from)
            multi = int(getattr(ctx, "nb_ranks", 1) or 1) > 1
            attempt = attempts[i] = attempts.get(i, 0) + 1
            if policy.mode == "abort" or hard or multi \
                    or attempt > policy.retries:
                # guaranteed-clean abort: errors drained, scheduler
                # queues flushed, the last snapshot still consistent —
                # a fresh incarnation resumes with resume_from=last_snap
                ctx.clear_task_errors()
                # best-effort in-memory rollback too, so a caller that
                # catches the abort never sees half-mutated tiles; the
                # ON-DISK snapshot set is the hard guarantee (a failed
                # restore must not mask the original error)
                try:
                    _restore(collections, prefix, last_snap)
                except Exception:  # noqa: BLE001  pragma: no cover
                    plog.warning("ft.restart: in-memory rollback to "
                                 "snapshot %d failed; on-disk snapshots "
                                 "remain authoritative", last_snap)
                why = (" — hard rank loss" if hard else
                       " — in-world retry unsupported on multi-rank "
                       "runs (peers cannot observe this rank's "
                       "rollback)" if multi and policy.mode != "abort"
                       else "")
                plog.warning(
                    "ft.restart: aborting at stage %d after %d "
                    "attempt(s) (%s%s); resume_from=%d", i, attempt,
                    type(root).__name__, why, last_snap)
                raise
            delay = policy.backoff * (2 ** (attempt - 1))
            plog.warning(
                "ft.restart: stage %d failed (%s: %s) — rolling back "
                "to snapshot %d, retry %d/%d in %.2fs", i,
                type(root).__name__, root, last_snap, attempt,
                policy.retries, delay)
            retries_total += 1
            time.sleep(delay)
            ctx.clear_task_errors()
            _restore(collections, prefix, last_snap)
            i = last_snap
            continue
        i += 1
        if (i - last_snap) >= policy.every or i == n:
            _save(collections, prefix, i, ctx)
            snapshots += 1
            last_snap = i
    return {"stages": n, "retries": retries_total,
            "snapshots": snapshots, "last_snapshot": last_snap}
