"""MCA parameter system tests (ref: parsec/utils/mca_param.c behavior)."""
import os

import pytest

from parsec_tpu.utils.params import ParamRegistry


@pytest.fixture
def reg():
    return ParamRegistry()


def test_default_resolution(reg):
    reg.reg_int("x", 7)
    assert reg.get("x") == 7
    assert reg.source("x") == "default"


def test_env_overrides_default(reg, monkeypatch):
    reg.reg_int("window", 100)
    monkeypatch.setenv("PARSEC_MCA_window", "42")
    assert reg.get("window") == 42
    assert reg.source("window") == "env"


def test_cmdline_overrides_env(reg, monkeypatch):
    reg.reg_string("sched", "lfq")
    monkeypatch.setenv("PARSEC_MCA_sched", "gd")
    rest = reg.parse_argv(["prog", "--mca", "sched", "ap", "positional"])
    assert rest == ["prog", "positional"]
    assert reg.get("sched") == "ap"
    assert reg.source("sched") == "cmdline"


def test_parse_argv_forms(reg):
    reg.reg_int("a", 0)
    reg.reg_int("b", 0)
    rest = reg.parse_argv(["--mca=a=1", "--parsec", "b=2", "keep"])
    assert rest == ["keep"]
    assert reg.get("a") == 1 and reg.get("b") == 2


def test_typed_coercion(reg, monkeypatch):
    reg.reg_bool("flag", False)
    reg.reg_sizet("sz", 0)
    monkeypatch.setenv("PARSEC_MCA_flag", "yes")
    monkeypatch.setenv("PARSEC_MCA_sz", "0x100")
    assert reg.get("flag") is True
    assert reg.get("sz") == 256


def test_sizet_rejects_negative(reg):
    reg.reg_sizet("n", 0)
    reg.set_cmdline("n", "-5")
    with pytest.raises(ValueError):
        reg.get("n")


def test_unknown_param_raises(reg):
    with pytest.raises(KeyError):
        reg.get("nope")


def test_file_values(reg, tmp_path, monkeypatch):
    conf = tmp_path / "mca.conf"
    conf.write_text("# comment\nfoo = 13\n")
    monkeypatch.setenv("PARSEC_SYSCONF_PARAMS", str(conf))
    reg.reg_int("foo", 1)
    assert reg.get("foo") == 13
    assert reg.source("foo") == "file"
