"""parsec_tpu — a TPU-native task-based runtime with the capabilities of
PaRSEC (DAG scheduling, PTG + DTD DSLs, tiled distributed collections,
asynchronous dataflow communication, per-device task incarnations,
tracing/profiling), designed for JAX/XLA/Pallas/PJRT rather than ported.

Public API mirrors the reference's surface (parsec/runtime.h):

    ctx = parsec_tpu.init(nb_cores=4)
    tp = parsec_tpu.dtd.taskpool_new()
    ctx.add_taskpool(tp)
    tp.insert_task(body, (tile, parsec_tpu.dtd.INOUT))
    tp.wait()
    ctx.fini()
"""
from .runtime.context import Context, init
from .runtime.compound import CompoundTaskpool, compose
from .runtime.recursive import recursive_call
from .runtime.taskpool import (Chore, Dep, Flow, HookReturn, Task, TaskClass,
                               Taskpool, TaskStatus)
from .data.data import Coherency, Data, DataCopy, FlowAccess, data_new_with_payload
from .data.datatype import Datatype, dtt_of_array
from .data.arena import Arena
from .utils.params import params
from . import dsl
from . import obs
from .dsl import dtd

__version__ = "0.1.0"

__all__ = [
    "Context", "init", "Taskpool", "TaskClass", "Task", "Chore", "Flow",
    "Dep", "HookReturn", "TaskStatus", "Data", "DataCopy", "Coherency",
    "FlowAccess", "Datatype", "Arena", "params", "dtd", "dsl", "obs",
    "CompoundTaskpool", "compose", "recursive_call",
    "data_new_with_payload", "dtt_of_array", "__version__",
]
