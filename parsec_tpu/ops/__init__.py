"""Tile kernels (XLA/Pallas executables for task BODYs) and tile
algorithms (dpotrf, dgeqrf, dgetrf_nopiv, pdgemm)."""
from .linalg import (axpy, gemm, gemm_nn, gemm_nn_sub, gemm_nt,
                     gemm_tn_sub, geqrt, geqrt_r, getrf_nopiv, potrf, scal,
                     syrk_ln, transpose, trsm_lower, trsm_lower_trans,
                     trsm_lower_unit, trsm_panel, trsm_upper_right, tsmqr,
                     tsqrt, tsqrt_r, unmqr)
from . import dpotrf as dpotrf_module
from .dpotrf import dpotrf, dpotrf_factory, dpotrf_taskpool, make_spd
from .dgeqrf import dgeqrf, dgeqrf_factory, dgeqrf_taskpool
from .inverse import dgesv, dgetrs, dlauum, dpotri, dtrtri
from .dgetrf import (dgetrf, dgetrf_factory, dgetrf_nopiv, dgetrf_nopiv_taskpool,
                     make_diag_dominant)
from .pdgemm import pdgemm, pdgemm_factory, pdgemm_taskpool
from .dtrsm import (dposv, dtrsm_lower_taskpool, dtrsm_lower_trans_taskpool)

try:  # pallas.tpu is optional at import time (older/partial jax builds)
    from . import pallas_kernels
    from .pallas_kernels import flash_attention
except ImportError:  # pragma: no cover
    pallas_kernels = None
    flash_attention = None

__all__ = ["potrf", "trsm_panel", "syrk_ln", "gemm_nt", "gemm_nn",
           "gemm_nn_sub", "gemm", "axpy", "scal", "transpose",
           "geqrt", "geqrt_r", "unmqr", "tsqrt", "tsqrt_r", "tsmqr",
           "getrf_nopiv", "trsm_lower_unit", "trsm_upper_right",
           "dpotrf", "dpotrf_factory", "dpotrf_taskpool", "make_spd",
           "dgeqrf", "dgeqrf_factory", "dgeqrf_taskpool",
           "dgetrf", "dgetrf_nopiv", "dgetrf_nopiv_taskpool", "dgetrf_factory",
           "dtrtri", "dlauum", "dpotri", "dgetrs", "dgesv",
           "make_diag_dominant",
           "pdgemm", "pdgemm_factory", "pdgemm_taskpool",
           "dposv", "dtrsm_lower_taskpool", "dtrsm_lower_trans_taskpool",
           "trsm_lower", "trsm_lower_trans", "gemm_tn_sub",
           "pallas_kernels", "flash_attention"]
