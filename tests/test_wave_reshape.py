"""Wave-mode reshape/NEW parity: the SAME JDF run through the per-task
runtime and through wave execution must leave identical collection
state (round-2 VERDICT item 5 — the wave-servable subset of
tests/test_reshape_parity.py scenarios; ref: parsec_reshape.c).
"""
import numpy as np
import pytest

import parsec_tpu
from parsec_tpu.collections import TwoDimBlockCyclic
from parsec_tpu.dsl import ptg
from parsec_tpu.dsl.ptg.wave import WaveError, WaveRunner

N = 8
NB = 4


def _base():
    return (np.arange(N * N, dtype=np.float32).reshape(N, N) + 1.0) / 7.0


def _run_runtime(fac, base, **globals_):
    ctx = parsec_tpu.init(nb_cores=1)
    try:
        coll = TwoDimBlockCyclic(N, N, NB, NB, dtype=np.float32)
        coll.name = "descA"
        coll.from_numpy(base.copy())
        tp = fac.new(descA=coll, **globals_)
        ctx.add_taskpool(tp)
        ctx.wait()
        return coll.to_numpy()
    finally:
        ctx.fini()


def _run_wave(fac, base, **globals_):
    coll = TwoDimBlockCyclic(N, N, NB, NB, dtype=np.float32)
    coll.name = "descA"
    coll.from_numpy(base.copy())
    WaveRunner(fac.new(descA=coll, **globals_)).run()
    return coll.to_numpy()


def _assert_parity(jdf, name, **globals_):
    fac = ptg.compile_jdf(jdf, name=name)
    base = _base()
    ref = _run_runtime(fac, base, **globals_)
    got = _run_wave(fac, base, **globals_)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
    return ref


MASKED_RW = """
descA [ type="collection" ]
NT [ type="int" ]

T(m)
m = 0 .. NT-1
: descA( m, m )
RW A <- descA( m, m )    [type_data=lower]
     -> descA( m, m )    [type_data=lower]
BODY
{
    A = A * 3.0 + 1.0
}
END
"""


def test_masked_type_data_rw_parity():
    ref = _assert_parity(MASKED_RW, "masked_rw", NT=N // NB)
    # sanity vs hand-computed: lower transformed, upper preserved
    base = _base()
    for m in range(N // NB):
        sl = slice(m * NB, (m + 1) * NB)
        tri = np.tril(np.ones((NB, NB), bool))
        exp = np.where(tri, np.tril(base[sl, sl]) * 3.0 + 1.0, base[sl, sl])
        np.testing.assert_allclose(ref[sl, sl], exp, rtol=1e-5)


INPUT_CONV_CHAIN = """
descA [ type="collection" ]

READ_L(k)
k = 0 .. 0
: descA( 0, 0 )
RW A <- descA( 0, 0 )    [type=upper]
     -> L USE( 0 )
BODY
{
    A = A + 0.5
}
END

USE(k)
k = 0 .. 0
: descA( 0, 1 )
RW B <- descA( 0, 1 )
     -> descA( 0, 1 )
READ L <- A READ_L( 0 )
BODY
{
    B = B + L
}
END
"""


def test_input_type_conversion_feeds_successor_parity():
    """[type=upper] on an input: the consumer of the flow sees the
    converted (masked) value the producer's body worked on."""
    _assert_parity(INPUT_CONV_CHAIN, "inconv")


NEW_CHAIN = """
descA [ type="collection" ]
NT [ type="int" ]

GEN(k)
k = 0 .. NT-1
: descA( k, 0 )
RW S <- NEW              [shape=4x4 dtype=float32]
     -> S USE( k )
BODY
{
    S = S + (k + 1.0)
}
END

USE(k)
k = 0 .. NT-1
: descA( k, 0 )
RW A <- descA( k, 0 )
     -> descA( k, 0 )
READ S <- S GEN( k )
BODY
{
    A = A + S
}
END
"""


def test_new_scratch_forwarded_parity():
    """NEW scratch written by a producer and consumed downstream: wave
    serves it from per-class scratch pools."""
    ref = _assert_parity(NEW_CHAIN, "newchain", NT=N // NB)
    base = _base()
    for k in range(N // NB):
        sl = slice(k * NB, (k + 1) * NB)
        np.testing.assert_allclose(ref[sl, 0:NB], base[sl, 0:NB] + (k + 1.0),
                                   rtol=1e-5)


NONUNIFORM = """
descA [ type="collection" ]
NT [ type="int" ]

T(m)
m = 0 .. NT-1
: descA( m, m )
RW A <- (m == 0) ? descA( m, m ) [type_data=lower]
     <- descA( m, m )            [type_data=upper]
     -> descA( m, m )
BODY
{
    A = A * 2.0
}
END
"""


def test_nonuniform_types_rejected():
    """Per-instance [type*] variation can't ride per-class kernels —
    must be refused loudly (the general runtime serves it)."""
    fac = ptg.compile_jdf(NONUNIFORM, name="nonuni")
    coll = TwoDimBlockCyclic(N, N, NB, NB, dtype=np.float32)
    coll.name = "descA"
    coll.from_numpy(_base())
    with pytest.raises(WaveError, match="vary across instances"):
        WaveRunner(fac.new(descA=coll, NT=N // NB))


def test_dist_wave_masked_writeback():
    """Masked writebacks also work distributed: the exchanged tile is
    the post-merge pool value."""
    from test_comm_multirank import spmd

    fac = ptg.compile_jdf(MASKED_RW, "masked_dist")
    base = _base()

    def run(rank, fabric):
        ce = fabric.engine(rank)
        coll = TwoDimBlockCyclic(N, N, NB, NB, dtype=np.float32,
                                 P=2, Q=1, nodes=2, rank=rank)
        coll.name = "descA"
        coll.from_numpy(base.copy())
        tp = fac.new(descA=coll, NT=N // NB, rank=rank, nb_ranks=2)
        w = ptg.wave(tp, comm=ce)
        w.run()
        out = {}
        for (i, j) in coll.tiles():
            if coll.rank_of(i, j) == rank:
                out[(i, j)] = np.asarray(
                    coll.data_of(i, j).sync_to_host().payload).copy()
        return out

    results, _ = spmd(2, run)
    got = {}
    for r in results:
        got.update(r)
    tri = np.tril(np.ones((NB, NB), bool))
    for m in range(N // NB):
        sl = slice(m * NB, (m + 1) * NB)
        exp = np.where(tri, np.tril(base[sl, sl]) * 3.0 + 1.0, base[sl, sl])
        np.testing.assert_allclose(got[(m, m)], exp, rtol=1e-5)


GUARDED_WB = """
descA [ type="collection" ]
descB [ type="collection" ]
NT [ type="int" ]

T(m)
m = 0 .. NT-1
: descA( m, m )
RW A <- descA( m, m )
     -> (m == 0) ? descA( m, m )   [type_data=lower]
     -> L C( m )
BODY
{
    A = A * 2.0
}
END

C(m)
m = 0 .. NT-1
: descB( m, 0 )
RW B <- descB( m, 0 )
     -> descB( m, 0 )
READ L <- A T( m )
BODY
{
    B = L
}
END
"""


def test_guarded_masked_writeback_only_where_declared():
    """Only the instance whose guarded out-dep RESOLVES gets the masked
    merge; the others' successors see the FULL body output and their
    home tile follows the runtime's in-place semantics (regression:
    the per-class wb mask used to apply to every instance)."""
    fac = ptg.compile_jdf(GUARDED_WB, name="guardedwb")
    base = _base()

    def run(cls):
        dA = TwoDimBlockCyclic(N, N, NB, NB, dtype=np.float32)
        dB = TwoDimBlockCyclic(N, N, NB, NB, dtype=np.float32)
        dA.name, dB.name = "descA", "descB"
        dA.from_numpy(base.copy())
        dB.from_numpy(np.zeros((N, N), np.float32))
        if cls == "wave":
            WaveRunner(fac.new(descA=dA, descB=dB, NT=N // NB)).run()
        else:
            ctx = parsec_tpu.init(nb_cores=1)
            try:
                ctx.add_taskpool(fac.new(descA=dA, descB=dB, NT=N // NB))
                ctx.wait()
            finally:
                ctx.fini()
        return dA.to_numpy(), dB.to_numpy()

    refA, refB = run("runtime")
    gotA, gotB = run("wave")
    np.testing.assert_allclose(gotA, refA, rtol=1e-5)
    np.testing.assert_allclose(gotB, refB, rtol=1e-5)
    # hand-computed: EVERY consumer sees the FULL body output (the
    # runtime hands successors the clone, not the memory merge), while
    # descA(0,0) memory keeps its upper region (masked writeback) and
    # m>0 home tiles are mutated in place (shared-copy semantics)
    tri = np.tril(np.ones((NB, NB), bool))
    for m in range(N // NB):
        sl = slice(m * NB, (m + 1) * NB)
        np.testing.assert_allclose(gotB[sl, 0:NB], 2.0 * base[sl, sl],
                                   rtol=1e-5)
        expA = (np.where(tri, 2.0 * base[sl, sl], base[sl, sl]) if m == 0
                else 2.0 * base[sl, sl])
        np.testing.assert_allclose(gotA[sl, sl], expA, rtol=1e-5)


def test_wave_dgeqrf_scratch_flows_parity():
    """QR's WRITE scratch flows (expression shapes, forwarded T factors)
    through wave vs the per-task runtime — the heaviest in-tree user of
    the NEW/scratch support."""
    from parsec_tpu.ops import dgeqrf_taskpool

    n, nb = 256, 64
    rng = np.random.RandomState(3)
    Am = rng.rand(n, n).astype(np.float32)

    def run(which):
        A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(
            Am.copy())
        tp = dgeqrf_taskpool(A)
        if which == "wave":
            WaveRunner(tp).run()
        else:
            ctx = parsec_tpu.init(nb_cores=1)
            try:
                ctx.add_taskpool(tp)
                ctx.wait()
            finally:
                ctx.fini()
        return A.to_numpy()

    ref = run("runtime")
    got = run("wave")
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
    # R agrees with LAPACK's up to column signs
    Rref = np.linalg.qr(Am.astype(np.float64))[1]
    np.testing.assert_allclose(np.abs(np.diag(np.triu(got))),
                               np.abs(np.diag(Rref)), rtol=1e-3)


# --------------------------------------------------------------------- #
# TURBO differential: the same reshape/NEW scenarios through the native #
# per-task loop (turbo inherits wave's slot + kernel machinery at       #
# chunk size 1 — its semantics must match the classic runtime too)     #
# --------------------------------------------------------------------- #
def _run_turbo(fac, base, **globals_):
    from parsec_tpu.dsl.ptg.turbo import TurboRunner

    coll = TwoDimBlockCyclic(N, N, NB, NB, dtype=np.float32)
    coll.name = "descA"
    coll.from_numpy(base.copy())
    TurboRunner(fac.new(descA=coll, **globals_)).run()
    return coll.to_numpy()


@pytest.mark.parametrize("jdf,name,globals_", [
    (MASKED_RW, "masked_rw_t", {"NT": N // NB}),
    (INPUT_CONV_CHAIN, "inconv_t", {}),
    (NEW_CHAIN, "newchain_t", {"NT": N // NB}),
    (GUARDED_WB, "guardedwb_t", None),
])
def test_turbo_reshape_parity(jdf, name, globals_):
    fac = ptg.compile_jdf(jdf, name=name)
    base = _base()
    if globals_ is None:
        # GUARDED_WB binds a second collection (mirror the original test)
        ctx = parsec_tpu.init(nb_cores=1)
        try:
            dA = TwoDimBlockCyclic(N, N, NB, NB, dtype=np.float32)
            dB = TwoDimBlockCyclic(N, N, NB, NB, dtype=np.float32)
            dA.name, dB.name = "descA", "descB"
            dA.from_numpy(base.copy())
            dB.from_numpy(base.copy())
            tp = fac.new(descA=dA, descB=dB, NT=N // NB)
            ctx.add_taskpool(tp)
            ctx.wait()
            ref = (dA.to_numpy(), dB.to_numpy())
        finally:
            ctx.fini()
        from parsec_tpu.dsl.ptg.turbo import TurboRunner
        dA2 = TwoDimBlockCyclic(N, N, NB, NB, dtype=np.float32)
        dB2 = TwoDimBlockCyclic(N, N, NB, NB, dtype=np.float32)
        dA2.name, dB2.name = "descA", "descB"
        dA2.from_numpy(base.copy())
        dB2.from_numpy(base.copy())
        TurboRunner(fac.new(descA=dA2, descB=dB2, NT=N // NB)).run()
        np.testing.assert_allclose(dA2.to_numpy(), ref[0], rtol=1e-5,
                                   atol=1e-6)
        np.testing.assert_allclose(dB2.to_numpy(), ref[1], rtol=1e-5,
                                   atol=1e-6)
        return
    ref = _run_runtime(fac, base, **globals_)
    got = _run_turbo(fac, base, **globals_)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
