"""Tile Cholesky (the north-star workload) correctness tests."""
import numpy as np
import pytest

import parsec_tpu
from parsec_tpu.collections import TwoDimBlockCyclic
from parsec_tpu.ops import dpotrf, dpotrf_taskpool, make_spd


@pytest.mark.parametrize("n,nb", [(64, 64), (128, 32), (192, 64), (100, 32)])
def test_dpotrf_numerics(ctx, n, nb):
    """L L^T must reconstruct A, including partial edge tiles (100/32)."""
    M = make_spd(n)
    A = TwoDimBlockCyclic(n, n, nb, nb, dtype=np.float32).from_numpy(M)
    tp = dpotrf_taskpool(A)
    ctx.add_taskpool(tp)
    ctx.wait()
    assert tp.completed
    nt = A.nt
    assert tp.nb_local_tasks == nt + 2 * (nt * (nt - 1) // 2) + \
        (nt * (nt - 1) * (nt - 2) // 6)
    L = np.tril(A.to_numpy())
    np.testing.assert_allclose(L @ L.T, M, atol=5e-4)


def test_dpotrf_matches_numpy(ctx):
    M = make_spd(96)
    A = TwoDimBlockCyclic(96, 96, 32, 32, dtype=np.float32).from_numpy(M)
    tp = dpotrf_taskpool(A)
    ctx.add_taskpool(tp)
    ctx.wait()
    L = np.tril(A.to_numpy())
    Lref = np.linalg.cholesky(M.astype(np.float64))
    np.testing.assert_allclose(L, Lref, atol=5e-4)


def test_dpotrf_runs_on_device(ctx4):
    M = make_spd(128)
    A = TwoDimBlockCyclic(128, 128, 32, 32, dtype=np.float32).from_numpy(M)
    tp = dpotrf_taskpool(A)
    ctx4.add_taskpool(tp)
    ctx4.wait()
    devs = [d for d in ctx4.devices if d.device_type == "tpu"]
    assert sum(d.stats["tasks"] for d in devs) == tp.nb_local_tasks
    L = np.tril(A.to_numpy())
    np.testing.assert_allclose(L @ L.T, M, atol=5e-4)
