"""Tile linear-algebra kernels: the BODY payloads of dense tile algorithms.

The reference delegates tile kernels to BLAS/LAPACK (DPLASMA sits on top of
the runtime; tests use hand-rolled GEMMs, e.g. dtd_test_simple_gemm.c).
Here each kernel is a jax-jit executable — XLA fuses scale/add into the
matmul and keeps the MXU fed; jit caches one executable per (shape, dtype)
so steady-state dispatch is a cache hit.

All kernels are functional (return new arrays) to match the device module's
stage-out convention; bf16 accumulation is avoided by pinning
``preferred_element_type`` to f32.
"""
from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.scipy.linalg import solve_triangular as _solve_tri


@jax.jit
def potrf(t: Any) -> Any:
    """Cholesky of one diagonal tile: T = chol_L(T)."""
    return jnp.linalg.cholesky(t)


@jax.jit
def trsm_panel(t: Any, c: Any) -> Any:
    """Right-looking panel solve: C <- C * T^{-T} with T lower triangular
    (L[m,k] = A[m,k] L[k,k]^{-T})."""
    return _solve_tri(t, c.T, lower=True).T


@jax.jit
def syrk_ln(t: Any, a: Any) -> Any:
    """T <- T - A A^T (lower, no-transpose SYRK)."""
    return t - jnp.dot(a, a.T, preferred_element_type=jnp.float32)


@jax.jit
def gemm_nt(c: Any, a: Any, b: Any) -> Any:
    """C <- C - A B^T."""
    return c - jnp.dot(a, b.T, preferred_element_type=jnp.float32)


@jax.jit
def gemm_nn(c: Any, a: Any, b: Any) -> Any:
    """C <- C + A B."""
    return c + jnp.dot(a, b, preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnums=(3, 4))
def gemm(c: Any, a: Any, b: Any, alpha: float = 1.0, beta: float = 1.0) -> Any:
    """C <- beta*C + alpha*A@B (general tile GEMM)."""
    return beta * c + alpha * jnp.dot(a, b, preferred_element_type=jnp.float32)


@jax.jit
def axpy(y: Any, x: Any, alpha: float = 1.0) -> Any:
    return y + alpha * x


@jax.jit
def scal(x: Any, alpha: float) -> Any:
    return alpha * x


@jax.jit
def transpose(x: Any) -> Any:
    return x.T
