"""Metric-name doc-drift gate (ISSUE 15 satellite): every ``PARSEC::*``
metric-name constant exported by ``obs/spans.py`` (and the histogram
names in ``obs/metrics.py``) must appear in docs/guide.md §9 — PR 13/14
added gauges fast, and an undocumented name is how the table rots.

Matching accepts the guide's established shorthand: either the FULL
name appears, or its family prefix (everything before the last ``::``)
AND its final segment both do (the "`PARSEC::COMM::BYTES_SENT` /
`BYTES_RECEIVED`" row style).
"""
import os
import re

_GUIDE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "docs", "guide.md")


def _section9():
    with open(_GUIDE) as fh:
        guide = fh.read()
    i = guide.index("## 9. Observability")
    j = guide.index("## 10.")
    return guide[i:j]


def _exported_names():
    import parsec_tpu.obs.metrics as metrics
    import parsec_tpu.obs.spans as spans

    names = {}
    for mod in (spans, metrics):
        for attr, val in vars(mod).items():
            if isinstance(val, str) and val.startswith("PARSEC::"):
                names[f"{mod.__name__.rsplit('.', 1)[1]}.{attr}"] = val
    return names


def test_every_exported_metric_name_is_documented():
    sec9 = _section9()
    missing = []
    for attr, name in sorted(_exported_names().items()):
        if name in sec9:
            continue
        prefix, _, last = name.rpartition("::")
        if prefix and prefix in sec9 and last in sec9:
            continue   # the documented "`FULL::A` / `B`" row shorthand
        missing.append((attr, name))
    assert not missing, (
        "metric-name constants missing from docs/guide.md §9.1 — add a "
        f"table row (or fix the constant): {missing}")


def test_drift_checker_sees_the_constants():
    """The gate must not pass vacuously: the export scan really finds
    the metric families the table documents."""
    names = set(_exported_names().values())
    for expected in ("PARSEC::COMM::BYTES_SENT",
                     "PARSEC::OBS::OVERLAP_FRACTION",
                     "PARSEC::OBS::CLOCK_OFFSET_US",
                     "PARSEC::OBS::FLOW_SENT",
                     "PARSEC::FT::PEER_ALIVE"):
        assert expected in names, expected
    assert len(names) >= 20


def test_documented_gauge_rows_use_known_prefixes():
    """Inverse sanity: every ``PARSEC::`` name in the §9.1 table uses a
    namespace some exporter owns (a typo'd table row is drift too)."""
    known_roots = ("PARSEC::COMM", "PARSEC::DEVICE", "PARSEC::FT",
                   "PARSEC::OBS", "PARSEC::STAGEC", "PARSEC::MEMPOOL",
                   "PARSEC::TASK", "PARSEC::SCHEDULER", "PARSEC::TUNE",
                   "PARSEC::SERVE",
                   "PARSEC::TASKS_ENABLED", "PARSEC::TASKS_RETIRED")
    for m in re.finditer(r"`(PARSEC::[A-Z_:<>a-z]+)`", _section9()):
        assert m.group(1).startswith(known_roots), m.group(1)
