"""MCA component repository: components discoverable and loadable by
(framework, name) at runtime.

Reference behavior: ``mca_components_open_bytype`` — every pluggable
subsystem (sched, device, pins, termdet) is a *framework* whose
components live in a repository; static tables hold the built-ins and
components can be opened by name at runtime
(ref: parsec/mca/mca_repository.c:1-225,
parsec/mca/mca_static_components.h.in).

TPU-native re-design: the built-in tables register here at import; two
DYNAMIC paths close the reference's load-by-type gap —
- a dotted path as the component name (``mypkg.mymod:MyClass`` or
  ``mypkg.mymod.MyClass``) imports the module and returns the class, so
  ``--mca sched mypkg.sched:Fancy`` plugs an out-of-tree scheduler in
  with no code changes;
- installed distributions may advertise components through the
  ``parsec_tpu.<framework>`` entry-point group (the analog of dropping
  a DSO into the reference's component dir).
Opened dynamic components are cached in the framework table, so
repeated opens are dict lookups.
"""
from __future__ import annotations

import importlib
from typing import Any, Dict, List, Optional

_frameworks: Dict[str, Dict[str, Any]] = {}


def register(framework: str, name: str, component: Any) -> None:
    """Add a component to ``framework``'s table (built-ins do this at
    import; dynamic opens cache through here too)."""
    _frameworks.setdefault(framework, {})[name] = component


def _load_dotted(path: str) -> Any:
    """Import ``pkg.mod:Attr`` (or ``pkg.mod.Attr``) and return Attr."""
    if ":" in path:
        modname, _, attr = path.partition(":")
        return getattr(importlib.import_module(modname), attr)
    modname, _, attr = path.rpartition(".")
    if not modname:
        raise ImportError(f"not a dotted component path: {path!r}")
    return getattr(importlib.import_module(modname), attr)


def open_component(framework: str, name: str) -> Optional[Any]:
    """Look up a component: framework table, then dotted-path import,
    then the ``parsec_tpu.<framework>`` entry-point group. Returns None
    when nothing matches (callers decide their fallback, like the
    reference's select-with-default)."""
    tbl = _frameworks.setdefault(framework, {})
    comp = tbl.get(name)
    if comp is not None:
        return comp
    if "." in name or ":" in name:
        try:
            comp = _load_dotted(name)
        except (ImportError, AttributeError):
            return None
        tbl[name] = comp
        return comp
    try:
        from importlib import metadata
        for ep in metadata.entry_points(group=f"parsec_tpu.{framework}"):
            if ep.name == name:
                comp = ep.load()
                tbl[name] = comp
                return comp
    except Exception:  # pragma: no cover - metadata backend quirks
        pass
    return None


def components(framework: str) -> List[str]:
    """Registered + advertised component names for one framework."""
    names = set(_frameworks.get(framework, {}))
    try:
        from importlib import metadata
        names.update(
            ep.name
            for ep in metadata.entry_points(group=f"parsec_tpu.{framework}"))
    except Exception:  # pragma: no cover
        pass
    return sorted(names)


def frameworks() -> List[str]:
    return sorted(_frameworks)
