"""Mesh-parallel primitive tests on the virtual 8-device CPU mesh:
ring attention == local attention, Ulysses == local attention, GPipe ==
sequential stages, expert-parallel MoE == single-shard MoE, and the full
5-axis training step reduces the loss.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

if not hasattr(jax, "shard_map"):
    # shard_map_compat needs the VMA-tracking jax.shard_map (the
    # jax.experimental spelling transposes psum differently, so grads
    # would be silently wrong, not just shaped differently)
    pytest.skip("jax.shard_map (VMA tracking) not available in this jax",
                allow_module_level=True)

from parsec_tpu.parallel import (make_mesh, shard_map_compat, sync_axes,
                                 gpipe, last_stage_value, local_attention,
                                 moe_ffn, ring_attention, ulysses_attention)


def _qkv(B=2, H=4, T=16, Dh=8, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, H, T, Dh)), dtype=jnp.float32)
    return mk(), mk(), mk()


def test_sync_axes():
    assert sync_axes(P("pp", None, "tp")) == ("dp", "sp", "ep")
    assert sync_axes(P()) == ("dp", "pp", "tp", "sp", "ep")
    assert sync_axes(P(("dp", "tp"))) == ("pp", "sp", "ep")


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_local(causal):
    q, k, v = _qkv()
    ref = local_attention(q, k, v, causal=causal)
    mesh = make_mesh(sizes={"sp": 4}, devices=jax.devices("cpu")[:4])
    f = shard_map_compat(
        lambda q, k, v: ring_attention(q, k, v, "sp", causal=causal),
        mesh, in_specs=(P(None, None, "sp"),) * 3,
        out_specs=P(None, None, "sp"))
    out = f(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_ring_attention_matches_jnp_ring(causal):
    """The Pallas-local-block ring (stats-merge across shards) must agree
    with the jnp online-softmax ring, forward AND gradients."""
    q, k, v = _qkv(T=32)
    mesh = make_mesh(sizes={"sp": 4}, devices=jax.devices("cpu")[:4])

    def run(use_pallas):
        f = shard_map_compat(
            lambda q, k, v: ring_attention(q, k, v, "sp", causal=causal,
                                           use_pallas=use_pallas),
            mesh, in_specs=(P(None, None, "sp"),) * 3,
            out_specs=P(None, None, "sp"))

        def loss(q, k, v):
            return (f(q, k, v) * jnp.cos(jnp.arange(q.shape[-1]))).sum()

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        return f(q, k, v), g

    out_f, g_f = run(True)
    out_j, g_j = run(False)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_j),
                               rtol=2e-4, atol=2e-4)
    for a, b in zip(g_f, g_j):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)
    # and against the single-shard reference
    ref = local_attention(q, k, v, causal=causal, use_pallas=False)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_matches_local():
    q, k, v = _qkv()
    ref = local_attention(q, k, v, causal=True)
    mesh = make_mesh(sizes={"sp": 4}, devices=jax.devices("cpu")[:4])
    f = shard_map_compat(
        lambda q, k, v: ulysses_attention(q, k, v, "sp", causal=True),
        mesh, in_specs=(P(None, None, "sp"),) * 3,
        out_specs=P(None, None, "sp"))
    out = f(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_gpipe_matches_sequential():
    """4 stages, each multiplies by its own matrix: pipeline result must
    equal the sequential composition."""
    S, M, mb, D = 4, 3, 2, 8
    rng = np.random.RandomState(1)
    Ws = jnp.asarray(rng.normal(size=(S, D, D)) / np.sqrt(D), jnp.float32)
    x = jnp.asarray(rng.normal(size=(M, mb, D)), jnp.float32)
    ref = x
    for s in range(S):
        ref = jnp.einsum("mbd,dk->mbk", ref, Ws[s])

    mesh = make_mesh(sizes={"pp": 4}, devices=jax.devices("cpu")[:4])

    def run(ws_local, xm):
        def stage_fn(w, a):
            return jnp.einsum("bd,dk->bk", a, w[0])
        out = gpipe(stage_fn, ws_local, xm, "pp")
        return last_stage_value(out, "pp")

    f = shard_map_compat(run, mesh, in_specs=(P("pp"), P()), out_specs=P())
    out = f(Ws, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4,
                               atol=1e-5)


def test_gpipe_gradient_flows():
    S, M, mb, D = 2, 2, 2, 4
    rng = np.random.RandomState(2)
    Ws = jnp.asarray(rng.normal(size=(S, D, D)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(M, mb, D)), jnp.float32)
    mesh = make_mesh(sizes={"pp": 2}, devices=jax.devices("cpu")[:2])

    def loss_fn(ws_local, xm):
        def stage_fn(w, a):
            return jnp.tanh(jnp.einsum("bd,dk->bk", a, w[0]))
        out = gpipe(stage_fn, ws_local, xm, "pp")
        return last_stage_value(jnp.sum(out ** 2), "pp")

    def grads(ws_local, xm):
        return jax.grad(loss_fn)(ws_local, xm)

    f = shard_map_compat(grads, mesh, in_specs=(P("pp"), P()),
                         out_specs=P("pp"))
    g = f(Ws, x)
    assert np.asarray(g).shape == (S, D, D)
    assert np.all(np.isfinite(np.asarray(g)))
    assert np.abs(np.asarray(g)).max() > 0


def test_moe_expert_parallel_matches_single():
    rng = np.random.RandomState(3)
    B, T, D, F, E = 2, 4, 8, 16, 4
    x = jnp.asarray(rng.normal(size=(B, T, D)), jnp.float32)
    gate = jnp.asarray(rng.normal(size=(D, E)), jnp.float32)
    w1 = jnp.asarray(rng.normal(size=(E, D, F)) / np.sqrt(D), jnp.float32)
    w2 = jnp.asarray(rng.normal(size=(E, F, D)) / np.sqrt(F), jnp.float32)

    mesh1 = make_mesh(sizes={"ep": 1}, devices=jax.devices("cpu")[:1])
    ref = shard_map_compat(
        lambda x, g, a, b: moe_ffn(x, g, a, b, "ep", top_k=2),
        mesh1, in_specs=(P(), P(), P("ep"), P("ep")), out_specs=P())(
            x, gate, w1, w2)

    mesh4 = make_mesh(sizes={"ep": 4}, devices=jax.devices("cpu")[:4])
    out = shard_map_compat(
        lambda x, g, a, b: moe_ffn(x, g, a, b, "ep", top_k=2),
        mesh4, in_specs=(P(), P(), P("ep"), P("ep")), out_specs=P())(
            x, gate, w1, w2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                               atol=2e-4)


def test_train_step_reduces_loss():
    """Full 5-axis training step on the 8-device mesh: loss must drop."""
    from parsec_tpu.models import (TransformerConfig, adam_init, init_params,
                                   make_train_step)
    mesh = make_mesh(8)
    sz = dict(zip(mesh.axis_names, mesh.devices.shape))
    cfg = TransformerConfig(
        vocab=32, d_model=16, n_heads=2 * sz["tp"] * sz["sp"], d_head=4,
        n_stages=sz["pp"], layers_per_stage=1, d_ff=4 * sz["tp"],
        n_experts=2 * sz["ep"], seq_len=4 * sz["sp"],
        batch=2 * sz["dp"] * 2, n_micro=2)
    params = init_params(cfg)
    state = adam_init(params)
    step = make_train_step(cfg, mesh, lr=5e-3)
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, cfg.vocab, size=(cfg.batch, cfg.seq_len)).astype(np.int32)
    labels = np.roll(tokens, -1, axis=1).astype(np.int32)
    losses = []
    for _ in range(8):
        params, state, loss = step(params, state, tokens, labels)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses


def test_train_step_flash_remat_matches_local():
    """attention='flash' (Pallas) + remat must produce the same step as
    'local' attention without remat — same loss trajectory (single-shard
    sequence: flash and local compute identical attention)."""
    from parsec_tpu.models import (TransformerConfig, adam_init, init_params,
                                   make_train_step)
    mesh = make_mesh(1)
    base = dict(vocab=64, d_model=32, n_heads=4, d_head=8,
                n_stages=1, layers_per_stage=2, d_ff=64,
                seq_len=32, batch=2, n_micro=1)
    rng = np.random.RandomState(1)
    tokens = rng.randint(0, 64, size=(2, 32)).astype(np.int32)
    labels = np.roll(tokens, -1, axis=1).astype(np.int32)

    def run(**kw):
        cfg = TransformerConfig(**base, **kw)
        params = init_params(cfg)
        state = adam_init(params)
        step = make_train_step(cfg, mesh, lr=5e-3)
        out = []
        for _ in range(3):
            params, state, loss = step(params, state, tokens, labels)
            out.append(float(loss))
        return out

    ref = run(attention="local", remat=False)
    got = run(attention="flash", remat=True)
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)
