"""Ex04: a chain reading and writing user data in place.

Teaches: data_of() — the first task pulls its input from the collection
(memory), the chain mutates it, and the last task writes it back
(ref: examples/Ex04_ChainData.jdf:18-45, the SURVEY.md worked example).
"""
import os, sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import parsec_tpu
from parsec_tpu.collections import DictCollection
from parsec_tpu.dsl import ptg

CHAIN_JDF = """
mydata  [ type="collection" ]
NB      [ type="int" ]

Task(k)

k = 0 .. NB

: mydata( k )

RW  A <- (k == 0)  ? mydata( k ) : A Task( k-1 )
      -> (k == NB) ? mydata( k ) : A Task( k+1 )

BODY
{
    A[...] += 1
    print(f"I am element {int(A.ravel()[0])} in the chain")
}
END
"""


def main(NB: int = 10) -> int:
    # one memory cell walked by the whole chain: every index maps to datum 0
    class Single(DictCollection):
        def data_of(self, *idx):
            return DictCollection.data_of(self, 0)

        def rank_of(self, *idx):
            return 0

    cell = np.array([300], dtype=np.int64)
    mydata = Single()
    mydata.add(0, 0, cell)

    ctx = parsec_tpu.init(nb_cores=2)
    try:
        tp = ptg.compile_jdf(CHAIN_JDF, name="chain04").new(
            mydata=mydata, NB=NB)
        ctx.add_taskpool(tp)
        ctx.wait()
    finally:
        ctx.fini()
    assert cell[0] == 300 + NB + 1, cell
    print(f"final value written back to memory: {cell[0]}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
