#!/usr/bin/env python
"""parsec_tpu process launcher — the mpiexec analog.

Spawns N SPMD rank processes of a user program, wiring each one's comm
engine via PARSEC_MCA_* env vars (the reference hands each process its
communicator through mpiexec + MPI_Init; here the launcher allocates the
control-plane endpoints and each rank's Context auto-builds a
TCPCommEngine + RemoteDepEngine at init, runtime/context.py
_comm_from_params). Ref: parsec/parsec_mpi_funnelled.c:245-365 (the
transport this replaces), SURVEY.md §5.8.

Usage:
  python tools/launch.py -n N [options] prog.py [prog args...]

Options:
  -n N                 number of ranks (default 2)
  --jax-distributed    also start a jax.distributed coordinator so the
                       ranks form ONE global jax device mesh (GSPMD
                       across processes); rank 0 hosts the coordinator
  --host H             bind host (default 127.0.0.1)
  --timeout S          per-rank wall clock limit (default 3600)
  --env K=V            extra env var for every rank (repeatable)

Each rank's stdout/stderr is streamed line-by-line with a "[r]" prefix.
Exit status: 0 when every rank exits 0; otherwise the first non-zero
rank's status (remaining ranks are killed — fail fast, like mpiexec).
"""
import argparse
import os
import signal
import subprocess
import sys
import threading

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def main() -> int:
    ap = argparse.ArgumentParser(
        prog="launch.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("-n", type=int, default=2, dest="nranks")
    ap.add_argument("--jax-distributed", action="store_true")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--timeout", type=float, default=3600.0)
    ap.add_argument("--env", action="append", default=[])
    ap.add_argument("prog")
    ap.add_argument("prog_args", nargs=argparse.REMAINDER)
    args = ap.parse_args()

    from parsec_tpu.comm.tcp import free_ports

    n = args.nranks
    ports = free_ports(n + (1 if args.jax_distributed else 0))
    endpoints = ",".join(f"{args.host}:{p}" for p in ports[:n])

    base_env = dict(os.environ)
    for kv in args.env:
        k, _, v = kv.partition("=")
        base_env[k] = v
    base_env["PARSEC_MCA_comm_transport"] = "tcp"
    base_env["PARSEC_MCA_comm_endpoints"] = endpoints
    if args.jax_distributed:
        base_env["PARSEC_MCA_jax_coordinator"] = \
            f"{args.host}:{ports[n]}"
        base_env["PARSEC_MCA_jax_num_processes"] = str(n)

    procs = []
    for r in range(n):
        env = dict(base_env)
        env["PARSEC_MCA_comm_rank"] = str(r)
        if args.jax_distributed:
            env["PARSEC_MCA_jax_process_id"] = str(r)
        procs.append(subprocess.Popen(
            [sys.executable, args.prog] + args.prog_args,
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))

    def pump(r, stream):
        for line in stream:
            sys.stdout.write(f"[{r}] {line}")
            sys.stdout.flush()

    pumps = [threading.Thread(target=pump, args=(r, p.stdout), daemon=True)
             for r, p in enumerate(procs)]
    for t in pumps:
        t.start()

    rc = 0
    try:
        for r, p in enumerate(procs):
            try:
                p.wait(timeout=args.timeout)
            except subprocess.TimeoutExpired:
                sys.stderr.write(f"launch.py: rank {r} exceeded "
                                 f"{args.timeout}s; killing all\n")
                rc = rc or 124
                break
            if p.returncode != 0 and rc == 0:
                sys.stderr.write(f"launch.py: rank {r} exited "
                                 f"{p.returncode}; killing the rest\n")
                rc = p.returncode
                break
    except KeyboardInterrupt:
        rc = 130
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for t in pumps:
            t.join(timeout=2)
    if rc == 0 and any(p.returncode != 0 for p in procs):
        rc = next(p.returncode for p in procs if p.returncode != 0)
    return rc


if __name__ == "__main__":
    sys.exit(main())
