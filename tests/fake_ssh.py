#!/usr/bin/env python
"""ssh stand-in for multi-host launcher tests (no sshd in CI): accepts
`fake_ssh.py <host> <shell-command>` exactly like `ssh host cmd` and
runs the command in a local shell. The launcher's remote path (command
construction, env wiring through `env K=V`, real-interface endpoint
binding on loopback aliases) is exercised for real; only the transport
to the other machine is faked."""
import subprocess
import sys

if __name__ == "__main__":
    if len(sys.argv) < 3:
        sys.stderr.write("usage: fake_ssh.py <host> <command>\n")
        sys.exit(2)
    sys.exit(subprocess.call(["/bin/sh", "-c", sys.argv[-1]]))
