"""Compile-time JDF dataflow verification (the ``jdf_sanity_checks`` analog).

Operates on the parsed AST (:mod:`..dsl.ptg.ast`) — everything here runs
before a taskpool is instantiated, so a mis-written spec fails in
milliseconds at compile time instead of hanging a multirank run.

Finding codes (PTG1xx; see docs/guide.md):

- ``PTG100`` parse-error: the text does not parse as JDF at all.
- ``PTG101`` dangling-endpoint: a dep names an unknown task class, an
  unknown flow of a known class, or an unknown collection global.
- ``PTG102`` ctl-data-mismatch: a CTL flow is wired to a data flow (or
  vice versa) — control edges carry no payload.
- ``PTG103`` write-endpoint: an out-dep feeds data into a WRITE-only
  peer flow.  WRITE flows *produce* values (their inputs are ``NEW`` or
  nothing); data arriving over such an edge is silently dropped.
- ``PTG104`` arity-mismatch: a task dep-target's argument count differs
  from the target class's parameter list.
- ``PTG105`` non-reciprocal-dep: ``A.X -> B.Y`` without a matching
  ``B.Y <- A.X`` (or an in-dep without the producer's out-dep).
  Activations are producer-driven and input counts consumer-declared,
  so a one-sided edge is a lost activation or an input that never
  arrives — at runtime, a hang.
- ``PTG106`` unused-global (warn): a declared global referenced by no
  expression, body, affinity, or dep property.
- ``PTG107`` unused-local (warn): a non-parameter local referenced
  nowhere (parameters are exempt: they name the instance space).
- ``PTG108`` unsatisfiable-guard: a dep guard that is statically false
  (constant-false, or a self-comparison like ``k < k``) — the edge can
  never fire.
- ``PTG109`` dependency-cycle: a concrete instantiation of the graph
  (enumerated via ``tools/dagenum.py``) has a CTL/data cycle.
- ``PTG180`` enumeration-skipped (note): the cycle pass could not
  instantiate the spec with the provided globals.
"""
from __future__ import annotations

import ast as pyast
import importlib.util
import os
import sys
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from ..dsl.ptg.ast import (DepAST, DepTarget, Expr, JDFFile, RangeExpr,
                           TaskClassAST)
from . import Finding


# --------------------------------------------------------------------- #
# helpers                                                               #
# --------------------------------------------------------------------- #
def _names_in(src: Optional[str]) -> Set[str]:
    """All identifier names (including attribute roots) in a Python
    expression/statement source.  Over-approximates usage, which is the
    right direction for unused-symbol checks (no false positives)."""
    if not src:
        return set()
    try:
        tree = pyast.parse(src)
    except SyntaxError:
        return set()
    return {n.id for n in pyast.walk(tree) if isinstance(n, pyast.Name)}


def _expr_names(e: Any) -> Set[str]:
    if e is None:
        return set()
    if isinstance(e, RangeExpr):
        out = _expr_names(e.lo) | _expr_names(e.hi)
        if e.step is not None:
            out |= _expr_names(e.step)
        return out
    if isinstance(e, Expr):
        return _names_in(e.src)
    return set()


def _dep_origin(d: DepAST, fallback: str = "") -> str:
    """Best source location for a dep: any Expr the parser stamped."""
    cands: List[Any] = [d.guard]
    for t in (d.target, d.alt_target):
        if t is not None:
            for a in t.args:
                cands.append(a.lo if isinstance(a, RangeExpr) else a)
    for c in cands:
        o = getattr(c, "origin", None)
        if o:
            return o
    return fallback


def _targets(d: DepAST) -> Iterable[DepTarget]:
    for t in (d.target, d.alt_target):
        if t is not None:
            yield t


# --------------------------------------------------------------------- #
# pass 1: endpoint existence / direction / arity                        #
# --------------------------------------------------------------------- #
def _check_endpoints(jdf: JDFFile, findings: List[Finding]) -> None:
    gnames = {g.name for g in jdf.globals}
    classes = {tc.name: tc for tc in jdf.task_classes}
    for tc in jdf.task_classes:
        for f in tc.flows:
            for d in f.deps:
                where = _dep_origin(d, f"{jdf.name} {tc.name}.{f.name}")
                for t in _targets(d):
                    if t.kind == "memory":
                        if t.collection not in gnames:
                            findings.append(Finding(
                                "PTG101",
                                f"{tc.name}.{f.name}: dep references "
                                f"unknown collection {t.collection!r}",
                                where))
                        continue
                    if t.kind != "task":
                        continue
                    peer = classes.get(t.task_class)
                    if peer is None:
                        findings.append(Finding(
                            "PTG101",
                            f"{tc.name}.{f.name}: dep targets unknown "
                            f"task class {t.task_class!r}", where))
                        continue
                    pf = next((x for x in peer.flows if x.name == t.flow),
                              None)
                    if pf is None:
                        findings.append(Finding(
                            "PTG101",
                            f"{tc.name}.{f.name}: dep targets unknown "
                            f"flow {t.task_class}.{t.flow}", where))
                        continue
                    if f.is_ctl != pf.is_ctl:
                        findings.append(Finding(
                            "PTG102",
                            f"{tc.name}.{f.name} ({f.access}) is wired "
                            f"to {t.task_class}.{t.flow} ({pf.access}): "
                            f"CTL flows only connect to CTL flows",
                            where))
                    if d.direction == "out" and pf.access == "WRITE" \
                            and not f.is_ctl:
                        findings.append(Finding(
                            "PTG103",
                            f"{tc.name}.{f.name} -> {t.task_class}."
                            f"{t.flow}: target flow is WRITE-only and "
                            f"takes no input — the sent data is dropped",
                            where))
                    if len(t.args) != len(peer.params):
                        findings.append(Finding(
                            "PTG104",
                            f"{tc.name}.{f.name}: dep target "
                            f"{t.task_class}({len(t.args)} args) does "
                            f"not match its parameter list "
                            f"({', '.join(peer.params)})", where))


# --------------------------------------------------------------------- #
# pass 2: dependency reciprocity                                        #
# --------------------------------------------------------------------- #
def _check_reciprocity(jdf: JDFFile, findings: List[Finding]) -> None:
    classes = {tc.name for tc in jdf.task_classes}
    outs: Dict[Tuple[str, str, str, str], str] = {}
    ins: Dict[Tuple[str, str, str, str], str] = {}
    for tc in jdf.task_classes:
        for f in tc.flows:
            for d in f.deps:
                for t in _targets(d):
                    if t.kind != "task" or t.task_class not in classes:
                        continue
                    key = (tc.name, f.name, t.task_class, t.flow)
                    where = _dep_origin(d, f"{jdf.name} {tc.name}.{f.name}")
                    (outs if d.direction == "out" else ins).setdefault(
                        key, where)
    for (a, af, b, bf), where in outs.items():
        if (b, bf, a, af) not in ins:
            findings.append(Finding(
                "PTG105",
                f"{a}.{af} -> {b}.{bf} has no matching inbound dep "
                f"({b}.{bf} never lists <- {af} {a}(...)): the "
                f"activation is sent but never counted — at runtime, "
                f"a lost input or a hang", where))
    for (b, bf, a, af), where in ins.items():
        if (a, af, b, bf) not in outs:
            findings.append(Finding(
                "PTG105",
                f"{b}.{bf} <- {af} {a}(...) has no matching outbound "
                f"dep ({a}.{af} never lists -> {bf} {b}(...)): the "
                f"input is counted but never produced — at runtime, "
                f"a hang", where))


# --------------------------------------------------------------------- #
# pass 3: unused globals / locals                                       #
# --------------------------------------------------------------------- #
def _all_referenced(jdf: JDFFile) -> Set[str]:
    used: Set[str] = set()
    for block in list(jdf.prologue) + list(jdf.epilogue):
        used |= _names_in(block)
    for g in jdf.globals:
        d = g.properties.get("default")
        if d is not None:
            used |= _names_in(d)
    for tc in jdf.task_classes:
        used |= _class_referenced(tc)
        if tc.affinity_collection:
            used.add(tc.affinity_collection)
    return used


def _class_referenced(tc: TaskClassAST) -> Set[str]:
    """Names referenced by a class's expressions, bodies, and deps."""
    used: Set[str] = set()
    for ld in tc.locals:
        if ld.range is not None:
            used |= _expr_names(ld.range)
        if ld.expr is not None:
            used |= _expr_names(ld.expr)
    for e in tc.affinity_args:
        used |= _expr_names(e)
    used |= _expr_names(tc.priority)
    for f in tc.flows:
        for d in f.deps:
            used |= _expr_names(d.guard)
            for t in _targets(d):
                if t.kind == "memory" and t.collection:
                    used.add(t.collection)
                for a in t.args:
                    used |= _expr_names(a)
            for pv in d.properties.values():
                used |= _names_in(pv)
    for b in tc.bodies:
        used |= _names_in(b.code)
    return used


def _check_unused(jdf: JDFFile, findings: List[Finding]) -> None:
    used = _all_referenced(jdf)
    for g in jdf.globals:
        if g.hidden or g.name in used:
            continue
        findings.append(Finding(
            "PTG106", f"global {g.name!r} is never referenced by any "
            f"expression, body, affinity, or dep property",
            f"{jdf.name} {g.name}", severity="warn"))
    for tc in jdf.task_classes:
        cused = _class_referenced(tc)
        for ld in tc.locals:
            if ld.name in tc.params or ld.name in cused:
                continue
            kind = "derived local" if ld.range is None else "range local"
            findings.append(Finding(
                "PTG107", f"{tc.name}: {kind} {ld.name!r} is never "
                f"referenced" + ("" if ld.range is None else
                                 " (it multiplies the instance space "
                                 "with identical copies)"),
                f"{jdf.name} {tc.name}", severity="warn"))


# --------------------------------------------------------------------- #
# pass 4: statically-unsatisfiable guards                               #
# --------------------------------------------------------------------- #
_NEVER_OPS = (pyast.Lt, pyast.Gt, pyast.NotEq)


def _guard_unsat(src: str) -> Optional[str]:
    try:
        tree = pyast.parse(src, mode="eval").body
    except SyntaxError:
        return None
    if isinstance(tree, pyast.Constant) and not tree.value:
        return f"guard {src!r} is constant false"
    if isinstance(tree, pyast.Compare) and len(tree.ops) == 1 \
            and isinstance(tree.ops[0], _NEVER_OPS) \
            and pyast.dump(tree.left) == pyast.dump(tree.comparators[0]):
        return f"guard {src!r} compares an expression against itself"
    return None


def _check_guards(jdf: JDFFile, findings: List[Finding]) -> None:
    for tc in jdf.task_classes:
        for f in tc.flows:
            for d in f.deps:
                if d.guard is None:
                    continue
                why = _guard_unsat(d.guard.src)
                if why:
                    findings.append(Finding(
                        "PTG108",
                        f"{tc.name}.{f.name}: {why} — the "
                        f"{'alternative' if d.alt_target else 'edge'} "
                        f"can never fire",
                        _dep_origin(d, f"{jdf.name} {tc.name}.{f.name}")))


# --------------------------------------------------------------------- #
# pass 5: cycle detection via concrete enumeration                      #
# --------------------------------------------------------------------- #
def _load_dagenum():
    """Import ``tools/dagenum.py`` (a repo-root package when the repo is
    on sys.path; loaded by file path otherwise)."""
    try:
        from tools import dagenum  # type: ignore
        return dagenum
    except ImportError:
        pass
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    path = os.path.join(root, "tools", "dagenum.py")
    if not os.path.exists(path):
        return None
    mod = sys.modules.get("_parsec_tpu_dagenum")
    if mod is not None:
        return mod
    spec = importlib.util.spec_from_file_location("_parsec_tpu_dagenum", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_parsec_tpu_dagenum"] = mod
    spec.loader.exec_module(mod)
    return mod


def default_enum_env(jdf: JDFFile, int_default: int = 4) -> Dict[str, Any]:
    """Small concrete global bindings for cycle enumeration: declared
    defaults evaluate first; remaining int-typed (or untyped, non-
    collection) globals bind to ``int_default``.  Collection globals are
    left for the enumerator's dummy-collection synthesis."""
    env: Dict[str, Any] = {}
    for g in jdf.globals:
        if g.properties.get("type") == "collection":
            continue
        d = g.properties.get("default")
        if d is not None:
            try:
                env[g.name] = Expr(d)(dict(env))
                continue
            except Exception:
                pass
        env[g.name] = int_default
    return env


def check_cycles(text: str, name: str = "jdf",
                 env: Optional[Dict[str, Any]] = None,
                 tiles: Tuple[int, int] = (4, 4),
                 jdf: Optional[JDFFile] = None) -> List[Finding]:
    """Enumerate one small concrete instantiation of the spec and report
    a PTG109 on a dependency cycle (reuses ``tools/dagenum.py``).
    ``jdf`` skips the re-parse when the caller already holds the AST."""
    dagenum = _load_dagenum()
    if dagenum is None:  # pragma: no cover - tools/ always ships in-tree
        return [Finding("PTG180", "tools/dagenum.py unavailable: cycle "
                        "pass skipped", name, severity="note")]
    from ..dsl.ptg.capture import CaptureError
    try:
        from ..dsl import ptg
        factory = ptg.JDFFactory(jdf) if jdf is not None \
            else ptg.compile_jdf(text, name=name)
        if env is None:
            env = default_enum_env(factory.jdf)
        dagenum.enumerate_factory(factory, env, tiles[0], tiles[1])
    except CaptureError as exc:
        if "cycle" in str(exc):
            return [Finding(
                "PTG109", f"dependency cycle in the enumerated instance "
                f"graph ({exc})", name)]
        return [Finding("PTG180", f"cycle enumeration failed: {exc}",
                        name, severity="note")]
    except Exception as exc:
        return [Finding("PTG180", f"cycle enumeration failed: "
                        f"{type(exc).__name__}: {exc}", name,
                        severity="note")]
    return []


# --------------------------------------------------------------------- #
# public API                                                            #
# --------------------------------------------------------------------- #
def verify_jdf(jdf: JDFFile) -> List[Finding]:
    """All static AST passes (no enumeration) over a parsed JDF."""
    findings: List[Finding] = []
    _check_endpoints(jdf, findings)
    _check_reciprocity(jdf, findings)
    _check_unused(jdf, findings)
    _check_guards(jdf, findings)
    return findings


def verify_jdf_text(text: str, name: str = "jdf",
                    enum_env: Optional[Dict[str, Any]] = None,
                    cycles: bool = True,
                    jdf: Optional[JDFFile] = None) -> List[Finding]:
    """Parse + verify JDF source text.  Parse failures come back as
    findings (PTG100/PTG101) instead of raising, so a lint run over many
    specs reports them all.  ``cycles`` additionally enumerates a small
    concrete instantiation (``enum_env`` overrides the global guesses).
    ``jdf`` supplies an already-parsed AST so a multi-pass caller
    (tools/parsec_lint.py) parses each spec exactly once."""
    if jdf is None:
        from ..dsl.ptg.parser import JDFParseError, parse_jdf
        try:
            jdf = parse_jdf(text, name=name)
        except JDFParseError as exc:
            msg = str(exc)
            code = ("PTG101" if ("bad dep target" in msg
                                 or "unknown collection" in msg
                                 or "no flow named" in msg
                                 or "no task class" in msg) else "PTG100")
            return [Finding(code, msg, name)]
        except SyntaxError as exc:
            return [Finding("PTG100", str(exc), name)]
    findings = verify_jdf(jdf)
    if cycles and not any(f.severity == "error" for f in findings):
        findings.extend(check_cycles(text, name, env=enum_env, jdf=jdf))
    return findings
