#!/usr/bin/env python
"""Break the cross-rank hop latency into components (VERDICT r2 item 8:
replace the 'thread-scheduling dominates' prose with a measured table).

A PTG ping-pong chain runs over the in-process fabric with timestamp
probes at the four stages of one hop:

  send      producer's comm engine posts the activation
  arrival   the message lands in the receiver's transport inbox
  callback  the receiver's activation handler runs (a worker woke up
            and drained the inbox — the wakeup + progress component)
  body      the successor task's body executes (release_deps, schedule,
            prepare_input — the dispatch component)
  next send the successor's own completion posts the next activation
            (completion + iterate_successors + pack — turnaround)

Components reported (median over hops):
  wire       = arrival - send        (transport post; ~memcpy in-process)
  wakeup     = callback - arrival    (worker wake + inbox drain)
  dispatch   = body - callback       (release/schedule/prepare/exec entry)
  turnaround = next send - body      (complete + successors + pack)

Usage: python tools/rtt_breakdown.py [hops]
Prints one JSON line; exit 0.
"""
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "tests"))

RTT_JDF = """
descX [ type="collection" ]
NB [ type="int" ]

PING(k)

k = 0 .. NB-1

: descX( k % 2, 0 )

RW X <- (k == 0) ? descX( 0, 0 ) : X PING( k-1 )
     -> (k < NB-1) ? X PING( k+1 )
     -> (k == NB-1) ? descX( (NB-1) % 2, 0 )

BODY
{
    X[0, 0] = X[0, 0] + 1.0
    stamp()
}
END
"""


def measure(hops: int = 60, mb: int = 8):
    import numpy as np

    import parsec_tpu
    from parsec_tpu.comm import LocalFabric, RemoteDepEngine
    from parsec_tpu.comm.engine import TAG_ACTIVATE
    from parsec_tpu.collections import TwoDimBlockCyclic
    from parsec_tpu.dsl import ptg

    events = []   # (kind, t) — the chain is serial, so global order pairs

    def rank_fn(rank, fabric):
        eng = RemoteDepEngine(fabric.engine(rank))
        ce = eng.ce

        orig_send = ce.send_am

        def send_am(dst, tag, payload):
            if tag == TAG_ACTIVATE:
                events.append(("send", time.perf_counter()))
            return orig_send(dst, tag, payload)

        ce.send_am = send_am
        orig_cb = ce._tag_cbs[TAG_ACTIVATE]

        def on_act(src, msg):
            events.append(("cb", time.perf_counter()))
            return orig_cb(src, msg)

        ce._tag_cbs[TAG_ACTIVATE] = on_act
        ctx = parsec_tpu.Context(nb_cores=1, comm=eng, enable_tpu=False)
        orig_arr = ce.on_arrival

        def on_arr():
            events.append(("arrival", time.perf_counter()))
            if orig_arr is not None:
                orig_arr()

        ce.on_arrival = on_arr
        try:
            coll = TwoDimBlockCyclic(2 * mb, mb, mb, mb, P=2, Q=1,
                                     nodes=2, rank=rank, dtype=np.float32)
            coll.name = "descX"
            tp = ptg.compile_jdf(RTT_JDF, name="rttb").new(
                descX=coll, NB=hops, rank=rank, nb_ranks=2)
            tp.global_env["stamp"] = lambda: events.append(
                ("body", time.perf_counter()))
            t0 = time.perf_counter()
            ctx.add_taskpool(tp)
            ctx.wait()
            return time.perf_counter() - t0
        finally:
            ctx.fini()

    from conftest import spmd
    results, _ = spmd(2, rank_fn)
    wall = max(r for r in results if r is not None)

    ev = sorted(events, key=lambda e: e[1])
    comp = {"wire": [], "wakeup": [], "dispatch": [], "turnaround": []}
    # walk send -> arrival -> cb -> body -> (next) send
    for i, (kind, t) in enumerate(ev):
        if kind != "send":
            continue
        seq = {"send": t}
        want = ["arrival", "cb", "body", "send"]
        j = i + 1
        for w in want:
            while j < len(ev) and ev[j][0] != w:
                j += 1
            if j >= len(ev):
                break
            seq[w + "2" if w == "send" else w] = ev[j][1]
            j += 1
        if "arrival" in seq and "cb" in seq and "body" in seq:
            comp["wire"].append(seq["arrival"] - seq["send"])
            comp["wakeup"].append(seq["cb"] - seq["arrival"])
            comp["dispatch"].append(seq["body"] - seq["cb"])
            if "send2" in seq:
                comp["turnaround"].append(seq["send2"] - seq["body"])

    def med(xs):
        xs = sorted(xs)
        return xs[len(xs) // 2] * 1e6 if xs else float("nan")

    out = {k: round(med(v), 1) for k, v in comp.items()}
    out["hop_total_us"] = round(sum(v for v in out.values()), 1)
    out["rtt_us"] = round(2 * out["hop_total_us"], 1)
    out["wall_us_per_rtt"] = round(wall / (hops / 2) * 1e6, 1)
    out["hops"] = hops
    return out


if __name__ == "__main__":
    hops = int(sys.argv[1]) if len(sys.argv) > 1 else 60
    print(json.dumps(measure(hops)))
