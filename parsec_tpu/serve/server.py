"""SessionServer: multi-tenant persistent serving over one Context.

One long-lived :class:`~parsec_tpu.runtime.context.Context` is shared by
named **tenants**; each tenant submits taskpools (PTG specs or DTD
closures, built by a zero-argument callable) that run concurrently on
the context's workers.  The server is the policy layer in front of the
untouched runtime:

- **admission control** — per-tenant caps on in-flight taskpools and
  tasks plus a declared byte quota (optionally fed by live named-Mempool
  outstanding-byte accounting); over-quota submissions are rejected
  (``serve_admission=reject``) or queued FIFO per tenant
  (``serve_admission=queue``) and drained as earlier pools retire;
- **weighted fairness** — tenant weight/priority class feeds
  :class:`~parsec_tpu.serve.fairness.TenantFairness`, whose deficit
  boosts ``stamp_dynamic_priority`` folds above the class-profile band
  (runtime/scheduling.py); the ap/spq/pbq schedulers are untouched;
- **attribution** — the submitting tenant is stamped into the pool's
  flow context (``FlowIds.tenants``) and charged into the live health
  monitor (:meth:`LiveHealth.note_tenant_latency`), so window digests,
  ``/health``, obs_report and merged timelines group per tenant; the
  ``PARSEC::SERVE::*`` gauges are registered on the context's SDE
  registry only when a server is constructed.

A remote front-end rides the existing active-message layer:
:meth:`attach_engine` installs a ``TAG_SERVE`` handler consuming the
versioned envelopes of :mod:`parsec_tpu.comm.wire`
(``serve_request``/``serve_reply``); over TCP the endpoint is gated by
the HELLO ``"sv"`` capability, so a knob-unset peer's wire bytes are
bit-for-bit those of a pre-serve build.

Lock ordering: the server lock is a leaf — taskpool construction,
``ctx.add_taskpool`` and reply sends all happen OUTSIDE it (completion
callbacks fire on worker threads holding taskpool claim state, and the
AM handler runs on whichever thread drains comm progress).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..obs.spans import (SERVE_ADMITTED, SERVE_INFLIGHT_PREFIX,
                         SERVE_P99_LATENCY_PREFIX, SERVE_QUEUED,
                         SERVE_QUOTA_BYTES_PREFIX, SERVE_REJECTED,
                         SERVE_TENANTS)
from ..utils import logging as plog
from ..utils.params import params
from .fairness import TenantFairness

__all__ = ["AdmissionError", "SessionServer", "Submission", "Tenant"]

# Tenant/Submission mutable fields (inflight_*, queued, lat_us,
# waiters, charged) are guarded by the owning SessionServer's _lock too
# — the lint's recv.lock matching can only express same-receiver
# guards, so those stay documentation (class docstrings) rather than
# declarations.  _nq (global queued count) is written under _lock and
# read lock-free as kick()'s fast-path early-out.
_GUARDED_BY = {
    "SessionServer._tenants": "_lock",
    "SessionServer._subs": "_lock",
}

#: default per-tenant latency ring length (server-side; the live
#: monitor keeps its own ring of the same default for fleet merging);
#: both resize from the serve_latency_window knob at construction
_LAT_RING = 512


class AdmissionError(RuntimeError):
    """Submission rejected by admission control (cap or quota)."""


class Tenant:
    """One named session: weight, caps, quota, accounting.

    All mutable fields are guarded by the owning server's ``_lock``
    (the server mediates every access; tenants have no lock of their
    own)."""

    __slots__ = ("name", "weight", "quota_bytes", "max_pools", "max_tasks",
                 "inflight_pools", "inflight_tasks", "inflight_bytes",
                 "queued", "lat_us", "mempools", "pools_done", "_gauges")

    def __init__(self, name: str, weight: int, quota_bytes: int,
                 max_pools: int, max_tasks: int,
                 lat_ring: int = _LAT_RING) -> None:
        self.name = name
        self.weight = max(1, int(weight))
        self.quota_bytes = int(quota_bytes)   # 0 = unlimited
        self.max_pools = int(max_pools)       # 0 = unlimited
        self.max_tasks = int(max_tasks)       # 0 = unlimited
        self.inflight_pools = 0
        self.inflight_tasks = 0
        self.inflight_bytes = 0
        self.queued: deque = deque()          # queued Submissions (FIFO)
        self.lat_us: deque = deque(maxlen=max(1, int(lat_ring)))
        self.pools_done = 0
        # named-Mempool quota feeds: (mempool, item_bytes)
        self.mempools: List[Tuple[Any, int]] = []
        self._gauges: List[Tuple[str, Callable]] = []

    def used_bytes_locked(self) -> int:  # holds: server._lock
        n = self.inflight_bytes
        for mp, item_bytes in self.mempools:
            n += int(mp.nb_outstanding) * int(item_bytes)
        return n


class Submission:
    """One admitted (or queued) taskpool submission."""

    __slots__ = ("ticket", "tenant", "build", "nbytes", "ntasks", "name",
                 "t_submit_ns", "taskpool", "done", "error", "waiters",
                 "lat_us", "charged")

    def __init__(self, ticket: int, tenant: str, build: Callable[[], Any],
                 nbytes: int, ntasks: int, name: Optional[str]) -> None:
        self.ticket = ticket
        self.tenant = tenant
        self.build = build
        self.nbytes = int(nbytes)
        self.ntasks = max(1, int(ntasks))
        self.name = name
        self.t_submit_ns = time.monotonic_ns()
        self.taskpool = None
        self.done = threading.Event()
        self.error: Optional[str] = None
        self.lat_us = 0.0
        # admission currently charged against the tenant (server _lock);
        # makes the release path idempotent against done/abort races
        self.charged = False
        # deferred remote "wait" replies: (src_rank, req_id)
        self.waiters: List[Tuple[int, int]] = []

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.done.wait(timeout)


class SessionServer:
    """The serving front-end bound to one persistent Context."""

    def __init__(self, ctx, admission: Optional[str] = None) -> None:
        self.ctx = ctx
        if admission is None:
            admission = params.get_or("serve_admission", "string", "reject")
        if admission not in ("reject", "queue"):
            raise ValueError(f"serve_admission must be reject|queue, "
                             f"got {admission!r}")
        self.admission = admission
        self.max_tenants = int(params.get_or("serve_max_tenants", "int", 64))
        self.default_weight = int(
            params.get_or("serve_default_weight", "int", 1))
        self.default_quota = int(
            params.get_or("serve_default_quota_bytes", "sizet", 0))
        self.lat_ring = max(1, int(
            params.get_or("serve_latency_window", "int", _LAT_RING)))
        self._lock = threading.Lock()
        self._tenants: Dict[str, Tenant] = {}
        self._subs: Dict[int, Submission] = {}
        self._next_ticket = 0
        self._nq = 0              # queued submissions across all tenants
        self._hooked_mempools: List[Any] = []
        self._closed = False
        self._ce = None
        self.fairness = TenantFairness()
        # hook the restamping seam: stamp_dynamic_priority now folds our
        # deficit boosts above the class-profile band
        ctx.serve_fairness = self.fairness
        # hook the flow stamp: outgoing wire contexts for pools we own
        # carry the submitting tenant (5th tuple slot, capability-gated)
        ce = getattr(ctx.comm, "ce", ctx.comm) if ctx.comm is not None \
            else None
        fl = getattr(ce, "_flow", None)
        if fl is not None:
            fl.tenants = self.fairness._pools
        # global serve gauges; per-tenant gauges register in open_tenant
        ctx.sde.register_poll(SERVE_TENANTS, lambda: len(self._tenants))
        plog.inform("serve: session server up (admission=%s, rank %d)",
                    self.admission, ctx.rank)

    # ------------------------------------------------------------------ #
    # tenants                                                            #
    # ------------------------------------------------------------------ #
    def open_tenant(self, name: str, weight: Optional[int] = None,
                    quota_bytes: Optional[int] = None, max_pools: int = 0,
                    max_tasks: int = 0) -> Tenant:
        """Open (or re-open idempotently) a named tenant session."""
        if weight is None:
            weight = self.default_weight
        if quota_bytes is None:
            quota_bytes = self.default_quota
        with self._lock:
            t = self._tenants.get(name)
            if t is not None:
                return t
            if len(self._tenants) >= self.max_tenants:
                raise AdmissionError(
                    f"tenant cap reached ({self.max_tenants})")
            t = Tenant(name, weight, quota_bytes, max_pools, max_tasks,
                       lat_ring=self.lat_ring)
            self._tenants[name] = t
        self.fairness.register(name, t.weight)
        self._register_tenant_gauges(t)
        return t

    def close_tenant(self, name: str) -> None:
        with self._lock:
            t = self._tenants.pop(name, None)
            if t is not None:
                self._nq -= len(t.queued)
        if t is None:
            return
        self.fairness.forget(name)
        for gname, fn in t._gauges:
            self.ctx.sde.unregister(gname, fn)
        t._gauges.clear()
        # queued submissions can never launch now: fail them so local
        # and remote waiters unblock instead of timing out
        for sub in t.queued:
            self._finish(sub, error=f"tenant {name!r} closed")
        t.queued.clear()

    def bind_mempool(self, tenant: str, mempool, item_bytes: int) -> None:
        """Feed a named Mempool's outstanding bytes into the tenant's
        quota: ``nb_outstanding * item_bytes`` counts against
        ``quota_bytes`` at admission time, so a tenant holding tiles
        hostage admits less new work.

        The pool's ``on_free`` hook is pointed at :meth:`kick` so that
        quota headroom appearing from a mempool free re-admits queued
        submissions — a tenant with zero in-flight pools has no
        ``_pool_done`` event to drain its queue otherwise."""
        with self._lock:
            t = self._tenants[tenant]
            t.mempools.append((mempool, int(item_bytes)))
        if getattr(mempool, "on_free", None) is None:
            mempool.on_free = self.kick
            self._hooked_mempools.append(mempool)

    def _register_tenant_gauges(self, t: Tenant) -> None:
        name = t.name
        sde = self.ctx.sde

        def _inflight() -> int:
            return t.inflight_pools  # lock: point-in-time gauge read

        def _quota() -> int:
            with self._lock:
                return t.used_bytes_locked()

        def _p99() -> float:
            with self._lock:
                lat = list(t.lat_us)
            return _pct(lat, 0.99) if lat else 0.0

        for gname, fn in ((f"{SERVE_INFLIGHT_PREFIX}::{name}", _inflight),
                          (f"{SERVE_QUOTA_BYTES_PREFIX}::{name}", _quota),
                          (f"{SERVE_P99_LATENCY_PREFIX}::{name}", _p99)):
            sde.register_poll(gname, fn)
            t._gauges.append((gname, fn))

    # ------------------------------------------------------------------ #
    # submission                                                         #
    # ------------------------------------------------------------------ #
    def submit(self, tenant: str, build: Callable[[], Any], *,
               nbytes: int = 0, ntasks: int = 1,
               name: Optional[str] = None) -> Submission:
        """Submit one taskpool for ``tenant``.

        ``build`` is a zero-argument callable returning a NOT-yet-added
        Taskpool (PTG spec instantiation or a DTD closure).  ``nbytes``
        and ``ntasks`` are the declared footprint admission charges
        against the tenant's quota/caps.  Returns a
        :class:`Submission`; raises :class:`AdmissionError` under the
        ``reject`` policy, queues under ``queue``."""
        with self._lock:
            if self._closed:
                raise AdmissionError("server closed")
            t = self._tenants.get(tenant)
            if t is None:
                raise AdmissionError(f"unknown tenant {tenant!r}")
            self._next_ticket += 1
            sub = Submission(self._next_ticket, tenant, build, nbytes,
                             ntasks, name)
            self._subs[sub.ticket] = sub
            verdict = self._admit_locked(t, sub)
            if verdict == "admit":
                self._charge_locked(t, sub)
            elif verdict == "queue":
                t.queued.append(sub)
                self._nq += 1
        if verdict == "admit":
            self.ctx.sde.inc(SERVE_ADMITTED)
            self._launch(sub)
        elif verdict == "queue":
            self.ctx.sde.inc(SERVE_QUEUED)
        else:
            self.ctx.sde.inc(SERVE_REJECTED)
            with self._lock:
                del self._subs[sub.ticket]
            raise AdmissionError(verdict)
        return sub

    def _admit_locked(self, t: Tenant,
                      sub: Submission) -> str:  # holds: self._lock
        """"admit", "queue", or a rejection reason string."""
        over = None
        if t.max_pools and t.inflight_pools >= t.max_pools:
            over = (f"tenant {t.name!r} at max in-flight taskpools "
                    f"({t.max_pools})")
        elif t.max_tasks and t.inflight_tasks + sub.ntasks > t.max_tasks:
            over = (f"tenant {t.name!r} at max in-flight tasks "
                    f"({t.max_tasks})")
        elif t.quota_bytes and \
                t.used_bytes_locked() + sub.nbytes > t.quota_bytes:
            over = (f"tenant {t.name!r} over byte quota "
                    f"({t.used_bytes_locked() + sub.nbytes} > "
                    f"{t.quota_bytes})")
        if over is None:
            return "admit"
        return "queue" if self.admission == "queue" else over

    def _charge_locked(self, t: Tenant,
                       sub: Submission) -> None:  # holds: self._lock
        sub.charged = True
        t.inflight_pools += 1
        t.inflight_tasks += sub.ntasks
        t.inflight_bytes += sub.nbytes

    def _drain_locked(self, t: Tenant
                      ) -> List[Submission]:  # holds: self._lock
        """Pop + charge the tenant's queue head(s) that now fit; the
        caller launches them OUTSIDE the lock."""
        promoted: List[Submission] = []
        while t.queued:
            nxt = t.queued[0]
            if self._admit_locked(t, nxt) != "admit":
                break
            t.queued.popleft()
            self._nq -= 1
            self._charge_locked(t, nxt)
            promoted.append(nxt)
        return promoted

    def _release(self, sub: Submission, *,
                 completed: bool) -> List[Submission]:
        """Un-charge ``sub``'s admission and drain the tenant's queue.

        Every path that charged a submission funnels here — normal
        completion, build/enqueue failure, and taskpool abort — so the
        tenant's capacity can never leak; the ``charged`` flag makes it
        idempotent.  Returns the promoted submissions for the caller to
        launch outside the lock."""
        with self._lock:
            if not sub.charged:
                return []
            sub.charged = False
            t = self._tenants.get(sub.tenant)
            if t is None:
                return []
            t.inflight_pools = max(0, t.inflight_pools - 1)
            t.inflight_tasks = max(0, t.inflight_tasks - sub.ntasks)
            t.inflight_bytes = max(0, t.inflight_bytes - sub.nbytes)
            if completed:
                t.pools_done += 1
                t.lat_us.append(sub.lat_us)
            return self._drain_locked(t)

    def _launch_promoted(self, promoted: List[Submission]) -> None:
        for nxt in promoted:
            self.ctx.sde.inc(SERVE_ADMITTED)
            self._launch(nxt)

    def kick(self) -> None:
        """Re-evaluate every tenant's queued submissions against the
        CURRENT capacity.  Headroom can appear without any same-tenant
        pool completing — a bound Mempool's outstanding bytes dropped —
        and ``_pool_done``'s drain never fires for a tenant with zero
        in-flight pools, so bound mempools invoke this from their free
        path (callers with external quota feeds may call it directly).
        Lock-free fast path: the plain global queued-count."""
        if not self._nq:
            return
        promoted: List[Submission] = []
        with self._lock:
            if self._closed:
                return
            for t in self._tenants.values():
                if t.queued:
                    promoted.extend(self._drain_locked(t))
        self._launch_promoted(promoted)

    def _launch(self, sub: Submission) -> None:
        """Build + enqueue OUTSIDE the server lock (add_taskpool takes
        runtime locks and may schedule inline)."""
        try:
            tp = sub.build()
        except Exception as exc:  # noqa: BLE001 - surface on the waiter
            promoted = self._release(sub, completed=False)
            self._finish(sub, error=f"build failed: {exc!r}")
            self._launch_promoted(promoted)
            return
        sub.taskpool = tp
        self.fairness.bind_pool(tp.taskpool_id, sub.tenant)
        tp._complete_cbs.append(lambda _tp: self._pool_done(sub))
        tp._abort_cbs.append(lambda _tp: self._pool_aborted(sub))
        try:
            self.ctx.add_taskpool(tp)
        except Exception as exc:  # noqa: BLE001
            self.fairness.release_pool(tp.taskpool_id)
            promoted = self._release(sub, completed=False)
            self._finish(sub, error=f"enqueue failed: {exc!r}")
            self._launch_promoted(promoted)
            return
        if getattr(tp, "_alive", False):
            # DTD pools hold a keep-alive runtime action for
            # post-enqueue inserts that normally only tp.wait() drops; a
            # served submission is sealed at build time (every insert
            # already happened inside build), so drop it here —
            # termination is then detected without any caller blocking
            # in tp.wait()
            tp._alive = False
            tp.tdm.taskpool_addto_runtime_actions(-1)
        # a persistent context parks its workers between waves; re-arm
        # them for the new pool (no-op while a wave is already running)
        self.ctx.start()

    def _pool_done(self, sub: Submission) -> None:
        """Completion hook — fires on a worker thread inside taskpool
        termination; charge fairness, release admission, drain queue."""
        self._settle(sub, error=None)

    def _pool_aborted(self, sub: Submission) -> None:
        """Abort hook (``Taskpool.abort``, the ft/ eviction path): the
        pool will never terminate, but its admission charges must not
        outlive it — release capacity, unbind fairness, and fail the
        submission so local and remote waiters unblock instead of
        riding their timeout."""
        self._settle(sub, error="taskpool aborted (rank eviction)")

    def _settle(self, sub: Submission, error: Optional[str]) -> None:
        lat_us = (time.monotonic_ns() - sub.t_submit_ns) / 1e3
        sub.lat_us = lat_us
        tp = sub.taskpool
        if tp is not None:
            self.fairness.release_pool(tp.taskpool_id)
        # aborted pools still charge virtual runtime: an always-failing
        # tenant must not accrue an unbounded deficit boost over
        # healthy ones
        self.fairness.note_done(sub.tenant, sub.ntasks)
        if error is None:
            live = getattr(self.ctx.obs, "live", None)
            if live is not None:
                live.note_tenant_latency(sub.tenant, lat_us)
        promoted = self._release(sub, completed=error is None)
        self._finish(sub, error)
        self._launch_promoted(promoted)

    def _finish(self, sub: Submission, error: Optional[str]) -> None:
        sub.error = error
        with self._lock:
            waiters = list(sub.waiters)
            sub.waiters.clear()
        sub.done.set()
        for src, req in waiters:
            self._reply(src, req, ok=error is None,
                        ticket=sub.ticket, lat_us=sub.lat_us,
                        **({"error": error} if error else {}))

    # ------------------------------------------------------------------ #
    # introspection                                                      #
    # ------------------------------------------------------------------ #
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {"tenants": {}}
            for name, t in self._tenants.items():
                lat = list(t.lat_us)
                out["tenants"][name] = {
                    "weight": t.weight,
                    "inflight_pools": t.inflight_pools,
                    "inflight_tasks": t.inflight_tasks,
                    "queued": len(t.queued),
                    "used_bytes": t.used_bytes_locked(),
                    "quota_bytes": t.quota_bytes,
                    "pools_done": t.pools_done,
                    "p50_lat_us": round(_pct(lat, 0.50), 1) if lat else 0.0,
                    "p99_lat_us": round(_pct(lat, 0.99), 1) if lat else 0.0,
                    "boost": self.fairness.boost_of_tenant(name),
                }
        return out

    # ------------------------------------------------------------------ #
    # remote endpoint (TAG_SERVE over the AM layer)                      #
    # ------------------------------------------------------------------ #
    def attach_engine(self, ce) -> None:
        """Serve remote clients: install the ``TAG_SERVE`` handler on
        ``ce``.  Over TCP the peer must have negotiated the HELLO
        ``"sv"`` capability (``ce.serve_to``) for its submissions to be
        honored."""
        from ..comm.engine import TAG_SERVE
        self._ce = ce
        fl = getattr(ce, "_flow", None)
        if fl is not None:
            fl.tenants = self.fairness._pools
        ce.tag_register(TAG_SERVE, self._on_request)

    def _reply(self, src: int, req: int, ok: bool, **kw) -> None:
        ce = self._ce
        if ce is None or src == self.ctx.rank:
            return
        from ..comm import wire
        from ..comm.engine import TAG_SERVE_REPLY
        try:
            ce.send_am(src, TAG_SERVE_REPLY, wire.serve_reply(req, ok, **kw))
        except Exception as exc:  # noqa: BLE001 - a dead client is not fatal
            plog.warning("serve: reply to rank %d failed: %r", src, exc)

    def _on_request(self, src: int, payload: Any) -> None:
        from ..comm import wire
        try:
            msg = wire.parse_serve(payload)
        except ValueError as exc:
            plog.warning("serve: bad request from rank %d: %r", src, exc)
            return
        if not self._ce.serve_to(src):
            # a peer that never negotiated "sv" gets a versioned error,
            # not silence — it can only hit this via a buggy client
            self._reply(src, msg["req"], ok=False,
                        error="peer did not negotiate the sv capability")
            return
        req = msg["req"]
        op = msg.get("op")
        try:
            if op == "open":
                t = self.open_tenant(
                    msg["tenant"], weight=msg.get("weight"),
                    quota_bytes=msg.get("quota_bytes"),
                    max_pools=msg.get("max_pools", 0),
                    max_tasks=msg.get("max_tasks", 0))
                self._reply(src, req, ok=True, tenant=t.name,
                            weight=t.weight, quota_bytes=t.quota_bytes)
            elif op == "submit":
                sub = self.submit(msg["tenant"], msg["build"],
                                  nbytes=msg.get("nbytes", 0),
                                  ntasks=msg.get("ntasks", 1),
                                  name=msg.get("name"))
                self._reply(src, req, ok=True, ticket=sub.ticket,
                            queued=sub.taskpool is None
                            and not sub.done.is_set())
            elif op == "wait":
                ticket = msg["ticket"]
                with self._lock:
                    sub = self._subs.get(ticket)
                    defer = sub is not None and not sub.done.is_set()
                    if defer:
                        sub.waiters.append((src, req))
                if sub is None:
                    self._reply(src, req, ok=False,
                                error=f"unknown ticket {ticket}")
                elif not defer:
                    self._reply(src, req, ok=sub.error is None,
                                ticket=ticket, lat_us=sub.lat_us,
                                **({"error": sub.error}
                                   if sub.error else {}))
            elif op == "stats":
                self._reply(src, req, ok=True, stats=self.stats())
            else:
                self._reply(src, req, ok=False, error=f"unknown op {op!r}")
        except AdmissionError as exc:
            self._reply(src, req, ok=False, error=str(exc), rejected=True)
        except Exception as exc:  # noqa: BLE001 - handler must not kill comm
            plog.warning("serve: op %r from rank %d failed: %r",
                         op, src, exc)
            self._reply(src, req, ok=False, error=repr(exc))

    # ------------------------------------------------------------------ #
    # shutdown                                                           #
    # ------------------------------------------------------------------ #
    def close(self) -> None:
        """Detach from the context: unhook fairness/flow/gauges.  Does
        not wait for in-flight pools (use Submission.wait / ctx.wait)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            tenants = list(self._tenants.values())
            self._tenants.clear()
            self._nq = 0
        for mp in self._hooked_mempools:
            if getattr(mp, "on_free", None) == self.kick:
                mp.on_free = None
        self._hooked_mempools = []
        for t in tenants:
            self.fairness.forget(t.name)
            for gname, fn in t._gauges:
                self.ctx.sde.unregister(gname, fn)
            for sub in t.queued:
                self._finish(sub, error="server closed")
            t.queued.clear()
        self.ctx.sde.unregister(SERVE_TENANTS)
        self.ctx.serve_fairness = None
        ce = getattr(self.ctx.comm, "ce", self.ctx.comm) \
            if self.ctx.comm is not None else None
        fl = getattr(ce, "_flow", None)
        if fl is not None and fl.tenants is self.fairness._pools:
            fl.tenants = None

    def __enter__(self) -> "SessionServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _pct(xs: List[float], q: float) -> float:
    """Nearest-rank percentile (mirrors obs/live.py's helper; duplicated
    so serve/ has no import-time dependency on the live monitor)."""
    if not xs:
        return 0.0
    s = sorted(xs)
    k = max(0, min(len(s) - 1, int(round(q * len(s) + 0.5)) - 1))
    return float(s[k])
