"""Device module base + registry + load-balanced placement.

Reference behavior: ``parsec_device_module_t`` {attach, taskpool_register,
memory_register, data_advise, ...} with per-device capability weights and
``parsec_get_best_device`` = min(load + ratio*weight) with a sticky-device
skew toward where the data already lives
(ref: parsec/mca/device/device.c:79-168, device.h:77-125).
"""
from __future__ import annotations

import threading
from typing import Any, List, Optional

from ..utils.params import params


class Device:
    """ref: parsec_device_module_t"""

    def __init__(self, device_type: str, device_index: int, name: str = "") -> None:
        self.device_type = device_type
        self.device_index = device_index
        self.name = name or f"{device_type}:{device_index}"
        self.device_load = 0.0          # outstanding estimated work (ns-ish)
        self.time_estimate_default = 1.0  # per-task default cost weight
        self.executed_tasks = 0
        self._load_lock = threading.Lock()
        # telemetry sink (obs.spans.DeviceObs); wired by ContextObs —
        # None keeps transfer sites on the one-attribute-check fast path
        self._obs = None

    # registration hooks (no-ops by default)
    def taskpool_register(self, tp) -> None:
        pass

    def taskpool_unregister(self, tp) -> None:
        pass

    def memory_register(self, buf) -> None:
        pass

    def memory_unregister(self, buf) -> None:
        pass

    def data_advise(self, data, advice: str) -> None:
        """advice in {"prefetch", "preferred_device", "warmup"}
        (ref: parsec_mca_device_data_advise)."""

    def load_add(self, est: float) -> None:
        with self._load_lock:
            self.device_load += est

    def load_sub(self, est: float) -> None:
        with self._load_lock:
            self.device_load = max(0.0, self.device_load - est)

    def progress(self, es) -> int:
        """Advance asynchronous work; returns the number of pipeline
        steps handled (completions AND submissions — a batched device
        flushing its accumulated ready queue made progress even when
        nothing finished yet)."""
        return 0

    def drain(self, context=None) -> None:
        """Flush the device pipeline at a run boundary: retire trailing
        in-flight records (recording async errors on ``context``) and
        discard ready-queue entries stranded by a DAG abort.  Called by
        ``Context.wait()`` exit and the FT rollback path
        (``Context._drain_devices``); no-op for synchronous devices."""

    def fini(self) -> None:
        pass

    def __repr__(self) -> str:  # pragma: no cover
        return f"<Device {self.name} load={self.device_load:.1f}>"


def get_best_device(task, devices: List[Device],
                    eligible_types: Optional[set] = None) -> Device:
    """ref: parsec_get_best_device (device.c:79-168).

    Sticky skew: a device already holding a valid copy of one of the task's
    written flows gets a ``device_load_balance_skew`` percent discount.
    """
    skew = params.get("device_load_balance_skew") / 100.0
    best, best_score = None, None
    data_homes = set()
    for ref in task.data:
        din = ref.data_in
        if din is not None and din.data is not None:
            od = din.data.owner_device
            if od >= 0:
                data_homes.add(od)
    for dev in devices:
        if eligible_types is not None and dev.device_type not in eligible_types:
            continue
        est = dev.time_estimate_default
        tc = task.task_class
        if tc.time_estimate is not None:
            est = tc.time_estimate(task, dev)
        score = dev.device_load + est
        if dev.device_index in data_homes:
            score *= (1.0 - skew)
        if best_score is None or score < best_score:
            best, best_score = dev, score
    assert best is not None, "no eligible device"
    return best
