"""Regression: a device-only BODY must produce correct results when run on
the host fallback (functional-style rebinding written back), and prologue
helpers must see each other."""
import numpy as np

import parsec_tpu
from parsec_tpu.collections import LocalArrayCollection
from parsec_tpu.dsl import ptg

DEVICE_ONLY_JDF = """
descA [ type="collection" ]
N [ type="int" ]

Inc(k)
k = 0 .. N-1
: descA( k )
RW A <- descA( k )
     -> descA( k )
BODY [type=tpu]
{
    A = A + 1.0
}
END
"""


def test_device_body_on_host_fallback_writes_back():
    ctx = parsec_tpu.Context(nb_cores=1, enable_tpu=False)
    try:
        arr = np.zeros((4, 2), dtype=np.float32)
        coll = LocalArrayCollection(arr, 4)
        tp = ptg.compile_jdf(DEVICE_ONLY_JDF, name="inc").new(descA=coll, N=4)
        ctx.add_taskpool(tp)
        ctx.wait()
        np.testing.assert_allclose(arr, 1.0)
    finally:
        ctx.fini()


PROLOGUE_JDF = '''
extern "C" %{
def helper_g(x):
    return x * 2

def helper_f(x):
    return helper_g(x) + 1
%}

descA [ type="collection" ]
N [ type="int" ]

T(k)
k = 0 .. N-1
: descA( k )
RW A <- descA( k )
BODY
{
    A[0] = helper_f(k)
}
END
'''


def test_prologue_helpers_see_each_other(ctx):
    arr = np.zeros((4, 1))
    coll = LocalArrayCollection(arr, 4)
    tp = ptg.compile_jdf(PROLOGUE_JDF, name="prol").new(descA=coll, N=4)
    ctx.add_taskpool(tp)
    ctx.wait()
    np.testing.assert_allclose(arr[:, 0], [1.0, 3.0, 5.0, 7.0])


def test_multirank_without_comm_raises():
    """A remote successor with no comm engine must fail loudly, not corrupt
    counters or hang."""
    import pytest
    from parsec_tpu.collections import TwoDimBlockCyclic
    JDF = """
descA [ type="collection" ]
N [ type="int" ]

T(k)
k = 0 .. N-1
: descA( k, 0 )
RW A <- descA( k, 0 )
     -> (k < N-1) ? A T( k+1 )
BODY
{
    A[0] += 1
}
END
"""
    ctx = parsec_tpu.Context(nb_cores=1, enable_tpu=False)
    try:
        # 2-rank distribution: successor of T(0) lives on rank 1
        coll = TwoDimBlockCyclic(4 * 8, 8, 8, 8, P=2, Q=1, nodes=2, rank=0)
        tp = ptg.compile_jdf(JDF, name="mr").new(descA=coll, N=4,
                                                 rank=0, nb_ranks=2)
        ctx.add_taskpool(tp)
        with pytest.raises(RuntimeError):
            ctx.wait()
    finally:
        ctx.fini()
