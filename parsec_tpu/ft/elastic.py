"""Elastic grid recovery: cross-grid checkpoint reshard + rank shrink/join.

PR 4 gave detection, injection, and checkpoint-restart — but recovery
required the *identical* rank count and process grid
(``utils/checkpoint`` fails fast on any mismatch), so losing one rank
killed the job until an operator rebuilt the exact same world. This
module turns that dead end into a recovery path ("Memory-efficient
array redistribution", arXiv:2112.01075 — the reshard machinery was
already on the shelf in ``collections/redistribute``):

- :func:`reshard_restore` — **cross-grid restore**: a snapshot written
  on any ``nodes/P×Q`` grid lands on the *current* grid. Each current
  participant loads the writer shards folded onto it, materializes a
  source-distribution :class:`_SnapshotView`, and drives
  ``collections.redistribute`` (whole-tile reshuffle fast path when
  the tile grids match — always true here, geometry is immutable — and
  fragment assembly otherwise) so every tile reaches its new owner
  over the ordinary DTD data plane. Reached through
  ``restore_collection(..., reshard=True)``; the strict default is
  untouched.
- :class:`ElasticCoordinator` — **membership agreement** over a new
  ``TAG_ELASTIC`` active message (wire-level ``K_ELASTIC`` on TCP,
  delivered by the receiver thread like ``K_PING``; mixed-version
  peers are excluded by the HELLO ``"el"`` capability exactly like
  heartbeats' ``"hb"``). A leader-decided vote/commit round: every
  voter sends its proposed member set + resume stage to all voters,
  the lowest-ranked voter commits when all votes match (or aborts a
  grow round whose window expired), and joiners receive a ``welcome``
  naming the member set and the snapshot stage to reshard from.
- **Shrink** (``--mca ft_elastic shrink``): when the heartbeat
  detector evicts a rank mid-run, ``ft.run_with_restart`` no longer
  only aborts — the survivors agree on a reduced grid (deterministic
  from the surviving rank set, :func:`plan_grid`), rebuild their
  collections on it (:class:`ElasticPolicy.rebuild`), reshard-restore
  the last snapshot, and replay from ``last_snap``. No human in the
  loop; the dead rank's *data* survives on disk in its shard files.
- **Join** (``ft_elastic grow`` / ``both``): a late rank announces
  itself; the incumbents fold it in at the next quiescent point
  (a stage boundary with a fresh snapshot), gated by
  ``ft_elastic_grow_min``; the same reshard machinery spreads tiles
  onto the grown grid.

Trust model: crash faults only. A membership view's ``dead`` list is
gossip from a peer's own detector and is believed (it accelerates
convergence when detectors fire at different times); a byzantine rank
could abuse it, which is outside this module's scope.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import logging as plog
from ..utils.params import params

__all__ = ["GridSpec", "plan_grid", "ElasticBlockCyclic", "ElasticPolicy",
           "ElasticCoordinator", "ElasticError", "reshard_restore",
           "maybe_install_elastic"]

#: cross-thread coordinator state (detector callback / transport
#: receiver threads deliver; the restart driver thread waits) — all of
#: it behind the one condition, whose notify doubles as the wakeup
_GUARDED_BY = {
    "ElasticCoordinator._views":   "_cond",
    "ElasticCoordinator._joins":   "_cond",
    "ElasticCoordinator._welcome": "_cond",
    "ElasticCoordinator._commit":  "_cond",
    "ElasticCoordinator._aborts":  "_cond",
    "ElasticCoordinator._epoch":   "_cond",
}

#: how often a waiting voter/joiner re-sends its current vote or join
#: announcement — membership frames ride the chaos-injected transports,
#: so the protocol must survive dropped frames
_RESEND_S = 0.25
#: default overall agreement deadline (``ft_elastic_timeout``)
_TIMEOUT_S = 30.0
#: a grow round is OPTIONAL (incumbents may proceed without resizing),
#: so the leader only holds the stage boundary this long for votes
_GROW_WINDOW_S = 5.0


class ElasticError(RuntimeError):
    """Membership agreement failed (timeout, eviction mid-agreement,
    or this rank was shrunk out) — the caller falls back to the strict
    abort path with the on-disk snapshot set still consistent."""


# --------------------------------------------------------------------- #
# grids                                                                 #
# --------------------------------------------------------------------- #
class GridSpec:
    """A deterministic process grid over an explicit member set.

    ``members[logical] = world rank``: collections built on the spec
    keep WORLD ranks in ``rank_of`` (comm addressing is untouched);
    only the block-cyclic math runs on logical coordinates. Every rank
    derives the same spec from the same member set — that determinism
    IS the agreement shortcut (peers exchange member sets, never
    layouts)."""

    def __init__(self, members: Sequence[int], world: int, rank: int) -> None:
        self.members = tuple(sorted(members))
        assert len(set(self.members)) == len(self.members), "duplicate members"
        self.world = int(world)
        self.rank = int(rank)
        n = len(self.members)
        # most-square factorization, rows >= cols (the tools/northstar
        # convention): n=4 -> 2x2, n=2 -> 2x1, n=3 -> 3x1
        q = max(p for p in range(1, int(n ** 0.5) + 1) if n % p == 0)
        self.P, self.Q = n // q, q

    @property
    def nodes(self) -> int:
        return len(self.members)

    def collection(self, lm: int, ln: int, mb: int, nb: int,
                   **kw: Any) -> "ElasticBlockCyclic":
        """A block-cyclic collection on this grid (manifest records
        ``members`` so a snapshot written here reshards back)."""
        return ElasticBlockCyclic(lm, ln, mb, nb, P=self.P, Q=self.Q,
                                  members=self.members, nodes=self.world,
                                  rank=self.rank, **kw)

    def __eq__(self, other: Any) -> bool:
        return (isinstance(other, GridSpec)
                and self.members == other.members and self.world == other.world)

    def __repr__(self) -> str:  # pragma: no cover
        return (f"GridSpec({self.P}x{self.Q} over members={self.members} "
                f"world={self.world})")


def plan_grid(members: Sequence[int], world: int, rank: int) -> GridSpec:
    """The one deterministic member-set -> grid function (shrink and
    grow both go through here, so every participant lands on the same
    layout without exchanging it)."""
    return GridSpec(members, world, rank)


from ..collections.matrix import TiledMatrix, TwoDimBlockCyclic  # noqa: E402


class ElasticBlockCyclic(TwoDimBlockCyclic):
    """2D block-cyclic over an explicit ``members`` world-rank map.

    ``nodes`` stays the WORLD size and ``rank`` the world rank, so the
    comm layer addresses real peers; ``rank_of`` routes the logical
    block-cyclic owner through ``members``. With the identity map this
    is exactly ``TwoDimBlockCyclic``."""

    def __init__(self, lm: int, ln: int, mb: int, nb: int,
                 members: Sequence[int], P: int = 1, Q: int = 1,
                 nodes: Optional[int] = None, rank: int = 0, **kw: Any) -> None:
        members = tuple(members)
        assert len(members) == P * Q, \
            f"grid {P}x{Q} needs {P * Q} members, got {len(members)}"
        world = nodes if nodes is not None else (max(members) + 1)
        super().__init__(lm, ln, mb, nb, P=P, Q=Q, nodes=world, rank=rank,
                         **kw)
        self.members = members

    def rank_of(self, m: int, n: int) -> int:
        return self.members[super().rank_of(m, n)]


# --------------------------------------------------------------------- #
# cross-grid restore                                                    #
# --------------------------------------------------------------------- #
def _participants(man_or_coll: Any) -> List[int]:
    """World ranks that own at least one logical grid slot — from a
    manifest dict or a live collection. ``members`` when recorded
    (elastic grids), else the identity map over the logical grid."""
    if isinstance(man_or_coll, dict):
        man = man_or_coll
        if man.get("members") is not None:
            return list(man["members"])
        p, q = man.get("P"), man.get("Q")
        if p and q:
            return list(range(int(p) * int(q)))
        return list(range(int(man.get("nodes", 1))))
    coll = man_or_coll
    if getattr(coll, "members", None) is not None:
        return list(coll.members)
    p, q = getattr(coll, "P", None), getattr(coll, "Q", None)
    if p and q:
        return list(range(p * q))
    return list(range(getattr(coll, "nodes", 1)))


def _src_rank_fn(man: Dict[str, Any]) -> Callable[[int, int], int]:
    """Reconstruct the snapshot grid's tile -> world-rank function from
    its manifest (the ``rank_of`` of a collection we no longer have)."""
    part = _participants(man)
    p, q = man.get("P"), man.get("Q")
    if p and q:
        P, Q = int(p), int(q)
        kr = int(man.get("krows", 1) or 1)
        kc = int(man.get("kcols", 1) or 1)

        def rank_of(m: int, n: int) -> int:
            return part[((m // kr) % P) * Q + (n // kc) % Q]
        return rank_of
    if len(part) == 1:
        return lambda m, n: part[0]
    raise ValueError(
        f"cannot reshard a {man.get('kind')!r} snapshot: its manifest "
        f"records no P/Q grid to reconstruct tile ownership from")


def _shard_identity(man: Dict[str, Any]) -> Tuple:
    """Everything that must agree across one snapshot's shard files —
    a mixed set (stale shards of an older grid left beside a newer
    save) must be rejected, not silently blended."""
    return tuple((k, repr(man.get(k)))
                 for k in ("lm", "ln", "mb", "nb", "dtype", "uplo", "kind",
                           "nodes", "P", "Q", "krows", "kcols", "members"))


def _load_folded_shards(prefix: str, man: Dict[str, Any],
                        writers: List[int], mine: List[int]):
    """Load tile arrays from the writer shards folded onto this rank.
    Returns {(m, n): array}. A torn shard or an identity mismatch
    raises CheckpointCorruptError (the restart driver then falls back
    to the previous complete snapshot)."""
    from ..utils import checkpoint as ckpt
    ident = _shard_identity(man)
    loaded: Dict[Tuple[int, int], Any] = {}
    for w in writers:
        if w not in mine:
            continue
        path = ckpt.checkpoint_path(prefix, w)
        with ckpt._open_snapshot(path) as z:
            import json
            shard_man = json.loads(str(z["__manifest__"]))
            if _shard_identity(shard_man) != ident:
                raise ckpt.CheckpointCorruptError(
                    f"checkpoint shard {path} disagrees with the other "
                    f"shards' manifest — a stale shard from a different "
                    f"grid is mixed into this snapshot")
            for name in z.files:
                if not name.startswith("t"):
                    continue
                m, n = map(int, name[1:].split("_"))
                loaded[(m, n)] = z[name]
    return loaded


def _make_view(coll: Any, man: Dict[str, Any], loaded: Dict, fold, src_rank):
    """Source-distribution view over the loaded shard arrays: tiles
    live where the fold landed them; redistribute moves them to the
    target's owners."""

    class _SnapshotView(TiledMatrix):
        def rank_of(self, m: int, n: int) -> int:
            return fold(src_rank(m, n))

    view = _SnapshotView(coll.lm, coll.ln, coll.mb, coll.nb,
                         dtype=coll.dtype, nodes=coll.nodes, rank=coll.rank,
                         uplo=man.get("uplo", "full"))
    view.name = f"{coll.name}::snapshot"
    missing = []
    for (m, n) in view.tiles():
        if view.rank_of(m, n) != view.rank:
            continue
        arr = loaded.get((m, n))
        if arr is None:
            missing.append((m, n))
            continue
        view.set_tile(m, n, arr)
    if missing:
        from ..utils import checkpoint as ckpt
        raise ckpt.CheckpointCorruptError(
            f"snapshot {view.name} is missing tiles {missing[:8]}"
            f"{'...' if len(missing) > 8 else ''} after loading every "
            f"reachable shard — shard files were lost or torn")
    return view


def reshard_restore(coll: Any, prefix: str,
                    context: Optional[Any] = None) -> int:
    """Restore ``coll`` from a snapshot written on a DIFFERENT grid.

    Geometry (tiling, dtype, extent, uplo) must match — resharding
    redistributes tiles, it cannot reinterpret bytes — so a tile-size
    mismatch still hard-fails with :class:`CheckpointMismatchError`.
    The distribution is free: any writer ``nodes``/P×Q/``members``
    lands on ``coll``'s grid.

    SPMD: call on every CURRENT participant with its own ``context``
    (required whenever the current grid spans more than one rank — the
    move is a collective DTD redistribution). A single-participant
    target restores directly, no context needed, provided every writer
    shard is reachable from this process. Returns the number of local
    tiles restored."""
    from ..collections.redistribute import redistribute
    from ..utils import checkpoint as ckpt

    t0 = time.perf_counter()
    man = ckpt.find_manifest(prefix)
    ours = ckpt._manifest_of(coll)
    geom_bad = [k for k in ckpt.GEOMETRY_KEYS
                if man.get(k, ours.get(k)) != ours.get(k)]
    if geom_bad:
        detail = "; ".join(f"{k}: snapshot {man.get(k)!r} != ours "
                           f"{ours.get(k)!r}" for k in geom_bad)
        raise ckpt.CheckpointMismatchError(
            f"cannot reshard {prefix}: tile GEOMETRY diverges ({detail}) "
            f"— resharding redistributes tiles between grids, it cannot "
            f"reinterpret tile shapes or dtypes")

    # the writer set and the fold come from the MANIFEST, never the
    # filesystem: ranks whose storage shows a different file set would
    # otherwise build divergent folds and the collective redistribution
    # below would be inserted inconsistently across ranks
    writers = sorted(set(_participants(man)))
    cur = _participants(coll)
    fold_map = {w: cur[i % len(cur)] for i, w in enumerate(writers)}
    src_rank = _src_rank_fn(man)
    mine = [w for w, r in fold_map.items() if r == coll.rank]

    def fold(w: int) -> int:
        return fold_map[w]

    loaded = _load_folded_shards(prefix, man, writers, mine)

    if len(cur) == 1:
        # single current participant: every writer folds here — plain
        # host copies, no taskpool/comm machinery required
        n = 0
        for (m, n_) in coll.tiles():
            if coll.rank_of(m, n_) != coll.rank:
                continue  # pragma: no cover - single participant owns all
            arr = loaded.get((m, n_))
            if arr is None:
                raise ckpt.CheckpointCorruptError(
                    f"snapshot {prefix} has no tile ({m},{n_}) in any "
                    f"reachable shard")
            coll.set_tile(m, n_, arr)
            n += 1
        _note_reshard(context, coll, n, t0)
        return n

    if context is None:
        raise ValueError(
            "reshard_restore onto a multi-rank grid is a collective "
            "redistribution: pass the rank's context (and call on every "
            "participant)")
    if getattr(coll, "name", None) in (None, type(coll).__name__):
        # the DTD registry keys tile messages by collection name: pin a
        # deterministic one before the SPMD-consistent insertion below
        coll.name = "resharded"
    view = _make_view(coll, man, loaded, fold, src_rank)
    tp = redistribute(view, coll, coll.lm, coll.ln, context=context,
                      tiles=list(coll.tiles()))
    n = sum(1 for _ in coll.local_tiles())
    plog.debug.verbose(2, "ft.elastic: reshard plan moved %d bytes "
                       "globally", getattr(tp, "redist_bytes", 0))
    _note_reshard(context, coll, n, t0)
    return n


def _note_reshard(context: Any, coll: Any, ntiles: int, t0: float) -> None:
    """Feed the FT::RESHARD_* gauges (engine-owned counters polled by
    obs.register_engine_gauges) — bytes = local tiles LANDED here."""
    ce = _engine_of(context)
    if ce is None:
        return
    nbytes = sum(
        coll.tile_shape(m, n)[0] * coll.tile_shape(m, n)[1]
        * coll.dtype.itemsize
        for (m, n) in coll.local_tiles())
    ce.elastic_stats["reshard_bytes"] += int(nbytes)
    ce.elastic_stats["reshard_us"] += int((time.perf_counter() - t0) * 1e6)
    plog.debug.verbose(2, "ft.elastic: resharded %d tile(s) / %d bytes "
                       "onto rank %d", ntiles, nbytes, coll.rank)


def _engine_of(context: Any) -> Optional[Any]:
    if context is None:
        return None
    comm = getattr(context, "comm", None)
    if comm is None:
        return None
    return getattr(comm, "ce", comm)


# --------------------------------------------------------------------- #
# membership agreement                                                  #
# --------------------------------------------------------------------- #
class ElasticCoordinator:
    """Per-rank membership agreement over TAG_ELASTIC / K_ELASTIC.

    Attaches to the engine (draining any frames buffered before a
    coordinator existed — a joiner may announce while the incumbents
    are mid-stage) and runs leader-decided vote/commit rounds:

    - every VOTER sends ``{"kind": "view", op, members, stage, epoch}``
      to all voters and records its own;
    - the LEADER (lowest-ranked voter) commits when every voter's view
      matches its proposal — broadcast ``commit`` + ``welcome`` the
      joiners — or, for an optional grow round, broadcasts ``abort``
      when the decision window expires;
    - NON-LEADERS wait for the matching decision; a leader death
      re-enters the round with the next-lowest leader.

    Shrink rounds are mandatory (survivors have nothing else to do, so
    they hold until the deadline then fall back to the strict abort);
    grow rounds are optional (the boundary is held only ``window``
    seconds — missing joiners stay pending for the next boundary).
    """

    def __init__(self, ce: Any) -> None:
        self.ce = ce
        self.rank = ce.rank
        self.world = ce.nb_ranks
        self._cond = threading.Condition()
        self._views: Dict[int, Dict[str, Any]] = {}
        self._joins: set = set()
        self._welcome: Optional[Dict[str, Any]] = None
        self._commit: Optional[Dict[str, Any]] = None
        self._aborts: set = set()          # (op, stage, epoch) tuples
        self._epoch = 0
        # attach under the engine's deferred lock: _on_elastic holds it
        # for its attach-check-or-buffer step, so no frame can slip
        # between this drain and the attach
        with ce._deferred_lock:
            buf = list(ce._elastic_buf)
            ce._elastic_buf.clear()
            ce.ft_elastic = self
        for src, payload in buf:
            self.deliver(src, payload)

    def detach(self) -> None:
        with self.ce._deferred_lock:
            if self.ce.ft_elastic is self:
                self.ce.ft_elastic = None

    # -- transport hooks (any thread) -----------------------------------
    def membership_changed(self) -> None:
        """A peer died or finished: wake any agreement wait so it
        re-proposes from the reduced set instead of waiting out its
        resend tick."""
        with self._cond:
            self._cond.notify_all()

    def deliver(self, src: int, payload: Dict[str, Any]) -> None:
        """One TAG_ELASTIC/K_ELASTIC frame (progress drain, or the TCP
        receiver thread)."""
        kind = payload.get("kind")
        gossip: List[int] = []
        with self._cond:
            if kind == "view":
                self._views[src] = payload
                for j in payload.get("joins", ()):
                    if j != self.rank:
                        self._joins.add(int(j))
                gossip = [int(d) for d in payload.get("dead", ())
                          if d != self.rank and d not in self.ce.dead_peers]
            elif kind == "join":
                self._joins.add(src)
            elif kind == "welcome":
                self._welcome = payload
            elif kind == "commit":
                self._commit = payload
            elif kind == "abort":
                self._aborts.add((payload.get("op"), payload.get("stage"),
                                  payload.get("epoch")))
            self._cond.notify_all()
        det = self.ce.ft_detector
        if det is not None:
            det.note_alive(src)   # an elastic frame is proof of life
        for d in gossip:
            # believe a peer's detector (crash-fault trust model): it
            # saw the death first; converging on the dead set NOW beats
            # waiting out our own heartbeat deadline
            self.ce.report_peer_failure(
                d, f"elastic membership view from rank {src}")

    # -- joiner side -----------------------------------------------------
    def announce_join(self, deadline_s: float = _TIMEOUT_S) -> Dict[str, Any]:
        """Broadcast this rank's arrival and wait for a welcome naming
        the member set and the snapshot stage to reshard from."""
        t_end = time.monotonic() + deadline_s
        last_tx = 0.0
        while time.monotonic() < t_end:
            now = time.monotonic()
            if now - last_tx >= _RESEND_S:
                last_tx = now
                for p in range(self.world):
                    if p != self.rank and p not in self.ce.dead_peers:
                        self.ce.ft_elastic_send(p, {"kind": "join"})
            with self._cond:
                w = self._welcome
                if w is not None:
                    self._welcome = None
                    self._epoch = int(w.get("epoch", self._epoch))
                    return w
                self._cond.wait(timeout=0.02)
            self.ce.progress()
        raise ElasticError(
            f"rank {self.rank}: join announcement went unanswered for "
            f"{deadline_s:.0f}s (no incumbent reached a quiescent point, "
            f"or grow is disabled on the incumbents)")

    def pending_joins(self, members: Sequence[int]) -> List[int]:
        with self._cond:
            return sorted(j for j in self._joins
                          if j not in members and j not in self.ce.dead_peers)

    # -- member side -----------------------------------------------------
    def _alive(self, members: Sequence[int]) -> List[int]:
        return [r for r in members
                if r == self.rank or (r not in self.ce.dead_peers
                                      and not self.ce.peer_finished(r))]

    def agree(self, op: str, members: Sequence[int], stage: int,
              deadline_s: float = _TIMEOUT_S,
              window_s: Optional[float] = None,
              tp_next: Optional[int] = None) -> Optional[Dict[str, Any]]:
        """One agreement round as voter (and leader when lowest).

        Returns the decision ``{"members": tuple, "tp_base": int|None}``,
        or None when an optional (grow) round was aborted; raises
        :class:`ElasticError` on deadline or when this rank is excluded
        from the commit.

        ``tp_next`` is this rank's next taskpool WIRE id
        (``RemoteDepEngine.next_tp_id``): DTD traffic is keyed by
        registration-order wire ids, and participants of a resize can
        disagree on them (survivors diverge by one registration at a
        mid-stage failure; a joiner registered nothing at all), so every
        vote carries the counter and the commit/welcome carries
        ``tp_base`` — the max — which every participant syncs to before
        registering the reshard pool."""
        assert op in ("shrink", "grow")
        with self._cond:
            # an ABORTED round leaves same-epoch views behind (only a
            # commit concludes/bumps): drop them so this round's leader
            # cannot instantly "commit" on the previous boundary's
            # votes — live voters re-send within one resend tick
            self._views.clear()
        t_end = time.monotonic() + deadline_s
        while time.monotonic() < t_end:
            voters = self._alive(members)
            if not voters or (voters == [self.rank] and op == "shrink"):
                # last one standing: nothing to agree with
                self._conclude((self.rank,))
                return {"members": (self.rank,), "tp_base": tp_next,
                        "stage": stage}
            if op == "grow":
                joins = self.pending_joins(members)
                if not joins:
                    return None   # everyone already folded in elsewhere
                prop = tuple(sorted(set(voters) | set(joins)))
            else:
                joins = []
                prop = tuple(voters)
            leader = min(voters)
            with self._cond:
                epoch = self._epoch
                self._views[self.rank] = {"members": list(prop),
                                          "stage": stage, "op": op,
                                          "epoch": epoch,
                                          "tp_next": tp_next}
            vote = {"kind": "view", "op": op, "members": list(prop),
                    "stage": stage, "epoch": epoch, "joins": list(joins),
                    "tp_next": tp_next,
                    "dead": sorted(self.ce.dead_peers)}
            got = (self._lead(op, prop, voters, joins, stage, epoch, vote,
                              t_end, window_s)
                   if self.rank == leader else
                   self._follow(op, voters, leader, stage, epoch, vote,
                                t_end))
            if got == "retry":
                continue
            if got == "aborted":
                return None
            assert isinstance(got, dict)
            committed = tuple(got["members"])
            if self.rank not in committed:
                raise ElasticError(
                    f"rank {self.rank} was excluded from the committed "
                    f"member set {committed} (a peer's detector declared "
                    f"us dead) — aborting this incarnation")
            self._conclude(committed)
            return {"members": committed, "tp_base": got.get("tp_base"),
                    "stage": got.get("stage", stage)}
        raise ElasticError(
            f"rank {self.rank}: {op} agreement on stage {stage} did not "
            f"converge within {deadline_s:.0f}s")

    def _conclude(self, committed: Tuple[int, ...]) -> None:
        with self._cond:
            self._epoch += 1
            self._views.clear()
            self._commit = None
            self._joins.difference_update(committed)

    def _matching_votes(self, op: str, prop: Tuple[int, ...],
                        voters: Sequence[int],
                        epoch: int) -> bool:  # holds: self._cond
        """Votes match on (op, members, epoch) — NOT on stage: a rank
        leaves a pool's wait as soon as its local part terminates, so
        survivors of a mid-stage failure can sit one snapshot apart.
        The leader reconciles by committing the MINIMUM voted stage
        (every voter provably wrote that snapshot's own shards; ranks
        ahead of it simply replay)."""
        for v in voters:
            view = self._views.get(v)
            if (view is None or view.get("op") != op
                    or tuple(view.get("members", ())) != prop
                    or view.get("epoch") != epoch):
                return False
        return True

    def _lead(self, op, prop, voters, joins, stage, epoch, vote, t_end,
              window_s):
        """Leader half of one round: gather matching votes, then
        broadcast commit (+ welcomes) or — optional rounds only —
        abort."""
        w_end = (time.monotonic() + window_s) if window_s is not None \
            else t_end
        last_tx = 0.0
        while time.monotonic() < min(w_end, t_end):
            now = time.monotonic()
            if now - last_tx >= _RESEND_S:
                last_tx = now
                for p in voters:
                    if p != self.rank:
                        self.ce.ft_elastic_send(p, vote)
            with self._cond:
                ok = self._matching_votes(op, prop, voters, epoch)
                tp_base = c_stage = None
                if ok:
                    views = [self._views[v] for v in voters
                             if self._views.get(v) is not None]
                    vals = [v.get("tp_next") for v in views
                            if v.get("tp_next") is not None]
                    tp_base = max(vals) if vals else None
                    # the committed resume point: the lowest voted
                    # snapshot — every voter provably wrote its own
                    # shards for it; ranks ahead of it replay
                    c_stage = min(v.get("stage", stage) for v in views)
            if ok:
                decision = {"kind": "commit", "op": op,
                            "members": list(prop), "stage": c_stage,
                            "epoch": epoch, "tp_base": tp_base}
                for p in prop:
                    if p == self.rank:
                        continue
                    msg = decision if p in voters else {
                        "kind": "welcome", "members": list(prop),
                        "stage": c_stage, "epoch": epoch + 1,
                        "tp_base": tp_base}
                    self.ce.ft_elastic_send(p, msg)
                return decision
            if self._alive(voters) != list(voters):
                return "retry"   # a voter died: re-propose without it
            with self._cond:
                self._cond.wait(timeout=0.01)
            self.ce.progress()
        if time.monotonic() >= t_end:
            return "retry"       # outer loop raises on the deadline
        # optional round, window expired: release the boundary
        for p in voters:
            if p != self.rank:
                self.ce.ft_elastic_send(
                    p, {"kind": "abort", "op": op, "stage": stage,
                        "epoch": epoch})
        return "aborted"

    def _follow(self, op, voters, leader, stage, epoch, vote, t_end):
        """Non-leader half: vote, then wait for the leader's decision."""
        last_tx = 0.0
        while time.monotonic() < t_end:
            now = time.monotonic()
            if now - last_tx >= _RESEND_S:
                last_tx = now
                for p in voters:
                    if p != self.rank:
                        self.ce.ft_elastic_send(p, vote)
            with self._cond:
                # the commit's stage may differ from OUR vote (the
                # leader reconciles divergent snapshots to the min) —
                # only op + epoch identify the round
                c = self._commit
                if (c is not None and c.get("op") == op
                        and c.get("epoch") == epoch):
                    return c
                if (op, stage, epoch) in self._aborts:
                    self._aborts.discard((op, stage, epoch))
                    return "aborted"
                self._cond.wait(timeout=0.01)
            if leader in self.ce.dead_peers \
                    or self.ce.peer_finished(leader):
                return "retry"   # next-lowest voter takes over
            self.ce.progress()
        return "retry"


# --------------------------------------------------------------------- #
# policy + context wiring                                               #
# --------------------------------------------------------------------- #
class ElasticPolicy:
    """What the restart driver needs from the application to resize.

    ``rebuild(grid: GridSpec) -> (stages, collections)`` constructs the
    run on an arbitrary member grid — called for the initial grid too
    (pass ``stages=None`` to ``run_with_restart``), so there is ONE
    source of truth for how the job lays itself out. Fresh collections
    may hold initial data; a resize reshard-restores over every tile,
    so stale initial values never leak into a recovered run.

    ``mode``: "shrink" | "grow" | "both" (default: the ``ft_elastic``
    MCA param; empty disables, keeping today's fail-fast contract).
    ``members``: the initial member world-rank set (default: all
    ranks). ``join=True`` marks this rank a late joiner: it announces,
    waits for a welcome, reshards, and picks the run up mid-flight.
    """

    def __init__(self, rebuild: Callable[[GridSpec], Tuple[Sequence, Sequence]],
                 mode: Optional[str] = None,
                 members: Optional[Sequence[int]] = None,
                 grow_min: Optional[int] = None,
                 timeout: Optional[float] = None,
                 grow_window: float = _GROW_WINDOW_S,
                 join: bool = False) -> None:
        if mode is None:
            mode = str(params.get("ft_elastic") or "").strip()
        if mode not in ("", "shrink", "grow", "both"):
            raise ValueError(f"unknown ft_elastic mode {mode!r} "
                             f"(want shrink | grow | both)")
        self.rebuild = rebuild
        self.mode = mode
        self.members = tuple(members) if members is not None else None
        if grow_min is None:
            raw = params.get("ft_elastic_grow_min")
            grow_min = int(raw) if raw else 1
        self.grow_min = max(1, int(grow_min))
        if timeout is None:
            raw = str(params.get("ft_elastic_timeout") or "").strip()
            timeout = float(raw) if raw else _TIMEOUT_S
        self.timeout = float(timeout)
        self.grow_window = float(grow_window)
        self.join = bool(join)

    @property
    def allows_shrink(self) -> bool:
        return self.mode in ("shrink", "both")

    @property
    def allows_grow(self) -> bool:
        return self.mode in ("grow", "both")


def maybe_install_elastic(ctx: Any) -> Optional[ElasticCoordinator]:
    """Attach a coordinator to the context's engine when ``ft_elastic``
    is configured — Context calls this at init (after the detector, so
    eviction callbacks find it; before obs, so the gauges see the
    engine's elastic_stats) — join announcements arriving mid-stage
    then reach a live coordinator instead of the engine buffer."""
    if ctx.comm is None or ctx.nb_ranks < 2:
        return None
    if not str(params.get("ft_elastic") or "").strip():
        return None
    ce = getattr(ctx.comm, "ce", ctx.comm)
    if ce.ft_elastic is not None:
        return ce.ft_elastic
    return ElasticCoordinator(ce)
