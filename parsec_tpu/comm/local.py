"""LocalFabric: N SPMD ranks inside one process.

The test transport: every rank is a thread with its own Context; messages
are queued between per-rank inboxes with payload deep-copies to model the
wire. This is the analog of the reference's CI strategy — distributed
behavior validated by oversubscribed mpiexec on one node with no fake
network backend (SURVEY.md §4) — except the "node" is one process.
"""
from __future__ import annotations

import copy as _copy
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..core.lists import Fifo
from .engine import (CommEngine, MemHandle, RankFailedError, TAG_GET_DATA,
                     TAG_GET_REQ, TAG_PUT_DATA)


class LocalFabric:
    """The shared 'network': per-rank inboxes + a barrier."""

    def __init__(self, nb_ranks: int) -> None:
        self.nb_ranks = nb_ranks
        self.inboxes: List[Fifo] = [Fifo() for _ in range(nb_ranks)]
        self.barrier = threading.Barrier(nb_ranks)
        self.engines: List[Optional["LocalCommEngine"]] = [None] * nb_ranks
        self.msg_count = 0
        self.bytes_count = 0
        # ranks that fini'd CLEANLY (the in-process analog of the TCP
        # GOODBYE): the heartbeat detector must never declare these
        # failed when their pings stop
        self.finished: set = set()
        self._stat_lock = threading.Lock()

    def engine(self, rank: int) -> "LocalCommEngine":
        eng = LocalCommEngine(self, rank)
        self.engines[rank] = eng
        return eng

    def _post(self, dst: int, src: int, tag: int, payload: Any) -> None:
        with self._stat_lock:
            self.msg_count += 1
            self.bytes_count += _payload_bytes(payload)
        self.inboxes[dst].push((src, tag, payload))
        eng = self.engines[dst]
        if eng is not None:
            eng._notify_arrival()  # wake a parked worker on the dst rank


def _payload_bytes(payload: Any) -> int:
    if isinstance(payload, np.ndarray):
        return payload.nbytes
    if isinstance(payload, dict):
        return sum(_payload_bytes(v) for v in payload.values())
    if isinstance(payload, (list, tuple)):
        return sum(_payload_bytes(v) for v in payload)
    return 8


def _wire_copy(payload: Any) -> Any:
    """Deep-copy ndarrays to model serialization (no aliasing across ranks)."""
    if isinstance(payload, np.ndarray):
        return payload.copy()
    if isinstance(payload, dict):
        return {k: _wire_copy(v) for k, v in payload.items()}
    if isinstance(payload, tuple):
        return tuple(_wire_copy(v) for v in payload)
    if isinstance(payload, list):
        return [_wire_copy(v) for v in payload]
    return payload


class LocalCommEngine(CommEngine):
    def __init__(self, fabric: LocalFabric, rank: int) -> None:
        super().__init__(rank, fabric.nb_ranks)
        self.fabric = fabric
        self._get_cbs: Dict[int, Callable] = {}
        self._get_srcs: Dict[int, int] = {}  # token -> peer rank owing data
        self._get_iter = 0
        self._lock = threading.Lock()
        # GET aggregation: gets issued from handlers DURING a progress
        # drain batch per peer and flush as ONE request frame at the end
        # of that progress call (several same-cycle rendezvous to one
        # peer cost one wire round-trip instead of N). Depth is
        # per-thread: progress() runs on every scheduler thread.
        self._get_queue: Dict[int, List[Tuple[int, int]]] = {}
        self._drain_depth = threading.local()
        self.tag_register(TAG_GET_REQ, self._on_get_req)
        self.tag_register(TAG_GET_DATA, self._on_get_data)
        self.tag_register(TAG_PUT_DATA, self._on_put_data)

    # -- AMs ----------------------------------------------------------------
    # transport extension points: subclasses replace these two to carry
    # the same AM/GET/PUT emulation over another wire (comm/tcp.py)
    def _transport_post(self, dst: int, src: int, tag: int, payload: Any) -> None:
        for _ in range(self.ft_outbound(dst, tag)):
            self.fabric._post(dst, src, tag, payload)

    def _transport_drain(self):
        """Yield pending (src, tag, payload) messages."""
        inbox = self.fabric.inboxes[self.rank]
        while True:
            item = inbox.pop()
            if item is None:
                return
            yield item

    def send_am(self, dst: int, tag: int, payload: Any) -> None:
        # self-sends also loop back through the inbox for ordering fidelity
        if dst != self.rank and dst in self.dead_peers:
            raise RankFailedError(dst, "send to failed rank")
        obs = self._obs
        if obs is None:
            self._transport_post(dst, self.rank, tag, _wire_copy(payload))
            return
        ctx = None
        if self._flow is not None:
            payload, ctx = self._flow_stamp(dst, tag, payload)
        t0 = time.monotonic_ns()
        self._transport_post(dst, self.rank, tag, _wire_copy(payload))
        obs.am_sent(self.rank, dst, tag, payload, t0)
        if ctx is not None:
            obs.flow_sent(dst, tag, ctx, t0)

    # -- one-sided emulation (GET-req AM + data reply) ----------------------
    def get(self, src_rank: int, remote_handle_id: int,
            on_complete: Callable[[Any], None]) -> None:
        with self._lock:
            self._get_iter += 1
            token = self._get_iter
            self._get_cbs[token] = on_complete
            self._get_srcs[token] = src_rank
        obs = self._obs
        if obs is not None:
            obs.get_begin(token, src_rank)
        if getattr(self._drain_depth, "n", 0) > 0:
            # inside a progress drain on this thread: batch — the flush
            # at the end of this progress call sends one request per
            # peer covering every GET the drained messages triggered
            with self._lock:
                self._get_queue.setdefault(src_rank, []).append(
                    (remote_handle_id, token))
            return
        self.send_am(src_rank, TAG_GET_REQ,
                     {"requester": self.rank,
                      "gets": [(remote_handle_id, token)]})

    def _flush_gets(self) -> None:
        with self._lock:
            if not self._get_queue:
                return
            pending, self._get_queue = self._get_queue, {}
        first_exc = None
        for peer, gets in pending.items():
            try:
                self.send_am(peer, TAG_GET_REQ,
                             {"requester": self.rank, "gets": gets})
            except Exception as exc:  # noqa: BLE001 - e.g. RankFailedError
                # one dead peer must not starve the OTHER peers' batched
                # requests (their callbacks would never fire); send to
                # everyone, then surface the first failure
                if first_exc is None:
                    first_exc = exc
        if first_exc is not None:
            raise first_exc

    def _serve_get(self, requester: int, h: MemHandle) -> Any:
        """Materialize one GET reply payload (transport hook: the mesh
        engine pushes the buffer onto the requester's device here)."""
        return h.array

    def _on_get_req(self, src: int, payload: Any) -> None:
        req = payload["requester"]
        items = []
        quantize_ok = True
        for handle_id, token in payload["gets"]:
            h = self._mem.get(handle_id)
            assert h is not None, f"GET for unknown mem handle {handle_id}"
            quantize_ok = quantize_ok and getattr(h, "quantize_ok", False)
            items.append({"token": token,
                          "data": self._serve_get(req, h),
                          "meta": h.meta})
        # every same-cycle GET from one requester rides ONE reply frame;
        # the reply is quantize-eligible (ISSUE 14) only when EVERY
        # served handle was registered as a tile payload — one lossless
        # shard in the batch keeps the whole frame lossless
        msg = {"items": items}
        if items and quantize_ok:
            msg["_qz_ok"] = True
        self.send_am(req, TAG_GET_DATA, msg)
        if self.on_get_served is not None:
            for handle_id, _token in payload["gets"]:
                self.on_get_served(handle_id)

    def _on_get_data(self, src: int, payload: Any) -> None:
        obs = self._obs
        first_exc = None
        for item in payload["items"]:
            with self._lock:
                cb = self._get_cbs.pop(item["token"])
                self._get_srcs.pop(item["token"], None)
            if obs is not None:
                # one matched begin/end span per one-sided transfer
                obs.get_end(item["token"], src, item["data"])
            try:
                cb(item["data"])
            except Exception as exc:  # noqa: BLE001
                # the reply frame carries SEVERAL gets: one callback
                # failing must not strand the remaining tokens (their
                # bytes are already consumed from the inbox) — deliver
                # everything, then surface the first failure
                if first_exc is None:
                    first_exc = exc
        if first_exc is not None:
            raise first_exc

    def put(self, dst_rank: int, remote_handle_id: int, array: Any,
            on_complete: Optional[Callable] = None) -> None:
        """One-sided put: copy into the remote registered region
        (PUT-data AM applied on the receiver's progress)."""
        obs = self._obs
        t0 = time.monotonic_ns() if obs is not None else 0
        self.send_am(dst_rank, TAG_PUT_DATA,
                     {"handle": remote_handle_id, "data": array})
        if obs is not None:
            obs.put(dst_rank, array, t0)
        if on_complete is not None:
            on_complete(array)

    def _on_put_data(self, src: int, payload: Any) -> None:
        h = self._mem.get(payload["handle"])
        assert h is not None, f"PUT for unknown mem handle {payload['handle']}"
        np.copyto(h.array, payload["data"])

    # -- progress -----------------------------------------------------------
    def progress(self) -> int:
        if self._ft_silenced:
            return 0   # injected kill: the inbox is never drained again
        obs = self._obs
        t0 = time.monotonic_ns() if obs is not None else 0
        n = 0
        tl = self._drain_depth
        tl.n = getattr(tl, "n", 0) + 1
        ok = False
        try:
            for src, tag, payload in self._transport_drain():
                if self.deliver_message(src, tag, payload):
                    n += 1
            ok = True
        finally:
            tl.n -= 1
            if tl.n == 0:
                if ok:
                    self._flush_gets()
                else:
                    # a handler raised mid-drain: still try to flush so
                    # live peers' batched GETs are not stranded, but the
                    # in-flight error must win over any flush failure
                    try:
                        self._flush_gets()
                    except Exception:
                        pass
        if obs is not None:
            obs.progress(n, t0)  # span only when work was done
        return n

    def mesh_local_with(self, peer: int) -> bool:
        """In-process SPMD ranks share one XLA client: device buffers
        are directly addressable on every peer (the test-fabric analog
        of two ranks whose chips sit on one mesh/slice)."""
        return 0 <= peer < self.nb_ranks

    def clock_offset_us(self, peer: int) -> float:
        """In-process ranks share ONE monotonic clock: the cross-rank
        trace offset (ISSUE 15) is identically zero — the estimator
        only exists on cross-process transports (comm/tcp.py)."""
        return 0.0

    def clock_offsets_us(self) -> Dict[int, float]:
        return {p: 0.0 for p in range(self.nb_ranks) if p != self.rank}

    def sync(self) -> None:
        self.fabric.barrier.wait()

    def peer_finished(self, peer: int) -> bool:
        with self.fabric._stat_lock:
            return peer in self.fabric.finished

    def ft_ping(self, peer: int, seq: int, t_ns: int) -> bool:
        """Probe-layer support gate (the in-process analog of TCP's
        HELLO ``hb`` capability): only probe engines with a live
        TAG_HEARTBEAT handler — the detector never judges a peer it
        could not probe, so a handler-less (mixed-version) peer is
        never declared dead."""
        from .engine import TAG_HEARTBEAT
        eng = (self.fabric.engines[peer]
               if 0 <= peer < len(self.fabric.engines) else None)
        if eng is None or TAG_HEARTBEAT not in eng._tag_cbs:
            return False
        return super().ft_ping(peer, seq, t_ns)

    def ft_elastic_send(self, peer: int, payload: Any) -> bool:
        """Same support gate as ``ft_ping``, for membership traffic
        (the in-process analog of TCP's HELLO ``el`` capability): a
        peer without a TAG_ELASTIC handler is a pre-elastic build and
        must never be drawn into a resize agreement."""
        from .engine import TAG_ELASTIC
        eng = (self.fabric.engines[peer]
               if 0 <= peer < len(self.fabric.engines) else None)
        if eng is None or TAG_ELASTIC not in eng._tag_cbs:
            return False
        return super().ft_elastic_send(peer, payload)

    def fini(self) -> None:
        # clean-shutdown advertisement (the in-process GOODBYE): a rank
        # under an injected kill died SILENTLY and must not mark itself
        # finished — proactive detection is the only way peers learn
        if not self._ft_silenced:
            with self.fabric._stat_lock:
                self.fabric.finished.add(self.rank)
