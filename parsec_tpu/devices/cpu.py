"""Host CPU device: chores run inline on the calling worker thread.

Reference behavior: the CPU incarnation's hook executes the BODY directly in
``__parsec_execute`` on the selecting thread (ref: parsec/scheduling.c:124-203).
Device index 0 is always the host.
"""
from __future__ import annotations

from .device import Device


class CPUDevice(Device):
    def __init__(self, device_index: int = 0) -> None:
        super().__init__("cpu", device_index, name="cpu")
        # relative capability weight; accelerators are ~weight 0.1 of it
        self.time_estimate_default = 10.0
